// Native MQTT load generator — the emqtt-bench analogue (the reference
// measures its 1M msg/s with an Erlang client fleet; a Python client
// fleet tops out around 15k msg/s total and would measure itself, not
// the broker). Single thread, nonblocking sockets, one epoll loop for
// the whole fleet: subscribers count deliveries and sample end-to-end
// latency from an 8-byte monotonic-ns timestamp at the head of every
// payload; publishers blast with TCP backpressure as the only pacing.
//
// Driven from bench.py over ctypes (emqx_loadgen_run blocks; ctypes
// releases the GIL so the broker's poll thread keeps running).

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <string>
#include <vector>

#include "coap.h"
#include "frame.h"
#include "sn.h"
#include "ws.h"

namespace {

using emqx_native::Framer;
using emqx_native::FrameStatus;
namespace lws = emqx_native::ws;

inline uint64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
}

void PutU16(std::string* s, uint16_t v) {
  s->push_back(static_cast<char>(v >> 8));
  s->push_back(static_cast<char>(v & 0xFF));
}

void PutVarint(std::string* s, size_t v) {
  do {
    uint8_t b = v & 0x7F;
    v >>= 7;
    s->push_back(static_cast<char>(v ? b | 0x80 : b));
  } while (v);
}

std::string Connect(const std::string& clientid, int proto_ver) {
  std::string body;
  PutU16(&body, 4);
  body += "MQTT";
  body.push_back(static_cast<char>(proto_ver));
  body.push_back(0x02);  // clean start
  PutU16(&body, 60);     // keepalive
  if (proto_ver == 5) body.push_back('\0');  // empty properties
  PutU16(&body, static_cast<uint16_t>(clientid.size()));
  body += clientid;
  std::string f;
  f.push_back(0x10);
  PutVarint(&f, body.size());
  return f + body;
}

std::string Subscribe(uint16_t pid, const std::string& filt, uint8_t qos,
                      int proto_ver) {
  std::string body;
  PutU16(&body, pid);
  if (proto_ver == 5) body.push_back('\0');
  PutU16(&body, static_cast<uint16_t>(filt.size()));
  body += filt;
  body.push_back(static_cast<char>(qos));
  std::string f;
  f.push_back(static_cast<char>(0x82));
  PutVarint(&f, body.size());
  return f + body;
}

std::string Publish(const std::string& topic, const std::string& payload,
                    uint8_t qos, uint16_t pid, int proto_ver) {
  std::string body;
  PutU16(&body, static_cast<uint16_t>(topic.size()));
  body += topic;
  if (qos) PutU16(&body, pid);
  if (proto_ver == 5) body.push_back('\0');
  body += payload;
  std::string f;
  f.push_back(static_cast<char>(0x30 | (qos << 1)));
  PutVarint(&f, body.size());
  return f + body;
}

std::string Ack(uint8_t header, uint16_t pid) {
  std::string f;
  f.push_back(static_cast<char>(header));
  f.push_back(0x02);
  PutU16(&f, pid);
  return f;
}

// [h][varint][pid] — pid of PUBACK/PUBREC/PUBREL/PUBCOMP
uint16_t AckPid(const std::string& f) {
  size_t pos = 1;
  while (pos < f.size() && (static_cast<uint8_t>(f[pos]) & 0x80)) pos++;
  pos++;
  if (pos + 2 > f.size()) return 0;
  return (static_cast<uint8_t>(f[pos]) << 8) |
         static_cast<uint8_t>(f[pos + 1]);
}

struct LgConn {
  int fd = -1;
  Framer framer{1 << 20};
  std::string outbuf;
  size_t outpos = 0;
  bool connacked = false;
  bool subacked = false;
  bool is_sub = false;
  uint32_t idx = 0;
  // -- ws mode (the emqtt-bench `ws://` analogue) --------------------------
  bool ws = false;
  bool ws_open = false;      // 101 received; frames flow
  std::string ws_hs;         // upgrade response accumulation
  // server frames arrive unmasked (require_mask=false); the decoder
  // still handles a masked frame generically should one appear
  lws::WsDecoder ws_dec{/*require_mask=*/false};
  uint8_t ws_key[4] = {};    // nonzero client mask key (per conn)
  std::string cid;           // CONNECT is deferred until the 101 lands
};

struct Loadgen {
  std::vector<LgConn> conns;
  int ep = -1;
  uint64_t received = 0, sent = 0, acks = 0, errors = 0;
  std::vector<uint64_t> lat;
  int proto_ver = 4;
  uint8_t qos = 0;

  ~Loadgen() {
    for (auto& c : conns)
      if (c.fd >= 0) close(c.fd);
    if (ep >= 0) close(ep);
  }

  // Append MQTT bytes to a conn's socket buffer; ws conns wrap them in
  // one masked binary frame (clients MUST mask, RFC6455 §5.3 — the key
  // is nonzero so the broker pays the real unmask cost).
  void AppendOut(LgConn& c, const std::string& bytes) {
    if (!c.ws) {
      c.outbuf += bytes;
      return;
    }
    lws::AppendFrameHeader(&c.outbuf, lws::kOpBinary, bytes.size(),
                           c.ws_key);
    size_t at = c.outbuf.size();
    c.outbuf += bytes;
    for (size_t i = 0; i < bytes.size(); i++)
      c.outbuf[at + i] ^= static_cast<char>(c.ws_key[i & 3]);
  }

  bool FlushOut(LgConn& c) {
    while (c.outpos < c.outbuf.size()) {
      ssize_t n = send(c.fd, c.outbuf.data() + c.outpos,
                       c.outbuf.size() - c.outpos, MSG_NOSIGNAL);
      if (n > 0) {
        c.outpos += static_cast<size_t>(n);
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.u32 = c.idx;
        epoll_ctl(ep, EPOLL_CTL_MOD, c.fd, &ev);
        return true;
      } else if (n < 0 && errno == EINTR) {
        continue;
      } else {
        return false;
      }
    }
    c.outbuf.clear();
    c.outpos = 0;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = c.idx;
    epoll_ctl(ep, EPOLL_CTL_MOD, c.fd, &ev);
    return true;
  }

  void OnFrame(LgConn& c, const std::string& f) {
    uint8_t type = static_cast<uint8_t>(f[0]) >> 4;
    if (type == 2) {  // CONNACK
      c.connacked = true;
    } else if (type == 9) {  // SUBACK
      c.subacked = true;
    } else if (type == 3) {  // PUBLISH delivery
      uint8_t dqos = (static_cast<uint8_t>(f[0]) >> 1) & 3;
      size_t pos = 1;
      while (pos < f.size() && (static_cast<uint8_t>(f[pos]) & 0x80)) pos++;
      pos++;
      if (pos + 2 > f.size()) return;
      uint16_t tlen = (static_cast<uint8_t>(f[pos]) << 8) |
                      static_cast<uint8_t>(f[pos + 1]);
      pos += 2 + tlen;
      if (dqos) {
        if (pos + 2 > f.size()) return;
        uint16_t pid = (static_cast<uint8_t>(f[pos]) << 8) |
                       static_cast<uint8_t>(f[pos + 1]);
        pos += 2;
        // qos1 delivery → PUBACK; qos2 → PUBREC (broker answers
        // PUBREL, completed below)
        AppendOut(c, Ack(dqos == 1 ? 0x40 : 0x50, pid));
      }
      if (proto_ver == 5 && pos < f.size()) {
        uint8_t plen = static_cast<uint8_t>(f[pos]);
        pos += 1 + plen;  // bench properties always fit one varint byte
      }
      if (pos + 8 <= f.size()) {
        uint64_t stamp;
        memcpy(&stamp, f.data() + pos, 8);
        uint64_t now = NowNs();
        if (now > stamp && now - stamp < 60ull * 1000000000ull)
          lat.push_back(now - stamp);
      }
      received++;
    } else if (type == 4) {  // PUBACK for our qos1 publishes
      acks++;
    } else if (type == 5) {  // PUBREC for our qos2 publish → PUBREL
      AppendOut(c, Ack(0x62, AckPid(f)));
    } else if (type == 6) {  // PUBREL from the broker → PUBCOMP
      AppendOut(c, Ack(0x70, AckPid(f)));
    } else if (type == 7) {  // PUBCOMP completes our qos2 publish
      acks++;
    }
  }

  // One conn's inbound bytes → MQTT frames (through the ws codec when
  // applicable; `data` is mutable for in-place unmasking). Returns
  // false on a framing/protocol error.
  bool Ingest(LgConn& c, uint8_t* data, size_t len) {
    if (!c.ws) return FeedMqtt(c, data, len);
    if (!c.ws_open) {
      c.ws_hs.append(reinterpret_cast<const char*>(data), len);
      size_t end = c.ws_hs.find("\r\n\r\n");
      if (end == std::string::npos) return c.ws_hs.size() <= 16384;
      if (c.ws_hs.compare(0, 12, "HTTP/1.1 101") != 0) return false;
      c.ws_open = true;
      AppendOut(c, Connect(c.cid, proto_ver));  // deferred CONNECT
      std::string left = c.ws_hs.substr(end + 4);
      c.ws_hs.clear();
      if (left.empty()) return true;
      return WsFeed(c, reinterpret_cast<uint8_t*>(&left[0]), left.size());
    }
    return WsFeed(c, data, len);
  }

  bool WsFeed(LgConn& c, uint8_t* data, size_t len) {
    bool ok = true;
    lws::WsStatus st = c.ws_dec.Feed(
        data, len,
        [&](const char* p, size_t n) {
          if (n && !FeedMqtt(c, reinterpret_cast<const uint8_t*>(p), n)) {
            ok = false;
            return false;
          }
          return true;
        },
        [&](uint8_t op, const char* p, size_t n) {
          if (op == lws::kOpPing) {  // masked pong echo
            lws::AppendFrameHeader(&c.outbuf, lws::kOpPong, n, c.ws_key);
            size_t at = c.outbuf.size();
            c.outbuf.append(p, n);
            for (size_t i = 0; i < n; i++)
              c.outbuf[at + i] ^= static_cast<char>(c.ws_key[i & 3]);
            return true;
          }
          return op != lws::kOpClose;  // close ends the conn
        });
    return ok && st == lws::WsStatus::kOk;
  }

  bool FeedMqtt(LgConn& c, const uint8_t* data, size_t len) {
    std::vector<std::string> frames;
    if (c.framer.Feed(data, len, &frames) != FrameStatus::kOk)
      return false;
    for (auto& f : frames) OnFrame(c, f);
    return true;
  }

  // Pump readable/writable conns once; returns false on fatal error.
  bool Pump(int timeout_ms) {
    epoll_event evs[128];
    int n = epoll_wait(ep, evs, 128, timeout_ms);
    if (n < 0) return errno == EINTR;
    uint8_t chunk[64 * 1024];
    for (int i = 0; i < n; i++) {
      LgConn& c = conns[evs[i].data.u32];
      if (c.fd < 0) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        errors++;
        close(c.fd);
        c.fd = -1;
        continue;
      }
      if (evs[i].events & EPOLLOUT) {
        if (!FlushOut(c)) {
          errors++;
          close(c.fd);
          c.fd = -1;
          continue;
        }
      }
      if (!(evs[i].events & EPOLLIN)) continue;
      for (;;) {
        ssize_t r = recv(c.fd, chunk, sizeof(chunk), 0);
        if (r > 0) {
          if (!Ingest(c, chunk, static_cast<size_t>(r))) {
            errors++;
            close(c.fd);
            c.fd = -1;
            break;
          }
          if (!c.outbuf.empty()) FlushOut(c);  // pubacks
          if (static_cast<size_t>(r) < sizeof(chunk)) break;
        } else if (r == 0) {
          close(c.fd);
          c.fd = -1;
          break;
        } else {
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          if (errno == EINTR) continue;
          errors++;
          close(c.fd);
          c.fd = -1;
          break;
        }
      }
    }
    return true;
  }
};

}  // namespace

extern "C" {

// out[8]: sent, received, wall_ns, p50_ns, p99_ns, max_ns, acks, errors
//
// qos selects the full exchange depth: 0 = fire-and-forget, 1 =
// PUBLISH/PUBACK both directions, 2 = the four-packet exactly-once
// exchange on both the publisher (PUBREC→PUBREL, PUBCOMP counts into
// acks) and the subscriber (PUBREC, PUBREL→PUBCOMP) sides.
//
// window = 0: blast mode — publishers keep ~64KB buffered and TCP
//   backpressure paces them; measures peak throughput, but delivery
//   latency then measures queue depth, not the broker.
// window > 0: windowed mode — total unreceived messages are capped at
//   `window`, so latency percentiles measure the broker's delivery
//   path at a sustainable rate (no coordinated omission).
// warmup != 0: each publisher first sends one message per topic and
//   waits, letting the broker's permit machinery move those
//   (conn, topic) pairs onto the native fast path before the clock
//   starts (permits are per-connection, so warming must happen in-run).
// ws != 0: the whole fleet speaks MQTT-over-WebSocket (RFC6455
//   upgrade on /mqtt, masked binary frames with nonzero keys so the
//   broker pays the real unmask cost) — point `port` at the broker's
//   WS listener.
int emqx_loadgen_run(const char* host, uint16_t port, uint32_t n_subs,
                     uint32_t n_pubs, uint32_t msgs_per_pub, uint8_t qos,
                     uint32_t payload_len, int proto_ver, int idle_timeout_ms,
                     uint32_t window, int warmup, int ws, uint32_t salt,
                     uint64_t* out) {
  Loadgen lg;
  lg.proto_ver = proto_ver;
  lg.qos = qos;
  uint32_t total = n_subs + n_pubs;
  lg.conns.resize(total);
  lg.ep = epoll_create1(EPOLL_CLOEXEC);
  if (lg.ep < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) return -2;

  for (uint32_t i = 0; i < total; i++) {
    LgConn& c = lg.conns[i];
    c.idx = i;
    c.is_sub = i < n_subs;
    c.fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (c.fd < 0) return -3;
    int one = 1;
    setsockopt(c.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (connect(c.fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 &&
        errno != EINPROGRESS)
      return -4;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = i;
    epoll_ctl(lg.ep, EPOLL_CTL_ADD, c.fd, &ev);
    std::string cid = (c.is_sub ? "lgs" : "lgp") + std::to_string(salt + i);
    if (ws) {
      c.ws = true;
      c.cid = cid;
      uint64_t seed = NowNs() ^ (0x9E3779B97F4A7C15ull * (i + 1));
      for (int b = 0; b < 4; b++)
        c.ws_key[b] = static_cast<uint8_t>((seed >> (8 * b)) & 0xFF);
      if (!(c.ws_key[0] | c.ws_key[1] | c.ws_key[2] | c.ws_key[3]))
        c.ws_key[0] = 1;
      // handshake request is HTTP, not a frame: raw bytes; the CONNECT
      // follows from Ingest once the 101 arrives
      c.outbuf += lws::BuildUpgradeRequest(
          host, "/mqtt", "bG9hZGdlbi1ub25jZS0wMDE=");
    } else {
      c.outbuf += Connect(cid, proto_ver);
    }
    lg.FlushOut(c);
  }

  // wait for all CONNACKs, then all SUBACKs (handshake deadline 15s)
  uint64_t deadline = NowNs() + 15ull * 1000000000ull;
  auto all = [&](bool LgConn::* flag, bool subs_only) {
    for (auto& c : lg.conns) {
      if (subs_only && !c.is_sub) continue;
      if (c.fd >= 0 && !(c.*flag)) return false;
    }
    return true;
  };
  while (!all(&LgConn::connacked, false)) {
    if (NowNs() > deadline || !lg.Pump(100)) return -5;
  }
  for (uint32_t i = 0; i < n_subs; i++) {
    LgConn& c = lg.conns[i];
    if (c.fd < 0) continue;
    lg.AppendOut(c, Subscribe(1, "lg/" + std::to_string(salt + i) + "/+", qos,
                              proto_ver));
    lg.FlushOut(c);
  }
  while (!all(&LgConn::subacked, true)) {
    if (NowNs() > deadline || !lg.Pump(100)) return -6;
  }

  uint64_t expected = static_cast<uint64_t>(n_pubs) * msgs_per_pub;
  lg.lat.reserve(std::min<uint64_t>(expected, 4u << 20));
  std::string pad(payload_len > 8 ? payload_len - 8 : 0, 'x');
  std::vector<uint32_t> next_msg(n_pubs, 0);

  if (warmup) {
    // one slow-path message per (publisher, topic) pair earns the
    // publish permits; then idle so the broker's grant step runs
    uint64_t warm_expected = static_cast<uint64_t>(n_pubs) * n_subs;
    for (uint32_t j = 0; j < n_pubs; j++) {
      LgConn& c = lg.conns[n_subs + j];
      if (c.fd < 0) continue;
      for (uint32_t k = 0; k < n_subs; k++) {
        uint64_t stamp = NowNs();
        std::string payload(reinterpret_cast<char*>(&stamp), 8);
        payload += pad;
        lg.AppendOut(c, Publish("lg/" + std::to_string(salt + k) + "/m",
                                payload, 0, 0, proto_ver));
      }
      lg.FlushOut(c);
    }
    uint64_t warm_deadline = NowNs() + 20ull * 1000000000ull;
    while (lg.received < warm_expected && NowNs() < warm_deadline) {
      if (!lg.Pump(100)) break;
    }
    // grant latency: the broker queues permits and applies them on an
    // idle poll step; 600ms of pumping is comfortably past that
    uint64_t settle_until = NowNs() + 600ull * 1000000ull;
    while (NowNs() < settle_until) lg.Pump(50);
    lg.received = lg.sent = lg.acks = 0;
    lg.lat.clear();
  }

  // blast/windowed: publisher j round-robins the subscriber topics;
  // payload head is the publish timestamp (ns), refreshed per message
  uint64_t t0 = NowNs();
  uint64_t last_progress = t0;
  uint64_t last_received = 0;
  uint16_t pid = 1;
  while (true) {
    // fill publisher buffers (~64KB each; EAGAIN pacing does the rest;
    // in windowed mode the in-flight cap paces instead)
    bool done_sending = true;
    for (uint32_t j = 0; j < n_pubs; j++) {
      LgConn& c = lg.conns[n_subs + j];
      if (c.fd < 0) continue;
      while (next_msg[j] < msgs_per_pub &&
             c.outbuf.size() - c.outpos < 64 * 1024 &&
             (window == 0 || lg.sent - lg.received < window)) {
        uint64_t stamp = NowNs();
        std::string payload(reinterpret_cast<char*>(&stamp), 8);
        payload += pad;
        std::string topic =
            "lg/" + std::to_string(salt + (j + next_msg[j]) % n_subs) + "/m";
        if (qos) pid = pid == 0x7FFF ? 1 : pid + 1;
        lg.AppendOut(c, Publish(topic, payload, qos, pid, proto_ver));
        next_msg[j]++;
        lg.sent++;
      }
      if (next_msg[j] < msgs_per_pub) done_sending = false;
      if (!c.outbuf.empty() && !lg.FlushOut(c)) {
        lg.errors++;
        close(c.fd);
        c.fd = -1;
      }
    }
    if (lg.received >= expected) break;
    if (!lg.Pump(done_sending ? 50 : 1)) break;
    uint64_t now = NowNs();
    if (lg.received != last_received) {
      last_received = lg.received;
      last_progress = now;
    } else if (now - last_progress >
               static_cast<uint64_t>(idle_timeout_ms) * 1000000ull) {
      break;  // stalled: report what we have
    }
  }
  uint64_t wall = NowNs() - t0;

  uint64_t p50 = 0, p99 = 0, mx = 0;
  if (!lg.lat.empty()) {
    size_t i50 = lg.lat.size() / 2;
    size_t i99 = lg.lat.size() * 99 / 100;
    if (i99 >= lg.lat.size()) i99 = lg.lat.size() - 1;
    std::nth_element(lg.lat.begin(), lg.lat.begin() + i50, lg.lat.end());
    p50 = lg.lat[i50];
    std::nth_element(lg.lat.begin(), lg.lat.begin() + i99, lg.lat.end());
    p99 = lg.lat[i99];
    mx = *std::max_element(lg.lat.begin(), lg.lat.end());
  }
  out[0] = lg.sent;
  out[1] = lg.received;
  out[2] = wall;
  out[3] = p50;
  out[4] = p99;
  out[5] = mx;
  out[6] = lg.acks;
  out[7] = lg.errors;
  return 0;
}

// --- MQTT-SN/UDP fleet (round 11) -------------------------------------------
// The emqtt-bench analogue for the SN gateway: a connected-UDP fleet
// speaking the shared sn.h codec (the same functions the host decodes
// with). Subscribers SUBSCRIBE "lgsn/<i>" and count deliveries (8-byte
// ns stamp at the payload head, like the TCP fleet); publisher j
// REGISTERs and blasts "lgsn/<j % n_subs>". UDP has no transport
// backpressure, so pacing is ALWAYS windowed (sent-minus-progress cap;
// window=0 defaults to 1024) — an unpaced blast would measure kernel
// datagram drops, not the broker.

namespace {

struct SnLgConn {
  int fd = -1;
  uint32_t idx = 0;
  bool is_sub = false;
  bool connacked = false;
  bool subacked = false;
  bool regacked = false;
  uint16_t pub_tid = 0;  // publisher's registered topic id
  std::string obuf;      // messages packed per datagram (sn.h cap)
};

}  // namespace

// out[8]: sent, received, wall_ns, p50_ns, p99_ns, max_ns, acks, errors
int emqx_loadgen_run_sn(const char* host, uint16_t port, uint32_t n_subs,
                        uint32_t n_pubs, uint32_t msgs_per_pub,
                        uint8_t qos, uint32_t payload_len,
                        int idle_timeout_ms, uint32_t window, int warmup,
                        uint64_t* out) {
  namespace lsn = emqx_native::sn;
  if (window == 0) window = 1024;
  uint32_t total = n_subs + n_pubs;
  std::vector<SnLgConn> conns(total);
  int ep = epoll_create1(EPOLL_CLOEXEC);
  if (ep < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(ep);
    return -2;
  }
  uint64_t sent = 0, received = 0, acks = 0, errors = 0;
  std::vector<uint64_t> lat;
  lat.reserve(1 << 20);

  auto cleanup = [&]() {
    for (auto& c : conns)
      if (c.fd >= 0) close(c.fd);
    close(ep);
  };
  // Messages pack into aggregate datagrams (one send() per ~46 small
  // messages instead of one each — per-datagram UDP syscalls dominate
  // on sandboxed kernels). flush_conn ships the pending aggregate;
  // every sender loop flushes before blocking so nothing is stranded.
  auto flush_conn = [&](SnLgConn& c) {
    if (c.obuf.empty()) return;
    if (send(c.fd, c.obuf.data(), c.obuf.size(), MSG_NOSIGNAL) < 0 &&
        errno != EAGAIN && errno != EWOULDBLOCK)
      errors++;
    c.obuf.clear();
  };
  auto send_msg = [&](SnLgConn& c, const lsn::SnMsg& m) {
    std::string dg;
    lsn::Serialize(m, &dg);
    if (!c.obuf.empty() && c.obuf.size() + dg.size() > lsn::kPackDatagram)
      flush_conn(c);
    c.obuf += dg;
    if (c.obuf.size() >= lsn::kPackDatagram) flush_conn(c);
  };

  for (uint32_t i = 0; i < total; i++) {
    SnLgConn& c = conns[i];
    c.idx = i;
    c.is_sub = i < n_subs;
    c.fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (c.fd < 0 ||
        connect(c.fd, reinterpret_cast<sockaddr*>(&addr),
                sizeof(addr)) < 0) {
      cleanup();
      return -3;
    }
    int buf = 4 << 20;  // datagram bursts queue in the kernel, not drop
    setsockopt(c.fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = i;
    epoll_ctl(ep, EPOLL_CTL_ADD, c.fd, &ev);
    lsn::SnMsg m;
    m.type = lsn::kConnect;
    m.flags = lsn::kFClean;
    m.duration = 60;
    m.clientid = (c.is_sub ? "lgsns" : "lgsnp") + std::to_string(i);
    send_msg(c, m);
  }

  auto pump = [&](int timeout_ms) {
    for (auto& c : conns) flush_conn(c);  // nothing stranded across waits
    epoll_event evs[128];
    int n = epoll_wait(ep, evs, 128, timeout_ms);
    if (n < 0) return errno == EINTR;
    uint8_t chunk[65536];
    std::vector<lsn::SnMsg> msgs;
    for (int i = 0; i < n; i++) {
      SnLgConn& c = conns[evs[i].data.u32];
      if (c.fd < 0) continue;
      for (;;) {
        ssize_t r = recv(c.fd, chunk, sizeof(chunk), 0);
        if (r < 0) {
          if (errno == EINTR) continue;
          break;  // EAGAIN (or ICMP error: next send surfaces it)
        }
        if (r == 0) continue;
        msgs.clear();
        lsn::ParseAll(chunk, static_cast<size_t>(r), &msgs);
        for (lsn::SnMsg& m : msgs) {
          if (m.type == lsn::kConnack) {
            c.connacked = true;
          } else if (m.type == lsn::kSuback) {
            c.subacked = true;
          } else if (m.type == lsn::kRegack) {
            c.regacked = true;
            c.pub_tid = m.topic_id;
          } else if (m.type == lsn::kRegister) {
            // gateway auto-REGISTER ahead of a delivery: acknowledge
            lsn::SnMsg ra;
            ra.type = lsn::kRegack;
            ra.topic_id = m.topic_id;
            ra.msg_id = m.msg_id;
            send_msg(c, ra);
          } else if (m.type == lsn::kPublish) {
            int dq = lsn::QosOf(m.flags);
            if (dq > 0) {
              lsn::SnMsg pa;
              pa.type = lsn::kPuback;
              pa.topic_id = m.topic_id;
              pa.msg_id = m.msg_id;
              send_msg(c, pa);
            }
            if (m.data.size() >= 8) {
              uint64_t stamp;
              memcpy(&stamp, m.data.data(), 8);
              uint64_t now = NowNs();
              if (now > stamp && now - stamp < 60ull * 1000000000ull)
                lat.push_back(now - stamp);
            }
            received++;
          } else if (m.type == lsn::kPuback) {
            acks++;
          }
        }
      }
      flush_conn(c);  // replies (PUBACK/REGACK) go out with the drain
    }
    return true;
  };

  // handshake: CONNACKs, then SUBACKs + publisher REGACKs (deadline
  // 15s with one datagram-loss retry at half time)
  uint64_t deadline = NowNs() + 15ull * 1000000000ull;
  bool retried = false;
  auto phase_done = [&](int phase) {
    for (auto& c : conns) {
      if (phase == 0 && !c.connacked) return false;
      if (phase == 1 && c.is_sub && !c.subacked) return false;
      if (phase == 1 && !c.is_sub && !c.regacked) return false;
    }
    return true;
  };
  while (!phase_done(0)) {
    if (NowNs() > deadline || !pump(100)) {
      cleanup();
      return -5;
    }
    if (!retried && NowNs() > deadline - 7ull * 1000000000ull) {
      retried = true;  // a lost CONNECT datagram: one resend sweep
      for (auto& c : conns)
        if (!c.connacked) {
          lsn::SnMsg m;
          m.type = lsn::kConnect;
          m.flags = lsn::kFClean;
          m.duration = 60;
          m.clientid =
              (c.is_sub ? "lgsns" : "lgsnp") + std::to_string(c.idx);
          send_msg(c, m);
        }
    }
  }
  for (uint32_t i = 0; i < total; i++) {
    SnLgConn& c = conns[i];
    if (c.is_sub) {
      lsn::SnMsg m;
      m.type = lsn::kSubscribe;
      m.flags = lsn::QosFlags(qos);
      m.msg_id = 1;
      m.topic_name = "lgsn/" + std::to_string(i);
      send_msg(c, m);
    } else {
      lsn::SnMsg m;
      m.type = lsn::kRegister;
      m.msg_id = 1;
      m.topic_name =
          "lgsn/" + std::to_string(n_subs ? (i - n_subs) % n_subs : 0);
      send_msg(c, m);
    }
  }
  while (!phase_done(1)) {
    if (NowNs() > deadline || !pump(100)) {
      cleanup();
      return -6;
    }
  }

  uint64_t expected = static_cast<uint64_t>(n_pubs) * msgs_per_pub;
  std::string pad(payload_len > 8 ? payload_len - 8 : 0, 'x');

  auto publish_one = [&](SnLgConn& c, uint8_t q, uint16_t mid) {
    uint64_t stamp = NowNs();
    lsn::SnMsg m;
    m.type = lsn::kPublish;
    m.flags = lsn::QosFlags(q);
    m.topic_id = c.pub_tid;
    m.msg_id = mid;
    m.data.assign(reinterpret_cast<char*>(&stamp), 8);
    m.data += pad;
    send_msg(c, m);
  };

  if (warmup) {
    // one slow-path message per publisher earns the publish permit;
    // then idle past the broker's grant step (the TCP fleet's shape)
    for (uint32_t j = 0; j < n_pubs; j++)
      publish_one(conns[n_subs + j], 0, 0);
    uint64_t settle = NowNs() + 800ull * 1000000ull;
    while (NowNs() < settle) pump(50);
    received = acks = 0;
    lat.clear();
  }

  // windowed blast: total outstanding (unacked for qos1, undelivered
  // for qos0-with-subs) capped at `window`
  std::vector<uint32_t> next_msg(n_pubs, 0);
  uint64_t t0 = NowNs();
  uint64_t last_progress = t0;
  uint64_t last_seen = 0;
  uint16_t mid = 1;
  while (true) {
    bool done_sending = true;
    uint64_t progress = qos ? acks : (n_subs ? received : sent);
    for (uint32_t j = 0; j < n_pubs; j++) {
      SnLgConn& c = conns[n_subs + j];
      uint32_t burst = 0;
      while (next_msg[j] < msgs_per_pub && sent - progress < window &&
             burst++ < 64) {
        mid = mid == 0xFFFF ? 1 : mid + 1;
        publish_one(c, qos, mid);
        next_msg[j]++;
        sent++;
      }
      if (next_msg[j] < msgs_per_pub) done_sending = false;
    }
    bool complete = done_sending &&
                    (qos ? acks >= expected : true) &&
                    (n_subs ? received >= expected : true);
    if (complete) break;
    if (!pump(done_sending ? 50 : 1)) break;
    uint64_t seen = received + acks;
    uint64_t now = NowNs();
    if (seen != last_seen) {
      last_seen = seen;
      last_progress = now;
    } else if (now - last_progress >
               static_cast<uint64_t>(idle_timeout_ms) * 1000000ull) {
      break;  // stalled (datagram loss): report what we have
    }
  }
  uint64_t wall = NowNs() - t0;

  uint64_t p50 = 0, p99 = 0, mx = 0;
  if (!lat.empty()) {
    size_t i50 = lat.size() / 2;
    size_t i99 = lat.size() * 99 / 100;
    if (i99 >= lat.size()) i99 = lat.size() - 1;
    std::nth_element(lat.begin(), lat.begin() + i50, lat.end());
    p50 = lat[i50];
    std::nth_element(lat.begin(), lat.begin() + i99, lat.end());
    p99 = lat[i99];
    mx = *std::max_element(lat.begin(), lat.end());
  }
  out[0] = sent;
  out[1] = received;
  out[2] = wall;
  out[3] = p50;
  out[4] = p99;
  out[5] = mx;
  out[6] = acks;
  out[7] = errors;
  cleanup();
  return 0;
}

// -- conn-scale herd (round 16) --------------------------------------------
//
// The C10M axis the fleet above cannot exercise: N mostly-idle conns
// that connect in a storm, then just sit there honoring staggered
// keepalives while a (separate, small) loadgen fleet measures fan-out
// throughput against the same broker. Per-conn state is deliberately
// tiny (the herd itself must not be the memory story it measures) and
// PINGREQ->PINGRESP round trips are the keepalive-latency probe: the
// bench's "keepalive p99 honored" gate is this herd's ping RTT p99
// plus zero broker-initiated closes during the hold.
//
// ctypes releases the GIL for the whole call; `live` is a 4-slot
// progress surface the caller polls from Python (connacked, errors,
// pings, broker_closes) and `stop` ends the hold early.

int emqx_loadgen_conn_scale(const char* host, uint16_t port,
                            uint32_t n_conns, uint32_t burst,
                            uint16_t keepalive_s, uint32_t sub_every,
                            uint32_t hold_ms, int proto_ver,
                            volatile int32_t* stop,
                            volatile uint64_t* live, uint64_t* out) {
  struct HerdConn {
    int fd = -1;
    // 0 = TCP connecting (awaiting writability), 1 = CONNECT sent
    // (awaiting CONNACK), 2 = up, 3 = dead
    uint8_t state = 0;
    uint64_t ping_t0 = 0;       // outstanding PINGREQ stamp (0 = none)
    uint64_t next_ping_ms = 0;  // staggered schedule
    std::string inbuf;          // partial-frame carry (tiny)
  };
  if (burst == 0) burst = 512;
  int ep = epoll_create1(EPOLL_CLOEXEC);
  if (ep < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(ep);
    return -2;
  }
  std::vector<HerdConn> conns(n_conns);
  std::vector<uint64_t> rtts;
  uint64_t t_start = NowNs();
  uint64_t connacked = 0, errors = 0, pings = 0, closes = 0;
  uint32_t started = 0;
  uint64_t ka_ms = static_cast<uint64_t>(keepalive_s) * 1000;
  auto now_ms = []() { return NowNs() / 1000000ull; };
  auto fail = [&](HerdConn& c) {
    if (c.fd >= 0) close(c.fd);
    c.fd = -1;
    if (c.state == 2) closes++;
    c.state = 3;
    errors++;
    if (live) {
      live[1] = errors;
      live[3] = closes;
    }
  };
  // minimal inbound machine: split frames (1-byte varints cover every
  // packet the herd can see except a delivered PUBLISH, which it skips
  // with the full varint), count CONNACK/PINGRESP
  auto ingest = [&](uint32_t idx, const uint8_t* data, size_t len) {
    HerdConn& c = conns[idx];
    c.inbuf.append(reinterpret_cast<const char*>(data), len);
    size_t pos = 0;
    while (true) {
      if (c.inbuf.size() - pos < 2) break;
      size_t hp = pos + 1;
      uint32_t rem = 0, mult = 1;
      bool done = false, bad = false;
      while (hp < c.inbuf.size()) {
        uint8_t b = static_cast<uint8_t>(c.inbuf[hp++]);
        rem += (b & 0x7F) * mult;
        if (!(b & 0x80)) {
          done = true;
          break;
        }
        if (mult > 128u * 128u * 128u) {
          bad = true;
          break;
        }
        mult *= 128;
      }
      if (bad) {
        fail(c);
        return;
      }
      if (!done || c.inbuf.size() - hp < rem) break;
      uint8_t type = static_cast<uint8_t>(c.inbuf[pos]) >> 4;
      if (type == 2 && c.state == 1) {
        c.state = 2;
        connacked++;
        if (live) live[0] = connacked;
        uint64_t base = now_ms();
        // stagger first pings uniformly across one keepalive interval
        c.next_ping_ms =
            base + 1 + (ka_ms ? (static_cast<uint64_t>(idx) * ka_ms) /
                                    (n_conns ? n_conns : 1)
                              : 0);
        if (sub_every && idx % sub_every == 0) {
          std::string sub = Subscribe(
              1, "herd/" + std::to_string(idx), 0, proto_ver);
          if (send(c.fd, sub.data(), sub.size(), MSG_NOSIGNAL) < 0 &&
              errno != EAGAIN && errno != EWOULDBLOCK)
            fail(c);
        }
      } else if (type == 13 && c.ping_t0) {  // PINGRESP
        uint64_t rtt = NowNs() - c.ping_t0;
        c.ping_t0 = 0;
        rtts.push_back(rtt);
        pings++;
        if (live) live[2] = pings;
      }
      pos = hp + rem;
    }
    if (pos) c.inbuf.erase(0, pos);
  };
  // CONNECT goes out only once the TCP handshake completed (a send
  // right after a nonblocking connect() EAGAINs and would silently
  // strand the conn pre-CONNECT — measured as a ~40%% stall at 6k)
  auto send_connect = [&](uint32_t idx, HerdConn& c) {
    std::string body;
    PutU16(&body, 4);
    body += "MQTT";
    body.push_back(static_cast<char>(proto_ver));
    body.push_back(0x02);
    PutU16(&body, keepalive_s);
    if (proto_ver == 5) body.push_back('\0');
    std::string cid = "herd" + std::to_string(idx);
    PutU16(&body, static_cast<uint16_t>(cid.size()));
    body += cid;
    std::string f;
    f.push_back(0x10);
    PutVarint(&f, body.size());
    f += body;
    if (send(c.fd, f.data(), f.size(), MSG_NOSIGNAL) !=
        static_cast<ssize_t>(f.size())) {
      fail(c);
      return;
    }
    c.state = 1;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = idx;
    epoll_ctl(ep, EPOLL_CTL_MOD, c.fd, &ev);
  };
  auto pump = [&](int timeout_ms) {
    epoll_event evs[256];
    int n = epoll_wait(ep, evs, 256, timeout_ms);
    uint8_t chunk[16 * 1024];
    for (int i = 0; i < n; i++) {
      uint32_t idx = evs[i].data.u32;
      HerdConn& c = conns[idx];
      if (c.fd < 0) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        fail(c);
        continue;
      }
      if ((evs[i].events & EPOLLOUT) && c.state == 0) {
        int err = 0;
        socklen_t el = sizeof(err);
        getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &el);
        if (err) {
          fail(c);
          continue;
        }
        send_connect(idx, c);
        if (c.fd < 0) continue;
      }
      if (!(evs[i].events & EPOLLIN)) continue;
      for (;;) {
        ssize_t r = recv(c.fd, chunk, sizeof(chunk), 0);
        if (r > 0) {
          ingest(idx, chunk, static_cast<size_t>(r));
          if (c.fd < 0 || static_cast<size_t>(r) < sizeof(chunk)) break;
        } else if (r == 0) {
          fail(c);
          break;
        } else {
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          if (errno == EINTR) continue;
          fail(c);
          break;
        }
      }
    }
  };
  // the keepalive service: runs during the STORM too — a large herd's
  // connect phase can outlast a keepalive interval, and the broker's
  // wheel shows no mercy to a client that negotiated one and went mute
  uint64_t next_ping_scan = 0;
  auto service_pings = [&]() {
    if (!ka_ms) return;
    uint64_t t = now_ms();
    // the herd must not become its own O(N)-per-pump sweep: ping
    // deadlines have second granularity, so a 250ms scan cadence
    // keeps the fleet honest without stealing the (possibly single)
    // core from the broker under measurement
    if (t < next_ping_scan) return;
    next_ping_scan = t + 250;
    for (uint32_t i = 0; i < started; i++) {
      HerdConn& c = conns[i];
      if (c.state != 2 || c.fd < 0 || t < c.next_ping_ms) continue;
      uint8_t pingreq[2] = {0xC0, 0x00};
      ssize_t w = send(c.fd, pingreq, 2, MSG_NOSIGNAL);
      if (w == 2) {
        if (!c.ping_t0) c.ping_t0 = NowNs();
        c.next_ping_ms = t + ka_ms;
      } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
        fail(c);
      } else {
        c.next_ping_ms = t + 50;  // backpressured: retry shortly
      }
    }
  };
  // connect storm, paced at `burst` initiations per pump cycle
  uint64_t connect_deadline =
      now_ms() + 60000 + static_cast<uint64_t>(n_conns) / 10;
  while (started < n_conns || connacked + errors < started) {
    uint32_t launched = 0;
    while (started < n_conns && launched < burst) {
      uint32_t i = started++;
      launched++;
      HerdConn& c = conns[i];
      c.fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
      if (c.fd < 0) {
        c.state = 3;
        errors++;
        continue;
      }
      int rc = connect(c.fd, reinterpret_cast<sockaddr*>(&addr),
                       sizeof(addr));
      if (rc < 0 && errno != EINPROGRESS) {
        fail(c);
        continue;
      }
      epoll_event ev{};
      // writability = handshake done; send_connect flips to EPOLLIN
      ev.events = EPOLLIN | EPOLLOUT;
      ev.data.u32 = i;
      epoll_ctl(ep, EPOLL_CTL_ADD, c.fd, &ev);
      if (rc == 0) send_connect(i, c);  // loopback same-call completion
    }
    pump(5);
    service_pings();
    if (stop && *stop) break;
    if (now_ms() > connect_deadline) break;
  }
  uint64_t peak = connacked;
  // hold: idle herd honoring staggered keepalives
  uint64_t hold_end = now_ms() + hold_ms;
  while ((stop == nullptr || !*stop) && now_ms() < hold_end) {
    pump(20);
    service_pings();
  }
  for (auto& c : conns)
    if (c.fd >= 0) close(c.fd);
  close(ep);
  std::sort(rtts.begin(), rtts.end());
  auto pct = [&](double q) -> uint64_t {
    if (rtts.empty()) return 0;
    size_t k = static_cast<size_t>(q * (rtts.size() - 1));
    return rtts[k];
  };
  out[0] = peak;
  out[1] = errors;
  out[2] = pings;
  out[3] = pct(0.50);
  out[4] = pct(0.99);
  out[5] = rtts.empty() ? 0 : rtts.back();
  out[6] = NowNs() - t_start;
  out[7] = closes;
  return 0;
}

// -- CoAP observer/publisher fleet (round 19) --------------------------------
//
// The coap-bench client: observers GET+Observe /ps/lgc/<i> topics,
// publishers POST to them — NON for qos0 (deliveries counted at the
// observers), CON with ?qos=1 for qos1 (the 2.04 acks gate the
// window). Speaks the SHARED coap.h codec, so the same fleet drives
// the native listener and the asyncio gateway/coap.py with identical
// wire traffic and identical pacing (the SN bench discipline). CoAP
// forbids packing messages into one datagram, so the syscall
// amortization is sendmmsg/recvmmsg batches of WHOLE datagrams.
// `fanout` puts every observer on ONE topic (the fan-out arm).
//
// out[8]: sent, received, wall_ns, p50_ns, p99_ns, max_ns, acks, errors
int emqx_loadgen_run_coap(const char* host, uint16_t port, uint32_t n_subs,
                          uint32_t n_pubs, uint32_t msgs_per_pub,
                          uint8_t qos, uint32_t payload_len,
                          int idle_timeout_ms, uint32_t window, int warmup,
                          int fanout, uint64_t* out) {
  namespace lco = emqx_native::coap;
  if (window == 0) window = 256;
  uint32_t total = n_subs + n_pubs;
  struct CoapLg {
    int fd = -1;
    uint32_t idx = 0;
    bool is_sub = false;
    bool ready = false;       // observer: 2.05 seen; publisher: warm ack
    uint16_t mid = 0;
    std::vector<std::string> obuf;  // whole datagrams, sendmmsg-batched
  };
  std::vector<CoapLg> conns(total);
  int ep = epoll_create1(EPOLL_CLOEXEC);
  if (ep < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(ep);
    return -2;
  }
  uint64_t sent = 0, received = 0, acks = 0, errors = 0;
  std::vector<uint64_t> lat;
  lat.reserve(1 << 20);
  auto cleanup = [&]() {
    for (auto& c : conns)
      if (c.fd >= 0) close(c.fd);
    close(ep);
  };
  constexpr int kBatch = 32;
  auto flush_conn = [&](CoapLg& c) {
    size_t i = 0;
    while (i < c.obuf.size()) {
      mmsghdr mm[kBatch];
      iovec iov[kBatch];
      int n = 0;
      for (; n < kBatch && i + n < c.obuf.size(); n++) {
        iov[n].iov_base = const_cast<char*>(c.obuf[i + n].data());
        iov[n].iov_len = c.obuf[i + n].size();
        memset(&mm[n].msg_hdr, 0, sizeof(mm[n].msg_hdr));
        mm[n].msg_hdr.msg_iov = &iov[n];
        mm[n].msg_hdr.msg_iovlen = 1;
      }
      int sn2 = sendmmsg(c.fd, mm, n, MSG_NOSIGNAL);
      if (sn2 < 0) {
        if (errno == EINTR) continue;
        if (errno != EAGAIN && errno != EWOULDBLOCK) errors++;
        break;  // keep the tail for the next flush
      }
      i += static_cast<size_t>(sn2);
    }
    c.obuf.erase(c.obuf.begin(), c.obuf.begin() + i);
  };
  auto send_msg = [&](CoapLg& c, const lco::CoapMsg& m) {
    std::string dg;
    lco::Serialize(m, &dg);
    c.obuf.push_back(std::move(dg));
    if (c.obuf.size() >= kBatch) flush_conn(c);
  };
  auto topic_of = [&](uint32_t i) {
    return std::to_string(fanout ? 0 : (n_subs ? i % n_subs : 0));
  };
  auto make_req = [&](CoapLg& c, uint8_t type, uint8_t code,
                      const std::string& tseg) {
    lco::CoapMsg m;
    m.type = type;
    m.code = code;
    c.mid = c.mid == 0xFFFF ? 1 : c.mid + 1;
    m.mid = c.mid;
    if (c.is_sub) {
      m.token.push_back(static_cast<char>(c.idx >> 8));
      m.token.push_back(static_cast<char>(c.idx & 0xFF));
    }
    m.options.emplace_back(lco::kOptUriPath, "ps");
    m.options.emplace_back(lco::kOptUriPath, "lgc");
    m.options.emplace_back(lco::kOptUriPath, tseg);
    m.options.emplace_back(
        lco::kOptUriQuery,
        std::string("clientid=") + (c.is_sub ? "lgcs" : "lgcp") +
            std::to_string(c.idx));
    if (qos)
      m.options.emplace_back(lco::kOptUriQuery,
                             std::string("qos=") + char('0' + qos));
    return m;
  };

  for (uint32_t i = 0; i < total; i++) {
    CoapLg& c = conns[i];
    c.idx = i;
    c.is_sub = i < n_subs;
    c.fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (c.fd < 0 ||
        connect(c.fd, reinterpret_cast<sockaddr*>(&addr),
                sizeof(addr)) < 0) {
      cleanup();
      return -3;
    }
    int buf = 4 << 20;
    setsockopt(c.fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = i;
    epoll_ctl(ep, EPOLL_CTL_ADD, c.fd, &ev);
  }

  // handshake: observers register (CON GET Observe:0 -> 2.05);
  // publishers send ONE CON warmup POST (earns the permit; the 2.04
  // proves the gateway session is up) — both planes, same shape
  auto kickoff = [&](CoapLg& c) {
    if (c.is_sub) {
      lco::CoapMsg m = make_req(c, lco::kCon, lco::kGet,
                                topic_of(c.idx));
      m.options.emplace_back(lco::kOptObserve, std::string());
      send_msg(c, m);
    } else {
      lco::CoapMsg m =
          make_req(c, lco::kCon, lco::kPost, topic_of(c.idx));
      m.payload.assign(8, '\0');  // zero stamp: never a latency sample
      send_msg(c, m);
    }
  };
  for (auto& c : conns) kickoff(c);

  uint8_t rxbuf[kBatch * 2048];
  auto pump = [&](int timeout_ms) {
    for (auto& c : conns) flush_conn(c);
    epoll_event evs[128];
    int n = epoll_wait(ep, evs, 128, timeout_ms);
    if (n < 0) return errno == EINTR;
    for (int i = 0; i < n; i++) {
      CoapLg& c = conns[evs[i].data.u32];
      if (c.fd < 0) continue;
      for (;;) {
        mmsghdr mm[kBatch];
        iovec iov[kBatch];
        for (int k = 0; k < kBatch; k++) {
          iov[k].iov_base = rxbuf + k * 2048;
          iov[k].iov_len = 2048;
          memset(&mm[k].msg_hdr, 0, sizeof(mm[k].msg_hdr));
          mm[k].msg_hdr.msg_iov = &iov[k];
          mm[k].msg_hdr.msg_iovlen = 1;
        }
        int r = recvmmsg(c.fd, mm, kBatch, 0, nullptr);
        if (r < 0) {
          if (errno == EINTR) continue;
          break;  // EAGAIN (or ICMP error: the next send surfaces it)
        }
        for (int k = 0; k < r; k++) {
          lco::CoapMsg m;
          if (!lco::Parse(rxbuf + k * 2048, mm[k].msg_len, &m)) continue;
          if (m.code == lco::kContent &&
              (m.type == lco::kCon || m.type == lco::kNon) &&
              m.Opt(lco::kOptObserve) != nullptr) {
            // an observe notification (the registration reply is an
            // ACK and lands in the branch below)
            if (m.type == lco::kCon) {
              lco::CoapMsg a;
              a.type = lco::kAck;
              a.mid = m.mid;
              send_msg(c, a);
            }
            if (m.payload.size() >= 8) {
              uint64_t stamp;
              memcpy(&stamp, m.payload.data(), 8);
              uint64_t now = NowNs();
              if (stamp && now > stamp &&
                  now - stamp < 60ull * 1000000000ull)
                lat.push_back(now - stamp);
            }
            received++;
          } else if (m.type == lco::kAck) {
            if (m.code == lco::kContent) {
              c.ready = true;  // observe registration accepted
            } else if (m.code == lco::kChanged) {
              if (!c.ready)
                c.ready = true;  // the warmup POST's ack
              else
                acks++;
            } else if (m.code >= 0x80) {
              errors++;
            }
          }
        }
        if (r < kBatch) break;
      }
      flush_conn(c);
    }
    return true;
  };

  uint64_t deadline = NowNs() + 15ull * 1000000000ull;
  bool retried = false;
  auto all_ready = [&]() {
    for (auto& c : conns)
      if (!c.ready) return false;
    return true;
  };
  while (!all_ready()) {
    if (NowNs() > deadline || !pump(100)) {
      cleanup();
      return -5;
    }
    if (!retried && NowNs() > deadline - 7ull * 1000000000ull) {
      retried = true;  // one datagram-loss resend sweep
      for (auto& c : conns)
        if (!c.ready) kickoff(c);
    }
  }
  if (warmup) {
    // idle past the broker's permit-grant step (the SN fleet's shape)
    uint64_t settle = NowNs() + 800ull * 1000000ull;
    while (NowNs() < settle) pump(50);
    received = acks = 0;
    lat.clear();
  }

  uint64_t expected = static_cast<uint64_t>(n_pubs) * msgs_per_pub;
  uint64_t expect_rx = fanout ? expected * n_subs : expected;
  std::string pad(payload_len > 8 ? payload_len - 8 : 0, 'x');
  std::vector<uint32_t> next_msg(n_pubs, 0);
  uint64_t t0 = NowNs();
  uint64_t last_progress = t0;
  uint64_t last_seen = 0;
  bool stalled = false;
  while (true) {
    bool done_sending = true;
    uint64_t progress = qos ? acks : (n_subs ? received : sent);
    uint64_t scale = (!qos && n_subs && fanout) ? n_subs : 1;
    for (uint32_t j = 0; j < n_pubs; j++) {
      CoapLg& c = conns[n_subs + j];
      uint32_t burst = 0;
      while (next_msg[j] < msgs_per_pub &&
             sent - progress / scale < window && burst++ < 64) {
        lco::CoapMsg m = make_req(c, qos ? lco::kCon : lco::kNon,
                                  lco::kPost, topic_of(c.idx));
        uint64_t stamp = NowNs();
        m.payload.assign(reinterpret_cast<char*>(&stamp), 8);
        m.payload += pad;
        send_msg(c, m);
        next_msg[j]++;
        sent++;
      }
      if (next_msg[j] < msgs_per_pub) done_sending = false;
    }
    bool complete = done_sending && (qos ? acks >= expected : true) &&
                    (n_subs ? received >= expect_rx : true);
    if (complete) break;
    if (!pump(done_sending ? 50 : 1)) break;
    uint64_t seen = received + acks;
    uint64_t now = NowNs();
    if (seen != last_seen) {
      last_seen = seen;
      last_progress = now;
    } else if (now - last_progress >
               static_cast<uint64_t>(idle_timeout_ms) * 1000000ull) {
      // stalled (datagram loss): report what we have — measured to
      // the LAST progress stamp, so the dead idle-timeout tail never
      // deflates the rate (which would flatter whichever arm runs
      // against the lossier plane)
      stalled = true;
      break;
    }
  }
  uint64_t wall = (stalled ? last_progress : NowNs()) - t0;
  if (wall == 0) wall = 1;
  uint64_t p50 = 0, p99 = 0, mx = 0;
  if (!lat.empty()) {
    size_t i50 = lat.size() / 2;
    size_t i99 = lat.size() * 99 / 100;
    if (i99 >= lat.size()) i99 = lat.size() - 1;
    std::nth_element(lat.begin(), lat.begin() + i50, lat.end());
    p50 = lat[i50];
    std::nth_element(lat.begin(), lat.begin() + i99, lat.end());
    p99 = lat[i99];
    mx = *std::max_element(lat.begin(), lat.end());
  }
  out[0] = sent;
  out[1] = received;
  out[2] = wall;
  out[3] = p50;
  out[4] = p99;
  out[5] = mx;
  out[6] = acks;
  out[7] = errors;
  cleanup();
  return 0;
}

}  // extern "C"
