// Native connection host: an epoll event loop owning listener + client
// sockets, doing MQTT framing in C++ and exchanging complete frames with
// the Python protocol layer through a compact event-record stream.
//
// This is the TPU-era answer to the BEAM's role in the reference
// (SURVEY.md §2.4 "[NATIVE] BEAM VM schedulers/ports"): the reference
// relies on the VM's C-level {active,N} socket polling + per-process
// mailboxes (emqx_connection.erl:132); here a C++ epoll loop performs
// accept/read/frame/write and batches complete frames up to the driver,
// which runs the channel FSM and the device router.
//
// Threading contract:
//   - exactly ONE thread calls emqx_host_poll (it runs the event loop);
//   - emqx_host_send / emqx_host_close_conn are thread-safe and may be
//     called from any thread (they enqueue + wake the poller via eventfd);
//   - emqx_host_destroy only after the polling thread has stopped.
//
// Event record wire format (host -> Python), little-endian:
//   u8 kind | u64 conn_id | u32 len | payload[len]
//   kind 1 = OPEN   payload = "ip:port" of the peer
//   kind 2 = FRAME  payload = one complete MQTT frame (verbatim bytes)
//   kind 3 = CLOSED payload = reason string

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "frame.h"

namespace emqx_native {
namespace {

constexpr size_t kReadChunk = 64 * 1024;

struct Conn {
  int fd = -1;
  Framer framer;
  std::string outbuf;   // unsent bytes (partial-write backlog)
  size_t outpos = 0;
  bool want_close = false;  // close once outbuf drains
};

std::string EncodeRecord(uint8_t kind, uint64_t id, const char* data,
                         size_t len) {
  std::string rec;
  rec.reserve(13 + len);
  rec.push_back(static_cast<char>(kind));
  for (int i = 0; i < 8; i++)
    rec.push_back(static_cast<char>((id >> (8 * i)) & 0xFF));
  for (int i = 0; i < 4; i++)
    rec.push_back(static_cast<char>((len >> (8 * i)) & 0xFF));
  rec.append(data, len);
  return rec;
}

int SetNonBlocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags < 0 ? -1 : fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

class Host {
 public:
  Host(uint32_t max_size, uint32_t max_conns)
      : max_size_(max_size), max_conns_(max_conns) {}

  ~Host() {
    for (auto& [id, c] : conns_) close(c.fd);
    if (listen_fd_ >= 0) close(listen_fd_);
    if (wake_fd_ >= 0) close(wake_fd_);
    if (epoll_fd_ >= 0) close(epoll_fd_);
  }

  bool Init(const char* bind_addr, uint16_t port) {
    epoll_fd_ = epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    listen_fd_ = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (epoll_fd_ < 0 || wake_fd_ < 0 || listen_fd_ < 0) return false;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return false;
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    if (listen(listen_fd_, 1024) < 0) return false;
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenTag;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    ev.events = EPOLLIN;
    ev.data.u64 = kWakeTag;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    return true;
  }

  int port() const { return port_; }

  // Thread-safe enqueue of outbound bytes for a connection.
  int Send(uint64_t id, const uint8_t* data, size_t len) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_.emplace_back(id, std::string(
          reinterpret_cast<const char*>(data), len));
    }
    Wake();
    return 0;
  }

  int CloseConn(uint64_t id) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_closes_.push_back(id);
    }
    Wake();
    return 0;
  }

  // Run one event-loop step on the calling thread; fill `buf` with as
  // many whole event records as fit. Returns bytes written (0 on
  // timeout with no events).
  long Poll(uint8_t* buf, size_t cap, int timeout_ms) {
    if (events_.empty()) {
      ApplyPending();
      epoll_event evs[256];
      int n = epoll_wait(epoll_fd_, evs, 256, timeout_ms);
      if (n < 0) return errno == EINTR ? 0 : -1;
      for (int i = 0; i < n; i++) HandleEvent(evs[i]);
      ApplyPending();
    }
    size_t written = 0;
    while (!events_.empty()) {
      const std::string& rec = events_.front();
      if (written + rec.size() > cap) {
        // A record larger than the caller's whole buffer can never be
        // delivered; retaining it would busy-spin Poll forever.  Drop it
        // and close the offending connection with an error event (which
        // is small and will fit on a later call).
        if (written == 0 && rec.size() > cap) {
          uint64_t id;
          memcpy(&id, rec.data() + 1, 8);
          events_.pop_front();
          if (id != kListenTag && id != kWakeTag) Drop(id, "oversized", true);
          continue;
        }
        break;
      }
      memcpy(buf + written, rec.data(), rec.size());
      written += rec.size();
      events_.pop_front();
    }
    return static_cast<long>(written);
  }

 private:
  static constexpr uint64_t kListenTag = ~0ull;
  static constexpr uint64_t kWakeTag = ~0ull - 1;

  void Wake() {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(wake_fd_, &one, sizeof(one));
  }

  // Move cross-thread sends/closes into connection write buffers.
  void ApplyPending() {
    std::vector<std::pair<uint64_t, std::string>> sends;
    std::vector<uint64_t> closes;
    {
      std::lock_guard<std::mutex> lk(mu_);
      sends.swap(pending_);
      closes.swap(pending_closes_);
    }
    for (auto& [id, data] : sends) {
      auto it = conns_.find(id);
      if (it == conns_.end()) continue;
      it->second.outbuf += data;
      Flush(id, it->second);
    }
    for (uint64_t id : closes) {
      auto it = conns_.find(id);
      if (it == conns_.end()) continue;
      it->second.want_close = true;
      if (it->second.outbuf.size() == it->second.outpos)
        Drop(id, "closed_by_host", false);
    }
  }

  void HandleEvent(const epoll_event& ev) {
    if (ev.data.u64 == kWakeTag) {
      uint64_t junk;
      while (read(wake_fd_, &junk, sizeof(junk)) > 0) {}
      return;
    }
    if (ev.data.u64 == kListenTag) {
      Accept();
      return;
    }
    uint64_t id = ev.data.u64;
    auto it = conns_.find(id);
    if (it == conns_.end()) return;
    if (ev.events & (EPOLLHUP | EPOLLERR)) {
      Drop(id, "sock_error", true);
      return;
    }
    if (ev.events & EPOLLOUT) {
      Flush(id, it->second);
      it = conns_.find(id);
      if (it == conns_.end()) return;
    }
    if (ev.events & EPOLLIN) Read(id, it->second);
  }

  void Accept() {
    for (;;) {
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = accept4(listen_fd_, reinterpret_cast<sockaddr*>(&peer), &plen,
                       SOCK_NONBLOCK | SOCK_CLOEXEC);
      if (fd < 0) return;
      if (conns_.size() >= max_conns_) {  // esockd max-conn limiting
        close(fd);
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      uint64_t id = next_id_++;
      Conn c;
      c.fd = fd;
      c.framer = Framer(max_size_);
      conns_.emplace(id, std::move(c));
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.u64 = id;
      epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
      char ip[INET_ADDRSTRLEN] = "?";
      inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
      std::string info = std::string(ip) + ":" +
                         std::to_string(ntohs(peer.sin_port));
      events_.push_back(EncodeRecord(1, id, info.data(), info.size()));
    }
  }

  void Read(uint64_t id, Conn& c) {
    uint8_t chunk[kReadChunk];
    for (;;) {
      ssize_t n = recv(c.fd, chunk, sizeof(chunk), 0);
      if (n > 0) {
        std::vector<std::string> frames;
        FrameStatus st = c.framer.Feed(chunk, static_cast<size_t>(n), &frames);
        for (auto& f : frames)
          events_.push_back(EncodeRecord(2, id, f.data(), f.size()));
        if (st != FrameStatus::kOk) {
          Drop(id, "frame_error", true);
          return;
        }
        if (static_cast<size_t>(n) < sizeof(chunk)) return;
      } else if (n == 0) {
        Drop(id, "sock_closed", true);
        return;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        if (errno == EINTR) continue;
        Drop(id, "sock_error", true);
        return;
      }
    }
  }

  void Flush(uint64_t id, Conn& c) {
    while (c.outpos < c.outbuf.size()) {
      ssize_t n = ::send(c.fd, c.outbuf.data() + c.outpos,
                         c.outbuf.size() - c.outpos, MSG_NOSIGNAL);
      if (n > 0) {
        c.outpos += static_cast<size_t>(n);
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.u64 = id;
        epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, c.fd, &ev);
        return;
      } else if (n < 0 && errno == EINTR) {
        continue;
      } else {
        Drop(id, "sock_error", true);
        return;
      }
    }
    c.outbuf.clear();
    c.outpos = 0;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = id;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, c.fd, &ev);
    if (c.want_close) Drop(id, "closed_by_host", false);
  }

  void Drop(uint64_t id, const char* reason, bool notify) {
    auto it = conns_.find(id);
    if (it == conns_.end()) return;
    epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
    close(it->second.fd);
    conns_.erase(it);
    if (notify)
      events_.push_back(EncodeRecord(3, id, reason, strlen(reason)));
  }

  uint32_t max_size_;
  uint32_t max_conns_;
  int epoll_fd_ = -1;
  int listen_fd_ = -1;
  int wake_fd_ = -1;
  int port_ = 0;
  uint64_t next_id_ = 1;
  std::unordered_map<uint64_t, Conn> conns_;
  std::deque<std::string> events_;  // encoded records awaiting pickup
  std::mutex mu_;
  std::vector<std::pair<uint64_t, std::string>> pending_;
  std::vector<uint64_t> pending_closes_;
};

}  // namespace
}  // namespace emqx_native

// ---------------------------------------------------------------------------
// C ABI for ctypes

extern "C" {

void* emqx_host_create(const char* bind_addr, uint16_t port,
                       uint32_t max_size, uint32_t max_conns) {
  auto* h = new emqx_native::Host(max_size, max_conns);
  if (!h->Init(bind_addr, port)) {
    delete h;
    return nullptr;
  }
  return h;
}

int emqx_host_port(void* h) {
  return static_cast<emqx_native::Host*>(h)->port();
}

long emqx_host_poll(void* h, uint8_t* buf, size_t cap, int timeout_ms) {
  return static_cast<emqx_native::Host*>(h)->Poll(buf, cap, timeout_ms);
}

int emqx_host_send(void* h, uint64_t conn, const uint8_t* data, size_t len) {
  return static_cast<emqx_native::Host*>(h)->Send(conn, data, len);
}

int emqx_host_close_conn(void* h, uint64_t conn) {
  return static_cast<emqx_native::Host*>(h)->CloseConn(conn);
}

void emqx_host_destroy(void* h) {
  delete static_cast<emqx_native::Host*>(h);
}

// --- standalone framer (for parity tests + non-socket embedding) ----------

void* emqx_framer_create(uint32_t max_size) {
  return new emqx_native::Framer(max_size);
}

// Feeds a chunk; returns a malloc'd buffer of concatenated
// [u32 len][frame bytes] records in *out/*out_len (caller frees with
// emqx_buf_free). Returns the FrameStatus as int.
int emqx_framer_feed(void* f, const uint8_t* data, size_t len, uint8_t** out,
                     size_t* out_len) {
  std::vector<std::string> frames;
  auto st = static_cast<emqx_native::Framer*>(f)->Feed(data, len, &frames);
  size_t total = 0;
  for (auto& fr : frames) total += 4 + fr.size();
  uint8_t* buf = static_cast<uint8_t*>(malloc(total ? total : 1));
  size_t pos = 0;
  for (auto& fr : frames) {
    uint32_t n = static_cast<uint32_t>(fr.size());
    memcpy(buf + pos, &n, 4);
    pos += 4;
    memcpy(buf + pos, fr.data(), fr.size());
    pos += fr.size();
  }
  *out = buf;
  *out_len = total;
  return static_cast<int>(st);
}

void emqx_framer_destroy(void* f) {
  delete static_cast<emqx_native::Framer*>(f);
}

void emqx_buf_free(void* p) { free(p); }

}  // extern "C"
