// Native connection host: an epoll event loop owning listener + client
// sockets, doing MQTT framing in C++ and exchanging complete frames with
// the Python protocol layer through a compact event-record stream —
// plus, since round 4, the QoS0/1 PUBLISH fast path: parse → match →
// fan-out entirely in C++ (SURVEY.md §7's "host side in C++" design,
// the emqx_connection.erl:403-440 → emqx_broker.erl:218-232 hot loop
// without a VM in the middle).
//
// Fast-path contract (enforced here, configured by the Python server):
//   - a connection only fast-paths after Python enables it post-CONNACK
//     (clean session, no mountpoint — broker/native_server.py);
//   - a PUBLISH only fast-paths when qos<=2, retain=0, topic is a plain
//     non-$ name, v5 property section is empty, AND Python has granted
//     this (conn, topic) a *permit* — the authz-cache analogue: the
//     first publish runs the full Python path (authorize, hooks, rules)
//     and the server grants the permit only if nothing slow listens;
//   - the match set comes from a mirror of the broker tables
//     (router.h); any matched *punt marker* (shared sub, persistent
//     session, non-native subscriber, subscription id) forwards the
//     frame to Python verbatim — native fan-out only runs when it is
//     provably complete;
//   - native QoS1/2 deliveries allocate packet ids in [32768, 65535];
//     Python sessions stay in [1, 32767] (session/session.py), so a
//     subscriber's PUBACK/PUBREC/PUBCOMP routes unambiguously: high
//     pids are consumed here, low pids forwarded to the Python session;
//   - publisher-side QoS2 exactly-once keys on the *awaiting-rel*
//     bitmap (emqx_session.erl:379-399): the native plane owns a
//     client packet id iff the id is in ITS awaiting-rel set, so a
//     PUBREL routes to whichever plane accepted the PUBLISH and the
//     two planes can never double-publish one id;
//   - window accounting (pid allocation, inflight insert/ack-erase,
//     window-full → pending-queue overflow) lives entirely here; the
//     Python sessions see ONE batched ack record per poll cycle
//     (kind 7, mirroring the rule-tap batching) instead of
//     per-message round trips.
//
// This is the TPU-era answer to the BEAM's role in the reference
// (SURVEY.md §2.4 "[NATIVE] BEAM VM schedulers/ports"): the reference
// relies on the VM's C-level {active,N} socket polling + per-process
// mailboxes (emqx_connection.erl:132); here a C++ epoll loop performs
// accept/read/frame/match/fan-out/write and batches the remaining
// frames up to the driver, which runs the channel FSM and the device
// router.
//
// Threading contract:
//   - exactly ONE thread calls emqx_host_poll (it runs the event loop);
//   - emqx_host_send / emqx_host_close_conn / the fast-path control
//     calls (sub_add/sub_del/permit/enable_fast/...) are thread-safe
//     and may be called from any thread (they enqueue + wake the
//     poller via eventfd; the loop applies them in ApplyPending, so
//     table mutations are serialized with matching);
//   - emqx_host_destroy only after the polling thread has stopped.
//
// Event record wire format (host -> Python), little-endian:
//   u8 kind | u64 conn_id | u32 len | payload[len]
//   kind 1 = OPEN   payload = "ip:port" of the peer ("ws:ip:port" for
//                   connections accepted on the WebSocket listener)
//   kind 2 = FRAME  payload = one complete MQTT frame (verbatim bytes)
//   kind 3 = CLOSED payload = reason string
//   kind 4 = LANE   conn_id = lane seq, payload = topic (device match)
//   kind 6 = TAP    payload = batched rule-tap records, one entry per
//                   tapped publish: [u64 publisher][u8 flags][u16 tlen]
//                   [topic] + (flags bit0 ? [u32 plen][payload] :
//                   payload identical to the PREVIOUS entry in this
//                   batch); flags bits 1-2 = qos, bit 3 = publisher
//                   DUP. Pre-parsed and
//                   payload-deduped so the Python rule worker never
//                   re-parses MQTT (the old full-frame copies were the
//                   rule-tap tax: BENCH_r05 rule_tap_vs_free=0.59)
//   kind 7 = ACKS   payload = one batched ack/window record per poll
//                   cycle: [u32 n] + n x ([u64 conn][u32 acked]
//                   [u32 rel][u32 inflight_now][u32 pending_now])
//   kind 9 = TRUNK  cluster-trunk plane events (trunk.h, round 9):
//                   payload[0] = sub-kind:
//                   [u8 1] link UP    conn_id = peer id (replay done)
//                   [u8 2] link DOWN  conn_id = peer id, rest = reason
//                   [u8 3] receiver-side punts: trunk entries whose
//                     local match set contains punt markers (or shared
//                     groups) — Python runs the local dispatch for
//                     them; entries in the pre-parse layout
//                     ([u64 origin][u8 flags][u16 tlen][topic] +
//                     (flags bit4 ? [u64 trace_id]) + [u32 plen]
//                     [payload]) with payloads always inline
//                     (conn_id = 0)
//   kind 10 = DURABLE  payload = one batched durable-store record per
//                   flush (round 10): [u64 base_guid][u64 ts_ms][u32 n]
//                   + n x pre-parsed entries ([u64 origin][u8 flags]
//                   [u16 ntok][u64 token x ntok][u16 tlen][topic] +
//                   (flags bit4 ? [u64 trace_id]) +
//                   (flags bit5 ? [u8 cidlen][origin clientid]) +
//                   (flags bit0 ? [u32 plen][payload] : payload of the
//                   PREVIOUS entry)) — the EXACT bytes appended to the
//                   store (store.h kRecMsgBatch body), so the store
//                   write and the Python marker-reconciliation event
//                   are one buffer. Flushed BEFORE any socket write of
//                   the same read batch: a qos1 publisher's PUBACK is
//                   only wired after its durable append (+fsync per
//                   policy) landed.
//   kind 11 = HANDOFF  live plane demotion (kDisableFast): the conn's
//                   AckState hands to the Python session instead of
//                   evaporating. conn_id = conn; payload[0] = sub-kind:
//                   [u8 1] window state: [u32 n_aw] + n x [u16 pid]
//                     (publisher awaiting-rel ids we owned) +
//                     [u32 n_if] + n x ([u16 pid][u8 state]) state
//                     bit0 = qos2, bit1 = rel phase (PUBREL sent,
//                     awaiting PUBCOMP); chunked at the tap bound,
//                     fields additive across chunks
//                   [u8 2] pending frames (the window-full mqueue):
//                     [u32 n] + n x ([u32 len][serialized PUBLISH,
//                     pid bytes zero]) — Python re-enqueues them into
//                     the session mqueue (retransmit-on-reconnect)
//   kind 8 = TELEMETRY  payload = concatenated sub-records, chunked at
//                   the tap bound like kinds 6/7:
//                   [u8 1] histogram delta: [u8 stage][u64 count_d]
//                     [u64 sum_d][u16 n] + n x ([u8 bucket][u32 delta])
//                     — deltas vs the last emission (flushed on a
//                     ~100ms cadence, not every cycle: the per-cycle
//                     record + Python decode taxed the blast path);
//                     summing every delta reproduces the totals exactly
//                   [u8 2] flight-recorder dump: [u64 conn][u8 reason]
//                     [u8 n] + n x 16B entries ([u32 ts_ms][u8 event]
//                     [u8 ptype][u16 arg][u32 topic_hash][u32 arg2]),
//                     oldest first; emitted on abnormal close, protocol
//                     error, or trace attach (reason 1/2/3)
//                   [u8 3] slow-ack sample: [u64 conn][u32 rtt_us]
//                     [u8 qos][u16 tlen][topic] — a sampled native
//                     QoS1/2 delivery whose ack RTT crossed the
//                     slow-ack threshold (feeds services/slow_subs.py)
//   kind 12 = TRACE  native distributed-tracing plane (round 13):
//                   payload = concatenated sub-records, chunked at the
//                   tap bound (sub-records never split); the record id
//                   slot carries the PRODUCING SHARD like kinds 7/8/10:
//                   [u8 1] span: [u64 trace_id][u8 stage][u64 t_ns]
//                     [u64 aux] — one point on a sampled publish's
//                     timeline. stage indexes the SpanStage enum
//                     (native/__init__.py SPAN_STAGES); t_ns is
//                     CLOCK_MONOTONIC; aux is stage-specific (ingress =
//                     publisher conn, route = match-set size,
//                     ring_cross = source shard, trunk_flush = peer id,
//                     store_append = durable-token count, deliver_write
//                     = subscriber conn, ack = subscriber conn with the
//                     delivery qos in bits 60-61, replay = guid).
//                   [u8 2] ledger: [u64 count][u64 trace_id][u64 aux]
//                     [u64 t_ns] preceded by [u8 reason] — ONE entry
//                     per degradation reason per poll cycle: count
//                     folds every ladder decision of that cycle
//                     (ring-full→punt, trunk→punt, kHighWater shed),
//                     trace_id is the last sampled publish that hit it
//                     (0 = none sampled), aux the last deciding
//                     peer/shard/conn. Reasons index the LedgerReason
//                     enum (native/__init__.py LEDGER_REASONS prefix).
//   kind 13 = COAP  one CoAP exchange degraded WHOLE to the Python
//                   oracle (round 19): conn_id = the CoAP conn, payload
//                   = the raw datagram verbatim (no fields — the
//                   gateway/coap.py oracle channel parses it itself and
//                   answers through emqx_host_coap_send). Punted for
//                   block-wise transfers, props-carrying retained
//                   reads, and non-/ps paths (the LwM2M seam) — never
//                   a partial exchange.
//
// WebSocket (round 7): a second listener serves MQTT-over-WebSocket
// (RFC6455, ws.h) on the SAME data plane: the upgrade handshake and
// frame codec run below the GIL, decoded payload bytes feed the same
// Framer/TryFast/ack machinery as TCP, and egress wraps each
// serialized span in one binary frame. The asyncio WS server
// (broker/ws.py) stays as the slow-plane oracle.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "coap.h"
#include "fault.h"
#include "frame.h"
#include "park.h"
#include "ring.h"
#include "router.h"
#include "sn.h"
#include "store.h"
#include "trunk.h"
#include "wheel.h"
#include "ws.h"

namespace emqx_native {
namespace {

constexpr size_t kReadChunk = 64 * 1024;
// Per-connection outbound backlog above which fast-path deliveries to
// that subscriber are dropped instead of buffered — the mqueue-full
// drop policy (emqx_mqueue.erl default max_len) applied at the socket.
constexpr size_t kHighWater = 4 * 1024 * 1024;
// Native QoS1 packet ids live in [kNativePidBase, 0xFFFF]; Python
// sessions allocate [1, kNativePidBase-1].
constexpr uint16_t kNativePidBase = 32768;

inline uint64_t NowMs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC_COARSE, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
}

// Telemetry timestamps need sub-ms resolution (the stages under
// measurement are microseconds); the vDSO CLOCK_MONOTONIC read is
// ~20ns, so every per-message call site is SAMPLED (1-in-8) rather
// than unconditional — see the < 2% overhead budget in bench.py's
// observe_overhead section.
inline uint64_t NowNs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
}

// ---------------------------------------------------------------------------
// Native telemetry plane (round 8): HDR-histogram-style log-bucketed
// latency capture + a per-connection flight recorder, exported as ONE
// batched kind-8 record per poll cycle (the kind-6/7 discipline).
// Everything here is poll-thread-owned plain memory: no locks, no
// atomics, no allocation on the record path.

// Histogram stage order (keep in sync with native/__init__.py
// HIST_STAGES — tests/test_stats_lint.py guards the stat slots; the
// stage list rides the same convention).
enum HistStage {
  kHistIngressRoute = 0,  // sampled: PUBLISH parse -> native fan-out done
  kHistRouteFlush,        // sampled: fan-out done -> socket flush done
  kHistQos1Rtt,           // sampled: qos1 delivery write -> PUBACK
  kHistQos2Rtt,           // sampled: qos2 delivery write -> PUBCOMP
  kHistLaneDwell,         // every lane dequeue: enqueue -> deliver/punt
  kHistGilStint,          // every poll: Poll() return -> next Poll() entry
  kHistWsIngest,          // sampled: WS decode+dispatch per read chunk
  kHistTrunkRtt,          // trunk batch flush -> peer ack (cross-node RTT)
  kHistTrunkBatchN,       // trunk batch occupancy: ENTRIES per flushed
                          // batch (a count, not ns — the one stage whose
                          // axis is not time; bench prints it raw)
  kHistStoreAppend,       // durable store: batch append (+policy fsync)
  kHistReplayDrain,       // resume replay: store fetch+consume+decode
                          // (stamped by Python via emqx_host_note_stage;
                          // poll-thread-only like conn_idle_ms)
  kHistSnIngest,          // sampled: SN datagram decode+dispatch
  kHistRetainDeliver,     // retained snapshot: match+encode+write per
                          // SUBSCRIBE-triggered delivery op
  kHistShardRingN,        // cross-shard ring occupancy: ENTRIES per
                          // applied ring batch (count-valued, the
                          // trunk_batch_n convention)
  kHistCoapIngest,        // sampled: CoAP datagram decode+dispatch
  kHistObserveNotify,     // sampled: observe notify resolve+encode+write
  kHistCount
};

// 64 log-bucketed (~power-of-√2) slots covering [0, ~4.3s): bucket 0
// holds [0,2)ns; a value with MSB position e >= 1 lands at 2e-1 (below
// √2·2^e, approximated as 1448/1024 fixed-point) or 2e; everything
// >= 2^32 ns clamps into bucket 63. Mirrored exactly by
// observe/metrics.py HIST_EDGES_NS / hist_bucket (differential test).
inline int HistBucket(uint64_t ns) {
  if (ns < 2) return 0;
  int e = 63 - __builtin_clzll(ns);
  if (e >= 32) return 63;
  return 2 * e - 1 + ((ns << 10) >= (1448ull << e) ? 1 : 0);
}

struct Hist {
  uint64_t b[64] = {};
  uint64_t cnt = 0;
  uint64_t sum = 0;
};

// Flight-recorder event codes (keep in sync with native/__init__.py
// FR_EVENT_NAMES).
enum FrEvent : uint8_t {
  kFrOpen = 1,   // accepted; arg = 1 for WS conns
  kFrFrame,      // slow-plane inbound frame; ptype, arg = len lo16
  kFrPunt,       // fast-eligible frame forwarded to Python anyway
  kFrFastPub,    // PUBLISH consumed natively; hash = topic hash
  kFrDeliver,    // fast-path delivery written; hash = topic hash
  kFrDrop,       // delivery dropped (backpressure / mqueue overflow)
  kFrAck,        // subscriber ack consumed natively; arg = pid
  // round 13: the recorder used to go blind the moment a publish left
  // its shard — these note the cross-plane legs on the PUBLISHER's
  // recorder so an operator's FR dump shows where the message went
  kFrRingCross,  // publish shipped to other shards; arg = shard count
  kFrTrunk,      // publish enqueued onto a trunk; arg = first peer id
};

// Dump reasons (kind-8 sub-record 2 header).
enum FrReason : uint8_t {
  kFrReasonClose = 1,  // abnormal close (sock_error, oversized, ...)
  kFrReasonError = 2,  // protocol error (frame_error, ws_error, ...)
  kFrReasonTrace = 3,  // trace attach / traced conn teardown
};

struct FrEntry {
  uint32_t ts_ms;  // NowMs() truncated — deltas are what matter
  uint8_t event;   // FrEvent
  uint8_t ptype;   // MQTT packet type where applicable
  uint16_t arg;    // event-specific (frame len, pid, reason)
  uint32_t hash;   // FNV-1a topic hash (0 when n/a)
  uint32_t arg2;
};
static_assert(sizeof(FrEntry) == 16, "kind-8 wire format");

constexpr uint8_t kFrCap = 16;  // entries per conn (256B, lazily alloc'd)

struct FlightRec {
  FrEntry e[kFrCap];
  uint8_t head = 0;  // next overwrite slot
  uint8_t n = 0;     // live entries (<= kFrCap)
};

inline uint32_t TopicHash(std::string_view t) {
  uint32_t h = 2166136261u;  // FNV-1a: cheap, stable across planes
  for (char c : t) {
    h ^= static_cast<uint8_t>(c);
    h *= 16777619u;
  }
  return h;
}

// Per-conn cap on concurrently-tracked ack-RTT samples: delivery
// stamps are taken only while a slot is free, so the steady-state cost
// is a tiny vector scan and the per-sample topic copy is bounded.
constexpr size_t kRttSamples = 4;

struct RttSample {
  uint64_t t0_ns;
  std::string topic;
  uint16_t pid;
  uint8_t qos;
  uint64_t trace = 0;  // sampled trace id: PUBACK closes the ack span
};

// ---------------------------------------------------------------------------
// Native distributed tracing (round 13): a deterministic 1-in-N
// publish sampler tags fast-path publishes with a 64-bit trace id that
// propagates through every native seam (cross-shard ring entries,
// trunk BATCH records on wire-v1 links, durable MSG-BATCH records)
// while the message stays on the fast path; each plane emits compact
// kind-12 span events a Python collector stitches into per-message
// timelines. Everything below is poll-thread-owned plain memory — the
// telemetry-plane discipline.

// Span stages (keep in sync with native/__init__.py SPAN_STAGES —
// tests/test_stats_lint.py parses this enum). kSpanReplay is emitted
// by PYTHON (the resume drain reads the persisted id back from the
// store), so it has no C++ emission site.
enum SpanStage : uint8_t {
  kSpanIngress = 0,   // sampled publish accepted natively; aux = conn
  kSpanRoute,         // native fan-out complete; aux = match-set size
  kSpanRingCross,     // consumer shard applied the ring entry; aux = src
  kSpanTrunkFlush,    // entry enqueued onto a trunk batch; aux = peer
  kSpanTrunkRecv,     // receiver fanned the trunk entry out natively
  kSpanStoreAppend,   // publish joined the durable batch; aux = n toks
  kSpanReplay,        // Python: resume replay re-joined the trace
  kSpanDeliverWrite,  // delivery written to a subscriber; aux = conn
                      // (bit 63 = truncation marker: the 8-per-publish
                      // cap clipped this fan-out — timeline is partial)
  kSpanAck,           // subscriber PUBACK/PUBCOMP closed the delivery
  kSpanCount
};

// Degradation-ledger reasons (a PREFIX of native/__init__.py
// LEDGER_REASONS — device_failover and store_degraded are Python-plane
// decisions folded into the same ledger there).
enum LedgerReason : uint8_t {
  kLrRingFull = 1,   // cross-shard ring full: publish degraded to punt
  kLrTrunkPunt,      // trunk down/ineligible: publish degraded to punt
  kLrShed,           // kHighWater backpressure shed (conn or trunk)
  kLrFault,          // faultline injection fired (aux = the fault site)
  kLrAcceptShed,     // accept-storm shed: admission denied before any
                     // conn side effect (round 16, aux = conn count)
  kLrCoapGiveup,     // CoAP CON-notify retransmit exhaustion: the
                     // unresponsive observer is dropped (RFC 7641
                     // §4.5; aux = the conn id)
  kLrCount
};

// deliver_write spans per sampled publish are capped: a megafan-out
// must not turn one sampled message into a span flood. When the cap
// clips a wide fan-out, ONE extra deliver_write span goes out with
// aux bit 63 set (the truncation bit — conn-id namespaces stop at bit
// 62) so a stitched timeline reads "first 8 of more", never silently
// as the full audience (round 17).
constexpr uint8_t kTraceMaxDeliverSpans = 8;
constexpr uint64_t kSpanTruncBit = 1ull << 63;
// Sampled publishes per POLL CYCLE are capped too (the tick still
// advances, so the 1-in-N ratio stays deterministic; the cap only
// clips extra picks within one cycle). Under blast a cycle drains
// thousands of publishes — 1-in-64 of 1M msg/s would be ~15k traces/s,
// and the Python-side span fold runs on the poll thread's GIL stints,
// which is exactly the plane-stall the telemetry rounds fought.
// Interactive traffic (a cycle per publish) never hits the cap.
constexpr uint32_t kTraceMaxPerCycle = 2;

// elevated-qos mqueue bound per subscriber (emqx_mqueue default
// max_len 1000); overflow drops the NEW message (kStDropsInflight)
constexpr size_t kMaxPending = 1000;
// publisher-side qos2 awaiting-rel cap: past it, NEW packet ids take
// the Python path, whose session enforces max_awaiting_rel quota
// semantics (emqx_session.erl:379-399)
constexpr uint32_t kMaxAwaitingRel = 8192;

inline bool BitTest(const uint64_t* b, uint32_t i) {
  return (b[i >> 6] >> (i & 63)) & 1;
}
inline void BitSet(uint64_t* b, uint32_t i) { b[i >> 6] |= 1ull << (i & 63); }
inline void BitClr(uint64_t* b, uint32_t i) {
  b[i >> 6] &= ~(1ull << (i & 63));
}

// Per-connection elevated-qos window state, allocated lazily on the
// first QoS1/2 interaction so a million idle / qos0-only connections
// pay nothing. Bitmaps replace the round-4 unordered_set bookkeeping:
// pid allocation and ack-erase are test-and-set bit ops, the profiled
// hash/alloc churn on the windowed QoS1 path (BENCH_r05's 641k cap).
struct AckState {
  // broker-allocated delivery pids, bit i = pid kNativePidBase + i;
  // a qos2 delivery holds its bit across the whole
  // PUBREC/PUBREL/PUBCOMP tail (no separate phase bitmap: nothing
  // natively retries mid-exchange, so the slot hold IS the state)
  uint64_t inflight[512] = {};   // allocated, awaiting PUBACK/PUBCOMP
  uint32_t inflight_cnt = 0;
  uint16_t next_pid = kNativePidBase;
  // publisher-side qos2 exactly-once: client pid space, bit = pid
  uint64_t awaiting_rel[1024] = {};
  uint32_t awaiting_cnt = 0;
  // deliveries awaiting an inflight slot — the mqueue analogue
  // (emqx_mqueue.erl): serialized PUBLISH (qos header already final)
  // with zeroed pid bytes + the pid offset to patch at dequeue
  std::deque<std::pair<std::string, size_t>> pending;
  // per-delivery phase bits for the demotion handoff (round 10): a
  // bare inflight bitmap cannot say qos1-vs-qos2 or publish-vs-rel
  // phase, and the Python session needs both to adopt the window.
  // Bit ops only — the round-6 no-hash-churn discipline holds.
  uint64_t infl_qos2[512] = {};  // bit set = the delivery was qos2
  uint64_t infl_rel[512] = {};   // bit set = PUBREL sent (await PUBCOMP)
  // per-poll-cycle ack-record accumulators (flushed as ONE kind-7
  // event per cycle — the rule-tap batching discipline applied to the
  // ack plane)
  uint32_t cyc_acked = 0;   // delivery slots freed (PUBACK + PUBCOMP)
  uint32_t cyc_rel = 0;     // publisher PUBREL exchanges completed
  bool cyc_dirty = false;   // queued on ack_dirty_ this cycle
  // sampled ack-RTT stamps (delivery write -> PUBACK/PUBCOMP); a
  // delivery only stamps while a slot is free, so this never grows
  std::vector<RttSample> rtt;
};

// Per-connection WebSocket transport state, allocated only for conns
// accepted on the WS listener — plain TCP conns pay nothing.
struct WsConnState {
  bool open = false;        // 101 sent; frames flow
  std::string hs_buf;       // HTTP upgrade request accumulation
  ws::WsDecoder dec{/*require_mask=*/true};  // clients MUST mask (§5.3)
};

// One tracked qos1 SN delivery awaiting its SN PUBACK: a full datagram
// copy (resent with DUP set on timeout) + the flags-byte offset to
// patch. The inflight BITMAP stays the authority — this is only the
// bytes needed to retransmit, retired by the same PUBACK that clears
// the bit.
struct SnInflightRx {
  uint16_t pid;
  std::string dgram;
  size_t flags_off;
  uint64_t last_tx_ms;
  uint8_t tries;
};

// Per-connection MQTT-SN transport state (round 11), allocated only
// for datagram peers on the SN listener — TCP/WS conns pay nothing.
// The conn has no socket of its own: egress rides sendto() on the
// shared UDP fd, keyed by `addr`.
struct SnConnState {
  sockaddr_in addr{};
  uint64_t conn_id = 0;     // this conn's id (for egress-side drains)
  bool anon = false;        // the shared QoS -1 publisher (no egress)
  bool connect_sent = false;  // MQTT CONNECT forwarded to Python
  bool connected = false;     // CONNACK rc=0 observed on egress
  bool connack_seen = false;  // any CONNACK observed (accept or reject)
  // messages pipelined into the CONNECT->CONNACK round trip; the
  // oracle connects synchronously so these must succeed, not bounce
  std::deque<sn::SnMsg> preconn;
  std::string clientid;
  bool awake = true;          // sleep mode (§6.14): deliveries park
  uint64_t sleep_until_ms = 0;  // announced wake deadline (keepalive)
  // per-client NORMAL topic-id registry (emqx_sn_registry.erl); the
  // predefined table is gateway-wide and lives on the Host
  std::unordered_map<uint16_t, std::string> topic_of_id;
  std::unordered_map<std::string, uint16_t> id_of_topic;
  uint16_t next_tid = 0;
  uint16_t next_mid = 0;
  // egress-translation context: MQTT msg-id -> the SN fields the SN
  // reply needs but the MQTT packet no longer carries
  std::unordered_map<uint16_t, uint16_t> pub_tid;   // PUBACK topic id
  std::unordered_map<uint16_t, uint32_t> sub_tid;   // (flags<<16)|tid
  // Python-plane egress bytes are an MQTT byte stream; this framer
  // splits them so each packet translates to one SN datagram
  Framer egress{1 << 20};
  std::deque<std::string> sleep_buf;   // parked datagrams, drop-oldest
  std::vector<SnInflightRx> rexmit;    // qos1 deliveries awaiting ack
  // qos1 retransmit wheel handle (round 16): the per-poll
  // SnRexmitScan sweep moved onto the timer wheel — armed when the
  // first rexmit copy is tracked, parked across announced sleep (the
  // retry clock restarts at wake), re-armed from the fire at the
  // conn's next retry deadline; @gen-handle
  uint64_t tm_rexmit = 0;
};

// -- native CoAP gateway state (round 19) -----------------------------------

// Inbound MID dedup entry (RFC 7252 §4.5, the oracle's parity-audited
// TransportManager window): a byte-identical retransmission replays
// the cached response instead of re-executing the request; a DIFFERENT
// token under the same mid is a recycled mid (the client's 16-bit
// counter wrapped inside the lifetime) and evicts the entry.
struct CoapSeen {
  std::string token;
  std::string response;  // "" = response still in flight: dup drops
  uint64_t expire_ms;
};

// One outstanding CON notify awaiting its ACK: resent VERBATIM on the
// RFC 7252 exponential backoff (ACK_TIMEOUT x 1.5, doubling — CoAP has
// no DUP bit; a retransmission is the same bytes), retired by the ACK
// (which also frees the MQTT window slot via a synthesized PUBACK),
// cancelled together with its observation by RST or exhaustion.
struct CoapConRx {
  uint16_t mid;         // CoAP message id (the wire key)
  uint16_t pid;         // MQTT delivery pid (0 = none to settle)
  std::string dgram;    // bare message bytes (no outbuf length prefix)
  std::string filter;   // owning observation (the RST/give-up cancel)
  uint64_t next_ms;     // retransmit deadline
  uint64_t timeout_ms;  // current backoff span (doubles per try)
  uint8_t tries;
};

// One observation (RFC 7641): GET+Observe registered this token on a
// /ps topic; notifications carry the token and the observation's OWN
// rolling 24-bit sequence (the oracle's per-observer counter).
struct CoapObserver {
  std::string filter;
  std::string token;
  uint8_t qos;    // subscription qos: >= 1 notifies as tracked CON
  uint32_t seq;   // 24-bit rolling observe sequence (starts at 1)
};

// Per-connection CoAP transport state, allocated only for datagram
// peers on the CoAP listener — TCP/WS/SN conns pay nothing. Like SN,
// the conn has no socket of its own: egress rides sendmmsg on the
// shared UDP fd keyed by `addr`, and MQTT translation gives the peer a
// real Python channel/session (auth, CM takeover, hooks) on demand.
struct CoapConnState {
  sockaddr_in addr{};
  uint64_t conn_id = 0;
  bool connect_sent = false;   // MQTT CONNECT forwarded to Python
  bool connected = false;      // CONNACK rc=0 observed on egress
  bool connack_seen = false;   // any CONNACK observed (accept or reject)
  bool oracle_used = false;    // ever punted to the Python oracle: an
                               // ACK/RST for an unknown mid routes there
  std::string clientid;        // registered identity (query ?clientid=)
  // requests pipelined into the CONNECT->CONNACK round trip (the
  // oracle registers synchronously, so these must be served, not
  // bounced); parked PARSED — the codec re-serializes byte-exactly
  std::deque<coap::CoapMsg> preconn;
  uint16_t next_mid = 0;       // notify mid allocator (oracle _next_mid)
  uint16_t next_mqtt_mid = 0;  // translated PUBLISH/SUBSCRIBE mid space
  std::unordered_map<uint16_t, CoapSeen> seen;  // inbound MID dedup
  // insertion-order companion for O(1) over-bound eviction: a
  // sustained blast must not pay an O(kCoapSeenMax) sweep per message
  // (may hold mids whose entry was already evicted/recycled — the
  // evictor just skips those)
  std::deque<uint16_t> seen_fifo;
  // MQTT mid -> the CoAP exchange whose response awaits that ack
  struct PendingPub { uint16_t mid; std::string token; bool con; };
  struct PendingSub { uint16_t mid; std::string token; std::string topic;
                      uint8_t qos; bool con; };
  std::unordered_map<uint16_t, PendingPub> pending_pub;
  std::unordered_map<uint16_t, PendingSub> pending_sub;
  std::vector<CoapObserver> observers;
  std::vector<CoapConRx> rexmit;     // CON notifies awaiting ACK
  // recent notify mid -> observing filter: RST cancels the observation
  // for ANY notify type (RFC 7641 §3.6); bounded, never evicting a mid
  // still awaiting its ACK (the oracle's _con_topic discipline)
  std::unordered_map<uint16_t, std::string> notify_obs;
  // Python-plane egress bytes are an MQTT byte stream; this framer
  // splits them so each packet translates to one CoAP message
  Framer egress{1 << 20};
  // CON retransmit wheel handle — armed when the first tracked notify
  // lands, re-armed from the fire at the conn's next backoff deadline
  // (named apart from SnConnState::tm_rexmit so each annotation stays
  // independently load-bearing); @gen-handle
  uint64_t tm_notify = 0;
};

struct Conn {
  int fd = -1;
  Framer framer;
  std::string outbuf;   // unsent bytes (partial-write backlog)
  size_t outpos = 0;
  bool want_close = false;  // close once outbuf drains
  std::unique_ptr<WsConnState> ws;  // non-null = WebSocket transport
  std::unique_ptr<SnConnState> sn;  // non-null = MQTT-SN datagram conn
  std::unique_ptr<CoapConnState> coap;  // non-null = CoAP datagram conn
  // -- fast path ----------------------------------------------------------
  bool fast = false;        // Python enabled the PUBLISH fast path
  uint8_t proto_ver = 4;    // 4 = MQTT 3.1.1, 5 = MQTT 5
  uint32_t max_inflight = 16384;
  bool dirty = false;       // has appended-but-unflushed outbuf bytes
  bool traced = false;      // TraceManager attached: PUBLISHes punt to
                            // Python so the hook fold sees them; the
                            // flight-recorder tail rides the trace log
  uint64_t last_rx_ms = 0;  // any inbound bytes (keepalive feed)
  // -- conn-scale plane (round 16) ----------------------------------------
  // last non-PINGREQ frame: the park-after clock. Keepalive pings are
  // traffic (last_rx_ms) but not WORK — an idle-but-pinging device
  // must still hibernate, and parked pings answer from the parked
  // record without inflation.
  uint64_t last_work_ms = 0;
  uint32_t keepalive_ms = 0;    // effective deadline (1.5x keepalive);
                                // 0 = no native keepalive enforcement
  uint64_t tm_keepalive = 0;    // wheel handles (0 = unarmed; the
                                // park.h twin carries the annotation)
  uint64_t tm_park = 0;         // @gen-handle
  std::unique_ptr<FlightRec> fr;             // telemetry flight recorder
  std::unique_ptr<AckState> ack;             // elevated-qos window state
  std::unordered_set<std::string> permits;   // publisher-side topic grants
  std::vector<std::string> own_subs;         // filters owned by this conn
  // (group token, filter) shared memberships owned by this conn
  std::vector<std::pair<uint64_t, std::string>> own_shared;
};

// Device-lane bounds: past the soft cap, NEW topics take the C++ walk
// (correct, just not device-matched); topics with entries already in
// flight stay on the lane regardless, preserving per-topic order. An
// entry older than the stale deadline means the pump wedged — the lane
// drains to Python in order and disables itself.
constexpr size_t kLaneSoftMax = 65536;
constexpr uint64_t kLaneStaleMs = 3000;
// One topic flooding faster than the pump drains cannot walk (its
// parked predecessors would be overtaken) — past this bound the NEW
// frame is dropped like any backpressured qos0 delivery (the mqueue-
// overflow analogue; an unacked qos1 publish is retried by the client)
constexpr uint32_t kLaneTopicMax = 8192;
// Tap batch record flush threshold — well under the Python-side poll
// buffer (max_packet_size + 64), since an oversized record is dropped.
constexpr size_t kTapFlushBytes = 192 * 1024;

// -- cluster trunk bounds (round 9) -----------------------------------------
// Remote-entry owners live far above conn ids AND the Python punt-token
// space (1 << 48): owner = kTrunkOwnerBase + peer id.
constexpr uint64_t kTrunkOwnerBase = 1ull << 62;
// Durable-entry owners (round 10) get their own namespace too: store
// tokens are small sequential ints EXACTLY like conn ids, and SubTable
// upserts key on (owner, filter) — an un-namespaced token N would
// collide with conn N's real entry on the same filter (the real entry
// would overwrite the durable one, silently un-persisting the session).
constexpr uint64_t kDurableOwnerBase = 1ull << 61;
// Trunk sock epoll tags carry this bit (conn ids are sequential small
// ints; the three listener tags sit at ~0ull and below).
constexpr uint64_t kTrunkSockBit = 1ull << 63;
// Unacked-batch replay ring bound per peer: past it NEW qos1 publishes
// with that remote audience degrade to the Python forward lane (the
// ring itself may overshoot by the in-flight cycle — a soft bound).
constexpr size_t kTrunkUnackedMax = 512;
// HELLO-answer grace (round 14): a fresh link's qos1 replay + UP event
// wait for the negotiated wire version so a replayed batch keeps its
// trace annotation on v1 links; an old peer never answers, so the
// deadline completes the link at v0 — one bounded delay per reconnect
// against old peers, one loopback RTT against current ones.
constexpr uint64_t kTrunkHelloGraceMs = 300;

// -- mqtt-sn gateway bounds (round 11) --------------------------------------
// Datagram conns get their own id range (the ISSUE's "own conn-id
// range"): below the durable-owner (1<<61) and trunk-owner (1<<62)
// namespaces, above any TCP/WS conn id the sequential counter could
// ever reach and above the Python punt-token space (1<<48).
constexpr uint64_t kSnConnBit = 1ull << 59;

// -- coap gateway bounds (round 19) -----------------------------------------
// CoAP datagram conns get their own id namespace too — but every bit
// ABOVE 59 is spoken for in contexts conn ids flow through (ring
// multi-target entries pack min_qos into bits 60-61 of the target word
// and mask conns to (1<<60)-1; durable/trunk owners sit at 61/62), so
// the CoAP discriminator composes bit 59 with bit 55, just below the
// shard field: sequential per-shard counters never approach 2^55, so
// SN ids (bit 55 clear) and CoAP ids can never collide.
constexpr uint64_t kCoapConnBit = (1ull << 59) | (1ull << 55);

// -- multi-core shard bounds (round 12) -------------------------------------
// The owner-namespace scheme extended to SHARDS: conn ids carry their
// shard index in bits 56-58 — above the Python punt-token space
// (tokens mint upward from 1<<48 and can never reach 1<<56), below the
// SN bit (59), so an SN conn on shard k composes as
// kSnConnBit | (k << kShardShift) | seq. Shard 0 ids are numerically
// identical to the unsharded scheme (back-compat by construction).
constexpr int kShardShift = 56;
constexpr uint64_t kShardMask = 7;  // up to ring::kMaxShards shards

inline int ShardOf(uint64_t conn_id) {
  return static_cast<int>((conn_id >> kShardShift) & kShardMask);
}

// Membership append for ONE publish's tiny scratch vectors (trunk
// peers, destination shards): linear scan beats any set at these sizes.
template <typename T>
inline void PushUnique(std::vector<T>* v, T x) {
  for (T e : *v)
    if (e == x) return;
  v->push_back(x);
}
// qos1 delivery retransmit-on-timeout (UDP loses datagrams; TCP conns
// never need this — the transport retransmits): resend with DUP after
// kSnRetryMs, abandon the delivery (freeing its inflight slot like a
// PUBACK would) after kSnMaxRetries attempts.
constexpr uint64_t kSnRetryMs = 1000;
constexpr uint8_t kSnMaxRetries = 3;

// -- conn-scale plane bounds (round 16) --------------------------------------
// Timer kinds on the per-shard wheel (wheel.h): the key is a conn id
// for keepalive/park/rexmit and a trunk peer id for the ack watchdog.
enum TimerKind : uint8_t {
  kTmKeepalive = 1,  // keepalive deadline (lazy-reprogrammed on fire)
  kTmPark,           // park-after check (hibernate idle conns)
  kTmSnRexmit,       // SN qos1 retransmit deadline (per conn)
  kTmTrunkAck,       // trunk silent-link watchdog (per peer)
  kTmCoapRexmit,     // CoAP CON-notify retransmit deadline (per conn)
};
// Default park-after when no keepalive is known (a conn with a
// keepalive parks after 2x its grace deadline = 3x keepalive).
constexpr uint64_t kParkAfterDefaultMs = 30000;
// Resident-conn memory estimate for the accept governor's budget: the
// struct + map node + framer/outbuf/permit steady-state heap. The
// bench measures the real number (RSS/conn); this constant only needs
// the right ORDER for the shed decision.
constexpr uint64_t kConnResidentEstBytes = 1024;

// Fast-path control ops enqueued from Python threads, applied on the
// poll thread (ApplyPending) so they serialize with matching.
struct Op {
  enum Kind : uint8_t {
    kSubAdd, kSubDel, kPermit, kEnableFast, kDisableFast, kPermitsFlush,
    kSharedAdd, kSharedDel, kSetLane, kLaneDeliver, kSetMaxQos,
    kSetInflightCap, kSetTrace, kSetTelemetry,
    kTrunkConnect, kTrunkDisconnect, kTrunkRouteAdd, kTrunkRouteDel,
    kTrunkIdent,
    kDurableAdd, kDurableDel,
    kSnPredef, kRetainSet, kRetainDel, kRetainDeliver, kSetTeleShift,
    kTrunkPeerState, kSetTracing, kSetTrunkWire, kSetTrunkAckTimeout,
    kSetKeepalive, kSetPark, kSynthConns,
    kCoapRetainState, kSetCoapAckTimeout, kCoapSend
  };
  Kind kind;
  uint64_t owner = 0;
  uint64_t token = 0;    // shared-group identity / retained deadline
  std::string str;       // filter / topic
  std::string str2;      // retained payload
  uint8_t qos = 0;
  uint8_t flags = 0;
  uint8_t proto_ver = 4;
  uint32_t max_inflight = 0;
};

// Stats slot order for emqx_host_stats (keep in sync with
// native/__init__.py STAT_NAMES — enforced by tests/test_stats_lint.py,
// which parses this enum and cross-checks names, order, and increment
// sites; slot kStFooBar must be named "foo_bar" on the Python side).
enum StatSlot {
  kStFastIn = 0,       // PUBLISHes fully handled in C++
  kStFastOut,          // PUBLISH deliveries written by the fast path
  kStFastBytesOut,
  kStPunts,            // fast-eligible frames forwarded to Python anyway
  kStDropsBackpressure,
  kStDropsInflight,
  kStNativeAcks,       // QoS1 PUBACKs consumed natively
  kStSharedDispatch,   // shared-group picks served natively
  kStSharedNoMember,   // shared groups with no deliverable member
  kStLaneIn,           // PUBLISHes queued to the device match lane
  kStLaneOut,          // lane messages delivered after a device response
  kStLanePunts,        // lane messages punted (punt filter / spill)
  kStLaneFallback,     // lane soft-cap hits served by the C++ walk
  kStLaneStale,        // stale-head lane shutdowns (pump wedge trips)
  kStTaps,             // rule-tap frame copies forwarded to Python
  kStQos1In,           // native qos1 PUBLISHes (subset of kStFastIn)
  kStQos2In,           // native qos2 PUBLISHes (subset of kStFastIn)
  kStQos2Rel,          // publisher PUBREL→PUBCOMP exchanges completed
  kStLaneTopicOverflow,  // per-topic lane flood drops (was silently
                         // folded into kStDropsBackpressure)
  kStAckBatches,       // batched ack records emitted to Python
  kStWsHandshakes,     // successful RFC6455 upgrades
  kStWsRejects,        // upgrade requests answered 400
  kStWsPings,          // client pings answered with pongs
  kStWsCloses,         // client-initiated close frames honoured
  kStPuntsTrace,       // PUBLISHes punted because the conn is traced
  kStFrDumps,          // flight-recorder dumps emitted (kind 8)
  kStTelemetryBatches,  // batched kind-8 telemetry records emitted
  kStTrunkOut,         // publishes forwarded onto a trunk link
  kStTrunkIn,          // trunk entries received and handled locally
  kStTrunkBatchesOut,  // trunk batch records flushed to peers
  kStTrunkBatchesIn,   // trunk batch records applied from peers
  kStTrunkPunts,       // received trunk entries handed to Python
  kStTrunkReplays,     // qos1 batches replayed after a reconnect
  kStTrunkShed,        // qos0 entries shed under trunk-link backpressure
  kStDurableIn,        // publishes persisted below the GIL (durable
                       // audience matched, fast path preserved)
  kStDurableBatches,   // kind-10 store/event records flushed
  kStStoreAppends,     // message entries appended to the durable store
  kStHandoffs,         // demotion handoffs emitted (kind 11)
  kStSnIn,             // SN PUBLISHes ingested over UDP (any qos >= 0)
  kStSnOut,            // SN PUBLISH deliveries encoded (sent or parked)
  kStSnQosM1,          // QoS -1 publish-without-connect datagrams
  kStSnPings,          // SN PINGREQs handled (wake + keepalive)
  kStSnRegisters,      // client REGISTERs answered with REGACK
  kStSnSleepParked,    // deliveries parked for a sleeping client
  kStSnDropsOversize,  // deliveries exceeding the SN u16 wire limit
  kStRetainSet,        // retained-snapshot entries installed/updated
  kStRetainDel,        // retained-snapshot entries removed
  kStRetainDeliver,    // SUBSCRIBE-triggered native retained lookups
  kStRetainMsgsOut,    // retained messages delivered below the GIL
  kStShardRingOut,     // deliveries shipped to another shard's ring
  kStShardRingIn,      // ring entries applied from other shards
  kStShardRingFull,    // publishes degraded ring-full -> punt -> Python
  kStTracedPubs,       // publishes tagged by the 1-in-N trace sampler
  kStSpanBatches,      // batched kind-12 trace records emitted
  kStFaultsInjected,   // faultline fires on this host (all sites)
  kStConnsParked,      // conns hibernated into parked records
  kStConnsInflated,    // parked conns re-inflated (first byte/delivery)
  kStConnsShed,        // accepts shed (memory budget / max_conns)
  kStParkedPings,      // PINGREQs answered from the parked record
  kStTrunkRingPersisted,  // trunk qos1 ring entries journaled into the
                          // durable store (round 18)
  kStTrunkRingRecovered,  // ring entries rebuilt from store segments
                          // after a restart/reattach
  kStCoapIn,              // CoAP /ps publishes ingested natively
  kStCoapNotifies,        // observe notifications encoded (CON or NON)
  kStCoapPings,           // CoAP pings (CON empty) answered with RST
  kStCoapDedupHits,       // retransmitted requests served from the MID
                          // dedup window (replay, or in-flight drop)
  kStCoapRexmits,         // CON notify retransmissions sent
  kStCoapGiveups,         // CON retransmit exhaustion: observer dropped
  kStCoapPunts,           // exchanges degraded WHOLE to the Python
                          // oracle (block-wise, props, non-/ps paths)
  kStCoapDropsOversize,   // deliveries exceeding the CoAP frame cap
  kStatCount
};

std::string EncodeRecord(uint8_t kind, uint64_t id, const char* data,
                         size_t len) {
  std::string rec;
  rec.reserve(13 + len);
  rec.push_back(static_cast<char>(kind));
  for (int i = 0; i < 8; i++)
    rec.push_back(static_cast<char>((id >> (8 * i)) & 0xFF));
  for (int i = 0; i < 4; i++)
    rec.push_back(static_cast<char>((len >> (8 * i)) & 0xFF));
  rec.append(data, len);
  return rec;
}

int SetNonBlocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags < 0 ? -1 : fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

class Host {
 public:
  Host(uint32_t max_size, uint32_t max_conns)
      : max_size_(max_size), max_conns_(max_conns) {}

  ~Host() {
    // producers in other shards stop shipping to this shard's rings;
    // the doorbell fd stays open (group-owned) so racing doorbell
    // writes never hit a recycled fd
    if (group_)
      group_->alive[shard_id_].store(false, std::memory_order_release);
    for (auto& [id, c] : conns_)
      if (c.fd >= 0) close(c.fd);  // SN conns share the listener fd
    for (auto& [id, slot] : parked_) {
      int pfd = park_slab_.at(slot).fd;
      if (pfd >= 0) close(pfd);
    }
    for (auto& [tag, s] : trunk_socks_) close(s.fd);
    if (listen_fd_ >= 0) close(listen_fd_);
    if (listen_ws_fd_ >= 0) close(listen_ws_fd_);
    if (listen_trunk_fd_ >= 0) close(listen_trunk_fd_);
    if (sn_fd_ >= 0) close(sn_fd_);
    if (coap_fd_ >= 0) close(coap_fd_);
    if (wake_fd_ >= 0) close(wake_fd_);
    if (epoll_fd_ >= 0) close(epoll_fd_);
  }

  // @plane(control) — before the poll thread starts only
  bool Init(const char* bind_addr, uint16_t port, bool reuseport = false) {
    epoll_fd_ = epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    listen_fd_ = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (epoll_fd_ < 0 || wake_fd_ < 0 || listen_fd_ < 0) return false;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    // SO_REUSEPORT accept sharding (round 12): every shard binds its
    // own listener on the SAME port and the kernel hash-distributes
    // incoming connections across them — no accept lock, no handoff
    if (reuseport)
      setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) return false;
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    if (listen(listen_fd_, 1024) < 0) return false;
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenTag;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    ev.events = EPOLLIN;
    ev.data.u64 = kWakeTag;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);
    return true;
  }

  int port() const { return port_; }
  int ws_port() const { return ws_port_; }
  int trunk_port() const { return trunk_port_; }

  // Open the WebSocket listener (call BEFORE the poll thread starts —
  // it mutates the epoll set from the caller's thread). Conns accepted
  // here run the RFC6455 handshake + frame codec in front of the MQTT
  // framer; `path` is the required upgrade request-target ("" accepts
  // any). Returns the bound port, or -1.
  // @plane(control)
  int ListenWs(const char* bind_addr, uint16_t port, const char* path,
               bool reuseport = false) {
    if (listen_ws_fd_ >= 0) return -1;  // one WS listener per host
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (reuseport)  // per-shard WS listeners on one port (round 12)
      setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1 ||
        bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        listen(fd, 1024) < 0) {
      close(fd);
      return -1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenWsTag;
    if (epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev) < 0) {
      close(fd);
      return -1;
    }
    listen_ws_fd_ = fd;
    ws_port_ = ntohs(addr.sin_port);
    ws_path_ = path ? path : "";
    return ws_port_;
  }

  // Open the cluster-trunk listener (call BEFORE the poll thread
  // starts, like ListenWs — it mutates the epoll set from the caller's
  // thread). Peers' hosts dial this port to forward publishes below
  // the GIL. Returns the bound port, or -1.
  // @plane(control)
  int ListenTrunk(const char* bind_addr, uint16_t port,
                  bool reuseport = false) {
    if (listen_trunk_fd_ >= 0) return -1;  // one trunk listener per host
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    // per-shard trunk listeners on ONE port (round 15, the link-spread
    // satellite): inbound peer links hash across shards like conns do
    if (reuseport)
      setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1 ||
        bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
        listen(fd, 64) < 0) {
      close(fd);
      return -1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenTrunkTag;
    if (epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev) < 0) {
      close(fd);
      return -1;
    }
    listen_trunk_fd_ = fd;
    trunk_port_ = ntohs(addr.sin_port);
    return trunk_port_;
  }

  // Open the MQTT-SN/UDP gateway socket (call BEFORE the poll thread
  // starts, like the other listeners — it mutates the epoll set from
  // the caller's thread). One datagram socket serves every SN client;
  // per-peer conns are minted on their first CONNECT. Returns the
  // bound port, or -1.
  // @plane(control)
  int ListenSn(const char* bind_addr, uint16_t port, int gw_id,
               bool reuseport = false) {
    if (sn_fd_ >= 0) return -1;  // one SN listener per host
    int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    // UDP SO_REUSEPORT (round 12): the kernel source-hash pins each SN
    // peer to ONE shard's socket, so a datagram conversation never
    // splits across poll threads
    if (reuseport)
      setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    // a datagram blast landing between two poll cycles must queue in
    // the kernel, not drop at the default (small) socket buffers
    int buf = 4 << 20;
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1 ||
        bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      close(fd);
      return -1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenSnTag;
    if (epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev) < 0) {
      close(fd);
      return -1;
    }
    sn_fd_ = fd;
    sn_port_ = ntohs(addr.sin_port);
    sn_gw_id_ = static_cast<uint8_t>(gw_id);
    return sn_port_;
  }

  int sn_port() const { return sn_port_; }

  // Open the CoAP/UDP gateway socket (call BEFORE the poll thread
  // starts, like the other listeners — it mutates the epoll set from
  // the caller's thread). One datagram socket serves every CoAP peer;
  // per-peer conns are minted on their first request. Returns the
  // bound port, or -1.
  // @plane(control)
  int ListenCoap(const char* bind_addr, uint16_t port,
                 bool reuseport = false) {
    if (coap_fd_ >= 0) return -1;  // one CoAP listener per host
    int fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    // UDP SO_REUSEPORT source-hash (the SN discipline): each CoAP peer
    // pins to ONE shard's socket, so an endpoint's message layer
    // (dedup window, observers, retransmit state) never splits across
    // poll threads
    if (reuseport)
      setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    int buf = 4 << 20;  // datagram blasts queue in the kernel, not drop
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1 ||
        bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
      close(fd);
      return -1;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kListenCoapTag;
    if (epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev) < 0) {
      close(fd);
      return -1;
    }
    coap_fd_ = fd;
    coap_port_ = ntohs(addr.sin_port);
    return coap_port_;
  }

  int coap_port() const { return coap_port_; }

  // Thread-safe enqueue of outbound bytes for a connection.
  int Send(uint64_t id, const uint8_t* data, size_t len) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_.emplace_back(id, std::string(
          reinterpret_cast<const char*>(data), len));
    }
    Wake();
    return 0;
  }

  int CloseConn(uint64_t id) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_closes_.push_back(id);
    }
    Wake();
    return 0;
  }

  // Thread-safe fast-path control plane (applied in ApplyPending).
  int Enqueue(Op op) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      pending_ops_.push_back(std::move(op));
    }
    Wake();
    return 0;
  }

  long Stat(int slot) const {
    if (slot < 0 || slot >= kStatCount) return -1;
    return static_cast<long>(stats_[slot].load(std::memory_order_relaxed));
  }

  // Attach the durable-session store (call BEFORE the poll thread
  // starts, like the listeners — store_ is read lock-free on the hot
  // path). The host never owns the store; Python manages its lifetime
  // and must destroy the host first. With shards, EVERY shard attaches
  // the same store: appends are batched per flush and the store's one
  // internal mutex serializes the (rare) concurrent flushes.
  // @plane(control)
  void AttachStore(store::DurableStore* s) { store_ = s; }

  // -- faultline control surface (thread-safe: atomics only) ---------------
  // One arm API covers the whole node: host sites arm this host's
  // injector, the two store_* sites forward to the attached store's
  // (shared across shard hosts — Python arms it once, via shard 0).
  int FaultArm(int site, int mode, double n_or_prob, uint64_t seed,
               uint64_t key) {
    if (site < 0 || site >= fault::kSiteCount) return -1;
    if (site == fault::kSiteStoreMsync ||
        site == fault::kSiteStoreSegOpen) {
      if (store_ == nullptr) return -1;
      store_->injector()->Arm(site, mode, n_or_prob, seed, key);
      return 0;
    }
    fault_.Arm(site, mode, n_or_prob, seed, key);
    return 0;
  }

  long FaultFired(int site) {
    if (site < 0 || site >= fault::kSiteCount) return -1;
    if (site == fault::kSiteStoreMsync ||
        site == fault::kSiteStoreSegOpen)
      return store_ == nullptr
                 ? 0
                 : static_cast<long>(
                       store_->injector()->FiredCount(site));
    return static_cast<long>(fault_.FiredCount(site));
  }

  // Join a shard group (call BEFORE any poll thread starts). This host
  // becomes shard `shard_id` of `g->n`: conn ids gain the shard
  // prefix, cross-shard deliveries ride the group's SPSC rings, and
  // the group's doorbell for this shard wakes our epoll loop.
  // @plane(control)
  int JoinGroup(ring::ShardGroup* g, int shard_id) {
    if (!g || shard_id < 0 || shard_id >= g->n ||
        g->n > ring::kMaxShards)
      return -1;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = kShardWakeTag;
    if (epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, g->doorbell[shard_id],
                  &ev) < 0)
      return -1;  // state untouched: a failed join leaves no group
                  // pointer for ~Host to chase and no alive=true for
                  // producers to ship into
    group_ = g;
    shard_id_ = shard_id;
    g->alive[shard_id].store(true, std::memory_order_release);
    return 0;
  }

  // Record one observation into a telemetry stage from the POLL THREAD
  // only (the native server's resume-replay drain runs there); the
  // wrong-thread refusal mirrors ConnIdleMs.
  // @plane(poll)
  int NoteStage(int stage, uint64_t ns) {
    pthread_t poller = poll_thread_.load(std::memory_order_acquire);
    if (poller != pthread_t{} && !pthread_equal(poller, pthread_self()))
      return -2;
    if (stage < 0 || stage >= kHistCount) return -1;
    if (telemetry_) RecordHist(stage, ns);
    return 0;
  }

  uint64_t LaneBacklog() const {
    return lane_backlog_.load(std::memory_order_relaxed);
  }

  // POLL-THREAD ONLY: walks conns_, which the loop mutates — a
  // cross-thread call races the hashtable structure itself (TSan
  // caught exactly this against Drop's erase). The product calls it
  // from _housekeep inside the poll step; a wrong-thread call fails
  // fast with -2 instead of silently racing.
  // (non-const since round 15: the housekeep_clock fault site counts
  // its fire; the poll-thread contract below already serializes it)
  // @plane(poll)
  long ConnIdleMs(uint64_t id) {
    pthread_t poller = poll_thread_.load(std::memory_order_acquire);
    if (poller != pthread_t{} && !pthread_equal(poller, pthread_self())) {
      // abort-free warn-once: misuse must show up in plain test output
      // and sanitizer runs, not as a silent -2 swallowed by a caller
      if (!idle_misuse_warned_.exchange(true, std::memory_order_relaxed))
        fprintf(stderr,
                "emqx_native: emqx_host_conn_idle_ms called off the poll "
                "thread; refusing (-2). This walks poll-thread-owned "
                "state — call it from the thread driving emqx_host_poll"
                ".\n");
      return -2;  // wrong thread: refuse rather than race conns_
    }
    auto it = conns_.find(id);
    if (it == conns_.end()) {
      auto pit = parked_.find(id);
      if (pit == parked_.end()) return -1;
      // housekeep clock skew applies to hibernating conns too
      uint64_t pnow = NowMs() + FaultSkewMs();
      uint64_t last = park_slab_.at(pit->second).last_rx_ms;
      return static_cast<long>(pnow > last ? pnow - last : 0);
    }
    // housekeep clock skew (faultline): keepalive scans judge conns
    // against a future clock while the site is armed
    uint64_t now = NowMs() + FaultSkewMs();
    const Conn& c = it->second;
    if (c.sn && !c.sn->awake) {
      if (now < c.sn->sleep_until_ms)
        return 0;  // announced sleep (§6.14): expected-silent, not idle
      // past the announced wake deadline the idle clock starts AT the
      // deadline — measuring from last_rx_ms would jump straight to
      // the full sleep span and kill the session with zero grace just
      // as the punctual wake PINGREQ is in flight
      uint64_t due = c.sn->sleep_until_ms;
      return static_cast<long>(now > due ? now - due : 0);
    }
    uint64_t last = c.last_rx_ms;
    return static_cast<long>(now > last ? now - last : 0);
  }

  // Conn-scale gauges (round 16): resident conns, parked conns,
  // parked-record bytes, armed wheel timers. POLL-THREAD ONLY like
  // ConnIdleMs (it reads poll-thread-owned containers); refuses with
  // -2 off thread. parked bytes alone is an atomic a cross-thread
  // caller may read via the stat surface.
  // @plane(poll)
  int ConnCounts(uint64_t out[4]) {
    pthread_t poller = poll_thread_.load(std::memory_order_acquire);
    if (poller != pthread_t{} && !pthread_equal(poller, pthread_self()))
      return -2;
    out[0] = conns_.size();
    out[1] = parked_.size();
    out[2] = parked_bytes_.load(std::memory_order_relaxed);
    out[3] = wheel_.armed();
    return 0;
  }

  // Run one event-loop step on the calling thread; fill `buf` with as
  // many whole event records as fit. Returns bytes written (0 on
  // timeout with no events).
  // @plane(poll) — the nativecheck root: everything reachable from
  // here runs on the poll thread (tools/nativecheck rule 1)
  long Poll(uint8_t* buf, size_t cap, int timeout_ms) {
    poll_thread_.store(pthread_self(), std::memory_order_release);
    trace_cyc_used_ = 0;  // the per-cycle sampler budget (TraceSample)
    if (telemetry_) {
      fr_now_ms_ = NowMs();  // one stamp per cycle for every FrNote
      if (poll_exit_ns_) {
        // the gap since the last Poll return is the caller's GIL
        // stint: time the Python driver held the plane stalled
        RecordHist(kHistGilStint, NowNs() - poll_exit_ns_);
      }
    }
    if (events_.empty()) {
      ApplyPending();
      gov_.BeginCycle();  // accept-burst defer window resets per cycle
      epoll_event evs[256];
      int n = epoll_wait(epoll_fd_, evs, 256, timeout_ms);
      if (n < 0) {
        if (telemetry_) poll_exit_ns_ = NowNs();
        return errno == EINTR ? 0 : -1;
      }
      for (int i = 0; i < n; i++) HandleEvent(evs[i]);
      ApplyPending();
      // inbound cross-shard deliveries apply before this cycle's
      // flushes so their acks/appends ride the same batch records
      if (group_) DrainShardRings();
      if (!lane_pending_.empty()) LaneStaleScan();
      // the timer wheel replaced the per-cycle O(N) deadline sweeps
      // (SN rexmit scan, trunk ack watchdog, the Python keepalive
      // loop): one Advance pays O(expired + cascades) per cycle
      wheel_.Advance(NowMs(), [this](uint64_t key, uint8_t kind) {
        FireTimer(key, kind);
      });
      TrunkHelloScan();  // old-peer HELLO grace deadlines (v0 links)
      FlushDurables();   // catch-all for appends with no dirty socket
      FlushTaps();
      FlushAcks();
      FlushTrunks();
      if (group_) FlushShards();
      // histogram deltas ride a ~100ms cadence, not every cycle: under
      // blast the per-cycle record + its Python-side decode measurably
      // taxed the plane (the observe_overhead budget); flight-recorder
      // dumps and slow-ack records still flush THIS cycle below
      if (telemetry_ && hist_dirty_
          && fr_now_ms_ - last_hist_flush_ms_ >= 100) {
        last_hist_flush_ms_ = fr_now_ms_;
        FlushHistDeltas();
      }
      FlushTelemetry();
      // span events are rare (1-in-N sampled) and timelines stitch
      // best fresh: flush every cycle, no 100ms cadence; the same
      // record carries this cycle's folded ledger entries
      FlushSpans();
    }
    size_t written = 0;
    while (!events_.empty()) {
      const std::string& rec = events_.front();
      if (written + rec.size() > cap) {
        // A record larger than the caller's whole buffer can never be
        // delivered; retaining it would busy-spin Poll forever.  Drop it
        // and close the offending connection with an error event (which
        // is small and will fit on a later call).
        if (written == 0 && rec.size() > cap) {
          uint64_t id;
          memcpy(&id, rec.data() + 1, 8);
          events_.pop_front();
          if (id != kListenTag && id != kWakeTag) Drop(id, "oversized", true);
          continue;
        }
        break;
      }
      memcpy(buf + written, rec.data(), rec.size());
      written += rec.size();
      events_.pop_front();
    }
    if (telemetry_) poll_exit_ns_ = NowNs();
    return static_cast<long>(written);
  }

 private:
  static constexpr uint64_t kListenTag = ~0ull;
  static constexpr uint64_t kWakeTag = ~0ull - 1;
  static constexpr uint64_t kListenWsTag = ~0ull - 2;
  static constexpr uint64_t kListenTrunkTag = ~0ull - 3;
  static constexpr uint64_t kListenSnTag = ~0ull - 4;
  static constexpr uint64_t kShardWakeTag = ~0ull - 5;
  static constexpr uint64_t kListenCoapTag = ~0ull - 6;

  void Wake() {
    uint64_t one = 1;
    [[maybe_unused]] ssize_t r = write(wake_fd_, &one, sizeof(one));
  }

  // Move cross-thread sends/closes/control-ops into loop-owned state.
  void ApplyPending() {
    std::vector<std::pair<uint64_t, std::string>> sends;
    std::vector<uint64_t> closes;
    std::vector<Op> ops;
    {
      std::lock_guard<std::mutex> lk(mu_);
      sends.swap(pending_);
      closes.swap(pending_closes_);
      ops.swap(pending_ops_);
    }
    for (auto& op : ops) ApplyOp(op);
    for (auto& [id, data] : sends) {
      auto it = FindConnInflate(id);  // egress re-inflates a parked conn
      if (it == conns_.end()) continue;
      // one WS binary frame per send() batch on WS conns
      AppendMqtt(it->second, data.data(), data.size());
      // AppendMqtt can rehash conns_ for CoAP conns (a CONNACK drains
      // preconn, and a parked re-register mints a successor conn):
      // never Flush through the pre-append iterator (review finding)
      auto again = conns_.find(id);
      if (again != conns_.end()) Flush(id, again->second);
    }
    for (uint64_t id : closes) {
      auto it = conns_.find(id);
      if (it == conns_.end()) {
        DropParked(id, "closed_by_host", false);  // no inflation to die
        continue;
      }
      it->second.want_close = true;
      if (it->second.outbuf.size() == it->second.outpos)
        Drop(id, "closed_by_host", false);
    }
  }

  void ApplyOp(Op& op) {
    switch (op.kind) {
      case Op::kSubAdd: {
        subs_.Add(op.owner, op.str, op.qos, op.flags);
        if (op.flags & kSubPunt)
          punt_subs_.Add(op.owner, op.str, op.qos, op.flags);
        // real entries (owner == a live conn id) are torn down with the
        // conn; remember them on the conn for that cleanup
        auto it = FindConnInflate(op.owner);
        if (it != conns_.end() && !(op.flags & kSubPunt))
          it->second.own_subs.push_back(op.str);
        break;
      }
      case Op::kSubDel:
        subs_.Remove(op.owner, op.str);
        punt_subs_.Remove(op.owner, op.str);
        break;
      case Op::kPermit: {
        auto it = FindConnInflate(op.owner);
        if (it != conns_.end() && it->second.permits.size() < 4096)
          it->second.permits.insert(op.str);
        break;
      }
      case Op::kEnableFast: {
        auto it = FindConnInflate(op.owner);
        if (it != conns_.end()) {
          it->second.fast = true;
          it->second.proto_ver = op.proto_ver;
          if (op.max_inflight)
            it->second.max_inflight =
                op.max_inflight < 0x7FFFu ? op.max_inflight : 0x7FFFu;
          // the publisher's clientid (round 18): durable appends stamp
          // it into the store (flags bit5) so no-local / from_
          // attribution survive a restart. Side map, not Conn state —
          // it must outlive park/inflate cycles.
          if (!op.str.empty() && op.str.size() <= 255)
            conn_cids_[op.owner] = op.str;
        }
        break;
      }
      case Op::kDisableFast: {
        auto it = FindConnInflate(op.owner);
        if (it != conns_.end()) {
          Conn& c = it->second;
          // live plane demotion (round 10): the AckState HANDS OFF to
          // the Python session (kind 11) instead of evaporating — a
          // qos2 retransmit straddling the demotion must dedup against
          // the awaiting-rel ids we owned, and the window/pending state
          // is the session's to finish. Emitted before the reset, and
          // only when there was a fast plane to demote (a second
          // disable on an already-slow conn is a no-op, not a loop).
          if (c.fast || c.ack) EmitHandoff(op.owner, c);
          c.fast = false;
          c.permits.clear();
          // orphaned native window state would eat acks meant for the
          // Python session once the conn goes slow-only
          c.ack.reset();
        }
        break;
      }
      case Op::kSetInflightCap: {
        // dynamic receive-window split: Python re-divides the client's
        // receive-maximum budget between the planes per ack cycle; the
        // caller guarantees native_cap + python_cap <= budget at every
        // step, so the sum of occupancies can never exceed the budget
        auto it = FindConnInflate(op.owner);
        if (it != conns_.end()) {
          it->second.max_inflight =
              op.max_inflight < 0x7FFFu ? op.max_inflight : 0x7FFFu;
          // a raised cap frees window slots: drain the pending queue
          DrainPending(op.owner, it->second);
          FlushDirty();
        }
        break;
      }
      case Op::kPermitsFlush:
        // topology changed (rule created, authz source changed, trace
        // enabled...): every publisher re-earns its permits through the
        // full Python path
        for (auto& [id, c] : conns_) c.permits.clear();
        break;
      case Op::kSharedAdd: {
        subs_.SharedAdd(op.token, op.owner, op.str, op.qos, op.flags);
        auto it = conns_.find(op.owner);
        if (it != conns_.end()) {
          auto& own = it->second.own_shared;
          bool seen = false;
          for (auto& [tok, filt] : own)
            if (tok == op.token && filt == op.str) {
              seen = true;       // reconcile re-upserts constantly;
              break;             // one bookkeeping entry is enough
            }
          if (!seen) own.emplace_back(op.token, op.str);
        }
        break;
      }
      case Op::kSharedDel: {
        subs_.SharedRemove(op.token, op.owner, op.str);
        auto it = conns_.find(op.owner);
        if (it != conns_.end()) {
          auto& own = it->second.own_shared;
          for (size_t i = 0; i < own.size(); i++)
            if (own[i].first == op.token && own[i].second == op.str) {
              own[i] = std::move(own.back());
              own.pop_back();
              break;
            }
        }
        break;
      }
      case Op::kSetLane:
        lane_enabled_ = op.flags != 0;
        if (!lane_enabled_) LaneDrainToPython();
        break;
      case Op::kLaneDeliver:
        LaneDeliver(op.str);
        break;
      case Op::kSetMaxQos:
        max_qos_allowed_ = op.qos;
        break;
      case Op::kSetTrace: {
        auto it = FindConnInflate(op.owner);
        if (it == conns_.end()) break;
        bool on = op.flags != 0;
        if (on && !it->second.traced) {
          it->second.traced = true;
          // attach the pre-trace tail NOW: the events leading up to
          // trace start are exactly what the operator wants to see
          EmitFlightRec(op.owner, it->second, kFrReasonTrace);
        } else if (!on) {
          it->second.traced = false;
        }
        break;
      }
      case Op::kSetTelemetry:
        telemetry_ = op.flags != 0;
        slow_ack_ns_ = op.token;
        break;
      case Op::kTrunkConnect: {
        trunk::Peer& p = trunk_peers_[op.owner];
        p.addr = op.str;
        p.port = static_cast<uint16_t>(op.token);
        TrunkRingLoad(op.owner, p);
        TrunkDial(op.owner, p);
        break;
      }
      case Op::kTrunkIdent: {
        // bind the peer id to its stable NODE NAME (round 18): the
        // store keys trunk replay rings on it, since peer ids are
        // minted per-process and a restart renumbers them
        trunk::Peer& p = trunk_peers_[op.owner];
        if (p.store_name.empty()) {
          p.store_name = op.str;
        } else if (p.store_name != op.str && p.unacked.empty()) {
          // a name change with NOTHING journaled yet (e.g. a flush
          // that raced ahead load-marked the fallback key): adopt the
          // real name and re-open the one-shot merge, or the previous
          // life's ring under the node name would never replay
          // (review finding). With live ring entries the old key is
          // authoritative — never strand their ack path.
          p.store_name = op.str;
          p.ring_loaded = false;
        }
        TrunkRingLoad(op.owner, p);
        break;
      }
      case Op::kTrunkDisconnect: {
        auto it = trunk_peers_.find(op.owner);
        if (it == trunk_peers_.end()) break;
        if (it->second.sock_tag) TrunkSockDead(it->second.sock_tag, "drop");
        // flags != 0 forgets the peer entirely (node left the cluster:
        // routes are already gone, the replay ring — including its
        // store-backed records — dies with it);
        // flags == 0 keeps the state so a redial replays unacked qos1
        if (op.flags) {
          if (store_) store_->TrunkDrop(TrunkStoreName(op.owner,
                                                       it->second));
          trunk_peers_.erase(op.owner);
        }
        break;
      }
      case Op::kTrunkRouteAdd:
        // the third entry kind: sibling of the punt marker. Mirrored
        // into punt_subs_ too so the DEVICE lane (whose model cannot
        // see remote routes) conservatively punts trunk audiences —
        // the walk path reads the kSubRemote flag straight from subs_.
        subs_.Add(kTrunkOwnerBase + op.owner, op.str, 0, kSubRemote);
        punt_subs_.Add(kTrunkOwnerBase + op.owner, op.str, 0, kSubRemote);
        break;
      case Op::kTrunkRouteDel:
        subs_.Remove(kTrunkOwnerBase + op.owner, op.str);
        punt_subs_.Remove(kTrunkOwnerBase + op.owner, op.str);
        break;
      case Op::kDurableAdd:
        // the FOURTH entry kind (round 10): a persistent session's
        // filter, served by the durable plane — NOT mirrored into
        // punt_subs_ (it must not punt; FanOut persists it, and the
        // device lane's MatchFilter finds it under the named filter).
        // owner namespaced: raw store tokens would collide with conn ids
        subs_.Add(kDurableOwnerBase + op.owner, op.str, op.qos,
                  kSubDurable);
        break;
      case Op::kDurableDel:
        subs_.Remove(kDurableOwnerBase + op.owner, op.str);
        break;
      case Op::kSnPredef:
        // gateway-wide predefined topic-id table (empty topic = forget)
        if (op.str.empty())
          sn_predefined_.erase(static_cast<uint16_t>(op.owner));
        else
          sn_predefined_[static_cast<uint16_t>(op.owner)] = op.str;
        break;
      case Op::kRetainSet:
        retained_.Set(op.str, op.str2, op.qos, op.token);
        stats_[kStRetainSet].fetch_add(1, std::memory_order_relaxed);
        break;
      case Op::kRetainDel:
        if (retained_.Del(op.str))
          stats_[kStRetainDel].fetch_add(1, std::memory_order_relaxed);
        break;
      case Op::kRetainDeliver:
        RetainDeliver(op.owner, op.str, op.qos);
        break;
      case Op::kCoapRetainState:
        // Python's retained mirror is complete (no props-carrying
        // topics excluded) -> plain CoAP GETs may serve from the
        // native snapshot; incomplete -> they degrade to the oracle
        coap_retain_complete_ = op.flags != 0;
        break;
      case Op::kSetCoapAckTimeout:
        // CON retransmit base (tests compress the RFC 7252 clock;
        // 0 restores the default ACK_TIMEOUT x 1.5)
        coap_ack_timeout_ms_ = op.token ? op.token : coap::kAckTimeoutMs;
        break;
      case Op::kCoapSend: {
        // raw oracle-plane response bytes for a CoAP peer (the punt
        // seam's answer path): framed into the conn outbuf verbatim
        auto cit = conns_.find(op.owner);
        if (cit == conns_.end() || !cit->second.coap) break;
        if (op.str.size() <= coap::kMaxMessage) {
          CoapOut(cit->second, op.str);
          Flush(op.owner, cit->second);
        }
        break;
      }
      case Op::kSetTeleShift:
        // EMQX_NATIVE_TELEMETRY_SHIFT: per-message stages sample
        // 1-in-2^shift (default shift 3 = 1-in-8); bench runs widen it
        tele_mask_ = (op.token >= 1 && op.token <= 16)
                         ? static_cast<uint32_t>((1ull << op.token) - 1)
                         : 7u;
        break;
      case Op::kTrunkPeerState:
        // the owner shard's kind-9 UP/DOWN mirrored onto every OTHER
        // shard by Python (round 15 — owners spread as peer % n): the
        // TrunkEligible oracle for ring-forwarded legs; the owner
        // ignores its own mirror entry (OwnsTrunkPeer routes it to
        // the authoritative peer state)
        trunk_peer_up_[op.owner] = op.flags != 0;
        break;
      case Op::kSetTracing:
        // the deterministic 1-in-2^shift publish sampler; seed carries
        // the node/shard prefix Python composed (nonzero — trace id 0
        // means "not sampled" everywhere)
        tracing_ = op.flags != 0;
        trace_mask_ = op.max_inflight <= 16
                          ? (1u << op.max_inflight) - 1
                          : 63u;
        if (op.token) trace_seed_ = op.token;
        break;
      case Op::kSetTrunkWire:
        // cap the advertised/accepted trunk wire version (tests dial
        // this to 0 to exercise the old-peer downshift)
        trunk_wire_max_ = op.qos <= trunk::kWireVersion
                              ? op.qos
                              : trunk::kWireVersion;
        break;
      case Op::kSetKeepalive: {
        // keepalive moves onto the wheel: `token` is the EFFECTIVE
        // deadline (Python passes 1.5x the negotiated keepalive); 0
        // disarms. The park horizon derives from it (2x the grace).
        auto it = FindConnInflate(op.owner);
        if (it == conns_.end()) break;
        Conn& c = it->second;
        c.keepalive_ms = static_cast<uint32_t>(op.token);
        if (c.tm_keepalive) {
          wheel_.Cancel(c.tm_keepalive);
          c.tm_keepalive = 0;
        }
        if (c.keepalive_ms)
          c.tm_keepalive = wheel_.Arm(op.owner, kTmKeepalive,
                                      NowMs() + c.keepalive_ms);
        if (c.tm_park) {
          wheel_.Cancel(c.tm_park);
          c.tm_park = 0;
        }
        // SN conns never park (CanPark rejects them; sleep mode is
        // their hibernation) — don't churn a timer that can't fire
        if (park_enabled_ && !c.sn) {
          uint64_t base = c.last_work_ms ? c.last_work_ms : c.last_rx_ms;
          c.tm_park = wheel_.Arm(op.owner, kTmPark,
                                 base + ParkAfterOf(c));
        }
        break;
      }
      case Op::kSetPark: {
        // conn-scale knobs: flags = park enabled, max_inflight = the
        // no-keepalive park-after fallback (ms, 0 keeps the default),
        // owner = accept burst/cycle, token = conn-memory budget bytes
        bool was = park_enabled_;
        park_enabled_ = op.flags != 0;
        park_after_ms_ = op.max_inflight;  // 0 = the 2x-grace default
        gov_.Configure(static_cast<uint32_t>(op.owner), op.token);
        if (park_enabled_) {
          // (re-)arm park deadlines against each conn's IDLE BASE —
          // not "now": reconfiguring must preserve elapsed idleness,
          // or a periodic set_park would postpone every park forever
          for (auto& [cid, c] : conns_) {
            if (c.sn) continue;
            if (c.tm_park) wheel_.Cancel(c.tm_park);
            uint64_t base = c.last_work_ms ? c.last_work_ms
                                           : c.last_rx_ms;
            c.tm_park = wheel_.Arm(cid, kTmPark, base + ParkAfterOf(c));
          }
        }
        break;
      }
      case Op::kSynthConns:
        SynthConns(static_cast<uint32_t>(op.owner),
                   static_cast<uint32_t>(op.token), op.max_inflight,
                   op.str);
        break;
      case Op::kSetTrunkAckTimeout:
        // silent-link watchdog deadline (round 15); tests tighten it
        // so a blackholed link dies in milliseconds instead of
        // seconds, and 0 DISABLES the watchdog (the store's
        // compact-age convention — a swallowed 0 was a review finding)
        trunk_ack_timeout_ms_ = op.token;
        // the deadline changed: re-arm every peer's wheel entry
        // against it (round 16 — the watchdog rides the wheel now)
        for (auto& [peer_id, p] : trunk_peers_) {
          if (p.tm_ack) {
            wheel_.Cancel(p.tm_ack);
            p.tm_ack = 0;
          }
          if (p.up) TrunkAckWatch(peer_id, p);
        }
        break;
    }
  }

  // -- device match lane --------------------------------------------------

  struct LaneEntry {
    uint64_t publisher = 0;
    uint8_t qos = 0;
    uint16_t pid = 0;
    uint64_t enq_ms = 0;
    std::string frame;  // original PUBLISH bytes (punts forward these)
    uint32_t topic_off = 0, topic_len = 0, payload_off = 0;
  };

  void LaneEnqueue(uint64_t seq, LaneEntry&& le) {
    key_scratch_.assign(le.frame.data() + le.topic_off, le.topic_len);
    lane_topic_pending_[key_scratch_]++;
    lane_pending_.emplace(seq, std::move(le));
    lane_order_.push_back(seq);
    lane_backlog_.store(lane_pending_.size(), std::memory_order_relaxed);
  }

  // Callers invoke this AFTER erasing the entry from lane_pending_, so
  // the backlog gauge reads the true remaining count (an entry-held
  // copy of the topic keeps this valid post-erase).
  void LaneForget(const LaneEntry& le) {
    key_scratch_.assign(le.frame.data() + le.topic_off, le.topic_len);
    auto it = lane_topic_pending_.find(key_scratch_);
    if (it != lane_topic_pending_.end() && --it->second == 0) {
      lane_topic_pending_.erase(it);
      // last parked frame for this topic resolved: the poison window
      // (below) closes — new frames already take the Python path via
      // the revoked permit
      lane_poisoned_.erase(key_scratch_);
    }
    lane_backlog_.store(lane_pending_.size(), std::memory_order_relaxed);
  }

  // Punt one parked frame to Python exactly as the walk path would have
  // BEFORE consuming it: the original bytes go up as a normal frame
  // event and the channel/broker run the whole fan-out.
  //
  // ``revoke_permit`` is the per-(publisher, topic) ordering guard for
  // NON-deterministic punts (pump failure, tokenizer/K-cap fallback,
  // stale drain): the next frame from this publisher must also take
  // the Python path — behind this one in the same FIFO — instead of a
  // native delivery overtaking it. Marker punts don't need it: the
  // marker makes every subsequent verdict punt identically, exactly
  // like the walk path.
  void LanePunt(LaneEntry& le, bool revoke_permit) {
    stats_[kStLanePunts].fetch_add(1, std::memory_order_relaxed);
    stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
    if (revoke_permit) {
      key_scratch_.assign(le.frame.data() + le.topic_off, le.topic_len);
      // poison the topic while same-topic frames remain parked in
      // OTHER in-flight batches: their device verdicts may differ from
      // this one, and a native delivery would overtake this punt in
      // Python's pipeline. Poisoned frames punt unconditionally — same
      // FIFO — until the topic's parked count drains to zero.
      if (lane_topic_pending_.count(key_scratch_))
        lane_poisoned_.insert(key_scratch_);
      auto it = FindConnInflate(le.publisher);
      if (it != conns_.end()) it->second.permits.erase(key_scratch_);
    }
    events_.push_back(
        EncodeRecord(2, le.publisher, le.frame.data(), le.frame.size()));
  }

  // Pump failure / lane shutdown: every parked frame goes to Python in
  // arrival order (Python's pipeline is FIFO, so per-topic order holds
  // within the drained set); permits are revoked so trailing frames
  // queue behind the drained ones instead of overtaking them natively.
  void LaneDrainToPython() {
    for (uint64_t seq : lane_order_) {
      auto it = lane_pending_.find(seq);
      if (it == lane_pending_.end()) continue;
      LaneEntry le = std::move(it->second);
      lane_pending_.erase(it);
      LaneForget(le);
      LanePunt(le, /*revoke_permit=*/true);
    }
    lane_order_.clear();
    lane_backlog_.store(0, std::memory_order_relaxed);
  }

  // A stale head means the Python pump stopped responding (device
  // wedge, thread death): fail the whole lane over to the slow path
  // and turn it off. Python watches the kStLaneStale counter and
  // resyncs its side (and may re-enable once the pump is healthy).
  void LaneStaleScan() {
    if (lane_order_.empty()) return;
    auto it = lane_pending_.find(lane_order_.front());
    while (it == lane_pending_.end() && !lane_order_.empty()) {
      lane_order_.pop_front();  // already answered; trim lazily
      if (lane_order_.empty()) return;
      it = lane_pending_.find(lane_order_.front());
    }
    if (it == lane_pending_.end()) return;
    if (NowMs() - it->second.enq_ms < kLaneStaleMs) return;
    lane_enabled_ = false;
    stats_[kStLaneStale].fetch_add(1, std::memory_order_relaxed);
    LaneDrainToPython();
  }

  // Shared native fan-out tail (TryFast walk path + LaneDeliver): the
  // publisher ack, the per-entry deliveries and the shared-group
  // rotation MUST stay one code path — callers pre-populate
  // match_scratch_/groups_scratch_ and have already ruled out punts.
  // ``count_fast=false`` is the trunk-receiver call shape: the publish
  // arrived over a trunk link (publisher = 0, no local conn to ack) and
  // counts as kStTrunkIn at the call site, not kStFastIn here.
  // @admit-gated — callers run the ladder (ShardAdmit) first
  void FanOut(uint64_t publisher, uint8_t qos, uint16_t pid,
              std::string_view topic, std::string_view payload,
              bool count_fast = true) {
    if (qos) {
      // ack first: the reference PUBACKs (or PUBRECs for qos2) as soon
      // as emqx_broker:publish returns
      auto pit = conns_.find(publisher);
      if (pit != conns_.end()) {
        char ack[4] = {static_cast<char>(qos == 1 ? 0x40 : 0x50), 0x02,
                       static_cast<char>(pid >> 8),
                       static_cast<char>(pid & 0xFF)};
        AppendMqtt(pit->second, ack, 4);
        MarkDirty(publisher, pit->second);
      }
    }
    if (count_fast)
      stats_[kStFastIn].fetch_add(1, std::memory_order_relaxed);
    // shared serialized frames per proto: qos0 frames are reused
    // verbatim; elevated-qos frames are built ONCE per publish with a
    // zero pid, then appended and pid/qos-patched in place per target
    // (the round-5 per-target BuildPublish rebuild was measurable on
    // the windowed qos1 path)
    frame_v4_.clear();
    frame_v5_.clear();
    frame_q_v4_.clear();
    frame_q_v5_.clear();
    dur_tok_scratch_.clear();
    fan_xshipped_ = 0;
    for (const SubEntry* e : match_scratch_) {
      // rule taps never deliver; remote entries forward via the trunk
      // (TryFast enqueues them) or punt — never through a local write;
      // durable entries persist (below) instead of delivering
      if (e->flags & kSubDurable) {
        dur_tok_scratch_.push_back(e->owner - kDurableOwnerBase);
        continue;
      }
      if (e->flags & (kSubRuleTap | kSubRemote)) continue;
      if ((e->flags & kSubNoLocal) && e->owner == publisher) continue;
      if (group_) {
        int ds = ShardOf(e->owner);
        if (ds != shard_id_) {
          // the subscriber's conn lives on another shard: collect it —
          // ONE multi-target ring entry per (publish, shard) ships
          // after the loop (admission already ran in ShardAdmit,
          // BEFORE any side effect of this publish); the target
          // shard's DeliverTo runs its window/backpressure machinery
          // and counts kStFastOut there
          uint8_t oq = qos < e->qos ? qos : e->qos;
          xtgt_scratch_[ds].push_back(
              e->owner | (static_cast<uint64_t>(oq) << 60));
          continue;
        }
      }
      DeliverTo(e->owner, *e, publisher, qos, topic, payload);
    }
    if (group_) {
      for (int ds = 0; ds < group_->n; ds++) {
        if (xtgt_scratch_[ds].empty()) continue;
        // Admitted publishes (TryFast/LaneDeliver ran ShardAdmit this
        // cycle, same thread, nothing pushed since) always pass this
        // re-check. The UNADMITTED caller — the trunk receiver's
        // fan-out, which cannot punt a publish that already left its
        // origin node — degrades ITS deliveries alone to a counted
        // drop here instead of appending to a batch whose seal-time
        // Push failure would discard other publishes' entries too.
        if (RingRoom(ds)) {
          XShipMulti(ds, xtgt_scratch_[ds], publisher, qos, topic,
                     payload);
          fan_xshipped_++;
        } else {
          stats_[kStShardRingFull].fetch_add(1,
                                             std::memory_order_relaxed);
          stats_[kStDropsBackpressure].fetch_add(
              xtgt_scratch_[ds].size(), std::memory_order_relaxed);
          LedgerNote(kLrRingFull, static_cast<uint64_t>(ds));
        }
        xtgt_scratch_[ds].clear();
      }
    }
    if (!dur_tok_scratch_.empty()) {
      // dedup once, O(S log S): two filters of one session yield one
      // marker + one replay (a per-entry linear scan was O(S^2) on the
      // fast path for wide durable audiences)
      std::sort(dur_tok_scratch_.begin(), dur_tok_scratch_.end());
      dur_tok_scratch_.erase(
          std::unique(dur_tok_scratch_.begin(), dur_tok_scratch_.end()),
          dur_tok_scratch_.end());
      if (store_) DurableAppend(publisher, qos, topic, payload);
    }
    // natively served $share groups: one member per group, rotating;
    // skipped members (gone / backpressured / window full) get the
    // redispatch treatment — the next member takes the message
    // (emqx_shared_sub.erl:190-217)
    for (SharedGroup* g : groups_scratch_) {
      size_t nmem = g->members.size();
      bool delivered = false;
      for (size_t k = 0; k < nmem && !delivered; k++) {
        const SubEntry& e = g->members[g->cursor % nmem];
        g->cursor++;
        if ((e.flags & kSubNoLocal) && e.owner == publisher) continue;
        if (group_ && ShardOf(e.owner) != shard_id_) {
          // cross-shard member: a full ring admits the ship; a full
          // one skips this member and the next takes the message —
          // the nack/redispatch shape, not a punt (groups are picked
          // one-member-at-a-time, so per-member degradation is safe)
          int ds = ShardOf(e.owner);
          if (RingRoom(ds)) {
            uint8_t oq = qos < e.qos ? qos : e.qos;
            XShip(ds, e.owner, publisher, oq, false, topic, payload);
            fan_xshipped_++;
            delivered = true;
          } else {
            stats_[kStShardRingFull].fetch_add(1,
                                               std::memory_order_relaxed);
            LedgerNote(kLrRingFull, static_cast<uint64_t>(ds));
          }
          continue;
        }
        delivered = DeliverTo(e.owner, e, publisher, qos, topic, payload);
      }
      stats_[delivered ? kStSharedDispatch : kStSharedNoMember].fetch_add(
          1, std::memory_order_relaxed);
    }
  }

  // Apply one pump response blob:
  //   [u32 count] then per item
  //   [u64 seq][u8 flags][u16 nf] + nf x ([u16 len][filter bytes])
  // flags bit0 = punt (device overflow / tokenizer reject / pump spill).
  void LaneDeliver(const std::string& blob) {
    size_t pos = 0;
    auto need = [&](size_t n) { return pos + n <= blob.size(); };
    auto rd_u16 = [&]() {
      uint16_t v = static_cast<uint8_t>(blob[pos]) |
                   (static_cast<uint8_t>(blob[pos + 1]) << 8);
      pos += 2;
      return v;
    };
    if (!need(4)) return;
    uint32_t count = 0;
    memcpy(&count, blob.data(), 4);
    pos = 4;
    for (uint32_t i = 0; i < count; i++) {
      if (!need(8 + 1 + 2)) return;  // truncated blob: keep rest parked
      uint64_t seq = 0;
      memcpy(&seq, blob.data() + pos, 8);
      pos += 8;
      uint8_t rflags = static_cast<uint8_t>(blob[pos++]);
      uint16_t nf = rd_u16();
      size_t filters_at = pos;
      for (uint16_t k = 0; k < nf; k++) {
        if (!need(2)) return;
        uint16_t fl = rd_u16();
        if (!need(fl)) return;
        pos += fl;
      }
      auto it = lane_pending_.find(seq);
      if (it == lane_pending_.end()) continue;  // drained/stale already
      LaneEntry le = std::move(it->second);
      lane_pending_.erase(it);
      if (telemetry_) {
        // lane dwell (enqueue -> device verdict applied): ms-scale by
        // nature (a device round trip), so the coarse clock suffices
        uint64_t now_ms = NowMs();
        RecordHist(kHistLaneDwell,
                   (now_ms > le.enq_ms ? now_ms - le.enq_ms : 0)
                       * 1000000ull);
      }
      std::string_view topic(le.frame.data() + le.topic_off, le.topic_len);
      std::string_view payload(le.frame.data() + le.payload_off,
                               le.frame.size() - le.payload_off);
      if (telemetry_) cur_hash_ = TopicHash(topic);  // for FanOut notes
      // poison must be read BEFORE LaneForget: forgetting the LAST
      // parked frame of a poisoned topic erases the poison, and the
      // pre-fix order let exactly that frame deliver natively —
      // overtaking the punted earlier frame still queued in Python's
      // FIFO (same-topic reorder)
      key_scratch_.assign(topic.data(), topic.size());
      bool poisoned = lane_poisoned_.count(key_scratch_) != 0;
      LaneForget(le);
      if (poisoned) {
        // an earlier same-topic frame was nondeterministically punted;
        // this one must follow it through Python, not overtake it
        LanePunt(le, /*revoke_permit=*/true);
        continue;
      }
      if (rflags & 1) {
        // pump failure / tokenizer reject / K-cap overflow: a verdict
        // the NEXT message may not repeat — revoke the permit so
        // per-publisher order survives the switch to the Python path
        LanePunt(le, /*revoke_permit=*/true);
        continue;
      }
      // the device model only sees broker-table subscriptions; punt
      // markers it cannot know about (remote routes, flips raced with
      // this batch) are re-checked against the punt-only trie. Remote
      // entries no longer punt wholesale (round 12, the lane+trunk
      // coexistence edge): an eligible trunk audience collects here
      // and the remote leg is enqueued AFTER the device-matched local
      // fan-out — only real punt shapes (or a down/ineligible trunk)
      // still force the Python path.
      punt_scratch_.clear();
      punt_subs_.Match(topic, &punt_scratch_);
      trunk_scratch_.clear();
      bool lane_punt = false;
      for (const SubEntry* pe : punt_scratch_) {
        if (!(pe->flags & kSubRemote)) {
          lane_punt = true;
          break;
        }
        uint64_t peer = pe->owner - kTrunkOwnerBase;
        if (!TrunkEligible(peer, le.qos,
                           15 + topic.size() + payload.size())) {
          LedgerNote(kLrTrunkPunt, peer);
          lane_punt = true;
          break;
        }
        PushUnique(&trunk_scratch_, peer);
      }
      if (lane_punt) {
        LanePunt(le, /*revoke_permit=*/false);
        continue;
      }
      match_scratch_.clear();
      groups_scratch_.clear();
      size_t fpos = filters_at;
      for (uint16_t k = 0; k < nf; k++) {
        uint16_t fl = static_cast<uint8_t>(blob[fpos]) |
                      (static_cast<uint8_t>(blob[fpos + 1]) << 8);
        fpos += 2;
        subs_.MatchFilter(std::string_view(blob.data() + fpos, fl),
                          &match_scratch_, &groups_scratch_);
        fpos += fl;
      }
      bool punt = false, tapped = false;
      for (const SubEntry* e : match_scratch_) {
        if (e->flags & kSubPunt) {
          punt = true;
          break;
        }
        if (e->flags & kSubRuleTap) tapped = true;
      }
      if (punt) {
        LanePunt(le, /*revoke_permit=*/false);
        continue;
      }
      if (!ShardAdmit()) {
        // a destination shard's ring cannot take this fan-out: the
        // walk path's ring-full -> punt -> Python ladder, through the
        // lane's punt seam (BEFORE the tap/ack side effects)
        LanePunt(le, /*revoke_permit=*/false);
        continue;
      }
      bool ldup = (static_cast<uint8_t>(le.frame[0]) & 0x08) != 0;
      // lane deliveries are native-consumed publishes too: same
      // sampling commit point as the walk path (shared ticker)
      TraceSample(le.publisher);
      if (tapped) EmitTap(le.publisher, le.qos, ldup, topic, payload);
      stats_[kStLaneOut].fetch_add(1, std::memory_order_relaxed);
      if (le.qos == 1)
        stats_[kStQos1In].fetch_add(1, std::memory_order_relaxed);
      cur_dup_ = ldup;
      FanOut(le.publisher, le.qos, le.pid, topic, payload);
      if (cur_trace_) SpanNote(kSpanRoute, match_scratch_.size());
      // the remote legs collected above (lane+trunk coexistence): the
      // trunk enqueue next to the device-matched local fan-out — the
      // TryFast walk path's two-halves discipline
      for (uint64_t peer : trunk_scratch_) {
        if (OwnsTrunkPeer(peer))
          TrunkEnqueue(peer, le.publisher, le.qos, ldup, topic, payload);
        else
          XShip(TrunkShardOf(peer), kTrunkOwnerBase + peer,
                le.publisher, le.qos, ldup, topic, payload);
      }
      cur_trace_ = 0;  // this frame's trace context ends here
      if (telemetry_ && (fan_xshipped_ || !trunk_scratch_.empty())) {
        auto pit = FindConnInflate(le.publisher);
        if (pit != conns_.end()) {
          if (fan_xshipped_)
            FrNote(pit->second, kFrRingCross, 3,
                   static_cast<uint16_t>(fan_xshipped_), cur_hash_);
          if (!trunk_scratch_.empty())
            FrNote(pit->second, kFrTrunk, 3,
                   static_cast<uint16_t>(trunk_scratch_[0] & 0xFFFF),
                   cur_hash_);
        }
      }
    }
    FlushDirty();
  }

  void HandleEvent(const epoll_event& ev) {
    if (ev.data.u64 == kWakeTag) {
      uint64_t junk;
      while (read(wake_fd_, &junk, sizeof(junk)) > 0) {}
      return;
    }
    if (ev.data.u64 == kShardWakeTag) {
      // another shard pushed onto our inbound rings; the drain itself
      // runs once per poll cycle (DrainShardRings) — just clear the
      // doorbell here
      uint64_t junk;
      while (read(group_->doorbell[shard_id_], &junk, sizeof(junk)) > 0) {}
      return;
    }
    if (ev.data.u64 == kListenTag || ev.data.u64 == kListenWsTag) {
      Accept(ev.data.u64 == kListenWsTag);
      return;
    }
    if (ev.data.u64 == kListenTrunkTag) {
      TrunkAccept();
      return;
    }
    if (ev.data.u64 == kListenSnTag) {
      // checked BEFORE the trunk-bit test: the listener tags live at
      // the top of the u64 space and carry bit 63 too
      SnRead();
      return;
    }
    if (ev.data.u64 == kListenCoapTag) {
      CoapRead();
      return;
    }
    if (ev.data.u64 & kTrunkSockBit) {
      TrunkEvent(ev);
      return;
    }
    uint64_t id = ev.data.u64;
    auto it = conns_.find(id);
    if (it == conns_.end()) {
      // hibernating conns keep their fd registered under the same tag:
      // the first byte (or HUP) lands here and is served from — or
      // re-inflates — the parked record before any fast-path work
      auto pit = parked_.find(id);
      if (pit == parked_.end()) return;
      if (ev.events & (EPOLLHUP | EPOLLERR)) {
        DropParked(id, "sock_error", true);
        return;
      }
      if (ev.events & EPOLLIN) ParkedRead(id, pit->second);
      return;
    }
    if (ev.events & (EPOLLHUP | EPOLLERR)) {
      Drop(id, "sock_error", true);
      return;
    }
    if (ev.events & EPOLLOUT) {
      Flush(id, it->second);
      it = conns_.find(id);
      if (it == conns_.end()) return;
    }
    if (ev.events & EPOLLIN) Read(id, it->second);
  }

  void Accept(bool is_ws) {
    int lfd = is_ws ? listen_ws_fd_ : listen_fd_;
    for (;;) {
      // backlog-pressure rung: past the per-cycle burst the kernel
      // listen backlog keeps the remainder for the next cycle — a
      // connect storm is paced, not serviced at the expense of every
      // established conn's poll latency (no side effects, no shed)
      if (gov_.Defer()) return;
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = accept4(lfd, reinterpret_cast<sockaddr*>(&peer), &plen,
                       SOCK_NONBLOCK | SOCK_CLOEXEC);
      if (fd < 0) return;
      // @fault(conn_accept) — the accepted conn is torn down on the
      // spot (the client sees an RST: an accept-storm shed)
      if (FaultHit(fault::kSiteConnAccept, 0)) {
        close(fd);
        continue;
      }
      // accept-shed rung: admission (memory budget, esockd max-conn
      // limiting) is decided BEFORE any conn side effect — no id, no
      // table entry, no OPEN event for a shed accept; the close is
      // ledger-visible instead of silent
      // the estimate INCLUDES the conn under admission: crossing the
      // budget sheds the conn that would cross it, not the one after
      bool admit = gov_.Admit(ConnMemEstimate() + kConnResidentEstBytes);
      if (!admit || conns_.size() + parked_.size() >= max_conns_) {
        close(fd);
        stats_[kStConnsShed].fetch_add(1, std::memory_order_relaxed);
        LedgerNote(kLrAcceptShed, conns_.size() + parked_.size());
        continue;
      }
      AcceptConn(fd, peer, is_ws);
    }
  }

  // Accept side effects: id mint, conn-table insert, epoll
  // registration, the OPEN event. Accept() calls this only after the
  // governor's admit check (the ladder contract — nativecheck rule 3).
  // @admit-gated
  void AcceptConn(int fd, const sockaddr_in& peer, bool is_ws) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    uint64_t id = MintConnId();
    Conn c;
    c.fd = fd;
    c.framer = Framer(max_size_);
    c.last_rx_ms = c.last_work_ms = NowMs();
    if (is_ws) c.ws = std::make_unique<WsConnState>();
    auto& cref = conns_.emplace(id, std::move(c)).first->second;
    if (park_enabled_)
      cref.tm_park =
          wheel_.Arm(id, kTmPark, cref.last_rx_ms + ParkAfterOf(cref));
    FrNote(cref, kFrOpen, 0, is_ws ? 1 : 0);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = id;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
    char ip[INET_ADDRSTRLEN] = "?";
    inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
    std::string info = std::string(is_ws ? "ws:" : "") + ip + ":" +
                       std::to_string(ntohs(peer.sin_port));
    events_.push_back(EncodeRecord(1, id, info.data(), info.size()));
  }

  // -- conn-scale plane (round 16): timer-wheel fires + hibernation -------
  // The per-shard wheel replaced every per-cycle deadline sweep; these
  // handlers run on the poll thread from wheel_.Advance and re-arm
  // themselves (handles are consumed by the fire — wheel.h contract).

  uint64_t ParkAfterOf(const Conn& c) const {
    // configured override wins; the DEFAULT is "2x keepalive grace
    // passed" (grace = the 1.5x-keepalive deadline), falling back to
    // a flat horizon for keepalive-less conns
    if (park_after_ms_) return park_after_ms_;
    return c.keepalive_ms ? 2ull * c.keepalive_ms : kParkAfterDefaultMs;
  }

  uint64_t ConnMemEstimate() const {
    return conns_.size() * kConnResidentEstBytes +
           parked_bytes_.load(std::memory_order_relaxed);
  }

  void FireTimer(uint64_t key, uint8_t kind) {
    switch (kind) {
      case kTmKeepalive: FireKeepalive(key); break;
      case kTmPark: FirePark(key); break;
      case kTmSnRexmit: FireSnRexmit(key); break;
      case kTmTrunkAck: FireTrunkAck(key); break;
      case kTmCoapRexmit: FireCoapRexmit(key); break;
    }
  }

  // Keepalive is lazy-reprogrammed: traffic never touches the wheel;
  // the fire re-checks the real idle clock and either closes the conn
  // or re-arms at the earliest possible expiry. Parked conns are
  // judged (and closed) WITHOUT inflation.
  void FireKeepalive(uint64_t id) {
    // housekeep clock skew (faultline): the wheel judges conns against
    // a future clock while the site is armed, exactly like ConnIdleMs
    uint64_t now = NowMs() + FaultSkewMs();
    auto it = conns_.find(id);
    if (it != conns_.end()) {
      Conn& c = it->second;
      c.tm_keepalive = 0;
      if (!c.keepalive_ms) return;
      uint64_t base = c.last_rx_ms;
      if (c.sn && !c.sn->awake) {
        if (now < c.sn->sleep_until_ms) {
          // announced sleep: expected-silent until the wake deadline;
          // the idle clock restarts AT the deadline (PR 6 grace rule)
          c.tm_keepalive = wheel_.Arm(
              id, kTmKeepalive, c.sn->sleep_until_ms + c.keepalive_ms);
          return;
        }
        if (c.sn->sleep_until_ms > base) base = c.sn->sleep_until_ms;
      }
      if (now - base >= c.keepalive_ms) {
        Drop(id, "keepalive_timeout", true);
        return;
      }
      c.tm_keepalive = wheel_.Arm(id, kTmKeepalive, base + c.keepalive_ms);
      return;
    }
    auto pit = parked_.find(id);
    if (pit == parked_.end()) return;
    park::Parked& p = park_slab_.at(pit->second);
    p.tm_keepalive = 0;
    if (!p.keepalive_ms) return;
    if (now - p.last_rx_ms >= p.keepalive_ms) {
      DropParked(id, "keepalive_timeout", true);
      return;
    }
    p.tm_keepalive =
        wheel_.Arm(id, kTmKeepalive, p.last_rx_ms + p.keepalive_ms);
  }

  void FirePark(uint64_t id) {
    auto it = conns_.find(id);
    if (it == conns_.end()) return;  // gone, or already parked
    Conn& c = it->second;
    c.tm_park = 0;
    if (!park_enabled_) return;
    uint64_t now = NowMs();
    uint64_t after = ParkAfterOf(c);
    uint64_t base = c.last_work_ms ? c.last_work_ms : c.last_rx_ms;
    if (now - base >= after && CanPark(c)) {
      Park(id, it);
      return;
    }
    // not idle enough (or mid-flight state blocks the diet): re-check
    // at the earliest possible park point
    c.tm_park = wheel_.Arm(
        id, kTmPark, (now - base >= after ? now : base) + after);
  }

  // Hibernation preconditions: everything the compact record cannot
  // carry must be empty/at-rest. Mid-flight ack windows ARE carried
  // (sparse summary); a queued-pending window or half-written outbuf
  // is not.
  bool CanPark(const Conn& c) const {
    // datagram conns never park: SN sleep mode already parks
    // deliveries, and a CoAP endpoint's message-layer state (dedup
    // window, observers, retransmit copies) has no compact summary
    if (c.sn || c.coap || c.traced || c.want_close || c.dirty)
      return false;
    if (!c.outbuf.empty() || c.outpos) return false;
    if (!c.framer.idle()) return false;
    if (c.ws && (!c.ws->open || !c.ws->dec.idle() || !c.ws->hs_buf.empty()))
      return false;
    if (c.ack && (!c.ack->pending.empty() || c.ack->cyc_dirty))
      return false;
    return true;
  }

  void Park(uint64_t id, std::unordered_map<uint64_t, Conn>::iterator it) {
    Conn& c = it->second;
    uint32_t slot = park_slab_.Alloc();
    park::Parked& p = park_slab_.at(slot);
    p.fd = c.fd;
    p.flags = (c.fast ? park::kPkFast : 0) |
              (c.ws ? park::kPkWs : 0) |
              (c.fd < 0 ? park::kPkSynth : 0);
    p.proto_ver = c.proto_ver;
    p.max_inflight = c.max_inflight;
    p.keepalive_ms = c.keepalive_ms;
    p.last_rx_ms = c.last_rx_ms;
    p.tm_keepalive = c.tm_keepalive;  // survives hibernation
    p.next_pid = kNativePidBase;
    if (c.ack) {
      // the 20KB bitmap AckState collapses to a sparse summary; the
      // window is INTACT across park/inflate (pids, qos2/rel phase,
      // publisher awaiting-rel, pid allocator position)
      AckState& a = *c.ack;
      p.next_pid = a.next_pid;
      if (a.inflight_cnt) {
        p.infl.reserve(a.inflight_cnt);
        for (uint32_t w = 0; w < 512; w++) {
          uint64_t bits = a.inflight[w];
          while (bits) {
            uint32_t b = static_cast<uint32_t>(__builtin_ctzll(bits));
            bits &= bits - 1;
            uint32_t bi = w * 64 + b;
            uint32_t e = bi;
            if (BitTest(a.infl_qos2, bi)) e |= 1u << 16;
            if (BitTest(a.infl_rel, bi)) e |= 1u << 17;
            p.infl.push_back(e);
          }
        }
      }
      if (a.awaiting_cnt) {
        p.awrel.reserve(a.awaiting_cnt);
        for (uint32_t w = 0; w < 1024; w++) {
          uint64_t bits = a.awaiting_rel[w];
          while (bits) {
            uint32_t b = static_cast<uint32_t>(__builtin_ctzll(bits));
            bits &= bits - 1;
            p.awrel.push_back(static_cast<uint16_t>(w * 64 + b));
          }
        }
      }
    }
    // subscriptions stay LIVE in the match table (a delivery to a
    // parked conn re-inflates it); only the teardown bookkeeping moves
    p.own_subs = std::move(c.own_subs);
    p.own_shared = std::move(c.own_shared);
    // permits are a cache: dropped here, re-earned through one punt
    // after the conn wakes (the authz-cache-miss path, always correct)
    parked_bytes_.fetch_add(park::RecordBytes(p),
                            std::memory_order_relaxed);
    parked_.emplace(id, slot);
    conns_.erase(it);
    stats_[kStConnsParked].fetch_add(1, std::memory_order_relaxed);
  }

  // Re-inflate a hibernating conn (first byte, delivery, control op).
  // Returns conns_.end() when the id is not parked either.
  std::unordered_map<uint64_t, Conn>::iterator InflateParked(uint64_t id) {
    auto pit = parked_.find(id);
    if (pit == parked_.end()) return conns_.end();
    uint32_t slot = pit->second;
    park::Parked& p = park_slab_.at(slot);
    size_t rec_bytes = park::RecordBytes(p);
    Conn c;
    c.fd = p.fd;
    c.framer = Framer(max_size_);
    c.fast = (p.flags & park::kPkFast) != 0;
    c.proto_ver = p.proto_ver;
    if (p.max_inflight) c.max_inflight = p.max_inflight;
    c.keepalive_ms = p.keepalive_ms;
    c.tm_keepalive = p.tm_keepalive;
    c.last_rx_ms = p.last_rx_ms;
    c.last_work_ms = NowMs();  // inflation IS work: no instant re-park
    if (p.flags & park::kPkWs) {
      c.ws = std::make_unique<WsConnState>();
      c.ws->open = true;
    }
    if (!p.infl.empty() || !p.awrel.empty() ||
        (p.next_pid && p.next_pid != kNativePidBase)) {
      c.ack = std::make_unique<AckState>();
      AckState& a = *c.ack;
      a.next_pid = p.next_pid ? p.next_pid : kNativePidBase;
      for (uint32_t e : p.infl) {
        uint32_t bi = e & 0xFFFFu;
        BitSet(a.inflight, bi);
        if (e & (1u << 16)) BitSet(a.infl_qos2, bi);
        if (e & (1u << 17)) BitSet(a.infl_rel, bi);
        a.inflight_cnt++;
      }
      for (uint16_t pidv : p.awrel) {
        BitSet(a.awaiting_rel, pidv);
        a.awaiting_cnt++;
      }
    }
    c.own_subs = std::move(p.own_subs);
    c.own_shared = std::move(p.own_shared);
    parked_bytes_.fetch_sub(rec_bytes, std::memory_order_relaxed);
    park_slab_.Free(slot);
    parked_.erase(pit);
    auto it = conns_.emplace(id, std::move(c)).first;
    if (park_enabled_)
      it->second.tm_park =
          wheel_.Arm(id, kTmPark, NowMs() + ParkAfterOf(it->second));
    stats_[kStConnsInflated].fetch_add(1, std::memory_order_relaxed);
    return it;
  }

  // Inflate-on-demand lookup: delivery/egress/control paths resolve a
  // conn that may be hibernating.
  std::unordered_map<uint64_t, Conn>::iterator FindConnInflate(uint64_t id) {
    auto it = conns_.find(id);
    if (it != conns_.end()) return it;
    return InflateParked(id);
  }

  // Tear a parked conn down without inflating it (keepalive expiry,
  // close_conn, socket death while hibernating).
  void DropParked(uint64_t id, const char* reason, bool notify) {
    auto pit = parked_.find(id);
    if (pit == parked_.end()) return;
    park::Parked& p = park_slab_.at(pit->second);
    for (const std::string& filt : p.own_subs) subs_.Remove(id, filt);
    for (const auto& [token, filt] : p.own_shared)
      subs_.SharedRemove(token, id, filt);
    if (p.tm_keepalive) wheel_.Cancel(p.tm_keepalive);
    if (p.fd >= 0) {
      epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, p.fd, nullptr);
      close(p.fd);
    }
    parked_bytes_.fetch_sub(park::RecordBytes(p),
                            std::memory_order_relaxed);
    park_slab_.Free(pit->second);
    parked_.erase(pit);
    conn_cids_.erase(id);
    if (notify)
      events_.push_back(EncodeRecord(3, id, reason, strlen(reason)));
  }

  // Inbound bytes on a hibernating conn. The keepalive fast path —
  // reads that are nothing but whole PINGREQs — answers from the
  // parked record and STAYS parked, so a million idle-but-pinging
  // devices never churn the park plane; anything else re-inflates
  // before a single fast-path byte is processed.
  void ParkedRead(uint64_t id, uint32_t slot) {
    park::Parked& p = park_slab_.at(slot);
    if (p.fd < 0) return;  // synthetic conns have no socket
    if (p.flags & park::kPkWs) {
      // WS pings arrive framed — not worth a parked-path codec; the
      // inflation cost is one WsConnState + a fresh decoder
      auto it = InflateParked(id);
      if (it != conns_.end()) Read(id, it->second);
      return;
    }
    uint8_t buf[512];
    for (;;) {
      // @fault(conn_read) — the same read seam as Read(): park-during-
      // storm chaos hits hibernating conns too
      ssize_t n = FaultRecv(fault::kSiteConnRead, id, p.fd, buf,
                            sizeof(buf));
      if (n == 0) {
        DropParked(id, "sock_closed", true);
        return;
      }
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno != EAGAIN && errno != EWOULDBLOCK)
          DropParked(id, "sock_error", true);
        return;
      }
      p.last_rx_ms = NowMs();
      bool all_ping = (n % 2) == 0;
      for (ssize_t i = 0; all_ping && i < n; i += 2)
        all_ping = buf[i] == 0xC0 && buf[i + 1] == 0x00;
      if (!all_ping) {
        // real work: inflate FIRST, then run the normal ingest over
        // these bytes and drain whatever else the kernel holds
        auto it = InflateParked(id);
        if (it == conns_.end()) return;
        if (!IngestMqtt(id, it->second, buf, static_cast<size_t>(n))) {
          Drop(id, "frame_error", true);
          return;
        }
        auto again = conns_.find(id);
        if (again != conns_.end()) Read(id, again->second);
        return;
      }
      size_t k = static_cast<size_t>(n) / 2;
      std::string pong(k * 2, '\0');
      for (size_t i = 0; i < k; i++)
        pong[2 * i] = static_cast<char>(0xD0);
      size_t off = 0;
      while (off < pong.size()) {
        // @fault(conn_write) — the parked egress seam
        ssize_t w = FaultSend(fault::kSiteConnWrite, id, p.fd,
                              pong.data() + off, pong.size() - off);
        if (w > 0) {
          off += static_cast<size_t>(w);
          continue;
        }
        if (w < 0 && errno == EINTR) continue;
        if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          // slow reader: inflate and let the outbuf machinery own it
          auto it = InflateParked(id);
          if (it == conns_.end()) return;
          it->second.outbuf.append(pong, off, std::string::npos);
          MarkDirty(id, it->second);
          Flush(id, it->second);
          return;
        }
        DropParked(id, "sock_error", true);
        return;
      }
      stats_[kStParkedPings].fetch_add(k, std::memory_order_relaxed);
      if (n < static_cast<ssize_t>(sizeof(buf))) return;
    }
  }

  // Bench/test surface (raw host only): conjure n resident conns with
  // no socket (fd < 0; egress is discarded) so the conn-scale
  // structures — wheel, park plane, match table — run at 10^6 scale
  // inside a 20k-fd container. Every conn takes the REAL park
  // machinery; none emits OPEN events (the Python server never sees
  // these ids — this is not a product path).
  void SynthConns(uint32_t n, uint32_t keepalive_ms, uint32_t sub_every,
                  const std::string& prefix) {
    uint64_t now = NowMs();
    std::string filt;
    for (uint32_t i = 0; i < n; i++) {
      // the synthetic herd respects the same admission budget
      if (!gov_.Admit(ConnMemEstimate() + kConnResidentEstBytes)) {
        stats_[kStConnsShed].fetch_add(1, std::memory_order_relaxed);
        LedgerNote(kLrAcceptShed, conns_.size() + parked_.size());
        continue;
      }
      uint64_t id = MintConnId();
      Conn c;
      c.fd = -1;
      c.framer = Framer(max_size_);
      c.fast = true;
      c.last_rx_ms = c.last_work_ms = now;
      c.keepalive_ms = keepalive_ms;
      auto& cref = conns_.emplace(id, std::move(c)).first->second;
      if (keepalive_ms)
        cref.tm_keepalive =
            wheel_.Arm(id, kTmKeepalive, now + keepalive_ms);
      if (park_enabled_)
        cref.tm_park = wheel_.Arm(id, kTmPark, now + ParkAfterOf(cref));
      if (sub_every && (i % sub_every) == 0) {
        filt = prefix;
        filt += '/';
        filt += std::to_string(id & 0xFFFFFFFFFFFFull);
        subs_.Add(id, filt, 0, 0);
        cref.own_subs.push_back(filt);
      }
    }
  }

  // Per-conn qos1-over-UDP retransmit: the old SnRexmitScan body for
  // ONE conn, driven by its wheel deadline instead of a per-cycle
  // sweep over every tracked conn.
  void FireSnRexmit(uint64_t id) {
    auto cit = conns_.find(id);
    if (cit == conns_.end() || !cit->second.sn) return;
    Conn& c = cit->second;
    c.sn->tm_rexmit = 0;
    if (c.sn->rexmit.empty()) return;
    if (!c.sn->awake) {
      // announced sleep (§6.14): the radio is off, so neither the
      // retry timer nor the abandonment counter may advance — the
      // parked sleep_buf copy is this delivery's FIRST transmission,
      // sent at wake, and the wake flush re-arms this timer there
      // (the PR 6 retry-clock lesson)
      return;
    }
    uint64_t now = NowMs();
    uint64_t next_due = 0;
    bool resent = false;
    auto& rx = c.sn->rexmit;
    for (size_t i = 0; i < rx.size();) {
      SnInflightRx& r = rx[i];
      if (now - r.last_tx_ms < kSnRetryMs) {
        uint64_t due = r.last_tx_ms + kSnRetryMs;
        if (!next_due || due < next_due) next_due = due;
        i++;
        continue;
      }
      if (r.tries >= kSnMaxRetries) {
        if (c.ack) {
          AckState& a = *c.ack;
          uint32_t bi = r.pid - kNativePidBase;
          if (BitTest(a.inflight, bi)) {
            BitClr(a.inflight, bi);
            a.inflight_cnt--;
            a.cyc_acked++;
            AckNote(id, a);
          }
        }
        stats_[kStDropsInflight].fetch_add(1, std::memory_order_relaxed);
        rx[i] = std::move(rx.back());
        rx.pop_back();
        continue;
      }
      r.dgram[r.flags_off] = static_cast<char>(
          static_cast<uint8_t>(r.dgram[r.flags_off]) | sn::kFDup);
      c.outbuf += r.dgram;
      MarkDirty(id, c);
      resent = true;
      r.last_tx_ms = now;
      r.tries++;
      uint64_t due = now + kSnRetryMs;
      if (!next_due || due < next_due) next_due = due;
      i++;
    }
    if (c.ack) DrainPending(id, c);  // abandoned slots pull the queue
    // DrainPending may have tracked a fresh delivery (SnRexmitTrack
    // arms the timer it found zeroed): never double-arm over it
    if (!rx.empty() && next_due && !c.sn->tm_rexmit)
      c.sn->tm_rexmit = wheel_.Arm(id, kTmSnRexmit, next_due);
    if (resent) FlushDirty();
  }

  // Trunk silent-link watchdog: the old per-cycle TrunkAckScan sweep,
  // now fired per peer from the wheel against the live ring front.
  void FireTrunkAck(uint64_t peer_id) {
    auto it = trunk_peers_.find(peer_id);
    if (it == trunk_peers_.end()) return;
    trunk::Peer& p = it->second;
    p.tm_ack = 0;
    if (!trunk_ack_timeout_ms_ || !p.up || !p.sock_tag ||
        p.unacked.empty())
      return;  // re-armed by the next flush/replay re-stamp
    uint64_t due = p.unacked.front().flush_ms + trunk_ack_timeout_ms_;
    uint64_t now = NowMs();
    if (now >= due) {
      TrunkSockDead(p.sock_tag, "ack_timeout");
      return;
    }
    p.tm_ack = wheel_.Arm(peer_id, kTmTrunkAck, due);
  }

  // Arm the watchdog when the ring front (re)gains its reference
  // stamp; a fire against a younger front simply re-arms.
  void TrunkAckWatch(uint64_t peer_id, trunk::Peer& p) {
    if (!trunk_ack_timeout_ms_ || p.tm_ack || p.unacked.empty()) return;
    p.tm_ack = wheel_.Arm(
        peer_id, kTmTrunkAck,
        p.unacked.front().flush_ms + trunk_ack_timeout_ms_);
  }

  void Read(uint64_t id, Conn& c) {
    uint8_t chunk[kReadChunk];
    c.last_rx_ms = NowMs();
    for (;;) {
      // @fault(conn_read) — errno/blackhole injection on the conn recv
      ssize_t n = FaultRecv(fault::kSiteConnRead, id, c.fd, chunk,
                            sizeof(chunk));
      if (n > 0) {
        bool ok;
        if (c.ws) {
          ok = WsIngest(id, c, chunk, static_cast<size_t>(n));
        } else {
          ok = IngestMqtt(id, c, chunk, static_cast<size_t>(n));
          if (!ok) Drop(id, "frame_error", true);
        }
        if (!ok) break;  // conn dropped (or closing); c is dead
        if (static_cast<size_t>(n) < sizeof(chunk)) break;
      } else if (n == 0) {
        Drop(id, "sock_closed", true);
        break;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR) continue;
        Drop(id, "sock_error", true);
        break;
      }
    }
    FlushDirty();
  }

  // Feed post-transport MQTT bytes into the frame scanner + fast path.
  // Returns false on a framing error (poisoned framer state). Does NOT
  // Drop: the WS path calls this from inside WsDecoder::Feed, and a
  // Drop there would destroy the decoder whose stack frame is still
  // live — callers drop AFTER the codec has unwound.
  bool IngestMqtt(uint64_t id, Conn& c, const uint8_t* data, size_t len) {
    std::vector<std::string> frames;
    FrameStatus st = c.framer.Feed(data, len, &frames);
    for (auto& f : frames) {
      // park-after clock: any frame but PINGREQ is WORK (keepalive
      // pings keep the conn alive without keeping it resident)
      if ((static_cast<uint8_t>(f[0]) >> 4) != 12)
        c.last_work_ms = c.last_rx_ms;
      if (!c.fast || !TryFast(id, c, f)) {
        // flight recorder: a frame bound for Python is a PUNT when the
        // conn was fast-eligible, a plain slow-plane FRAME otherwise
        FrNote(c, c.fast ? kFrPunt : kFrFrame,
               static_cast<uint8_t>(f[0]) >> 4,
               static_cast<uint16_t>(f.size() & 0xFFFF));
        events_.push_back(EncodeRecord(2, id, f.data(), f.size()));
      }
    }
    return st == FrameStatus::kOk;
  }

  // WS transport ingest: HTTP upgrade first, then the RFC6455 codec in
  // front of IngestMqtt (`data` is mutable: masked payloads unmask in
  // place). Returns false when the conn is gone.
  bool WsIngest(uint64_t id, Conn& c, uint8_t* data, size_t len) {
    WsConnState& w = *c.ws;
    if (!w.open) {
      w.hs_buf.append(reinterpret_cast<const char*>(data), len);
      size_t hdr_end = w.hs_buf.find("\r\n\r\n");
      if (hdr_end == std::string::npos) {
        if (w.hs_buf.size() > 16384) {  // runaway pre-upgrade request
          Drop(id, "ws_handshake_overflow", true);
          return false;
        }
        return true;
      }
      std::string key, path;
      bool mqtt_proto = false;
      bool ok = ws::ParseUpgradeRequest(
          std::string_view(w.hs_buf).substr(0, hdr_end + 4), &key, &path,
          &mqtt_proto);
      if (!ok || (!ws_path_.empty() && path != ws_path_)) {
        // same terminal answer as the asyncio oracle: 400, close. A
        // non-/mqtt target is NOT served here — deployments keep the
        // asyncio WS listener for any other endpoint.
        stats_[kStWsRejects].fetch_add(1, std::memory_order_relaxed);
        c.outbuf += ws::Build400();
        Flush(id, c);
        if (conns_.count(id)) Drop(id, "ws_handshake", true);
        return false;
      }
      c.outbuf += ws::BuildUpgradeResponse(ws::AcceptKey(key), mqtt_proto);
      MarkDirty(id, c);
      stats_[kStWsHandshakes].fetch_add(1, std::memory_order_relaxed);
      w.open = true;
      // a client may pipeline its first frames behind the request
      std::string leftover = w.hs_buf.substr(hdr_end + 4);
      w.hs_buf.clear();
      w.hs_buf.shrink_to_fit();
      if (leftover.empty()) return true;
      return WsDecode(id, c,
                      reinterpret_cast<uint8_t*>(&leftover[0]),
                      leftover.size());
    }
    return WsDecode(id, c, data, len);
  }

  // Sampled WS-ingest overhead: the decode+dispatch cost one read
  // chunk pays on the WS transport (the TCP path feeds IngestMqtt
  // directly, so this stage is what RFC6455 adds to the plane).
  bool WsDecode(uint64_t id, Conn& c, uint8_t* data, size_t len) {
    if (telemetry_ && ((++tele_tick_ws_ & tele_mask_) == 0)) {
      uint64_t t0 = NowNs();
      bool ok = WsDecodeInner(id, c, data, len);
      RecordHist(kHistWsIngest, NowNs() - t0);
      return ok;
    }
    return WsDecodeInner(id, c, data, len);
  }

  bool WsDecodeInner(uint64_t id, Conn& c, uint8_t* data, size_t len) {
    bool mqtt_err = false, closing = false;
    ws::WsStatus st = c.ws->dec.Feed(
        data, len,
        [&](const char* p, size_t n) {
          // data payload bytes ARE the MQTT byte stream (packets need
          // not align with WS frames — MQTT 5 §6.0); fragments
          // reassemble by arriving here in order. A framing error only
          // FLAGS here: the Drop must wait until Feed has unwound (it
          // would destroy the decoder running this very callback).
          if (n && !IngestMqtt(id, c,
                               reinterpret_cast<const uint8_t*>(p), n)) {
            mqtt_err = true;
            return false;
          }
          return true;
        },
        [&](uint8_t op, const char* p, size_t n) {
          if (op == ws::kOpPing) {  // pong echoes the ping payload
            ws::AppendFrameHeader(&c.outbuf, ws::kOpPong, n);
            c.outbuf.append(p, n);
            MarkDirty(id, c);
            stats_[kStWsPings].fetch_add(1, std::memory_order_relaxed);
            return true;
          }
          if (op == ws::kOpClose) {
            // echo the close (status code included) and tear down
            ws::AppendFrameHeader(&c.outbuf, ws::kOpClose, n);
            c.outbuf.append(p, n);
            stats_[kStWsCloses].fetch_add(1, std::memory_order_relaxed);
            closing = true;
            return false;
          }
          return true;  // pong: keepalive evidence only
        });
    if (mqtt_err) {  // decoder is off the stack now: safe to tear down
      Drop(id, "frame_error", true);
      return false;
    }
    if (closing || st != ws::WsStatus::kOk) {
      if (!closing) {
        // protocol error: best-effort close frame with the oracle's
        // codes (1002 protocol error / 1009 too big), then drop
        uint16_t code = st == ws::WsStatus::kCtrlTooBig ? 1009 : 1002;
        char body[2] = {static_cast<char>(code >> 8),
                        static_cast<char>(code & 0xFF)};
        ws::AppendFrameHeader(&c.outbuf, ws::kOpClose, 2);
        c.outbuf.append(body, 2);
      }
      Flush(id, c);  // may itself Drop on sock_error
      if (conns_.count(id))
        Drop(id, closing ? "ws_close" : "ws_error", true);
      return false;
    }
    return true;
  }

  // Flush every connection the fast path appended to during this read
  // batch — one send() per touched subscriber instead of one per
  // delivered message.
  void FlushDirty() {
    // durable batch FIRST: the qos1 publisher's PUBACK (and every
    // fast delivery of this read batch) reaches the wire only after
    // the matching store append — and its policy fsync — landed, so a
    // kill -9 can never ack a message the store lost
    FlushDurables();
    // the SAME discipline for trunk-routed qos1 (round 18): a dirty
    // peer batch holding elevated entries seals NOW — its replay
    // record journals into the store (TrunkPut + policy fsync) before
    // any socket write of this read batch, so the publisher's PUBACK
    // can never outrun the ring record a post-kill replay needs.
    // qos0-only batches keep the cheaper cycle-end seal (nothing to
    // replay, nothing a crash could lose that the contract covers).
    if (store_ && !trunk_dirty_.empty()) {
      for (uint64_t peer_id : trunk_dirty_) {
        auto it = trunk_peers_.find(peer_id);
        if (it != trunk_peers_.end() && it->second.q1_n) {
          FlushTrunks();
          break;
        }
      }
    }
    if (dirty_.empty()) {
      flush_t0_ = 0;  // sampled publish had no targets: no flush stage
      return;
    }
    std::vector<uint64_t> dirty;
    dirty.swap(dirty_);
    for (uint64_t id : dirty) {
      auto it = conns_.find(id);
      if (it == conns_.end()) continue;
      it->second.dirty = false;
      Flush(id, it->second);
      // a stalled SN outbuf (sendmmsg EAGAIN on the shared UDP fd) has
      // no per-conn EPOLLOUT to re-arm the way TCP's Flush does —
      // re-queue it so the next poll cycle retries, or a want_close
      // teardown would wait forever on unrelated traffic. Re-find: the
      // Flush may have Dropped the conn.
      auto rt = conns_.find(id);
      if (rt != conns_.end() && rt->second.sn &&
          rt->second.outpos < rt->second.outbuf.size())
        MarkDirty(id, rt->second);
    }
    if (flush_t0_) {
      RecordHist(kHistRouteFlush, NowNs() - flush_t0_);
      flush_t0_ = 0;
    }
  }

  // -- fast path ----------------------------------------------------------

  // Returns true when the frame was fully handled natively (consumed);
  // false forwards it to Python (the slow path), which is always safe.
  bool TryFast(uint64_t id, Conn& c, const std::string& f) {
    // per-frame trace context: an ack frame's DrainPending (and any
    // other delivery this frame triggers) must not inherit the LAST
    // publish's sampled id
    cur_trace_ = 0;
    uint8_t h = static_cast<uint8_t>(f[0]);
    uint8_t type = h >> 4;
    if (type == 4) return TryFastPuback(id, c, f);
    if (type == 5) return TryFastPubrec(id, c, f);
    if (type == 6) return TryFastPubrel(id, c, f);
    if (type == 7) return TryFastPubcomp(id, c, f);
    if (type != 3) return false;  // PUBLISH + the four ack types only
    // sampled ingress->route stamp (1-in-8): a NowNs per message would
    // be a measurable tax at 7 figures/s; the ticker is global so a
    // deterministic share of walk-path publishes lands in the histogram
    uint64_t t_in = 0;
    if (telemetry_ && ((++tele_tick_ & tele_mask_) == 0)) t_in = NowNs();
    uint8_t qos = (h >> 1) & 3;
    bool retain = h & 1;
    if (qos > 2 || retain) return false;  // malformed qos / retained
    if (qos > max_qos_allowed_) return false;  // over-cap publish must
    // reach the channel, which answers with DISCONNECT 0x9B
    // ([MQTT-3.2.2-11]) instead of a native ack
    // parse: [h][varint remaining][topic u16][pid? u16][props? varint][payload]
    size_t pos = 1;
    while (pos < f.size() && (static_cast<uint8_t>(f[pos]) & 0x80)) pos++;
    pos++;  // last varint byte (framer already validated the length)
    if (pos + 2 > f.size()) return false;
    uint16_t tlen = (static_cast<uint8_t>(f[pos]) << 8) |
                    static_cast<uint8_t>(f[pos + 1]);
    pos += 2;
    if (pos + tlen > f.size() || tlen == 0) return false;
    std::string_view topic(f.data() + pos, tlen);
    pos += tlen;
    if (topic[0] == '$') return false;  // $SYS / $delayed / ...: Python
    for (char ch : topic)
      if (ch == '+' || ch == '#' || ch == '\0') return false;  // invalid name
    uint16_t pid = 0;
    if (qos >= 1) {
      if (pos + 2 > f.size()) return false;
      pid = (static_cast<uint8_t>(f[pos]) << 8) |
            static_cast<uint8_t>(f[pos + 1]);
      pos += 2;
    }
    if (c.proto_ver == 5) {
      // fast path requires an empty property section: a topic alias,
      // message expiry or response topic needs the Python channel
      if (pos >= f.size() || f[pos] != 0) return false;
      pos++;
    }
    std::string_view payload(f.data() + pos, f.size() - pos);
    // one hash per publish, shared by every FrNote it triggers (the
    // per-delivery rehash was part of the telemetry tax)
    if (telemetry_) cur_hash_ = TopicHash(topic);
    if (qos == 2) {
      if (c.ack && BitTest(c.ack->awaiting_rel, pid)) {
        // retransmit of an exchange WE own (dup while awaiting PUBREL):
        // re-answer PUBREC, no second delivery [MQTT-4.3.3]. Checked
        // before the permit so a mid-exchange permit flush cannot hand
        // the id to Python for a double publish.
        char rec[4] = {0x50, 0x02, static_cast<char>(pid >> 8),
                       static_cast<char>(pid & 0xFF)};
        AppendMqtt(c, rec, 4);
        MarkDirty(id, c);
        return true;
      }
      if (h & 0x08) {
        // DUP retransmit of an exchange we do NOT own: the original
        // ran on the Python plane (e.g. it earned this very permit),
        // whose session holds the awaiting-rel state — fast-pathing it
        // as a fresh publish would deliver a second copy. Forward, and
        // the session re-answers PUBREC from its own dedup.
        return false;
      }
    }
    if (c.traced) {
      // TraceManager attached to this client: every publish must run
      // the Python plane so the hook fold (and the trace log) sees it.
      // Checked AFTER the awaiting-rel dedup above — a mid-exchange
      // trace must not hand an owned qos2 id to Python — and BEFORE
      // the permit, which may still be installed when the trace races
      // the permit flush.
      stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
      stats_[kStPuntsTrace].fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    key_scratch_.assign(topic.data(), topic.size());  // no per-msg alloc
    if (c.permits.find(key_scratch_) == c.permits.end())
      return false;  // unpermitted topic: full Python path (authz, rules)
    if (lane_enabled_ && qos == 2) {
      // qos2 never parks on the lane (its exchange state lives here);
      // with same-topic frames already parked, a walk delivery would
      // overtake them — poison the topic so the parked frames punt and
      // everything for it serializes through the Python FIFO
      auto tp = lane_topic_pending_.find(key_scratch_);
      if (tp != lane_topic_pending_.end()) {
        lane_poisoned_.insert(key_scratch_);
        c.permits.erase(key_scratch_);
        stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
        return false;
      }
      // no parked frames: fall through to the per-message walk below
    } else if (lane_enabled_) {
      // device lane: park the frame, ship the topic to the batched
      // device matcher. A topic with entries already in flight MUST
      // stay on the lane (a walk here would overtake them); new topics
      // spill to the walk once the lane is soft-capped.
      auto tp = lane_topic_pending_.find(key_scratch_);
      bool topic_in_flight = tp != lane_topic_pending_.end();
      if (topic_in_flight && tp->second >= kLaneTopicMax) {
        // distinct counter (NOT folded into drops_backpressure):
        // operators must be able to tell inbound per-topic lane
        // overload from subscriber delivery backpressure; Python logs
        // on every advance (native_server._merge_fast_metrics)
        stats_[kStLaneTopicOverflow].fetch_add(1,
                                               std::memory_order_relaxed);
        if (telemetry_)
          FrNote(c, kFrDrop, 3, qos, cur_hash_);
        return true;  // consumed: dropped under per-topic lane overload
      }
      if (!topic_in_flight && !punt_subs_.Empty()) {
        // known punt audience: the device verdict can only be "punt" —
        // skip the round trip and punt synchronously like the walk.
        // Topics with entries in flight stay on the lane (ordering).
        punt_scratch_.clear();
        punt_subs_.Match(topic, &punt_scratch_);
        bool must_punt = false;
        for (const SubEntry* pe : punt_scratch_) {
          // lane+trunk coexistence (round 12, carried edge): an
          // ELIGIBLE remote audience no longer forces the Python
          // path — the frame parks on the lane and LaneDeliver trunks
          // the remote leg next to the device-matched local fan-out.
          // Anything else in the punt trie (real punt markers, a down
          // trunk, qos2) still punts like before.
          if (!(pe->flags & kSubRemote)) {
            must_punt = true;
            break;
          }
          uint64_t peer = pe->owner - kTrunkOwnerBase;
          if (!TrunkEligible(peer, qos,
                             15 + topic.size() + payload.size())) {
            LedgerNote(kLrTrunkPunt, peer);
            must_punt = true;
            break;
          }
        }
        if (must_punt) {
          stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
          return false;
        }
      }
      if (topic_in_flight || lane_pending_.size() < kLaneSoftMax) {
        uint64_t seq = lane_seq_++;
        LaneEntry le;
        le.publisher = id;
        le.qos = qos;
        le.pid = pid;
        le.enq_ms = NowMs();
        le.topic_off = static_cast<uint32_t>(topic.data() - f.data());
        le.topic_len = static_cast<uint32_t>(topic.size());
        le.payload_off = static_cast<uint32_t>(pos);
        le.frame = f;
        stats_[kStLaneIn].fetch_add(1, std::memory_order_relaxed);
        if (telemetry_)  // arg2=1 marks a lane park, not a walk
          FrNote(c, kFrFastPub, 3, qos, cur_hash_, 1);
        events_.push_back(
            EncodeRecord(4, seq, topic.data(), topic.size()));
        LaneEnqueue(seq, std::move(le));
        return true;
      }
      stats_[kStLaneFallback].fetch_add(1, std::memory_order_relaxed);
      // fall through: the per-message walk serves this one
    }
    match_scratch_.clear();
    groups_scratch_.clear();
    subs_.Match(topic, &match_scratch_, &groups_scratch_);
    bool tapped = false;
    trunk_scratch_.clear();
    for (const SubEntry* e : match_scratch_) {
      if (e->flags & kSubPunt) {
        // a mixed/foreign shared group / persistent session /
        // non-native subscriber matched: Python must run the WHOLE
        // fan-out (it re-matches and delivers natively-served
        // subscribers too — and its hook fold runs the rules, so no
        // tap copy is emitted for punted frames)
        stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
        return false;
      }
      if (e->flags & kSubDurable) {
        // durable audience: FanOut persists the publish below the GIL
        // and the fast path proceeds. No attached store means Python
        // misconfigured the flip — degrade to a punt (always correct).
        if (!store_) {
          stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
          return false;
        }
        continue;
      }
      if (e->flags & kSubRuleTap) {
        tapped = true;
        continue;
      }
      if (e->flags & kSubRemote) {
        // remote entry (round 9): the peer's trunk carries this leg —
        // unless the trunk is down, the qos1 replay ring is full, or
        // the publish is qos2 (exactly-once spans two nodes' session
        // state), in which case the entry degrades to a punt marker
        // and Python's forward_fn lane carries the message. Decided
        // BEFORE any side effect: a partial native fan-out followed by
        // a punt would double-deliver the local audience. Non-trunk
        // shards consult their link-state mirror; the leg itself rides
        // the ring to shard 0 (TrunkEligible).
        uint64_t peer = e->owner - kTrunkOwnerBase;
        if (!TrunkEligible(peer, qos,
                           15 + topic.size() + payload.size())) {
          stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
          LedgerNote(kLrTrunkPunt, peer);
          return false;
        }
        PushUnique(&trunk_scratch_, peer);
        continue;
      }
    }
    if (!ShardAdmit()) {
      // a destination shard's ring cannot take this publish: the whole
      // fan-out degrades ring-full -> punt -> Python BEFORE any side
      // effect (the trunk-down ladder; ordering across the boundary is
      // best-effort, same as the trunk's)
      stats_[kStPunts].fetch_add(1, std::memory_order_relaxed);
      return false;
    }
    if (qos == 2) {
      AckState& a = EnsureAck(c);
      if (a.awaiting_cnt >= kMaxAwaitingRel)
        return false;  // table full: Python enforces the quota answer
      // record BEFORE the fan-out: the exchange is owned from the
      // moment we decide to deliver (a dup racing the fan-out must
      // dedup against it)
      BitSet(a.awaiting_rel, pid);
      a.awaiting_cnt++;
      AckNote(id, a);
      stats_[kStQos2In].fetch_add(1, std::memory_order_relaxed);
    } else if (qos == 1) {
      stats_[kStQos1In].fetch_add(1, std::memory_order_relaxed);
    }
    // the sampling commit point: every punt decision is behind us, so
    // the tick counts exactly the natively-consumed publishes
    TraceSample(id);
    if (tapped) EmitTap(id, qos, (h & 0x08) != 0, topic, payload);
    cur_dup_ = (h & 0x08) != 0;  // durable entries keep the DUP bit
    FanOut(id, qos, pid, topic, payload);
    if (cur_trace_) SpanNote(kSpanRoute, match_scratch_.size());
    // remote legs last: the local fan-out above and the trunk enqueue
    // below are the two halves of emqx_broker:publish's route loop.
    // Non-owner shards ship the leg to the peer's OWNER shard over the
    // ring (target = the trunk owner-namespace id, the scheme the conn
    // prefix reuses; round 15 spread the owners across shards).
    for (uint64_t peer : trunk_scratch_) {
      if (OwnsTrunkPeer(peer))
        TrunkEnqueue(peer, id, qos, (h & 0x08) != 0, topic, payload);
      else
        XShip(TrunkShardOf(peer), kTrunkOwnerBase + peer, id, qos,
              (h & 0x08) != 0, topic, payload);
    }
    if (telemetry_) {
      FrNote(c, kFrFastPub, 3, qos, cur_hash_);
      // cross-plane legs on the publisher's recorder (round 13): the
      // FR used to go blind once a publish left its shard
      if (fan_xshipped_)
        FrNote(c, kFrRingCross, 3,
               static_cast<uint16_t>(fan_xshipped_), cur_hash_);
      if (!trunk_scratch_.empty())
        FrNote(c, kFrTrunk, 3,
               static_cast<uint16_t>(trunk_scratch_[0] & 0xFFFF),
               cur_hash_);
      if (t_in) {
        uint64_t t1 = NowNs();
        RecordHist(kHistIngressRoute, t1 - t_in);
        // the same sampled message anchors the route->flush stage;
        // FlushDirty closes it when this read batch hits the socket
        if (!flush_t0_) flush_t0_ = t1;
      }
    }
    return true;
  }

  // Hand a natively-served publish to the rule runtime (kSubRuleTap
  // matched): delivery already happened in C++; Python only evaluates
  // the rules against it, asynchronously. Entries BATCH into one event
  // record per poll cycle — a per-message record made Python's event
  // decode the data-plane bottleneck (measured: 1.7M -> 0.3M msg/s
  // under a FROM '#' rule). Round 7 copy elision (the remaining
  // rule-tap tax, BENCH_r05 rule_tap_vs_free=0.59): entries carry the
  // PRE-PARSED fields ([u64 publisher][u8 flags][u16 tlen][topic]
  // [u32 plen][payload]) instead of whole-frame copies, so the Python
  // worker never re-parses MQTT while the blast is live, and a payload
  // identical to the previous entry's is elided (flags bit0 = 0) — the
  // shared delivery frames were already built once per publish; the tap
  // plane now follows the same discipline. flags: bit0 = payload
  // inline, bits1-2 = qos, bit3 = publisher DUP.
  // @admit-gated — a tap copy is a side effect of an ADMITTED publish
  // @bounded(tap_buf_)
  void EmitTap(uint64_t publisher, uint8_t qos, bool dup_flag,
               std::string_view topic, std::string_view payload) {
    stats_[kStTaps].fetch_add(1, std::memory_order_relaxed);
    // flush BEFORE an append that would overflow the cap: the Python
    // poll buffer is max_size_+65600, and Poll silently drops any record
    // larger than the caller's whole buffer — a lost batch would be
    // hundreds of rule messages with no accounting. With this
    // discipline a record never exceeds max(cap, one max-size entry)
    // + 13, which always fits (framer bounds frames at max_size_).
    size_t cap = kTapFlushBytes;
    if (cap > max_size_ / 2) cap = max_size_ / 2 + 1;
    size_t entry_max = 15 + topic.size() + payload.size();
    if (tap_buf_.size() > 13 && tap_buf_.size() - 13 + entry_max > cap)
      FlushTaps();
    // header slot AFTER the flush check: a mid-batch flush empties the
    // buffer, and appending into it headerless would let FlushTaps
    // stamp the record header over the first entry (corrupt batch)
    if (tap_buf_.empty()) tap_buf_.assign(13, '\0');
    bool dup = tap_have_prev_ && payload == tap_prev_payload_;
    char hdr[11];
    memcpy(hdr, &publisher, 8);
    hdr[8] = static_cast<char>((dup ? 0 : 1) | (qos << 1)
                               | (dup_flag ? 8 : 0));
    uint16_t tl = static_cast<uint16_t>(topic.size());
    memcpy(hdr + 9, &tl, 2);
    tap_buf_.append(hdr, 11);
    tap_buf_.append(topic.data(), topic.size());
    if (!dup) {
      uint32_t pl = static_cast<uint32_t>(payload.size());
      tap_buf_.append(reinterpret_cast<const char*>(&pl), 4);
      tap_buf_.append(payload.data(), payload.size());
      tap_prev_payload_.assign(payload.data(), payload.size());
      tap_have_prev_ = true;
    }
    if (tap_buf_.size() - 13 > cap) FlushTaps();
  }

  void FlushTaps() {
    if (tap_buf_.size() <= 13) return;
    // patch the record header in place and MOVE the buffer out: the
    // batch is copied once (into the poll buffer), not re-copied
    // through EncodeRecord first
    tap_buf_[0] = 6;
    uint64_t id = 0;
    memcpy(&tap_buf_[1], &id, 8);
    uint32_t plen = static_cast<uint32_t>(tap_buf_.size() - 13);
    memcpy(&tap_buf_[9], &plen, 4);
    events_.push_back(std::move(tap_buf_));
    tap_buf_.clear();
    tap_have_prev_ = false;  // dedup never crosses a record boundary
  }

  AckState& EnsureAck(Conn& c) {
    if (!c.ack) c.ack = std::make_unique<AckState>();
    return *c.ack;
  }

  // Queue the conn for this cycle's batched ack record.
  void AckNote(uint64_t id, AckState& a) {
    if (!a.cyc_dirty) {
      a.cyc_dirty = true;
      ack_dirty_.push_back(id);
    }
  }

  // Write one PUBLISH to `owner` (qos = min(pub, sub)); returns whether
  // a delivery (or an elevated-qos queue admit) happened.
  bool DeliverTo(uint64_t owner, const SubEntry& e, uint64_t publisher,
                 uint8_t qos, std::string_view topic,
                 std::string_view payload) {
    // a delivery to a hibernating subscriber re-inflates it first
    auto it = FindConnInflate(owner);
    if (it == conns_.end()) return false;  // stale entry (conn mid-close)
    Conn& t = it->second;
    if (t.outbuf.size() - t.outpos > kHighWater) {
      stats_[kStDropsBackpressure].fetch_add(1, std::memory_order_relaxed);
      LedgerNote(kLrShed, owner);
      if (telemetry_) FrNote(t, kFrDrop, 3, 0, cur_hash_);
      return false;
    }
    uint8_t out_qos = qos < e.qos ? qos : e.qos;
    if (t.coap) {
      // observe notifies cap at qos1 (CON) and at the CoAP frame
      // limit; the oversize decision lands BEFORE any window slot is
      // allocated (the SN discipline — a slot with no deliverable
      // bytes would leak until conn death)
      if (out_qos > 1) out_qos = 1;
      if (payload.size() > coap::kMaxPayload) {
        stats_[kStCoapDropsOversize].fetch_add(1,
                                               std::memory_order_relaxed);
        return false;
      }
    }
    if (t.sn) {
      // SN subscribers take SN framing but the SAME window machinery;
      // deliveries cap at qos1 (the oracle's handle_deliver cap)
      if (out_qos == 0) {
        if (telemetry_) FrNote(t, kFrDeliver, 3, 0, cur_hash_);
        SnDeliverPublish(t, topic, payload, 0, false, false, 0);
      } else {
        int r = SnDeliverElevated(owner, t, topic, payload, false);
        if (r == 0) return false;
        if (r == 2) return true;  // parked; kStFastOut counts at dequeue
      }
      TraceDeliverNote(owner);
      stats_[kStFastOut].fetch_add(1, std::memory_order_relaxed);
      MarkDirty(owner, t);
      return true;
    }
    if (out_qos == 0) {
      std::string& shared = t.proto_ver == 5 ? frame_v5_ : frame_v4_;
      if (shared.empty())
        BuildPublish(&shared, topic, payload, 0, 0, t.proto_ver == 5);
      AppendMqtt(t, shared.data(), shared.size());
      stats_[kStFastBytesOut].fetch_add(shared.size(),
                                        std::memory_order_relaxed);
      if (telemetry_) FrNote(t, kFrDeliver, 3, 0, cur_hash_);
      TraceDeliverNote(owner);
    } else {
      AckState& a = EnsureAck(t);
      std::string& sq = t.proto_ver == 5 ? frame_q_v5_ : frame_q_v4_;
      size_t& qoff = t.proto_ver == 5 ? qpid_off_v5_ : qpid_off_v4_;
      if (sq.empty()) {
        // built once per publish: qos1 header, zero pid; per-target
        // the header qos bits and pid bytes are patched in place.
        // pid offset = header(1) + varint + topic len field(2) + topic
        BuildPublish(&sq, topic, payload, 1, 0, t.proto_ver == 5);
        size_t var_len = 1;
        while (static_cast<uint8_t>(sq[var_len]) & 0x80) var_len++;
        qoff = var_len + 1 + 2 + topic.size();
      }
      if (a.inflight_cnt >= t.max_inflight) {
        // receive window full: queue (the mqueue), drop on overflow
        if (a.pending.size() >= kMaxPending) {
          stats_[kStDropsInflight].fetch_add(1, std::memory_order_relaxed);
          if (telemetry_) FrNote(t, kFrDrop, 3, 1, cur_hash_);
          return false;
        }
        a.pending.emplace_back(sq, qoff);
        a.pending.back().first[0] =
            static_cast<char>(0x30 | (out_qos << 1));
        AckNote(owner, a);
        return true;   // admitted; kStFastOut counts at dequeue
      }
      uint16_t tp = NextPid(a);
      if (out_qos == 2) BitSet(a.infl_qos2, tp - kNativePidBase);
      if (telemetry_) {
        // ack-RTT sample (delivery write -> PUBACK/PUBCOMP): stamped
        // only while a slot is free, closed out in TeleAckRtt — it
        // also carries the active trace id so the ack span can close
        // the sampled publish's timeline
        if (a.rtt.size() < kRttSamples)
          a.rtt.push_back({NowNs(), std::string(topic), tp, out_qos,
                           cur_trace_});
        FrNote(t, kFrDeliver, 3, tp, cur_hash_);
        TraceDeliverNote(owner);
      }
      if (t.coap) {
        // CoAP conns cannot take raw MQTT bytes in the outbuf: patch
        // the shared frame in a scratch and run the egress translation
        // (-> a tracked CON notify carrying this pid)
        coap_pub_scratch_.assign(sq);
        coap_pub_scratch_[0] = static_cast<char>(0x30 | (out_qos << 1));
        coap_pub_scratch_[qoff] = static_cast<char>(tp >> 8);
        coap_pub_scratch_[qoff + 1] = static_cast<char>(tp & 0xFF);
        AppendMqtt(t, coap_pub_scratch_.data(), coap_pub_scratch_.size());
      } else {
        if (t.ws)  // frame header first so `at` lands on the MQTT bytes
          ws::AppendFrameHeader(&t.outbuf, ws::kOpBinary, sq.size());
        size_t at = t.outbuf.size();
        t.outbuf += sq;
        t.outbuf[at] = static_cast<char>(0x30 | (out_qos << 1));
        t.outbuf[at + qoff] = static_cast<char>(tp >> 8);
        t.outbuf[at + qoff + 1] = static_cast<char>(tp & 0xFF);
      }
      stats_[kStFastBytesOut].fetch_add(sq.size(),
                                        std::memory_order_relaxed);
      AckNote(owner, a);
    }
    stats_[kStFastOut].fetch_add(1, std::memory_order_relaxed);
    MarkDirty(owner, t);
    return true;
  }

  // [h][varint][pid u16][...]: the shared pid parse for the four ack
  // packet types (the framer already validated the length varint)
  static bool ParsePid(const std::string& f, uint16_t* pid) {
    size_t pos = 1;
    while (pos < f.size() && (static_cast<uint8_t>(f[pos]) & 0x80)) pos++;
    pos++;
    if (pos + 2 > f.size()) return false;
    *pid = (static_cast<uint8_t>(f[pos]) << 8) |
           static_cast<uint8_t>(f[pos + 1]);
    return true;
  }

  // Freed window slots pull queued deliveries in (mqueue dequeue).
  // SN conns park whole SN datagrams (always qos1): the dequeue
  // patches the msg-id field and registers the retransmit copy.
  void DrainPending(uint64_t id, Conn& c) {
    if (!c.ack) return;
    AckState& a = *c.ack;
    while (!a.pending.empty() && a.inflight_cnt < c.max_inflight) {
      auto [frame, pid_off] = std::move(a.pending.front());
      a.pending.pop_front();
      uint16_t np = NextPid(a);
      if (!c.sn && ((static_cast<uint8_t>(frame[0]) >> 1) & 3) == 2)
        BitSet(a.infl_qos2, np - kNativePidBase);
      frame[pid_off] = static_cast<char>(np >> 8);
      frame[pid_off + 1] = static_cast<char>(np & 0xFF);
      stats_[kStFastOut].fetch_add(1, std::memory_order_relaxed);
      stats_[kStFastBytesOut].fetch_add(frame.size(),
                                        std::memory_order_relaxed);
      if (c.sn) {
        stats_[kStSnOut].fetch_add(1, std::memory_order_relaxed);
        SnOut(c, frame);
        // msg-id offset sits 3 bytes past the flags byte (sn.h layout)
        SnRexmitTrack(id, c, np, std::move(frame), pid_off - 3);
      } else {
        AppendMqtt(c, frame.data(), frame.size());
      }
      AckNote(id, a);
      MarkDirty(id, c);
    }
  }

  bool TryFastPuback(uint64_t id, Conn& c, const std::string& f) {
    // pids >= kNativePidBase belong to the native inflight set; lower
    // pids are the Python session's and are forwarded
    uint16_t pid;
    if (!ParsePid(f, &pid) || pid < kNativePidBase) return false;
    if (c.ack) {
      AckState& a = *c.ack;
      uint32_t i = pid - kNativePidBase;
      if (BitTest(a.inflight, i)) {
        BitClr(a.inflight, i);
        a.inflight_cnt--;
        a.cyc_acked++;
        AckNote(id, a);
        stats_[kStNativeAcks].fetch_add(1, std::memory_order_relaxed);
        if (!a.rtt.empty()) TeleAckRtt(id, a, pid);
        FrNote(c, kFrAck, 4, pid);
        DrainPending(id, c);
      }
    }
    return true;  // native pid space: consumed even when already freed
  }

  // Subscriber answered a native qos2 delivery with PUBREC: answer
  // PUBREL (emqx_session.erl:466-476); the inflight bit stays held
  // until PUBCOMP — the exactly-once hold-across IS the slot hold.
  bool TryFastPubrec(uint64_t id, Conn& c, const std::string& f) {
    uint16_t pid;
    if (!ParsePid(f, &pid) || pid < kNativePidBase) return false;
    // phase advance for the demotion handoff: PUBREL is on the wire,
    // the exchange now awaits PUBCOMP
    if (c.ack && BitTest(c.ack->inflight, pid - kNativePidBase))
      BitSet(c.ack->infl_rel, pid - kNativePidBase);
    // answer PUBREL even for an already-freed pid (a retransmitted
    // PUBREC must still complete the client's flow); Python can never
    // own a pid in this space, so consuming is always safe
    char rel[4] = {0x62, 0x02, static_cast<char>(pid >> 8),
                   static_cast<char>(pid & 0xFF)};
    AppendMqtt(c, rel, 4);
    MarkDirty(id, c);
    return true;
  }

  // Subscriber completed a native qos2 delivery: free the slot.
  bool TryFastPubcomp(uint64_t id, Conn& c, const std::string& f) {
    uint16_t pid;
    if (!ParsePid(f, &pid) || pid < kNativePidBase) return false;
    if (c.ack) {
      AckState& a = *c.ack;
      uint32_t i = pid - kNativePidBase;
      if (BitTest(a.inflight, i)) {
        BitClr(a.inflight, i);
        a.inflight_cnt--;
        a.cyc_acked++;
        AckNote(id, a);
        stats_[kStNativeAcks].fetch_add(1, std::memory_order_relaxed);
        if (!a.rtt.empty()) TeleAckRtt(id, a, pid);
        FrNote(c, kFrAck, 7, pid);
        DrainPending(id, c);
      }
    }
    return true;
  }

  // Publisher released a qos2 exchange the native plane owns (its pid
  // sits in OUR awaiting-rel set): complete with PUBCOMP. Ids we do
  // not own forward to the Python session, which owns their state.
  bool TryFastPubrel(uint64_t id, Conn& c, const std::string& f) {
    uint16_t pid;
    if (!ParsePid(f, &pid)) return false;
    if (!c.ack || !BitTest(c.ack->awaiting_rel, pid)) return false;
    AckState& a = *c.ack;
    BitClr(a.awaiting_rel, pid);
    a.awaiting_cnt--;
    a.cyc_rel++;
    AckNote(id, a);
    stats_[kStQos2Rel].fetch_add(1, std::memory_order_relaxed);
    char comp[4] = {0x70, 0x02, static_cast<char>(pid >> 8),
                    static_cast<char>(pid & 0xFF)};
    AppendMqtt(c, comp, 4);
    MarkDirty(id, c);
    return true;
  }

  uint16_t NextPid(AckState& a) {
    // [kNativePidBase, 0xFFFF], skipping ids still in flight
    for (int guard = 0; guard < 0x8000; guard++) {
      uint16_t p = a.next_pid;
      a.next_pid = p == 0xFFFF ? kNativePidBase : p + 1;
      uint32_t i = p - kNativePidBase;
      if (!BitTest(a.inflight, i)) {
        BitSet(a.inflight, i);
        // fresh slot: stale phase bits from a previous tenant would
        // corrupt a later demotion handoff
        BitClr(a.infl_qos2, i);
        BitClr(a.infl_rel, i);
        a.inflight_cnt++;
        return p;
      }
    }
    return kNativePidBase;  // unreachable: inflight capped below 0x8000
  }

  // Batched ack records per poll cycle (the EmitTap/FlushTaps
  // discipline applied to the ack plane): Python's per-message PUBACK
  // bookkeeping becomes one decode per cycle. Chunked at the tap
  // bound: Poll permanently drops any record larger than the caller's
  // whole buffer, and the per-cycle counters are reset here BEFORE
  // emission — an unbounded record would silently lose every conn's
  // ack deltas each cycle once enough conns are window-active.
  void FlushAcks() {
    if (ack_dirty_.empty()) return;
    size_t cap = kTapFlushBytes;
    if (cap > max_size_ / 2) cap = max_size_ / 2 + 1;
    ack_buf_.clear();
    uint32_t n = 0;
    char ent[24];
    auto emit = [&]() {
      if (!n) return;
      std::string payload(reinterpret_cast<char*>(&n), 4);
      payload += ack_buf_;
      // the record id slot carries the shard (round 12): concurrent
      // poll threads feed one Python reconciler, which must attribute
      // each ack batch to the producing shard's host
      events_.push_back(EncodeRecord(7, static_cast<uint64_t>(shard_id_),
                                     payload.data(), payload.size()));
      stats_[kStAckBatches].fetch_add(1, std::memory_order_relaxed);
      ack_buf_.clear();
      n = 0;
    };
    for (uint64_t id : ack_dirty_) {
      auto it = conns_.find(id);
      if (it == conns_.end() || !it->second.ack) continue;
      AckState& a = *it->second.ack;
      a.cyc_dirty = false;
      memcpy(ent, &id, 8);
      uint32_t v = a.cyc_acked;
      memcpy(ent + 8, &v, 4);
      v = a.cyc_rel;
      memcpy(ent + 12, &v, 4);
      v = a.inflight_cnt;
      memcpy(ent + 16, &v, 4);
      v = static_cast<uint32_t>(a.pending.size());
      memcpy(ent + 20, &v, 4);
      a.cyc_acked = a.cyc_rel = 0;
      if (4 + ack_buf_.size() + 24 > cap) emit();
      ack_buf_.append(ent, 24);
      n++;
    }
    ack_dirty_.clear();
    emit();
  }

  // -- durable-session plane (round 10) -----------------------------------
  // A publish whose match set contains kSubDurable entries is appended
  // to the per-flush batch here (pre-parsed layout, payload deduped vs
  // the previous entry — the kind-6 discipline); FlushDurables writes
  // the batch into the store (store.h) and ships the SAME bytes to
  // Python as one kind-10 event for marker reconciliation + live
  // delivery to the connected persistent session.

  // A single entry's record must ALWAYS fit the Python poll buffer
  // (max_size + 65600 — native/__init__.py), or Poll drops it whole
  // and connected persistent sessions silently miss the live delivery
  // while keeping their markers (a ghost replay on next resume). The
  // worst case is 33 header bytes + 17 entry bytes + 8*ntok + the
  // frame's topic+payload (< max_size), so capping tokens per entry at
  // 4096 (32 KB) guarantees the fit; a wider audience splits into
  // several entries sharing the deduped payload.
  static constexpr size_t kDurMaxToksPerEntry = 4096;

  void DurableAppend(uint64_t publisher, uint8_t qos,
                     std::string_view topic, std::string_view payload) {
    stats_[kStDurableIn].fetch_add(1, std::memory_order_relaxed);
    if (cur_trace_) SpanNote(kSpanStoreAppend, dur_tok_scratch_.size());
    // the publisher's clientid persists with the entry (flags bit5):
    // no-local and from_ attribution must survive a restart, and the
    // origin conn id is meaningless in the next life
    const std::string* cid = nullptr;
    auto cit = conn_cids_.find(publisher);
    if (cit != conn_cids_.end() && !cit->second.empty())
      cid = &cit->second;
    for (size_t g = 0; g < dur_tok_scratch_.size();
         g += kDurMaxToksPerEntry)
      DurableAppendEntry(
          publisher, qos, topic, payload, cid, g,
          std::min(dur_tok_scratch_.size(), g + kDurMaxToksPerEntry));
  }

  // @bounded(dur_buf_)
  void DurableAppendEntry(uint64_t publisher, uint8_t qos,
                          std::string_view topic, std::string_view payload,
                          const std::string* cid,
                          size_t tok_begin, size_t tok_end) {
    size_t cap = TeleCap();
    size_t ntok = tok_end - tok_begin;
    size_t entry_max = 19 + 8 * ntok + 2 + topic.size() + 4
                       + payload.size()
                       + (cid ? 1 + cid->size() : 0);
    // 33 = 13-byte event-record header slot + 20-byte batch header
    // ([base_guid][ts][n]); both patched at flush (EmitTap's
    // seed-after-flush lesson: never append headerless post-flush)
    if (dur_buf_.size() > 33 && dur_buf_.size() - 33 + entry_max > cap)
      FlushDurables();
    if (dur_buf_.empty()) dur_buf_.assign(33, '\0');
    bool dup_pl = dur_have_prev_ && payload == dur_prev_payload_;
    char hdr[11];
    memcpy(hdr, &publisher, 8);
    hdr[8] = static_cast<char>((dup_pl ? 0 : 1) | (qos << 1)
                               | (cur_dup_ ? 8 : 0)
                               | (cur_trace_ ? 0x10 : 0)
                               | (cid ? 0x20 : 0));
    uint16_t nt = static_cast<uint16_t>(ntok);
    memcpy(hdr + 9, &nt, 2);
    dur_buf_.append(hdr, 11);
    for (size_t k = tok_begin; k < tok_end; k++) {
      uint64_t tok = dur_tok_scratch_[k];
      dur_buf_.append(reinterpret_cast<const char*>(&tok), 8);
    }
    uint16_t tl = static_cast<uint16_t>(topic.size());
    dur_buf_.append(reinterpret_cast<const char*>(&tl), 2);
    dur_buf_.append(topic.data(), topic.size());
    // flags bit4 (round 13): the sampled trace id persists with the
    // message so a resume replay can re-join its timeline
    if (cur_trace_)
      dur_buf_.append(reinterpret_cast<const char*>(&cur_trace_), 8);
    // flags bit5 (round 18): the publisher's clientid (<= 255 bytes —
    // kEnableFast refuses longer ones at the bind)
    if (cid) {
      dur_buf_.push_back(static_cast<char>(cid->size()));
      dur_buf_.append(*cid);
    }
    if (!dup_pl) {
      uint32_t pl = static_cast<uint32_t>(payload.size());
      dur_buf_.append(reinterpret_cast<const char*>(&pl), 4);
      dur_buf_.append(payload.data(), payload.size());
      dur_prev_payload_.assign(payload.data(), payload.size());
      dur_have_prev_ = true;
    }
    dur_n_++;
    if (dur_buf_.size() - 33 > cap) FlushDurables();
  }

  void FlushDurables() {
    if (dur_buf_.size() <= 33 || !store_) {
      dur_buf_.clear();
      dur_n_ = 0;
      dur_have_prev_ = false;
      return;
    }
    uint64_t base = store_->AllocGuids(dur_n_);
    uint64_t ts = store::WallMs();
    memcpy(&dur_buf_[13], &base, 8);
    memcpy(&dur_buf_[21], &ts, 8);
    memcpy(&dur_buf_[29], &dur_n_, 4);
    uint64_t t0 = telemetry_ ? NowNs() : 0;
    store_->AppendBatch(dur_buf_.data() + 13, dur_buf_.size() - 13);
    if (telemetry_) RecordHist(kHistStoreAppend, NowNs() - t0);
    stats_[kStStoreAppends].fetch_add(dur_n_, std::memory_order_relaxed);
    stats_[kStDurableBatches].fetch_add(1, std::memory_order_relaxed);
    dur_buf_[0] = 10;
    // id slot = shard (round 12): durable consume folds kind-10
    // batches from every shard; guids stay globally unique (the store
    // is shared, AllocGuids is atomic) but attribution is per-shard
    uint64_t id = static_cast<uint64_t>(shard_id_);
    memcpy(&dur_buf_[1], &id, 8);
    uint32_t plen = static_cast<uint32_t>(dur_buf_.size() - 13);
    memcpy(&dur_buf_[9], &plen, 4);
    events_.push_back(std::move(dur_buf_));
    dur_buf_.clear();
    dur_n_ = 0;
    dur_have_prev_ = false;
  }

  // Live plane demotion (kDisableFast): serialize the AckState into
  // kind-11 records the Python session adopts — awaiting-rel ids (the
  // publisher-side qos2 exactly-once set), the inflight window with
  // per-delivery qos/phase, and the window-full pending frames.
  // Chunked at the tap bound; fields are additive across chunks. At
  // least one sub-1 record always goes out so Python sees the flip.
  void EmitHandoff(uint64_t id, Conn& c) {
    stats_[kStHandoffs].fetch_add(1, std::memory_order_relaxed);
    size_t cap = TeleCap();
    std::vector<uint16_t> aw, ifp;
    std::vector<uint8_t> ifs;
    if (c.ack) {
      AckState& a = *c.ack;
      if (a.awaiting_cnt)
        for (uint32_t w = 0; w < 1024; w++) {
          uint64_t bits = a.awaiting_rel[w];
          while (bits) {
            uint32_t b = static_cast<uint32_t>(__builtin_ctzll(bits));
            bits &= bits - 1;
            aw.push_back(static_cast<uint16_t>(w * 64 + b));
          }
        }
      if (a.inflight_cnt)
        for (uint32_t w = 0; w < 512; w++) {
          uint64_t bits = a.inflight[w];
          while (bits) {
            uint32_t b = static_cast<uint32_t>(__builtin_ctzll(bits));
            bits &= bits - 1;
            uint32_t i = w * 64 + b;
            ifp.push_back(static_cast<uint16_t>(kNativePidBase + i));
            ifs.push_back(static_cast<uint8_t>(
                (BitTest(a.infl_qos2, i) ? 1 : 0)
                | (BitTest(a.infl_rel, i) ? 2 : 0)));
          }
        }
    }
    size_t ai = 0, ii = 0;
    bool first = true;
    while (first || ai < aw.size() || ii < ifp.size()) {
      first = false;
      std::string rec;
      rec.push_back(1);
      size_t aw_at = rec.size();
      rec.append(4, '\0');
      uint32_t na = 0;
      while (ai < aw.size() && rec.size() + 2 + 4 < cap) {
        uint16_t pid = aw[ai++];
        rec.append(reinterpret_cast<const char*>(&pid), 2);
        na++;
      }
      memcpy(&rec[aw_at], &na, 4);
      size_t if_at = rec.size();
      rec.append(4, '\0');
      uint32_t ni = 0;
      while (ii < ifp.size() && rec.size() + 3 < cap) {
        uint16_t pid = ifp[ii];
        rec.append(reinterpret_cast<const char*>(&pid), 2);
        rec.push_back(static_cast<char>(ifs[ii]));
        ii++;
        ni++;
      }
      memcpy(&rec[if_at], &ni, 4);
      events_.push_back(EncodeRecord(11, id, rec.data(), rec.size()));
    }
    if (c.ack && !c.ack->pending.empty()) {
      std::string rec;
      uint32_t n = 0;
      auto open = [&]() {
        rec.clear();
        rec.push_back(2);
        rec.append(4, '\0');
        n = 0;
      };
      auto emit = [&]() {
        if (!n) return;
        memcpy(&rec[1], &n, 4);
        events_.push_back(EncodeRecord(11, id, rec.data(), rec.size()));
      };
      open();
      for (auto& [frame, off] : c.ack->pending) {
        (void)off;
        if (n && rec.size() + 4 + frame.size() > cap) {
          emit();
          open();
        }
        uint32_t fl = static_cast<uint32_t>(frame.size());
        rec.append(reinterpret_cast<const char*>(&fl), 4);
        rec += frame;
        n++;
      }
      emit();
    }
  }

  // -- cluster trunk (round 9) --------------------------------------------
  // Cross-node publish forwarding on the C++ plane: per-peer batch
  // buffers flushed as length-prefixed trunk records (trunk.h) straight
  // into the peer host's decoder → local fan-out. All state below is
  // poll-thread-owned; control arrives via ops (kTrunk*).

  void TrunkAccept() {
    for (;;) {
      sockaddr_in peer{};
      socklen_t plen = sizeof(peer);
      int fd = accept4(listen_trunk_fd_, reinterpret_cast<sockaddr*>(&peer),
                       &plen, SOCK_NONBLOCK | SOCK_CLOEXEC);
      if (fd < 0) return;
      // @fault(trunk_accept) — the peer's dial lands on an RST and its
      // redial backoff machinery takes over
      if (FaultHit(fault::kSiteTrunkAccept, 0)) {
        close(fd);
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      uint64_t tag = kTrunkSockBit | next_trunk_tag_++;
      trunk::Sock s;
      s.fd = fd;
      s.dialer = false;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.u64 = tag;
      epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
      trunk_socks_.emplace(tag, std::move(s));
    }
  }

  void TrunkDial(uint64_t peer_id, trunk::Peer& p) {
    if (p.sock_tag) {
      auto sit = trunk_socks_.find(p.sock_tag);
      if (sit != trunk_socks_.end()
          && (sit->second.connecting || p.hello_pending))
        return;  // a dial (or the HELLO grace) is already in flight —
      //           killing it on every retry tick would livelock any
      //           connect slower than the redial cadence (the kernel's
      //           own connect timeout eventually fails it and emits
      //           DOWN; the HELLO grace is deadline-bounded)
      TrunkSockDead(p.sock_tag, "redial");  // replace established link
    }
    int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
    if (fd < 0) {
      TrunkEmitDown(peer_id, "socket");
      return;
    }
    // @fault(trunk_connect) — the dial fails before it starts; Python
    // sees DOWN and drives the (jittered) redial backoff
    if (FaultHit(fault::kSiteTrunkConnect, peer_id)) {
      close(fd);
      TrunkEmitDown(peer_id, "fault_connect");
      return;
    }
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(p.port);
    if (inet_pton(AF_INET, p.addr.c_str(), &addr.sin_addr) != 1) {
      close(fd);
      TrunkEmitDown(peer_id, "bad_addr");
      return;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    if (rc < 0 && errno != EINPROGRESS) {
      close(fd);
      TrunkEmitDown(peer_id, "connect");
      return;
    }
    uint64_t tag = kTrunkSockBit | next_trunk_tag_++;
    trunk::Sock s;
    s.fd = fd;
    s.dialer = true;
    s.peer_id = peer_id;
    s.connecting = rc < 0;
    epoll_event ev{};
    ev.events = s.connecting ? (EPOLLIN | EPOLLOUT) : EPOLLIN;
    ev.data.u64 = tag;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
    p.sock_tag = tag;
    trunk_socks_.emplace(tag, std::move(s));
    if (rc == 0) TrunkUp(peer_id, p);
  }

  // Link established: HELLO first (round 13 — advertise our wire
  // version before any batch), then WAIT for the answer (or the grace
  // deadline, for old peers that ignore unknown record types) before
  // completing the link: the qos1 replay must go out at the link's
  // NEGOTIATED version, or a shadow carrying trace annotations would
  // always downshift to v0 (the round-13 carried edge) — and a v1
  // shadow must never hit a v0 peer's decoder. TrunkCompleteUp then
  // replays BEFORE any new traffic (p.up stays false through the
  // grace, so remote entries punt conservatively — the link-down
  // ladder, bounded by kTrunkHelloGraceMs) and tells Python (kind 9
  // sub 1) so it can flush permits — the ordering guard for the
  // punt→trunk flip, same reasoning as the slow→fast permit grant.
  void TrunkUp(uint64_t peer_id, trunk::Peer& p) {
    auto sit = trunk_socks_.find(p.sock_tag);
    if (sit == trunk_socks_.end()) return;
    if (trunk_wire_max_ >= 1) {
      char hv = static_cast<char>(trunk_wire_max_);
      trunk::AppendRecord(&sit->second.outbuf, trunk::kRecHello, &hv, 1);
      p.hello_pending = true;
      p.hello_deadline_ms = NowMs() + kTrunkHelloGraceMs;
      trunk_hello_pending_++;
      TrunkFlushSock(p.sock_tag, sit->second);
      return;  // TrunkCompleteUp runs on the answer or the deadline
    }
    TrunkCompleteUp(peer_id, p);
  }

  // -- store-backed trunk ring (round 18) ---------------------------------
  // The per-peer unacked qos1 ring journals into the durable store
  // (kRecTrunk / kRecTrunkAck, keyed by peer NODE NAME): kill -9 of a
  // node no longer loses the ring — the reconnect replay draws from
  // recovered segments and the exact-match ack machinery retires store
  // records alongside memory slots.

  const std::string& TrunkStoreName(uint64_t peer_id, trunk::Peer& p) {
    if (p.store_name.empty()) {
      // raw/single-process fallback: tests that never call trunk_ident
      // still get a stable-within-the-dir key
      char buf[24];
      snprintf(buf, sizeof(buf), "peer:%llu",
               static_cast<unsigned long long>(peer_id));
      p.store_name = buf;
    }
    return p.store_name;
  }

  // Merge the persisted ring into the in-memory one (once per peer
  // life): runs before the first dial/journal so a recovered entry can
  // never duplicate a live one.
  void TrunkRingLoad(uint64_t peer_id, trunk::Peer& p) {
    if (!store_ || p.ring_loaded) return;
    p.ring_loaded = true;
    if (!p.unacked.empty()) return;  // live ring exists: nothing to merge
    uint8_t* blob = nullptr;
    size_t blen = 0;
    long n = store_->TrunkFetch(TrunkStoreName(peer_id, p), &blob, &blen);
    size_t pos = 0;
    uint64_t now = NowMs();
    for (long i = 0; i < n && pos + 13 <= blen; i++) {
      uint64_t seq;
      memcpy(&seq, blob + pos, 8);
      uint8_t tf = blob[pos + 8];
      uint32_t rl;
      memcpy(&rl, blob + pos + 9, 4);
      pos += 13;
      if (pos + rl > blen) break;
      trunk::Unacked u;
      u.seq = seq;
      u.flush_ms = now;  // watchdog clock restarts at recovery
      u.has_trace = (tf & 1) != 0;
      u.q1_record.assign(reinterpret_cast<const char*>(blob + pos), rl);
      pos += rl;
      p.unacked.push_back(std::move(u));
      if (seq >= p.next_seq) p.next_seq = seq + 1;
      stats_[kStTrunkRingRecovered].fetch_add(1,
                                              std::memory_order_relaxed);
    }
    free(blob);
  }

  // Negotiation resolved (answer arrived, deadline passed, or this
  // host speaks v0 and never negotiates): replay the unacked qos1 ring
  // at the negotiated version, then emit UP.
  void TrunkCompleteUp(uint64_t peer_id, trunk::Peer& p) {
    if (p.hello_pending) {
      p.hello_pending = false;
      if (trunk_hello_pending_) trunk_hello_pending_--;
    }
    auto sit = trunk_socks_.find(p.sock_tag);
    if (sit == trunk_socks_.end()) return;  // link died in the window
    p.up = true;
    // qos0-only ring entries (empty q1_record: they existed for the
    // OLD link's RTT stage) are dropped here, not replayed: with
    // exact-match acks (round 15) an unreplayable entry at the ring
    // front would read as an ack_gap the moment the peer acked the
    // first replayed batch behind it. Survivors re-stamp their
    // watchdog clock — a ring carried across a down window must not
    // trip ack_timeout the instant the link comes back.
    uint64_t now = NowMs();
    std::deque<trunk::Unacked> keep;
    for (trunk::Unacked& u : p.unacked) {
      if (u.q1_record.empty()) continue;
      u.flush_ms = now;
      // the shadow persists the sampled trace ids (round 14); a
      // reconnect that negotiated below v1 strips them losslessly —
      // never put bytes on a wire the peer cannot parse
      if (u.has_trace && p.wire_ver < 1)
        sit->second.outbuf += trunk::StripTraceRecord(u.q1_record);
      else
        sit->second.outbuf += u.q1_record;
      stats_[kStTrunkReplays].fetch_add(1, std::memory_order_relaxed);
      keep.push_back(std::move(u));
    }
    p.unacked.swap(keep);
    // the watchdog reference moved: re-arm against the fresh front
    if (p.tm_ack) {
      wheel_.Cancel(p.tm_ack);
      p.tm_ack = 0;
    }
    TrunkAckWatch(peer_id, p);
    char sub = 1;
    events_.push_back(EncodeRecord(9, peer_id, &sub, 1));
    TrunkFlushSock(p.sock_tag, sit->second);
  }

  // Once per poll cycle: complete any link whose HELLO answer never
  // came within the grace (an old peer) at wire v0.
  void TrunkHelloScan() {
    if (!trunk_hello_pending_) return;
    uint64_t now = NowMs();
    for (auto& [peer_id, p] : trunk_peers_) {
      if (p.hello_pending && p.sock_tag && now >= p.hello_deadline_ms)
        TrunkCompleteUp(peer_id, p);
    }
  }

  void TrunkEmitDown(uint64_t peer_id, const char* reason) {
    std::string payload;
    payload.push_back(2);
    payload.append(reason);
    events_.push_back(
        EncodeRecord(9, peer_id, payload.data(), payload.size()));
  }

  void TrunkEvent(const epoll_event& ev) {
    uint64_t tag = ev.data.u64;
    auto it = trunk_socks_.find(tag);
    if (it == trunk_socks_.end()) return;
    trunk::Sock& s = it->second;
    if (s.connecting) {
      int err = 0;
      socklen_t el = sizeof(err);
      getsockopt(s.fd, SOL_SOCKET, SO_ERROR, &err, &el);
      if (err != 0 || (ev.events & (EPOLLERR | EPOLLHUP))) {
        TrunkSockDead(tag, "connect_failed");
        return;
      }
      if (!(ev.events & EPOLLOUT)) return;
      s.connecting = false;
      epoll_event e2{};
      e2.events = EPOLLIN;
      e2.data.u64 = tag;
      epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, s.fd, &e2);
      auto pit = trunk_peers_.find(s.peer_id);
      if (pit != trunk_peers_.end() && pit->second.sock_tag == tag)
        TrunkUp(s.peer_id, pit->second);
      return;
    }
    if (ev.events & (EPOLLHUP | EPOLLERR)) {
      TrunkSockDead(tag, "sock_error");
      return;
    }
    if (ev.events & EPOLLOUT) {
      TrunkFlushSock(tag, s);
      if (!trunk_socks_.count(tag)) return;  // flush hit an error
    }
    if (ev.events & EPOLLIN) TrunkRead(tag);
  }

  void TrunkSockDead(uint64_t tag, const char* reason) {
    auto it = trunk_socks_.find(tag);
    if (it == trunk_socks_.end()) return;
    trunk::Sock s = std::move(it->second);
    epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, s.fd, nullptr);
    close(s.fd);
    trunk_socks_.erase(it);
    if (!s.dialer) return;
    auto pit = trunk_peers_.find(s.peer_id);
    if (pit != trunk_peers_.end() && pit->second.sock_tag == tag) {
      pit->second.sock_tag = 0;
      pit->second.up = false;
      // per-LINK negotiation: the next connect re-runs HELLO (the
      // replacement peer may be an older build); a death inside the
      // HELLO grace clears the pending state with the link
      if (pit->second.hello_pending) {
        pit->second.hello_pending = false;
        if (trunk_hello_pending_) trunk_hello_pending_--;
      }
      pit->second.wire_ver = 0;
      // remote entries now behave as punt markers (TryFast reads
      // p.up); the unacked ring is KEPT for the reconnect replay.
      // Python sees DOWN (kind 9 sub 2) and drives the redial.
      TrunkEmitDown(s.peer_id, reason);
    }
  }

  void TrunkRead(uint64_t tag) {
    auto it = trunk_socks_.find(tag);
    if (it == trunk_socks_.end()) return;
    trunk::Sock& s = it->second;
    uint8_t chunk[kReadChunk];
    for (;;) {
      // @fault(trunk_read) — a blackholed trunk read is one half of a
      // partition: the peer's batches/acks/HELLOs vanish in flight
      ssize_t n = FaultRecv(fault::kSiteTrunkRead, s.peer_id, s.fd,
                            chunk, sizeof(chunk));
      if (n > 0) {
        s.inbuf.append(reinterpret_cast<char*>(chunk),
                       static_cast<size_t>(n));
        if (static_cast<size_t>(n) < sizeof(chunk)) break;
      } else if (n == 0) {
        TrunkSockDead(tag, "sock_closed");
        return;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) break;
        if (errno == EINTR) continue;
        TrunkSockDead(tag, "sock_error");
        return;
      }
    }
    size_t pos = 0;
    while (s.inbuf.size() - pos >= 5) {
      uint32_t len = 0;
      memcpy(&len, s.inbuf.data() + pos, 4);
      // protocol-fixed bound (trunk.h), NOT this host's max_size_:
      // nodes with different max_packet_size configs must agree on
      // what a well-formed record is, or a legal record from a
      // bigger-configured peer poisons the link forever
      if (len < 1 || len > trunk::kMaxRecordBytes) {
        TrunkSockDead(tag, "bad_record");
        return;
      }
      if (s.inbuf.size() - pos < 4 + static_cast<size_t>(len)) break;
      uint8_t type = static_cast<uint8_t>(s.inbuf[pos + 4]);
      const char* body = s.inbuf.data() + pos + 5;
      size_t blen = len - 1;
      if (type == trunk::kRecBatch) {
        // per-sock seqs must strictly ascend (round 15): a regressed
        // or duplicate seq means the byte stream desynced (an injected
        // partition chopped it) — kill the link; redial replays
        if (blen >= 8) {
          uint64_t bseq = 0;
          memcpy(&bseq, body, 8);
          if (s.last_seq && bseq <= s.last_seq) {
            TrunkSockDead(tag, "seq_regress");
            return;
          }
          s.last_seq = bseq;
        }
        TrunkApplyBatch(s, body, blen);
      } else if (type == trunk::kRecAck && s.dialer && blen >= 8) {
        uint64_t seq = 0;
        memcpy(&seq, body, 8);
        TrunkApplyAck(s.peer_id, seq);
        // an ack_gap verdict kills THIS sock from under the read loop
        // (the TrunkEvent-after-flush guard, applied here too)
        if (!trunk_socks_.count(tag)) return;
      } else if (type == trunk::kRecHello && blen >= 1) {
        uint8_t theirs = static_cast<uint8_t>(body[0]);
        if (s.dialer) {
          // the peer's answer: the link speaks min(ours, theirs) —
          // and negotiation resolving completes the deferred link
          // bring-up (qos1 replay at the negotiated version + UP)
          auto pit = trunk_peers_.find(s.peer_id);
          if (pit != trunk_peers_.end() && pit->second.sock_tag == tag) {
            pit->second.wire_ver =
                theirs < trunk_wire_max_ ? theirs : trunk_wire_max_;
            if (pit->second.hello_pending) {
              uint64_t peer_id = s.peer_id;
              TrunkCompleteUp(peer_id, pit->second);
              // CompleteUp's replay flush may have hit a dead socket:
              // TrunkSockDead then erased `s` out from under this read
              // loop (the TrunkEvent-after-flush guard, applied here)
              if (!trunk_socks_.count(tag)) return;
            }
          }
        } else if (trunk_wire_max_ >= 1) {
          // receiver side: answer with our version (an old dialer
          // never sends HELLO, so this branch never fires against one)
          char hv = static_cast<char>(trunk_wire_max_);
          trunk::AppendRecord(&s.outbuf, trunk::kRecHello, &hv, 1);
        }
      }
      pos += 4 + len;
    }
    s.inbuf.erase(0, pos);
    TrunkPuntFlush();
    FlushDirty();             // deliveries written during ApplyBatch
    TrunkFlushSock(tag, s);   // the per-batch ACKs appended above
  }

  // Apply one received BATCH record: per-entry local fan-out through
  // the SAME match/deliver machinery the fast path uses. Entries whose
  // match set contains punt markers (or shared groups — defensive:
  // replication lag can race a group flip) go up to Python as kind-9
  // punt records instead; rule taps do NOT fire here — rules run on
  // the PUBLISHING node, exactly like the reference's forward lane
  // (emqx_broker:dispatch runs no hooks on the receiving node).
  void TrunkApplyBatch(trunk::Sock& s, const char* body, size_t blen) {
    if (blen < 12) return;
    uint64_t seq = 0;
    uint32_t n = 0;
    memcpy(&seq, body, 8);
    memcpy(&n, body + 8, 4);
    stats_[kStTrunkBatchesIn].fetch_add(1, std::memory_order_relaxed);
    size_t pos = 12;
    std::string_view prev_payload;
    bool have_prev = false;
    for (uint32_t i = 0; i < n && pos + 11 <= blen; i++) {
      uint64_t origin = 0;
      memcpy(&origin, body + pos, 8);
      uint8_t flags = static_cast<uint8_t>(body[pos + 8]);
      uint16_t tlen = 0;
      memcpy(&tlen, body + pos + 9, 2);
      pos += 11;
      if (pos + tlen > blen) break;
      std::string_view topic(body + pos, tlen);
      pos += tlen;
      uint64_t trace = 0;
      if (flags & 0x10) {  // wire-v1 trace extension (negotiated)
        if (pos + 8 > blen) break;
        memcpy(&trace, body + pos, 8);
        pos += 8;
      }
      std::string_view payload;
      if (flags & 1) {
        if (pos + 4 > blen) break;
        uint32_t pl = 0;
        memcpy(&pl, body + pos, 4);
        pos += 4;
        if (pos + pl > blen) break;
        payload = std::string_view(body + pos, pl);
        pos += pl;
        prev_payload = payload;
        have_prev = true;
      } else {
        if (!have_prev) break;  // corrupt batch: dedup with no reference
        payload = prev_payload;
      }
      TrunkFanOut(origin, (flags >> 1) & 3, (flags & 8) != 0, topic,
                  payload, trace);
    }
    cur_trace_ = 0;  // batch context over
    // ack AFTER fan-out: the sender's ring holds the qos1 copy until
    // every local delivery for this batch has been written
    char ab[8];
    memcpy(ab, &seq, 8);
    trunk::AppendRecord(&s.outbuf, trunk::kRecAck, ab, 8);
  }

  void TrunkFanOut(uint64_t origin, uint8_t qos, bool dup,
                   std::string_view topic, std::string_view payload,
                   uint64_t trace = 0) {
    stats_[kStTrunkIn].fetch_add(1, std::memory_order_relaxed);
    match_scratch_.clear();
    groups_scratch_.clear();
    subs_.Match(topic, &match_scratch_, &groups_scratch_);
    bool punt = !groups_scratch_.empty();
    if (!punt)
      for (const SubEntry* e : match_scratch_)
        if (e->flags & kSubPunt) {
          punt = true;
          break;
        }
    if (punt) {
      stats_[kStTrunkPunts].fetch_add(1, std::memory_order_relaxed);
      TrunkPuntAppend(origin, qos, dup, topic, payload, trace);
      return;
    }
    if (telemetry_) cur_hash_ = TopicHash(topic);
    cur_dup_ = dup;
    // re-join the sampled publish's timeline on the RECEIVING node:
    // the deliver_write spans below run under the wire-propagated id
    cur_trace_ = trace;
    if (trace) {
      cur_trace_delivers_ = 0;
      SpanNote(kSpanTrunkRecv, origin);
    }
    // publisher id 0 can never collide with a local conn (ids start at
    // 1), so no ack is written and no-local can never false-match a
    // local subscriber that happens to share the REMOTE publisher's id
    FanOut(0, qos, 0, topic, payload, /*count_fast=*/false);
  }

  // Receiver-side punts ride ONE kind-9 record per read batch (payload
  // [u8 3] + entries, payloads always inline — the sender's dedup may
  // reference an entry that was NOT punted).
  void TrunkPuntAppend(uint64_t origin, uint8_t qos, bool dup,
                       std::string_view topic, std::string_view payload,
                       uint64_t trace = 0) {
    size_t cap = TeleCap();
    size_t entry = 23 + topic.size() + payload.size();
    if (!trunk_punt_buf_.empty() && trunk_punt_buf_.size() + entry > cap)
      TrunkPuntFlush();
    if (trunk_punt_buf_.empty()) trunk_punt_buf_.push_back(3);
    trunk::AppendEntry(&trunk_punt_buf_, origin, qos, dup,
                       /*inline_payload=*/true, topic, payload, trace);
  }

  void TrunkPuntFlush() {
    if (trunk_punt_buf_.empty()) return;
    events_.push_back(EncodeRecord(9, 0, trunk_punt_buf_.data(),
                                   trunk_punt_buf_.size()));
    trunk_punt_buf_.clear();
  }

  // Sender: append one publish to the peer's batch under construction
  // (payload deduped vs the previous entry — the kind-6 discipline);
  // qos1 entries ALSO append a full copy to the qos1-only shadow that
  // becomes this batch's replay record. One FIFO per peer keeps
  // per-topic order trivially (total order per link).
  // @admit-gated — TrunkEligible decides BEFORE the entry lands here
  void TrunkEnqueue(uint64_t peer_id, uint64_t origin, uint8_t qos,
                    bool dup, std::string_view topic,
                    std::string_view payload) {
    auto it = trunk_peers_.find(peer_id);
    if (it == trunk_peers_.end()) return;
    trunk::Peer& p = it->second;
    bool inline_payload = !(p.have_prev && payload == p.prev_payload);
    // wire-versioned trace propagation (round 13): the id rides the
    // entry only on links that negotiated >= v1 — an old peer gets v0
    // entries with the id STRIPPED (losslessly; topic/payload intact)
    uint64_t wire_trace = p.wire_ver >= 1 ? cur_trace_ : 0;
    trunk::AppendEntry(&p.batch, origin, qos, dup, inline_payload, topic,
                       payload, wire_trace);
    if (wire_trace) SpanNote(kSpanTrunkFlush, peer_id, wire_trace);
    if (inline_payload) {
      p.prev_payload.assign(payload.data(), payload.size());
      p.have_prev = true;
    }
    if (qos) {
      // the replay shadow keeps the SAMPLED id even on a v0 link: the
      // replay happens on a FUTURE link whose version is negotiated
      // then — TrunkCompleteUp strips at replay time when that link
      // speaks v0 (round 14; the shadow used to be unconditionally v0
      // and a replayed batch always lost its trace annotation)
      trunk::AppendEntry(&p.q1_batch, origin, qos, dup,
                         /*inline_payload=*/true, topic, payload,
                         cur_trace_);
      if (cur_trace_) p.q1_has_trace = true;
      p.q1_n++;
    } else {
      p.q0_n++;
    }
    if (p.batch_n++ == 0) trunk_dirty_.push_back(peer_id);
    stats_[kStTrunkOut].fetch_add(1, std::memory_order_relaxed);
    size_t cap = TeleCap();
    // BOTH buffers bound the flush: deduped entries add ~15 bytes to
    // `batch` while adding the FULL payload to the qos1 shadow, so a
    // same-payload qos1 burst could otherwise build a replay record
    // past the receiver's record-size bound — which would poison every
    // reconnect with "bad_record" forever
    if (p.batch.size() > cap || p.q1_batch.size() > cap)
      FlushTrunkPeer(peer_id, p);
  }

  // Seal the batch under construction into one wire record + its ring
  // entry. Writes to the socket only while the link is up; a batch
  // sealed while down loses its qos0 entries (in-flight loss, same as
  // a death mid-send) but its qos1 record replays on reconnect.
  void FlushTrunkPeer(uint64_t peer_id, trunk::Peer& p) {
    if (p.batch_n == 0) return;
    // merge the previous life's persisted ring BEFORE minting this
    // batch's seq: recovered entries carry the old (higher) seqs, and
    // a fresh seq minted below them would regress the link's stream
    if (store_ && !p.ring_loaded) TrunkRingLoad(peer_id, p);
    uint64_t seq = p.next_seq++;
    std::string body;
    body.reserve(12 + p.batch.size());
    body.append(reinterpret_cast<const char*>(&seq), 8);
    body.append(reinterpret_cast<const char*>(&p.batch_n), 4);
    body += p.batch;
    trunk::Unacked u;
    u.seq = seq;
    u.t0_ns = telemetry_ ? NowNs() : 0;
    u.flush_ms = NowMs();   // the ack_timeout watchdog's reference
    u.has_trace = p.q1_has_trace;
    if (p.q1_n) {
      std::string q1body;
      q1body.reserve(12 + p.q1_batch.size());
      q1body.append(reinterpret_cast<const char*>(&seq), 8);
      q1body.append(reinterpret_cast<const char*>(&p.q1_n), 4);
      q1body += p.q1_batch;
      trunk::AppendRecord(&u.q1_record, trunk::kRecBatch, q1body.data(),
                          q1body.size());
      if (store_) {
        // journal the replay record BEFORE any socket write of this
        // batch (the PUBACK-after-store discipline applied to the
        // trunk): a kill -9 between the write and the journal could
        // otherwise lose a batch the peer never processed
        store_->TrunkPut(TrunkStoreName(peer_id, p), seq,
                         u.has_trace ? 1 : 0, u.q1_record.data(),
                         u.q1_record.size());
        stats_[kStTrunkRingPersisted].fetch_add(
            1, std::memory_order_relaxed);
      }
    }
    if (p.up) {
      auto sit = trunk_socks_.find(p.sock_tag);
      if (sit != trunk_socks_.end()) {
        trunk::Sock& s = sit->second;
        // the kHighWater mqueue-drop policy applied to the trunk link:
        // a connected-but-stalled peer must not grow the sender's
        // socket backlog without bound. qos0 entries shed (the same
        // fate a backpressured local delivery gets in DeliverTo);
        // qos1 keeps flowing as the qos1-only record because its
        // volume is already bounded by the unacked-ring admission gate
        bool congested = s.outbuf.size() - s.outpos > kHighWater;
        if (!congested) {
          trunk::AppendRecord(&s.outbuf, trunk::kRecBatch,
                              body.data(), body.size());
        } else if (!u.q1_record.empty()) {
          s.outbuf += u.q1_record;
          if (p.q0_n) {
            stats_[kStTrunkShed].fetch_add(p.q0_n,
                                           std::memory_order_relaxed);
            LedgerNote(kLrShed, peer_id);
          }
        } else {
          stats_[kStTrunkShed].fetch_add(p.batch_n,
                                         std::memory_order_relaxed);
          LedgerNote(kLrShed, peer_id);
        }
      }
    }
    // ring admission: qos0-only entries exist only for the RTT stage —
    // never let them grow the ring past its bound (a front entry
    // holding a qos1 record would otherwise block the trim below while
    // qos0 ballast accumulated behind it indefinitely); qos1 overshoot
    // stays soft-bounded by TryFast's admission gate
    if (!u.q1_record.empty() || p.unacked.size() < kTrunkUnackedMax)
      p.unacked.push_back(std::move(u));
    while (p.unacked.size() > kTrunkUnackedMax &&
           p.unacked.front().q1_record.empty())
      p.unacked.pop_front();  // qos0-only entries are droppable ballast
    TrunkAckWatch(peer_id, p);  // first unacked entry arms the watchdog
    if (telemetry_) RecordHist(kHistTrunkBatchN, p.batch_n);
    stats_[kStTrunkBatchesOut].fetch_add(1, std::memory_order_relaxed);
    p.batch.clear();
    p.q1_batch.clear();
    p.batch_n = 0;
    p.q1_n = 0;
    p.q0_n = 0;
    p.q1_has_trace = false;
    p.prev_payload.clear();
    p.have_prev = false;
  }

  // One batch record per poll cycle per dirty peer — the FlushTaps /
  // FlushAcks batching discipline applied to the wire.
  void FlushTrunks() {
    if (trunk_dirty_.empty()) return;
    std::vector<uint64_t> dirty;
    dirty.swap(trunk_dirty_);
    for (uint64_t peer_id : dirty) {
      auto it = trunk_peers_.find(peer_id);
      if (it == trunk_peers_.end()) continue;
      FlushTrunkPeer(peer_id, it->second);
      if (it->second.up) {
        uint64_t tag = it->second.sock_tag;
        auto sit = trunk_socks_.find(tag);
        if (sit != trunk_socks_.end()) TrunkFlushSock(tag, sit->second);
      }
    }
  }

  // Exact-match ack (round 15 — was cumulative): retire precisely the
  // ring entry the ack names. A cumulative trim was the silent-loss
  // enabler under an up-but-black link: batches written into the void
  // were retired by the first post-heal ack for a LATER seq. Acks
  // arrive in seq order on a healthy link, so the front always
  // matches; an ack AHEAD of the front is proof the peer never saw
  // the front batch — kill the link and let the redial replay it
  // (loss becomes at-least-once dups, never silence).
  void TrunkApplyAck(uint64_t peer_id, uint64_t seq) {
    auto it = trunk_peers_.find(peer_id);
    if (it == trunk_peers_.end()) return;
    trunk::Peer& p = it->second;
    if (p.unacked.empty() || seq < p.unacked.front().seq)
      return;  // stale ack (entry already retired): ignore
    if (seq > p.unacked.front().seq) {
      if (p.sock_tag) TrunkSockDead(p.sock_tag, "ack_gap");
      return;
    }
    if (telemetry_ && p.unacked.front().t0_ns)
      RecordHist(kHistTrunkRtt, NowNs() - p.unacked.front().t0_ns);
    // the ack retires the STORE record alongside the memory slot
    // (round 18): qos0-only entries were never journaled
    if (store_ && !p.unacked.front().q1_record.empty())
      store_->TrunkAck(TrunkStoreName(peer_id, p), seq);
    p.unacked.pop_front();
  }

  // Silent-link watchdog (round 15), once per poll cycle next to the
  // The HELLO-grace deadline stays a (tiny, O(peers)) scan; the ack
  // watchdog itself moved onto the wheel (FireTrunkAck): a
  // partitioned-but-ESTABLISHED link never fails a syscall, so only
  // the unacked-front deadline notices its acks stopped. Entries
  // sealed while the link was down are exempt by construction (the
  // fire requires p.up, and TrunkCompleteUp re-stamps every survivor
  // at replay time).

  void TrunkFlushSock(uint64_t tag, trunk::Sock& s) {
    while (s.outpos < s.outbuf.size()) {
      // @fault(trunk_write) — blackhole = the up-but-black link: sends
      // "succeed" while the bytes vanish; the ack_gap/ack_timeout
      // watchdogs are what turn that loss back into a replay
      ssize_t n = FaultSend(fault::kSiteTrunkWrite, s.peer_id, s.fd,
                            s.outbuf.data() + s.outpos,
                            s.outbuf.size() - s.outpos);
      if (n > 0) {
        s.outpos += static_cast<size_t>(n);
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.u64 = tag;
        epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, s.fd, &ev);
        return;
      } else if (n < 0 && errno == EINTR) {
        continue;
      } else {
        TrunkSockDead(tag, "sock_error");
        return;
      }
    }
    s.outbuf.clear();
    s.outpos = 0;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = tag;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, s.fd, &ev);
  }

  // -- multi-core shards (round 12) ---------------------------------------
  // One Host instance per shard, each a full single-threaded epoll
  // plane; the match table is replicated (Python broadcasts ops) and
  // only DELIVERY crosses shards, over ring.h's SPSC rings in the
  // trunk BATCH entry layout prefixed with an explicit [u64 target] —
  // the producer shard did the match, so the consumer delivers by conn
  // id instead of re-matching. Degradation ladder mirrors the trunk's:
  // ring-full -> punt -> Python, decided BEFORE any side effect.

  uint64_t ShardPrefix() const {
    return static_cast<uint64_t>(shard_id_) << kShardShift;
  }
  uint64_t MintConnId() { return ShardPrefix() | next_id_++; }
  // Trunk peer links SPREAD across shards (round 15 — they all lived
  // on shard 0, the hotspot an N-node mesh would have measured): peer
  // P's dialer, replay ring, and peer state live on shard P % n.
  // Python routes the link ops there; every shard's trunk LISTENER
  // shares one port via SO_REUSEPORT so inbound links spread too. An
  // unsharded host owns every peer.
  int TrunkShardOf(uint64_t peer) const {
    return group_ ? static_cast<int>(peer % group_->n) : 0;
  }
  bool OwnsTrunkPeer(uint64_t peer) const {
    return TrunkShardOf(peer) == shard_id_;
  }

  // Producer-side admission for one destination: alive consumer and
  // >= 2 free slots (room for the open batch plus one mid-publish
  // seal — a single publish can trigger at most one byte-cap seal, so
  // the cycle-end seal always has a slot).
  // (non-const since round 15: the forced-ring_full fault site counts
  // its fire through the stats/ledger accounting)
  // @admit-check
  bool RingRoom(int dst) {
    // @fault(ring_seal) — forced ring_full: the publish degrades
    // ring-full -> punt -> Python through the REAL ladder accounting
    if (FaultHit(fault::kSiteRingSeal,
                 static_cast<uint64_t>(dst) + 1))
      return false;
    return group_ != nullptr &&
           group_->alive[dst].load(std::memory_order_acquire) &&
           group_->rings[shard_id_][dst].Free() >= 2;
  }

  // Can this publish ride `peer`'s trunk from THIS shard? Non-owner
  // shards consult their Python-broadcast up/down mirror
  // (kTrunkPeerState) and conservatively punt while the mirror lags;
  // the qos1 replay-ring bound is enforced where the ring lives
  // (the peer's owner shard — ring-forwarded entries may overshoot it
  // by the in-flight cycle, the trunk's documented soft bound).
  // @admit-check
  bool TrunkEligible(uint64_t peer, uint8_t qos,
                     size_t entry_bytes) const {
    if (qos == 2 || entry_bytes > trunk::kMaxEntryBytes) return false;
    if (OwnsTrunkPeer(peer)) {
      auto tp = trunk_peers_.find(peer);
      return tp != trunk_peers_.end() && tp->second.up &&
             !(qos == 1 &&
               tp->second.unacked.size() >= kTrunkUnackedMax);
    }
    auto it = trunk_peer_up_.find(peer);
    return it != trunk_peer_up_.end() && it->second;
  }

  // Collect the destination shards this match set needs (plain
  // cross-shard entries + each trunk leg's owner shard when it must
  // ride the ring) and check ring room for each. False = the publish
  // must degrade to a punt — called BEFORE any side effect, the trunk
  // discipline.
  // @admit-check
  bool ShardAdmit() {
    if (!group_) return true;
    xdst_scratch_.clear();
    for (const SubEntry* e : match_scratch_) {
      if (e->flags & (kSubPunt | kSubDurable | kSubRuleTap | kSubRemote))
        continue;
      int ds = ShardOf(e->owner);
      if (ds == shard_id_) continue;
      PushUnique(&xdst_scratch_, ds);
    }
    for (uint64_t peer : trunk_scratch_) {
      int ts = TrunkShardOf(peer);
      if (ts != shard_id_) PushUnique(&xdst_scratch_, ts);
    }
    for (int ds : xdst_scratch_) {
      if (!RingRoom(ds)) {
        stats_[kStShardRingFull].fetch_add(1, std::memory_order_relaxed);
        LedgerNote(kLrRingFull, static_cast<uint64_t>(ds));
        return false;
      }
    }
    return true;
  }

  // Append one cross-shard entry ([u64 target] + the trunk pre-parse
  // entry, payload-deduped per destination batch) and seal at the byte
  // cap. `target` is a conn id (delivery) or kTrunkOwnerBase + peer
  // (trunk forward from a non-trunk shard). Bit 63 of the target word
  // marks the MULTI-TARGET form below; every real target (conn ids
  // top out at bit 59, the trunk owner bit is 62) keeps it clear.
  // @admit-gated — RingRoom/ShardAdmit decide BEFORE a slot is spent
  void XShip(int dst, uint64_t target, uint64_t origin, uint8_t qos,
             bool dup, std::string_view topic, std::string_view payload) {
    std::string& b = XBatch(dst);
    char t8[8];
    memcpy(t8, &target, 8);
    b.append(t8, 8);
    XAppendEntry(dst, b, origin, qos, dup, topic, payload);
    stats_[kStShardRingOut].fetch_add(1, std::memory_order_relaxed);
    if (b.size() > kTapFlushBytes) SealShardBatch(dst);
  }

  // The fan-out form (the perf_opt spine): ONE entry per (publish,
  // destination shard) — [u64 bit63|n][n x u64 (min_qos<<60 | conn)]
  // + the shared trunk pre-parse entry. The consumer decodes the
  // topic/payload ONCE and builds the shared frames ONCE per publish,
  // exactly like FanOut's per-publish shared-frame discipline; the
  // per-target min-qos rides bits 60-61 of each target word (conn ids
  // top out at bit 59). Halves ring bytes and consumer decode for
  // wide audiences vs one single-target entry per subscriber.
  // @admit-gated — RingRoom/ShardAdmit decide BEFORE a slot is spent
  void XShipMulti(int dst, const std::vector<uint64_t>& targets,
                  uint64_t origin, uint8_t qos, std::string_view topic,
                  std::string_view payload) {
    std::string& b = XBatch(dst);
    uint64_t marker = (1ull << 63) | targets.size();
    char t8[8];
    memcpy(t8, &marker, 8);
    b.append(t8, 8);
    b.append(reinterpret_cast<const char*>(targets.data()),
             8 * targets.size());
    XAppendEntry(dst, b, origin, qos, /*dup=*/false, topic, payload);
    stats_[kStShardRingOut].fetch_add(targets.size(),
                                      std::memory_order_relaxed);
    if (b.size() > kTapFlushBytes) SealShardBatch(dst);
  }

  std::string& XBatch(int dst) {
    std::string& b = xbatch_[dst];
    if (b.empty()) {
      b.reserve(kTapFlushBytes + 512);  // one allocation per batch
      b.assign(4, '\0');  // [u32 n] patched at seal
      xdirty_.push_back(dst);
    }
    return b;
  }

  void XAppendEntry(int dst, std::string& b, uint64_t origin,
                    uint8_t qos, bool dup, std::string_view topic,
                    std::string_view payload) {
    bool inline_payload =
        !(xhave_prev_[dst] && payload == xprev_payload_[dst]);
    // the active trace id rides the ring entry (flags bit4): both ends
    // are this binary, so no version negotiation — the consumer shard
    // re-joins the sampled publish's timeline at ring_cross
    trunk::AppendEntry(&b, origin, qos, dup, inline_payload, topic,
                       payload, cur_trace_);
    if (inline_payload) {
      xprev_payload_[dst].assign(payload.data(), payload.size());
      xhave_prev_[dst] = true;
    }
    xbatch_n_[dst]++;
  }

  void SealShardBatch(int dst) {
    std::string& b = xbatch_[dst];
    if (xbatch_n_[dst] == 0) {
      b.clear();
      return;
    }
    memcpy(&b[0], &xbatch_n_[dst], 4);
    // ring the doorbell on the FIRST seal of a cycle, not just at
    // cycle end (FlushShards): a long read-backlog cycle seals many
    // byte-cap batches, and a consumer sleeping until cycle end would
    // turn the pipeline half-duplex (measured ~15% on the 2-core box)
    bool first = xbatch_sealed_[dst] == 0;
    xbatch_sealed_[dst]++;
    if (!group_->rings[shard_id_][dst].Push(std::move(b))) {
      // the consumer wedged past the admission margin (it only holds
      // under a torn-down shard racing the pre-check): drop with the
      // backpressure accounting a stalled local subscriber would get
      stats_[kStShardRingFull].fetch_add(1, std::memory_order_relaxed);
      stats_[kStDropsBackpressure].fetch_add(xbatch_n_[dst],
                                             std::memory_order_relaxed);
      LedgerNote(kLrRingFull, static_cast<uint64_t>(dst));
    }
    b.clear();  // Push moved it on success; failure keeps it — clear both
    xbatch_n_[dst] = 0;
    xprev_payload_[dst].clear();
    xhave_prev_[dst] = false;
    // @fault(ring_doorbell) — a suppressed wakeup: the consumer must
    // still drain on its next natural poll timeout (late, never lost)
    if (first && !FaultHit(fault::kSiteRingDoorbell,
                           static_cast<uint64_t>(dst) + 1))
      group_->RingDoorbell(dst);
  }

  // Once per poll cycle (the FlushTrunks discipline): seal every dirty
  // destination batch and ring its doorbell.
  void FlushShards() {
    if (xdirty_.empty()) return;
    std::vector<int> dirty;
    dirty.swap(xdirty_);
    for (int dst : dirty) {
      SealShardBatch(dst);
      // @fault(ring_doorbell) — cycle-end wakeup suppressed too
      if (!FaultHit(fault::kSiteRingDoorbell,
                    static_cast<uint64_t>(dst) + 1))
        group_->RingDoorbell(dst);
      xbatch_sealed_[dst] = 0;
    }
  }

  // Consume every inbound ring once per poll cycle.
  void DrainShardRings() {
    bool any = false;
    std::string rec;
    for (int src = 0; src < group_->n; src++) {
      if (src == shard_id_) continue;
      ring::SpscRing& r = group_->rings[src][shard_id_];
      while (r.Pop(&rec)) {
        ApplyShardBatch(src, rec);
        any = true;
      }
    }
    if (any) FlushDirty();
  }

  // Apply one ring batch: explicit per-target deliveries (the producer
  // shard did the match and pre-minned each target's qos), plus
  // trunk-forward entries (target carries the trunk owner bit) from
  // shards without trunk links. Fan-out entries carry one target LIST
  // per publish (XShipMulti), so topic/payload decode and the shared
  // frame builds run once per publish — FanOut's discipline, across
  // the ring.
  void ApplyShardBatch(int src, const std::string& rec) {
    if (rec.size() < 4) return;
    uint32_t n = 0;
    memcpy(&n, rec.data(), 4);
    if (telemetry_) RecordHist(kHistShardRingN, n);
    const char* body = rec.data();
    size_t blen = rec.size();
    size_t pos = 4;
    std::string_view prev_payload;
    bool have_prev = false;
    std::string_view last_topic;
    const char* last_pl = nullptr;
    uint64_t applied = 0;
    constexpr uint64_t kConnMask = (1ull << 60) - 1;
    for (uint32_t i = 0; i < n && pos + 8 <= blen; i++) {
      uint64_t t0 = 0;
      memcpy(&t0, body + pos, 8);
      pos += 8;
      uint32_t ntgt = 0;
      size_t tgts_at = 0;
      if (t0 >> 63) {  // multi-target marker: [bit63|n][n x u64]
        ntgt = static_cast<uint32_t>(t0 & 0xFFFFFFFFu);
        if (ntgt == 0 || pos + 8ull * ntgt > blen) break;
        tgts_at = pos;
        pos += 8ull * ntgt;
      }
      if (pos + 11 > blen) break;
      uint64_t origin = 0;
      memcpy(&origin, body + pos, 8);
      uint8_t flags = static_cast<uint8_t>(body[pos + 8]);
      uint16_t tlen = 0;
      memcpy(&tlen, body + pos + 9, 2);
      pos += 11;
      if (pos + tlen > blen) break;
      std::string_view topic(body + pos, tlen);
      pos += tlen;
      uint64_t trace = 0;
      if (flags & 0x10) {  // the producer shard sampled this publish
        if (pos + 8 > blen) break;
        memcpy(&trace, body + pos, 8);
        pos += 8;
      }
      std::string_view payload;
      if (flags & 1) {
        if (pos + 4 > blen) break;
        uint32_t pl = 0;
        memcpy(&pl, body + pos, 4);
        pos += 4;
        if (pos + pl > blen) break;
        payload = std::string_view(body + pos, pl);
        pos += pl;
        prev_payload = payload;
        have_prev = true;
      } else {
        if (!have_prev) break;  // corrupt batch: dedup with no reference
        payload = prev_payload;
      }
      uint8_t qos = (flags >> 1) & 3;
      bool dup = (flags & 8) != 0;
      // re-join the sampled publish's timeline on THIS shard: the
      // consumer-side deliveries below emit deliver_write spans under
      // the propagated id, anchored by one ring_cross point (aux =
      // the producing shard)
      cur_trace_ = trace;
      if (trace) {
        cur_trace_delivers_ = 0;
        SpanNote(kSpanRingCross, static_cast<uint64_t>(src));
      }
      if (ntgt == 0 && (t0 & kTrunkOwnerBase)) {
        applied++;
        TrunkEnqueue(t0 - kTrunkOwnerBase, origin, qos, dup, topic,
                     payload);
        continue;
      }
      // DeliverTo's shared frames are per-publish scratch (the qos0
      // frame and the zero-pid elevated frame are both qos-patched per
      // target): rebuild only when (topic, payload) changed
      if (topic != last_topic || payload.data() != last_pl) {
        frame_v4_.clear();
        frame_v5_.clear();
        frame_q_v4_.clear();
        frame_q_v5_.clear();
        last_topic = topic;
        last_pl = payload.data();
        if (telemetry_) cur_hash_ = TopicHash(topic);
      }
      if (ntgt == 0) {
        applied++;
        SubEntry e{t0, qos, 0};
        DeliverTo(t0, e, origin, qos, topic, payload);
        continue;
      }
      applied += ntgt;
      for (uint32_t k = 0; k < ntgt; k++) {
        uint64_t w = 0;
        memcpy(&w, body + tgts_at + 8ull * k, 8);
        uint8_t oq = static_cast<uint8_t>((w >> 60) & 3);
        uint64_t conn = w & kConnMask;
        SubEntry e{conn, oq, 0};
        DeliverTo(conn, e, origin, oq, topic, payload);
      }
    }
    cur_trace_ = 0;  // batch context over: nothing later may inherit it
    if (applied)
      stats_[kStShardRingIn].fetch_add(applied, std::memory_order_relaxed);
  }

  // -- mqtt-sn gateway (round 11) -----------------------------------------
  // Foreign framing → same MQTT fast path, the ws.h pattern applied to
  // the first UDP gateway: datagrams decode with the shared sn.h codec,
  // translate into MQTT frames, and ride TryFast / the Python channel
  // exactly like TCP bytes would. Egress reverses the translation (one
  // SN datagram per MQTT packet), with a per-conn topic-id registry,
  // sleeping-client buffering, and qos1 retransmit-on-timeout — the
  // asyncio gateway (gateway/mqttsn.py) stays the protocol oracle.

  static uint64_t SnAddrKey(const sockaddr_in& a) {
    return (static_cast<uint64_t>(a.sin_addr.s_addr) << 16) | a.sin_port;
  }

  static void BuildMqttFrame(std::string* out, uint8_t header,
                             const std::string& body) {
    out->push_back(static_cast<char>(header));
    size_t r = body.size();
    do {
      uint8_t b = r & 0x7F;
      r >>= 7;
      out->push_back(static_cast<char>(r ? b | 0x80 : b));
    } while (r);
    *out += body;
  }

  static void MakeMqttAck(std::string* out, uint8_t header, uint16_t pid) {
    out->push_back(static_cast<char>(header));
    out->push_back(0x02);
    out->push_back(static_cast<char>(pid >> 8));
    out->push_back(static_cast<char>(pid & 0xFF));
  }

  // One recvmmsg drains up to kSnRecvBatch datagrams per syscall.
  // Per-datagram UDP syscalls are brutal on sandboxed kernels
  // (~30us/recvfrom measured here vs ~5us amortized via recvmmsg),
  // and peers aggregate messages per datagram (sn.h kPackDatagram),
  // so one syscall can carry thousands of SN messages.
  static constexpr int kSnRecvBatch = 32;
  static constexpr size_t kSnRecvBuf = 65536;  // UDP max: never truncates

  void SnRead() {
    if (sn_rx_buf_.empty()) sn_rx_buf_.resize(kSnRecvBatch * kSnRecvBuf);
    mmsghdr mm[kSnRecvBatch];
    iovec iov[kSnRecvBatch];
    sockaddr_in peers[kSnRecvBatch];
    // bounded per cycle so an SN blast cannot starve the TCP/WS side
    for (int budget = 0; budget < 4096; budget += kSnRecvBatch) {
      for (int i = 0; i < kSnRecvBatch; i++) {
        iov[i].iov_base = sn_rx_buf_.data() + i * kSnRecvBuf;
        iov[i].iov_len = kSnRecvBuf;
        memset(&mm[i].msg_hdr, 0, sizeof(mm[i].msg_hdr));
        mm[i].msg_hdr.msg_name = &peers[i];
        mm[i].msg_hdr.msg_namelen = sizeof(peers[i]);
        mm[i].msg_hdr.msg_iov = &iov[i];
        mm[i].msg_hdr.msg_iovlen = 1;
      }
      int n = recvmmsg(sn_fd_, mm, kSnRecvBatch, 0, nullptr);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;  // EAGAIN: drained
      }
      for (int i = 0; i < n; i++) {
        if (mm[i].msg_len == 0) continue;
        const uint8_t* d = sn_rx_buf_.data() + i * kSnRecvBuf;
        if (telemetry_ && ((++tele_tick_sn_ & tele_mask_) == 0)) {
          uint64_t t0 = NowNs();
          SnIngest(peers[i], d, mm[i].msg_len);
          RecordHist(kHistSnIngest, NowNs() - t0);
        } else {
          SnIngest(peers[i], d, mm[i].msg_len);
        }
      }
      if (n < kSnRecvBatch) break;  // drained
    }
    FlushDirty();
  }

  void SnIngest(const sockaddr_in& peer, const uint8_t* data, size_t len) {
    sn_msgs_scratch_.clear();
    sn::ParseAll(data, len, &sn_msgs_scratch_);
    for (sn::SnMsg& m : sn_msgs_scratch_) SnHandle(peer, m);
  }

  // Mirror of IngestMqtt's per-frame body for a single translated frame.
  void SnForward(uint64_t id, Conn& c, const std::string& f) {
    if (!c.fast || !TryFast(id, c, f)) {
      FrNote(c, c.fast ? kFrPunt : kFrFrame,
             static_cast<uint8_t>(f[0]) >> 4,
             static_cast<uint16_t>(f.size() & 0xFFFF));
      events_.push_back(EncodeRecord(2, id, f.data(), f.size()));
    }
  }

  void SnReply(uint64_t id, Conn& c, const sn::SnMsg& m) {
    // control answers bypass the sleep buffer (the oracle's handle_in
    // replies go straight out too; only DELIVERIES park)
    std::string dg;
    sn::Serialize(m, &dg);
    c.outbuf += dg;
    MarkDirty(id, c);
  }

  // Conn-less direct answer (SEARCHGW, not-connected DISCONNECT).
  void SnSendTo(const sockaddr_in& peer, const sn::SnMsg& m) {
    std::string dg;
    sn::Serialize(m, &dg);
    sendto(sn_fd_, dg.data(), dg.size(), MSG_NOSIGNAL,
           reinterpret_cast<const sockaddr*>(&peer), sizeof(peer));
  }

  std::string SnDefaultCid(uint64_t id) {
    // the oracle mints "sn-<id(self)>"-style fallbacks; ours are the
    // conn id, which is stable for the conn's lifetime
    return "sn-" + std::to_string(id & 0xFFFFFFFFull);
  }

  uint64_t SnNewConn(const sockaddr_in& peer) {
    Conn c;
    c.fd = -1;  // egress rides sendto() on the shared UDP socket
    c.framer = Framer(max_size_);
    c.sn = std::make_unique<SnConnState>();
    c.sn->addr = peer;
    uint64_t id = kSnConnBit | ShardPrefix() | next_sn_id_++;
    c.sn->conn_id = id;
    auto& cref = conns_.emplace(id, std::move(c)).first->second;
    sn_addr_conn_[SnAddrKey(peer)] = id;
    cref.last_rx_ms = NowMs();
    FrNote(cref, kFrOpen, 0, 2);  // arg 2 = SN transport
    char ip[INET_ADDRSTRLEN] = "?";
    inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
    std::string info = std::string("sn:") + ip + ":" +
                       std::to_string(ntohs(peer.sin_port));
    events_.push_back(EncodeRecord(1, id, info.data(), info.size()));
    return id;
  }

  // Translate + forward the CONNECT; the Python channel owns the
  // session (auth, CM takeover, hooks) exactly as for TCP clients.
  void SnConnect(uint64_t id, const sn::SnMsg& m) {
    auto it = conns_.find(id);
    if (it == conns_.end()) return;
    Conn& c = it->second;
    SnConnState& s = *c.sn;
    s.clientid = m.clientid.empty() ? SnDefaultCid(id) : m.clientid;
    s.connect_sent = true;
    s.connected = false;
    // duration 0 = "no keepalive" on the wire; the asyncio listener
    // idle-times those peers out at 300s (conn.py UdpGwListener
    // default) — translating 0 to 300 gives the native conn the same
    // effective lifetime instead of leaking it forever
    uint16_t keepalive = m.duration ? m.duration : 300;
    std::string body;
    body.push_back(0);
    body.push_back(4);
    body += "MQTT";
    body.push_back(4);  // translated SN sessions speak MQTT 3.1.1
    body.push_back((m.flags & sn::kFClean) ? 0x02 : 0x00);
    sn::PutBe16(&body, keepalive);
    sn::PutBe16(&body, static_cast<uint16_t>(s.clientid.size()));
    body += s.clientid;
    std::string f;
    BuildMqttFrame(&f, 0x10, body);
    SnForward(id, c, f);
  }

  bool SnResolveTopic(SnConnState& s, uint8_t kind, uint16_t topic_id,
                      std::string* topic) {
    if (kind == sn::kTidPredef) {
      auto it = sn_predefined_.find(topic_id);
      if (it == sn_predefined_.end()) return false;
      *topic = it->second;
      return true;
    }
    if (kind == sn::kTidShort) {
      topic->clear();
      topic->push_back(static_cast<char>(topic_id >> 8));
      topic->push_back(static_cast<char>(topic_id & 0xFF));
      return true;
    }
    auto it = s.topic_of_id.find(topic_id);
    if (it == s.topic_of_id.end()) return false;
    *topic = it->second;
    return true;
  }

  // Per-conn NORMAL id allocation: wrap at the u16 ceiling skipping
  // ids still in use and the reserved 0x0000 (the oracle's fixed
  // _alloc_tid). Returns 0 only when all 65535 ids are taken.
  uint16_t SnAllocTid(SnConnState& s, const std::string& topic) {
    auto it = s.id_of_topic.find(topic);
    if (it != s.id_of_topic.end()) return it->second;
    // wrap in 1..0xFFFE: 0x0000 AND 0xFFFF are reserved (§5.3.11)
    for (int guard = 0; guard < 0xFFFE; guard++) {
      s.next_tid = static_cast<uint16_t>(s.next_tid % 0xFFFE + 1);
      if (!s.topic_of_id.count(s.next_tid)) {
        s.id_of_topic[topic] = s.next_tid;
        s.topic_of_id[s.next_tid] = topic;
        return s.next_tid;
      }
    }
    return 0;
  }

  uint16_t SnNextMid(SnConnState& s) {
    s.next_mid = static_cast<uint16_t>(s.next_mid % 0xFFFF + 1);
    return s.next_mid;
  }

  void SnHandle(const sockaddr_in& peer, sn::SnMsg& m) {
    if (m.type == sn::kSearchGw) {
      sn::SnMsg gi;
      gi.type = sn::kGwInfo;
      gi.rc = sn_gw_id_;
      SnSendTo(peer, gi);
      return;
    }
    if (m.type == sn::kPublish && sn::QosOf(m.flags) < 0) {
      SnQosM1(m);
      return;
    }
    uint64_t key = SnAddrKey(peer);
    auto ait = sn_addr_conn_.find(key);
    if (ait == sn_addr_conn_.end()) {
      if (m.type == sn::kConnect) {
        if (conns_.size() >= max_conns_) return;  // esockd max-conn
        SnConnect(SnNewConn(peer), m);
      } else if (m.type != sn::kDisconnect && m.type != sn::kPingReq) {
        // unknown peer mid-protocol: the oracle's not-connected answer
        sn::SnMsg d;
        d.type = sn::kDisconnect;
        SnSendTo(peer, d);
      }
      return;
    }
    uint64_t id = ait->second;
    auto cit = conns_.find(id);
    if (cit == conns_.end()) {
      sn_addr_conn_.erase(ait);
      return;
    }
    Conn& c = cit->second;
    SnConnState& s = *c.sn;
    c.last_rx_ms = NowMs();
    if (m.type == sn::kConnect) {
      if (s.connected) {
        // any CONNECT on a live conn re-runs the session open — the
        // oracle re-authenticates and re-opens on EVERY CONNECT (a
        // rebooted device with F_CLEAN must get clean-start semantics,
        // and a freshly banned clientid must be re-checked, not waved
        // through as a CONNACK retransmit). Release the old session
        // through the Python channel (close_session parity) and
        // connect fresh; same-clientid reconnects take over their old
        // session in Python exactly like a TCP takeover. The old conn
        // keeps draining; the addr now maps to the new conn.
        sn_addr_conn_.erase(key);
        std::string f;
        f.push_back(static_cast<char>(0xE0));
        f.push_back(0);
        SnForward(id, c, f);
        // conns_ may rehash on the emplace: no Conn& use after this
        SnConnect(SnNewConn(peer), m);
      }
      // else: CONNECT retransmit while the first is awaiting its
      // CONNACK — the in-flight answer covers it
      return;
    }
    if (m.type == sn::kPingReq) {
      stats_[kStSnPings].fetch_add(1, std::memory_order_relaxed);
      if (!s.awake || !s.sleep_buf.empty()) {
        // waking flushes parked deliveries BEFORE the ping answer
        // (MQTT-SN §6.14 buffered delivery on the keepalive ping)
        s.awake = true;
        s.sleep_until_ms = 0;
        while (!s.sleep_buf.empty()) {
          c.outbuf += s.sleep_buf.front();
          s.sleep_buf.pop_front();
        }
        // the flush IS the first transmission of any qos1 delivery
        // parked during sleep — restart the retry clock from here
        uint64_t woke = NowMs();
        for (auto& r : s.rexmit) r.last_tx_ms = woke;
        // re-arm the rexmit wheel deadline the sleep entry cancelled
        if (!s.rexmit.empty() && !s.tm_rexmit)
          s.tm_rexmit = wheel_.Arm(id, kTmSnRexmit, woke + kSnRetryMs);
        MarkDirty(id, c);
      }
      if (s.connected) {
        std::string f;
        f.push_back(static_cast<char>(0xC0));
        f.push_back(0);
        SnForward(id, c, f);  // Python answers PINGRESP -> SN PINGRESP
      }
      return;
    }
    if (m.type == sn::kDisconnect) {
      sn::SnMsg d;
      d.type = sn::kDisconnect;
      if (m.duration) {
        // sleep mode: keep the session, stop delivering, start the
        // announced-silence window the keepalive feed honours
        s.awake = false;
        s.sleep_until_ms = NowMs() + static_cast<uint64_t>(m.duration)
                                     * 1000;
        // park the retry clock with the radio (wake re-arms it)
        if (s.tm_rexmit) {
          wheel_.Cancel(s.tm_rexmit);
          s.tm_rexmit = 0;
        }
        SnReply(id, c, d);
        return;
      }
      SnReply(id, c, d);
      std::string f;
      f.push_back(static_cast<char>(0xE0));
      f.push_back(0);
      SnForward(id, c, f);  // Python tears the session down + closes
      return;
    }
    if (!s.connected) {
      if (s.connect_sent && !s.connack_seen &&
          s.preconn.size() < kSnPreconnMax) {
        // CONNECT is in flight to the Python channel. The oracle
        // connects synchronously, so a client that pipelines
        // REGISTER/SUBSCRIBE/PUBLISH behind its CONNECT (or packs
        // them into one datagram) must have them served, not bounced.
        // Park until the CONNACK egresses, then replay in order.
        s.preconn.push_back(std::move(m));
        return;
      }
      // oracle: everything else requires a session
      sn::SnMsg d;
      d.type = sn::kDisconnect;
      SnReply(id, c, d);
      return;
    }
    SnDispatch(id, c, m);
  }

  // One post-session SN message (the oracle's connected-state
  // handle_in). Split from SnHandle so the preconn replay after a
  // CONNACK egress runs the identical code path.
  static constexpr size_t kSnPreconnMax = 64;

  void SnDispatch(uint64_t id, Conn& c, sn::SnMsg& m) {
    SnConnState& s = *c.sn;
    switch (m.type) {
      case sn::kRegister: {
        uint16_t tid = SnAllocTid(s, m.topic_name);
        stats_[kStSnRegisters].fetch_add(1, std::memory_order_relaxed);
        sn::SnMsg ra;
        ra.type = sn::kRegack;
        ra.topic_id = tid;
        ra.msg_id = m.msg_id;
        // tid 0 is the reserved invalid id: a full registry must answer
        // "rejected: congestion", not hand 0 out as a success
        ra.rc = tid ? sn::kRcAccepted : sn::kRcCongestion;
        SnReply(id, c, ra);
        break;
      }
      case sn::kPublish: {
        int qi = sn::QosOf(m.flags);
        uint8_t qos = qi < 0 ? 0 : static_cast<uint8_t>(qi);
        std::string topic;
        if (!SnResolveTopic(s, m.flags & 0x3, m.topic_id, &topic)) {
          if (qos > 0) {
            sn::SnMsg pa;
            pa.type = sn::kPuback;
            pa.topic_id = m.topic_id;
            pa.msg_id = m.msg_id;
            pa.rc = sn::kRcInvalidTopicId;
            SnReply(id, c, pa);
          }
          break;
        }
        stats_[kStSnIn].fetch_add(1, std::memory_order_relaxed);
        if (qos > 0) {
          // the MQTT ack coming back carries only the msg id; the SN
          // PUBACK needs the topic id too (runaway-bound: a client
          // that never sees its acks can't grow this past the id space)
          if (s.pub_tid.size() > 8192) s.pub_tid.clear();
          s.pub_tid[m.msg_id] = m.topic_id;
        }
        std::string body;
        sn::PutBe16(&body, static_cast<uint16_t>(topic.size()));
        body += topic;
        if (qos) sn::PutBe16(&body, m.msg_id);
        body += m.data;
        uint8_t h = static_cast<uint8_t>(0x30 | (qos << 1));
        if (m.flags & sn::kFDup) h |= 0x08;
        if (m.flags & sn::kFRetain) h |= 0x01;
        std::string f;
        BuildMqttFrame(&f, h, body);
        SnForward(id, c, f);
        break;
      }
      case sn::kPuback: {
        // subscriber acked a delivery: retire the retransmit copy
        // FIRST, then route the ack like any wire PUBACK (native pids
        // consume in TryFastPuback, Python pids forward to the session)
        SnRexmitAck(id, s, m.msg_id);
        std::string f;
        MakeMqttAck(&f, 0x40, m.msg_id);
        SnForward(id, c, f);
        break;
      }
      case sn::kPubrec: {
        std::string f;
        MakeMqttAck(&f, 0x50, m.msg_id);
        SnForward(id, c, f);
        break;
      }
      case sn::kPubrel: {
        std::string f;
        MakeMqttAck(&f, 0x62, m.msg_id);
        SnForward(id, c, f);
        break;
      }
      case sn::kPubcomp: {
        std::string f;
        MakeMqttAck(&f, 0x70, m.msg_id);
        SnForward(id, c, f);
        break;
      }
      case sn::kSubscribe: {
        uint8_t kind = m.flags & 0x3;
        std::string topic;
        uint16_t tid = 0;
        if (kind == sn::kTidPredef) {
          auto pit = sn_predefined_.find(m.topic_id);
          if (pit != sn_predefined_.end()) {
            topic = pit->second;
            tid = m.topic_id;
          }
        } else {
          topic = m.topic_name;
          bool wild = topic.find('+') != std::string::npos ||
                      topic.find('#') != std::string::npos;
          // wildcard filters get no id (delivery auto-registers one)
          tid = (wild || topic.empty()) ? 0 : SnAllocTid(s, topic);
        }
        if (topic.empty()) {
          sn::SnMsg sa;
          sa.type = sn::kSuback;
          sa.flags = m.flags;
          sa.msg_id = m.msg_id;
          sa.rc = sn::kRcInvalidTopicId;
          SnReply(id, c, sa);
          break;
        }
        // grant what delivery honours: SN deliveries cap at qos1
        // (oracle handle_deliver), so the granted qos does too
        int qi = sn::QosOf(m.flags);
        uint8_t qos = qi < 1 ? 0 : 1;
        if (s.sub_tid.size() > 1024) s.sub_tid.clear();
        s.sub_tid[m.msg_id] =
            (static_cast<uint32_t>(m.flags) << 16) | tid;
        std::string body;
        sn::PutBe16(&body, m.msg_id);
        sn::PutBe16(&body, static_cast<uint16_t>(topic.size()));
        body += topic;
        body.push_back(static_cast<char>(qos));
        std::string f;
        BuildMqttFrame(&f, 0x82, body);
        SnForward(id, c, f);  // SUBSCRIBE always runs the Python plane
        break;
      }
      case sn::kUnsubscribe: {
        std::string topic;
        if ((m.flags & 0x3) == sn::kTidPredef) {
          auto pit = sn_predefined_.find(m.topic_id);
          if (pit != sn_predefined_.end()) topic = pit->second;
        } else {
          topic = m.topic_name;
        }
        if (topic.empty()) {
          sn::SnMsg ua;
          ua.type = sn::kUnsuback;
          ua.msg_id = m.msg_id;
          SnReply(id, c, ua);  // the oracle UNSUBACKs regardless
          break;
        }
        std::string body;
        sn::PutBe16(&body, m.msg_id);
        sn::PutBe16(&body, static_cast<uint16_t>(topic.size()));
        body += topic;
        std::string f;
        BuildMqttFrame(&f, 0xA2, body);
        SnForward(id, c, f);
        break;
      }
      default:
        break;  // WILL machinery et al: not served (oracle parity)
    }
  }

  // QoS -1 (§6.8): publish-without-connect on a predefined or short
  // topic. Routed through ONE shared anonymous conn whose synthesized
  // session ("sn-anon") earns publish permits like any client — so a
  // hot QoS -1 topic runs the native fast path after its first pass.
  void SnQosM1(const sn::SnMsg& m) {
    stats_[kStSnQosM1].fetch_add(1, std::memory_order_relaxed);
    uint8_t kind = m.flags & 0x3;
    std::string topic;
    if (kind == sn::kTidPredef) {
      auto it = sn_predefined_.find(m.topic_id);
      if (it == sn_predefined_.end()) return;  // fire-and-forget: drop
      topic = it->second;
    } else if (kind == sn::kTidShort) {
      topic.push_back(static_cast<char>(m.topic_id >> 8));
      topic.push_back(static_cast<char>(m.topic_id & 0xFF));
    } else {
      return;  // NORMAL ids need a connection's registry (oracle)
    }
    uint64_t id = EnsureSnAnon();
    auto it = conns_.find(id);
    if (it == conns_.end()) return;
    std::string body;
    sn::PutBe16(&body, static_cast<uint16_t>(topic.size()));
    body += topic;
    body += m.data;
    uint8_t h = static_cast<uint8_t>(
        0x30 | ((m.flags & sn::kFRetain) ? 1 : 0));
    std::string f;
    BuildMqttFrame(&f, h, body);
    SnForward(id, it->second, f);
  }

  uint64_t EnsureSnAnon() {
    if (sn_anon_id_ && conns_.count(sn_anon_id_)) return sn_anon_id_;
    Conn c;
    c.fd = -1;
    c.framer = Framer(max_size_);
    c.sn = std::make_unique<SnConnState>();
    c.sn->anon = true;
    c.sn->connected = true;
    c.sn->connect_sent = true;
    // per-shard clientid: two shards each minting "sn-anon" would CM-
    // takeover-kick each other's session forever (shard 0 keeps the
    // unsharded name)
    std::string cid = shard_id_ ? "sn-anon-s" + std::to_string(shard_id_)
                                : "sn-anon";
    c.sn->clientid = cid;
    uint64_t id = kSnConnBit | ShardPrefix() | next_sn_id_++;
    c.sn->conn_id = id;
    auto& cref = conns_.emplace(id, std::move(c)).first->second;
    cref.last_rx_ms = NowMs();
    sn_anon_id_ = id;
    events_.push_back(EncodeRecord(1, id, "sn:anon", 7));
    // synthesize the CONNECT so the Python channel opens a real
    // session; keepalive 0 = the anon publisher never idles out
    std::string body;
    body.push_back(0);
    body.push_back(4);
    body += "MQTT";
    body.push_back(4);
    body.push_back(0x02);
    sn::PutBe16(&body, 0);
    sn::PutBe16(&body, static_cast<uint16_t>(cid.size()));
    body += cid;
    std::string f;
    BuildMqttFrame(&f, 0x10, body);
    SnForward(id, cref, f);
    return id;
  }

  // -- SN egress (MQTT -> SN translation) ---------------------------------

  void SnEgress(Conn& c, const char* data, size_t len) {
    sn_frames_scratch_.clear();
    c.sn->egress.Feed(reinterpret_cast<const uint8_t*>(data), len,
                      &sn_frames_scratch_);
    for (const std::string& f : sn_frames_scratch_)
      SnTranslateEgress(c, f);
    // a CONNACK in this span settles the CONNECT round trip: replay
    // pipelined messages AFTER the scratch loop (dispatch may re-enter
    // egress paths) and after the CONNACK bytes joined the outbuf, so
    // the client sees CONNACK before any REGACK/SUBACK/PUBACK
    if (c.sn->connack_seen && !c.sn->preconn.empty())
      SnDrainPreconn(c.sn->conn_id);
  }

  void SnDrainPreconn(uint64_t id) {
    std::deque<sn::SnMsg> q;
    {
      auto it = conns_.find(id);
      if (it == conns_.end() || !it->second.sn) return;
      q.swap(it->second.sn->preconn);
    }
    for (sn::SnMsg& m : q) {
      // re-find each round: a dispatched PUBLISH can rehash conns_
      auto it = conns_.find(id);
      if (it == conns_.end() || !it->second.sn) return;
      Conn& c = it->second;
      if (c.sn->connected) {
        SnDispatch(id, c, m);
      } else {
        // CONNACK was a reject: the oracle answers each post-CONNECT
        // message in the not-connected state with DISCONNECT
        sn::SnMsg d;
        d.type = sn::kDisconnect;
        SnReply(id, c, d);
      }
    }
  }

  void SnTranslateEgress(Conn& c, const std::string& f) {
    SnConnState& s = *c.sn;
    uint8_t type = static_cast<uint8_t>(f[0]) >> 4;
    size_t pos = 1;
    while (pos < f.size() && (static_cast<uint8_t>(f[pos]) & 0x80)) pos++;
    pos++;  // first body byte
    auto pid_at = [&](size_t at) -> uint16_t {
      if (at + 2 > f.size()) return 0;
      return static_cast<uint16_t>(
          (static_cast<uint8_t>(f[at]) << 8) |
          static_cast<uint8_t>(f[at + 1]));
    };
    sn::SnMsg m;
    switch (type) {
      case 2: {  // CONNACK
        if (pos + 2 > f.size()) return;
        uint8_t rc = static_cast<uint8_t>(f[pos + 1]);
        s.connack_seen = true;
        if (rc == 0) s.connected = true;
        m.type = sn::kConnack;
        m.rc = rc ? sn::kRcNotSupported : sn::kRcAccepted;
        break;
      }
      case 3: {  // PUBLISH: a Python-plane delivery for this SN client
        uint8_t h = static_cast<uint8_t>(f[0]);
        uint8_t qos = (h >> 1) & 3;
        if (pos + 2 > f.size()) return;
        uint16_t tlen = pid_at(pos);
        pos += 2;
        if (pos + tlen > f.size()) return;
        std::string_view topic(f.data() + pos, tlen);
        pos += tlen;
        uint16_t pid = 0;
        if (qos) {
          pid = pid_at(pos);
          pos += 2;
          if (pos > f.size()) return;
        }
        std::string_view payload(f.data() + pos, f.size() - pos);
        // the oracle's delivery cap: SN PUBLISHes never exceed qos1
        SnDeliverPublish(c, topic, payload, qos > 1 ? 1 : qos,
                         (h & 1) != 0, (h & 8) != 0, pid);
        return;
      }
      case 4: {  // PUBACK: needs the topic id the MQTT ack dropped
        uint16_t pid = pid_at(pos);
        m.type = sn::kPuback;
        m.msg_id = pid;
        m.rc = sn::kRcAccepted;
        auto it = s.pub_tid.find(pid);
        if (it != s.pub_tid.end()) {
          m.topic_id = it->second;
          s.pub_tid.erase(it);
        }
        break;
      }
      case 5:
        m.type = sn::kPubrec;
        m.msg_id = pid_at(pos);
        break;
      case 6:
        m.type = sn::kPubrel;
        m.msg_id = pid_at(pos);
        break;
      case 7:
        m.type = sn::kPubcomp;
        m.msg_id = pid_at(pos);
        s.pub_tid.erase(m.msg_id);  // the qos2 ingest entry retires here
        break;
      case 9: {  // SUBACK
        uint16_t pid = pid_at(pos);
        uint8_t rc = static_cast<uint8_t>(f.back());
        m.type = sn::kSuback;
        m.msg_id = pid;
        uint32_t ctx2 = 0;
        auto it = s.sub_tid.find(pid);
        if (it != s.sub_tid.end()) {
          ctx2 = it->second;
          s.sub_tid.erase(it);
        }
        if (rc >= 0x80) {
          // denied: echo the REQUEST flags, tid 0 (oracle shape)
          m.flags = static_cast<uint8_t>(ctx2 >> 16);
          m.topic_id = 0;
          m.rc = sn::kRcNotSupported;
        } else {
          m.flags = sn::QosFlags(rc);
          m.topic_id = static_cast<uint16_t>(ctx2 & 0xFFFF);
          m.rc = sn::kRcAccepted;
        }
        break;
      }
      case 11:
        m.type = sn::kUnsuback;
        m.msg_id = pid_at(pos);
        break;
      case 13:
        m.type = sn::kPingResp;
        break;
      case 14:
        m.type = sn::kDisconnect;
        break;
      default:
        return;  // nothing else egresses to an SN client
    }
    std::string dg;
    sn::Serialize(m, &dg);
    c.outbuf += dg;  // control answers bypass the sleep buffer
  }

  // -- SN delivery encode -------------------------------------------------

  void SnOut(Conn& c, const std::string& dgram) {
    SnConnState& s = *c.sn;
    if (!s.awake) {
      // asleep (radio off): park until the next PINGREQ, bounded
      // drop-oldest like the session mqueue (oracle parity). Oldest
      // means oldest PUBLISH — evicting a parked auto-REGISTER while
      // keeping its paired PUBLISH would leave the client holding
      // deliveries on a topic id it never learned, undecodable for
      // the rest of the session (the oracle is immune: it parks
      // deliveries pre-encoding and auto-registers at wake).
      if (s.sleep_buf.size() >= kMaxPending) {
        auto vic = s.sleep_buf.begin();
        for (; vic != s.sleep_buf.end(); ++vic) {
          const std::string& d = *vic;
          size_t toff = static_cast<uint8_t>(d[0]) == 1 ? 3 : 1;
          if (toff < d.size() &&
              static_cast<uint8_t>(d[toff]) != sn::kRegister)
            break;
        }
        s.sleep_buf.erase(vic == s.sleep_buf.end() ? s.sleep_buf.begin()
                                                   : vic);
      }
      s.sleep_buf.push_back(dgram);
      stats_[kStSnSleepParked].fetch_add(1, std::memory_order_relaxed);
      return;
    }
    c.outbuf += dgram;
  }

  // Resolve (auto-registering) the NORMAL topic id a delivery needs —
  // the REGISTER goes out (or parks) ahead of the PUBLISH, so the
  // client can decode the id (oracle handle_deliver).
  uint16_t SnDeliverTid(Conn& c, std::string_view topic) {
    SnConnState& s = *c.sn;
    if (topic.size() > sn::kMaxTopic) return 0;  // REGISTER can't frame it
    std::string key(topic);
    auto it = s.id_of_topic.find(key);
    if (it != s.id_of_topic.end()) return it->second;
    uint16_t tid = SnAllocTid(s, key);
    if (!tid) return 0;  // registry full: nothing deliverable
    sn::SnMsg rg;
    rg.type = sn::kRegister;
    rg.topic_id = tid;
    rg.msg_id = SnNextMid(s);
    rg.topic_name = key;
    std::string dg;
    sn::Serialize(rg, &dg);
    SnOut(c, dg);
    return tid;
  }

  void SnDeliverPublish(Conn& c, std::string_view topic,
                        std::string_view payload, uint8_t qos, bool retain,
                        bool dup, uint16_t pid) {
    if (payload.size() > sn::kMaxPayload) {
      // exceeds the SN u16 wire limit: drop, never truncate the length
      stats_[kStSnDropsOversize].fetch_add(1, std::memory_order_relaxed);
      return;
    }
    uint16_t tid = SnDeliverTid(c, topic);
    if (!tid) return;
    uint8_t flags = sn::QosFlags(qos);
    if (retain) flags |= sn::kFRetain;
    if (dup) flags |= sn::kFDup;
    std::string dg;
    sn::BuildPublish(&dg, flags, tid, qos ? pid : 0, payload, nullptr,
                     nullptr);
    stats_[kStSnOut].fetch_add(1, std::memory_order_relaxed);
    stats_[kStFastBytesOut].fetch_add(dg.size(),
                                      std::memory_order_relaxed);
    SnOut(c, dg);
  }

  void SnRexmitTrack(uint64_t id, Conn& c, uint16_t pid, std::string dgram,
                     size_t flags_off) {
    uint64_t now = NowMs();
    c.sn->rexmit.push_back({pid, std::move(dgram), flags_off, now, 0});
    // the wheel replaced the per-cycle scan: one deadline per conn,
    // parked while the client announced sleep (armed again at wake)
    if (!c.sn->tm_rexmit && c.sn->awake)
      c.sn->tm_rexmit = wheel_.Arm(id, kTmSnRexmit, now + kSnRetryMs);
  }

  void SnRexmitAck(uint64_t id, SnConnState& s, uint16_t pid) {
    auto& rx = s.rexmit;
    for (size_t i = 0; i < rx.size(); i++) {
      if (rx[i].pid != pid) continue;
      rx[i] = std::move(rx.back());
      rx.pop_back();
      break;
    }
    if (rx.empty() && s.tm_rexmit) {
      wheel_.Cancel(s.tm_rexmit);
      s.tm_rexmit = 0;
    }
  }

  // qos1 fast-path delivery to an SN subscriber: SN framing + the SAME
  // AckState window/pending machinery as TCP, plus a retransmit copy
  // (UDP loses datagrams; the inflight bitmap is the authority the
  // timeout scan reads). Returns whether a delivery/admit happened.
  // 0 = dropped, 1 = written to the outbuf, 2 = parked in the window
  // queue (the caller must NOT count kStFastOut — the dequeue does)
  int SnDeliverElevated(uint64_t owner, Conn& t, std::string_view topic,
                        std::string_view payload, bool retain) {
    if (payload.size() > sn::kMaxPayload) {
      // exceeds the SN u16 wire limit: drop, never truncate the length
      stats_[kStSnDropsOversize].fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
    AckState& a = EnsureAck(t);
    uint16_t tid = SnDeliverTid(t, topic);
    if (!tid) return 0;
    uint8_t flags = sn::QosFlags(1);
    if (retain) flags |= sn::kFRetain;
    if (a.inflight_cnt >= t.max_inflight) {
      // receive window full: queue (the mqueue), drop on overflow —
      // the parked copy is a whole SN datagram with a zero msg id the
      // dequeue patches (DrainPending's SN branch)
      if (a.pending.size() >= kMaxPending) {
        stats_[kStDropsInflight].fetch_add(1, std::memory_order_relaxed);
        if (telemetry_) FrNote(t, kFrDrop, 3, 1, cur_hash_);
        return 0;
      }
      std::string dg;
      size_t fo, mo;
      sn::BuildPublish(&dg, flags, tid, 0, payload, &fo, &mo);
      a.pending.emplace_back(std::move(dg), mo);
      AckNote(owner, a);
      return 2;
    }
    uint16_t tp = NextPid(a);
    std::string dg;
    size_t fo, mo;
    sn::BuildPublish(&dg, flags, tid, tp, payload, &fo, &mo);
    if (telemetry_) {
      if (a.rtt.size() < kRttSamples)
        a.rtt.push_back({NowNs(), std::string(topic), tp, 1,
                         cur_trace_});
      FrNote(t, kFrDeliver, 3, tp, cur_hash_);
    }
    stats_[kStSnOut].fetch_add(1, std::memory_order_relaxed);
    stats_[kStFastBytesOut].fetch_add(dg.size(),
                                      std::memory_order_relaxed);
    SnOut(t, dg);
    SnRexmitTrack(owner, t, tp, std::move(dg), fo);
    AckNote(owner, a);
    return 1;
  }

  // Timeout scan (~4/s, gated on any tracked delivery existing):
  // resend with DUP, abandon after kSnMaxRetries freeing the window
  // slot exactly as a PUBACK would.
  // Datagram egress: outbuf holds whole self-delimiting SN messages.
  // Consecutive messages pack into aggregate datagrams up to
  // sn::kPackDatagram (the peer's ParseAll loop decodes them all from
  // one recv), and up to kSnSendBatch aggregates go out per sendmmsg —
  // two layers of syscall amortization, because a per-message sendto
  // costs ~65us on sandboxed kernels. EAGAIN keeps the tail for a
  // later flush; other send errors (ICMP unreachable) drop one
  // aggregate and keep going — UDP semantics.
  static constexpr int kSnSendBatch = 16;

  void SnFlush(uint64_t id, Conn& c) {
    SnConnState& s = *c.sn;
    if (s.anon) {
      // the shared QoS -1 publisher has no peer to answer
      c.outbuf.clear();
      c.outpos = 0;
      if (c.want_close) Drop(id, "closed_by_host", false);
      return;
    }
    while (c.outpos < c.outbuf.size()) {
      // carve the pending range into packed spans at message bounds
      iovec iov[kSnSendBatch];
      mmsghdr mm[kSnSendBatch];
      size_t span_end[kSnSendBatch];
      int nspan = 0;
      size_t pos = c.outpos;
      bool corrupt = false;
      while (pos < c.outbuf.size() && nspan < kSnSendBatch) {
        size_t start = pos;
        while (pos < c.outbuf.size()) {
          uint8_t b0 = static_cast<uint8_t>(c.outbuf[pos]);
          size_t dlen;
          if (b0 == 1) {
            if (pos + 3 > c.outbuf.size()) {
              corrupt = true;  // torn prefix: whole messages only live here
              break;
            }
            dlen = (static_cast<uint8_t>(c.outbuf[pos + 1]) << 8) |
                   static_cast<uint8_t>(c.outbuf[pos + 2]);
          } else {
            dlen = b0;
          }
          if (dlen < 2 || pos + dlen > c.outbuf.size()) {
            corrupt = true;  // never spin on bad framing
            break;
          }
          if (pos > start && pos + dlen - start > sn::kPackDatagram)
            break;  // aggregate full; oversized singles go out alone
          pos += dlen;
        }
        if (pos == start) break;  // corrupt head, nothing to carve
        iov[nspan].iov_base = const_cast<char*>(c.outbuf.data() + start);
        iov[nspan].iov_len = pos - start;
        memset(&mm[nspan].msg_hdr, 0, sizeof(mm[nspan].msg_hdr));
        mm[nspan].msg_hdr.msg_name = &s.addr;
        mm[nspan].msg_hdr.msg_namelen = sizeof(s.addr);
        mm[nspan].msg_hdr.msg_iov = &iov[nspan];
        mm[nspan].msg_hdr.msg_iovlen = 1;
        span_end[nspan] = pos;
        nspan++;
        if (corrupt) break;  // send what precedes the corrupt boundary
      }
      if (nspan == 0) {
        if (corrupt) {  // bad framing at the head: never spin on it
          c.outbuf.clear();
          c.outpos = 0;
        }
        break;
      }
      int sentn = sendmmsg(sn_fd_, mm, nspan, MSG_NOSIGNAL);
      if (sentn < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        c.outpos = span_end[0];  // drop one aggregate, keep going
        continue;
      }
      c.outpos = span_end[sentn - 1];
      // partial send or a corrupt boundary: loop — the next carve either
      // retries the remainder or clears the corrupt head above
    }
    if (c.outpos >= c.outbuf.size()) {
      c.outbuf.clear();
      c.outpos = 0;
    }
    if (c.want_close && c.outbuf.empty())
      Drop(id, "closed_by_host", false);
  }

  // -- native CoAP gateway (round 19) -------------------------------------
  // RFC 7252 terminates in the host: datagrams decode with the shared
  // coap.h codec on the SN plane's listener machinery (recvmmsg
  // ingress, batched sendmmsg egress, per-peer conns in their own
  // id namespace), the /ps pub-sub surface translates into MQTT
  // frames that ride TryFast / the Python channel exactly like SN
  // bytes, and observe notifications resolve host-side on the
  // delivery seam (per-observer 24-bit sequences; CON mode on the
  // native ack plane with wheel-driven RFC 7252 backoff). The asyncio
  // gateway (gateway/coap.py) stays the protocol oracle; any exchange
  // outside the native vocabulary — block-wise transfers,
  // props-carrying retained reads, non-/ps paths (the LwM2M seam) —
  // degrades WHOLE to it as a kind-13 event, never a partial set.

  static constexpr int kCoapRecvBatch = 32;
  static constexpr size_t kCoapRecvBuf = 65536;  // UDP max: no truncation
  static constexpr size_t kCoapSeenMax = 8192;   // MID dedup entries/conn
  static constexpr size_t kCoapNotifyObsMax = 512;  // RST-cancel history
  static constexpr size_t kCoapBlock2Threshold = 1024;  // oracle's
                                                        // block2_size

  void CoapRead() {
    if (coap_rx_buf_.empty())
      coap_rx_buf_.resize(kCoapRecvBatch * kCoapRecvBuf);
    mmsghdr mm[kCoapRecvBatch];
    iovec iov[kCoapRecvBatch];
    sockaddr_in peers[kCoapRecvBatch];
    // bounded per cycle so a CoAP blast cannot starve the TCP/WS side
    for (int budget = 0; budget < 4096; budget += kCoapRecvBatch) {
      for (int i = 0; i < kCoapRecvBatch; i++) {
        iov[i].iov_base = coap_rx_buf_.data() + i * kCoapRecvBuf;
        iov[i].iov_len = kCoapRecvBuf;
        memset(&mm[i].msg_hdr, 0, sizeof(mm[i].msg_hdr));
        mm[i].msg_hdr.msg_name = &peers[i];
        mm[i].msg_hdr.msg_namelen = sizeof(peers[i]);
        mm[i].msg_hdr.msg_iov = &iov[i];
        mm[i].msg_hdr.msg_iovlen = 1;
      }
      int n = recvmmsg(coap_fd_, mm, kCoapRecvBatch, 0, nullptr);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;  // EAGAIN: drained
      }
      for (int i = 0; i < n; i++) {
        if (mm[i].msg_len == 0) continue;
        const uint8_t* d = coap_rx_buf_.data() + i * kCoapRecvBuf;
        // a fired read fault LOSES the datagram (errno and blackhole
        // alike: UDP's loss shape), scoped to the peer's conn
        // @fault(conn_read) — the CoAP datagram-ingress seam
        if (fault_.armed(fault::kSiteConnRead)) {
          auto ait = coap_addr_conn_.find(SnAddrKey(peers[i]));
          uint64_t fkey = ait == coap_addr_conn_.end() ? 0 : ait->second;
          if (fault_.Fire(fault::kSiteConnRead, fkey)) {
            FaultNote(fault::kSiteConnRead);
            continue;
          }
        }
        if (telemetry_ && ((++tele_tick_coap_ & tele_mask_) == 0)) {
          uint64_t t0 = NowNs();
          CoapIngest(peers[i], d, mm[i].msg_len);
          RecordHist(kHistCoapIngest, NowNs() - t0);
        } else {
          CoapIngest(peers[i], d, mm[i].msg_len);
        }
      }
      if (n < kCoapRecvBatch) break;  // drained
    }
    FlushDirty();
  }

  void CoapIngest(const sockaddr_in& peer, const uint8_t* data,
                  size_t len) {
    coap::CoapMsg m;
    if (!coap::Parse(data, len, &m)) return;  // the oracle drops it too
    uint64_t key = SnAddrKey(peer);
    auto ait = coap_addr_conn_.find(key);
    uint64_t id;
    if (ait != coap_addr_conn_.end() && conns_.count(ait->second)) {
      id = ait->second;
    } else {
      // only REQUESTS (and pings) mint endpoint state: a bare ACK/RST
      // from an unknown peer settles nothing, and letting reflected
      // garbage fill the conn table would be an amplification surface
      bool request = (m.type == coap::kCon || m.type == coap::kNon) &&
                     m.code >= coap::kGet && m.code <= 0x1F;
      bool ping = m.type == coap::kCon && m.code == coap::kEmpty;
      if (!request && !ping) return;
      if (conns_.size() >= max_conns_) return;  // esockd max-conn
      id = CoapNewConn(peer);
    }
    auto it = conns_.find(id);
    if (it == conns_.end()) return;
    it->second.last_rx_ms = NowMs();
    CoapHandle(id, it->second, m, data, len);
  }

  uint64_t CoapNewConn(const sockaddr_in& peer) {
    Conn c;
    c.fd = -1;  // egress rides sendmmsg on the shared UDP socket
    c.framer = Framer(max_size_);
    c.coap = std::make_unique<CoapConnState>();
    c.coap->addr = peer;
    uint64_t id = kCoapConnBit | ShardPrefix() | next_coap_id_++;
    c.coap->conn_id = id;
    auto& cref = conns_.emplace(id, std::move(c)).first->second;
    coap_addr_conn_[SnAddrKey(peer)] = id;
    uint64_t now = NowMs();
    cref.last_rx_ms = now;
    // connectionless transport: reap silent endpoints like the asyncio
    // UDP listener's 300s idle default (a later translated CONNECT
    // re-arms the real deadline through set_keepalive)
    cref.keepalive_ms = 300000;
    cref.tm_keepalive = wheel_.Arm(id, kTmKeepalive, now + 300000);
    FrNote(cref, kFrOpen, 0, 3);  // arg 3 = CoAP transport
    char ip[INET_ADDRSTRLEN] = "?";
    inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
    std::string info = std::string("coap:") + ip + ":" +
                       std::to_string(ntohs(peer.sin_port));
    events_.push_back(EncodeRecord(1, id, info.data(), info.size()));
    return id;
  }

  // Frame one CoAP message into the conn outbuf. CoAP messages are not
  // self-delimiting (the datagram boundary is the delimiter), so an
  // internal [u16 len] prefix carries each message to CoapFlush, which
  // re-establishes the boundaries with one datagram per message.
  void CoapOut(Conn& c, const std::string& dgram) {
    c.outbuf.push_back(static_cast<char>(dgram.size() >> 8));
    c.outbuf.push_back(static_cast<char>(dgram.size() & 0xFF));
    c.outbuf += dgram;
  }

  static coap::CoapMsg CoapResp(const coap::CoapMsg& req, uint8_t code) {
    coap::CoapMsg r;
    r.type = req.type == coap::kCon ? coap::kAck : coap::kNon;
    r.code = code;
    r.mid = req.mid;
    r.token = req.token;
    return r;
  }

  // Serialize + emit one response, caching the bytes in the MID dedup
  // window so a retransmitted request replays them (oracle remember).
  void CoapReply(uint64_t id, Conn& c, const coap::CoapMsg& resp) {
    std::string dg;
    coap::Serialize(resp, &dg);
    auto it = c.coap->seen.find(resp.mid);
    if (it != c.coap->seen.end()) it->second.response = dg;
    CoapOut(c, dg);
    MarkDirty(id, c);
  }

  uint16_t CoapNextMid(CoapConnState& s) {
    s.next_mid = static_cast<uint16_t>(s.next_mid % 0xFFFF + 1);
    return s.next_mid;
  }

  uint16_t CoapNextMqttMid(CoapConnState& s) {
    s.next_mqtt_mid = static_cast<uint16_t>(s.next_mqtt_mid % 0xFFFF + 1);
    return s.next_mqtt_mid;
  }

  void CoapHandle(uint64_t id, Conn& c, coap::CoapMsg& m,
                  const uint8_t* raw, size_t len) {
    CoapConnState& s = *c.coap;
    if (m.type == coap::kCon && m.code == coap::kEmpty) {
      // CoAP ping (§4.3): pong with RST. The client's mid space is
      // independent of ours — it must NOT settle a pending notify
      // that happens to share the number (oracle parity).
      stats_[kStCoapPings].fetch_add(1, std::memory_order_relaxed);
      coap::CoapMsg r;
      r.type = coap::kRst;
      r.mid = m.mid;
      std::string dg;
      coap::Serialize(r, &dg);
      CoapOut(c, dg);
      MarkDirty(id, c);
      return;
    }
    if (m.type == coap::kAck || m.type == coap::kRst) {
      CoapSettle(id, c, m, raw, len);
      return;
    }
    if (m.code == coap::kEmpty) return;  // NON empty: nothing to do
    if (m.code >= 0x20) return;  // a response class from a client
    // the native-vs-oracle decision comes BEFORE any side effect —
    // one exchange is served whole by exactly one plane
    if (!CoapEligible(m)) {
      CoapPunt(id, c, raw, len);
      return;
    }
    // inbound MID dedup (the oracle's parity-audited window): a
    // byte-identical retransmission replays the cached response; a
    // recycled mid (different token) evicts and runs fresh
    auto sit = s.seen.find(m.mid);
    if (sit != s.seen.end()) {
      if (NowMs() >= sit->second.expire_ms ||
          sit->second.token != m.token) {
        s.seen.erase(sit);
      } else {
        stats_[kStCoapDedupHits].fetch_add(1, std::memory_order_relaxed);
        if (!sit->second.response.empty()) {
          CoapOut(c, sit->second.response);
          MarkDirty(id, c);
        }
        return;  // response still in flight: drop the retransmission
      }
    }
    CoapSeenInsert(s, m);
    CoapServe(id, c, m);
  }

  void CoapSeenInsert(CoapConnState& s, const coap::CoapMsg& m) {
    uint64_t life = m.type == coap::kCon ? coap::kExchangeLifetimeMs
                                         : coap::kNonLifetimeMs;
    uint64_t now = NowMs();
    // over the bound, evict OLDEST-INSERTED first (amortized O(1) via
    // the fifo — a sustained NON blast wraps the 16-bit mid space well
    // inside the RFC lifetimes, and bounded memory beats a perfect
    // replay window there; the natural-expiry case never gets here)
    while (s.seen.size() >= kCoapSeenMax && !s.seen_fifo.empty()) {
      s.seen.erase(s.seen_fifo.front());
      s.seen_fifo.pop_front();
    }
    s.seen[m.mid] = {m.token, "", now + life};
    s.seen_fifo.push_back(m.mid);
    // the fifo tolerates stale mids (recycled entries); cap its drift
    if (s.seen_fifo.size() > 2 * kCoapSeenMax) {
      std::deque<uint16_t> fresh;
      for (uint16_t mid : s.seen_fifo)
        if (s.seen.count(mid) &&
            (fresh.empty() || fresh.back() != mid))
          fresh.push_back(mid);
      s.seen_fifo.swap(fresh);
    }
  }

  // ACK/RST for a message WE originated (a CON notify): settle the
  // retransmit copy — the CoAP ACK is the delivery ack, so a tracked
  // pid routes as a synthesized MQTT PUBACK (native pids consume in
  // TryFastPuback, Python pids forward to the session). RST cancels
  // the observation for ANY notification type (RFC 7641 §3.6). Mids
  // unknown to the native plane route to the Python oracle when it has
  // ever served this endpoint (its own CON commands — e.g. LwM2M
  // downlinks — are tracked there).
  void CoapSettle(uint64_t id, Conn& c, const coap::CoapMsg& m,
                  const uint8_t* raw, size_t len) {
    CoapConnState& s = *c.coap;
    bool known = false;
    auto& rx = s.rexmit;
    for (size_t i = 0; i < rx.size(); i++) {
      if (rx[i].mid != m.mid) continue;
      known = true;
      uint16_t pid = rx[i].pid;
      std::string filter = std::move(rx[i].filter);
      rx[i] = std::move(rx.back());
      rx.pop_back();
      if (rx.empty() && s.tm_notify) {
        wheel_.Cancel(s.tm_notify);
        s.tm_notify = 0;
      }
      if (pid) {
        std::string f;
        MakeMqttAck(&f, 0x40, pid);
        SnForward(id, c, f);
      }
      if (m.type == coap::kRst) CoapCancelObserve(id, c, filter);
      break;
    }
    auto nit = s.notify_obs.find(m.mid);
    if (nit != s.notify_obs.end()) {
      known = true;
      if (m.type == coap::kRst) CoapCancelObserve(id, c, nit->second);
      s.notify_obs.erase(nit);
    }
    if (!known && s.oracle_used) CoapPunt(id, c, raw, len);
  }

  // Drop one observation: remove the observer entry and release the
  // broker subscription through the SAME seam a client unobserve takes
  // (a synthesized MQTT UNSUBSCRIBE — the Python session owns the
  // subscription state; the match-table entry tears down through it).
  void CoapCancelObserve(uint64_t id, Conn& c, const std::string& filter) {
    CoapConnState& s = *c.coap;
    bool found = false;
    for (size_t i = 0; i < s.observers.size(); i++) {
      if (s.observers[i].filter != filter) continue;
      s.observers[i] = std::move(s.observers.back());
      s.observers.pop_back();
      found = true;
      break;
    }
    if (!found || !s.connected) return;
    std::string body;
    sn::PutBe16(&body, CoapNextMqttMid(s));
    sn::PutBe16(&body, static_cast<uint16_t>(filter.size()));
    body += filter;
    std::string f;
    BuildMqttFrame(&f, 0xA2, body);
    SnForward(id, c, f);  // the UNSUBACK egress is swallowed
  }

  // The native-vocabulary test: everything this rejects is served
  // WHOLE by the Python oracle (gateway/coap.py or the configured
  // channel) — block-wise transfers and any other unknown option,
  // plain reads while the retained mirror is incomplete (v5 props),
  // and non-/ps paths including the LwM2M /rd surface. Decided before
  // ANY side effect, so an exchange never splits across planes.
  // @admit-check
  bool CoapEligible(const coap::CoapMsg& m) {
    bool first_seen = false, first_is_ps = false;
    for (const auto& [n, v] : m.options) {
      if (n == coap::kOptUriPath) {
        if (!first_seen) {
          first_seen = true;
          first_is_ps = v == "ps";
        }
      } else if (n != coap::kOptObserve && n != coap::kOptUriQuery &&
                 n != coap::kOptContentFormat) {
        return false;  // Block1/Block2/ETag/...: oracle vocabulary
      }
    }
    if (!first_is_ps) return false;  // /rd et al -> the oracle channel
    if (m.code == coap::kGet && coap::ObserveOf(m) < 0 &&
        !coap_retain_complete_)
      return false;  // plain read with an incomplete retained mirror
    return true;
  }

  // Degrade one exchange WHOLE to the Python oracle (kind 13): the raw
  // datagram ships verbatim; gateway/coap.py (or the configured
  // oracle channel — LwM2M) parses, dedups, executes, and answers
  // through emqx_host_coap_send. The native plane took no side effect
  // for it — never a partial exchange.
  void CoapPunt(uint64_t id, Conn& c, const uint8_t* raw, size_t len) {
    c.coap->oracle_used = true;
    stats_[kStCoapPunts].fetch_add(1, std::memory_order_relaxed);
    FrNote(c, kFrPunt, 0, static_cast<uint16_t>(len & 0xFFFF));
    events_.push_back(EncodeRecord(
        13, id, reinterpret_cast<const char*>(raw), len));
  }

  // Execute one admitted (native-vocabulary) request — the oracle's
  // _handle_request shape. Requests arriving before the translated
  // CONNECT's CONNACK park in preconn and replay through here; the
  // drain (and every other caller) re-runs CoapEligible first.
  // @admit-gated
  void CoapServe(uint64_t id, Conn& c, coap::CoapMsg& m) {
    CoapConnState& s = *c.coap;
    coap_path_scratch_.clear();
    coap::JoinPath(m, &coap_path_scratch_);
    std::string topic;  // "/".join(path[1:]), the oracle's topic
    for (size_t i = 1; i < coap_path_scratch_.size(); i++) {
      if (i > 1) topic += '/';
      topic.append(coap_path_scratch_[i].data(),
                   coap_path_scratch_[i].size());
    }
    if (topic.empty()) {
      CoapReply(id, c, CoapResp(m, coap::kBadRequest));
      return;
    }
    if (!s.connected) {
      if (s.connect_sent && !s.connack_seen) {
        // CONNECT in flight to the Python channel: requests pipelined
        // into the round trip park and replay after the CONNACK (the
        // oracle registers synchronously, so they must be served)
        if (s.preconn.size() < kSnPreconnMax)
          s.preconn.push_back(std::move(m));
        return;
      }
      if (s.connack_seen) {
        // rejected CONNACK: denied auth (oracle UNAUTHORIZED parity);
        // the Python channel is tearing this conn down
        CoapReply(id, c, CoapResp(m, coap::kUnauthorized));
        return;
      }
      CoapConnect(id, c, m);
      auto it = conns_.find(id);
      if (it != conns_.end() && it->second.coap)
        it->second.coap->preconn.push_back(std::move(m));
      return;
    }
    std::string_view want;
    if (coap::Query(m, "clientid", &want) && !want.empty() &&
        want != s.clientid) {
      // the peer RE-REGISTERS under a new identity: the old session's
      // observers must not leak into the new one and the new clientid
      // must be re-authenticated (the parity-audited oracle fix; the
      // SN re-CONNECT discipline — the addr slot moves to a successor
      // conn, the old one keeps draining)
      // the successor conn pays the same admission the first datagram
      // did (review finding: an endpoint flipping identities at the
      // cap must not grow the table past max_conns_ while its old
      // conns drain) — at the cap the request drops like any other
      // over-cap datagram and the client's retransmit retries
      if (conns_.size() >= max_conns_) return;
      CoapSeen carry{};
      auto old_seen = s.seen.find(m.mid);
      bool have_seen = old_seen != s.seen.end();
      if (have_seen) carry = old_seen->second;
      sockaddr_in peer = s.addr;
      coap_addr_conn_.erase(SnAddrKey(peer));
      std::string f;
      f.push_back(static_cast<char>(0xE0));
      f.push_back(0);
      SnForward(id, c, f);  // Python closes the old session
      // conns_ may rehash on the emplace: no Conn& use after this
      uint64_t nid = CoapNewConn(peer);
      auto nit = conns_.find(nid);
      if (nit != conns_.end() && nit->second.coap) {
        // the dedup entry follows the exchange to the successor conn
        // (a retransmission must not re-execute on the new identity)
        if (have_seen) nit->second.coap->seen[m.mid] = carry;
        CoapConnect(nid, nit->second, m);
        nit->second.coap->preconn.push_back(std::move(m));
      }
      return;
    }
    CoapExecute(id, c, m, topic);
  }

  void CoapExecute(uint64_t id, Conn& c, coap::CoapMsg& m,
                   const std::string& topic) {
    CoapConnState& s = *c.coap;
    if (m.code == coap::kPut || m.code == coap::kPost) {
      // publish: qos/retain from the Uri-Query (oracle parity). A
      // qos>=1 publish answers 2.04 only when its MQTT ack lands —
      // the native ack plane gates the CoAP response (CON reliability
      // means "the broker has it", not "the gateway heard it")
      std::string_view qv;
      uint8_t qos = 0;
      if (coap::Query(m, "qos", &qv) && !qv.empty() && qv[0] >= '0' &&
          qv[0] <= '2')
        qos = static_cast<uint8_t>(qv[0] - '0');
      std::string_view rv;
      bool retain =
          coap::Query(m, "retain", &rv) && (rv == "true" || rv == "1");
      stats_[kStCoapIn].fetch_add(1, std::memory_order_relaxed);
      uint16_t mqtt_mid = 0;
      if (qos > 0) {
        mqtt_mid = CoapNextMqttMid(s);
        // runaway bound: a client that never sees its 2.04s cannot
        // grow this past the mid space (the SN pub_tid discipline)
        if (s.pending_pub.size() > 8192) s.pending_pub.clear();
        s.pending_pub[mqtt_mid] = {m.mid, m.token,
                                   m.type == coap::kCon};
      }
      std::string body;
      sn::PutBe16(&body, static_cast<uint16_t>(topic.size()));
      body += topic;
      if (qos) sn::PutBe16(&body, mqtt_mid);
      body += m.payload;
      uint8_t h =
          static_cast<uint8_t>(0x30 | (qos << 1) | (retain ? 1 : 0));
      std::string f;
      BuildMqttFrame(&f, h, body);
      SnForward(id, c, f);
      if (qos == 0)  // nothing acks a qos0 publish: answer now
        CoapReply(id, c, CoapResp(m, coap::kChanged));
      return;
    }
    if (m.code == coap::kGet) {
      long obs = coap::ObserveOf(m);
      if (obs == 0) {
        // observe register -> MQTT SUBSCRIBE (always the Python
        // plane, like SN); the observer entry and the 2.05 reply
        // land when the SUBACK egresses
        std::string_view qv;
        uint8_t qos = 0;
        if (coap::Query(m, "qos", &qv) && !qv.empty() &&
            qv[0] >= '0' && qv[0] <= '2')
          qos = static_cast<uint8_t>(qv[0] - '0');
        uint16_t mqtt_mid = CoapNextMqttMid(s);
        if (s.pending_sub.size() > 1024) s.pending_sub.clear();
        s.pending_sub[mqtt_mid] = {m.mid, m.token, topic, qos,
                                   m.type == coap::kCon};
        std::string body;
        sn::PutBe16(&body, mqtt_mid);
        sn::PutBe16(&body, static_cast<uint16_t>(topic.size()));
        body += topic;
        body.push_back(static_cast<char>(qos));
        std::string f;
        BuildMqttFrame(&f, 0x82, body);
        SnForward(id, c, f);
        return;
      }
      if (obs == 1) {
        // deregister: the oracle replies 2.05 whether or not the
        // observation existed
        CoapCancelObserve(id, c, topic);
        CoapReply(id, c, CoapResp(m, coap::kContent));
        return;
      }
      // plain read: latest retained message. The mirror is complete
      // (CoapEligible gated on it); bodies past the oracle's block2
      // threshold degrade the WHOLE exchange to its slicing — decided
      // before any side effect (a read has none)
      retain_scratch_.clear();
      retained_.Match(topic, store::WallMs(), &retain_scratch_);
      if (retain_scratch_.empty()) {
        CoapReply(id, c, CoapResp(m, coap::kNotFound));
        return;
      }
      const RetainEntry* e = retain_scratch_.back();
      if (e->payload.size() > kCoapBlock2Threshold) {
        s.seen.erase(m.mid);  // the oracle owns this exchange's dedup
        std::string raw;
        coap::Serialize(m, &raw);  // codec roundtrips byte-exactly
        CoapPunt(id, c, reinterpret_cast<const uint8_t*>(raw.data()),
                 raw.size());
        return;
      }
      coap::CoapMsg r = CoapResp(m, coap::kContent);
      r.payload = e->payload;
      CoapReply(id, c, r);
      return;
    }
    if (m.code == coap::kDelete) {
      CoapReply(id, c, CoapResp(m, coap::kDeleted));
      return;
    }
    CoapReply(id, c, CoapResp(m, coap::kNotAllowed));
  }

  // Translate the endpoint's registration into an MQTT CONNECT the
  // Python channel owns (auth/CM takeover/hooks exactly like TCP/SN).
  // Identity comes from the Uri-Query (?clientid/?username/?password,
  // the oracle's _ensure_client), defaulting like SnDefaultCid.
  void CoapConnect(uint64_t id, Conn& c, const coap::CoapMsg& m) {
    CoapConnState& s = *c.coap;
    std::string_view cid, user, pass;
    coap::Query(m, "clientid", &cid);
    bool has_user = coap::Query(m, "username", &user);
    bool has_pass = coap::Query(m, "password", &pass);
    if (has_pass && !has_user) {
      has_user = true;  // 3.1.1 forbids password-without-username
      user = std::string_view();
    }
    s.clientid = cid.empty()
                     ? "coap-" + std::to_string(id & 0xFFFFFFFFull)
                     : std::string(cid);
    s.connect_sent = true;
    s.connected = false;
    std::string body;
    body.push_back(0);
    body.push_back(4);
    body += "MQTT";
    body.push_back(4);  // translated CoAP sessions speak MQTT 3.1.1
    uint8_t flags = 0x02;  // clean session: CoAP endpoints are
                           // connectionless; state lives in observers
    if (has_user) flags |= 0x80;
    if (has_pass) flags |= 0x40;
    body.push_back(static_cast<char>(flags));
    sn::PutBe16(&body, 300);  // the asyncio UDP listener's idle default
    sn::PutBe16(&body, static_cast<uint16_t>(s.clientid.size()));
    body += s.clientid;
    if (has_user) {
      sn::PutBe16(&body, static_cast<uint16_t>(user.size()));
      body.append(user.data(), user.size());
    }
    if (has_pass) {
      sn::PutBe16(&body, static_cast<uint16_t>(pass.size()));
      body.append(pass.data(), pass.size());
    }
    std::string f;
    BuildMqttFrame(&f, 0x10, body);
    SnForward(id, c, f);
  }

  void CoapDrainPreconn(uint64_t id) {
    std::deque<coap::CoapMsg> q;
    {
      auto it = conns_.find(id);
      if (it == conns_.end() || !it->second.coap) return;
      q.swap(it->second.coap->preconn);
    }
    for (coap::CoapMsg& m : q) {
      // re-find each round: a dispatched request can rehash conns_
      auto it = conns_.find(id);
      if (it == conns_.end() || !it->second.coap) return;
      Conn& c = it->second;
      if (!c.coap->connected) {
        // the CONNACK was a reject: the oracle answers UNAUTHORIZED
        CoapReply(id, c, CoapResp(m, coap::kUnauthorized));
        continue;
      }
      // the ladder re-decides per parked message (the vocabulary may
      // have narrowed while parked — e.g. the retained mirror went
      // incomplete); parked messages were already dedup-inserted
      if (!CoapEligible(m)) {
        c.coap->seen.erase(m.mid);
        std::string raw;
        coap::Serialize(m, &raw);
        CoapPunt(id, c, reinterpret_cast<const uint8_t*>(raw.data()),
                 raw.size());
        continue;
      }
      CoapServe(id, c, m);
    }
  }

  // -- CoAP egress (MQTT -> CoAP translation) -----------------------------

  void CoapEgress(Conn& c, const char* data, size_t len) {
    // LOCAL frame list: translation re-enters this function on the
    // same conn (PUBREC -> synthesized PUBREL -> PUBCOMP egress), and
    // a member scratch would be cleared mid-iteration (review
    // finding). The swap recycles the member's capacity in the
    // common non-nested case.
    std::vector<std::string> frames;
    frames.swap(coap_frames_scratch_);
    frames.clear();
    c.coap->egress.Feed(reinterpret_cast<const uint8_t*>(data), len,
                        &frames);
    for (const std::string& f : frames) CoapTranslateEgress(c, f);
    frames.clear();
    coap_frames_scratch_.swap(frames);
    // a CONNACK in this span settles the CONNECT round trip: replay
    // parked requests AFTER the scratch loop (dispatch re-enters
    // egress paths) and after the responses above joined the outbuf
    if (c.coap->connack_seen && !c.coap->preconn.empty())
      CoapDrainPreconn(c.coap->conn_id);
  }

  void CoapTranslateEgress(Conn& c, const std::string& f) {
    CoapConnState& s = *c.coap;
    uint8_t type = static_cast<uint8_t>(f[0]) >> 4;
    size_t pos = 1;
    while (pos < f.size() && (static_cast<uint8_t>(f[pos]) & 0x80)) pos++;
    pos++;  // first body byte
    auto pid_at = [&](size_t at) -> uint16_t {
      if (at + 2 > f.size()) return 0;
      return static_cast<uint16_t>((static_cast<uint8_t>(f[at]) << 8) |
                                   static_cast<uint8_t>(f[at + 1]));
    };
    switch (type) {
      case 2: {  // CONNACK: no CoAP analogue — flips the session gate
        if (pos + 2 > f.size()) return;
        s.connack_seen = true;
        if (static_cast<uint8_t>(f[pos + 1]) == 0) s.connected = true;
        return;
      }
      case 3: {  // PUBLISH: a delivery for this endpoint's observers
        uint8_t h = static_cast<uint8_t>(f[0]);
        uint8_t qos = (h >> 1) & 3;
        if (pos + 2 > f.size()) return;
        uint16_t tlen = pid_at(pos);
        pos += 2;
        if (pos + tlen > f.size()) return;
        std::string_view topic(f.data() + pos, tlen);
        pos += tlen;
        uint16_t pid = 0;
        if (qos) {
          pid = pid_at(pos);
          pos += 2;
          if (pos > f.size()) return;
        }
        std::string_view payload(f.data() + pos, f.size() - pos);
        CoapDeliverNotify(c, topic, payload, pid);
        return;
      }
      case 4:  // PUBACK: the client's qos1 publish is done -> 2.04
        CoapPubDone(c, pid_at(pos));
        return;
      case 5: {  // PUBREC: self-complete the qos2 exchange (the CoAP
                 // client knows nothing of the PUBREL leg)
        std::string rel;
        MakeMqttAck(&rel, 0x62, pid_at(pos));
        SnForward(s.conn_id, c, rel);
        return;
      }
      case 7:  // PUBCOMP: the qos2 publish is done -> 2.04
        CoapPubDone(c, pid_at(pos));
        return;
      case 9: {  // SUBACK: complete the observe registration
        uint16_t pid = pid_at(pos);
        auto it = s.pending_sub.find(pid);
        if (it == s.pending_sub.end()) return;
        CoapConnState::PendingSub ctx = it->second;
        s.pending_sub.erase(it);
        // the oracle registers the observer unconditionally (before
        // ctx.subscribe, denied or not) and replies 2.05 regardless —
        // mirror exactly; a same-filter re-register replaces the
        // token/qos and restarts the observation's sequence
        bool replaced = false;
        for (auto& o : s.observers) {
          if (o.filter != ctx.topic) continue;
          o.token = ctx.token;
          o.qos = ctx.qos;
          o.seq = 1;
          replaced = true;
          break;
        }
        if (!replaced)
          s.observers.push_back({ctx.topic, ctx.token, ctx.qos, 1});
        coap::CoapMsg r;
        r.type = ctx.con ? coap::kAck : coap::kNon;
        r.code = coap::kContent;
        r.mid = ctx.mid;
        r.token = ctx.token;
        r.options.emplace_back(coap::kOptObserve,
                               std::string("\x00\x00\x01", 3));
        CoapReply(s.conn_id, c, r);
        return;
      }
      default:
        return;  // UNSUBACK/PINGRESP/DISCONNECT: nothing to translate
    }
  }

  // Shared PUBACK/PUBCOMP tail: the MQTT ack for a translated publish
  // answers the original exchange 2.04 Changed (piggybacked on the
  // CoAP ACK for CON requests — the response rides the ack plane).
  void CoapPubDone(Conn& c, uint16_t pid) {
    CoapConnState& s = *c.coap;
    auto it = s.pending_pub.find(pid);
    if (it == s.pending_pub.end()) return;
    coap::CoapMsg r;
    r.type = it->second.con ? coap::kAck : coap::kNon;
    r.code = coap::kChanged;
    r.mid = it->second.mid;
    r.token = it->second.token;
    s.pending_pub.erase(it);
    CoapReply(s.conn_id, c, r);
  }

  // Resolve + encode one observe notification on the delivery seam.
  // pid != 0 ties the notify to an MQTT window slot (the peer's ACK,
  // by mid, becomes the synthesized PUBACK that frees it). Per-observer
  // 24-bit sequences; oracle parity throughout.
  void CoapDeliverNotify(Conn& c, std::string_view topic,
                         std::string_view payload, uint16_t pid) {
    CoapConnState& s = *c.coap;
    uint64_t t0 = 0;
    if (telemetry_ && ((++tele_tick_notify_ & tele_mask_) == 0))
      t0 = NowNs();
    CoapObserver* obs = nullptr;
    for (auto& o : s.observers) {
      if (coap::TopicMatch(topic, o.filter)) {
        obs = &o;
        break;
      }
    }
    if (obs == nullptr || payload.size() > coap::kMaxPayload) {
      if (obs != nullptr)
        stats_[kStCoapDropsOversize].fetch_add(
            1, std::memory_order_relaxed);
      // a delivery that cannot reach the peer abandons its window
      // slot exactly as an ack would (the SN exhaustion discipline)
      CoapAbandonPid(s.conn_id, c, pid);
      return;
    }
    obs->seq = (obs->seq + 1) & 0xFFFFFF;
    uint16_t mid = CoapNextMid(s);
    // CON-vs-NON follows the OBSERVER's subscription qos (the oracle's
    // notify_type rule — even a qos0-published message notifies a
    // qos>=1 observation as a tracked CON; pid 0 just means there is
    // no window slot to settle when it resolves)
    uint8_t mtype = obs->qos ? coap::kCon : coap::kNon;
    std::string dg;
    coap::BuildNotify(&dg, mtype, mid, obs->token, obs->seq, payload);
    // the RST-cancel map covers NON notifies too (RFC 7641 §3.6);
    // bounded — but never evict a mid whose CON still awaits its ACK
    // (losing it would orphan the give-up/RST cancel path)
    if (s.notify_obs.size() >= kCoapNotifyObsMax) {
      for (auto it = s.notify_obs.begin(); it != s.notify_obs.end();
           ++it) {
        bool tracked = false;
        for (const auto& r : s.rexmit)
          if (r.mid == it->first) {
            tracked = true;
            break;
          }
        if (!tracked) {
          s.notify_obs.erase(it);
          break;
        }
      }
    }
    s.notify_obs[mid] = obs->filter;
    if (mtype == coap::kCon) {
      uint64_t now = NowMs();
      s.rexmit.push_back({mid, pid, dg, obs->filter,
                          now + coap_ack_timeout_ms_,
                          coap_ack_timeout_ms_, 0});
      if (!s.tm_notify)
        s.tm_notify = wheel_.Arm(s.conn_id, kTmCoapRexmit,
                                 now + coap_ack_timeout_ms_);
    }
    stats_[kStCoapNotifies].fetch_add(1, std::memory_order_relaxed);
    CoapOut(c, dg);
    MarkDirty(s.conn_id, c);
    if (t0) RecordHist(kHistObserveNotify, NowNs() - t0);
  }

  // A delivery that cannot reach the peer (no observer / oversize /
  // retransmit exhaustion) abandons its window slot exactly as a
  // PUBACK would: native pids free inline; Python pids stay with
  // their session's retry machinery.
  void CoapAbandonPid(uint64_t id, Conn& c, uint16_t pid) {
    if (pid < kNativePidBase || !c.ack) return;
    AckState& a = *c.ack;
    uint32_t bi = pid - kNativePidBase;
    if (!BitTest(a.inflight, bi)) return;
    BitClr(a.inflight, bi);
    a.inflight_cnt--;
    a.cyc_acked++;
    AckNote(id, a);
  }

  // Per-conn CON-notify retransmit: the RFC 7252 exponential backoff
  // (base x 2^n) on the timer wheel — the FireSnRexmit shape with
  // per-entry doubling deadlines. Exhaustion drops the unresponsive
  // observer (RFC 7641 §4.5 — stop notifying dead clients), frees the
  // window slot, and lands in the degradation ledger as coap_giveup.
  void FireCoapRexmit(uint64_t id) {
    auto cit = conns_.find(id);
    if (cit == conns_.end() || !cit->second.coap) return;
    Conn& c = cit->second;
    c.coap->tm_notify = 0;
    if (c.coap->rexmit.empty()) return;
    uint64_t now = NowMs();
    uint64_t next_due = 0;
    bool resent = false;
    std::vector<std::string> cancel;
    auto& rx = c.coap->rexmit;
    for (size_t i = 0; i < rx.size();) {
      CoapConRx& r = rx[i];
      if (now < r.next_ms) {
        if (!next_due || r.next_ms < next_due) next_due = r.next_ms;
        i++;
        continue;
      }
      if (r.tries >= coap::kMaxRetransmit) {
        stats_[kStCoapGiveups].fetch_add(1, std::memory_order_relaxed);
        LedgerNote(kLrCoapGiveup, id);
        CoapAbandonPid(id, c, r.pid);
        c.coap->notify_obs.erase(r.mid);
        cancel.push_back(std::move(r.filter));
        rx[i] = std::move(rx.back());
        rx.pop_back();
        continue;
      }
      CoapOut(c, r.dgram);  // resent VERBATIM (CoAP has no DUP bit)
      MarkDirty(id, c);
      resent = true;
      r.tries++;
      r.timeout_ms *= 2;
      r.next_ms = now + r.timeout_ms;
      if (!next_due || r.next_ms < next_due) next_due = r.next_ms;
      stats_[kStCoapRexmits].fetch_add(1, std::memory_order_relaxed);
      i++;
    }
    // cancellations AFTER the scan: CoapCancelObserve forwards MQTT
    // frames whose handling can re-enter the delivery paths
    for (const std::string& filt : cancel) CoapCancelObserve(id, c, filt);
    auto again = conns_.find(id);
    if (again == conns_.end() || !again->second.coap) return;
    Conn& c2 = again->second;
    if (c2.ack) DrainPending(id, c2);  // freed slots pull the queue
    // DrainPending may have tracked a fresh CON (CoapDeliverNotify
    // arms the timer it found zeroed): never double-arm over it
    if (!c2.coap->rexmit.empty() && next_due && !c2.coap->tm_notify)
      c2.coap->tm_notify = wheel_.Arm(id, kTmCoapRexmit, next_due);
    if (resent) FlushDirty();
  }

  // Datagram egress: the outbuf holds [u16 len]-prefixed CoAP
  // messages (one message = one datagram on the wire, RFC 7252 §3);
  // up to kCoapSendBatch go out per sendmmsg — the SN syscall
  // amortization minus packing, which CoAP forbids (so the batch runs
  // deeper than SN's: every message pays its own datagram).
  static constexpr int kCoapSendBatch = 32;

  void CoapFlush(uint64_t id, Conn& c) {
    CoapConnState& s = *c.coap;
    while (c.outpos < c.outbuf.size()) {
      iovec iov[kCoapSendBatch];
      mmsghdr mm[kCoapSendBatch];
      size_t span_end[kCoapSendBatch];
      int nspan = 0;
      size_t pos = c.outpos;
      bool corrupt = false;
      while (pos < c.outbuf.size() && nspan < kCoapSendBatch) {
        if (pos + 2 > c.outbuf.size()) {
          corrupt = true;  // torn prefix: whole messages only live here
          break;
        }
        size_t dlen =
            (static_cast<size_t>(static_cast<uint8_t>(c.outbuf[pos]))
             << 8) |
            static_cast<uint8_t>(c.outbuf[pos + 1]);
        if (pos + 2 + dlen > c.outbuf.size()) {
          corrupt = true;
          break;
        }
        iov[nspan].iov_base =
            const_cast<char*>(c.outbuf.data() + pos + 2);
        iov[nspan].iov_len = dlen;
        memset(&mm[nspan].msg_hdr, 0, sizeof(mm[nspan].msg_hdr));
        mm[nspan].msg_hdr.msg_name = &s.addr;
        mm[nspan].msg_hdr.msg_namelen = sizeof(s.addr);
        mm[nspan].msg_hdr.msg_iov = &iov[nspan];
        mm[nspan].msg_hdr.msg_iovlen = 1;
        span_end[nspan] = pos + 2 + dlen;
        nspan++;
        pos += 2 + dlen;
      }
      if (nspan == 0) {
        if (corrupt) {  // bad framing at the head: never spin on it
          c.outbuf.clear();
          c.outpos = 0;
        }
        break;
      }
      // errno loses the head datagram, short sends only the first of
      // the batch, blackhole claims success while every byte vanishes
      // (the CON-exhaustion rig: notifies into the void retransmit to
      // give-up with no FIN/RST ever surfacing)
      int want = nspan;
      // @fault(conn_write) — the CoAP datagram-egress seam
      if (fault_.armed(fault::kSiteConnWrite)) {
        int fmode = fault_.Fire(fault::kSiteConnWrite, id);
        if (fmode) {
          FaultNote(fault::kSiteConnWrite);
          if (fmode == fault::kModeBlackhole) {
            c.outpos = span_end[nspan - 1];
            continue;
          }
          if (fmode == fault::kModeShort) {
            want = 1;
          } else {  // errno: the datagram is lost (UDP semantics)
            c.outpos = span_end[0];
            continue;
          }
        }
      }
      int sentn = sendmmsg(coap_fd_, mm, want, MSG_NOSIGNAL);
      if (sentn < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        c.outpos = span_end[0];  // drop one datagram, keep going
        continue;
      }
      c.outpos = span_end[sentn - 1];
    }
    if (c.outpos >= c.outbuf.size()) {
      c.outbuf.clear();
      c.outpos = 0;
    }
    if (c.want_close && c.outbuf.empty())
      Drop(id, "closed_by_host", false);
  }

  // -- retained snapshot (round 11) ---------------------------------------
  // SUBSCRIBE-triggered retained delivery below the GIL: the Python
  // retainer (services/retainer.py — the oracle and authoritative
  // store) mirrors every store/delete/expire into retained_ via ops,
  // and the server enqueues one kRetainDeliver op per eligible
  // subscription. Resolution + encode + write all happen here, for
  // TCP, WS, and SN subscribers alike.

  void RetainDeliver(uint64_t id, const std::string& filter,
                     uint8_t maxqos) {
    auto it = FindConnInflate(id);
    if (it == conns_.end()) return;
    cur_trace_ = 0;  // retained bursts are not part of any sampled trace
    Conn& c = it->second;
    stats_[kStRetainDeliver].fetch_add(1, std::memory_order_relaxed);
    uint64_t t0 = telemetry_ ? NowNs() : 0;
    retain_scratch_.clear();
    retained_.Match(filter, store::WallMs(), &retain_scratch_);
    // NO kHighWater break here: the acceptance contract is a retained
    // set bit-identical to the Python oracle, and _native_retained has
    // already told Python the subscription was served — truncating
    // mid-set would silently lose the tail with no fallback. Memory is
    // bounded by the retainer store itself (max_retained), exactly the
    // exposure the asyncio path has; ordinary publish backpressure
    // still applies to everything after this burst.
    for (const RetainEntry* e : retain_scratch_) {
      uint8_t oq = e->qos < maxqos ? e->qos : maxqos;
      if ((c.sn || c.coap) && oq > 1) oq = 1;  // the datagram-gw cap
      if (oq == 0) {
        if (c.sn) {
          SnDeliverPublish(c, e->topic, e->payload, 0, /*retain=*/true,
                           false, 0);
        } else {
          pub_scratch_.clear();
          BuildPublish(&pub_scratch_, e->topic, e->payload, 0, 0,
                       c.proto_ver == 5);
          pub_scratch_[0] = static_cast<char>(0x30 | 0x01);  // retain=1
          AppendMqtt(c, pub_scratch_.data(), pub_scratch_.size());
          stats_[kStFastBytesOut].fetch_add(pub_scratch_.size(),
                                            std::memory_order_relaxed);
        }
      } else if (c.sn) {
        if (!SnDeliverElevated(id, c, e->topic, e->payload,
                               /*retain=*/true))
          continue;
      } else {
        AckState& a = EnsureAck(c);
        pub_scratch_.clear();
        BuildPublish(&pub_scratch_, e->topic, e->payload, 1, 0,
                     c.proto_ver == 5);
        pub_scratch_[0] = static_cast<char>(0x30 | (oq << 1) | 0x01);
        size_t var = 1;
        while (static_cast<uint8_t>(pub_scratch_[var]) & 0x80) var++;
        size_t qoff = var + 1 + 2 + e->topic.size();
        if (a.inflight_cnt >= c.max_inflight) {
          if (a.pending.size() >= kMaxPending) {
            stats_[kStDropsInflight].fetch_add(
                1, std::memory_order_relaxed);
            continue;
          }
          // parked with the retain bit already in the header; the
          // dequeue patch touches only the qos bits and the pid
          a.pending.emplace_back(pub_scratch_, qoff);
          AckNote(id, a);
        } else {
          uint16_t tp = NextPid(a);
          if (oq == 2) BitSet(a.infl_qos2, tp - kNativePidBase);
          if (telemetry_ && a.rtt.size() < kRttSamples)
            a.rtt.push_back({NowNs(), e->topic, tp, oq});
          pub_scratch_[qoff] = static_cast<char>(tp >> 8);
          pub_scratch_[qoff + 1] = static_cast<char>(tp & 0xFF);
          AppendMqtt(c, pub_scratch_.data(), pub_scratch_.size());
          stats_[kStFastBytesOut].fetch_add(pub_scratch_.size(),
                                            std::memory_order_relaxed);
          AckNote(id, a);
        }
      }
      stats_[kStRetainMsgsOut].fetch_add(1, std::memory_order_relaxed);
    }
    MarkDirty(id, c);
    FlushDirty();
    if (telemetry_) RecordHist(kHistRetainDeliver, NowNs() - t0);
  }

  // -- telemetry plane ----------------------------------------------------

  // -- faultline (round 15) ------------------------------------------------
  // Deterministic fault injection at the syscall seams (fault.h). The
  // disarmed cost is ONE relaxed atomic load + branch per seam; every
  // fired fault is observable through the same seams as organic
  // degradation: a faults_injected stat tick + a kLrFault ledger entry
  // (aux = the site) folded once per poll cycle. All fire sites below
  // run on the poll thread (LedgerNote's ownership contract); the
  // store's own sites live in store.h under its mutex.

  void FaultNote(int site) {
    stats_[kStFaultsInjected].fetch_add(1, std::memory_order_relaxed);
    LedgerNote(kLrFault, static_cast<uint64_t>(site));
  }

  // Armed-site decision + accounting for sites with one behavior
  // (accept/connect/ring/doorbell/clock): true = the fault fires.
  bool FaultHit(int site, uint64_t key) {
    if (!fault_.armed(site)) return false;
    if (!fault_.Fire(site, key)) return false;
    FaultNote(site);
    return true;
  }

  // The socket-read seam. errno mode fails with ECONNRESET; blackhole
  // models a partition: whatever the kernel holds is drained and
  // DISCARDED (bytes in flight are lost in the void, and the
  // level-triggered epoll quiesces) while the caller sees "nothing
  // arrived" — no FIN/RST ever surfaces through a blackholed read.
  ssize_t FaultRecv(int site, uint64_t key, int fd, void* buf,
                    size_t cap) {
    if (!fault_.armed(site)) return recv(fd, buf, cap, 0);
    int m = fault_.Fire(site, key);
    if (m == 0) return recv(fd, buf, cap, 0);
    FaultNote(site);
    if (m == fault::kModeBlackhole) {
      [[maybe_unused]] ssize_t junk = recv(fd, buf, cap, 0);
      errno = EAGAIN;
      return -1;
    }
    errno = ECONNRESET;
    return -1;
  }

  // The socket-write seam. short mode genuinely sends only a prefix
  // (the partial-write backlog machinery runs for real); blackhole
  // claims full success while the bytes vanish — the up-but-black
  // link shape the trunk watchdog exists for.
  ssize_t FaultSend(int site, uint64_t key, int fd, const char* buf,
                    size_t len) {
    if (!fault_.armed(site))
      return ::send(fd, buf, len, MSG_NOSIGNAL);
    int m = fault_.Fire(site, key);
    if (m == 0) return ::send(fd, buf, len, MSG_NOSIGNAL);
    FaultNote(site);
    if (m == fault::kModeBlackhole) return static_cast<ssize_t>(len);
    if (m == fault::kModeShort)
      return ::send(fd, buf, len > 1 ? len / 2 : 1, MSG_NOSIGNAL);
    errno = ECONNRESET;
    return -1;
  }

  // Housekeep clock skew: ConnIdleMs sees NowMs() + this many ms while
  // the site is armed (keepalive scans judge conns against a future
  // clock — the idle-teardown machinery under test).
  uint64_t FaultSkewMs() {
    // @fault(housekeep_clock)
    if (!FaultHit(fault::kSiteHousekeepClock, 0)) return 0;
    return static_cast<uint64_t>(
        fault_.Param(fault::kSiteHousekeepClock));
  }

  void RecordHist(int stage, uint64_t ns) {
    Hist& h = hists_[stage];
    h.b[HistBucket(ns)]++;
    h.cnt++;
    h.sum += ns;
    hist_dirty_ |= 1u << stage;
  }

  // Ring-buffer note on a conn's flight recorder (lazy 256B alloc).
  void FrNote(Conn& c, uint8_t event, uint8_t ptype, uint16_t arg,
              uint32_t hash = 0, uint32_t arg2 = 0) {
    if (!telemetry_) return;
    if (!c.fr) c.fr = std::make_unique<FlightRec>();
    FlightRec& r = *c.fr;
    // fr_now_ms_ is the cycle stamp (refreshed at Poll entry): ms
    // resolution is the recorder's contract, and a clock read per
    // note was a measurable share of the telemetry tax
    r.e[r.head] = {static_cast<uint32_t>(fr_now_ms_), event, ptype, arg,
                   hash, arg2};
    r.head = static_cast<uint8_t>((r.head + 1) % kFrCap);
    if (r.n < kFrCap) r.n++;
  }

  size_t TeleCap() const {
    size_t cap = kTapFlushBytes;
    if (cap > max_size_ / 2) cap = max_size_ / 2 + 1;
    return cap;
  }

  // -- native distributed tracing (round 13) ------------------------------

  // Mint the next sampled trace id (seed carries node+shard bits from
  // Python; the low 44 bits count upward, so ids are unique per shard
  // for ~17T sampled publishes).
  uint64_t NextTraceId() {
    return trace_seed_ | (++trace_ctr_ & ((1ull << 44) - 1));
  }

  // The per-publish sampling decision (the kind-8 ticker discipline):
  // tick once per natively-consumed publish, tag 1-in-(mask+1). Called
  // at the commit point — after every punt decision, before any side
  // effect — so the tick count is exactly the native publish count and
  // the sampled subset is deterministic. Rate-bounded per poll cycle
  // (kTraceMaxPerCycle): a blast cycle draining thousands of publishes
  // clips its extra picks instead of flooding the span plane.
  // @admit-gated — the commit point sits AFTER every punt decision
  void TraceSample(uint64_t publisher) {
    cur_trace_ = 0;
    if (!telemetry_ || !tracing_) return;
    if ((++trace_tick_ & trace_mask_) != 0) return;
    if (trace_cyc_used_ >= kTraceMaxPerCycle) return;
    trace_cyc_used_++;
    cur_trace_ = NextTraceId();
    cur_trace_delivers_ = 0;
    stats_[kStTracedPubs].fetch_add(1, std::memory_order_relaxed);
    SpanNote(kSpanIngress, publisher);
  }

  // Emit one span point for the active (or explicitly named) trace.
  void SpanNote(uint8_t stage, uint64_t aux, uint64_t trace = 0) {
    if (!telemetry_) return;
    uint64_t tid = trace ? trace : cur_trace_;
    if (!tid) return;
    char e[26];
    e[0] = 1;
    memcpy(e + 1, &tid, 8);
    e[9] = static_cast<char>(stage);
    uint64_t t = NowNs();
    memcpy(e + 10, &t, 8);
    memcpy(e + 18, &aux, 8);
    SpanAppend(e, 26);
  }

  // Fold one degradation-ladder decision into this cycle's per-reason
  // ledger slot (O(1), no allocation — ladder decisions can be
  // message-rate under overload; FlushSpans emits at most one ledger
  // entry per reason per cycle carrying the folded count).
  void LedgerNote(uint8_t reason, uint64_t aux) {
    if (!telemetry_ || reason == 0 || reason >= kLrCount) return;
    ledger_cyc_[reason]++;
    ledger_aux_[reason] = aux;
    if (cur_trace_) ledger_trace_[reason] = cur_trace_;
  }

  // One deliver_write span per written delivery of the active sampled
  // publish, capped so a wide fan-out cannot flood the span plane.
  // The first delivery past the cap emits ONE truncation marker
  // (aux bit 63) so the clipped timeline declares itself clipped.
  void TraceDeliverNote(uint64_t owner) {
    if (!cur_trace_) return;
    if (cur_trace_delivers_ < kTraceMaxDeliverSpans) {
      cur_trace_delivers_++;
      SpanNote(kSpanDeliverWrite, owner);
    } else if (cur_trace_delivers_ == kTraceMaxDeliverSpans) {
      cur_trace_delivers_++;  // marker fires once per (publish, shard)
      SpanNote(kSpanDeliverWrite, owner | kSpanTruncBit);
    }
  }

  // Whole-sub-record append at the tap bound (the TeleAppend shape —
  // header slot seeded AFTER the flush check).
  // @bounded(span_buf_)
  void SpanAppend(const char* data, size_t len) {
    size_t cap = TeleCap();
    if (span_buf_.size() > 13 && span_buf_.size() - 13 + len > cap)
      FlushSpans();
    if (span_buf_.empty()) span_buf_.assign(13, '\0');
    span_buf_.append(data, len);
    if (span_buf_.size() - 13 > cap) FlushSpans();
  }

  void FlushSpans() {
    for (int r = 1; r < kLrCount; r++) {
      if (!ledger_cyc_[r]) continue;
      char e[34];
      e[0] = 2;
      e[1] = static_cast<char>(r);
      memcpy(e + 2, &ledger_cyc_[r], 8);
      memcpy(e + 10, &ledger_trace_[r], 8);
      memcpy(e + 18, &ledger_aux_[r], 8);
      uint64_t t = NowNs();
      memcpy(e + 26, &t, 8);
      ledger_cyc_[r] = ledger_trace_[r] = ledger_aux_[r] = 0;
      SpanAppend(e, 34);  // zeroed first: a reentrant flush re-scans
    }
    if (span_buf_.size() <= 13) {
      span_buf_.clear();
      return;
    }
    span_buf_[0] = 12;
    // id slot = shard, the kind-7/8/10 convention: N poll threads feed
    // one Python fold, which attributes spans to the producing shard
    uint64_t id = static_cast<uint64_t>(shard_id_);
    memcpy(&span_buf_[1], &id, 8);
    uint32_t plen = static_cast<uint32_t>(span_buf_.size() - 13);
    memcpy(&span_buf_[9], &plen, 4);
    events_.push_back(std::move(span_buf_));
    span_buf_.clear();
    stats_[kStSpanBatches].fetch_add(1, std::memory_order_relaxed);
  }

  // Append ONE whole sub-record; flushes at the tap bound so a chunk
  // boundary never splits a sub-record (Poll drops any record larger
  // than the caller's whole buffer — the kind-6/7 lesson). The header
  // slot is seeded AFTER the flush check (the round-7 EmitTap bug:
  // a headerless post-flush append gets overwritten by the patch).
  // @bounded(tele_buf_)
  void TeleAppend(const char* data, size_t len) {
    size_t cap = TeleCap();
    if (tele_buf_.size() > 13 && tele_buf_.size() - 13 + len > cap)
      FlushTelemetry();
    if (tele_buf_.empty()) tele_buf_.assign(13, '\0');
    tele_buf_.append(data, len);
    if (tele_buf_.size() - 13 > cap) FlushTelemetry();
  }

  void FlushTelemetry() {
    if (tele_buf_.size() <= 13) return;
    tele_buf_[0] = 8;
    // id slot = shard (round 12): the telemetry fold runs under one
    // lock across N poll threads and tags per-shard gauges by this
    uint64_t id = static_cast<uint64_t>(shard_id_);
    memcpy(&tele_buf_[1], &id, 8);
    uint32_t plen = static_cast<uint32_t>(tele_buf_.size() - 13);
    memcpy(&tele_buf_[9], &plen, 4);
    events_.push_back(std::move(tele_buf_));
    tele_buf_.clear();
    stats_[kStTelemetryBatches].fetch_add(1, std::memory_order_relaxed);
  }

  // Per-cycle histogram deltas (sub-record 1): only dirty stages, only
  // buckets that moved. The flushed shadow updates as each record is
  // BUILT, so the deltas sum to the totals exactly — even when
  // TeleAppend chunks the cycle across several kind-8 events.
  void FlushHistDeltas() {
    if (!telemetry_ || !hist_dirty_) return;
    for (int s = 0; s < kHistCount; s++) {
      if (!(hist_dirty_ & (1u << s))) continue;
      Hist& cur = hists_[s];
      Hist& old = hists_flushed_[s];
      tele_scratch_.clear();
      char hdr[20];
      hdr[0] = 1;
      hdr[1] = static_cast<char>(s);
      uint64_t cd = cur.cnt - old.cnt;
      uint64_t sd = cur.sum - old.sum;
      memcpy(hdr + 2, &cd, 8);
      memcpy(hdr + 10, &sd, 8);
      tele_scratch_.append(hdr, 20);  // bytes 18-19 patched below
      uint16_t nb = 0;
      for (int i = 0; i < 64; i++) {
        uint64_t d = cur.b[i] - old.b[i];
        if (!d) continue;
        char ent[5];
        ent[0] = static_cast<char>(i);
        uint32_t d32 = d > 0xFFFFFFFFull ? 0xFFFFFFFFu
                                         : static_cast<uint32_t>(d);
        memcpy(ent + 1, &d32, 4);
        tele_scratch_.append(ent, 5);
        nb++;
      }
      memcpy(&tele_scratch_[18], &nb, 2);
      old = cur;
      TeleAppend(tele_scratch_.data(), tele_scratch_.size());
    }
    hist_dirty_ = 0;
  }

  // Dump a conn's flight-recorder tail (sub-record 2), oldest first.
  void EmitFlightRec(uint64_t id, Conn& c, uint8_t reason) {
    if (!telemetry_ || !c.fr || c.fr->n == 0) return;
    FlightRec& r = *c.fr;
    tele_scratch_.clear();
    char hdr[11];
    hdr[0] = 2;
    memcpy(hdr + 1, &id, 8);
    hdr[9] = static_cast<char>(reason);
    hdr[10] = static_cast<char>(r.n);
    tele_scratch_.append(hdr, 11);
    uint8_t start = static_cast<uint8_t>((r.head + kFrCap - r.n) % kFrCap);
    for (uint8_t i = 0; i < r.n; i++) {
      const FrEntry& e = r.e[(start + i) % kFrCap];
      tele_scratch_.append(reinterpret_cast<const char*>(&e), sizeof(e));
    }
    stats_[kStFrDumps].fetch_add(1, std::memory_order_relaxed);
    TeleAppend(tele_scratch_.data(), tele_scratch_.size());
  }

  // Sampled native ack RTT past the slow-ack threshold (sub-record 3):
  // services/slow_subs.py ranks these next to Python-plane deliveries.
  void EmitSlowAck(uint64_t id, uint8_t qos, uint64_t rtt_ns,
                   const std::string& topic) {
    if (rtt_ns < slow_ack_ns_) return;
    tele_scratch_.clear();
    char hdr[16];
    hdr[0] = 3;
    memcpy(hdr + 1, &id, 8);
    uint64_t us = rtt_ns / 1000;
    uint32_t us32 = us > 0xFFFFFFFFull ? 0xFFFFFFFFu
                                       : static_cast<uint32_t>(us);
    memcpy(hdr + 9, &us32, 4);
    hdr[13] = static_cast<char>(qos);
    uint16_t tl = topic.size() > 0xFFFF
                      ? 0xFFFF
                      : static_cast<uint16_t>(topic.size());
    memcpy(hdr + 14, &tl, 2);
    tele_scratch_.append(hdr, 16);
    tele_scratch_.append(topic.data(), tl);
    TeleAppend(tele_scratch_.data(), tele_scratch_.size());
  }

  // Close out a matching ack-RTT sample (PUBACK ends a qos1 stamp,
  // PUBCOMP a qos2 one — the full exchange RTT by construction, since
  // the inflight bit holds across PUBREC/PUBREL).
  void TeleAckRtt(uint64_t id, AckState& a, uint16_t pid) {
    for (size_t i = 0; i < a.rtt.size(); i++) {
      if (a.rtt[i].pid != pid) continue;
      uint64_t rtt = NowNs() - a.rtt[i].t0_ns;
      RecordHist(a.rtt[i].qos == 2 ? kHistQos2Rtt : kHistQos1Rtt, rtt);
      if (telemetry_) {
        EmitSlowAck(id, a.rtt[i].qos, rtt, a.rtt[i].topic);
        // a traced delivery's ack closes its timeline (round 13): the
        // sample carried the publish's trace id across the exchange.
        // aux = subscriber conn with the delivery qos in bits 60-61
        // (conn ids top out at bit 59 + the shard prefix), so the
        // Python fold can attribute the exemplar to the right RTT
        // histogram (qos1_rtt vs qos2_rtt)
        if (a.rtt[i].trace)
          SpanNote(kSpanAck,
                   id | (static_cast<uint64_t>(a.rtt[i].qos) << 60),
                   a.rtt[i].trace);
      }
      a.rtt[i] = std::move(a.rtt.back());
      a.rtt.pop_back();
      return;
    }
  }

  static void BuildPublish(std::string* out, std::string_view topic,
                           std::string_view payload, uint8_t qos,
                           uint16_t pid, bool v5) {
    size_t remaining = 2 + topic.size() + (qos ? 2 : 0) + (v5 ? 1 : 0) +
                       payload.size();
    out->push_back(static_cast<char>(0x30 | (qos << 1)));
    size_t r = remaining;
    do {
      uint8_t b = r & 0x7F;
      r >>= 7;
      out->push_back(static_cast<char>(r ? b | 0x80 : b));
    } while (r);
    out->push_back(static_cast<char>(topic.size() >> 8));
    out->push_back(static_cast<char>(topic.size() & 0xFF));
    out->append(topic.data(), topic.size());
    if (qos) {
      out->push_back(static_cast<char>(pid >> 8));
      out->push_back(static_cast<char>(pid & 0xFF));
    }
    if (v5) out->push_back('\0');  // empty property section
    out->append(payload.data(), payload.size());
  }

  void MarkDirty(uint64_t id, Conn& c) {
    if (!c.dirty) {
      c.dirty = true;
      dirty_.push_back(id);
    }
  }

  // Append one MQTT byte span to a conn's transport buffer; WS conns
  // get it wrapped in a binary frame (one frame per serialized span,
  // matching the asyncio server's one-frame-per-packet-batch shape);
  // SN conns run the MQTT->SN egress translation (sn gateway, below).
  void AppendMqtt(Conn& c, const char* data, size_t len) {
    if (c.sn) {
      SnEgress(c, data, len);
      return;
    }
    if (c.coap) {
      CoapEgress(c, data, len);
      return;
    }
    if (c.ws) ws::AppendFrameHeader(&c.outbuf, ws::kOpBinary, len);
    c.outbuf.append(data, len);
  }

  void Flush(uint64_t id, Conn& c) {
    if (c.sn) {
      SnFlush(id, c);
      return;
    }
    if (c.coap) {
      CoapFlush(id, c);
      return;
    }
    if (c.fd < 0) {
      // synthetic conns (bench/test herd) have no socket: egress is
      // discarded, want_close honours the normal teardown path
      c.outbuf.clear();
      c.outpos = 0;
      if (c.want_close) Drop(id, "closed_by_host", false);
      return;
    }
    while (c.outpos < c.outbuf.size()) {
      // @fault(conn_write) — errno/short/blackhole on the conn send
      ssize_t n = FaultSend(fault::kSiteConnWrite, id, c.fd,
                            c.outbuf.data() + c.outpos,
                            c.outbuf.size() - c.outpos);
      if (n > 0) {
        c.outpos += static_cast<size_t>(n);
      } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.u64 = id;
        epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, c.fd, &ev);
        return;
      } else if (n < 0 && errno == EINTR) {
        continue;
      } else {
        Drop(id, "sock_error", true);
        return;
      }
    }
    c.outbuf.clear();
    c.outpos = 0;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = id;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, c.fd, &ev);
    if (c.want_close) Drop(id, "closed_by_host", false);
  }

  void Drop(uint64_t id, const char* reason, bool notify) {
    auto it = conns_.find(id);
    if (it == conns_.end()) {
      // hibernating conns tear down from the parked record directly —
      // no inflation on the way to the grave
      DropParked(id, reason, notify);
      return;
    }
    // wheel timers die with the conn (generation-checked: a handle
    // already consumed by a same-tick fire no-ops here)
    if (it->second.tm_keepalive) wheel_.Cancel(it->second.tm_keepalive);
    if (it->second.tm_park) wheel_.Cancel(it->second.tm_park);
    if (it->second.sn && it->second.sn->tm_rexmit)
      wheel_.Cancel(it->second.sn->tm_rexmit);
    if (it->second.coap && it->second.coap->tm_notify)
      wheel_.Cancel(it->second.coap->tm_notify);
    if (telemetry_ && it->second.fr) {
      // flight-recorder dump on abnormal close / protocol error, and
      // always for traced conns (the tail rides the trace log).
      // want_close means PYTHON asked for this teardown (channel error,
      // keepalive, server shutdown): those close as closed_by_host even
      // when the drain hits a dead socket mid-flush, so only genuine
      // C++-level protocol errors dump the recorder (the Python-side
      // teardown noise used to dump on every raced sock_error).
      Conn& c = it->second;
      bool benign = c.want_close ||
                    strcmp(reason, "sock_closed") == 0 ||
                    strcmp(reason, "closed_by_host") == 0 ||
                    strcmp(reason, "ws_close") == 0;
      if (c.traced || !benign) {
        uint8_t why = c.traced ? kFrReasonTrace
                      : (strcmp(reason, "frame_error") == 0 ||
                         strncmp(reason, "ws_", 3) == 0)
                          ? kFrReasonError
                          : kFrReasonClose;
        EmitFlightRec(id, c, why);
      }
    }
    // tear down this conn's real subscription entries; punt markers are
    // owned by Python tokens and removed through the broker observer
    for (const std::string& filt : it->second.own_subs)
      subs_.Remove(id, filt);
    for (const auto& [token, filt] : it->second.own_shared)
      subs_.SharedRemove(token, id, filt);
    if (it->second.sn) {
      // datagram conns share the listener fd: release only the
      // bookkeeping (the addr slot may already point at a successor
      // after a new-identity re-CONNECT — never steal it)
      SnConnState& s = *it->second.sn;
      if (!s.anon) {
        auto ait = sn_addr_conn_.find(SnAddrKey(s.addr));
        if (ait != sn_addr_conn_.end() && ait->second == id)
          sn_addr_conn_.erase(ait);
      }
      if (id == sn_anon_id_) sn_anon_id_ = 0;
    } else if (it->second.coap) {
      // CoAP conns share the listener fd too: release only the addr
      // slot, and only if it still points at US (a new-identity
      // re-register may have handed it to a successor conn)
      auto ait = coap_addr_conn_.find(SnAddrKey(it->second.coap->addr));
      if (ait != coap_addr_conn_.end() && ait->second == id)
        coap_addr_conn_.erase(ait);
    } else if (it->second.fd >= 0) {  // synthetic conns have no socket
      epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, it->second.fd, nullptr);
      close(it->second.fd);
    }
    conns_.erase(it);
    conn_cids_.erase(id);
    if (notify)
      events_.push_back(EncodeRecord(3, id, reason, strlen(reason)));
  }

  uint32_t max_size_;
  uint32_t max_conns_;
  int epoll_fd_ = -1;
  int listen_fd_ = -1;
  int wake_fd_ = -1;
  int port_ = 0;
  uint64_t next_id_ = 1;
  std::unordered_map<uint64_t, Conn> conns_;
  // conn -> clientid (round 18, poll-thread-owned like conns_): set by
  // kEnableFast, read by DurableAppend to stamp the origin clientid
  // into persisted entries; a SIDE map (not Conn state) so it survives
  // park/inflate cycles — erased only at real teardown
  std::unordered_map<uint64_t, std::string> conn_cids_;
  std::deque<std::string> events_;  // encoded records awaiting pickup
  std::mutex mu_;
  std::vector<std::pair<uint64_t, std::string>> pending_;         // @guards(mu_)
  std::vector<uint64_t> pending_closes_;                          // @guards(mu_)
  std::vector<Op> pending_ops_;                                   // @guards(mu_)
  // fast path (poll-thread-owned)
  SubTable subs_;
  std::vector<const SubEntry*> match_scratch_;
  std::vector<SharedGroup*> groups_scratch_;
  std::string pub_scratch_;
  std::string key_scratch_;
  std::string frame_v4_, frame_v5_;  // per-publish shared qos0 frames
  // per-publish shared elevated-qos frames (zero pid, qos1 header;
  // patched per target) + their pid byte offsets
  std::string frame_q_v4_, frame_q_v5_;
  size_t qpid_off_v4_ = 0, qpid_off_v5_ = 0;
  // conns with window activity this poll cycle → one kind-7 record
  std::vector<uint64_t> ack_dirty_;
  std::string ack_buf_;
  std::vector<uint64_t> dirty_;
  // @atomic(relaxed: monotone counters; poll thread bumps, gauge reads tear-free but unordered)
  std::atomic<uint64_t> stats_[kStatCount] = {};
  // enforces ConnIdleMs contract
  // @atomic(acq_rel: poll-thread start release-publishes loop state; misuse checks acquire-load)
  std::atomic<pthread_t> poll_thread_{};
  // @atomic(relaxed: warn-once latch, exact count never matters)
  mutable std::atomic<bool> idle_misuse_warned_{false};
  // -- telemetry plane (poll-thread-owned) --------------------------------
  bool telemetry_ = true;        // EMQX_NATIVE_TELEMETRY=0 escape hatch
  uint64_t slow_ack_ns_ = 500ull * 1000 * 1000;  // slow-ack report floor
  Hist hists_[kHistCount];
  Hist hists_flushed_[kHistCount];  // shadow at last kind-8 emission
  uint32_t hist_dirty_ = 0;         // bit per stage
  uint64_t poll_exit_ns_ = 0;       // GIL-stint reference stamp
  uint64_t flush_t0_ = 0;           // sampled route->flush stamp
  uint32_t tele_tick_ = 0;          // sampled publish-stage counter
  uint32_t tele_tick_ws_ = 0;       // sampled WS-ingest counter
  uint32_t tele_tick_sn_ = 0;       // sampled SN-ingest counter
  // per-message stages sample 1-in-(mask+1); default 7 = the 1-in-8
  // documented cadence, overridable via EMQX_NATIVE_TELEMETRY_SHIFT
  uint32_t tele_mask_ = 7;
  uint64_t fr_now_ms_ = 0;          // per-cycle flight-recorder stamp
  uint64_t last_hist_flush_ms_ = 0;  // hist-delta emission cadence
  uint32_t cur_hash_ = 0;           // current publish's topic hash
  // @bounded — kind-8 batch (bytes [0,13) = header slot)
  std::string tele_buf_;
  std::string tele_scratch_;  // one sub-record under construction
  // -- native distributed tracing (round 13, poll-thread-owned) ------------
  bool tracing_ = true;       // EMQX_NATIVE_TRACING=0 escape hatch
  uint32_t trace_mask_ = 63;  // sample 1-in-(mask+1); default 1-in-64
  uint32_t trace_tick_ = 0;   // global publish ticker (deterministic)
  uint64_t trace_seed_ = 1ull << 63;  // node+shard prefix (Python sets)
  uint64_t trace_ctr_ = 0;
  uint32_t trace_cyc_used_ = 0;  // sampled publishes this poll cycle
  uint64_t cur_trace_ = 0;    // active publish's trace id (0 = unsampled)
  uint8_t cur_trace_delivers_ = 0;  // deliver_write spans emitted so far
  uint32_t fan_xshipped_ = 0;  // shards shipped by the LAST FanOut
  // @bounded — kind-12 batch (bytes [0,13) = header slot)
  std::string span_buf_;
  // per-cycle degradation-ledger accumulators (one kind-12 sub-2 entry
  // per nonzero reason per cycle)
  uint64_t ledger_cyc_[kLrCount] = {};
  uint64_t ledger_trace_[kLrCount] = {};
  uint64_t ledger_aux_[kLrCount] = {};
  // highest trunk wire version this host speaks/advertises (tests cap
  // it at 0 to simulate an old peer)
  uint8_t trunk_wire_max_ = trunk::kWireVersion;
  // -- faultline (round 15) ------------------------------------------------
  // deterministic fault injection (fault.h): armed from any thread,
  // fired on the poll thread; disarmed sites cost one relaxed load
  fault::Injector fault_;
  // silent-link watchdog deadline: a front ring entry unacked this
  // long on an UP link kills it (TrunkAckScan) — the only way an
  // up-but-black partition ever resolves into a replay
  uint64_t trunk_ack_timeout_ms_ = 10000;
  // -- device match lane (poll-thread-owned) ------------------------------
  // Permitted PUBLISHes whose wildcard match runs on the DEVICE router
  // instead of the C++ trie walk: the frame parks here keyed by a lane
  // sequence number while its topic rides a batched kernel launch
  // (broker/native_server.py pump → models/router_model.py); the
  // response names the matched filter strings and delivery resolves
  // them through SubTable::MatchFilter. The per-message walk stays as
  // the always-correct fallback (soft cap, stale drain, lane off).
  bool lane_enabled_ = false;
  uint8_t max_qos_allowed_ = 2;  // mqtt.max_qos_allowed zone cap mirror
  uint64_t lane_seq_ = 1;
  std::unordered_map<uint64_t, LaneEntry> lane_pending_;
  std::deque<uint64_t> lane_order_;          // seqs in arrival order
  // per-topic pending counts: a topic with lane entries in flight must
  // keep going through the lane (a walk fallback would overtake them)
  std::unordered_map<std::string, uint32_t> lane_topic_pending_;
  // topics whose remaining parked frames must punt (ordering guard
  // after a nondeterministic punt); cleared as their counts drain
  std::unordered_set<std::string> lane_poisoned_;
  // @atomic(relaxed: backlog gauge; poll thread stores, mgmt reads tear-free but unordered)
  std::atomic<uint64_t> lane_backlog_{0};
  // -- durable-session plane (poll-thread-owned) ---------------------------
  // The host-side message store (store.h): attached by Python BEFORE
  // the poll thread starts (like the listeners). Null = durable plane
  // off; matched kSubDurable entries then degrade to punts.
  store::DurableStore* store_ = nullptr;
  // @bounded — bytes [0,33) = event+batch header slot
  std::string dur_buf_;
  uint32_t dur_n_ = 0;             // entries in dur_buf_
  std::string dur_prev_payload_;   // payload-dedup reference
  bool dur_have_prev_ = false;
  std::vector<uint64_t> dur_tok_scratch_;  // tokens matched by ONE publish
  bool cur_dup_ = false;           // current publish's DUP bit (FanOut)
  // punt markers mirrored into their own table: the device model only
  // covers broker-table subscriptions, so lane delivery re-checks this
  // (usually tiny) trie per message — remote "n:" routes and any punt
  // shape the device cannot see still force the Python fan-out
  SubTable punt_subs_;
  std::vector<const SubEntry*> punt_scratch_;
  // batched rule-tap entries awaiting one event; bytes [0,13) are the
  // record header slot FlushTaps patches before moving the buffer out
  // @bounded
  std::string tap_buf_;
  std::string tap_prev_payload_;  // payload-dedup reference
  bool tap_have_prev_ = false;
  // -- websocket listener --------------------------------------------------
  int listen_ws_fd_ = -1;
  int ws_port_ = 0;
  std::string ws_path_ = "/mqtt";  // required upgrade request-target
  // -- cluster trunk (poll-thread-owned) -----------------------------------
  int listen_trunk_fd_ = -1;
  int trunk_port_ = 0;
  uint64_t next_trunk_tag_ = 1;
  uint32_t trunk_hello_pending_ = 0;  // links inside the HELLO grace
  std::unordered_map<uint64_t, trunk::Sock> trunk_socks_;  // tag → sock
  std::unordered_map<uint64_t, trunk::Peer> trunk_peers_;  // peer → state
  std::vector<uint64_t> trunk_dirty_;    // peers batched this cycle
  std::vector<uint64_t> trunk_scratch_;  // peers matched by ONE publish
  std::string trunk_punt_buf_;           // kind-9 sub-3 under construction
  // -- mqtt-sn gateway (round 11, poll-thread-owned) -----------------------
  int sn_fd_ = -1;
  int sn_port_ = 0;
  uint8_t sn_gw_id_ = 1;
  uint64_t next_sn_id_ = 1;             // ids minted under kSnConnBit
  uint64_t sn_anon_id_ = 0;             // the shared QoS -1 publisher
  std::unordered_map<uint64_t, uint64_t> sn_addr_conn_;  // addr → conn
  std::unordered_map<uint16_t, std::string> sn_predefined_;
  // -- conn-scale plane (round 16, poll-thread-owned) ----------------------
  // The per-shard timer wheel (keepalive, park-after, SN rexmit, trunk
  // ack watchdog) + the hibernation plane. parked_bytes_/counters are
  // atomics only because Python-side gauges read them cross-thread.
  wheel::Wheel wheel_{NowMs()};
  park::Slab<park::Parked> park_slab_;
  std::unordered_map<uint64_t, uint32_t> parked_;  // conn id -> slab slot
  // @atomic(relaxed: parked-memory gauge; poll thread adds/subs, conn_counts reads tear-free but unordered)
  std::atomic<uint64_t> parked_bytes_{0};
  park::AcceptGovernor gov_;
  bool park_enabled_ = true;
  uint64_t park_after_ms_ = 0;  // explicit override; 0 = 2x-grace auto
  std::vector<sn::SnMsg> sn_msgs_scratch_;
  std::vector<std::string> sn_frames_scratch_;
  std::vector<uint8_t> sn_rx_buf_;  // recvmmsg slots, sized on first read
  // -- coap gateway (round 19, poll-thread-owned) --------------------------
  int coap_fd_ = -1;
  int coap_port_ = 0;
  uint64_t next_coap_id_ = 1;           // ids minted under kCoapConnBit
  std::unordered_map<uint64_t, uint64_t> coap_addr_conn_;  // addr → conn
  std::vector<uint8_t> coap_rx_buf_;    // recvmmsg slots, lazy-sized
  std::vector<std::string> coap_frames_scratch_;  // egress MQTT frames
  std::vector<std::string_view> coap_path_scratch_;
  std::string coap_pub_scratch_;        // per-target qos1 frame patch
  // Python's retained mirror carries no props-bearing topics; while
  // ANY exist the mirror is incomplete and plain GETs degrade whole
  // to the oracle (kCoapRetainState keeps this in sync)
  bool coap_retain_complete_ = true;
  uint64_t coap_ack_timeout_ms_ = coap::kAckTimeoutMs;
  uint32_t tele_tick_coap_ = 0;         // sampled CoAP-ingest counter
  uint32_t tele_tick_notify_ = 0;       // sampled observe-notify counter
  // -- retained snapshot (round 11, poll-thread-owned) ---------------------
  RetainTable retained_;
  std::vector<const RetainEntry*> retain_scratch_;
  // -- multi-core shards (round 12, poll-thread-owned) ---------------------
  // The group is Python-owned and outlives every member host; shard 0
  // with group_ == nullptr IS the unsharded host (every shard check
  // short-circuits). Outbound batches accumulate per destination and
  // seal once per poll cycle (FlushShards) or at the byte cap.
  ring::ShardGroup* group_ = nullptr;
  int shard_id_ = 0;
  std::string xbatch_[ring::kMaxShards];       // open batch per dest
  uint32_t xbatch_n_[ring::kMaxShards] = {};   // entries in each batch
  uint32_t xbatch_sealed_[ring::kMaxShards] = {};  // seals this cycle
  std::string xprev_payload_[ring::kMaxShards];  // payload-dedup ref
  bool xhave_prev_[ring::kMaxShards] = {};
  std::vector<int> xdirty_;       // destinations batched this cycle
  std::vector<int> xdst_scratch_;  // dest shards of ONE publish (admission)
  // ONE publish's cross-shard audience per destination (FanOut collects,
  // XShipMulti ships one multi-target entry per non-empty slot)
  std::vector<uint64_t> xtgt_scratch_[ring::kMaxShards];
  // each peer's OWNER-shard link state mirrored here by Python
  // (kTrunkPeerState broadcast off the kind-9 UP/DOWN events, round
  // 15 spread): non-owner shards decide trunk-vs-punt from this,
  // conservatively down while the mirror lags
  std::unordered_map<uint64_t, bool> trunk_peer_up_;
};

}  // namespace
}  // namespace emqx_native

// ---------------------------------------------------------------------------
// C ABI for ctypes

extern "C" {

// reuseport != 0 binds the TCP listener with SO_REUSEPORT so N shard
// hosts can share one port (kernel accept sharding — round 12).
void* emqx_host_create(const char* bind_addr, uint16_t port,
                       uint32_t max_size, uint32_t max_conns,
                       int reuseport) {
  auto* h = new emqx_native::Host(max_size, max_conns);
  if (!h->Init(bind_addr, port, reuseport != 0)) {
    delete h;
    return nullptr;
  }
  return h;
}

int emqx_host_port(void* h) {
  return static_cast<emqx_native::Host*>(h)->port();
}

// Open the RFC6455 listener on an already-created host. Call BEFORE
// the poll thread starts (the epoll set is mutated from this thread).
// Returns the bound port, or -1.
int emqx_host_listen_ws(void* h, const char* bind_addr, uint16_t port,
                        const char* path, int reuseport) {
  return static_cast<emqx_native::Host*>(h)->ListenWs(bind_addr, port,
                                                      path,
                                                      reuseport != 0);
}

long emqx_host_poll(void* h, uint8_t* buf, size_t cap, int timeout_ms) {
  return static_cast<emqx_native::Host*>(h)->Poll(buf, cap, timeout_ms);
}

int emqx_host_send(void* h, uint64_t conn, const uint8_t* data, size_t len) {
  return static_cast<emqx_native::Host*>(h)->Send(conn, data, len);
}

int emqx_host_close_conn(void* h, uint64_t conn) {
  return static_cast<emqx_native::Host*>(h)->CloseConn(conn);
}

// --- fast-path control plane (thread-safe, applied on the poll thread) ----

// ``clientid`` (nullable) binds the conn's clientid for origin
// attribution: durable appends persist it (store entry flags bit5) so
// no-local / from_ survive a restart (round 18).
int emqx_host_enable_fast(void* h, uint64_t conn, int proto_ver,
                          uint32_t max_inflight, const char* clientid) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kEnableFast;
  op.owner = conn;
  op.proto_ver = static_cast<uint8_t>(proto_ver);
  op.max_inflight = max_inflight;
  if (clientid) op.str = clientid;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_disable_fast(void* h, uint64_t conn) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kDisableFast;
  op.owner = conn;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// flags: bit0 = punt marker, bit1 = no-local
int emqx_host_sub_add(void* h, uint64_t owner, const char* filter,
                      uint8_t qos, uint8_t flags) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSubAdd;
  op.owner = owner;
  op.str = filter;
  op.qos = qos;
  op.flags = flags;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_sub_del(void* h, uint64_t owner, const char* filter) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSubDel;
  op.owner = owner;
  op.str = filter;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_shared_add(void* h, uint64_t token, uint64_t conn,
                         const char* filter, uint8_t qos, uint8_t flags) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSharedAdd;
  op.token = token;
  op.owner = conn;
  op.str = filter;
  op.qos = qos;
  op.flags = flags;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_shared_del(void* h, uint64_t token, uint64_t conn,
                         const char* filter) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSharedDel;
  op.token = token;
  op.owner = conn;
  op.str = filter;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_permit(void* h, uint64_t conn, const char* topic) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kPermit;
  op.owner = conn;
  op.str = topic;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_permits_flush(void* h) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kPermitsFlush;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_set_lane(void* h, int enabled) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetLane;
  op.flags = enabled ? 1 : 0;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_lane_deliver(void* h, const uint8_t* blob, size_t len) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kLaneDeliver;
  op.str.assign(reinterpret_cast<const char*>(blob), len);
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

long emqx_host_lane_backlog(void* h) {
  return static_cast<long>(
      static_cast<emqx_native::Host*>(h)->LaneBacklog());
}

// Dynamic native-plane share of a conn's receive-maximum budget: the
// Python server re-divides the budget per batched ack cycle (the caps
// of the two planes always sum to <= the budget, so occupancy cannot
// exceed the client's Receive Maximum).
int emqx_host_set_inflight_cap(void* h, uint64_t conn, uint32_t cap) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetInflightCap;
  op.owner = conn;
  op.max_inflight = cap;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Trace punt (observability): a traced conn's PUBLISHes take the
// Python plane (full hook visibility) and its flight-recorder tail is
// dumped — immediately on attach and again at teardown (kind 8).
int emqx_host_set_trace(void* h, uint64_t conn, int on) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetTrace;
  op.owner = conn;
  op.flags = on ? 1 : 0;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Telemetry master switch + slow-ack report floor (ns). Histograms,
// flight recorders, and kind-8 emission all gate on `enabled` — the
// EMQX_NATIVE_TELEMETRY=0 escape hatch for overhead-sensitive runs.
int emqx_host_set_telemetry(void* h, int enabled, uint64_t slow_ack_ns) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetTelemetry;
  op.flags = enabled ? 1 : 0;
  op.token = slow_ack_ns;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Native distributed tracing (round 13): the deterministic 1-in-2^shift
// publish sampler. `seed` carries the node+shard prefix trace ids mint
// under (nonzero; 0 keeps the current seed). Tracing also gates on the
// telemetry master switch.
int emqx_host_set_tracing(void* h, int enabled, int shift, uint64_t seed) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetTracing;
  op.flags = enabled ? 1 : 0;
  op.max_inflight = shift >= 0 && shift <= 16
                        ? static_cast<uint32_t>(shift)
                        : 6u;
  op.token = seed;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Cap the trunk wire version this host advertises/accepts (tests set 0
// to exercise the old-peer trace-id downshift).
int emqx_host_set_trunk_wire(void* h, int version) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetTrunkWire;
  op.qos = static_cast<uint8_t>(version < 0 ? 0 : version);
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// --- cluster trunk plane (round 9) ----------------------------------------

// Open the trunk listener (BEFORE the poll thread starts). Peer hosts
// dial this port; received batch records fan out locally below the GIL.
// Returns the bound port, or -1.
int emqx_host_trunk_listen(void* h, const char* bind_addr, uint16_t port,
                           int reuseport) {
  return static_cast<emqx_native::Host*>(h)->ListenTrunk(bind_addr, port,
                                                         reuseport != 0);
}

// Silent-link watchdog deadline in ms (round 15): a front replay-ring
// entry unacked this long on an UP link kills the link so the redial
// can replay it — the only resolution for an up-but-black partition.
// 0 disables the watchdog (default 10s).
int emqx_host_set_trunk_ack_timeout(void* h, uint64_t ms) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetTrunkAckTimeout;
  op.token = ms;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// --- faultline (round 15) ---------------------------------------------------

// Arm (mode 0 disarms) one named fault site — see fault.h for the
// site/mode catalog and the n_or_prob/seed/key determinism contract.
// Store sites forward to the attached store's injector. Thread-safe.
int emqx_host_fault_arm(void* h, int site, int mode, double n_or_prob,
                        uint64_t seed, uint64_t key) {
  return static_cast<emqx_native::Host*>(h)->FaultArm(site, mode,
                                                      n_or_prob, seed,
                                                      key);
}

// Faults fired at one site so far (-1 on a bad site index).
long emqx_host_fault_fired(void* h, int site) {
  return static_cast<emqx_native::Host*>(h)->FaultFired(site);
}

// Dial (or re-dial) a peer's trunk listener. Thread-safe; the poll
// thread performs the nonblocking connect and reports the outcome as a
// kind-9 UP/DOWN event. A successful (re)connect replays the peer's
// unacked qos1 batches before any new traffic.
int emqx_host_trunk_connect(void* h, uint64_t peer, const char* addr,
                            uint16_t port) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kTrunkConnect;
  op.owner = peer;
  op.str = addr;
  op.token = port;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Bind a peer id to its stable NODE NAME (round 18): the durable store
// keys the persisted trunk replay ring on it, since peer ids renumber
// per process. Call before trunk_connect so the previous life's ring
// merges ahead of fresh traffic.
int emqx_host_trunk_ident(void* h, uint64_t peer, const char* name) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kTrunkIdent;
  op.owner = peer;
  op.str = name ? name : "";
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Drop a peer link. forget=0 keeps the peer state (the qos1 replay
// ring survives for the next connect); forget=1 erases it entirely
// (the node left the cluster and its routes are gone — including the
// store-backed ring records).
int emqx_host_trunk_disconnect(void* h, uint64_t peer, int forget) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kTrunkDisconnect;
  op.owner = peer;
  op.flags = forget ? 1 : 0;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Install/remove a remote entry: a cross-node route served by `peer`'s
// trunk instead of a punt marker. While the trunk is down the entry
// BEHAVES as a punt marker (degradation ladder trunk → punt → Python).
int emqx_host_trunk_route_add(void* h, uint64_t peer, const char* filter) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kTrunkRouteAdd;
  op.owner = peer;
  op.str = filter;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_trunk_route_del(void* h, uint64_t peer, const char* filter) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kTrunkRouteDel;
  op.owner = peer;
  op.str = filter;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// --- multi-core shard plane (round 12) --------------------------------------

// Create the cross-shard ring group for `n` shard hosts. Python owns
// the group: create it BEFORE any host joins, destroy it AFTER every
// member host is destroyed (the group owns the doorbell eventfds a
// racing producer may still write to during a member's teardown).
void* emqx_shard_group_create(int n) {
  if (n < 1 || n > emqx_native::ring::kMaxShards) return nullptr;
  return new emqx_native::ring::ShardGroup(n);
}

void emqx_shard_group_destroy(void* g) {
  delete static_cast<emqx_native::ring::ShardGroup*>(g);
}

// Make `h` shard `shard_id` of group `g` (call BEFORE the poll thread
// starts): conn ids gain the shard prefix (bits 56-58), cross-shard
// deliveries ride the group's SPSC rings, and the group's doorbell for
// this shard joins the epoll set. Returns 0, or -1 on a bad id.
int emqx_host_join_group(void* h, void* g, int shard_id) {
  return static_cast<emqx_native::Host*>(h)->JoinGroup(
      static_cast<emqx_native::ring::ShardGroup*>(g), shard_id);
}

// Mirror a peer's OWNER-shard link state onto the other shards
// (Python broadcasts the kind-9 UP/DOWN events here): each shard's
// trunk-vs-punt oracle for legs it would ring-forward to the owner
// (peer % n since round 15).
int emqx_host_trunk_peer_state(void* h, uint64_t peer, int up) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kTrunkPeerState;
  op.owner = peer;
  op.flags = up ? 1 : 0;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// --- mqtt-sn gateway + retained snapshot (round 11) -------------------------

// Open the MQTT-SN/UDP gateway socket (BEFORE the poll thread starts,
// like the other listeners). Returns the bound port, or -1.
int emqx_host_listen_sn(void* h, const char* bind_addr, uint16_t port,
                        int gw_id, int reuseport) {
  return static_cast<emqx_native::Host*>(h)->ListenSn(bind_addr, port,
                                                      gw_id,
                                                      reuseport != 0);
}

// Install/remove a gateway-wide predefined topic id (empty topic
// forgets the id). Thread-safe; applied on the poll thread.
int emqx_host_sn_predefined(void* h, uint16_t topic_id,
                            const char* topic) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSnPredef;
  op.owner = topic_id;
  op.str = topic ? topic : "";
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Mirror one retained message into the host-side snapshot (the Python
// retainer stays the oracle and the authoritative store). deadline_ms
// is the EFFECTIVE absolute wall-clock expiry (0 = never): Python
// folds per-message expiry and the store default into one number.
int emqx_host_set_retained(void* h, const char* topic,
                           const uint8_t* payload, uint32_t plen,
                           uint8_t qos, uint64_t deadline_ms) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kRetainSet;
  op.str = topic;
  op.str2.assign(reinterpret_cast<const char*>(payload), plen);
  op.qos = qos;
  op.token = deadline_ms;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_retain_del(void* h, const char* topic) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kRetainDel;
  op.str = topic;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// SUBSCRIBE-triggered retained delivery below the GIL: match the
// snapshot against `filter` and write every live entry to `conn`
// (retain=1, qos = min(msg, max_qos); elevated qos rides the native
// ack plane, SN conns get SN framing + the qos1 cap).
int emqx_host_retain_deliver(void* h, uint64_t conn, const char* filter,
                             uint8_t max_qos) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kRetainDeliver;
  op.owner = conn;
  op.str = filter;
  op.qos = max_qos;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Per-message telemetry sampling override: stages sample 1-in-2^shift
// (default 3). Out-of-range shifts reset to the default.
int emqx_host_set_telemetry_shift(void* h, int shift) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetTeleShift;
  op.token = static_cast<uint64_t>(shift);
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Codec test surface: parse every SN message in `in` with the shared
// sn.h codec and re-serialize — tests/test_native_sn.py drives the
// Python oracle codec through the same vectors and compares bytes.
long emqx_sn_roundtrip(const uint8_t* in, size_t len, uint8_t** out,
                       size_t* out_len) {
  std::vector<emqx_native::sn::SnMsg> msgs;
  emqx_native::sn::ParseAll(in, len, &msgs);
  std::string buf;
  for (const auto& m : msgs) emqx_native::sn::Serialize(m, &buf);
  uint8_t* p = static_cast<uint8_t*>(malloc(buf.size() ? buf.size() : 1));
  memcpy(p, buf.data(), buf.size());
  *out = p;
  *out_len = buf.size();
  return static_cast<long>(msgs.size());
}

// --- coap gateway (round 19) ------------------------------------------------

// Open the CoAP/UDP gateway socket (BEFORE the poll thread starts,
// like the other listeners). Returns the bound port, or -1.
int emqx_host_listen_coap(void* h, const char* bind_addr, uint16_t port,
                          int reuseport) {
  return static_cast<emqx_native::Host*>(h)->ListenCoap(bind_addr, port,
                                                        reuseport != 0);
}

// Answer path for oracle-served (kind-13 punted) exchanges: raw CoAP
// response bytes for `conn`'s peer. Thread-safe; applied on the poll
// thread, framed into the conn's datagram outbuf verbatim.
int emqx_host_coap_send(void* h, uint64_t conn, const uint8_t* data,
                        uint32_t len) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kCoapSend;
  op.owner = conn;
  op.str.assign(reinterpret_cast<const char*>(data), len);
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Mirror whether the retained snapshot is COMPLETE (no props-carrying
// topics excluded): plain CoAP GETs serve natively only while it is;
// otherwise they degrade whole to the Python oracle's lookup.
int emqx_host_coap_retain_state(void* h, int complete) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kCoapRetainState;
  op.flags = complete ? 1 : 0;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// CON-notify retransmit base in ms (0 restores the RFC 7252 default
// ACK_TIMEOUT x 1.5 = 3000); tests compress the backoff clock with it.
int emqx_host_set_coap_ack_timeout(void* h, uint64_t ms) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetCoapAckTimeout;
  op.token = ms;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Codec test surface: parse one CoAP datagram with the shared coap.h
// codec and re-serialize — tests/test_native_coap.py drives the Python
// oracle codec through the same vectors and compares bytes.
long emqx_coap_roundtrip(const uint8_t* in, size_t len, uint8_t** out,
                         size_t* out_len) {
  emqx_native::coap::CoapMsg m;
  std::string buf;
  long n = 0;
  if (emqx_native::coap::Parse(in, len, &m)) {
    emqx_native::coap::Serialize(m, &buf);
    n = 1;
  }
  uint8_t* p = static_cast<uint8_t*>(malloc(buf.size() ? buf.size() : 1));
  memcpy(p, buf.data(), buf.size());
  *out = p;
  *out_len = buf.size();
  return n;
}

// --- durable-session plane (round 10) --------------------------------------

// Open (or recover) a durable store. dir "" = anonymous (in-memory)
// segments; fsync_policy: 0 never, 1 per batch, 2 ~100ms interval.
// Returns null when the directory cannot be used at all.
void* emqx_store_open(const char* dir, uint64_t segment_bytes,
                      int fsync_policy) {
  auto* s = new emqx_native::store::DurableStore(
      dir ? dir : "", static_cast<size_t>(segment_bytes), fsync_policy);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void emqx_store_close(void* s) {
  delete static_cast<emqx_native::store::DurableStore*>(s);
}

// sid -> stable (restart-surviving) token; markers key on it.
uint64_t emqx_store_register(void* s, const char* sid) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Register(sid);
}

// sid -> token without creating one; 0 = never registered.
uint64_t emqx_store_lookup(void* s, const char* sid) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Lookup(sid);
}

// Single-message append (Python-plane persistence + test surface); the
// data plane appends whole batches through the attached host instead.
// `trace` != 0 persists a sampled trace id with the entry (flags bit4);
// `cid`/`cl` persist the publisher's clientid (flags bit5) so no-local
// and from_ attribution survive a restart. Returns the assigned guid
// (0 on a malformed call).
uint64_t emqx_store_append(void* s, uint64_t origin, uint8_t flags,
                           const uint64_t* toks, uint16_t ntok,
                           const char* topic, uint16_t tlen,
                           const char* payload, uint32_t plen,
                           uint64_t trace, const char* cid, uint8_t cl) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Append(
      origin, flags, toks, ntok, topic, tlen, payload, plen, trace,
      cid, cl);
}

// --- one-recovery-path surfaces (round 18) ---------------------------------

// Retire a REGISTER token (session-expiry GC): sid→token mapping,
// SESSION record, and leftover markers die with it.
int emqx_store_unregister(void* s, uint64_t token) {
  static_cast<emqx_native::store::DurableStore*>(s)->Unregister(token);
  return 0;
}

// Write (blen > 0) or delete (blen == 0) a session-catalog record.
int emqx_store_put_session(void* s, uint64_t token, const char* body,
                           uint32_t blen) {
  static_cast<emqx_native::store::DurableStore*>(s)->PutSession(
      token, body ? body : "", blen);
  return 0;
}

// All live session-catalog records as a malloc'd blob of
// [u64 token][u16 sidlen][sid][u32 blen][body] entries (free with
// emqx_buf_free). Returns the count — the boot walk.
long emqx_store_sessions(void* s, uint8_t** out, size_t* out_len) {
  return static_cast<emqx_native::store::DurableStore*>(s)->FetchSessions(
      out, out_len);
}

// Trunk replay-ring records, keyed by peer NODE NAME (the host's data
// plane journals through these via its attached store; this is the
// raw test/inspection surface).
int emqx_store_trunk_put(void* s, const char* name, uint64_t seq,
                         uint8_t tflags, const char* data, size_t len) {
  static_cast<emqx_native::store::DurableStore*>(s)->TrunkPut(
      name ? name : "", seq, tflags, data, len);
  return 0;
}

int emqx_store_trunk_ack(void* s, const char* name, uint64_t seq) {
  static_cast<emqx_native::store::DurableStore*>(s)->TrunkAck(
      name ? name : "", seq);
  return 0;
}

// The named ring in seq order as a malloc'd blob of
// [u64 seq][u8 tflags][u32 len][record bytes] entries. Returns count.
long emqx_store_trunk_fetch(void* s, const char* name, uint8_t** out,
                            size_t* out_len) {
  return static_cast<emqx_native::store::DurableStore*>(s)->TrunkFetch(
      name ? name : "", out, out_len);
}

long emqx_store_trunk_pending(void* s, const char* name) {
  return static_cast<emqx_native::store::DurableStore*>(s)->TrunkPending(
      name ? name : "");
}

// Consume (token, guid) markers; returns how many were live.
long emqx_store_consume(void* s, uint64_t token, const uint64_t* guids,
                        uint32_t n) {
  return static_cast<long>(
      static_cast<emqx_native::store::DurableStore*>(s)->Consume(
          token, guids, n));
}

// Pending messages for a token (guid order) as a malloc'd blob of
// [u64 guid][u64 origin][u64 ts_ms][u8 flags][u16 tlen][topic]
// [u32 plen][payload] entries (free with emqx_buf_free). Returns count.
long emqx_store_fetch(void* s, uint64_t token, uint8_t** out,
                      size_t* out_len) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Fetch(
      token, out, out_len);
}

long emqx_store_pending(void* s, uint64_t token) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Pending(token);
}

// Unlink all-consumed sealed segments + compact thin live tails;
// returns segments freed.
long emqx_store_gc(void* s) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Gc();
}

int emqx_store_sync(void* s) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Sync();
}

long emqx_store_stat(void* s, int slot) {
  return static_cast<emqx_native::store::DurableStore*>(s)->Stat(slot);
}

// Age-based compaction trigger (round 15): a sealed segment whose live
// tail has sat past `ms` gets re-homed regardless of the thin-tail
// byte bound, so one huge live message can no longer pin an otherwise
// dead segment forever. 0 disables the age trigger.
int emqx_store_set_compact_age(void* s, uint64_t ms) {
  static_cast<emqx_native::store::DurableStore*>(s)->SetCompactAge(ms);
  return 0;
}

// Direct store-injector surface (raw store tests; the product path
// arms through emqx_host_fault_arm, which forwards store sites here).
int emqx_store_fault_arm(void* s, int site, int mode, double n_or_prob,
                         uint64_t seed, uint64_t key) {
  if (site < 0 || site >= emqx_native::fault::kSiteCount) return -1;
  static_cast<emqx_native::store::DurableStore*>(s)->injector()->Arm(
      site, mode, n_or_prob, seed, key);
  return 0;
}

long emqx_store_fault_fired(void* s, int site) {
  return static_cast<long>(
      static_cast<emqx_native::store::DurableStore*>(s)
          ->injector()
          ->FiredCount(site));
}

// Attach a store to a host (BEFORE the poll thread starts). The host
// borrows the pointer: destroy the host first, then close the store.
int emqx_host_attach_store(void* h, void* s) {
  static_cast<emqx_native::Host*>(h)->AttachStore(
      static_cast<emqx_native::store::DurableStore*>(s));
  return 0;
}

// Install/remove a durable entry (the FOURTH match-table entry kind):
// publishes matching `filter` are persisted below the GIL for the
// session registered under `token` while the fast path proceeds.
int emqx_host_durable_add(void* h, uint64_t token, const char* filter,
                          uint8_t qos) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kDurableAdd;
  op.owner = token;
  op.str = filter;
  op.qos = qos;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

int emqx_host_durable_del(void* h, uint64_t token, const char* filter) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kDurableDel;
  op.owner = token;
  op.str = filter;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Poll-thread-only telemetry note (the replay_drain stage): -2 off
// thread, -1 bad stage.
int emqx_host_note_stage(void* h, int stage, uint64_t ns) {
  return static_cast<emqx_native::Host*>(h)->NoteStage(stage, ns);
}

int emqx_host_set_max_qos(void* h, int max_qos) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetMaxQos;
  op.qos = static_cast<uint8_t>(max_qos);
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

long emqx_host_stat(void* h, int slot) {
  return static_cast<emqx_native::Host*>(h)->Stat(slot);
}

long emqx_host_conn_idle_ms(void* h, uint64_t conn) {
  return static_cast<emqx_native::Host*>(h)->ConnIdleMs(conn);
}

void emqx_host_destroy(void* h) {
  delete static_cast<emqx_native::Host*>(h);
}

// -- conn-scale plane (round 16) -------------------------------------------

// Arm/replace a conn's native keepalive deadline on the shard's timer
// wheel. `deadline_ms` is the EFFECTIVE expiry (callers pass 1.5x the
// negotiated keepalive, the [MQTT-3.1.2-24] grace); 0 disarms. The
// Python housekeep loop stops scanning conns whose keepalive lives
// here — the O(N)-per-tick sweep becomes O(expired).
int emqx_host_set_keepalive(void* h, uint64_t conn, uint64_t deadline_ms) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetKeepalive;
  op.owner = conn;
  op.token = deadline_ms;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Conn-scale knobs: `enabled` gates hibernation, `park_after_ms` is
// the no-keepalive park horizon fallback (0 keeps the default; conns
// with a keepalive park after 2x their grace deadline),
// `accept_burst` caps accepts per poll cycle (0 = unlimited; the
// remainder defers to the kernel backlog), `mem_budget_bytes` sheds
// accepts once the conn-memory estimate crosses it (0 = unlimited,
// sheds are ledger-visible as accept_shed).
int emqx_host_set_park(void* h, int enabled, uint32_t park_after_ms,
                       uint32_t accept_burst, uint64_t mem_budget_bytes) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSetPark;
  op.flags = enabled ? 1 : 0;
  op.max_inflight = park_after_ms;
  op.owner = accept_burst;
  op.token = mem_budget_bytes;
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// Bench/test surface (raw hosts only): conjure `n` resident fast
// conns with no socket so the conn-scale structures run at 10^6 scale
// inside an fd-capped container; every `sub_every`-th conn installs a
// unique subscription under `topic_prefix`. Not a product path.
int emqx_host_synth_conns(void* h, uint32_t n, uint32_t keepalive_ms,
                          uint32_t sub_every, const char* topic_prefix) {
  emqx_native::Op op;
  op.kind = emqx_native::Op::kSynthConns;
  op.owner = n;
  op.token = keepalive_ms;
  op.max_inflight = sub_every;
  op.str = topic_prefix ? topic_prefix : "synth";
  return static_cast<emqx_native::Host*>(h)->Enqueue(std::move(op));
}

// out[4] = {resident conns, parked conns, parked bytes, armed timers}.
// POLL-THREAD ONLY (returns -2 off thread, the ConnIdleMs contract).
int emqx_host_conn_counts(void* h, uint64_t* out) {
  return static_cast<emqx_native::Host*>(h)->ConnCounts(out);
}

// Timer-wheel parity surface: run a seeded op script on a standalone
// wheel (caller's thread, no host) and return the op/fire journal the
// Python brute-force oracle replays (free with emqx_buf_free).
long emqx_wheel_selftest(uint64_t seed, uint32_t n_ops, uint8_t** out,
                         size_t* out_len) {
  std::vector<uint8_t> buf;
  emqx_native::wheel::SelfTestScript(seed, n_ops, &buf);
  uint8_t* mem = static_cast<uint8_t*>(malloc(buf.empty() ? 1 : buf.size()));
  if (!buf.empty()) memcpy(mem, buf.data(), buf.size());
  *out = mem;
  *out_len = buf.size();
  return static_cast<long>(buf.size());
}

// --- standalone sub table (differential testing vs router/trie.py) --------

void* emqx_subtable_create() { return new emqx_native::SubTable(); }

void emqx_subtable_destroy(void* t) {
  delete static_cast<emqx_native::SubTable*>(t);
}

void emqx_subtable_add(void* t, uint64_t owner, const char* filter,
                       uint8_t qos, uint8_t flags) {
  static_cast<emqx_native::SubTable*>(t)->Add(owner, filter, qos, flags);
}

int emqx_subtable_del(void* t, uint64_t owner, const char* filter) {
  return static_cast<emqx_native::SubTable*>(t)->Remove(owner, filter) ? 1 : 0;
}

// Fills out[] with the owners of every matching entry; returns the
// total match count (callers re-invoke with a larger buffer if needed).
long emqx_subtable_match(void* t, const char* topic, uint64_t* out,
                         long cap) {
  std::vector<const emqx_native::SubEntry*> hits;
  static_cast<emqx_native::SubTable*>(t)->Match(topic, &hits);
  long n = 0;
  for (const auto* e : hits) {
    if (n < cap) out[n] = e->owner;
    n++;
  }
  return n;
}

// Per-filter terminal lookup (the device lane's delivery primitive),
// exposed for differential testing against Match: the union of
// MatchFilter over a topic's oracle-matched filters must equal the
// walk's match set.
long emqx_subtable_match_filter(void* t, const char* filter, uint64_t* out,
                                long cap) {
  std::vector<const emqx_native::SubEntry*> hits;
  static_cast<emqx_native::SubTable*>(t)->MatchFilter(filter, &hits);
  long n = 0;
  for (const auto* e : hits) {
    if (n < cap) out[n] = e->owner;
    n++;
  }
  return n;
}

// Bulk match benchmark surface (the emqx_broker_bench.erl:run1/4 shape:
// many topics against a wildcard-dense table): matches every
// newline-separated topic in one call so per-call ctypes overhead stays
// off the measurement. Returns topics processed; *out_matches totals the
// entries matched across all topics.
long emqx_subtable_match_many(void* t, const char* topics, size_t len,
                              long* out_matches) {
  auto* table = static_cast<emqx_native::SubTable*>(t);
  std::vector<const emqx_native::SubEntry*> hits;
  long n_topics = 0, matches = 0;
  size_t start = 0;
  for (size_t i = 0; i <= len; i++) {
    if (i == len || topics[i] == '\n') {
      if (i > start) {
        hits.clear();
        table->Match(std::string_view(topics + start, i - start), &hits);
        matches += static_cast<long>(hits.size());
        n_topics++;
      }
      start = i + 1;
    }
  }
  *out_matches = matches;
  return n_topics;
}

void emqx_subtable_shared_add(void* t, uint64_t token, uint64_t owner,
                              const char* filter, uint8_t qos,
                              uint8_t flags) {
  static_cast<emqx_native::SubTable*>(t)->SharedAdd(token, owner, filter,
                                                    qos, flags);
}

int emqx_subtable_shared_del(void* t, uint64_t token, uint64_t owner,
                             const char* filter) {
  return static_cast<emqx_native::SubTable*>(t)->SharedRemove(
             token, owner, filter)
             ? 1
             : 0;
}

// One rotating pick per matched shared group; out pairs are
// (group token, picked owner). All-or-nothing: when every pickable
// group fits the buffer, all pairs are written, cursors advance, and
// the pair count is returned; on overflow NOTHING is written and NO
// cursor moves (a retry after a cursor-advancing partial call would
// double-rotate the already-written groups and starve fixed members),
// *out_total reports the size to re-invoke with. Empty groups are
// skipped — no pick exists for them.
long emqx_subtable_shared_pick(void* t, const char* topic, uint64_t* out,
                               long cap, long* out_total) {
  std::vector<const emqx_native::SubEntry*> hits;
  std::vector<emqx_native::SharedGroup*> groups;
  static_cast<emqx_native::SubTable*>(t)->Match(topic, &hits, &groups);
  long total = 0;
  for (auto* g : groups)
    if (!g->members.empty()) total++;
  if (out_total) *out_total = total;
  if (2 * total > cap) return 0;
  long n = 0;
  for (auto* g : groups) {
    if (g->members.empty()) continue;
    const auto& e = g->members[g->cursor % g->members.size()];
    g->cursor++;
    out[2 * n] = g->token;
    out[2 * n + 1] = e.owner;
    n++;
  }
  return n;
}

// Bulk dispatch benchmark surface: run rotating picks for every
// newline-separated topic in one call (per-call ctypes overhead would
// otherwise dominate the measurement). Returns topics processed;
// *out_picks counts the group picks made.
long emqx_subtable_shared_pick_many(void* t, const char* topics, size_t len,
                                    long* out_picks) {
  auto* table = static_cast<emqx_native::SubTable*>(t);
  std::vector<const emqx_native::SubEntry*> hits;
  std::vector<emqx_native::SharedGroup*> groups;
  long n_topics = 0, picks = 0;
  size_t start = 0;
  for (size_t i = 0; i <= len; i++) {
    if (i == len || topics[i] == '\n') {
      if (i > start) {
        hits.clear();
        groups.clear();
        table->Match(std::string_view(topics + start, i - start), &hits,
                     &groups);
        for (auto* g : groups) {
          if (!g->members.empty()) {
            g->cursor++;
            picks++;
          }
        }
        n_topics++;
      }
      start = i + 1;
    }
  }
  *out_picks = picks;
  return n_topics;
}

// --- standalone framer (for parity tests + non-socket embedding) ----------

void* emqx_framer_create(uint32_t max_size) {
  return new emqx_native::Framer(max_size);
}

// Feeds a chunk; returns a malloc'd buffer of concatenated
// [u32 len][frame bytes] records in *out/*out_len (caller frees with
// emqx_buf_free). Returns the FrameStatus as int.
int emqx_framer_feed(void* f, const uint8_t* data, size_t len, uint8_t** out,
                     size_t* out_len) {
  std::vector<std::string> frames;
  auto st = static_cast<emqx_native::Framer*>(f)->Feed(data, len, &frames);
  size_t total = 0;
  for (auto& fr : frames) total += 4 + fr.size();
  uint8_t* buf = static_cast<uint8_t*>(malloc(total ? total : 1));
  size_t pos = 0;
  for (auto& fr : frames) {
    uint32_t n = static_cast<uint32_t>(fr.size());
    memcpy(buf + pos, &n, 4);
    pos += 4;
    memcpy(buf + pos, fr.data(), fr.size());
    pos += fr.size();
  }
  *out = buf;
  *out_len = total;
  return static_cast<int>(st);
}

void emqx_framer_destroy(void* f) {
  delete static_cast<emqx_native::Framer*>(f);
}

void emqx_buf_free(void* p) { free(p); }

}  // extern "C"
