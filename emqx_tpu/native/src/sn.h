// MQTT-SN 1.2 for the native plane — the C++ twin of
// gateway/mqttsn.py (which stays the asyncio oracle and the
// conformance reference; tests/test_native_sn.py drives BOTH planes
// through one shared vector set so the codecs cannot drift apart).
// Shared by host.cc (gateway side: datagram decode, SN<->MQTT
// translation, delivery encode) and loadgen.cc (client side: the SN
// publisher/subscriber fleet for the mixed-protocol bench), so the two
// ends are framed by the same functions and a bug cannot hide behind a
// matching bug — the ws.h discipline applied to the UDP gateway.
//
// Wire shape (MQTT-SN 1.2 §5.2): one datagram carries one or more
// messages, each [len u8][type u8][body] — or, for len >= 256,
// [0x01][len u16 BE][type u8][body] where len covers the 3-byte
// prefix. All multi-byte integers are big-endian.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <vector>

namespace emqx_native {
namespace sn {

// message types (§5.2.1)
constexpr uint8_t kAdvertise = 0x00;
constexpr uint8_t kSearchGw = 0x01;
constexpr uint8_t kGwInfo = 0x02;
constexpr uint8_t kConnect = 0x04;
constexpr uint8_t kConnack = 0x05;
constexpr uint8_t kWillTopicReq = 0x06;
constexpr uint8_t kWillMsgReq = 0x08;
constexpr uint8_t kRegister = 0x0A;
constexpr uint8_t kRegack = 0x0B;
constexpr uint8_t kPublish = 0x0C;
constexpr uint8_t kPuback = 0x0D;
constexpr uint8_t kPubcomp = 0x0E;
constexpr uint8_t kPubrec = 0x0F;
constexpr uint8_t kPubrel = 0x10;
constexpr uint8_t kSubscribe = 0x12;
constexpr uint8_t kSuback = 0x13;
constexpr uint8_t kUnsubscribe = 0x14;
constexpr uint8_t kUnsuback = 0x15;
constexpr uint8_t kPingReq = 0x16;
constexpr uint8_t kPingResp = 0x17;
constexpr uint8_t kDisconnect = 0x18;

// return codes (§5.3.10)
constexpr uint8_t kRcAccepted = 0;
constexpr uint8_t kRcCongestion = 1;
constexpr uint8_t kRcInvalidTopicId = 2;
constexpr uint8_t kRcNotSupported = 3;

// flag bits (§5.3.4)
constexpr uint8_t kFDup = 0x80;
constexpr uint8_t kFRetain = 0x10;
constexpr uint8_t kFWill = 0x08;
constexpr uint8_t kFClean = 0x04;

// topic-id kinds (flags bits 0-1)
constexpr uint8_t kTidNormal = 0;
constexpr uint8_t kTidPredef = 1;
constexpr uint8_t kTidShort = 2;

// Packed-datagram cap: both ends aggregate consecutive small messages
// to the same peer into one datagram up to this size (§5.2 allows any
// number of messages per datagram; one MTU keeps aggregates fragment-
// free on real networks). A single message larger than the cap still
// goes out alone — the cap bounds aggregation, not message size.
constexpr size_t kPackDatagram = 1400;

// The long-form length prefix is a u16, so no SN message may exceed
// 65535 wire bytes (§5.2.1). Deliveries that cannot fit are DROPPED at
// the translation seam — silently truncating the length field would
// corrupt the egress stream (the peer's carve would misparse payload
// bytes as message boundaries). PUBLISH wire overhead = 2 (long-form
// length) + 7 (len byte + type + flags + tid + mid); REGISTER = 2 + 6
// plus the topic name.
constexpr size_t kMaxPayload = 0xFFFF - 9;
constexpr size_t kMaxTopic = 0xFFFF - 8;

// flags qos field: 0b11 encodes the spec's QoS -1 (§6.8)
inline int QosOf(uint8_t flags) {
  int q = (flags >> 5) & 3;
  return q == 3 ? -1 : q;
}

inline uint8_t QosFlags(int qos) {
  return qos < 0 ? 0x60 : static_cast<uint8_t>((qos & 3) << 5);
}

struct SnMsg {
  uint8_t type = 0;
  uint8_t flags = 0;
  uint16_t topic_id = 0;
  uint16_t msg_id = 0;
  uint16_t duration = 0;
  uint8_t rc = 0;
  std::string topic_name;
  std::string clientid;
  std::string data;
};

inline uint16_t Be16(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}

// Decode one message body (type byte + fields). Field offsets mirror
// gateway/mqttsn.py _parse_body exactly; where the Python oracle would
// raise on a truncated body (struct.unpack_from) and the listener drops
// the datagram, this returns false and the caller skips the message —
// the same observable outcome.
inline bool ParseBody(const uint8_t* b, size_t n, SnMsg* m) {
  if (n == 0) return false;
  m->type = b[0];
  uint8_t t = m->type;
  if (t == kConnect) {
    if (n < 5) return false;
    m->flags = b[1];
    m->duration = Be16(b + 3);
    m->clientid.assign(reinterpret_cast<const char*>(b + 5), n - 5);
  } else if (t == kConnack || t == kWillTopicReq || t == kWillMsgReq ||
             t == kPingResp) {
    if (n > 1) m->rc = b[1];
  } else if (t == kRegister) {
    if (n < 5) return false;
    m->topic_id = Be16(b + 1);
    m->msg_id = Be16(b + 3);
    m->topic_name.assign(reinterpret_cast<const char*>(b + 5), n - 5);
  } else if (t == kRegack) {
    if (n < 6) return false;
    m->topic_id = Be16(b + 1);
    m->msg_id = Be16(b + 3);
    m->rc = b[5];
  } else if (t == kPublish) {
    if (n < 6) return false;
    m->flags = b[1];
    m->topic_id = Be16(b + 2);
    m->msg_id = Be16(b + 4);
    m->data.assign(reinterpret_cast<const char*>(b + 6), n - 6);
  } else if (t == kPuback) {
    if (n < 6) return false;
    m->topic_id = Be16(b + 1);
    m->msg_id = Be16(b + 3);
    m->rc = b[5];
  } else if (t == kPubrec || t == kPubrel || t == kPubcomp ||
             t == kUnsuback) {
    if (n < 3) return false;
    m->msg_id = Be16(b + 1);
  } else if (t == kSubscribe || t == kUnsubscribe) {
    if (n < 4) return false;
    m->flags = b[1];
    m->msg_id = Be16(b + 2);
    if ((m->flags & 0x3) == kTidPredef) {
      if (n < 6) return false;
      m->topic_id = Be16(b + 4);
    } else {
      m->topic_name.assign(reinterpret_cast<const char*>(b + 4), n - 4);
    }
  } else if (t == kSuback) {
    if (n < 7) return false;
    m->flags = b[1];
    m->topic_id = Be16(b + 2);
    m->msg_id = Be16(b + 4);
    m->rc = b[6];
  } else if (t == kPingReq) {
    m->clientid.assign(reinterpret_cast<const char*>(b + 1), n - 1);
  } else if (t == kDisconnect) {
    if (n >= 3) m->duration = Be16(b + 1);
  } else if (t == kSearchGw) {
    if (n > 1) m->rc = b[1];  // radius
  }
  return true;
}

// Decode every message in one datagram (the oracle's Frame.parse loop:
// malformed length prefixes terminate the scan instead of spinning).
// A body too short for its type voids the WHOLE datagram — the oracle
// raises mid-parse there and the UDP listener drops the datagram, so
// none of its messages (even earlier valid ones) are ever applied.
inline void ParseAll(const uint8_t* d, size_t len, std::vector<SnMsg>* out) {
  size_t base = out->size();
  size_t pos = 0;
  while (pos < len) {
    size_t body_at, msg_len;
    if (d[pos] == 0x01) {
      if (len - pos < 3) break;
      msg_len = Be16(d + pos + 1);
      if (msg_len < 4) break;  // length covers the 3-byte prefix + type
      body_at = pos + 3;
    } else {
      msg_len = d[pos];
      if (msg_len < 2) break;  // 0/1 would not consume any bytes
      body_at = pos + 1;
    }
    if (pos + msg_len > len) break;  // truncated: refuse, don't spin
    SnMsg m;
    if (!ParseBody(d + body_at, pos + msg_len - body_at, &m)) {
      out->resize(base);  // datagram voided, oracle parity
      return;
    }
    out->push_back(std::move(m));
    pos += msg_len;
  }
}

inline void PutBe16(std::string* s, uint16_t v) {
  s->push_back(static_cast<char>(v >> 8));
  s->push_back(static_cast<char>(v & 0xFF));
}

// Prepend the length framing to a finished body (type byte included).
inline void Frame(std::string* out, const std::string& body) {
  size_t ln = body.size() + 1;
  if (ln < 256) {
    out->push_back(static_cast<char>(ln));
  } else {
    out->push_back(0x01);
    PutBe16(out, static_cast<uint16_t>(ln + 2));
  }
  *out += body;
}

// Serialize one message; field layouts mirror the oracle's
// Frame.serialize (including the parity-audit fixes: PINGREQ carries
// the clientid, DISCONNECT carries a nonzero sleep duration).
inline void Serialize(const SnMsg& m, std::string* out) {
  std::string body;
  uint8_t t = m.type;
  body.push_back(static_cast<char>(t));
  if (t == kConnack) {
    body.push_back(static_cast<char>(m.rc));
  } else if (t == kConnect) {
    body.push_back(static_cast<char>(m.flags));
    body.push_back(0x01);  // protocol id
    PutBe16(&body, m.duration);
    body += m.clientid;
  } else if (t == kRegister) {
    PutBe16(&body, m.topic_id);
    PutBe16(&body, m.msg_id);
    body += m.topic_name;
  } else if (t == kRegack) {
    PutBe16(&body, m.topic_id);
    PutBe16(&body, m.msg_id);
    body.push_back(static_cast<char>(m.rc));
  } else if (t == kPublish) {
    body.push_back(static_cast<char>(m.flags));
    PutBe16(&body, m.topic_id);
    PutBe16(&body, m.msg_id);
    body += m.data;
  } else if (t == kPuback) {
    PutBe16(&body, m.topic_id);
    PutBe16(&body, m.msg_id);
    body.push_back(static_cast<char>(m.rc));
  } else if (t == kPubrec || t == kPubrel || t == kPubcomp ||
             t == kUnsuback) {
    PutBe16(&body, m.msg_id);
  } else if (t == kSubscribe || t == kUnsubscribe) {
    body.push_back(static_cast<char>(m.flags));
    PutBe16(&body, m.msg_id);
    if ((m.flags & 0x3) == kTidPredef)
      PutBe16(&body, m.topic_id);
    else
      body += m.topic_name;
  } else if (t == kSuback) {
    body.push_back(static_cast<char>(m.flags));
    PutBe16(&body, m.topic_id);
    PutBe16(&body, m.msg_id);
    body.push_back(static_cast<char>(m.rc));
  } else if (t == kPingReq) {
    body += m.clientid;
  } else if (t == kPingResp) {
    // bare
  } else if (t == kDisconnect) {
    if (m.duration) PutBe16(&body, m.duration);
  } else if (t == kGwInfo) {
    body.push_back(static_cast<char>(m.rc));
  } else if (t == kAdvertise) {
    body.push_back(static_cast<char>(m.rc));
    PutBe16(&body, m.duration);
  }
  Frame(out, body);
}

// Append one SN PUBLISH datagram; reports the absolute offsets of the
// flags byte and the msg-id field inside *out so the delivery path can
// patch a freshly allocated packet id into a parked copy (the host's
// pending-queue discipline) and set DUP on a retransmit.
inline void BuildPublish(std::string* out, uint8_t flags, uint16_t topic_id,
                         uint16_t msg_id, std::string_view payload,
                         size_t* flags_off, size_t* mid_off) {
  size_t ln = 1 + 1 + 1 + 2 + 2 + payload.size();
  size_t base = out->size();
  if (ln < 256) {
    out->push_back(static_cast<char>(ln));
    base += 1;
  } else {
    out->push_back(0x01);
    PutBe16(out, static_cast<uint16_t>(ln + 2));
    base += 3;
  }
  out->push_back(static_cast<char>(kPublish));
  out->push_back(static_cast<char>(flags));
  PutBe16(out, topic_id);
  PutBe16(out, msg_id);
  out->append(payload.data(), payload.size());
  if (flags_off) *flags_off = base + 1;
  if (mid_off) *mid_off = base + 4;
}

}  // namespace sn
}  // namespace emqx_native
