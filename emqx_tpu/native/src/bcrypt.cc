// bcrypt (OpenBSD Blowfish password hashing) — the reference lists
// bcrypt as a native dep (mix.exs:635, used by
// emqx_authn_password_hashing.erl); the image ships no bcrypt wheel, so
// this is the from-scratch C++ primitive behind access/hashing.py.
//
// Two deliberate design points:
//
// 1. The Blowfish P-array and S-boxes are the first 18+1024 words of
//    the hexadecimal expansion of pi. Instead of embedding a 4 KiB
//    constant blob, InitTables() COMPUTES them at first use with a
//    fixed-point Machin formula (pi = 16*atan(1/5) - 4*atan(1/239))
//    over a little-endian u32 bignum — ~50 ms once, then cached. The
//    first word is asserted against the universally known 0x243F6A88.
//
// 2. The EksBlowfish schedule follows the OpenBSD structure
//    (Blowfish_expandstate / expand0state; bcrypt_hashpass): state
//    seeded from pi, salted expansion, then 2^cost alternating
//    key/salt expansions, then "OrpheanBeholderScryDoubt" enciphered
//    64 times; 23 of 24 output bytes are emitted in bcrypt's own
//    base64 alphabet. Verified against the published John-the-Ripper /
//    OpenBSD test vectors (tests/test_bcrypt.py).

#include <string.h>

#include <cstdint>
#include <cstdio>
#include <mutex>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// pi hex digits via fixed-point Machin

constexpr int kPiWords = 18 + 1024;  // P + 4 S-boxes
constexpr int kGuard = 2;            // truncation-guard limbs

// value is sum(limb[i] * 2^(32 i)); fixed size, little-endian
using Big = std::vector<uint32_t>;

void DivSmall(Big* a, uint32_t d) {
  uint64_t rem = 0;
  for (int i = static_cast<int>(a->size()) - 1; i >= 0; i--) {
    uint64_t cur = (rem << 32) | (*a)[i];
    (*a)[i] = static_cast<uint32_t>(cur / d);
    rem = cur % d;
  }
}

void AddInto(Big* a, const Big& b) {
  uint64_t carry = 0;
  for (size_t i = 0; i < a->size(); i++) {
    uint64_t cur = static_cast<uint64_t>((*a)[i]) + b[i] + carry;
    (*a)[i] = static_cast<uint32_t>(cur);
    carry = cur >> 32;
  }
}

void SubFrom(Big* a, const Big& b) {
  int64_t borrow = 0;
  for (size_t i = 0; i < a->size(); i++) {
    int64_t cur = static_cast<int64_t>((*a)[i]) - b[i] - borrow;
    borrow = cur < 0;
    (*a)[i] = static_cast<uint32_t>(cur + (borrow ? (1ll << 32) : 0));
  }
}

void MulSmall(Big* a, uint32_t m) {
  uint64_t carry = 0;
  for (size_t i = 0; i < a->size(); i++) {
    uint64_t cur = static_cast<uint64_t>((*a)[i]) * m + carry;
    (*a)[i] = static_cast<uint32_t>(cur);
    carry = cur >> 32;
  }
}

bool IsZero(const Big& a) {
  for (uint32_t w : a)
    if (w) return false;
  return true;
}

// atan(1/x) * 2^(32*(n-1)) for an n-limb working size (the top limb
// holds the integer part, which is 0 for x >= 2)
Big AtanInv(uint32_t x, size_t n) {
  Big term(n, 0);
  term[n - 1] = 1;                       // 1.0 in fixed point
  DivSmall(&term, x);                    // 1/x
  Big sum = term;
  uint32_t x2 = x * x;
  bool add = false;                      // next op after the first term
  for (uint32_t k = 3;; k += 2) {
    DivSmall(&term, x2);
    if (IsZero(term)) break;
    Big t = term;
    DivSmall(&t, k);
    if (add)
      AddInto(&sum, t);
    else
      SubFrom(&sum, t);
    add = !add;
  }
  return sum;
}

const uint32_t* PiWords() {
  static uint32_t words[kPiWords];
  static std::once_flag once;
  std::call_once(once, [] {
    size_t n = kPiWords + kGuard + 1;    // +1 limb for the integer part
    Big pi = AtanInv(5, n);
    MulSmall(&pi, 16);
    Big a239 = AtanInv(239, n);
    MulSmall(&a239, 4);
    SubFrom(&pi, a239);
    // integer part (3) lives in the top limb; the fraction's hex
    // digits follow MSB-first in the limbs below it
    for (int i = 0; i < kPiWords; i++)
      words[i] = pi[n - 2 - i];
    // the one constant everybody knows: P[0] = first 8 hex digits
    if (words[0] != 0x243F6A88u)
      words[0] = 0;  // poison => every vector test fails loudly
  });
  return words;
}

// ---------------------------------------------------------------------------
// Blowfish / EksBlowfish (OpenBSD structure)

struct BlfState {
  uint32_t P[18];
  uint32_t S[4][256];
};

inline uint32_t F(const BlfState& s, uint32_t x) {
  return ((s.S[0][x >> 24] + s.S[1][(x >> 16) & 0xFF]) ^
          s.S[2][(x >> 8) & 0xFF]) +
         s.S[3][x & 0xFF];
}

void Encipher(const BlfState& s, uint32_t* xl, uint32_t* xr) {
  uint32_t Xl = *xl ^ s.P[0];
  uint32_t Xr = *xr;
  for (int i = 1; i <= 16; i += 2) {
    Xr ^= F(s, Xl) ^ s.P[i];
    Xl ^= F(s, Xr) ^ s.P[i + 1];
  }
  *xl = Xr ^ s.P[17];
  *xr = Xl;
}

void InitState(BlfState* s) {
  const uint32_t* w = PiWords();
  memcpy(s->P, w, sizeof(s->P));
  memcpy(s->S, w + 18, sizeof(s->S));
}

// big-endian cyclic word stream over a byte buffer
inline uint32_t Stream2Word(const uint8_t* data, size_t len, size_t* j) {
  uint32_t w = 0;
  for (int i = 0; i < 4; i++) {
    w = (w << 8) | data[*j];
    *j = (*j + 1) % len;
  }
  return w;
}

void ExpandState(BlfState* s, const uint8_t* salt, size_t salt_len,
                 const uint8_t* key, size_t key_len) {
  size_t j = 0;
  for (int i = 0; i < 18; i++) s->P[i] ^= Stream2Word(key, key_len, &j);
  j = 0;
  uint32_t L = 0, R = 0;
  for (int i = 0; i < 18; i += 2) {
    L ^= Stream2Word(salt, salt_len, &j);
    R ^= Stream2Word(salt, salt_len, &j);
    Encipher(*s, &L, &R);
    s->P[i] = L;
    s->P[i + 1] = R;
  }
  for (int b = 0; b < 4; b++) {
    for (int i = 0; i < 256; i += 2) {
      L ^= Stream2Word(salt, salt_len, &j);
      R ^= Stream2Word(salt, salt_len, &j);
      Encipher(*s, &L, &R);
      s->S[b][i] = L;
      s->S[b][i + 1] = R;
    }
  }
}

void Expand0State(BlfState* s, const uint8_t* key, size_t key_len) {
  size_t j = 0;
  for (int i = 0; i < 18; i++) s->P[i] ^= Stream2Word(key, key_len, &j);
  uint32_t L = 0, R = 0;
  for (int i = 0; i < 18; i += 2) {
    Encipher(*s, &L, &R);
    s->P[i] = L;
    s->P[i + 1] = R;
  }
  for (int b = 0; b < 4; b++) {
    for (int i = 0; i < 256; i += 2) {
      Encipher(*s, &L, &R);
      s->S[b][i] = L;
      s->S[b][i + 1] = R;
    }
  }
}

// ---------------------------------------------------------------------------
// bcrypt proper

const char kB64[] =
    "./ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789";

int B64Index(char c) {
  for (int i = 0; i < 64; i++)
    if (kB64[i] == c) return i;
  return -1;
}

// bcrypt base64: 22 chars -> 16 bytes (salt)
bool DecodeSalt(const char* s22, uint8_t out[16]) {
  int bits = 0, acc = 0, n = 0;
  for (int i = 0; i < 22; i++) {
    int v = B64Index(s22[i]);
    if (v < 0) return false;
    acc = (acc << 6) | v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      if (n < 16) out[n++] = static_cast<uint8_t>((acc >> bits) & 0xFF);
    }
  }
  return n == 16;
}

void EncodeB64(const uint8_t* data, int len, char* out) {
  int bits = 0, acc = 0, n = 0;
  for (int i = 0; i < len; i++) {
    acc = (acc << 8) | data[i];
    bits += 8;
    while (bits >= 6) {
      bits -= 6;
      out[n++] = kB64[(acc >> bits) & 0x3F];
    }
  }
  if (bits) out[n++] = kB64[(acc << (6 - bits)) & 0x3F];
  out[n] = '\0';
}

}  // namespace

extern "C" {

// setting: "$2a$NN$<22-char salt>" (or $2b/$2y — identical for keys
// <= 72 bytes, which the caller enforces); out must hold >= 61 bytes.
// Returns 0 on success.
int emqx_bcrypt_hash(const uint8_t* password, size_t pw_len,
                     const char* setting, char* out) {
  if (strlen(setting) < 29 || setting[0] != '$' || setting[3] != '$' ||
      setting[6] != '$')
    return -1;
  char minor = setting[2];
  if (setting[1] != '2' ||
      (minor != 'a' && minor != 'b' && minor != 'y'))
    return -1;
  int cost = (setting[4] - '0') * 10 + (setting[5] - '0');
  if (cost < 4 || cost > 31) return -2;
  uint8_t salt[16];
  if (!DecodeSalt(setting + 7, salt)) return -3;
  if (pw_len > 72) pw_len = 72;

  // key = password + trailing NUL (the $2a/$2b convention)
  std::vector<uint8_t> key(pw_len + 1);
  memcpy(key.data(), password, pw_len);
  key[pw_len] = 0;

  BlfState s;
  InitState(&s);
  ExpandState(&s, salt, 16, key.data(), key.size());
  for (uint64_t k = 0; k < (1ull << cost); k++) {
    Expand0State(&s, key.data(), key.size());
    Expand0State(&s, salt, 16);
  }

  static const char kMagic[] = "OrpheanBeholderScryDoubt";  // 24 bytes
  uint32_t cdata[6];
  size_t j = 0;
  for (int i = 0; i < 6; i++)
    cdata[i] = Stream2Word(reinterpret_cast<const uint8_t*>(kMagic), 24, &j);
  for (int k = 0; k < 64; k++)
    for (int i = 0; i < 6; i += 2) Encipher(s, &cdata[i], &cdata[i + 1]);

  uint8_t digest[24];
  for (int i = 0; i < 6; i++) {
    digest[4 * i] = static_cast<uint8_t>(cdata[i] >> 24);
    digest[4 * i + 1] = static_cast<uint8_t>(cdata[i] >> 16);
    digest[4 * i + 2] = static_cast<uint8_t>(cdata[i] >> 8);
    digest[4 * i + 3] = static_cast<uint8_t>(cdata[i]);
  }
  memcpy(out, setting, 29);
  out[29] = '\0';
  EncodeB64(digest, 23, out + 29);      // bcrypt drops the 24th byte
  return 0;
}

// 16 random bytes -> "$2b$NN$<22 chars>" (caller supplies entropy so
// this stays a pure function; out >= 30 bytes)
int emqx_bcrypt_gensalt(int cost, const uint8_t rnd[16], char* out) {
  if (cost < 4 || cost > 31) return -1;
  snprintf(out, 8, "$2b$%02d$", cost);
  EncodeB64(rnd, 16, out + 7);
  return 0;
}

}  // extern "C"
