"""Native (C++) runtime components and their ctypes bindings.

The shared library is built on first use with the system toolchain and
cached next to the sources; ``available()`` gates every caller so the
pure-Python paths remain fully functional without a compiler.

Components (see ``src/``):

- ``frame.h``   — incremental MQTT frame splitter (emqx_frame.erl:163-217
  analogue, byte-level only);
- ``host.cc``   — epoll connection host: accept/read/frame/write in C++,
  complete frames exchanged with Python as compact event records (the
  SURVEY.md §2.4 "[NATIVE] BEAM schedulers/ports" replacement).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")

# EMQX_NATIVE_SANITIZE=address|thread builds/loads a sanitized variant
# (separate artifact; the sanitizer runtime must be LD_PRELOADed into the
# interpreter — see tests/test_native_sanitizers.py for the harness)
_SANITIZE = os.environ.get("EMQX_NATIVE_SANITIZE", "")
# EMQX_NATIVE_NOFAULT=1 builds the faultline-compiled-OUT variant
# (-DEMQX_NO_FAULTLINE): bench.py's fault_overhead section compares it
# against the normal binary to prove disarmed fault sites are free
_NOFAULT = os.environ.get("EMQX_NATIVE_NOFAULT", "") == "1"
_LIB_NAME = (f"libemqx_native.{_SANITIZE}.so" if _SANITIZE
             else "libemqx_native.nofault.so" if _NOFAULT
             else "libemqx_native.so")
_LIB_PATH = os.path.join(os.path.dirname(__file__), _LIB_NAME)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, f)) > lib_mtime
        for f in os.listdir(_SRC_DIR)
    )


def _build() -> None:
    cmd = [
        "g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
        os.path.join(_SRC_DIR, "host.cc"),
        os.path.join(_SRC_DIR, "snappy.cc"),
        os.path.join(_SRC_DIR, "loadgen.cc"),
        os.path.join(_SRC_DIR, "bcrypt.cc"),
        "-o", _LIB_PATH,
    ]
    if _SANITIZE:
        cmd[1:1] = [f"-fsanitize={_SANITIZE}", "-g",
                    "-fno-omit-frame-pointer"]
    elif _NOFAULT:
        cmd[1:1] = ["-DEMQX_NO_FAULTLINE"]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.emqx_host_create.restype = ctypes.c_void_p
    lib.emqx_host_create.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_int]
    lib.emqx_host_port.restype = ctypes.c_int
    lib.emqx_host_port.argtypes = [ctypes.c_void_p]
    lib.emqx_host_listen_ws.restype = ctypes.c_int
    lib.emqx_host_listen_ws.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
        ctypes.c_int]
    lib.emqx_shard_group_create.restype = ctypes.c_void_p
    lib.emqx_shard_group_create.argtypes = [ctypes.c_int]
    lib.emqx_shard_group_destroy.restype = None
    lib.emqx_shard_group_destroy.argtypes = [ctypes.c_void_p]
    lib.emqx_host_join_group.restype = ctypes.c_int
    lib.emqx_host_join_group.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_trunk_peer_state.restype = ctypes.c_int
    lib.emqx_host_trunk_peer_state.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.emqx_host_poll.restype = ctypes.c_long
    lib.emqx_host_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int]
    lib.emqx_host_send.restype = ctypes.c_int
    lib.emqx_host_send.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_size_t]
    lib.emqx_host_close_conn.restype = ctypes.c_int
    lib.emqx_host_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_host_enable_fast.restype = ctypes.c_int
    lib.emqx_host_enable_fast.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint32,
        ctypes.c_char_p]
    lib.emqx_host_trunk_ident.restype = ctypes.c_int
    lib.emqx_host_trunk_ident.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_host_disable_fast.restype = ctypes.c_int
    lib.emqx_host_disable_fast.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_host_sub_add.restype = ctypes.c_int
    lib.emqx_host_sub_add.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint8, ctypes.c_uint8]
    lib.emqx_host_sub_del.restype = ctypes.c_int
    lib.emqx_host_sub_del.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_host_permit.restype = ctypes.c_int
    lib.emqx_host_permit.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_host_shared_add.restype = ctypes.c_int
    lib.emqx_host_shared_add.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint8, ctypes.c_uint8]
    lib.emqx_host_shared_del.restype = ctypes.c_int
    lib.emqx_host_shared_del.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_subtable_shared_add.restype = None
    lib.emqx_subtable_shared_add.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint8, ctypes.c_uint8]
    lib.emqx_subtable_shared_del.restype = ctypes.c_int
    lib.emqx_subtable_shared_del.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_subtable_shared_pick.restype = ctypes.c_long
    lib.emqx_subtable_shared_pick.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_long,
        ctypes.POINTER(ctypes.c_long)]
    lib.emqx_subtable_match_many.restype = ctypes.c_long
    lib.emqx_subtable_match_many.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_long)]
    lib.emqx_subtable_shared_pick_many.restype = ctypes.c_long
    lib.emqx_subtable_shared_pick_many.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_long)]
    lib.emqx_host_permits_flush.restype = ctypes.c_int
    lib.emqx_host_permits_flush.argtypes = [ctypes.c_void_p]
    lib.emqx_host_set_lane.restype = ctypes.c_int
    lib.emqx_host_set_lane.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_lane_deliver.restype = ctypes.c_int
    lib.emqx_host_lane_deliver.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    lib.emqx_host_lane_backlog.restype = ctypes.c_long
    lib.emqx_host_lane_backlog.argtypes = [ctypes.c_void_p]
    lib.emqx_host_set_max_qos.restype = ctypes.c_int
    lib.emqx_host_set_max_qos.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_trunk_listen.restype = ctypes.c_int
    lib.emqx_host_trunk_listen.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int]
    lib.emqx_host_set_trunk_ack_timeout.restype = ctypes.c_int
    lib.emqx_host_set_trunk_ack_timeout.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_host_fault_arm.restype = ctypes.c_int
    lib.emqx_host_fault_arm.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_uint64, ctypes.c_uint64]
    lib.emqx_host_fault_fired.restype = ctypes.c_long
    lib.emqx_host_fault_fired.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_store_fault_arm.restype = ctypes.c_int
    lib.emqx_store_fault_arm.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_uint64, ctypes.c_uint64]
    lib.emqx_store_fault_fired.restype = ctypes.c_long
    lib.emqx_store_fault_fired.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_store_set_compact_age.restype = ctypes.c_int
    lib.emqx_store_set_compact_age.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_host_trunk_connect.restype = ctypes.c_int
    lib.emqx_host_trunk_connect.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint16]
    lib.emqx_host_trunk_disconnect.restype = ctypes.c_int
    lib.emqx_host_trunk_disconnect.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.emqx_host_trunk_route_add.restype = ctypes.c_int
    lib.emqx_host_trunk_route_add.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_host_trunk_route_del.restype = ctypes.c_int
    lib.emqx_host_trunk_route_del.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_host_set_trace.restype = ctypes.c_int
    lib.emqx_host_set_trace.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int]
    lib.emqx_host_set_telemetry.restype = ctypes.c_int
    lib.emqx_host_set_telemetry.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64]
    lib.emqx_host_set_tracing.restype = ctypes.c_int
    lib.emqx_host_set_tracing.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    lib.emqx_host_set_trunk_wire.restype = ctypes.c_int
    lib.emqx_host_set_trunk_wire.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_set_inflight_cap.restype = ctypes.c_int
    lib.emqx_host_set_inflight_cap.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.emqx_store_open.restype = ctypes.c_void_p
    lib.emqx_store_open.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
    lib.emqx_store_close.restype = None
    lib.emqx_store_close.argtypes = [ctypes.c_void_p]
    lib.emqx_store_register.restype = ctypes.c_uint64
    lib.emqx_store_register.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.emqx_store_lookup.restype = ctypes.c_uint64
    lib.emqx_store_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.emqx_store_append.restype = ctypes.c_uint64
    lib.emqx_store_append.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint8,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint16,
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
        ctypes.c_uint32, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint8]
    lib.emqx_store_unregister.restype = ctypes.c_int
    lib.emqx_store_unregister.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_store_put_session.restype = ctypes.c_int
    lib.emqx_store_put_session.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint32]
    lib.emqx_store_sessions.restype = ctypes.c_long
    lib.emqx_store_sessions.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_store_trunk_put.restype = ctypes.c_int
    lib.emqx_store_trunk_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_uint8, ctypes.c_char_p, ctypes.c_size_t]
    lib.emqx_store_trunk_ack.restype = ctypes.c_int
    lib.emqx_store_trunk_ack.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.emqx_store_trunk_fetch.restype = ctypes.c_long
    lib.emqx_store_trunk_fetch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_store_trunk_pending.restype = ctypes.c_long
    lib.emqx_store_trunk_pending.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p]
    lib.emqx_store_consume.restype = ctypes.c_long
    lib.emqx_store_consume.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32]
    lib.emqx_store_fetch.restype = ctypes.c_long
    lib.emqx_store_fetch.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_store_pending.restype = ctypes.c_long
    lib.emqx_store_pending.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_store_gc.restype = ctypes.c_long
    lib.emqx_store_gc.argtypes = [ctypes.c_void_p]
    lib.emqx_store_sync.restype = ctypes.c_int
    lib.emqx_store_sync.argtypes = [ctypes.c_void_p]
    lib.emqx_store_stat.restype = ctypes.c_long
    lib.emqx_store_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_attach_store.restype = ctypes.c_int
    lib.emqx_host_attach_store.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.emqx_host_durable_add.restype = ctypes.c_int
    lib.emqx_host_durable_add.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint8]
    lib.emqx_host_durable_del.restype = ctypes.c_int
    lib.emqx_host_durable_del.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_host_note_stage.restype = ctypes.c_int
    lib.emqx_host_note_stage.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64]
    lib.emqx_host_listen_sn.restype = ctypes.c_int
    lib.emqx_host_listen_sn.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int,
        ctypes.c_int]
    lib.emqx_host_sn_predefined.restype = ctypes.c_int
    lib.emqx_host_sn_predefined.argtypes = [
        ctypes.c_void_p, ctypes.c_uint16, ctypes.c_char_p]
    lib.emqx_host_set_retained.restype = ctypes.c_int
    lib.emqx_host_set_retained.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint32, ctypes.c_uint8, ctypes.c_uint64]
    lib.emqx_host_retain_del.restype = ctypes.c_int
    lib.emqx_host_retain_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.emqx_host_retain_deliver.restype = ctypes.c_int
    lib.emqx_host_retain_deliver.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint8]
    lib.emqx_host_set_telemetry_shift.restype = ctypes.c_int
    lib.emqx_host_set_telemetry_shift.argtypes = [
        ctypes.c_void_p, ctypes.c_int]
    lib.emqx_sn_roundtrip.restype = ctypes.c_long
    lib.emqx_sn_roundtrip.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_host_listen_coap.restype = ctypes.c_int
    lib.emqx_host_listen_coap.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int]
    lib.emqx_host_coap_send.restype = ctypes.c_int
    lib.emqx_host_coap_send.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint32]
    lib.emqx_host_coap_retain_state.restype = ctypes.c_int
    lib.emqx_host_coap_retain_state.argtypes = [
        ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_set_coap_ack_timeout.restype = ctypes.c_int
    lib.emqx_host_set_coap_ack_timeout.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_coap_roundtrip.restype = ctypes.c_long
    lib.emqx_coap_roundtrip.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_loadgen_run_coap.restype = ctypes.c_int
    lib.emqx_loadgen_run_coap.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint8,
        ctypes.c_uint32, ctypes.c_int, ctypes.c_uint32, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)]
    lib.emqx_loadgen_run_sn.restype = ctypes.c_int
    lib.emqx_loadgen_run_sn.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint8,
        ctypes.c_uint32, ctypes.c_int, ctypes.c_uint32, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.emqx_subtable_match_filter.restype = ctypes.c_long
    lib.emqx_subtable_match_filter.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_long]
    lib.emqx_host_stat.restype = ctypes.c_long
    lib.emqx_host_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.emqx_host_conn_idle_ms.restype = ctypes.c_long
    lib.emqx_host_conn_idle_ms.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.emqx_subtable_create.restype = ctypes.c_void_p
    lib.emqx_subtable_create.argtypes = []
    lib.emqx_subtable_destroy.restype = None
    lib.emqx_subtable_destroy.argtypes = [ctypes.c_void_p]
    lib.emqx_subtable_add.restype = None
    lib.emqx_subtable_add.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint8, ctypes.c_uint8]
    lib.emqx_subtable_del.restype = ctypes.c_int
    lib.emqx_subtable_del.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.emqx_subtable_match.restype = ctypes.c_long
    lib.emqx_subtable_match.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_long]
    lib.emqx_bcrypt_hash.restype = ctypes.c_int
    lib.emqx_bcrypt_hash.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p]
    lib.emqx_bcrypt_gensalt.restype = ctypes.c_int
    lib.emqx_bcrypt_gensalt.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]
    lib.emqx_loadgen_run.restype = ctypes.c_int
    lib.emqx_loadgen_run.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint8, ctypes.c_uint32, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint64)]
    lib.emqx_host_set_keepalive.restype = ctypes.c_int
    lib.emqx_host_set_keepalive.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.emqx_host_set_park.restype = ctypes.c_int
    lib.emqx_host_set_park.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint64]
    lib.emqx_host_synth_conns.restype = ctypes.c_int
    lib.emqx_host_synth_conns.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_char_p]
    lib.emqx_host_conn_counts.restype = ctypes.c_int
    lib.emqx_host_conn_counts.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.emqx_wheel_selftest.restype = ctypes.c_long
    lib.emqx_wheel_selftest.argtypes = [
        ctypes.c_uint64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_loadgen_conn_scale.restype = ctypes.c_int
    lib.emqx_loadgen_conn_scale.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint16, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.emqx_host_destroy.restype = None
    lib.emqx_host_destroy.argtypes = [ctypes.c_void_p]
    lib.emqx_framer_create.restype = ctypes.c_void_p
    lib.emqx_framer_create.argtypes = [ctypes.c_uint32]
    lib.emqx_framer_feed.restype = ctypes.c_int
    lib.emqx_framer_feed.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.emqx_framer_destroy.restype = None
    lib.emqx_framer_destroy.argtypes = [ctypes.c_void_p]
    lib.emqx_buf_free.restype = None
    lib.emqx_buf_free.argtypes = [ctypes.c_void_p]
    lib.emqx_snappy_max_compressed.restype = ctypes.c_long
    lib.emqx_snappy_max_compressed.argtypes = [ctypes.c_long]
    lib.emqx_snappy_compress.restype = ctypes.c_long
    lib.emqx_snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long]
    lib.emqx_snappy_uncompressed_length.restype = ctypes.c_long
    lib.emqx_snappy_uncompressed_length.argtypes = [
        ctypes.c_char_p, ctypes.c_long]
    lib.emqx_snappy_decompress.restype = ctypes.c_long
    lib.emqx_snappy_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Build (if stale) and load the native library; None if unavailable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        try:
            if _needs_build():
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except (OSError, subprocess.CalledProcessError) as e:
            _build_error = (
                e.stderr if isinstance(e, subprocess.CalledProcessError)
                else str(e))
            return None
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> Optional[str]:
    return _build_error


# ---------------------------------------------------------------------------
# thin object wrappers


class NativeFramer:
    """ctypes wrapper over the C++ incremental framer (parity-test surface)."""

    def __init__(self, max_size: int = 0x0FFFFFFF):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        self._h = self._lib.emqx_framer_create(max_size)

    def feed(self, data: bytes) -> list[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        st = self._lib.emqx_framer_feed(
            self._h, data, len(data), ctypes.byref(out), ctypes.byref(out_len))
        raw = ctypes.string_at(out, out_len.value)
        self._lib.emqx_buf_free(out)
        frames, pos = [], 0
        while pos < len(raw):
            n = int.from_bytes(raw[pos:pos + 4], "little")
            pos += 4
            frames.append(raw[pos:pos + n])
            pos += n
        if st != 0:
            raise ValueError(f"frame error status={st}")
        return frames

    def close(self) -> None:
        if self._h:
            self._lib.emqx_framer_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# event kinds from host.cc
EV_OPEN, EV_FRAME, EV_CLOSED, EV_LANE, EV_TAP, EV_ACKS = 1, 2, 3, 4, 6, 7
EV_TELEMETRY = 8
EV_TRUNK = 9
EV_DURABLE = 10     # batched durable-store record (round 10)
EV_HANDOFF = 11     # live plane demotion: AckState -> Python session
EV_SPANS = 12       # distributed-tracing spans + ledger (round 13)
EV_COAP = 13        # CoAP exchange degraded whole to the Python oracle
                    # (round 19): payload = the raw datagram verbatim


def parse_durable(payload: bytes) -> tuple[int, int, list[tuple]]:
    """Decode one kind-10 durable record into ``(base_guid, ts_ms,
    [(origin_conn, flags, [tokens], topic, payload, trace_id, cid),
    ...])`` — entry i's guid is ``base_guid + i``; flags bits1-2 =
    qos, bit3 = publisher DUP (bit0 = payload-inline, bit4 =
    trace-id-present and bit5 = clientid-present are resolved here;
    trace_id is 0 for unsampled publishes, cid is "" when the
    publisher's clientid was unknown)."""
    base = int.from_bytes(payload[0:8], "little")
    ts = int.from_bytes(payload[8:16], "little")
    n = int.from_bytes(payload[16:20], "little")
    out: list[tuple] = []
    pos, blen = 20, len(payload)
    body = b""
    for _ in range(n):
        if pos + 11 > blen:
            break
        origin = int.from_bytes(payload[pos:pos + 8], "little")
        flags = payload[pos + 8]
        ntok = int.from_bytes(payload[pos + 9:pos + 11], "little")
        pos += 11
        if pos + 8 * ntok + 2 > blen:
            break
        toks = [int.from_bytes(payload[pos + 8 * i:pos + 8 * i + 8],
                               "little") for i in range(ntok)]
        pos += 8 * ntok
        tlen = int.from_bytes(payload[pos:pos + 2], "little")
        pos += 2
        topic = payload[pos:pos + tlen].decode("utf-8", "replace")
        pos += tlen
        trace = 0
        if flags & 0x10:
            if pos + 8 > blen:
                break
            trace = int.from_bytes(payload[pos:pos + 8], "little")
            pos += 8
        cid = ""
        if flags & 0x20:
            if pos + 1 > blen:
                break
            cl = payload[pos]
            cid = payload[pos + 1:pos + 1 + cl].decode("utf-8", "replace")
            pos += 1 + cl
        if flags & 1:
            if pos + 4 > blen:
                break
            plen = int.from_bytes(payload[pos:pos + 4], "little")
            pos += 4
            body = payload[pos:pos + plen]
            pos += plen
        out.append((origin, flags, toks, topic, body, trace, cid))
    return base, ts, out


def parse_handoff(payload: bytes) -> dict:
    """Decode one kind-11 demotion-handoff record:

    - sub 1 → ``{"awaiting": [pid...], "inflight": [(pid, qos, phase)]}``
      (phase "publish" | "pubrel")
    - sub 2 → ``{"pending": [frame bytes, ...]}``

    Chunks are additive: callers merge the fields across records."""
    out: dict = {"awaiting": [], "inflight": [], "pending": []}
    if not payload:
        return out
    sub = payload[0]
    pos = 1
    if sub == 1:
        n_aw = int.from_bytes(payload[pos:pos + 4], "little")
        pos += 4
        for _ in range(n_aw):
            out["awaiting"].append(
                int.from_bytes(payload[pos:pos + 2], "little"))
            pos += 2
        n_if = int.from_bytes(payload[pos:pos + 4], "little")
        pos += 4
        for _ in range(n_if):
            pid = int.from_bytes(payload[pos:pos + 2], "little")
            st = payload[pos + 2]
            pos += 3
            out["inflight"].append(
                (pid, 2 if st & 1 else 1,
                 "pubrel" if st & 2 else "publish"))
    elif sub == 2:
        n = int.from_bytes(payload[pos:pos + 4], "little")
        pos += 4
        for _ in range(n):
            fl = int.from_bytes(payload[pos:pos + 4], "little")
            pos += 4
            out["pending"].append(payload[pos:pos + fl])
            pos += fl
    return out

# kind-9 trunk event sub-kinds (payload[0])
TRUNK_UP, TRUNK_DOWN, TRUNK_PUNT = 1, 2, 3


def parse_trunk_punts(payload: bytes) -> list[tuple]:
    """Decode one kind-9 sub-3 record (receiver-side trunk punts) into
    ``(origin_conn, qos, dup, topic, payload)`` tuples. Payloads are
    always inline in punt records (host.cc TrunkPuntAppend); a trace id
    (flags bit4) is skipped — the message is leaving the native plane."""
    out: list[tuple] = []
    pos, n = 1, len(payload)
    while pos + 11 <= n:
        origin = int.from_bytes(payload[pos:pos + 8], "little")
        flags = payload[pos + 8]
        tlen = int.from_bytes(payload[pos + 9:pos + 11], "little")
        pos += 11
        topic = payload[pos:pos + tlen].decode("utf-8", "replace")
        pos += tlen
        if flags & 0x10:
            pos += 8          # trace_id: Python dispatch is untraced
        if pos + 4 > n:
            break
        plen = int.from_bytes(payload[pos:pos + 4], "little")
        pos += 4
        body = payload[pos:pos + plen]
        pos += plen
        out.append((origin, (flags >> 1) & 3, bool(flags & 8), topic, body))
    return out

# ---------------------------------------------------------------------------
# native telemetry plane (host.cc kind-8 records)

# histogram stage order (host.cc HistStage enum)
HIST_STAGES = ("ingress_route", "route_flush", "qos1_rtt", "qos2_rtt",
               "lane_dwell", "gil_stint", "ws_ingest",
               # trunk stages (round 9): trunk_rtt = batch flush →
               # peer ack; trunk_batch_n records ENTRIES per flushed
               # batch (occupancy — a count, not nanoseconds)
               "trunk_rtt", "trunk_batch_n",
               # durable plane (round 10): store_append = per-batch
               # store write (+policy fsync); replay_drain = resume
               # replay fetch+consume+decode (noted by Python via
               # emqx_host_note_stage on the poll thread)
               "store_append", "replay_drain",
               # edge-gateway plane (round 11): sn_ingest = sampled SN
               # datagram decode+dispatch; retain_deliver = one
               # SUBSCRIBE-triggered retained snapshot lookup+write
               "sn_ingest", "retain_deliver",
               # multi-core shards (round 12): ENTRIES per applied
               # cross-shard ring batch (occupancy — a count, the
               # trunk_batch_n convention, not nanoseconds)
               "shard_ring_n",
               # coap gateway plane (round 19): coap_ingest = sampled
               # CoAP datagram decode+dispatch; observe_notify = one
               # observe notification resolve+encode+write
               "coap_ingest", "observe_notify")

# flight-recorder event codes (host.cc FrEvent)
FR_EVENT_NAMES = {1: "open", 2: "frame", 3: "punt", 4: "fast_pub",
                  5: "deliver", 6: "drop", 7: "ack",
                  # round 13: cross-plane legs on the publisher's
                  # recorder (the FR used to go blind off-shard)
                  8: "ring_cross", 9: "trunk"}
# dump reasons (host.cc FrReason)
FR_REASON_NAMES = {1: "abnormal_close", 2: "protocol_error", 3: "trace"}

# ---------------------------------------------------------------------------
# native distributed tracing (host.cc kind-12 records, round 13)

# span stage order (host.cc SpanStage enum — the stats-lint guards the
# mapping mechanically, like HIST_STAGES)
SPAN_STAGES = ("ingress", "route", "ring_cross", "trunk_flush",
               "trunk_recv", "store_append", "replay", "deliver_write",
               "ack")

# degradation-ledger reasons. The C++ LedgerReason enum is a PREFIX of
# this tuple (ring_full/trunk_punt/shed/fault fold below the GIL);
# device_failover and store_degraded are Python-plane decisions folded
# into the same ledger by broker/native_server.py and broker/broker.py.
# "fault" (round 15) is a faultline injection firing — chaos lands in
# the SAME ledger as organic degradation (aux = the fault-site index).
# "accept_shed" (round 16) is the accept-storm rung: admission denied
# in the accept loop before any conn side effect (conn-scale plane).
# "kernel_overflow" / "kernel_hostmatch" (ISSUE 18) are the device
# router's degradation legs — K/M/ret_cap spill falling back to the
# host oracle, and a whole batch served by the cpu host-matcher —
# folded by broker/broker.py at the publish_batch_collect seam.
# Python-plane, so they append at the END (the C++ enum stays a prefix).
LEDGER_REASONS = ("ring_full", "trunk_punt", "shed", "fault",
                  "accept_shed", "coap_giveup",
                  "device_failover", "store_degraded",
                  "kernel_overflow", "kernel_hostmatch")

# ---------------------------------------------------------------------------
# faultline (round 15): deterministic fault injection (fault.h)

# fault-site order (fault.h Site enum — tests/test_stats_lint.py guards
# the mechanical mapping; the nativecheck `fault` rule guards that every
# site has an annotated C++ fire site exercised by a test)
FAULT_SITES = ("conn_read", "conn_write", "conn_accept",
               "trunk_read", "trunk_write", "trunk_accept",
               "trunk_connect", "store_msync", "store_seg_open",
               "ring_seal", "ring_doorbell", "housekeep_clock")

# fault modes (fault.h Mode enum): what an armed site does when it
# fires — see the fault.h header for per-site semantics
FAULT_MODES = {"off": 0, "errno": 1, "short": 2, "blackhole": 3,
               "full": 4, "skew": 5}


def fault_site_index(site: str) -> int:
    """Site name -> fault.h enum index; unknown names FAIL loudly (the
    sanitizer-lint discipline: a typo'd site must never arm nothing)."""
    try:
        return FAULT_SITES.index(site)
    except ValueError:
        raise ValueError(
            f"unknown fault site {site!r}; valid: {FAULT_SITES}") from None


def parse_spans(payload: bytes) -> list[tuple]:
    """Decode one kind-12 payload into its sub-records:

    - ``("span", trace_id, stage_idx, t_ns, aux)`` — one point on a
      sampled publish's timeline (stage indexes SPAN_STAGES);
    - ``("ledger", reason_idx, count, trace_id, aux, t_ns)`` — one
      folded degradation-ladder entry (reason 1-indexed into
      LEDGER_REASONS).

    Sub-records never split across kind-12 chunks (host.cc SpanAppend),
    so each payload parses independently; the producing shard rides the
    event record's id slot."""
    out: list[tuple] = []
    pos, n = 0, len(payload)
    while pos < n:
        sub = payload[pos]
        pos += 1
        if sub == 1:
            if pos + 25 > n:
                break
            out.append((
                "span",
                int.from_bytes(payload[pos:pos + 8], "little"),
                payload[pos + 8],
                int.from_bytes(payload[pos + 9:pos + 17], "little"),
                int.from_bytes(payload[pos + 17:pos + 25], "little"),
            ))
            pos += 25
        elif sub == 2:
            if pos + 33 > n:
                break
            out.append((
                "ledger",
                payload[pos],
                int.from_bytes(payload[pos + 1:pos + 9], "little"),
                int.from_bytes(payload[pos + 9:pos + 17], "little"),
                int.from_bytes(payload[pos + 17:pos + 25], "little"),
                int.from_bytes(payload[pos + 25:pos + 33], "little"),
            ))
            pos += 33
        else:
            break  # unknown sub-record kind: length unknowable, stop
    return out


# Declared field widths per event-record kind — what the decoders above
# (and native_server's folds) actually consume. tests/test_native_wire_
# lint.py parses the host.cc wire-format comment and asserts the
# [uNN name] token set per kind matches this table exactly, so a field
# added or widened on ONE side fails the build (the cross-plane
# analogue of the StatSlot lint).
WIRE_FIELDS: dict[int, frozenset] = {
    6: frozenset({("u64", "publisher"), ("u8", "flags"),
                  ("u16", "tlen"), ("u32", "plen")}),
    7: frozenset({("u32", "n"), ("u64", "conn"), ("u32", "acked"),
                  ("u32", "rel"), ("u32", "inflight_now"),
                  ("u32", "pending_now")}),
    8: frozenset({("u8", "stage"), ("u64", "count_d"), ("u64", "sum_d"),
                  ("u16", "n"), ("u8", "bucket"), ("u32", "delta"),
                  ("u64", "conn"), ("u8", "reason"), ("u8", "n"),
                  ("u32", "ts_ms"), ("u8", "event"), ("u8", "ptype"),
                  ("u16", "arg"), ("u32", "topic_hash"), ("u32", "arg2"),
                  ("u32", "rtt_us"), ("u8", "qos"), ("u16", "tlen")}),
    9: frozenset({("u64", "origin"), ("u8", "flags"), ("u16", "tlen"),
                  ("u64", "trace_id"), ("u32", "plen")}),
    10: frozenset({("u64", "base_guid"), ("u64", "ts_ms"), ("u32", "n"),
                   ("u64", "origin"), ("u8", "flags"), ("u16", "ntok"),
                   ("u64", "token"), ("u16", "tlen"),
                   ("u64", "trace_id"), ("u8", "cidlen"),
                   ("u32", "plen")}),
    11: frozenset({("u32", "n_aw"), ("u16", "pid"), ("u32", "n_if"),
                   ("u8", "state"), ("u32", "n"), ("u32", "len")}),
    12: frozenset({("u64", "trace_id"), ("u8", "stage"), ("u64", "t_ns"),
                   ("u64", "aux"), ("u8", "reason"), ("u64", "count")}),
    # kind 13 carries the raw CoAP datagram verbatim — no fields
    13: frozenset(),
}


def parse_telemetry(payload: bytes) -> list[tuple]:
    """Decode one kind-8 payload into its sub-records:

    - ``("hist", stage_idx, count_delta, sum_delta_ns, {bucket: delta})``
    - ``("flight", conn_id, reason, [(ts_ms, event, ptype, arg, topic_hash,
      arg2), ...])``
    - ``("slow_ack", conn_id, rtt_us, qos, topic)``

    Sub-records never split across kind-8 chunks (host.cc TeleAppend),
    so each payload parses independently; histogram deltas from every
    chunk sum to the C++ totals exactly."""
    out: list[tuple] = []
    pos, n = 0, len(payload)
    while pos < n:
        sub = payload[pos]
        pos += 1
        if sub == 1:
            stage = payload[pos]
            cnt = int.from_bytes(payload[pos + 1:pos + 9], "little")
            sum_ns = int.from_bytes(payload[pos + 9:pos + 17], "little")
            nb = int.from_bytes(payload[pos + 17:pos + 19], "little")
            pos += 19
            buckets = {}
            for _ in range(nb):
                buckets[payload[pos]] = int.from_bytes(
                    payload[pos + 1:pos + 5], "little")
                pos += 5
            out.append(("hist", stage, cnt, sum_ns, buckets))
        elif sub == 2:
            conn = int.from_bytes(payload[pos:pos + 8], "little")
            reason = payload[pos + 8]
            cnt = payload[pos + 9]
            pos += 10
            entries = []
            for _ in range(cnt):
                entries.append((
                    int.from_bytes(payload[pos:pos + 4], "little"),
                    payload[pos + 4], payload[pos + 5],
                    int.from_bytes(payload[pos + 6:pos + 8], "little"),
                    int.from_bytes(payload[pos + 8:pos + 12], "little"),
                    int.from_bytes(payload[pos + 12:pos + 16], "little"),
                ))
                pos += 16
            out.append(("flight", conn, reason, entries))
        elif sub == 3:
            conn = int.from_bytes(payload[pos:pos + 8], "little")
            rtt_us = int.from_bytes(payload[pos + 8:pos + 12], "little")
            qos = payload[pos + 12]
            tl = int.from_bytes(payload[pos + 13:pos + 15], "little")
            pos += 15
            topic = payload[pos:pos + tl].decode("utf-8", "replace")
            pos += tl
            out.append(("slow_ack", conn, rtt_us, qos, topic))
        else:
            break  # unknown sub-record kind: length unknowable, stop
    return out


def format_flight(entries: list[tuple]) -> list[str]:
    """Human-readable flight-recorder lines (for trace logs / debug)."""
    lines = []
    base = entries[0][0] if entries else 0
    for ts_ms, event, ptype, arg, topic_hash, _arg2 in entries:
        name = FR_EVENT_NAMES.get(event, f"ev{event}")
        part = f"+{ts_ms - base}ms {name} ptype={ptype} arg={arg}"
        if topic_hash:
            part += f" topic#{topic_hash:08x}"
        lines.append(part)
    return lines

def loadgen_run(host: str, port: int, n_subs: int, n_pubs: int,
                msgs_per_pub: int, qos: int = 0, payload_len: int = 16,
                proto_ver: int = 4, idle_timeout_ms: int = 5000,
                window: int = 0, warmup: bool = True,
                ws: bool = False, salt: int = 0) -> dict:
    """Run the native load generator (loadgen.cc) against a broker.
    Blocks for the duration of the run (ctypes releases the GIL, so an
    in-process broker keeps serving). ``window=0`` blasts for peak
    throughput; ``window>0`` caps total in-flight messages so the
    latency percentiles measure the broker, not loadgen queue depth.
    ``ws=True`` runs the fleet over MQTT-over-WebSocket (point ``port``
    at a WS listener). ``salt`` offsets clientids AND the lg/<i> topic
    space so two fleets (e.g. the mixed bench's TCP + WS arms) can run
    concurrently against one broker without takeover kicks or
    cross-plane fan-out. Returns sent/received counts, wall ns and
    latency percentiles."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = (ctypes.c_uint64 * 8)()
    rc = lib.emqx_loadgen_run(host.encode(), port, n_subs, n_pubs,
                              msgs_per_pub, qos, payload_len, proto_ver,
                              idle_timeout_ms, window, int(warmup),
                              int(ws), int(salt), out)
    if rc != 0:
        raise RuntimeError(f"loadgen failed rc={rc}")
    keys = ("sent", "received", "wall_ns", "p50_ns", "p99_ns", "max_ns",
            "acks", "errors")
    return dict(zip(keys, out))


def loadgen_sn_run(host: str, port: int, n_subs: int, n_pubs: int,
                   msgs_per_pub: int, qos: int = 0, payload_len: int = 16,
                   idle_timeout_ms: int = 5000, window: int = 0,
                   warmup: bool = True) -> dict:
    """Run the MQTT-SN/UDP load generator (loadgen.cc, the shared sn.h
    codec) against an SN gateway port — the native host's or the
    asyncio gateway's, so the mixed bench can compare the two planes on
    identical wire traffic. Pacing is always windowed (UDP has no
    transport backpressure); ``window=0`` defaults to 1024."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = (ctypes.c_uint64 * 8)()
    rc = lib.emqx_loadgen_run_sn(host.encode(), port, n_subs, n_pubs,
                                 msgs_per_pub, qos, payload_len,
                                 idle_timeout_ms, window, int(warmup),
                                 out)
    if rc != 0:
        raise RuntimeError(f"sn loadgen failed rc={rc}")
    keys = ("sent", "received", "wall_ns", "p50_ns", "p99_ns", "max_ns",
            "acks", "errors")
    return dict(zip(keys, out))


def wheel_selftest(seed: int, n_ops: int = 20000) -> list[tuple]:
    """Run the C++ timer wheel's seeded self-test script (wheel.h
    SelfTestScript) and decode its op/fire journal:

    - ``("arm", key, deadline_ms)``
    - ``("cancel", key)``
    - ``("advance", now_ms, [fired keys...])``

    The connscale test replays the journal through a brute-force
    oracle: fired sets must match {armed keys whose deadline, rounded
    up to the 16ms tick, is <= the advance clock's tick} exactly."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    lib.emqx_wheel_selftest(int(seed), int(n_ops), ctypes.byref(out),
                            ctypes.byref(out_len))
    raw = ctypes.string_at(out, out_len.value)
    lib.emqx_buf_free(out)
    events: list[tuple] = []
    pos, n = 0, len(raw)
    while pos < n:
        kind = raw[pos]
        pos += 1
        if kind == 2:
            events.append(("arm",
                           int.from_bytes(raw[pos:pos + 8], "little"),
                           int.from_bytes(raw[pos + 8:pos + 16],
                                          "little")))
            pos += 16
        elif kind == 3:
            events.append(("cancel",
                           int.from_bytes(raw[pos:pos + 8], "little")))
            pos += 8
        elif kind == 1:
            now = int.from_bytes(raw[pos:pos + 8], "little")
            fired_n = int.from_bytes(raw[pos + 8:pos + 16], "little")
            pos += 16
            fired = [int.from_bytes(raw[pos + 8 * i:pos + 8 * i + 8],
                                    "little") for i in range(fired_n)]
            pos += 8 * fired_n
            events.append(("advance", now, fired))
        else:
            raise ValueError(f"bad selftest record kind {kind}")
    return events


def loadgen_conn_scale(host: str, port: int, n_conns: int,
                       burst: int = 512, keepalive_s: int = 30,
                       sub_every: int = 0, hold_ms: int = 5000,
                       proto_ver: int = 4, stop=None, live=None) -> dict:
    """Run the conn-scale herd (loadgen.cc): a connect storm of
    ``n_conns`` mostly-idle clients that then hold for ``hold_ms``
    honoring staggered keepalives; PINGREQ round trips are the
    keepalive-latency probe. ``stop``/``live`` are optional
    ctypes.c_int32 / (ctypes.c_uint64 * 4) the caller polls/sets from
    another thread (ctypes releases the GIL for the whole call)."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = (ctypes.c_uint64 * 8)()
    rc = lib.emqx_loadgen_conn_scale(
        host.encode(), port, int(n_conns), int(burst), int(keepalive_s),
        int(sub_every), int(hold_ms), int(proto_ver),
        ctypes.byref(stop) if stop is not None else None,
        ctypes.cast(live, ctypes.POINTER(ctypes.c_uint64))
        if live is not None else None,
        out)
    if rc != 0:
        raise RuntimeError(f"conn-scale loadgen failed rc={rc}")
    keys = ("connected", "errors", "pings", "ping_p50_ns", "ping_p99_ns",
            "ping_max_ns", "wall_ns", "broker_closes")
    return dict(zip(keys, out))


def sn_roundtrip(data: bytes) -> tuple[int, bytes]:
    """Parse + re-serialize SN datagram bytes with the NATIVE codec
    (sn.h); returns (message count, reserialized bytes). The codec
    parity test drives the Python oracle through the same vectors."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    n = lib.emqx_sn_roundtrip(data, len(data), ctypes.byref(out),
                              ctypes.byref(out_len))
    raw = ctypes.string_at(out, out_len.value)
    lib.emqx_buf_free(out)
    return int(n), raw


def coap_roundtrip(data: bytes) -> tuple[int, bytes]:
    """Parse + re-serialize one CoAP datagram with the NATIVE codec
    (coap.h); returns (message count — 0 or 1, reserialized bytes).
    The codec parity test drives the gateway/coap.py oracle through
    the same vectors."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    n = lib.emqx_coap_roundtrip(data, len(data), ctypes.byref(out),
                                ctypes.byref(out_len))
    raw = ctypes.string_at(out, out_len.value)
    lib.emqx_buf_free(out)
    return int(n), raw


def loadgen_coap_run(host: str, port: int, n_subs: int, n_pubs: int,
                     msgs_per_pub: int, qos: int = 0,
                     payload_len: int = 16, idle_timeout_ms: int = 8000,
                     window: int = 256, warmup: bool = True,
                     fanout: bool = False) -> dict:
    """CoAP observer/publisher fleet (loadgen.cc, shared coap.h codec):
    observers GET+Observe /ps topics, publishers POST to them (NON for
    qos0, CON with ?qos=1 for qos1 — acks gate the window). Runs
    IDENTICALLY against the native listener and the asyncio gateway,
    so both bench arms see the same wire traffic and pacing. With
    ``fanout`` every observer watches ONE topic (the fan-out arm)."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native lib unavailable: {_build_error}")
    out = (ctypes.c_uint64 * 8)()
    rc = lib.emqx_loadgen_run_coap(
        host.encode(), port, int(n_subs), int(n_pubs),
        int(msgs_per_pub), int(qos), int(payload_len),
        int(idle_timeout_ms), int(window), 1 if warmup else 0,
        1 if fanout else 0, out)
    if rc != 0:
        raise RuntimeError(f"coap loadgen failed rc={rc}")
    keys = ("sent", "received", "wall_ns", "p50_ns", "p99_ns", "max_ns",
            "acks", "errors")
    return dict(zip(keys, out))


class NativeSubTable:
    """Standalone wrapper over the C++ subscription table (router.h) —
    the differential-test surface against router/trie.py."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        self._h = self._lib.emqx_subtable_create()

    def add(self, owner: int, filter_: str, qos: int = 0,
            flags: int = 0) -> None:
        self._lib.emqx_subtable_add(self._h, owner, filter_.encode(),
                                    qos, flags)

    def remove(self, owner: int, filter_: str) -> bool:
        return bool(self._lib.emqx_subtable_del(self._h, owner,
                                                filter_.encode()))

    def match(self, topic: str) -> list[int]:
        cap = 256
        while True:
            buf = (ctypes.c_uint64 * cap)()
            n = self._lib.emqx_subtable_match(self._h, topic.encode(),
                                              buf, cap)
            if n <= cap:
                return list(buf[:n])
            cap = n

    def match_filter(self, filter_: str) -> list[int]:
        """Owners registered under EXACTLY this filter (the device
        lane's delivery lookup; differential-tested against match)."""
        cap = 256
        while True:
            buf = (ctypes.c_uint64 * cap)()
            n = self._lib.emqx_subtable_match_filter(
                self._h, filter_.encode(), buf, cap)
            if n <= cap:
                return list(buf[:n])
            cap = n

    def shared_add(self, token: int, owner: int, filter_: str,
                   qos: int = 0, flags: int = 0) -> None:
        self._lib.emqx_subtable_shared_add(self._h, token, owner,
                                           filter_.encode(), qos, flags)

    def shared_del(self, token: int, owner: int, filter_: str) -> bool:
        return bool(self._lib.emqx_subtable_shared_del(
            self._h, token, owner, filter_.encode()))

    def shared_pick(self, topic: str) -> list[tuple[int, int]]:
        """One rotating (group token, picked owner) per matched group.
        The C side is all-or-nothing: on overflow it writes nothing and
        advances no cursor (a partial pass would double-rotate on the
        retry), reporting the needed size — re-invoke bigger."""
        cap = 512
        while True:
            buf = (ctypes.c_uint64 * cap)()
            total = ctypes.c_long()
            n = self._lib.emqx_subtable_shared_pick(
                self._h, topic.encode(), buf, cap, ctypes.byref(total))
            if 2 * total.value <= cap:
                return [(buf[2 * i], buf[2 * i + 1]) for i in range(n)]
            cap = 2 * total.value + 2

    def match_many(self, topics: list[str]) -> tuple[int, int]:
        """Bulk match (bench surface): one C call for the whole topic
        batch. Returns (topics processed, total entries matched)."""
        blob = "\n".join(topics).encode()
        matches = ctypes.c_long()
        n = self._lib.emqx_subtable_match_many(
            self._h, blob, len(blob), ctypes.byref(matches))
        return n, matches.value

    def shared_pick_many(self, topics: list[str]) -> tuple[int, int]:
        """Bulk rotating picks (bench surface): one C call for the whole
        topic batch. Returns (topics processed, picks made)."""
        blob = "\n".join(topics).encode()
        picks = ctypes.c_long()
        n = self._lib.emqx_subtable_shared_pick_many(
            self._h, blob, len(blob), ctypes.byref(picks))
        return n, picks.value

    def close(self) -> None:
        if self._h:
            self._lib.emqx_subtable_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# fast-path stat slots (host.cc StatSlot order; the drift guard in
# tests/test_stats_lint.py derives these names from the C++ enum and
# fails the build on any order/name/coverage mismatch)
STAT_NAMES = ("fast_in", "fast_out", "fast_bytes_out", "punts",
              "drops_backpressure", "drops_inflight", "native_acks",
              "shared_dispatch", "shared_no_member",
              "lane_in", "lane_out", "lane_punts", "lane_fallback",
              "lane_stale", "taps",
              "qos1_in", "qos2_in", "qos2_rel", "lane_topic_overflow",
              "ack_batches",
              "ws_handshakes", "ws_rejects", "ws_pings", "ws_closes",
              "punts_trace", "fr_dumps", "telemetry_batches",
              "trunk_out", "trunk_in", "trunk_batches_out",
              "trunk_batches_in", "trunk_punts", "trunk_replays",
              "trunk_shed",
              "durable_in", "durable_batches", "store_appends",
              "handoffs",
              "sn_in", "sn_out", "sn_qos_m1", "sn_pings",
              "sn_registers", "sn_sleep_parked", "sn_drops_oversize",
              "retain_set", "retain_del", "retain_deliver",
              "retain_msgs_out",
              "shard_ring_out", "shard_ring_in", "shard_ring_full",
              "traced_pubs", "span_batches", "faults_injected",
              # conn-scale plane (round 16): hibernation + accept shed
              "conns_parked", "conns_inflated", "conns_shed",
              "parked_pings",
              # one-recovery-path plane (round 18): the trunk qos1
              # replay ring is store-backed
              "trunk_ring_persisted", "trunk_ring_recovered",
              # coap gateway plane (round 19)
              "coap_in", "coap_notifies", "coap_pings",
              "coap_dedup_hits", "coap_rexmits", "coap_giveups",
              "coap_punts", "coap_drops_oversize")

# durable-store stat slots (store.h StoreStat order)
STORE_STAT_NAMES = ("appends", "consumed", "pending", "messages",
                    "segments", "gc_segments", "rewrites", "torn_drops",
                    "bytes", "degraded",
                    # one-recovery-path plane (round 18)
                    "replay_bytes", "sessions", "trunk_pending",
                    "meta_rewrites")

# durable-store on-disk record types (store.h kRec* constants — the
# record catalog of the ONE recovery path; tests/test_native_wire_lint
# pins name/value parity against the C++ side)
STORE_RECORD_TYPES = {"msg_batch": 1, "consume": 2, "register": 3,
                      "rewrite": 4, "session": 5, "unregister": 6,
                      "trunk": 7, "trunk_ack": 8}

# subscription-entry flags (router.h)
SUB_PUNT, SUB_NO_LOCAL, SUB_RULE_TAP, SUB_REMOTE = 1, 2, 4, 8
SUB_DURABLE = 16

# multi-core shard conn-id scheme (host.cc, round 12): bits 56-58 carry
# the shard index — above the Python punt-token space (1<<48), below
# the SN (59), durable (61), trunk (62) and trunk-sock (63) namespaces.
SHARD_SHIFT = 56
SHARD_MASK = 7
MAX_SHARDS = 8


def shard_of(conn_id: int) -> int:
    """Which shard's host owns this conn id (0 for unsharded hosts)."""
    return (conn_id >> SHARD_SHIFT) & SHARD_MASK


class NativeShardGroup:
    """The cross-shard SPSC ring group (ring.h). Python owns it: create
    BEFORE any host joins, destroy AFTER every member host is destroyed
    (the group owns the doorbell eventfds a racing producer shard may
    still write during a member's teardown)."""

    def __init__(self, n: int):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        if not 1 <= n <= MAX_SHARDS:
            raise ValueError(f"shards must be 1..{MAX_SHARDS}, got {n}")
        self.n = n
        self._h = self._lib.emqx_shard_group_create(n)
        if not self._h:
            raise OSError("cannot create shard group")

    # set True by an owner that must abandon the group (a wedged shard
    # poll thread may still push into the rings): destroy becomes a
    # no-op forever, including the gc-time __del__ path
    leaked = False

    def destroy(self) -> None:
        if self.leaked:
            return
        if self._h:
            self._lib.emqx_shard_group_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.destroy()
        except Exception:
            pass


FSYNC_POLICY = {"never": 0, "batch": 1, "interval": 2}


class NativeStore:
    """ctypes wrapper over the durable-session message store (store.h):
    a segmented mmap-backed append-only log with CRC32-framed records.
    The data plane appends through an attached ``NativeHost`` below the
    GIL; this wrapper is the Python control surface (register sessions,
    resume fetch, marker consumption, GC) and the test surface."""

    def __init__(self, dir_: str = "", segment_bytes: int = 4 << 20,
                 fsync: str = "batch"):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        policy = FSYNC_POLICY.get(fsync, 1)
        self._h = self._lib.emqx_store_open(
            dir_.encode(), segment_bytes, policy)
        if not self._h:
            raise OSError(f"cannot open durable store at {dir_!r}")
        self.dir = dir_

    def register(self, sid: str) -> int:
        """sid -> stable token (markers key on it; survives restart)."""
        return int(self._lib.emqx_store_register(self._h, sid.encode()))

    def lookup(self, sid: str) -> int:
        """sid -> token without creating one; 0 = never registered."""
        return int(self._lib.emqx_store_lookup(self._h, sid.encode()))

    def append(self, origin: int, qos: int, tokens: list[int],
               topic: str, payload: bytes, dup: bool = False,
               trace: int = 0, cid: str = "") -> int:
        """Single-message append (Python-plane persistence + test
        surface); returns the guid. ``trace`` persists a sampled trace
        id with the entry; ``cid`` persists the publisher's clientid
        (no-local / from_ attribution across restart)."""
        toks = (ctypes.c_uint64 * max(1, len(tokens)))(*tokens)
        t = topic.encode()
        c = (cid or "").encode()
        if len(c) > 255:
            # the bit5 extension carries a u8 length: an oversized
            # clientid is DROPPED (from_ degrades to "$durable", the
            # pre-round-18 behavior), never truncated — a truncated
            # prefix could falsely equal ANOTHER client's id and
            # wrongly suppress its no-local delivery. Mirrors the C++
            # kEnableFast bound.
            c = b""
        flags = (qos << 1) | (8 if dup else 0)
        return int(self._lib.emqx_store_append(
            self._h, origin, flags, toks, len(tokens),
            t, len(t), payload, len(payload), trace, c, len(c)))

    def unregister(self, sid: str) -> None:
        """Retire a sid's REGISTER token (session-expiry GC): the
        sid→token mapping, SESSION record, and leftover markers die
        with it, so a dead session stops pinning segments."""
        tok = self.lookup(sid)
        if tok:
            self._lib.emqx_store_unregister(self._h, tok)

    def put_session(self, sid: str, body: bytes) -> None:
        """Write the sid's session-catalog record (subscriptions +
        expiry metadata — the bytes the Python JSON DiskStore used to
        hold). Registers the sid when needed."""
        tok = self.register(sid)
        self._lib.emqx_store_put_session(self._h, tok, body, len(body))

    def delete_session(self, sid: str) -> None:
        tok = self.lookup(sid)
        if tok:
            self._lib.emqx_store_put_session(self._h, tok, b"", 0)

    def sessions(self) -> list[tuple[str, bytes]]:
        """All live session-catalog records as (sid, body) — the boot
        walk of the one recovery path."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        n = self._lib.emqx_store_sessions(self._h, ctypes.byref(out),
                                          ctypes.byref(out_len))
        raw = ctypes.string_at(out, out_len.value)
        self._lib.emqx_buf_free(out)
        entries, pos = [], 0
        for _ in range(n):
            sl = int.from_bytes(raw[pos + 8:pos + 10], "little")
            sid = raw[pos + 10:pos + 10 + sl].decode("utf-8", "replace")
            pos += 10 + sl
            bl = int.from_bytes(raw[pos:pos + 4], "little")
            body = raw[pos + 4:pos + 4 + bl]
            pos += 4 + bl
            entries.append((sid, body))
        return entries

    def trunk_put(self, name: str, seq: int, record: bytes,
                  has_trace: bool = False) -> None:
        """Journal one trunk replay-ring record under the peer NODE
        NAME (raw test surface; the host's data plane journals through
        its attached store)."""
        self._lib.emqx_store_trunk_put(
            self._h, name.encode(), seq, 1 if has_trace else 0,
            record, len(record))

    def trunk_ack(self, name: str, seq: int) -> None:
        self._lib.emqx_store_trunk_ack(self._h, name.encode(), seq)

    def trunk_fetch(self, name: str) -> list[tuple[int, bool, bytes]]:
        """The named peer's persisted ring in seq order:
        ``[(seq, has_trace, record bytes), ...]``."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        n = self._lib.emqx_store_trunk_fetch(
            self._h, name.encode(), ctypes.byref(out),
            ctypes.byref(out_len))
        raw = ctypes.string_at(out, out_len.value)
        self._lib.emqx_buf_free(out)
        entries, pos = [], 0
        for _ in range(n):
            seq = int.from_bytes(raw[pos:pos + 8], "little")
            tf = raw[pos + 8]
            rl = int.from_bytes(raw[pos + 9:pos + 13], "little")
            pos += 13
            entries.append((seq, bool(tf & 1), raw[pos:pos + rl]))
            pos += rl
        return entries

    def trunk_pending(self, name: str) -> int:
        return int(self._lib.emqx_store_trunk_pending(
            self._h, name.encode()))

    def consume(self, token: int, guids: list[int]) -> int:
        if not guids:
            return 0
        arr = (ctypes.c_uint64 * len(guids))(*guids)
        return int(self._lib.emqx_store_consume(
            self._h, token, arr, len(guids)))

    def fetch(self, token: int) -> list[tuple]:
        """Pending messages for ``token`` in guid (arrival) order:
        ``[(guid, origin, ts_ms, qos, dup, topic, payload, trace_id,
        cid), ...]`` — trace_id is 0 unless the appending publish was
        tagged by the native trace sampler; cid is the persisted
        origin clientid ("" = unknown)."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        n = self._lib.emqx_store_fetch(self._h, token,
                                       ctypes.byref(out),
                                       ctypes.byref(out_len))
        raw = ctypes.string_at(out, out_len.value)
        self._lib.emqx_buf_free(out)
        entries, pos = [], 0
        for _ in range(n):
            guid = int.from_bytes(raw[pos:pos + 8], "little")
            origin = int.from_bytes(raw[pos + 8:pos + 16], "little")
            ts = int.from_bytes(raw[pos + 16:pos + 24], "little")
            flags = raw[pos + 24]
            tlen = int.from_bytes(raw[pos + 25:pos + 27], "little")
            pos += 27
            topic = raw[pos:pos + tlen].decode("utf-8", "replace")
            pos += tlen
            trace = 0
            if flags & 0x10:
                trace = int.from_bytes(raw[pos:pos + 8], "little")
                pos += 8
            cid = ""
            if flags & 0x20:
                cl = raw[pos]
                cid = raw[pos + 1:pos + 1 + cl].decode("utf-8", "replace")
                pos += 1 + cl
            plen = int.from_bytes(raw[pos:pos + 4], "little")
            pos += 4
            body = raw[pos:pos + plen]
            pos += plen
            entries.append((guid, origin, ts, (flags >> 1) & 3,
                            bool(flags & 8), topic, body, trace, cid))
        return entries

    def pending(self, token: int) -> int:
        return int(self._lib.emqx_store_pending(self._h, token))

    def gc(self) -> int:
        return int(self._lib.emqx_store_gc(self._h))

    def sync(self) -> None:
        self._lib.emqx_store_sync(self._h)

    def set_compact_age_ms(self, ms: int) -> None:
        """Age-based compaction trigger (round 15): a sealed segment
        whose live tail has sat past ``ms`` re-homes regardless of the
        thin-tail byte bound — one huge live message can no longer pin
        an otherwise-dead segment forever. 0 disables; default 60s."""
        self._lib.emqx_store_set_compact_age(self._h, int(ms))

    def fault_arm(self, site: str, mode: str = "errno",
                  n_or_prob: float = 0.0, seed: int = 1,
                  key: int = 0) -> None:
        """Arm a store fault site directly (store_msync /
        store_seg_open) — the raw-store test surface; the product path
        arms through the host, which forwards here."""
        self._lib.emqx_store_fault_arm(
            self._h, fault_site_index(site), FAULT_MODES[mode],
            float(n_or_prob), int(seed), int(key))

    def fault_fired(self, site: str) -> int:
        return int(self._lib.emqx_store_fault_fired(
            self._h, fault_site_index(site)))

    def stats(self) -> dict[str, int]:
        return {name: int(self._lib.emqx_store_stat(self._h, i))
                for i, name in enumerate(STORE_STAT_NAMES)}

    def close(self) -> None:
        if self._h:
            self._lib.emqx_store_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class NativeHost:
    """The epoll connection host. One thread calls ``poll()``; ``send`` and
    ``close_conn`` are safe from any thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_size: int = 1 << 20, max_conns: int = 1_000_000,
                 reuseport: bool = False):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(f"native lib unavailable: {_build_error}")
        self._h = self._lib.emqx_host_create(
            host.encode(), port, max_size, max_conns, int(reuseport))
        if not self._h:
            raise OSError(f"cannot bind {host}:{port}")
        self.port = self._lib.emqx_host_port(self._h)
        self.ws_port = 0       # set by listen_ws()
        self.trunk_port = 0    # set by trunk_listen()
        self.sn_port = 0       # set by listen_sn()
        self.coap_port = 0     # set by listen_coap()
        # The poll buffer must hold at least one whole event record: 13-byte
        # header + payload up to max_size (a max-size PUBLISH frame).  A
        # smaller buffer would leave host.cc unable to ever deliver that
        # record, busy-spinning the poll thread forever. The 65600-byte
        # margin covers the largest single durable entry on top of a
        # max-size publish (host.cc kDurMaxToksPerEntry * 8 + headers) —
        # a kind-10 record larger than this buffer would be dropped
        # whole, silently skipping live persistent-session delivery.
        self._buf = ctypes.create_string_buffer(max_size + 65600)

    def poll(self, timeout_ms: int = 100) -> Iterator[tuple[int, int, bytes]]:
        """Yield ``(kind, conn_id, payload)`` events from one loop step."""
        n = self._lib.emqx_host_poll(
            self._h, self._buf, len(self._buf), timeout_ms)
        if n <= 0:
            return
        raw = self._buf.raw[:n]
        pos = 0
        while pos < n:
            kind = raw[pos]
            conn = int.from_bytes(raw[pos + 1:pos + 9], "little")
            plen = int.from_bytes(raw[pos + 9:pos + 13], "little")
            pos += 13
            yield kind, conn, raw[pos:pos + plen]
            pos += plen

    def listen_ws(self, host: str = "127.0.0.1", port: int = 0,
                  path: str = "/mqtt", reuseport: bool = False) -> int:
        """Open the RFC6455 listener (BEFORE the poll thread starts).
        Conns accepted there run the WS handshake + frame codec in C++
        in front of the MQTT framer; their OPEN events carry a
        ``ws:ip:port`` peer string. Returns the bound port."""
        p = self._lib.emqx_host_listen_ws(
            self._h, host.encode(), port, path.encode(), int(reuseport))
        if p < 0:
            raise OSError(f"cannot bind ws listener {host}:{port}")
        self.ws_port = p
        return p

    # -- multi-core shards (round 12) ---------------------------------------

    def join_group(self, group: "NativeShardGroup", shard_id: int) -> None:
        """Make this host shard ``shard_id`` of ``group`` (call BEFORE
        the poll thread starts): conn ids gain the shard prefix (bits
        56-58), cross-shard deliveries ride the group's SPSC rings, and
        the group's doorbell for this shard joins the epoll set."""
        # hold the group FIRST: ~Host writes group_->alive at destroy
        # time, so gc-order must never free the group before a member
        # host (an abandoned half-built server has no stop() to order
        # it) — held even across a failed join for symmetry
        self._group = group
        rc = self._lib.emqx_host_join_group(self._h, group._h,
                                            int(shard_id))
        if rc != 0:
            raise ValueError(f"cannot join shard group as {shard_id}")

    def trunk_peer_state(self, peer_id: int, up: bool) -> None:
        """Mirror a peer's OWNER-shard link state onto this
        (non-owner) shard: its trunk-vs-punt oracle for remote legs it
        would ring-forward to the owner (``peer_id % n_shards`` since
        round 15)."""
        self._lib.emqx_host_trunk_peer_state(self._h, peer_id,
                                             1 if up else 0)

    # -- cluster trunk (round 9) -------------------------------------------

    def trunk_listen(self, host: str = "127.0.0.1", port: int = 0,
                     reuseport: bool = False) -> int:
        """Open the cluster-trunk listener (BEFORE the poll thread
        starts). Peer hosts dial it to forward publishes below the GIL;
        received batches fan out locally without touching Python.
        ``reuseport=True`` lets every shard listen on one port (the
        round-15 link spread). Returns the bound port."""
        p = self._lib.emqx_host_trunk_listen(self._h, host.encode(), port,
                                             int(reuseport))
        if p < 0:
            raise OSError(f"cannot bind trunk listener {host}:{port}")
        self.trunk_port = p
        return p

    def set_trunk_ack_timeout(self, ms: int) -> None:
        """Silent-link watchdog deadline: a front replay-ring entry
        unacked this long on an UP link kills the link (the only
        resolution for an up-but-black partition). Default 10s;
        0 disables the watchdog."""
        self._lib.emqx_host_set_trunk_ack_timeout(self._h, int(ms))

    # -- faultline (round 15) ------------------------------------------------

    def fault_arm(self, site: str, mode: str = "errno",
                  n_or_prob: float = 0.0, seed: int = 1,
                  key: int = 0) -> None:
        """Arm one named fault site (see fault.h / FAULT_SITES).
        ``n_or_prob``: 0 fires every hit while armed; n >= 1 fires the
        next n hits then auto-disarms; 0 < p < 1 fires each hit with
        probability p from a PRNG seeded by ``seed`` (same seed + same
        hit order = the bit-identical firing sequence). ``key`` scopes
        the site to one conn/peer (0 = all). Unknown site or mode names
        raise — a typo must never arm nothing. Store sites forward to
        the attached durable store's injector."""
        idx = fault_site_index(site)
        rc = self._lib.emqx_host_fault_arm(
            self._h, idx, FAULT_MODES[mode], float(n_or_prob),
            int(seed), int(key))
        if rc != 0:
            raise ValueError(
                f"cannot arm fault site {site!r} (no store attached?)")

    def fault_disarm(self, site: str) -> None:
        idx = fault_site_index(site)
        self._lib.emqx_host_fault_arm(self._h, idx, 0, 0.0, 0, 0)

    def fault_fired(self, site: str) -> int:
        """Faults injected at ``site`` on this host so far."""
        return int(self._lib.emqx_host_fault_fired(
            self._h, fault_site_index(site)))

    def trunk_connect(self, peer_id: int, host: str, port: int) -> None:
        """Dial (or re-dial) a peer's trunk listener; the outcome
        arrives as a kind-9 UP/DOWN event. Reconnects replay the peer's
        unacked qos1 batches before new traffic."""
        self._lib.emqx_host_trunk_connect(self._h, peer_id,
                                          host.encode(), port)

    def trunk_ident(self, peer_id: int, name: str) -> None:
        """Bind ``peer_id`` to its stable NODE NAME: the durable store
        keys the persisted trunk replay ring on it (peer ids renumber
        per process). Call before trunk_connect so the previous life's
        ring merges ahead of fresh traffic."""
        self._lib.emqx_host_trunk_ident(self._h, peer_id, name.encode())

    def trunk_disconnect(self, peer_id: int, forget: bool = False) -> None:
        """Drop the peer link. ``forget=False`` keeps the replay ring
        for the next connect; ``forget=True`` erases the peer state."""
        self._lib.emqx_host_trunk_disconnect(self._h, peer_id,
                                             1 if forget else 0)

    def trunk_route_add(self, peer_id: int, filter_: str) -> None:
        """Install a REMOTE entry (the third entry kind): publishes
        matching ``filter_`` forward over ``peer_id``'s trunk for
        QoS0/1; while the trunk is down the entry behaves as a punt
        marker and the Python forward lane carries the message."""
        self._lib.emqx_host_trunk_route_add(self._h, peer_id,
                                            filter_.encode())

    def trunk_route_del(self, peer_id: int, filter_: str) -> None:
        self._lib.emqx_host_trunk_route_del(self._h, peer_id,
                                            filter_.encode())

    def send(self, conn: int, data: bytes) -> None:
        self._lib.emqx_host_send(self._h, conn, data, len(data))

    def close_conn(self, conn: int) -> None:
        self._lib.emqx_host_close_conn(self._h, conn)

    # -- fast-path control plane (thread-safe) -----------------------------

    def enable_fast(self, conn: int, proto_ver: int,
                    max_inflight: int = 0, clientid: str = "") -> None:
        """``clientid`` binds the conn's clientid for origin
        attribution: durable appends persist it (flags bit5) so
        no-local / from_ survive a restart."""
        self._lib.emqx_host_enable_fast(self._h, conn, proto_ver,
                                        max_inflight,
                                        (clientid or "").encode())

    def disable_fast(self, conn: int) -> None:
        self._lib.emqx_host_disable_fast(self._h, conn)

    def sub_add(self, owner: int, filter_: str, qos: int = 0,
                flags: int = 0) -> None:
        self._lib.emqx_host_sub_add(self._h, owner,
                                    filter_.encode(), qos, flags)

    def sub_del(self, owner: int, filter_: str) -> None:
        self._lib.emqx_host_sub_del(self._h, owner, filter_.encode())

    def permit(self, conn: int, topic: str) -> None:
        self._lib.emqx_host_permit(self._h, conn, topic.encode())

    def shared_add(self, token: int, conn: int, filter_: str,
                   qos: int = 0, flags: int = 0) -> None:
        self._lib.emqx_host_shared_add(self._h, token, conn,
                                       filter_.encode(), qos, flags)

    def shared_del(self, token: int, conn: int, filter_: str) -> None:
        self._lib.emqx_host_shared_del(self._h, token, conn,
                                       filter_.encode())

    def set_lane(self, enabled: bool) -> None:
        """Enable/disable the device match lane; disabling drains every
        parked frame to the Python slow path in arrival order."""
        self._lib.emqx_host_set_lane(self._h, 1 if enabled else 0)

    def lane_deliver(self, blob: bytes) -> None:
        """Apply one pump response blob (see host.cc LaneDeliver)."""
        self._lib.emqx_host_lane_deliver(self._h, blob, len(blob))

    def lane_backlog(self) -> int:
        return int(self._lib.emqx_host_lane_backlog(self._h))

    def set_max_qos(self, max_qos: int) -> None:
        """Mirror mqtt.max_qos_allowed: over-cap publishes skip the
        fast path so the channel can refuse them per spec."""
        self._lib.emqx_host_set_max_qos(self._h, int(max_qos))

    def set_trace(self, conn: int, on: bool) -> None:
        """Trace punt: while on, the conn's PUBLISHes bypass the fast
        path so the Python hook fold (TraceManager) sees every one, and
        its flight-recorder tail is dumped as a kind-8 record —
        immediately on attach and again at teardown."""
        self._lib.emqx_host_set_trace(self._h, conn, 1 if on else 0)

    def set_telemetry(self, enabled: bool,
                      slow_ack_ms: float = 500.0) -> None:
        """Master switch for the native telemetry plane (histograms,
        flight recorders, kind-8 export) plus the slow-ack report floor
        in milliseconds (sampled ack RTTs past it feed slow_subs)."""
        self._lib.emqx_host_set_telemetry(
            self._h, 1 if enabled else 0, int(slow_ack_ms * 1_000_000))

    def set_tracing(self, enabled: bool, shift: int = 6,
                    seed: int = 0) -> None:
        """Native distributed tracing (round 13): sample 1-in-2^shift
        natively-consumed publishes (deterministic global ticker) and
        tag them with trace ids minted under ``seed`` (the node+shard
        prefix; 0 keeps the current seed). Gates on the telemetry
        master switch too."""
        self._lib.emqx_host_set_tracing(
            self._h, 1 if enabled else 0, int(shift), int(seed))

    def set_trunk_wire(self, version: int) -> None:
        """Cap the trunk wire version this host advertises/accepts —
        tests set 0 to simulate an old peer (trace ids are then
        stripped from outgoing trunk entries, losslessly)."""
        self._lib.emqx_host_set_trunk_wire(self._h, int(version))

    # -- durable-session plane (round 10) ----------------------------------

    def attach_store(self, store: "NativeStore") -> None:
        """Attach the durable store (BEFORE the poll thread starts).
        The host borrows the handle: destroy the host first, then close
        the store."""
        self._lib.emqx_host_attach_store(self._h, store._h)

    def durable_add(self, token: int, filter_: str, qos: int = 0) -> None:
        """Install a durable entry (the fourth match-table entry kind):
        publishes matching ``filter_`` persist below the GIL for the
        session registered under ``token`` while the fast path — the
        publisher and every fast subscriber — proceeds unpunted."""
        self._lib.emqx_host_durable_add(self._h, token,
                                        filter_.encode(), qos)

    def durable_del(self, token: int, filter_: str) -> None:
        self._lib.emqx_host_durable_del(self._h, token, filter_.encode())

    def note_stage(self, stage_name: str, ns: int) -> int:
        """POLL-THREAD ONLY: record one observation into a telemetry
        stage (the resume replay_drain stamp). Returns 0, or -2 when
        called off the poll thread (refused, like conn_idle_ms)."""
        try:
            idx = HIST_STAGES.index(stage_name)
        except ValueError:
            return -1
        return int(self._lib.emqx_host_note_stage(self._h, idx, int(ns)))

    # -- mqtt-sn gateway + retained snapshot (round 11) ---------------------

    def listen_sn(self, host: str = "127.0.0.1", port: int = 0,
                  gw_id: int = 1, reuseport: bool = False) -> int:
        """Open the MQTT-SN/UDP gateway socket (BEFORE the poll thread
        starts). Datagram peers become conns on their first CONNECT;
        their OPEN events carry an ``sn:ip:port`` peer string. Returns
        the bound port."""
        p = self._lib.emqx_host_listen_sn(self._h, host.encode(), port,
                                          int(gw_id), int(reuseport))
        if p < 0:
            raise OSError(f"cannot bind sn listener {host}:{port}")
        self.sn_port = p
        return p

    def listen_coap(self, host: str = "127.0.0.1", port: int = 0,
                    reuseport: bool = False) -> int:
        """Open the CoAP/UDP gateway socket (BEFORE the poll thread
        starts). Datagram peers become conns on their first request;
        their OPEN events carry a ``coap:ip:port`` peer string.
        Returns the bound port."""
        p = self._lib.emqx_host_listen_coap(self._h, host.encode(), port,
                                            int(reuseport))
        if p < 0:
            raise OSError(f"cannot bind coap listener {host}:{port}")
        self.coap_port = p
        return p

    def coap_send(self, conn: int, data: bytes) -> None:
        """Send raw CoAP response bytes to ``conn``'s peer — the answer
        path for oracle-served (kind-13 punted) exchanges."""
        self._lib.emqx_host_coap_send(self._h, conn, data, len(data))

    def coap_retain_state(self, complete: bool) -> None:
        """Mirror whether the retained snapshot is complete (no
        props-carrying topics excluded): plain CoAP GETs serve natively
        only while it is."""
        self._lib.emqx_host_coap_retain_state(self._h,
                                              1 if complete else 0)

    def set_coap_ack_timeout(self, ms: int) -> None:
        """CON-notify retransmit base in ms (0 restores the RFC 7252
        default ACK_TIMEOUT x 1.5 = 3000ms)."""
        self._lib.emqx_host_set_coap_ack_timeout(self._h, int(ms))

    def sn_predefined(self, topic_id: int, topic: Optional[str]) -> None:
        """Install (or, with ``topic=None``, forget) a gateway-wide
        predefined topic id (MQTT-SN predefined id space)."""
        self._lib.emqx_host_sn_predefined(
            self._h, topic_id, (topic or "").encode())

    def set_retained(self, topic: str, payload: bytes, qos: int,
                     deadline_ms: int = 0) -> None:
        """Mirror one retained message into the host-side snapshot.
        ``deadline_ms`` is the EFFECTIVE absolute wall-clock expiry
        (0 = never) — the caller folds per-message and store-default
        expiry into one number."""
        self._lib.emqx_host_set_retained(
            self._h, topic.encode(), payload, len(payload), qos,
            int(deadline_ms))

    def retain_del(self, topic: str) -> None:
        self._lib.emqx_host_retain_del(self._h, topic.encode())

    def retain_deliver(self, conn: int, filter_: str,
                       max_qos: int = 0) -> None:
        """Deliver every live retained message matching ``filter_`` to
        ``conn`` below the GIL (retain=1, qos capped at ``max_qos``;
        elevated qos rides the native ack plane)."""
        self._lib.emqx_host_retain_deliver(self._h, conn,
                                           filter_.encode(), max_qos)

    def set_telemetry_shift(self, shift: int) -> None:
        """Per-message telemetry sampling override: stages sample
        1-in-2^shift (default 3 = the documented 1-in-8). Out-of-range
        values reset the default."""
        self._lib.emqx_host_set_telemetry_shift(self._h, int(shift))

    def set_inflight_cap(self, conn: int, cap: int) -> None:
        """Re-divide a conn's receive-maximum budget: set the native
        plane's inflight cap (the Python session holds the rest; the
        caller keeps the two caps summing to <= the budget)."""
        self._lib.emqx_host_set_inflight_cap(self._h, conn, int(cap))

    def permits_flush(self) -> None:
        self._lib.emqx_host_permits_flush(self._h)

    def stats(self) -> dict[str, int]:
        return {name: self._lib.emqx_host_stat(self._h, i)
                for i, name in enumerate(STAT_NAMES)}

    # -- conn-scale plane (round 16) ----------------------------------------

    def set_keepalive(self, conn: int, deadline_ms: int) -> None:
        """Arm (or, with 0, disarm) a conn's native keepalive deadline
        on the shard's timer wheel. Pass the EFFECTIVE expiry — the
        server passes 1.5x the negotiated keepalive, the MQTT grace.
        Conns armed here leave the Python housekeep scan entirely."""
        self._lib.emqx_host_set_keepalive(self._h, conn, int(deadline_ms))

    def set_park(self, enabled: bool = True, park_after_ms: int = 0,
                 accept_burst: int = 0, mem_budget_bytes: int = 0) -> None:
        """Conn-scale knobs: hibernation on/off, the no-keepalive
        park-after fallback (0 keeps the 30s default; keepalive'd conns
        park after 2x their grace), the per-cycle accept burst cap
        (defer rung) and the conn-memory shed budget (accept_shed)."""
        self._lib.emqx_host_set_park(
            self._h, 1 if enabled else 0, int(park_after_ms),
            int(accept_burst), int(mem_budget_bytes))

    def synth_conns(self, n: int, keepalive_ms: int = 0,
                    sub_every: int = 0, topic_prefix: str = "synth") -> None:
        """Bench/test surface (raw hosts only): conjure ``n`` resident
        fast conns with no socket so the conn-scale structures run at
        10^6 scale inside an fd-capped container. Not a product path —
        the server never sees these ids (no OPEN events)."""
        self._lib.emqx_host_synth_conns(
            self._h, int(n), int(keepalive_ms), int(sub_every),
            topic_prefix.encode())

    def conn_counts(self) -> dict[str, int]:
        """POLL-THREAD ONLY (the conn_idle_ms contract): resident and
        parked conn counts, parked-record bytes, armed wheel timers."""
        out = (ctypes.c_uint64 * 4)()
        rc = self._lib.emqx_host_conn_counts(self._h, out)
        if rc != 0:
            raise RuntimeError("conn_counts refused off the poll thread")
        return {"resident": int(out[0]), "parked": int(out[1]),
                "parked_bytes": int(out[2]), "timers_armed": int(out[3])}

    def conn_idle_ms(self, conn: int) -> int:
        """POLL-THREAD ONLY (unlike the other control calls): walks the
        connection table the loop mutates. Call it from the same thread
        that drives poll() — the server's housekeep does."""
        return self._lib.emqx_host_conn_idle_ms(self._h, conn)

    # set True by an owner that must abandon the host (a wedged poll
    # thread may still be inside emqx_host_poll): destroy becomes a
    # no-op forever, including the gc-time __del__ path
    leaked = False

    def destroy(self) -> None:
        if self.leaked:
            return
        if self._h:
            self._lib.emqx_host_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.destroy()
        except Exception:
            pass
