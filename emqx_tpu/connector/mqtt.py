"""MQTT-bridge connector — parity with ``emqx_connector_mqtt.erl`` +
its ``mqtt/`` worker (emqtt client + replayq in the reference; our
MqttClient + the BufferWorker's replayq here).

The client runs on a private asyncio loop in a daemon thread so the
synchronous resource/worker machinery can drive it:

- egress: ``on_query({"topic", "payload", "qos", "retain"})`` publishes
  to the remote broker (raises on failure → buffer worker retries).
- ingress: ``subscribe_remote(filter, on_message)`` subscribes on the
  remote side and calls back for every message (the ``$bridges/...``
  hook-topic feed, emqx_rule_events.erl:145).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Optional

from emqx_tpu.mqtt.client import MqttClient
from emqx_tpu.resource.resource import Resource


class MqttConnector(Resource):
    def __init__(self, host: str = "127.0.0.1", port: int = 1883, *,
                 clientid: str = "bridge", username: Optional[str] = None,
                 password: Optional[bytes] = None,
                 timeout_s: float = 5.0) -> None:
        self.host, self.port = host, port
        self.clientid = clientid
        self.username, self.password = username, password
        self.timeout_s = timeout_s
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._client: Optional[MqttClient] = None
        self._ingress_task = None
        self._on_message: dict[str, Callable] = {}

    # -- loop-thread plumbing ------------------------------------------------

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def _call(self, coro, timeout: Optional[float] = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout or self.timeout_s)

    # -- resource behaviour --------------------------------------------------

    def on_start(self, conf: dict) -> None:
        if self._loop is None:
            self._loop = asyncio.new_event_loop()
            self._thread = threading.Thread(
                target=self._run_loop, daemon=True,
                name=f"mqtt-bridge-{self.clientid}")
            self._thread.start()
        self._client = MqttClient(
            host=self.host, port=self.port, clientid=self.clientid,
            username=self.username, password=self.password,
        )
        self._call(self._client.connect(timeout=self.timeout_s))
        if self._on_message:
            for filt in self._on_message:
                self._call(self._client.subscribe(filt, qos=1))
            self._start_ingress()

    def on_stop(self) -> None:
        if self._ingress_task is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(self._ingress_task.cancel)
            self._ingress_task = None
        if self._client is not None:
            try:
                self._call(self._client.close())
            except Exception:
                pass
            self._client = None
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._thread is not None:
                self._thread.join(timeout=2)
            self._loop, self._thread = None, None

    def on_query(self, req: Any) -> Any:
        self._call(self._client.publish(
            topic=req["topic"], payload=_as_bytes(req.get("payload", b"")),
            qos=int(req.get("qos", 0)), retain=bool(req.get("retain")),
        ))
        return {"ok": True}

    def on_health_check(self) -> bool:
        if self._client is None or self._loop is None:
            return False
        try:
            self._call(self._client.ping())
            return True
        except Exception:
            return False

    # -- ingress -------------------------------------------------------------

    def subscribe_remote(self, filt: str,
                         on_message: Callable[[str, bytes, int], None]) -> None:
        """Register an ingress leg; takes effect at (re)connect, or
        immediately if already connected."""
        self._on_message[filt] = on_message
        if self._client is not None and self._loop is not None:
            self._call(self._client.subscribe(filt, qos=1))
            self._start_ingress()

    def unsubscribe_remote(self, filt: str) -> None:
        self._on_message.pop(filt, None)
        if self._client is not None and self._loop is not None:
            try:
                self._call(self._client.unsubscribe(filt))
            except Exception:
                pass

    def _start_ingress(self) -> None:
        from emqx_tpu.core import topic as T

        client = self._client           # bind: a reconnect swaps clients

        async def pump():
            while True:
                pkt = await client.messages.get()
                for filt, cb in list(self._on_message.items()):
                    # route by the subscribed filter — one connector can
                    # carry several ingress legs with disjoint topics
                    if not T.match(pkt.topic, filt):
                        continue
                    try:
                        cb(pkt.topic, pkt.payload, pkt.qos)
                    except Exception:
                        pass

        async def spawn():
            # always re-pump on (re)connect: the old task is parked on
            # the *previous* client's queue and must not block the new one
            if self._ingress_task is not None:
                self._ingress_task.cancel()
            self._ingress_task = asyncio.ensure_future(pump())

        self._call(spawn())


def _as_bytes(p) -> bytes:
    if isinstance(p, bytes):
        return p
    return str(p).encode()
