"""HTTP connector — the ``emqx_connector_http`` (ehttpc) analogue,
on stdlib ``http.client`` with per-query connections (the pooling the
reference gets from ehttpc workers maps onto the buffer worker's
batching here; a keep-alive pool is a later optimization).

Query shape: ``{"method", "path", "headers", "body"}`` — the bridge
layer renders rule-engine templates into these fields.
"""

from __future__ import annotations

import http.client
import socket
from typing import Any
from urllib.parse import urlparse

from emqx_tpu.resource.resource import Resource


class HttpConnector(Resource):
    def __init__(self, base_url: str, *, timeout_s: float = 5.0,
                 headers: dict | None = None) -> None:
        u = urlparse(base_url)
        if u.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme {u.scheme!r}")
        self.scheme = u.scheme
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.base_path = u.path.rstrip("/")
        self.timeout_s = timeout_s
        self.headers = headers or {}

    def _conn(self) -> http.client.HTTPConnection:
        cls = (http.client.HTTPSConnection if self.scheme == "https"
               else http.client.HTTPConnection)
        return cls(self.host, self.port, timeout=self.timeout_s)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(
                f"http service {self.host}:{self.port} unreachable")

    def on_query(self, req: Any) -> Any:
        method = (req.get("method") or "POST").upper()
        path = self.base_path + (req.get("path") or "/")
        body = req.get("body")
        if isinstance(body, str):
            body = body.encode()
        conn = self._conn()
        try:
            conn.request(method, path, body=body,
                         headers={**self.headers,
                                  **(req.get("headers") or {})})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 500:
                raise ConnectionError(f"http {resp.status}")
            return {"status": resp.status, "body": data}
        finally:
            conn.close()

    def on_health_check(self) -> bool:
        try:
            with socket.create_connection(
                    (self.host, self.port), timeout=self.timeout_s):
                return True
        except OSError:
            return False
