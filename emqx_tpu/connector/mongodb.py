"""MongoDB connector — the ``emqx_connector_mongo`` analogue.

A from-scratch OP_MSG (opcode 2013) client with a minimal BSON codec
(documents, strings, int32/64, double, bool, null, arrays, embedded
docs, binary) — the modern command protocol every supported server
speaks. Commands are plain documents (``find``/``insert``/``update``
with ``$db``), replies are single kind-0 body sections.

``MiniMongo`` is the in-repo miniature backend for tests: real OP_MSG
framing + BSON over dict collections, answering ``hello``/``ping``/
``find`` (equality filters)/``insert``. Auth is unauthenticated, the
reference's default mongo topology for authn tests.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Any, Optional

from emqx_tpu.resource.resource import Resource


class MongoError(Exception):
    pass


# ---------------------------------------------------------------------------
# minimal BSON


def bson_encode(doc: dict) -> bytes:
    body = b"".join(_enc_elem(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _enc_elem(key: str, v: Any) -> bytes:
    k = key.encode() + b"\x00"
    if isinstance(v, bool):
        return b"\x08" + k + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + k + struct.pack("<i", v)
        return b"\x12" + k + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + k + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + k + struct.pack("<i", len(b) + 1) + b + b"\x00"
    if isinstance(v, bytes):
        return b"\x05" + k + struct.pack("<i", len(v)) + b"\x00" + v
    if v is None:
        return b"\x0a" + k
    if isinstance(v, dict):
        return b"\x03" + k + bson_encode(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + k + bson_encode(
            {str(i): x for i, x in enumerate(v)})
    raise MongoError(f"unsupported BSON type {type(v).__name__}")


def bson_decode(data: bytes, pos: int = 0) -> tuple[dict, int]:
    (ln,) = struct.unpack_from("<i", data, pos)
    end = pos + ln - 1
    pos += 4
    out: dict = {}
    while pos < end:
        t = data[pos]
        pos += 1
        z = data.index(b"\x00", pos)
        key = data[pos:z].decode()
        pos = z + 1
        if t == 0x01:
            (out[key],) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif t == 0x02:
            (sl,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 4:pos + 4 + sl - 1].decode()
            pos += 4 + sl
        elif t in (0x03, 0x04):
            sub, pos = bson_decode(data, pos)
            out[key] = (list(sub.values()) if t == 0x04 else sub)
        elif t == 0x05:
            (bl,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 5:pos + 5 + bl]
            pos += 5 + bl
        elif t == 0x08:
            out[key] = data[pos] == 1
            pos += 1
        elif t == 0x0A:
            out[key] = None
        elif t == 0x10:
            (out[key],) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif t == 0x12:
            (out[key],) = struct.unpack_from("<q", data, pos)
            pos += 8
        elif t == 0x07:                       # ObjectId — keep raw
            out[key] = data[pos:pos + 12]
            pos += 12
        elif t == 0x11:                       # timestamp
            (out[key],) = struct.unpack_from("<q", data, pos)
            pos += 8
        else:
            raise MongoError(f"unsupported BSON element type 0x{t:02x}")
    return out, end + 1


# ---------------------------------------------------------------------------
# OP_MSG framing

OP_MSG = 2013


def _op_msg(doc: dict, request_id: int, response_to: int = 0) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
    return struct.pack("<iiii", len(body) + 16, request_id,
                       response_to, OP_MSG) + body


def _parse_op_msg(payload: bytes) -> dict:
    # flagBits(4) + kind byte; kind 0 = single body document
    if payload[4] != 0:
        raise MongoError("only kind-0 OP_MSG sections supported")
    doc, _ = bson_decode(payload, 5)
    return doc


class MongoClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 database: str = "mqtt", timeout_s: float = 5.0) -> None:
        self.addr = (host, port)
        self.database = database
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._req = 0
        self._lock = threading.Lock()

    def _exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("mongo closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def command(self, doc: dict) -> dict:
        """Run one database command; raises MongoError on ok: 0."""
        doc = {**doc}
        doc.setdefault("$db", self.database)
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._sock = socket.create_connection(
                            self.addr, self.timeout_s)
                        self._sock.settimeout(self.timeout_s)
                        self._buf = b""
                    self._req += 1
                    self._sock.sendall(_op_msg(doc, self._req))
                    head = self._exact(16)
                    (ln, _rid, _rto, op) = struct.unpack("<iiii", head)
                    payload = self._exact(ln - 16)
                    break
                except (OSError, ConnectionError):
                    self.close()
                    if attempt:
                        raise
            if op != OP_MSG:
                raise MongoError(f"unexpected opcode {op}")
            reply = _parse_op_msg(payload)
            if not reply.get("ok"):
                raise MongoError(reply.get("errmsg", "command failed"))
            return reply

    def find(self, collection: str, filter_: dict) -> list[dict]:
        reply = self.command({"find": collection, "filter": filter_})
        return reply.get("cursor", {}).get("firstBatch", [])

    def insert(self, collection: str, docs: list[dict]) -> int:
        reply = self.command({"insert": collection, "documents": docs})
        return int(reply.get("n", 0))

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._buf = b""


class MongoConnector(Resource):
    def __init__(self, **kw: Any) -> None:
        self.client = MongoClient(**kw)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(f"mongodb {self.client.addr} unreachable")

    def on_stop(self) -> None:
        self.client.close()

    def on_query(self, req: Any) -> Any:
        try:
            if isinstance(req, dict) and "find" in req:
                return self.client.find(req["find"],
                                        req.get("filter", {}))
            if isinstance(req, dict) and "insert" in req:
                return self.client.insert(req["insert"],
                                          req.get("documents", []))
            return self.client.command(dict(req))
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_health_check(self) -> bool:
        try:
            return bool(self.client.command({"ping": 1}).get("ok"))
        except (OSError, ConnectionError, MongoError):
            return False


# ---------------------------------------------------------------------------
# in-repo miniature server (test backend)


class MiniMongo:
    """OP_MSG subset over dict collections: hello/ping/find (equality
    filter)/insert."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.collections: dict[str, list[dict]] = {}
        mini = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    mini._session(self.request)
                except (ConnectionError, OSError):
                    pass

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def _session(self, sock: socket.socket) -> None:
        buf = b""

        def exact(n: int) -> bytes:
            nonlocal buf
            while len(buf) < n:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            out, rest = buf[:n], buf[n:]
            buf = rest
            return out

        while True:
            head = exact(16)
            (ln, rid, _rto, op) = struct.unpack("<iiii", head)
            payload = exact(ln - 16)
            if op != OP_MSG:
                return
            try:
                cmd = _parse_op_msg(payload)
                reply = self._run(cmd)
            except Exception as e:   # noqa: BLE001 — protocol reply
                reply = {"ok": 0.0, "errmsg": str(e)}
            sock.sendall(_op_msg(reply, 0, rid))

    def _run(self, cmd: dict) -> dict:
        name = next(iter(cmd))
        if name in ("hello", "isMaster", "ismaster"):
            return {"isWritablePrimary": True, "maxWireVersion": 17,
                    "minWireVersion": 0, "ok": 1.0}
        if name == "ping":
            return {"ok": 1.0}
        if name == "find":
            coll = self.collections.get(cmd["find"], [])
            filt = cmd.get("filter", {}) or {}
            batch = [d for d in coll
                     if all(d.get(k) == v for k, v in filt.items())]
            return {"cursor": {"id": 0, "ns": f"mqtt.{cmd['find']}",
                               "firstBatch": batch}, "ok": 1.0}
        if name == "insert":
            docs = cmd.get("documents", [])
            self.collections.setdefault(cmd["insert"], []).extend(docs)
            return {"n": len(docs), "ok": 1.0}
        raise MongoError(f"no such command: '{name}'")

    def start(self) -> "MiniMongo":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-mongo")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
