"""In-memory connector — the test double the resource/bridge suites
drive (the reference's emqx_connector_demo / test resources). Records
queries, supports failure injection and health flapping."""

from __future__ import annotations

from typing import Any

from emqx_tpu.resource.resource import Resource


class MemoryConnector(Resource):
    def __init__(self) -> None:
        self.started = False
        self.healthy = True
        self.fail_queries = False
        self.fail_start = False
        self.queries: list[Any] = []
        self.batches: list[list] = []

    def on_start(self, conf: dict) -> None:
        if self.fail_start:
            raise ConnectionError("injected start failure")
        self.started = True

    def on_stop(self) -> None:
        self.started = False

    def on_query(self, req: Any) -> Any:
        if self.fail_queries:
            raise ConnectionError("injected query failure")
        self.queries.append(req)
        return {"ok": req}

    def on_batch_query(self, reqs: list) -> list:
        if self.fail_queries:
            raise ConnectionError("injected query failure")
        self.batches.append(list(reqs))
        self.queries.extend(reqs)
        return [{"ok": r} for r in reqs]

    def on_health_check(self) -> bool:
        return self.healthy
