"""Connector clients — the ``emqx_connector`` app (HTTP, MQTT bridge,
plus a memory test double standing in for the SQL/NoSQL pool clients).
"""

from emqx_tpu.connector.memory import MemoryConnector     # noqa: F401
from emqx_tpu.connector.http import HttpConnector         # noqa: F401
from emqx_tpu.connector.mqtt import MqttConnector         # noqa: F401
