"""MySQL connector — the ``emqx_connector_mysql`` analogue.

A from-scratch client-server protocol implementation (no external deps):
HandshakeV10 → HandshakeResponse41 with ``mysql_native_password``
(SHA1(pw) ⊕ SHA1(scramble ∥ SHA1(SHA1(pw)))) → COM_QUERY text
resultsets (column definitions + rows as length-encoded strings,
EOF-terminated). Placeholders substitute client-side with literal
quoting, mirroring the observable queries of the reference's prepared
statements.

``MiniMySQL`` is the in-repo miniature backend for tests: real
handshake + scramble verification + the same tiny SQL engine as MiniPg.
"""

from __future__ import annotations

import hashlib
import os
import re
import socket
import socketserver
import struct
import threading
from typing import Any, Optional

from emqx_tpu.connector.pgsql import (_COND_RE, _INSERT_RE, _SELECT_RE,
                                      _unquote, render_sql)
from emqx_tpu.resource.resource import Resource

CLIENT_LONG_PASSWORD = 0x0001
CLIENT_PROTOCOL_41 = 0x0200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x00080000

_CAPS = CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41 | \
    CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH


class MySqlError(Exception):
    pass


def native_password(password: str, scramble: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(scramble + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(scramble + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenenc(n: int) -> bytes:
    if n < 251:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


class _Conn:
    """Packet-framed socket (3-byte little-endian length + sequence id)."""

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.buf = b""
        self.seq = 0

    def _exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("mysql closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def read(self) -> bytes:
        head = self._exact(4)
        ln = int.from_bytes(head[:3], "little")
        self.seq = head[3] + 1
        return self._exact(ln)

    def write(self, payload: bytes) -> None:
        self.sock.sendall(
            len(payload).to_bytes(3, "little") + bytes([self.seq & 0xFF])
            + payload)
        self.seq += 1


def _read_lenenc(data: bytes, pos: int) -> tuple[Optional[int], int]:
    b0 = data[pos]
    if b0 < 0xFB:
        return b0, pos + 1
    if b0 == 0xFB:
        return None, pos + 1                       # NULL
    if b0 == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if b0 == 0xFD:
        return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9


class MySqlClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 3306,
                 user: str = "root", password: str = "",
                 database: str = "mqtt", timeout_s: float = 5.0) -> None:
        self.addr = (host, port)
        self.user, self.password, self.database = user, password, database
        self.timeout_s = timeout_s
        self._conn: Optional[_Conn] = None
        self._lock = threading.Lock()

    def _connect(self) -> None:
        sock = socket.create_connection(self.addr, self.timeout_s)
        sock.settimeout(self.timeout_s)
        conn = _Conn(sock)
        greet = conn.read()
        if greet[:1] == b"\xff":
            raise MySqlError(greet[9:].decode("utf-8", "replace"))
        pos = 1
        end = greet.index(b"\0", pos)              # server version
        pos = end + 1 + 4                          # thread id
        scramble = greet[pos:pos + 8]
        pos += 8 + 1 + 2 + 1 + 2 + 2 + 1 + 10      # filler..reserved
        scramble += greet[pos:pos + 12]            # part 2 (12 of 13)
        auth = native_password(self.password, scramble)
        resp = struct.pack("<IIB", _CAPS, 1 << 24, 0x21) + b"\0" * 23
        resp += self.user.encode() + b"\0"
        resp += bytes([len(auth)]) + auth
        resp += b"mysql_native_password\0"
        conn.write(resp)
        ok = conn.read()
        if ok[:1] == b"\xff":
            raise MySqlError(ok[9:].decode("utf-8", "replace"))
        self._conn = conn
        if self.database:
            self._query_locked(f"USE {self.database}")

    def query(self, sql: str) -> tuple[list[str], list[list]]:
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._conn is None:
                        self._connect()
                    return self._query_locked(sql)
                except (OSError, ConnectionError):
                    self.close()
                    if attempt:
                        raise
            raise ConnectionError("unreachable")

    def _query_locked(self, sql: str) -> tuple[list[str], list[list]]:
        conn = self._conn
        conn.seq = 0
        conn.write(b"\x03" + sql.encode())
        first = conn.read()
        if first[:1] == b"\xff":
            raise MySqlError(first[9:].decode("utf-8", "replace"))
        if first[:1] == b"\x00":                   # OK packet (no resultset)
            return [], []
        ncols, _ = _read_lenenc(first, 0)
        cols = []
        for _ in range(ncols):
            d = conn.read()
            # catalog, schema, table, org_table, name, org_name (lenenc strs)
            pos = 0
            vals = []
            for _f in range(6):
                ln, pos = _read_lenenc(d, pos)
                vals.append(d[pos:pos + (ln or 0)])
                pos += ln or 0
            cols.append(vals[4].decode())
        eof = conn.read()
        assert eof[:1] == b"\xfe"
        rows: list[list] = []
        while True:
            d = conn.read()
            if d[:1] == b"\xfe" and len(d) < 9:    # EOF
                break
            if d[:1] == b"\xff":
                raise MySqlError(d[9:].decode("utf-8", "replace"))
            row, pos = [], 0
            for _ in range(ncols):
                ln, pos = _read_lenenc(d, pos)
                if ln is None:
                    row.append(None)
                else:
                    row.append(d[pos:pos + ln].decode("utf-8", "replace"))
                    pos += ln
            rows.append(row)
        return cols, rows

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.sock.close()
            except OSError:
                pass
        self._conn = None


class MySqlConnector(Resource):
    def __init__(self, **kw: Any) -> None:
        self.client = MySqlClient(**kw)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(f"mysql {self.client.addr} unreachable")

    def on_stop(self) -> None:
        self.client.close()

    def on_query(self, req: Any) -> Any:
        sql = req["sql"] if isinstance(req, dict) else str(req)
        binds = req.get("binds", {}) if isinstance(req, dict) else {}
        try:
            return self.client.query(render_sql(sql, binds))
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_health_check(self) -> bool:
        try:
            self.client.query("SELECT 1")
            return True
        except (OSError, ConnectionError, MySqlError):
            return False


# ---------------------------------------------------------------------------
# in-repo miniature server (test backend)


class MiniMySQL:
    """HandshakeV10 + native-password verification + the tiny SQL engine
    (same dict tables as MiniPg)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 user: str = "root", password: str = "") -> None:
        self.tables: dict[str, list[dict]] = {}
        self.user, self.password = user, password
        mini = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    mini._session(_Conn(self.request))
                except (ConnectionError, OSError):
                    pass

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def _session(self, conn: _Conn) -> None:
        scramble = os.urandom(20)
        greet = (b"\x0a" + b"8.0-mini\0" + struct.pack("<I", 1)
                 + scramble[:8] + b"\0"
                 + struct.pack("<H", _CAPS & 0xFFFF) + b"\x21"
                 + struct.pack("<H", 2)
                 + struct.pack("<H", (_CAPS >> 16) & 0xFFFF)
                 + bytes([21]) + b"\0" * 10
                 + scramble[8:] + b"\0"
                 + b"mysql_native_password\0")
        conn.write(greet)
        resp = conn.read()
        pos = 4 + 4 + 1 + 23
        end = resp.index(b"\0", pos)
        user = resp[pos:end].decode()
        pos = end + 1
        alen = resp[pos]
        auth = resp[pos + 1:pos + 1 + alen]
        want = native_password(self.password, scramble)
        if user != self.user or auth != want:
            conn.write(b"\xff" + struct.pack("<H", 1045) + b"#28000"
                       + b"Access denied")
            return
        conn.write(b"\x00\x00\x00\x02\x00\x00\x00")     # OK
        while True:
            conn.seq = 0
            try:
                pkt = conn.read()
            except (ConnectionError, OSError):
                return
            if not pkt or pkt[:1] == b"\x01":            # COM_QUIT
                return
            if pkt[:1] != b"\x03":                       # only COM_QUERY
                conn.write(b"\x00\x00\x00\x02\x00\x00\x00")
                continue
            sql = pkt[1:].decode("utf-8", "replace")
            try:
                self._run(conn, sql)
            except Exception as e:   # noqa: BLE001 — surfaced as mysql err
                conn.write(b"\xff" + struct.pack("<H", 1064) + b"#42000"
                           + str(e).encode())

    def _run(self, conn: _Conn, sql: str) -> None:
        up = sql.strip().upper()
        if up.startswith(("USE ", "SET ")):
            conn.write(b"\x00\x00\x00\x02\x00\x00\x00")
            return
        if up.startswith("SELECT 1"):
            self._result(conn, ["1"], [["1"]])
            return
        m = _SELECT_RE.match(sql)
        if m:
            table = self.tables.get(m.group("table").lower(), [])
            conds = []
            if m.group("where"):
                conds = [(c, _unquote(v))
                         for c, v in _COND_RE.findall(m.group("where"))]
            cols = [c.strip() for c in m.group("cols").split(",")]
            rows = []
            for rec in table:
                if all(str(rec.get(c, "")) == v for c, v in conds):
                    if cols == ["*"]:
                        cols = list(rec)
                    rows.append([None if rec.get(c) is None
                                 else str(rec.get(c, "")) for c in cols])
            self._result(conn, cols if cols != ["*"] else [], rows)
            return
        m = _INSERT_RE.match(sql)
        if m:
            cols = [c.strip() for c in m.group("cols").split(",")]
            vals = [_unquote(v) for v in
                    re.findall(r"'(?:[^']|'')*'|[^,]+", m.group("vals"))]
            self.tables.setdefault(m.group("table").lower(), []).append(
                dict(zip(cols, vals)))
            conn.write(b"\x00\x01\x00\x02\x00\x00\x00")  # OK, 1 row
            return
        raise MySqlError(f"unsupported SQL: {sql[:60]}")

    @staticmethod
    def _result(conn: _Conn, cols: list[str], rows: list[list]) -> None:
        conn.write(_lenenc(len(cols)))
        for c in cols:
            name = c.encode()
            d = (_lenenc(3) + b"def" + _lenenc(0) + _lenenc(0) + _lenenc(0)
                 + _lenenc(len(name)) + name + _lenenc(len(name)) + name
                 + b"\x0c" + struct.pack("<HIBHB", 0x21, 255, 253, 0, 0)
                 + b"\0\0")
            conn.write(d)
        conn.write(b"\xfe\x00\x00\x02\x00")              # EOF
        for row in rows:
            out = b""
            for v in row:
                if v is None:
                    out += b"\xfb"
                else:
                    b = str(v).encode()
                    out += _lenenc(len(b)) + b
            conn.write(out)
        conn.write(b"\xfe\x00\x00\x02\x00")              # EOF

    def start(self) -> "MiniMySQL":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-mysql")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
