"""LDAP connector — the ``emqx_connector_ldap`` analogue.

A from-scratch LDAPv3 client (RFC 4511) over a minimal BER codec:
simple BindRequest, SearchRequest with RFC 4515 filter strings,
UnbindRequest. The reference pools `eldap` connections and exposes
``{search, Base, Filter, Attributes}`` queries
(emqx_connector_ldap.erl:102-118, search/4); this client exposes the
same surface plus ``check_bind`` (re-bind as a looked-up DN), the
classic LDAP password-check primitive its authn integrations use.

``MiniLDAP`` is the in-repo miniature directory for tests: real BER
framing over an in-memory DN tree, answering bind (against
``userPassword``), search (base/one/sub scopes, and/or/not/equality/
presence/substring filters) and unbind — the same role the reference's
docker-compose openldap container plays in CI
(.ci/docker-compose-file/docker-compose-ldap-tcp.yaml).
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Any, Iterable, Optional

from emqx_tpu.resource.resource import Resource


class LdapError(Exception):
    pass


class TruncatedBer(LdapError):
    """More bytes may complete the element — retry after recv().
    Distinct from structural malformation (plain LdapError), which no
    amount of extra bytes can repair; the client must fail fast on the
    latter instead of spinning on recv() until the socket timeout."""


# ---------------------------------------------------------------------------
# minimal BER (definite lengths only — LDAP never needs indefinite)

SEQUENCE = 0x30
SET = 0x31
INTEGER = 0x02
OCTET_STRING = 0x04
ENUMERATED = 0x0A
BOOLEAN = 0x01


def ber(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(lb)]) + lb + content


def ber_int(v: int, tag: int = INTEGER) -> bytes:
    if v == 0:
        return bytes([tag, 1, 0])
    out = v.to_bytes((v.bit_length() // 8) + 1, "big", signed=True)
    # strip redundant leading 0x00 for positive values that fit
    while len(out) > 1 and out[0] == 0 and out[1] < 0x80:
        out = out[1:]
    return ber(tag, out)


def ber_str(s: str | bytes, tag: int = OCTET_STRING) -> bytes:
    return ber(tag, s.encode() if isinstance(s, str) else s)


def ber_read(data: bytes, pos: int) -> tuple[int, bytes, int]:
    """-> (tag, content, next_pos). Raises TruncatedBer when more bytes
    may complete the element, LdapError on structural malformation."""
    if pos + 2 > len(data):
        raise TruncatedBer("truncated BER header")
    tag = data[pos]
    ln = data[pos + 1]
    pos += 2
    if ln & 0x80:
        k = ln & 0x7F
        if k == 0:            # X.690 8.1.3.6: 0x80 = indefinite form,
            raise LdapError(  # forbidden in LDAP's DER subset
                "reserved/indefinite BER length")
        if pos + k > len(data):
            raise TruncatedBer("truncated BER length")
        ln = int.from_bytes(data[pos:pos + k], "big")
        pos += k
    if pos + ln > len(data):
        raise TruncatedBer("truncated BER content")
    return tag, data[pos:pos + ln], pos + ln


def ber_seq(data: bytes) -> list[tuple[int, bytes]]:
    """Decode all TLVs inside a constructed value."""
    out, pos = [], 0
    while pos < len(data):
        tag, content, pos = ber_read(data, pos)
        out.append((tag, content))
    return out


def _decode_int(content: bytes) -> int:
    return int.from_bytes(content, "big", signed=True)


# ---------------------------------------------------------------------------
# RFC 4515 filter strings -> LDAP Filter BER

_F_AND, _F_OR, _F_NOT = 0xA0, 0xA1, 0xA2
_F_EQ, _F_SUBSTR, _F_GE, _F_LE, _F_PRESENT = 0xA3, 0xA4, 0xA5, 0xA6, 0x87


def parse_filter(s: str) -> bytes:
    """Parse an RFC 4515 filter string into its BER encoding.

    Supports &, |, !, equality, presence (=*), substrings (a=*b*c),
    >= and <= — the operator set the reference's LDAP integrations
    generate.
    """
    out, pos = _parse_filter(s.strip(), 0)
    if pos != len(s.strip()):
        raise LdapError(f"trailing filter input at {pos}")
    return out


def _parse_filter(s: str, pos: int) -> tuple[bytes, int]:
    if pos >= len(s) or s[pos] != "(":
        raise LdapError(f"filter must start with '(' at {pos}")
    pos += 1
    if pos >= len(s):
        raise LdapError("unterminated filter")
    c = s[pos]
    if c in "&|":
        tag = _F_AND if c == "&" else _F_OR
        pos += 1
        subs = []
        while pos < len(s) and s[pos] == "(":
            sub, pos = _parse_filter(s, pos)
            subs.append(sub)
        if not subs:
            raise LdapError("empty and/or filter")
        return _close(s, pos, ber(tag, b"".join(subs)))
    if c == "!":
        sub, pos = _parse_filter(s, pos + 1)
        return _close(s, pos, ber(_F_NOT, sub))
    # item: attr OP value
    end = s.find(")", pos)
    if end < 0:
        raise LdapError("unterminated filter item")
    item = s[pos:end]
    pos = end
    for op, tag in (("<=", _F_LE), (">=", _F_GE), ("=", _F_EQ)):
        k = item.find(op)
        if k > 0:
            attr, val = item[:k], item[k + len(op):]
            break
    else:
        raise LdapError(f"no operator in filter item {item!r}")
    if tag == _F_EQ and val == "*":
        return _close(s, pos, ber_str(attr, _F_PRESENT))
    if tag == _F_EQ and "*" in val:
        parts = val.split("*")
        subs = b""
        if parts[0]:
            subs += ber_str(_unescape(parts[0]), 0x80)      # initial
        for mid in parts[1:-1]:
            if mid:
                subs += ber_str(_unescape(mid), 0x81)       # any
        if parts[-1]:
            subs += ber_str(_unescape(parts[-1]), 0x82)     # final
        return _close(s, pos, ber(
            _F_SUBSTR, ber_str(attr) + ber(SEQUENCE, subs)))
    return _close(s, pos, ber(
        tag, ber_str(attr) + ber_str(_unescape(val))))


def _close(s: str, pos: int, encoded: bytes) -> tuple[bytes, int]:
    if pos >= len(s) or s[pos] != ")":
        raise LdapError(f"expected ')' at {pos}")
    return encoded, pos + 1


def _unescape(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        if v[i] == "\\":
            if i + 3 > len(v):
                raise LdapError("truncated filter escape")
            try:
                out.append(chr(int(v[i + 1:i + 3], 16)))
            except ValueError:
                raise LdapError(
                    f"bad filter escape \\{v[i + 1:i + 3]}") from None
            i += 3
        else:
            out.append(v[i])
            i += 1
    return "".join(out)


def ldap_escape(v: str) -> str:
    """RFC 4515 §3 value escaping — for substituting untrusted strings
    (usernames, clientids) into filter templates."""
    return "".join(f"\\{ord(c):02x}" if c in "\\*()\x00" else c for c in v)


# ---------------------------------------------------------------------------
# protocol ops (APPLICATION tags, RFC 4511 §4)

_OP_BIND_REQ, _OP_BIND_RESP = 0x60, 0x61
_OP_UNBIND = 0x42
_OP_SEARCH_REQ = 0x63
_OP_SEARCH_ENTRY, _OP_SEARCH_DONE = 0x64, 0x65

SCOPES = {"base": 0, "one": 1, "sub": 2}

RESULT_SUCCESS = 0
RESULT_INVALID_CREDENTIALS = 49
RESULT_NO_SUCH_OBJECT = 32
RESULT_UNWILLING = 53


def _msg(msg_id: int, op: bytes) -> bytes:
    return ber(SEQUENCE, ber_int(msg_id) + op)


def _bind_request(msg_id: int, dn: str, password: str | bytes) -> bytes:
    body = ber_int(3) + ber_str(dn) + ber_str(password, 0x80)  # simple auth
    return _msg(msg_id, ber(_OP_BIND_REQ, body))


def _search_request(msg_id: int, base: str, scope: str, filt: bytes,
                    attrs: Iterable[str], size_limit: int = 0) -> bytes:
    body = (ber_str(base) + ber_int(SCOPES[scope], ENUMERATED) +
            ber_int(0, ENUMERATED) +                 # neverDerefAliases
            ber_int(size_limit) + ber_int(0) +       # sizeLimit, timeLimit
            bytes([BOOLEAN, 1, 0]) +                 # typesOnly = false
            filt +
            ber(SEQUENCE, b"".join(ber_str(a) for a in attrs)))
    return _msg(msg_id, ber(_OP_SEARCH_REQ, body))


def _result(op_tag: int, code: int, dn: str = "", diag: str = "") -> bytes:
    return ber(op_tag,
               ber_int(code, ENUMERATED) + ber_str(dn) + ber_str(diag))


def _parse_result(content: bytes) -> tuple[int, str]:
    parts = ber_seq(content)
    code = _decode_int(parts[0][1])
    diag = parts[2][1].decode("utf-8", "replace") if len(parts) > 2 else ""
    return code, diag


def _parse_entry(content: bytes) -> tuple[str, dict[str, list[str]]]:
    parts = ber_seq(content)
    dn = parts[0][1].decode("utf-8", "replace")
    attrs: dict[str, list[str]] = {}
    for _tag, pa in ber_seq(parts[1][1]):
        fields = ber_seq(pa)
        name = fields[0][1].decode("utf-8", "replace")
        vals = [v.decode("utf-8", "replace")
                for _t, v in ber_seq(fields[1][1])]
        attrs[name] = vals
    return dn, attrs


# ---------------------------------------------------------------------------
# client


class LdapClient:
    """Blocking LDAPv3 client: connect-and-bind lazily, retry once on a
    dead socket (same discipline as the other wire clients here)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 389,
                 bind_dn: str = "", bind_password: str = "",
                 timeout_s: float = 5.0) -> None:
        self.addr = (host, port)
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._msg_id = 0
        self._lock = threading.Lock()

    # -- wire --------------------------------------------------------------

    def _connect(self) -> None:
        self._sock = socket.create_connection(self.addr, self.timeout_s)
        self._sock.settimeout(self.timeout_s)
        self._buf = b""
        self._msg_id = 0
        code, diag = self._bind(self.bind_dn, self.bind_password)
        if code != RESULT_SUCCESS:
            self.close()
            raise LdapError(f"bind failed ({code}): {diag}")

    def _recv_msg(self) -> tuple[int, int, bytes]:
        """-> (msg_id, op_tag, op_content)"""
        while True:
            try:
                _tag, content, used = ber_read(self._buf, 0)
            except TruncatedBer:      # only truncation retries; malformed
                chunk = self._sock.recv(65536)   # BER fails fast below
                if not chunk:
                    raise ConnectionError("ldap closed") from None
                self._buf += chunk
                continue
            except LdapError:
                # wire desync is unrecoverable: drop the connection so
                # the next call reconnects instead of replaying the
                # poisoned buffer forever
                self.close()
                raise
            self._buf = self._buf[used:]
            try:
                parts = ber_seq(content)
                msg_id = _decode_int(parts[0][1])
                op_tag, op_content = parts[1][0], parts[1][1]
            except (LdapError, IndexError) as e:
                # a complete outer envelope with malformed content is
                # the same desync — same teardown
                self.close()
                raise LdapError(f"malformed LDAPMessage: {e}") from None
            return msg_id, op_tag, op_content

    def _bind(self, dn: str, password: str | bytes) -> tuple[int, str]:
        self._msg_id += 1
        self._sock.sendall(_bind_request(self._msg_id, dn, password))
        while True:
            mid, op, content = self._recv_msg()
            if mid == self._msg_id and op == _OP_BIND_RESP:
                return _parse_result(content)

    # -- public ------------------------------------------------------------

    def search(self, base: str, filter_: str, attrs: Iterable[str] = (),
               scope: str = "sub") -> list[tuple[str, dict[str, list[str]]]]:
        """{search, Base, Filter, Attributes} — returns [(dn, attrs)]."""
        filt = parse_filter(filter_)
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._connect()
                    self._msg_id += 1
                    self._sock.sendall(_search_request(
                        self._msg_id, base, scope, filt, attrs))
                    entries = []
                    while True:
                        mid, op, content = self._recv_msg()
                        if mid != self._msg_id:
                            continue
                        if op == _OP_SEARCH_ENTRY:
                            entries.append(_parse_entry(content))
                        elif op == _OP_SEARCH_DONE:
                            code, diag = _parse_result(content)
                            if code not in (RESULT_SUCCESS,
                                            RESULT_NO_SUCH_OBJECT):
                                raise LdapError(
                                    f"search failed ({code}): {diag}")
                            return entries
                except (OSError, ConnectionError):
                    self.close()
                    if attempt:
                        raise

    def check_bind(self, dn: str, password: str | bytes) -> bool:
        """Authenticate by re-binding as ``dn`` on a scratch connection —
        the LDAP way to verify a password without reading the hash."""
        sock = socket.create_connection(self.addr, self.timeout_s)
        sock.settimeout(self.timeout_s)
        try:
            sock.sendall(_bind_request(1, dn, password))
            buf = b""
            while True:
                try:
                    _t, content, _u = ber_read(buf, 0)
                    break
                except TruncatedBer:    # malformed BER propagates; the
                    chunk = sock.recv(65536)   # finally closes the sock
                    if not chunk:
                        raise ConnectionError("ldap closed") from None
                    buf += chunk
            parts = ber_seq(content)
            code, _ = _parse_result(parts[1][1])
            return code == RESULT_SUCCESS
        finally:
            try:
                sock.sendall(ber(SEQUENCE, ber_int(2) + ber(_OP_UNBIND, b"")))
                sock.close()
            except OSError:
                pass

    def ping(self) -> bool:
        try:
            self.search("", "(objectClass=*)", scope="base")
            return True
        except (OSError, ConnectionError, LdapError):
            return False

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._buf = b""


class LdapConnector(Resource):
    """emqx_resource-shaped wrapper (emqx_connector_ldap.erl on_start/
    on_query/on_get_status)."""

    def __init__(self, **kw: Any) -> None:
        self.client = LdapClient(**kw)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(f"ldap {self.client.addr} unreachable")

    def on_stop(self) -> None:
        self.client.close()

    def on_query(self, req: Any) -> Any:
        try:
            if isinstance(req, dict) and "search" in req:
                return self.client.search(
                    req["search"], req.get("filter", "(objectClass=*)"),
                    req.get("attributes", ()), req.get("scope", "sub"))
            if isinstance(req, dict) and "bind" in req:
                return self.client.check_bind(
                    req["bind"], req.get("password", ""))
            raise LdapError(f"unsupported ldap query {req!r}")
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_health_check(self) -> bool:
        return self.client.ping()


# ---------------------------------------------------------------------------
# in-repo miniature directory server (test backend)


class MiniLDAP:
    """BER-real LDAP subset over an in-memory DN→attrs map.

    bind: "" (anonymous), the configured root DN, or any entry DN whose
    ``userPassword`` matches. search: base/one/sub scopes with the
    filter operators parse_filter emits.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 root_dn: str = "cn=admin,dc=emqx,dc=io",
                 root_password: str = "admin") -> None:
        self.entries: dict[str, dict[str, list[str]]] = {}
        self.root_dn = root_dn.lower()
        self.root_password = root_password
        mini = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                mini._live.add(self.request)
                try:
                    mini._session(self.request)
                except (ConnectionError, OSError, LdapError):
                    pass
                finally:
                    mini._live.discard(self.request)

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None
        self._live: set[socket.socket] = set()

    def add(self, dn: str, **attrs: str | list[str]) -> None:
        self.entries[dn.lower()] = {
            k.replace("_", "").lower(): (v if isinstance(v, list) else [v])
            for k, v in attrs.items()}

    # -- session -----------------------------------------------------------

    def _session(self, sock: socket.socket) -> None:
        buf = b""
        while True:
            while True:
                try:
                    _t, content, used = ber_read(buf, 0)
                    break
                except TruncatedBer:
                    chunk = sock.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                except LdapError:
                    return            # malformed frame: drop the session
            buf = buf[used:]
            try:
                parts = ber_seq(content)
                msg_id = _decode_int(parts[0][1])
                op_tag, op = parts[1]
            except (LdapError, IndexError):
                return                # malformed content: drop the session
            if op_tag == _OP_UNBIND:
                return
            if op_tag == _OP_BIND_REQ:
                sock.sendall(_msg(msg_id, self._do_bind(op)))
            elif op_tag == _OP_SEARCH_REQ:
                for frame in self._do_search(msg_id, op):
                    sock.sendall(frame)
            else:
                sock.sendall(_msg(msg_id, _result(
                    _OP_SEARCH_DONE, RESULT_UNWILLING,
                    diag="unsupported operation")))

    def _do_bind(self, op: bytes) -> bytes:
        fields = ber_seq(op)
        dn = fields[1][1].decode("utf-8", "replace").lower()
        password = fields[2][1].decode("utf-8", "replace")
        ok = (dn == "" or
              (dn == self.root_dn and password == self.root_password) or
              password in self.entries.get(dn, {}).get("userpassword", ()))
        return _result(_OP_BIND_RESP,
                       RESULT_SUCCESS if ok else RESULT_INVALID_CREDENTIALS,
                       diag="" if ok else "invalid credentials")

    def _do_search(self, msg_id: int, op: bytes):
        fields = ber_seq(op)
        base = fields[0][1].decode("utf-8", "replace").lower()
        scope = _decode_int(fields[1][1])
        filt = (fields[6][0], fields[6][1])
        attrs_wanted = [a.decode() for _t, a in ber_seq(fields[7][1])]
        frames = []
        for dn, attrs in self.entries.items():
            if not _in_scope(dn, base, scope):
                continue
            if not _eval_filter(filt, attrs):
                continue
            out = {k: v for k, v in attrs.items()
                   if not attrs_wanted or k in [a.lower()
                                                for a in attrs_wanted]}
            body = ber_str(dn) + ber(SEQUENCE, b"".join(
                ber(SEQUENCE, ber_str(k) + ber(SET, b"".join(
                    ber_str(x) for x in vs)))
                for k, vs in out.items()))
            frames.append(_msg(msg_id, ber(_OP_SEARCH_ENTRY, body)))
        frames.append(_msg(msg_id, _result(_OP_SEARCH_DONE, RESULT_SUCCESS)))
        return frames

    def start(self) -> "MiniLDAP":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="mini-ldap")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        for s in list(self._live):       # drop live sessions too, so a
            try:                         # "restarted" server on the same
                s.close()                # port gets the reconnects
            except OSError:
                pass


def _in_scope(dn: str, base: str, scope: int) -> bool:
    if scope == 0:
        return dn == base
    if dn == base:
        # RFC 4511 §4.5.1.2: wholeSubtree includes the base object;
        # singleLevel (scope 1) covers immediate subordinates only
        return scope == 2
    if base and not dn.endswith("," + base):
        return False
    rel = dn[:-len(base)].rstrip(",") if base else dn
    if scope == 1:
        return "," not in rel
    return True


def _eval_filter(filt: tuple[int, bytes],
                 attrs: dict[str, list[str]]) -> bool:
    tag, content = filt

    def vals(name: bytes) -> list[str]:
        return attrs.get(name.decode().lower(), [])

    if tag == _F_AND:
        return all(_eval_filter(f, attrs) for f in ber_seq(content))
    if tag == _F_OR:
        return any(_eval_filter(f, attrs) for f in ber_seq(content))
    if tag == _F_NOT:
        (inner,) = ber_seq(content)
        return not _eval_filter(inner, attrs)
    if tag == _F_PRESENT:
        return bool(vals(content))
    if tag == _F_EQ:
        a, v = ber_seq(content)
        return v[1].decode().lower() in [x.lower() for x in vals(a[1])]
    if tag in (_F_GE, _F_LE):
        a, v = ber_seq(content)
        want = v[1].decode()
        op = (lambda x: x >= want) if tag == _F_GE else (lambda x: x <= want)
        return any(op(x) for x in vals(a[1]))
    if tag == _F_SUBSTR:
        a, subseq = ber_seq(content)
        cands = [x.lower() for x in vals(a[1])]
        pieces = ber_seq(subseq[1])
        for cand in cands:
            pos, ok = 0, True
            for i, (ptag, pval) in enumerate(pieces):
                p = pval.decode().lower()
                if ptag == 0x80:                      # initial
                    if not cand.startswith(p):
                        ok = False
                        break
                    pos = len(p)
                elif ptag == 0x82:                    # final
                    if not cand.endswith(p) or cand.rfind(p) < pos:
                        ok = False
                        break
                else:                                 # any
                    k = cand.find(p, pos)
                    if k < 0:
                        ok = False
                        break
                    pos = k + len(p)
            if ok:
                return True
        return False
    raise LdapError(f"unsupported filter tag 0x{tag:02x}")
