"""GCP PubSub connector — the ``emqx_ee_connector_gcp_pubsub`` analogue.

Auth follows the reference exactly: a **self-signed service-account
JWT** used directly as the bearer token (no OAuth token exchange) with
``aud = "https://pubsub.googleapis.com/"``, ``iss = sub =
client_email``, ``kid`` from the service-account JSON, RS256, 1-hour
expiry, refreshed ahead of expiry by the connector (the reference runs
a jwt_worker process per resource —
emqx_ee_connector_gcp_pubsub.erl:255-300,
emqx_connector_jwt_worker.erl).

Publish is ``POST /v1/projects/{project}/topics/{topic}:publish`` with
``{"messages": [{"data": base64, "attributes": ..., "orderingKey":
...}]}`` (publish_path/1, encode_payload/2 — data is base64 of the
rendered payload template).

``MiniPubSub`` is the in-repo miniature endpoint for tests: verifies
the RS256 bearer JWT (signature, aud, iss, exp) against the service
account's public key and records published messages per topic.
"""

from __future__ import annotations

import base64
import http.server
import json
import threading
import time
from typing import Any, Optional

from emqx_tpu.access.authn import _b64url, _unb64url
from emqx_tpu.resource.resource import Resource

PUBSUB_AUD = "https://pubsub.googleapis.com/"
_TOKEN_TTL_S = 3600
_REFRESH_AHEAD_S = 300


class PubSubError(Exception):
    pass


def rs256_sign(claims: dict, private_key_pem: bytes,
               kid: Optional[str] = None) -> str:
    """Mint an RS256 JWT (the service-account self-signed token)."""
    from cryptography.hazmat.primitives.asymmetric import padding
    from cryptography.hazmat.primitives.hashes import SHA256
    from cryptography.hazmat.primitives.serialization import (
        load_pem_private_key)

    header: dict[str, Any] = {"alg": "RS256", "typ": "JWT"}
    if kid:
        header["kid"] = kid
    signing_input = (_b64url(json.dumps(header).encode()) + b"." +
                     _b64url(json.dumps(claims).encode()))
    key = load_pem_private_key(private_key_pem, password=None)
    sig = key.sign(signing_input, padding.PKCS1v15(), SHA256())
    return (signing_input + b"." + _b64url(sig)).decode()


class GcpPubSubConnector(Resource):
    """service_account_json: the GCP key-file dict — needs project_id,
    client_email, private_key (PEM), private_key_id. ``base_url``
    overrides the endpoint (tests point it at MiniPubSub)."""

    def __init__(self, service_account_json: dict, pubsub_topic: str,
                 base_url: str = "https://pubsub.googleapis.com",
                 timeout_s: float = 5.0) -> None:
        for field in ("project_id", "client_email", "private_key"):
            if not service_account_json.get(field):
                raise PubSubError(f"service_account_json missing {field}")
        self.sa = service_account_json
        self.pubsub_topic = pubsub_topic
        self.timeout_s = timeout_s
        from emqx_tpu.connector.http import HttpConnector
        self.http = HttpConnector(base_url, timeout_s=timeout_s)
        self._token: Optional[str] = None
        self._token_exp = 0.0
        self._lock = threading.Lock()

    # -- token lifecycle ---------------------------------------------------

    def _bearer(self) -> str:
        with self._lock:
            now = time.time()
            if self._token is None or now > self._token_exp - _REFRESH_AHEAD_S:
                claims = {
                    "iss": self.sa["client_email"],
                    "sub": self.sa["client_email"],
                    "aud": PUBSUB_AUD,
                    "iat": int(now),
                    "exp": int(now) + _TOKEN_TTL_S,
                }
                self._token = rs256_sign(
                    claims, self.sa["private_key"].encode(),
                    kid=self.sa.get("private_key_id"))
                self._token_exp = now + _TOKEN_TTL_S
            return self._token

    @property
    def publish_path(self) -> str:
        return (f"/v1/projects/{self.sa['project_id']}"
                f"/topics/{self.pubsub_topic}:publish")

    # -- resource callbacks ------------------------------------------------

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(
                f"pubsub endpoint {self.http.host}:{self.http.port} "
                "unreachable")

    def on_stop(self) -> None:
        self._token = None

    def _publish(self, messages: list[dict]) -> list[str]:
        resp = self.http.on_query({
            "method": "post",
            "path": self.publish_path,
            "headers": {"Authorization": f"Bearer {self._bearer()}",
                        "Content-Type": "application/json"},
            "body": json.dumps({"messages": messages}),
        })
        if resp["status"] == 401:
            # expired/revoked token: re-mint once and retry
            with self._lock:
                self._token = None
            resp = self.http.on_query({
                "method": "post",
                "path": self.publish_path,
                "headers": {"Authorization": f"Bearer {self._bearer()}",
                            "Content-Type": "application/json"},
                "body": json.dumps({"messages": messages}),
            })
        if resp["status"] != 200:
            raise PubSubError(
                f"publish failed {resp['status']}: "
                f"{resp['body'][:200]!r}")
        return json.loads(resp["body"]).get("messageIds", [])

    def on_query(self, req: Any) -> Any:
        msgs = req["messages"] if isinstance(req, dict) and "messages" in req \
            else [req]
        return self._publish(msgs)

    def on_batch_query(self, reqs: list) -> list:
        """One :publish call for the whole flushed batch."""
        flat: list[dict] = []
        counts = []
        for r in reqs:
            ms = r["messages"] if isinstance(r, dict) and "messages" in r \
                else [r]
            flat.extend(ms)
            counts.append(len(ms))
        ids = self._publish(flat)
        out, k = [], 0
        for n in counts:
            out.append(ids[k:k + n])
            k += n
        return out

    def on_health_check(self) -> bool:
        return self.http.on_health_check()


# ---------------------------------------------------------------------------
# in-repo miniature endpoint (test backend)


class MiniPubSub:
    """Verifies the self-signed bearer JWT and records messages.

    Construct with the service account's *public* key PEM (tests derive
    it from the private key they generate)."""

    def __init__(self, public_key_pem: bytes, project_id: str = "proj",
                 host: str = "127.0.0.1", port: int = 0) -> None:
        from cryptography.hazmat.primitives.serialization import (
            load_pem_public_key)

        self.public_key = load_pem_public_key(public_key_pem)
        self.project_id = project_id
        self.topics: dict[str, list[dict]] = {}
        self.auth_failures = 0
        mini = self

        class _H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):       # quiet
                pass

            def do_POST(self):
                ln = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(ln)
                status, reply = mini._handle(self.path, self.headers, body)
                data = json.dumps(reply).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class _S(http.server.ThreadingHTTPServer):
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # -- request handling --------------------------------------------------

    def _check_jwt(self, headers) -> Optional[str]:
        """-> error string, or None if the bearer token verifies."""
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives.asymmetric import padding
        from cryptography.hazmat.primitives.hashes import SHA256

        auth = headers.get("Authorization") or ""
        if not auth.startswith("Bearer "):
            return "missing bearer"
        token = auth[7:]
        try:
            h, b, s = token.split(".")
            sig = _unb64url(s)
            self.public_key.verify(
                sig, f"{h}.{b}".encode(), padding.PKCS1v15(), SHA256())
            claims = json.loads(_unb64url(b))
        except (ValueError, InvalidSignature):
            return "bad signature"
        if claims.get("aud") != PUBSUB_AUD:
            return "bad aud"
        if claims.get("exp", 0) < time.time():
            return "expired"
        return None

    def _handle(self, path: str, headers, body: bytes):
        err = self._check_jwt(headers)
        if err:
            self.auth_failures += 1
            return 401, {"error": {"code": 401, "message": err}}
        prefix = f"/v1/projects/{self.project_id}/topics/"
        if not (path.startswith(prefix) and path.endswith(":publish")):
            return 404, {"error": {"code": 404, "message": "not found"}}
        topic = path[len(prefix):-len(":publish")]
        try:
            msgs = json.loads(body)["messages"]
            store = self.topics.setdefault(topic, [])
            ids = []
            for m in msgs:
                store.append({
                    "data": base64.b64decode(m.get("data", "")),
                    "attributes": m.get("attributes") or {},
                    "orderingKey": m.get("orderingKey"),
                })
                ids.append(str(len(store)))
            return 200, {"messageIds": ids}
        except (KeyError, ValueError) as e:
            return 400, {"error": {"code": 400, "message": str(e)}}

    def start(self) -> "MiniPubSub":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-pubsub")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def make_test_service_account(project_id: str = "proj") -> tuple[dict, bytes]:
    """Generate an RSA service-account JSON + its public key PEM (for
    MiniPubSub) — test/tooling helper."""
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.hazmat.primitives.serialization import (
        Encoding, NoEncryption, PrivateFormat, PublicFormat)

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    priv = key.private_bytes(Encoding.PEM, PrivateFormat.PKCS8,
                             NoEncryption()).decode()
    pub = key.public_key().public_bytes(Encoding.PEM,
                                        PublicFormat.SubjectPublicKeyInfo)
    sa = {"type": "service_account", "project_id": project_id,
          "private_key_id": "kid-1", "private_key": priv,
          "client_email": f"svc@{project_id}.iam.gserviceaccount.com"}
    return sa, pub
