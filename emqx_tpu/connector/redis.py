"""Redis connector — the ``emqx_connector_redis`` (eredis) analogue.

A from-scratch RESP2 client over a blocking socket (no external deps):
commands go out as RESP arrays, replies parse simple strings, errors,
integers, bulk and multi-bulk. Query shape: ``{"cmd": ["HGETALL", key]}``
or a raw list. The in-repo ``MiniRedis`` server below backs the tests
the way the reference's CI uses a real Redis container (SURVEY.md §4.5 —
real backends, not mocks; ours is a protocol-faithful miniature).
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Any, Optional

from emqx_tpu.resource.resource import Resource


class RedisError(Exception):
    pass


def encode_command(args: list) -> bytes:
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode()
        out.append(f"${len(b)}\r\n".encode() + b + b"\r\n")
    return b"".join(out)


class _RespReader:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.buf = b""

    def _line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _exactly(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def read(self) -> Any:
        line = self._line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RedisError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n == -1 else self._exactly(n)
        if t == b"*":
            n = int(rest)
            return None if n == -1 else [self.read() for _ in range(n)]
        raise RedisError(f"bad RESP type byte {t!r}")


class RedisClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: Optional[str] = None, db: int = 0,
                 timeout_s: float = 5.0) -> None:
        self.addr = (host, port)
        self.password = password
        self.db = db
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[_RespReader] = None
        self._lock = threading.Lock()

    def _connect(self) -> None:
        self._sock = socket.create_connection(self.addr, self.timeout_s)
        self._sock.settimeout(self.timeout_s)
        self._reader = _RespReader(self._sock)
        if self.password:
            self._do(["AUTH", self.password])
        if self.db:
            self._do(["SELECT", self.db])

    def _do(self, args: list) -> Any:
        self._sock.sendall(encode_command(args))
        return self._reader.read()

    # Commands that are safe to resend after an ambiguous failure (the
    # request may or may not have executed server-side).  Write commands
    # (LPUSH/INCR/SET...) are excluded: a reply-phase drop after the
    # request was written would make a blind retry execute them twice.
    _IDEMPOTENT = frozenset({
        "PING", "GET", "MGET", "EXISTS", "TYPE", "TTL", "PTTL", "STRLEN",
        "HGET", "HMGET", "HGETALL", "HLEN", "HEXISTS", "HKEYS", "HVALS",
        "LRANGE", "LLEN", "LINDEX", "SMEMBERS", "SISMEMBER", "SCARD",
        "ZRANGE", "ZSCORE", "ZCARD", "KEYS", "SCAN", "INFO", "TIME",
        "SELECT", "AUTH",
    })

    def command(self, args: list) -> Any:
        with self._lock:
            connecting = self._sock is None
            try:
                if connecting:
                    try:
                        self._connect()
                    except (OSError, ConnectionError):
                        self.close()
                        raise
                request_written = False
                try:
                    self._sock.sendall(encode_command(args))
                    request_written = True
                    return self._reader.read()
                except (OSError, ConnectionError):
                    self.close()
                    if connecting:
                        raise
                    # Stale pooled connection (server restarted, idle
                    # drop): retry once on a fresh connection — but only
                    # when the failure provably preceded the request
                    # (nothing written yet) or the command is idempotent.
                    # A non-idempotent command that may already have
                    # executed must surface the error to the caller.
                    cmd = str(args[0]).upper() if args else ""
                    if request_written and cmd not in self._IDEMPOTENT:
                        raise
                    try:
                        self._connect()
                        return self._do(args)
                    except (OSError, ConnectionError, RedisError):
                        self.close()
                        raise
            except RedisError:
                if connecting:
                    # handshake rejection (AUTH/SELECT error, -LOADING):
                    # drop the half-set-up socket so the next command
                    # retries the full handshake instead of running
                    # unauthenticated forever
                    self.close()
                raise

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._reader = None


class RedisConnector(Resource):
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: Optional[str] = None, db: int = 0,
                 timeout_s: float = 5.0) -> None:
        self.client = RedisClient(host, port, password, db, timeout_s)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(f"redis {self.client.addr} unreachable")

    def on_stop(self) -> None:
        self.client.close()

    def on_query(self, req: Any) -> Any:
        cmd = req["cmd"] if isinstance(req, dict) else req
        try:
            return self.client.command(list(cmd))
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_health_check(self) -> bool:
        try:
            return self.client.command(["PING"]) == "PONG"
        except (OSError, ConnectionError, RedisError):
            return False


# ---------------------------------------------------------------------------
# in-repo miniature server (test backend)


class MiniRedis:
    """Protocol-faithful subset: PING/AUTH/SELECT/GET/SET/DEL/HSET/HGET/
    HGETALL/SMEMBERS/SADD/EXISTS — what the authn/authz/bridge paths use."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 password: Optional[str] = None) -> None:
        self.data: dict[bytes, Any] = {}
        self.password = password
        store = self.data
        required = password

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                reader = _RespReader(self.request)
                authed = required is None
                while True:
                    try:
                        args = reader.read()
                    except (ConnectionError, OSError):
                        return
                    except RedisError:
                        # malformed RESP from the client: reply -ERR and
                        # drop (protocol state is unrecoverable)
                        try:
                            self.request.sendall(b"-ERR protocol error\r\n")
                        except OSError:
                            pass
                        return
                    if not isinstance(args, list) or not args:
                        continue
                    cmd = bytes(args[0]).upper()
                    try:
                        if cmd == b"AUTH":
                            if required is not None and bytes(
                                    args[1]).decode() == required:
                                authed = True
                                resp = b"+OK\r\n"
                            else:
                                resp = b"-ERR invalid password\r\n"
                        elif not authed:
                            resp = b"-NOAUTH Authentication required.\r\n"
                        else:
                            resp = MiniRedis._exec(store, cmd, args[1:])
                    except Exception as e:   # noqa: BLE001 — protocol error
                        resp = f"-ERR {e}\r\n".encode()
                    try:
                        self.request.sendall(resp)
                    except OSError:
                        return

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _bulk(v: Optional[bytes]) -> bytes:
        if v is None:
            return b"$-1\r\n"
        return f"${len(v)}\r\n".encode() + v + b"\r\n"

    @staticmethod
    def _array(items: list[bytes]) -> bytes:
        return (f"*{len(items)}\r\n".encode()
                + b"".join(MiniRedis._bulk(i) for i in items))

    @staticmethod
    def _exec(store: dict, cmd: bytes, args: list) -> bytes:
        a = [bytes(x) for x in args]
        if cmd == b"PING":
            return b"+PONG\r\n"
        if cmd == b"SELECT":
            return b"+OK\r\n"
        if cmd == b"SET":
            store[a[0]] = a[1]
            return b"+OK\r\n"
        if cmd == b"GET":
            v = store.get(a[0])
            return MiniRedis._bulk(v if isinstance(v, bytes) else None)
        if cmd == b"DEL":
            n = sum(1 for k in a if store.pop(k, None) is not None)
            return f":{n}\r\n".encode()
        if cmd == b"EXISTS":
            return f":{sum(1 for k in a if k in store)}\r\n".encode()
        if cmd == b"HSET":
            h = store.setdefault(a[0], {})
            n = 0
            for i in range(1, len(a) - 1, 2):
                n += a[i] not in h
                h[a[i]] = a[i + 1]
            return f":{n}\r\n".encode()
        if cmd == b"HGET":
            h = store.get(a[0]) or {}
            return MiniRedis._bulk(h.get(a[1]))
        if cmd == b"HGETALL":
            h = store.get(a[0]) or {}
            flat: list[bytes] = []
            for k, v in h.items():
                flat += [k, v]
            return MiniRedis._array(flat)
        if cmd == b"SADD":
            s = store.setdefault(a[0], set())
            n = sum(1 for m in a[1:] if m not in s)
            s.update(a[1:])
            return f":{n}\r\n".encode()
        if cmd == b"SMEMBERS":
            return MiniRedis._array(sorted(store.get(a[0]) or set()))
        return f"-ERR unknown command '{cmd.decode()}'\r\n".encode()

    def start(self) -> "MiniRedis":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-redis")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
