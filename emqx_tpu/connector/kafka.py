"""Kafka producer connector — the ``emqx_ee_bridge_kafka`` (wolff)
analogue.

A from-scratch Kafka wire-protocol client (no external deps) covering
the produce path the bridge needs:

- request framing: int32 size ∥ api_key ∥ api_version ∥ correlation_id
  ∥ client_id, responses correlated by id;
- ``Metadata`` v1 — leader discovery and partition counts;
- ``Produce`` v3 — record batches in the v2 format (varint-encoded
  records, CRC32-C over the batch tail, the format every broker since
  0.11 speaks);
- partitioning: murmur2 of the key like the Java client, round-robin
  when keyless.

``MiniKafka`` is the in-repo miniature broker for tests: real framing,
Metadata + Produce v3 with CRC verification, records retained per
topic-partition (SURVEY §4.5 — the reference's CI drives a real Kafka
container; this miniature speaks the same bytes). crc32c is implemented
in-table here — the reference pulls the crc32cer NIF for the same job
(SURVEY §2.4).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Any, Optional

from emqx_tpu.resource.resource import Resource


class KafkaError(Exception):
    pass


# -- crc32c (Castagnoli), table-driven — the crc32cer NIF's job ------------

_CRC32C_TABLE = []


def _crc32c_init() -> None:
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC32C_TABLE.append(crc)


_crc32c_init()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc = ~crc & 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return ~crc & 0xFFFFFFFF


# -- zigzag varints (record batch v2) --------------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def varint(n: int) -> bytes:
    n = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_varint(data: bytes, pos: int) -> tuple[int, int]:
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(n), pos
        shift += 7


# -- primitive codecs ------------------------------------------------------


def _str16(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


def _bytes32(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _rd_str16(d: bytes, pos: int) -> tuple[Optional[str], int]:
    (n,) = struct.unpack_from(">h", d, pos)
    pos += 2
    if n == -1:
        return None, pos
    return d[pos:pos + n].decode(), pos + n


# -- murmur2 (Java client partitioner) -------------------------------------


def murmur2(data: bytes) -> int:
    seed, m, r = 0x9747B28C, 0x5BD1E995, 24
    h = (seed ^ len(data)) & 0xFFFFFFFF
    for i in range(0, len(data) - 3, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * m) & 0xFFFFFFFF
        k ^= k >> r
        k = (k * m) & 0xFFFFFFFF
        h = ((h * m) & 0xFFFFFFFF) ^ k
    rest = len(data) & 3
    if rest:
        tail = data[len(data) - rest:]
        for j in range(rest - 1, -1, -1):
            h ^= tail[j] << (8 * j)
        h = (h * m) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * m) & 0xFFFFFFFF
    h ^= h >> 15
    return h


# -- record batch v2 -------------------------------------------------------


CODEC_NONE, CODEC_SNAPPY = 0, 2               # attributes bits 0-2


def encode_record_batch(records: list[tuple[Optional[bytes], bytes]],
                        base_ts: Optional[int] = None,
                        codec: int = CODEC_NONE) -> bytes:
    """[(key, value)] → one record batch (magic 2). ``codec``
    compresses the records section (snappy = raw block format for
    magic-2 batches — no xerial framing, that is magic 0/1 only)."""
    base_ts = int(time.time() * 1000) if base_ts is None else base_ts
    recs = bytearray()
    for i, (key, value) in enumerate(records):
        body = bytearray()
        body += b"\x00"                        # attributes
        body += varint(0)                      # timestamp delta
        body += varint(i)                      # offset delta
        body += varint(-1 if key is None else len(key))
        if key is not None:
            body += key
        body += varint(len(value))
        body += value
        body += varint(0)                      # headers count
        recs += varint(len(body)) + body

    if codec == CODEC_SNAPPY:
        from emqx_tpu.utils.snappy import compress
        recs = bytearray(compress(bytes(recs)))
    elif codec != CODEC_NONE:
        raise KafkaError(f"unsupported codec {codec}")

    n = len(records)
    tail = bytearray()
    tail += struct.pack(">h", codec)           # attributes
    tail += struct.pack(">i", n - 1)           # last offset delta
    tail += struct.pack(">q", base_ts)         # first timestamp
    tail += struct.pack(">q", base_ts)         # max timestamp
    tail += struct.pack(">q", -1)              # producer id
    tail += struct.pack(">h", -1)              # producer epoch
    tail += struct.pack(">i", -1)              # base sequence
    tail += struct.pack(">i", n)
    tail += recs

    crc = crc32c(bytes(tail))
    batch = bytearray()
    batch += struct.pack(">q", 0)              # base offset
    batch += struct.pack(">i", len(tail) + 4 + 4 + 1)  # batch length
    batch += struct.pack(">i", -1)             # partition leader epoch
    batch += b"\x02"                           # magic
    batch += struct.pack(">I", crc)
    batch += tail
    return bytes(batch)


def decode_record_batch(data: bytes) -> list[tuple[Optional[bytes], bytes]]:
    """Validating decoder (MiniKafka + tests): checks magic and CRC32-C."""
    (_base, _ln, _epoch) = struct.unpack_from(">qii", data, 0)
    magic = data[16]
    if magic != 2:
        raise KafkaError(f"unsupported magic {magic}")
    (crc,) = struct.unpack_from(">I", data, 17)
    tail = data[21:]
    if crc32c(tail) != crc:
        raise KafkaError("record batch CRC mismatch")
    (attrs,) = struct.unpack_from(">h", tail, 0)
    (n,) = struct.unpack_from(">i", tail, 2 + 4 + 8 + 8 + 8 + 2 + 4)
    pos = 2 + 4 + 8 + 8 + 8 + 2 + 4 + 4
    codec = attrs & 0x07
    if codec == CODEC_SNAPPY:
        from emqx_tpu.utils.snappy import SnappyError, decompress
        try:
            tail = tail[:pos] + decompress(bytes(tail[pos:]))
        except SnappyError as e:
            raise KafkaError(f"bad snappy records section: {e}") from None
    elif codec != CODEC_NONE:
        raise KafkaError(f"unsupported codec {codec}")
    out = []
    for _ in range(n):
        _ln, pos = read_varint(tail, pos)
        pos += 1                               # attributes
        _td, pos = read_varint(tail, pos)
        _od, pos = read_varint(tail, pos)
        klen, pos = read_varint(tail, pos)
        key = None
        if klen >= 0:
            key = tail[pos:pos + klen]
            pos += klen
        vlen, pos = read_varint(tail, pos)
        value = tail[pos:pos + vlen]
        pos += vlen
        hn, pos = read_varint(tail, pos)
        for _h in range(hn):
            kl, pos = read_varint(tail, pos)
            pos += kl
            vl, pos = read_varint(tail, pos)
            pos += max(vl, 0)
        out.append((key, value))
    return out


# -- client ----------------------------------------------------------------

API_PRODUCE, API_METADATA = 0, 3


NOT_LEADER = 6


class _BrokerConn:
    def __init__(self, addr: tuple, timeout_s: float) -> None:
        self.sock = socket.create_connection(addr, timeout_s)
        self.sock.settimeout(timeout_s)
        self.buf = b""

    def exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("kafka closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class KafkaClient:
    """Produce-path client with per-broker connections: metadata names
    each partition's leader node, and produces go to THAT broker (a
    produce sent elsewhere answers NOT_LEADER_FOR_PARTITION — one
    metadata refresh + retry heals a moved leader, like wolff)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9092,
                 client_id: str = "emqx_tpu", timeout_s: float = 5.0,
                 acks: int = -1, compression: str = "none") -> None:
        self.addr = (host, port)               # bootstrap
        self.client_id = client_id
        self.timeout_s = timeout_s
        self.acks = acks
        try:
            self.codec = {"none": CODEC_NONE,
                          "snappy": CODEC_SNAPPY}[compression]
        except KeyError:
            raise KafkaError(
                f"unsupported compression {compression!r}") from None
        self._conns: dict[Optional[int], _BrokerConn] = {}
        self._brokers: dict[int, tuple] = {}   # node id → (host, port)
        self._leaders: dict[tuple, int] = {}   # (topic, part) → node id
        self._nparts: dict[str, int] = {}      # topic → partition count
        self._corr = 0
        self._rr = 0
        self._lock = threading.Lock()

    # wire helpers ----------------------------------------------------------

    def _conn(self, node: Optional[int]) -> _BrokerConn:
        conn = self._conns.get(node)
        if conn is None:
            addr = self._brokers.get(node, self.addr)
            conn = self._conns[node] = _BrokerConn(addr, self.timeout_s)
        return conn

    def _drop_conn(self, node: Optional[int]) -> None:
        conn = self._conns.pop(node, None)
        if conn is not None:
            conn.close()

    def _call(self, api: int, version: int, body: bytes,
              node: Optional[int] = None) -> bytes:
        for attempt in (0, 1):
            try:
                conn = self._conn(node)
                self._corr += 1
                head = struct.pack(">hhi", api, version, self._corr) \
                    + _str16(self.client_id)
                msg = head + body
                conn.sock.sendall(struct.pack(">i", len(msg)) + msg)
                (ln,) = struct.unpack(">i", conn.exact(4))
                resp = conn.exact(ln)
                (corr,) = struct.unpack_from(">i", resp, 0)
                if corr != self._corr:
                    raise KafkaError(f"correlation mismatch {corr}")
                return resp[4:]
            except (OSError, ConnectionError):
                self._drop_conn(node)
                if attempt:
                    raise
        raise ConnectionError("unreachable")

    # metadata --------------------------------------------------------------

    def _refresh_metadata(self, topic: str) -> None:
        body = struct.pack(">i", 1) + _str16(topic)
        resp = self._call(API_METADATA, 1, body)   # bootstrap conn
        pos = 0
        (nb,) = struct.unpack_from(">i", resp, pos)
        pos += 4
        for _ in range(nb):
            (node,) = struct.unpack_from(">i", resp, pos)
            pos += 4
            host, pos = _rd_str16(resp, pos)
            (port,) = struct.unpack_from(">i", resp, pos)
            pos += 4
            _rack, pos = _rd_str16(resp, pos)
            self._brokers[node] = (host, port)
        pos += 4                               # controller id
        (nt,) = struct.unpack_from(">i", resp, pos)
        pos += 4
        for _ in range(nt):
            (terr,) = struct.unpack_from(">h", resp, pos)
            pos += 2
            tname, pos = _rd_str16(resp, pos)
            pos += 1                           # is_internal
            (np_,) = struct.unpack_from(">i", resp, pos)
            pos += 4
            for _p in range(np_):
                (_perr, pid, leader) = struct.unpack_from(">hii", resp, pos)
                pos += 10
                (nr,) = struct.unpack_from(">i", resp, pos)
                pos += 4 + 4 * nr
                (ni,) = struct.unpack_from(">i", resp, pos)
                pos += 4 + 4 * ni
                if tname == topic:
                    self._leaders[(topic, pid)] = leader
            if tname == topic:
                if terr:
                    raise KafkaError(f"metadata error {terr} for {topic}")
                self._nparts[topic] = np_
        if not self._nparts.get(topic):
            raise KafkaError(f"unknown topic {topic}")

    def partitions(self, topic: str) -> int:
        with self._lock:
            return self._partitions_locked(topic)

    def _partitions_locked(self, topic: str) -> int:
        if topic not in self._nparts:
            self._refresh_metadata(topic)
        return self._nparts[topic]

    def metadata_probe(self) -> None:
        """Liveness probe on the bootstrap connection (locked — shares
        sockets with produce)."""
        with self._lock:
            self._call(API_METADATA, 1, struct.pack(">i", 0))

    def _partition_for(self, topic: str, key: Optional[bytes]) -> int:
        n = self._partitions_locked(topic)
        if key is None:
            self._rr += 1
            return self._rr % n
        return (murmur2(key) & 0x7FFFFFFF) % n

    # produce ---------------------------------------------------------------

    def produce(self, topic: str, value: bytes,
                key: Optional[bytes] = None,
                partition: Optional[int] = None) -> int:
        """Produce one record; returns the assigned base offset."""
        (off,) = self.produce_many(
            topic, [(key, value)], partition=partition)
        return off

    def produce_many(self, topic: str,
                     records: list[tuple[Optional[bytes], bytes]],
                     partition: Optional[int] = None) -> list[int]:
        """Produce a list of (key, value) records grouped per partition —
        ONE request per involved partition (the wolff batching shape).
        Returns each record's assigned offset, input order."""
        with self._lock:
            groups: dict[int, list[int]] = {}
            for i, (key, _v) in enumerate(records):
                pid = (partition if partition is not None
                       else self._partition_for(topic, key))
                groups.setdefault(pid, []).append(i)
            offsets = [0] * len(records)
            for pid, idxs in groups.items():
                base = self._produce_batch_locked(
                    topic, pid, [records[i] for i in idxs])
                for j, i in enumerate(idxs):
                    offsets[i] = base + j
            return offsets

    def _produce_batch_locked(self, topic: str, partition: int,
                              records: list) -> int:
        batch = encode_record_batch(records, codec=self.codec)
        body = _str16(None)                            # transactional id
        body += struct.pack(">hi", self.acks, 10_000)  # acks, timeout
        body += struct.pack(">i", 1) + _str16(topic)
        body += struct.pack(">i", 1)
        body += struct.pack(">i", partition) + _bytes32(batch)
        for attempt in (0, 1):
            node = self._leaders.get((topic, partition))
            resp = self._call(API_PRODUCE, 3, body, node=node)
            try:
                return self._parse_produce(resp, topic)
            except KafkaError as e:
                if f"error {NOT_LEADER}" in str(e) and attempt == 0:
                    # leader moved: refresh the view and retry once
                    self._refresh_metadata(topic)
                    continue
                raise
        raise KafkaError("unreachable")

    @staticmethod
    def _parse_produce(resp: bytes, topic: str) -> int:
        pos = 0
        (nt,) = struct.unpack_from(">i", resp, pos)
        pos += 4
        offset = -1
        for _ in range(nt):
            _t, pos = _rd_str16(resp, pos)
            (np_,) = struct.unpack_from(">i", resp, pos)
            pos += 4
            for _p in range(np_):
                (pid, err, off) = struct.unpack_from(">ihq", resp, pos)
                pos += 4 + 2 + 8
                pos += 8                               # log append time
                if err:
                    raise KafkaError(
                        f"produce error {err} on {topic}[{pid}]")
                offset = off
        return offset

    def close(self) -> None:
        for node in list(self._conns):
            self._drop_conn(node)
        self._nparts.clear()
        self._leaders.clear()


class KafkaConnector(Resource):
    def __init__(self, **kw: Any) -> None:
        self.client = KafkaClient(**kw)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(f"kafka {self.client.addr} unreachable")

    def on_stop(self) -> None:
        self.client.close()

    @staticmethod
    def _kv(req: dict) -> tuple[Optional[bytes], bytes]:
        key = req.get("key")
        if isinstance(key, str):
            key = key.encode()
        value = req.get("value", "")
        if isinstance(value, bytes):
            pass
        elif isinstance(value, str):
            value = value.encode()
        else:
            import json as _json
            value = _json.dumps(value).encode()   # dict/list/number columns
        return key, value

    def on_query(self, req: Any) -> Any:
        try:
            key, value = self._kv(req)
            return self.client.produce(req["topic"], value, key=key)
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_batch_query(self, reqs: list) -> list:
        """One Produce per (topic, partition) for the whole flushed
        batch (the wolff batching shape), not N round trips."""
        try:
            by_topic: dict[str, list[int]] = {}
            for i, r in enumerate(reqs):
                by_topic.setdefault(r["topic"], []).append(i)
            out = [None] * len(reqs)
            for topic, idxs in by_topic.items():
                offs = self.client.produce_many(
                    topic, [self._kv(reqs[i]) for i in idxs])
                for j, i in enumerate(idxs):
                    out[i] = offs[j]
            return out
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_health_check(self) -> bool:
        try:
            # a Metadata round trip is the liveness probe (wolff does the
            # same via partition-count refresh); shares the produce lock
            self.client.metadata_probe()
            return True
        except (OSError, ConnectionError, KafkaError):
            return False


# ---------------------------------------------------------------------------
# in-repo miniature broker (test backend)


class MiniKafka:
    """Metadata v1 + Produce v3 over real framing; records stored per
    topic-partition with CRC-validated batches."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Optional[dict[str, int]] = None,
                 node_id: int = 0,
                 redirect_to: Optional["MiniKafka"] = None) -> None:
        self.topics: dict[str, int] = dict(topics or {})   # name → #parts
        self.records: dict[tuple[str, int], list] = {}
        self.node_id = node_id
        # multi-broker simulation: when set, metadata lists BOTH brokers
        # and names the other one leader of every partition; a produce
        # here answers NOT_LEADER_FOR_PARTITION (tests the client's
        # leader routing + refresh-and-retry)
        self.redirect_to = redirect_to
        mini = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    mini._session(self.request)
                except (ConnectionError, OSError):
                    pass

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def _session(self, sock: socket.socket) -> None:
        buf = b""

        def exact(n: int) -> bytes:
            nonlocal buf
            while len(buf) < n:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            out, rest = buf[:n], buf[n:]
            buf = rest
            return out

        while True:
            (ln,) = struct.unpack(">i", exact(4))
            req = exact(ln)
            (api, ver, corr) = struct.unpack_from(">hhi", req, 0)
            pos = 8
            _cid, pos = _rd_str16(req, pos)
            try:
                if api == API_METADATA:
                    body = self._metadata(req, pos)
                elif api == API_PRODUCE:
                    body = self._produce(req, pos)
                else:
                    continue                      # unsupported api: drop
            except Exception:   # noqa: BLE001 — malformed request: drop conn
                return
            resp = struct.pack(">i", corr) + body
            sock.sendall(struct.pack(">i", len(resp)) + resp)

    def _metadata(self, req: bytes, pos: int) -> bytes:
        (nt,) = struct.unpack_from(">i", req, pos)
        pos += 4
        wanted = []
        for _ in range(nt):
            t, pos = _rd_str16(req, pos)
            wanted.append(t)
        if nt <= 0:
            wanted = list(self.topics)
        brokers = [(self.node_id, self.host, self.port)]
        leader = self.node_id
        if self.redirect_to is not None:
            other = self.redirect_to
            brokers.append((other.node_id, other.host, other.port))
            leader = other.node_id
        out = struct.pack(">i", len(brokers))
        for nid, h, p in brokers:
            out += struct.pack(">i", nid) + _str16(h) \
                + struct.pack(">i", p) + _str16(None)
        out += struct.pack(">i", self.node_id)            # controller id
        out += struct.pack(">i", len(wanted))
        for t in wanted:
            nparts = self.topics.get(t)
            if nparts is None:
                # auto-create like a dev broker (topic with 1 partition)
                nparts = self.topics[t] = 1
            out += struct.pack(">h", 0) + _str16(t) + b"\x00"
            out += struct.pack(">i", nparts)
            for p in range(nparts):
                out += struct.pack(">hii", 0, p, leader)  # err, id, leader
                out += struct.pack(">ii", 1, leader)      # replicas
                out += struct.pack(">ii", 1, leader)      # isr
        return out

    def _produce(self, req: bytes, pos: int) -> bytes:
        _txid, pos = _rd_str16(req, pos)
        (_acks, _timeout) = struct.unpack_from(">hi", req, pos)
        pos += 6
        (nt,) = struct.unpack_from(">i", req, pos)
        pos += 4
        out_topics = []
        for _ in range(nt):
            topic, pos = _rd_str16(req, pos)
            (np_,) = struct.unpack_from(">i", req, pos)
            pos += 4
            parts = []
            for _p in range(np_):
                (pid,) = struct.unpack_from(">i", req, pos)
                pos += 4
                (blen,) = struct.unpack_from(">i", req, pos)
                pos += 4
                batch = req[pos:pos + blen]
                pos += blen
                if self.redirect_to is not None:
                    parts.append((pid, 6, -1))     # NOT_LEADER here
                    continue
                records = decode_record_batch(batch)   # CRC enforced
                store = self.records.setdefault((topic, pid), [])
                base = len(store)
                store.extend(records)
                parts.append((pid, 0, base))
            out_topics.append((topic, parts))
        out = struct.pack(">i", len(out_topics))
        for topic, parts in out_topics:
            out += _str16(topic) + struct.pack(">i", len(parts))
            for pid, err, base in parts:
                out += struct.pack(">ihqq", pid, err, base, -1)
        out += struct.pack(">i", 0)                       # throttle ms
        return out

    def start(self) -> "MiniKafka":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="mini-kafka")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
