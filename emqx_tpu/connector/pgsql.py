"""PostgreSQL connector — the ``emqx_connector_pgsql`` (epgsql) analogue.

A from-scratch v3 wire-protocol client (no external deps), simple-query
flow only: StartupMessage → Authentication (trust, cleartext or MD5) →
ReadyForQuery; ``Query`` messages return text-format rows parsed from
RowDescription/DataRow/CommandComplete. The reference uses prepared
statements (epgsql equery); here placeholders substitute client-side
with literal quoting — same observable queries, no second round trip.

``MiniPg`` is the in-repo miniature backend for tests (SURVEY §4.5:
real wire protocols, not mocks): startup + cleartext auth + a tiny SQL
engine over dict tables (SELECT ... WHERE col = 'v' [AND ...] /
INSERT INTO ... VALUES).
"""

from __future__ import annotations

import hashlib
import re
import socket
import socketserver
import struct
import threading
from typing import Any, Optional

from emqx_tpu.resource.resource import Resource


class PgError(Exception):
    pass


def quote_literal(v: Any) -> str:
    """Escape a value as a SQL literal (client-side parameterization)."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, bytes):
        v = v.decode("utf-8", "replace")
    return "'" + str(v).replace("'", "''").replace("\\", "\\\\") + "'"


def render_sql(template: str, binds: dict) -> str:
    """``${username}``-style placeholder substitution with quoting."""
    def sub(m):
        return quote_literal(binds.get(m.group(1), ""))
    return re.sub(r"\$\{(\w+)\}", sub, template)


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


class PgClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 5432,
                 user: str = "postgres", password: str = "",
                 database: str = "mqtt", timeout_s: float = 5.0) -> None:
        self.addr = (host, port)
        self.user, self.password, self.database = user, password, database
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = threading.Lock()

    # -- wire ----------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("pg closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_msg(self) -> tuple[bytes, bytes]:
        head = self._read_exact(5)
        tag = head[:1]
        (ln,) = struct.unpack(">I", head[1:5])
        return tag, self._read_exact(ln - 4)

    def _connect(self) -> None:
        self._sock = socket.create_connection(self.addr, self.timeout_s)
        self._sock.settimeout(self.timeout_s)
        self._buf = b""
        params = (f"user\0{self.user}\0database\0{self.database}\0\0"
                  .encode())
        startup = struct.pack(">I", 196608) + params      # protocol 3.0
        self._sock.sendall(struct.pack(">I", len(startup) + 4) + startup)
        while True:
            tag, body = self._read_msg()
            if tag == b"R":
                (kind,) = struct.unpack(">I", body[:4])
                if kind == 0:
                    continue                               # AuthenticationOk
                if kind == 3:                              # cleartext
                    self._sock.sendall(
                        _msg(b"p", self.password.encode() + b"\0"))
                elif kind == 5:                            # MD5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        (self.password + self.user).encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._sock.sendall(
                        _msg(b"p", b"md5" + outer.encode() + b"\0"))
                else:
                    raise PgError(f"unsupported auth method {kind}")
            elif tag in (b"S", b"K", b"N"):
                continue            # ParameterStatus/BackendKeyData/Notice
            elif tag == b"Z":
                return              # ReadyForQuery
            elif tag == b"E":
                raise PgError(self._err_text(body))
            else:
                raise PgError(f"unexpected startup message {tag!r}")

    @staticmethod
    def _err_text(body: bytes) -> str:
        fields = {}
        for part in body.split(b"\0"):
            if part:
                fields[chr(part[0])] = part[1:].decode("utf-8", "replace")
        return fields.get("M", "error")

    # -- API -----------------------------------------------------------------

    def query(self, sql: str) -> tuple[list[str], list[list]]:
        """Simple query → (column names, rows of str|None). Retries once
        on a stale pooled connection before the request is written."""
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._connect()
                    self._sock.sendall(_msg(b"Q", sql.encode() + b"\0"))
                    break
                except (OSError, ConnectionError):
                    self.close()
                    if attempt:
                        raise
            cols: list[str] = []
            rows: list[list] = []
            err: Optional[str] = None
            try:
                while True:
                    tag, body = self._read_msg()
                    if tag == b"T":
                        cols = self._parse_cols(body)
                    elif tag == b"D":
                        rows.append(self._parse_row(body))
                    elif tag == b"E":
                        err = self._err_text(body)
                    elif tag in (b"C", b"N", b"S"):
                        continue
                    elif tag == b"Z":
                        break
            except (OSError, ConnectionError):
                self.close()
                raise
            if err is not None:
                raise PgError(err)
            return cols, rows

    @staticmethod
    def _parse_cols(body: bytes) -> list[str]:
        (n,) = struct.unpack(">H", body[:2])
        cols, pos = [], 2
        for _ in range(n):
            end = body.index(b"\0", pos)
            cols.append(body[pos:end].decode())
            pos = end + 1 + 18          # skip the fixed field descriptor
        return cols

    @staticmethod
    def _parse_row(body: bytes) -> list:
        (n,) = struct.unpack(">H", body[:2])
        out, pos = [], 2
        for _ in range(n):
            (ln,) = struct.unpack(">i", body[pos:pos + 4])
            pos += 4
            if ln == -1:
                out.append(None)
            else:
                out.append(body[pos:pos + ln].decode("utf-8", "replace"))
                pos += ln
        return out

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._buf = b""


class PgConnector(Resource):
    """Resource wrapper: query templates with ${placeholders}
    (emqx_connector_pgsql.erl's prepared-statement surface)."""

    def __init__(self, **kw: Any) -> None:
        self.client = PgClient(**kw)

    def on_start(self, conf: dict) -> None:
        if not self.on_health_check():
            raise ConnectionError(f"pgsql {self.client.addr} unreachable")

    def on_stop(self) -> None:
        self.client.close()

    def on_query(self, req: Any) -> Any:
        sql = req["sql"] if isinstance(req, dict) else str(req)
        binds = req.get("binds", {}) if isinstance(req, dict) else {}
        try:
            return self.client.query(render_sql(sql, binds))
        except (OSError, ConnectionError) as e:
            raise ConnectionError(str(e)) from None

    def on_health_check(self) -> bool:
        try:
            self.client.query("SELECT 1")
            return True
        except (OSError, ConnectionError, PgError):
            return False


# ---------------------------------------------------------------------------
# in-repo miniature server (test backend)


_SELECT_RE = re.compile(
    r"^\s*SELECT\s+(?P<cols>.+?)\s+FROM\s+(?P<table>\w+)"
    r"(?:\s+WHERE\s+(?P<where>.+?))?\s*;?\s*$", re.I | re.S)
_INSERT_RE = re.compile(
    r"^\s*INSERT\s+INTO\s+(?P<table>\w+)\s*\((?P<cols>[^)]*)\)\s*"
    r"VALUES\s*\((?P<vals>.*)\)\s*;?\s*$", re.I | re.S)
_COND_RE = re.compile(r"(\w+)\s*=\s*('(?:[^']|'')*'|\d+)")


def _unquote(tok: str) -> str:
    tok = tok.strip()
    if tok.startswith("'") and tok.endswith("'"):
        return tok[1:-1].replace("''", "'")
    return tok


class MiniPg:
    """Startup + cleartext-auth + simple-query subset over dict tables:
    ``tables = {name: [ {col: val} ]}``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 password: Optional[str] = None) -> None:
        self.tables: dict[str, list[dict]] = {}
        self.password = password
        mini = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    mini._session(self.request)
                except (ConnectionError, OSError):
                    pass

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # -- session -------------------------------------------------------------

    def _session(self, sock: socket.socket) -> None:
        buf = b""

        def read_exact(n: int) -> bytes:
            nonlocal buf
            while len(buf) < n:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            out, rest = buf[:n], buf[n:]
            buf = rest
            return out

        (ln,) = struct.unpack(">I", read_exact(4))
        startup = read_exact(ln - 4)
        (proto,) = struct.unpack(">I", startup[:4])
        if proto == 80877103:          # SSLRequest → refuse, retry plain
            sock.sendall(b"N")
            (ln,) = struct.unpack(">I", read_exact(4))
            startup = read_exact(ln - 4)
        if self.password is not None:
            sock.sendall(_msg(b"R", struct.pack(">I", 3)))   # cleartext
            tag = read_exact(1)
            (ln,) = struct.unpack(">I", read_exact(4))
            body = read_exact(ln - 4)
            if tag != b"p" or body.rstrip(b"\0").decode() != self.password:
                sock.sendall(_msg(b"E", b"SERROR\0C28P01\0"
                                  b"Mpassword authentication failed\0\0"))
                return
        sock.sendall(_msg(b"R", struct.pack(">I", 0)))       # Ok
        sock.sendall(_msg(b"Z", b"I"))
        while True:
            tag = read_exact(1)
            (ln,) = struct.unpack(">I", read_exact(4))
            body = read_exact(ln - 4)
            if tag == b"X":            # Terminate
                return
            if tag != b"Q":
                sock.sendall(_msg(b"E", b"SERROR\0C0A000\0"
                                  b"Msimple query only\0\0"))
                sock.sendall(_msg(b"Z", b"I"))
                continue
            sql = body.rstrip(b"\0").decode("utf-8", "replace")
            try:
                sock.sendall(self._run(sql))
            except Exception as e:     # noqa: BLE001 — surfaced as pg error
                sock.sendall(_msg(
                    b"E", b"SERROR\0C42601\0M" + str(e).encode() + b"\0\0"))
            sock.sendall(_msg(b"Z", b"I"))

    # -- the tiny SQL engine -------------------------------------------------

    def _run(self, sql: str) -> bytes:
        if sql.strip().upper().startswith("SELECT 1"):
            return self._result(["?column?"], [["1"]])
        m = _SELECT_RE.match(sql)
        if m:
            table = self.tables.get(m.group("table").lower(), [])
            conds = []
            if m.group("where"):
                conds = [(c, _unquote(v))
                         for c, v in _COND_RE.findall(m.group("where"))]
            cols = [c.strip() for c in m.group("cols").split(",")]
            rows = []
            for rec in table:
                if all(str(rec.get(c, "")) == v for c, v in conds):
                    if cols == ["*"]:
                        cols = list(rec)
                    rows.append([None if rec.get(c) is None
                                 else str(rec.get(c, "")) for c in cols])
            return self._result(cols if cols != ["*"] else [], rows)
        m = _INSERT_RE.match(sql)
        if m:
            cols = [c.strip() for c in m.group("cols").split(",")]
            vals = [_unquote(v) for v in
                    re.findall(r"'(?:[^']|'')*'|[^,]+", m.group("vals"))]
            self.tables.setdefault(m.group("table").lower(), []).append(
                dict(zip(cols, vals)))
            return _msg(b"C", b"INSERT 0 1\0")
        raise PgError(f"unsupported SQL: {sql[:60]}")

    @staticmethod
    def _result(cols: list[str], rows: list[list]) -> bytes:
        out = []
        desc = struct.pack(">H", len(cols))
        for c in cols:
            desc += c.encode() + b"\0" + struct.pack(
                ">IHIHiH", 0, 0, 25, -1 & 0xFFFF, -1, 0)
        out.append(_msg(b"T", desc))
        for row in rows:
            body = struct.pack(">H", len(row))
            for v in row:
                if v is None:
                    body += struct.pack(">i", -1)
                else:
                    b = str(v).encode()
                    body += struct.pack(">i", len(b)) + b
            out.append(_msg(b"D", body))
        out.append(_msg(b"C", f"SELECT {len(rows)}\0".encode()))
        return b"".join(out)

    def start(self) -> "MiniPg":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="mini-pg")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
