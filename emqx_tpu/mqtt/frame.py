"""MQTT wire codec: incremental parser + serializer.

Parity with ``apps/emqx/src/emqx_frame.erl``: the varint remaining-length
state machine (emqx_frame.erl:163-217), body parsing (:236+), and the
serializer, for protocol versions 3.1/3.1.1/5.0. The parser is
*incremental*: feed arbitrary byte chunks, get complete packets out plus a
resumable state — the contract the connection host needs for
``{active,N}``-style socket batching.

(The production ingest path implements this same format in C++
(emqx_tpu/native); this module is the reference implementation and the
one the Python broker stack uses.)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Optional

from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.packet import FrameError

MAX_REMAINING_LEN = 0xFFFFFFF  # 268435455, 4-byte varint cap


# --------------------------------------------------------------------------
# primitive readers (over a memoryview + offset)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def remaining(self) -> int:
        return len(self.buf) - self.pos

    def u8(self) -> int:
        if self.remaining() < 1:
            raise FrameError("truncated u8")
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        if self.remaining() < 2:
            raise FrameError("truncated u16")
        v = int.from_bytes(self.buf[self.pos : self.pos + 2], "big")
        self.pos += 2
        return v

    def u32(self) -> int:
        if self.remaining() < 4:
            raise FrameError("truncated u32")
        v = int.from_bytes(self.buf[self.pos : self.pos + 4], "big")
        self.pos += 4
        return v

    def varint(self) -> int:
        mult, val = 1, 0
        for _ in range(4):
            b = self.u8()
            val += (b & 0x7F) * mult
            if not b & 0x80:
                return val
            mult *= 128
        raise FrameError("varint too long")

    def bin(self) -> bytes:
        n = self.u16()
        if self.remaining() < n:
            raise FrameError("truncated binary")
        v = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return v

    def utf8(self) -> str:
        try:
            return self.bin().decode("utf-8")
        except UnicodeDecodeError as e:
            raise FrameError(f"invalid utf8: {e}") from None

    def rest(self) -> bytes:
        v = bytes(self.buf[self.pos :])
        self.pos = len(self.buf)
        return v


def _parse_properties(r: _Reader) -> dict[str, Any]:
    n = r.varint()
    end = r.pos + n
    props: dict[str, Any] = {}
    while r.pos < end:
        pid = r.varint()
        spec = P.PROPERTIES.get(pid)
        if spec is None:
            raise FrameError(f"unknown property id 0x{pid:02x}")
        name, ty = spec
        if ty == "byte":
            val = r.u8()
        elif ty == "two":
            val = r.u16()
        elif ty == "four":
            val = r.u32()
        elif ty == "varint":
            val = r.varint()
        elif ty == "utf8":
            val = r.utf8()
        elif ty == "binary":
            val = r.bin()
        else:  # utf8pair
            val = (r.utf8(), r.utf8())
        if name == "User-Property":
            props.setdefault("User-Property", []).append(val)
        elif name == "Subscription-Identifier":
            props.setdefault("Subscription-Identifier", []).append(val)
        else:
            if name in props:
                raise FrameError(f"duplicate property {name}", P.RC_PROTOCOL_ERROR)
            props[name] = val
    if r.pos != end:
        raise FrameError("property length mismatch")
    return props


# --------------------------------------------------------------------------
# incremental parser


@dataclass(frozen=True)
class ParseState:
    """Resumable state between socket reads (emqx_frame:initial_parse_state).

    phase: 0 = awaiting fixed header byte; 1 = reading remaining-length
    varint; 2 = accumulating body.
    """

    version: int = P.MQTT_V4
    max_size: int = MAX_REMAINING_LEN
    phase: int = 0
    header: int = 0
    len_value: int = 0
    len_mult: int = 1
    need: int = 0
    acc: bytes = b""


class Parser:
    """Feed chunks → complete packets (list) + updated state."""

    def __init__(self, version: int = P.MQTT_V4, max_size: int = MAX_REMAINING_LEN):
        self.state = ParseState(version=version, max_size=max_size)

    def set_version(self, version: int) -> None:
        self.state = replace(self.state, version=version)

    def feed(self, data: bytes) -> list[P.Packet]:
        out: list[P.Packet] = []
        st = self.state
        buf = st.acc + data if st.phase == 2 else data
        # re-enter mid-varint/header phases with the raw bytes
        if st.phase != 2 and st.acc:
            buf = st.acc + data
            st = replace(st, acc=b"")
        pos = 0
        phase, header = st.phase, st.header
        len_value, len_mult, need = st.len_value, st.len_mult, st.need
        n = len(buf)
        while True:
            if phase == 0:
                if pos >= n:
                    st = replace(
                        st, phase=0, acc=b"", len_value=0, len_mult=1, need=0
                    )
                    break
                header = buf[pos]
                pos += 1
                if header >> 4 not in P.TYPE_NAMES:
                    raise FrameError(f"bad packet type {header >> 4}")
                phase, len_value, len_mult = 1, 0, 1
            elif phase == 1:
                if pos >= n:
                    st = replace(
                        st,
                        phase=1,
                        header=header,
                        len_value=len_value,
                        len_mult=len_mult,
                        acc=b"",
                    )
                    break
                b = buf[pos]
                pos += 1
                len_value += (b & 0x7F) * len_mult
                if b & 0x80:
                    len_mult *= 128
                    if len_mult > 128**3:
                        raise FrameError("remaining length varint too long")
                else:
                    if len_value > st.max_size:
                        raise FrameError(
                            "packet too large", P.RC_PACKET_TOO_LARGE
                        )
                    phase, need = 2, len_value
            else:  # phase == 2
                avail = n - pos
                if avail < need:
                    st = replace(
                        st,
                        phase=2,
                        header=header,
                        need=need,
                        acc=bytes(buf[pos:]),
                    )
                    break
                body = bytes(buf[pos : pos + need])
                pos += need
                out.append(_parse_packet(header, body, st.version))
                phase = 0
        self.state = st
        return out


def _parse_packet(header: int, body: bytes, ver: int) -> P.Packet:
    ptype = header >> 4
    flags = header & 0x0F
    r = _Reader(body)
    if ptype == P.PUBLISH:
        dup = bool(flags & 0x08)
        qos = (flags >> 1) & 0x03
        retain = bool(flags & 0x01)
        if qos == 3:
            raise FrameError("bad publish qos")
        topic = r.utf8()
        pid = r.u16() if qos > 0 else None
        props = _parse_properties(r) if ver == P.MQTT_V5 else {}
        return P.Publish(
            topic=topic, payload=r.rest(), qos=qos, retain=retain,
            dup=dup, packet_id=pid, properties=props,
        )
    if ptype == P.CONNECT:
        proto_name = r.utf8()
        proto_ver = r.u8()
        if proto_name not in ("MQTT", "MQIsdp"):
            raise FrameError(
                "bad protocol name", P.RC_UNSUPPORTED_PROTOCOL_VERSION
            )
        cf = r.u8()
        if cf & 0x01:
            raise FrameError("connect reserved flag set", P.RC_PROTOCOL_ERROR)
        clean_start = bool(cf & 0x02)
        will_flag = bool(cf & 0x04)
        will_qos = (cf >> 3) & 0x03
        will_retain = bool(cf & 0x20)
        has_password = bool(cf & 0x40)
        has_username = bool(cf & 0x80)
        keepalive = r.u16()
        props = _parse_properties(r) if proto_ver == P.MQTT_V5 else {}
        clientid = r.utf8()
        will_props: dict[str, Any] = {}
        will_topic = will_payload = None
        if will_flag:
            if proto_ver == P.MQTT_V5:
                will_props = _parse_properties(r)
            will_topic = r.utf8()
            will_payload = r.bin()
        username = r.utf8() if has_username else None
        password = r.bin() if has_password else None
        return P.Connect(
            proto_name=proto_name, proto_ver=proto_ver,
            clean_start=clean_start, keepalive=keepalive, clientid=clientid,
            username=username, password=password, will_flag=will_flag,
            will_qos=will_qos, will_retain=will_retain,
            will_topic=will_topic, will_payload=will_payload,
            will_props=will_props, properties=props,
        )
    if ptype == P.CONNACK:
        ack = r.u8()
        rc = r.u8()
        props = _parse_properties(r) if ver == P.MQTT_V5 else {}
        return P.Connack(
            session_present=bool(ack & 0x01), reason_code=rc, properties=props
        )
    if ptype in (P.PUBACK, P.PUBREC, P.PUBREL, P.PUBCOMP):
        if ptype == P.PUBREL and flags != 0x02:
            raise FrameError("bad pubrel flags")
        pid = r.u16()
        rc, props = P.RC_SUCCESS, {}
        if ver == P.MQTT_V5 and r.remaining():
            rc = r.u8()
            if r.remaining():
                props = _parse_properties(r)
        cls = {P.PUBACK: P.PubAck, P.PUBREC: P.PubRec,
               P.PUBREL: P.PubRel, P.PUBCOMP: P.PubComp}[ptype]
        return cls(packet_id=pid, reason_code=rc, properties=props)
    if ptype == P.SUBSCRIBE:
        if flags != 0x02:
            raise FrameError("bad subscribe flags")
        pid = r.u16()
        props = _parse_properties(r) if ver == P.MQTT_V5 else {}
        tfs: list[tuple[str, dict[str, int]]] = []
        while r.remaining():
            tf = r.utf8()
            opts = r.u8()
            if opts & 0xC0:
                raise FrameError("subscribe reserved bits", P.RC_PROTOCOL_ERROR)
            tfs.append((tf, {
                "qos": opts & 0x03,
                "nl": (opts >> 2) & 0x01,
                "rap": (opts >> 3) & 0x01,
                "rh": (opts >> 4) & 0x03,
            }))
        if not tfs:
            raise FrameError("empty subscribe", P.RC_PROTOCOL_ERROR)
        return P.Subscribe(packet_id=pid, topic_filters=tfs, properties=props)
    if ptype == P.SUBACK:
        pid = r.u16()
        props = _parse_properties(r) if ver == P.MQTT_V5 else {}
        return P.SubAck(
            packet_id=pid, reason_codes=list(r.rest()), properties=props
        )
    if ptype == P.UNSUBSCRIBE:
        if flags != 0x02:
            raise FrameError("bad unsubscribe flags")
        pid = r.u16()
        props = _parse_properties(r) if ver == P.MQTT_V5 else {}
        tfs2: list[str] = []
        while r.remaining():
            tfs2.append(r.utf8())
        if not tfs2:
            raise FrameError("empty unsubscribe", P.RC_PROTOCOL_ERROR)
        return P.Unsubscribe(packet_id=pid, topic_filters=tfs2, properties=props)
    if ptype == P.UNSUBACK:
        pid = r.u16()
        props = _parse_properties(r) if ver == P.MQTT_V5 else {}
        return P.UnsubAck(
            packet_id=pid, reason_codes=list(r.rest()), properties=props
        )
    if ptype == P.PINGREQ:
        return P.PingReq()
    if ptype == P.PINGRESP:
        return P.PingResp()
    if ptype == P.DISCONNECT:
        rc, props = P.RC_SUCCESS, {}
        if ver == P.MQTT_V5 and r.remaining():
            rc = r.u8()
            if r.remaining():
                props = _parse_properties(r)
        return P.Disconnect(reason_code=rc, properties=props)
    if ptype == P.AUTH:
        rc, props = P.RC_SUCCESS, {}
        if r.remaining():
            rc = r.u8()
            if r.remaining():
                props = _parse_properties(r)
        return P.Auth(reason_code=rc, properties=props)
    raise FrameError(f"unhandled packet type {ptype}")


def parse_one(frame: bytes, version: int = P.MQTT_V4) -> P.Packet:
    """Parse one *complete* wire frame (as emitted by the native framer,
    header byte + remaining-length varint + body) into a packet."""
    header = frame[0]
    pos = 1
    while frame[pos] & 0x80:
        pos += 1
    return _parse_packet(header, frame[pos + 1:], version)


# --------------------------------------------------------------------------
# serializer


def _w_varint(n: int) -> bytes:
    if n > MAX_REMAINING_LEN:
        raise FrameError("varint overflow")
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | 0x80 if n else b)
        if not n:
            return bytes(out)


def _w_bin(b: bytes) -> bytes:
    return len(b).to_bytes(2, "big") + b


def _w_utf8(s: str) -> bytes:
    return _w_bin(s.encode("utf-8"))


def _w_properties(props: dict[str, Any]) -> bytes:
    body = bytearray()
    for name, val in props.items():
        pid, ty = P.PROP_IDS[name]
        vals = val if name in ("User-Property", "Subscription-Identifier") else [val]
        if not isinstance(vals, list):
            vals = [vals]
        for v in vals:
            body += _w_varint(pid)
            if ty == "byte":
                body.append(v)
            elif ty == "two":
                body += int(v).to_bytes(2, "big")
            elif ty == "four":
                body += int(v).to_bytes(4, "big")
            elif ty == "varint":
                body += _w_varint(v)
            elif ty == "utf8":
                body += _w_utf8(v)
            elif ty == "binary":
                body += _w_bin(v)
            else:
                body += _w_utf8(v[0]) + _w_utf8(v[1])
    return _w_varint(len(body)) + bytes(body)


def serialize(pkt: P.Packet, version: int = P.MQTT_V4) -> bytes:
    v5 = version == P.MQTT_V5
    t = pkt.type
    flags = 0
    body = bytearray()
    if t == P.PUBLISH:
        flags = (pkt.dup << 3) | (pkt.qos << 1) | int(pkt.retain)
        body += _w_utf8(pkt.topic)
        if pkt.qos > 0:
            if pkt.packet_id is None:
                raise FrameError("publish qos>0 needs packet_id")
            body += pkt.packet_id.to_bytes(2, "big")
        if v5:
            body += _w_properties(pkt.properties)
        body += pkt.payload
    elif t == P.CONNECT:
        body += _w_utf8(pkt.proto_name)
        body.append(pkt.proto_ver)
        cf = (
            (bool(pkt.username) << 7) | (pkt.password is not None) << 6
            | (pkt.will_retain << 5) | (pkt.will_qos << 3)
            | (pkt.will_flag << 2) | (pkt.clean_start << 1)
        )
        body.append(cf)
        body += pkt.keepalive.to_bytes(2, "big")
        if pkt.proto_ver == P.MQTT_V5:
            body += _w_properties(pkt.properties)
        body += _w_utf8(pkt.clientid)
        if pkt.will_flag:
            if pkt.proto_ver == P.MQTT_V5:
                body += _w_properties(pkt.will_props)
            body += _w_utf8(pkt.will_topic or "")
            body += _w_bin(pkt.will_payload or b"")
        if pkt.username:
            body += _w_utf8(pkt.username)
        if pkt.password is not None:
            body += _w_bin(pkt.password)
    elif t == P.CONNACK:
        body.append(int(pkt.session_present))
        body.append(pkt.reason_code)
        if v5:
            body += _w_properties(pkt.properties)
    elif t in (P.PUBACK, P.PUBREC, P.PUBREL, P.PUBCOMP):
        if t == P.PUBREL:
            flags = 0x02
        body += pkt.packet_id.to_bytes(2, "big")
        if v5 and (pkt.reason_code != P.RC_SUCCESS or pkt.properties):
            body.append(pkt.reason_code)
            if pkt.properties:
                body += _w_properties(pkt.properties)
    elif t == P.SUBSCRIBE:
        flags = 0x02
        body += pkt.packet_id.to_bytes(2, "big")
        if v5:
            body += _w_properties(pkt.properties)
        for tf, opts in pkt.topic_filters:
            body += _w_utf8(tf)
            body.append(
                (opts.get("qos", 0) & 0x03)
                | (opts.get("nl", 0) << 2)
                | (opts.get("rap", 0) << 3)
                | ((opts.get("rh", 0) & 0x03) << 4)
            )
    elif t == P.SUBACK:
        body += pkt.packet_id.to_bytes(2, "big")
        if v5:
            body += _w_properties(pkt.properties)
        body += bytes(pkt.reason_codes)
    elif t == P.UNSUBSCRIBE:
        flags = 0x02
        body += pkt.packet_id.to_bytes(2, "big")
        if v5:
            body += _w_properties(pkt.properties)
        for tf in pkt.topic_filters:
            body += _w_utf8(tf)
    elif t == P.UNSUBACK:
        body += pkt.packet_id.to_bytes(2, "big")
        if v5:
            body += _w_properties(pkt.properties)
            body += bytes(pkt.reason_codes)
    elif t in (P.PINGREQ, P.PINGRESP):
        pass
    elif t == P.DISCONNECT:
        if v5 and (pkt.reason_code != P.RC_SUCCESS or pkt.properties):
            body.append(pkt.reason_code)
            if pkt.properties:
                body += _w_properties(pkt.properties)
    elif t == P.AUTH:
        if pkt.reason_code != P.RC_SUCCESS or pkt.properties:
            body.append(pkt.reason_code)
            if pkt.properties:
                body += _w_properties(pkt.properties)
    else:
        raise FrameError(f"cannot serialize type {t}")
    return bytes([(t << 4) | flags]) + _w_varint(len(body)) + bytes(body)
