"""MQTT control-packet model (3.1 / 3.1.1 / 5.0).

Parity with the reference's packet records (``apps/emqx/include/emqx_mqtt.hrl``)
and helpers (``apps/emqx/src/emqx_packet.erl``): packet type constants,
per-type dataclasses, v5 reason codes, and property names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

# protocol versions
MQTT_V3 = 3
MQTT_V4 = 4   # a.k.a. 3.1.1
MQTT_V5 = 5

# control packet types
CONNECT = 1
CONNACK = 2
PUBLISH = 3
PUBACK = 4
PUBREC = 5
PUBREL = 6
PUBCOMP = 7
SUBSCRIBE = 8
SUBACK = 9
UNSUBSCRIBE = 10
UNSUBACK = 11
PINGREQ = 12
PINGRESP = 13
DISCONNECT = 14
AUTH = 15

TYPE_NAMES = {
    CONNECT: "CONNECT", CONNACK: "CONNACK", PUBLISH: "PUBLISH",
    PUBACK: "PUBACK", PUBREC: "PUBREC", PUBREL: "PUBREL",
    PUBCOMP: "PUBCOMP", SUBSCRIBE: "SUBSCRIBE", SUBACK: "SUBACK",
    UNSUBSCRIBE: "UNSUBSCRIBE", UNSUBACK: "UNSUBACK", PINGREQ: "PINGREQ",
    PINGRESP: "PINGRESP", DISCONNECT: "DISCONNECT", AUTH: "AUTH",
}

QOS_0, QOS_1, QOS_2 = 0, 1, 2

# MQTT 5.0 reason codes (subset used broker-wide; emqx_mqtt.hrl RC_*)
RC_SUCCESS = 0x00
RC_GRANTED_QOS_1 = 0x01
RC_GRANTED_QOS_2 = 0x02
RC_NO_MATCHING_SUBSCRIBERS = 0x10
RC_NO_SUBSCRIPTION_EXISTED = 0x11
RC_UNSPECIFIED_ERROR = 0x80
RC_MALFORMED_PACKET = 0x81
RC_PROTOCOL_ERROR = 0x82
RC_IMPLEMENTATION_SPECIFIC_ERROR = 0x83
RC_UNSUPPORTED_PROTOCOL_VERSION = 0x84
RC_CLIENT_IDENTIFIER_NOT_VALID = 0x85
RC_BAD_USER_NAME_OR_PASSWORD = 0x86
RC_NOT_AUTHORIZED = 0x87
RC_SERVER_UNAVAILABLE = 0x88
RC_SERVER_BUSY = 0x89
RC_BANNED = 0x8A
RC_BAD_AUTHENTICATION_METHOD = 0x8C
RC_KEEP_ALIVE_TIMEOUT = 0x8D
RC_SESSION_TAKEN_OVER = 0x8E
RC_TOPIC_FILTER_INVALID = 0x8F
RC_TOPIC_NAME_INVALID = 0x90
RC_PACKET_IDENTIFIER_IN_USE = 0x91
RC_PACKET_IDENTIFIER_NOT_FOUND = 0x92
RC_RECEIVE_MAXIMUM_EXCEEDED = 0x93
RC_TOPIC_ALIAS_INVALID = 0x94
RC_PACKET_TOO_LARGE = 0x95
RC_MESSAGE_RATE_TOO_HIGH = 0x96
RC_QUOTA_EXCEEDED = 0x97
RC_ADMINISTRATIVE_ACTION = 0x98
RC_PAYLOAD_FORMAT_INVALID = 0x99
RC_RETAIN_NOT_SUPPORTED = 0x9A
RC_QOS_NOT_SUPPORTED = 0x9B
RC_USE_ANOTHER_SERVER = 0x9C
RC_SERVER_MOVED = 0x9D
RC_SHARED_SUBSCRIPTIONS_NOT_SUPPORTED = 0x9E
RC_CONNECTION_RATE_EXCEEDED = 0x9F
RC_MAXIMUM_CONNECT_TIME = 0xA0
RC_SUBSCRIPTION_IDENTIFIERS_NOT_SUPPORTED = 0xA1
RC_WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED = 0xA2

# v5 property ids → (name, type); type ∈ byte|two|four|varint|utf8|binary|utf8pair
PROPERTIES = {
    0x01: ("Payload-Format-Indicator", "byte"),
    0x02: ("Message-Expiry-Interval", "four"),
    0x03: ("Content-Type", "utf8"),
    0x08: ("Response-Topic", "utf8"),
    0x09: ("Correlation-Data", "binary"),
    0x0B: ("Subscription-Identifier", "varint"),
    0x11: ("Session-Expiry-Interval", "four"),
    0x12: ("Assigned-Client-Identifier", "utf8"),
    0x13: ("Server-Keep-Alive", "two"),
    0x15: ("Authentication-Method", "utf8"),
    0x16: ("Authentication-Data", "binary"),
    0x17: ("Request-Problem-Information", "byte"),
    0x18: ("Will-Delay-Interval", "four"),
    0x19: ("Request-Response-Information", "byte"),
    0x1A: ("Response-Information", "utf8"),
    0x1C: ("Server-Reference", "utf8"),
    0x1F: ("Reason-String", "utf8"),
    0x21: ("Receive-Maximum", "two"),
    0x22: ("Topic-Alias-Maximum", "two"),
    0x23: ("Topic-Alias", "two"),
    0x24: ("Maximum-QoS", "byte"),
    0x25: ("Retain-Available", "byte"),
    0x26: ("User-Property", "utf8pair"),
    0x27: ("Maximum-Packet-Size", "four"),
    0x28: ("Wildcard-Subscription-Available", "byte"),
    0x29: ("Subscription-Identifier-Available", "byte"),
    0x2A: ("Shared-Subscription-Available", "byte"),
}
PROP_IDS = {name: (pid, ty) for pid, (name, ty) in PROPERTIES.items()}


class FrameError(Exception):
    """Malformed packet (maps to RC_MALFORMED_PACKET / connection close)."""

    def __init__(self, reason: str, rc: int = RC_MALFORMED_PACKET):
        super().__init__(reason)
        self.rc = rc


@dataclass
class Connect:
    proto_name: str = "MQTT"
    proto_ver: int = MQTT_V4
    clean_start: bool = True
    keepalive: int = 60
    clientid: str = ""
    username: Optional[str] = None
    password: Optional[bytes] = None
    will_flag: bool = False
    will_qos: int = 0
    will_retain: bool = False
    will_topic: Optional[str] = None
    will_payload: Optional[bytes] = None
    will_props: dict[str, Any] = field(default_factory=dict)
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = CONNECT


@dataclass
class Connack:
    session_present: bool = False
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = CONNACK


@dataclass
class Publish:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    packet_id: Optional[int] = None   # required iff qos > 0
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = PUBLISH


@dataclass
class PubAck:
    packet_id: int
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = PUBACK


@dataclass
class PubRec:
    packet_id: int
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = PUBREC


@dataclass
class PubRel:
    packet_id: int
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = PUBREL


@dataclass
class PubComp:
    packet_id: int
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = PUBCOMP


@dataclass
class Subscribe:
    packet_id: int
    # [(topic_filter, {qos, nl, rap, rh})]
    topic_filters: list[tuple[str, dict[str, int]]] = field(default_factory=list)
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = SUBSCRIBE


@dataclass
class SubAck:
    packet_id: int
    reason_codes: list[int] = field(default_factory=list)
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = SUBACK


@dataclass
class Unsubscribe:
    packet_id: int
    topic_filters: list[str] = field(default_factory=list)
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = UNSUBSCRIBE


@dataclass
class UnsubAck:
    packet_id: int
    reason_codes: list[int] = field(default_factory=list)
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = UNSUBACK


@dataclass
class PingReq:
    type: int = PINGREQ


@dataclass
class PingResp:
    type: int = PINGRESP


@dataclass
class Disconnect:
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = DISCONNECT


@dataclass
class Auth:
    reason_code: int = RC_SUCCESS
    properties: dict[str, Any] = field(default_factory=dict)
    type: int = AUTH


Packet = (
    Connect | Connack | Publish | PubAck | PubRec | PubRel | PubComp
    | Subscribe | SubAck | Unsubscribe | UnsubAck | PingReq | PingResp
    | Disconnect | Auth
)
