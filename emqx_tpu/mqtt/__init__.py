from emqx_tpu.mqtt import packet
from emqx_tpu.mqtt.frame import ParseState, Parser, serialize

__all__ = ["packet", "ParseState", "Parser", "serialize"]
