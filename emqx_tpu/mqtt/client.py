"""Async MQTT client — the ``emqtt`` analogue (used by the test suites
the way the reference drives its broker with emqtt, and by the MQTT
data bridge)."""

from __future__ import annotations

import asyncio
from typing import Optional

from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import Parser, serialize


class MqttClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 1883,
                 clientid: str = "", proto_ver: int = P.MQTT_V4,
                 clean_start: bool = True, keepalive: int = 60,
                 username: Optional[str] = None,
                 password: Optional[bytes] = None,
                 properties: Optional[dict] = None,
                 will: Optional[P.Connect] = None,
                 ssl=None, server_hostname: Optional[str] = None,
                 auto_ack: bool = True):
        self.host, self.port = host, port
        self.auto_ack = auto_ack        # False: tests ack via puback()
        self.ssl = ssl                  # ssl.SSLContext | None
        self.server_hostname = server_hostname
        self.clientid = clientid
        self.proto_ver = proto_ver
        self.clean_start = clean_start
        self.keepalive = keepalive
        self.username, self.password = username, password
        self.properties = properties or {}
        self._parser = Parser(version=proto_ver)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._incoming: asyncio.Queue[P.Packet] = asyncio.Queue()
        self.messages: asyncio.Queue[P.Publish] = asyncio.Queue()
        self._next_pid = 0
        self.connack: Optional[P.Connack] = None

    def _pid(self) -> int:
        self._next_pid = self._next_pid % 65535 + 1
        return self._next_pid

    async def connect(self, will_topic=None, will_payload=b"",
                      will_qos=0, timeout: float = 5.0) -> P.Connack:
        kw = {}
        if self.ssl is not None:
            kw["ssl"] = self.ssl
            kw["server_hostname"] = self.server_hostname or self.host
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, **kw
        )
        self._recv_task = asyncio.create_task(self._recv_loop())
        await self._send(P.Connect(
            proto_ver=self.proto_ver, clean_start=self.clean_start,
            keepalive=self.keepalive, clientid=self.clientid,
            username=self.username, password=self.password,
            properties=self.properties,
            will_flag=will_topic is not None, will_qos=will_qos,
            will_topic=will_topic, will_payload=will_payload,
        ))
        pkt = await self._expect(P.CONNACK, timeout)
        self.connack = pkt
        if pkt.reason_code != P.RC_SUCCESS:
            await self.close()
            raise ConnectionRefusedError(
                f"CONNACK reason_code=0x{pkt.reason_code:02x}")
        return pkt

    async def _send(self, pkt: P.Packet) -> None:
        assert self._writer is not None
        self._writer.write(serialize(pkt, self.proto_ver))
        await self._writer.drain()

    async def _recv_loop(self) -> None:
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    break
                for pkt in self._parser.feed(data):
                    await self._route_in(pkt)
        except (ConnectionError, asyncio.CancelledError):
            pass

    async def _route_in(self, pkt: P.Packet) -> None:
        if pkt.type == P.PUBLISH:
            await self.messages.put(pkt)
            if not self.auto_ack:
                pass
            elif pkt.qos == 1:
                await self._send(P.PubAck(packet_id=pkt.packet_id))
            elif pkt.qos == 2:
                await self._send(P.PubRec(packet_id=pkt.packet_id))
        elif pkt.type == P.PUBREL:
            if self.auto_ack:
                await self._send(P.PubComp(packet_id=pkt.packet_id))
            else:
                # manual-ack mode: surface the PUBREL so tests can run
                # the subscriber-side QoS2 exchange by hand (the old
                # unconditional auto-PubComp swallowed it, making
                # _expect(PUBREL) unreachable)
                await self._incoming.put(pkt)
        elif pkt.type == P.PINGRESP:
            pass
        else:
            await self._incoming.put(pkt)

    async def _expect(self, ptype: int, timeout: float = 5.0) -> P.Packet:
        while True:
            pkt = await asyncio.wait_for(self._incoming.get(), timeout)
            if pkt.type == ptype:
                return pkt

    async def subscribe(self, topic: str, qos: int = 0,
                        properties: Optional[dict] = None,
                        **opts) -> P.SubAck:
        """``properties`` are SUBSCRIBE packet-level (e.g.
        Subscription-Identifier); ``opts`` are per-filter (nl/rap/rh)."""
        await self._send(P.Subscribe(
            packet_id=self._pid(), properties=properties or {},
            topic_filters=[(topic, {"qos": qos, **opts})],
        ))
        return await self._expect(P.SUBACK)

    async def unsubscribe(self, topic: str) -> P.UnsubAck:
        await self._send(P.Unsubscribe(
            packet_id=self._pid(), topic_filters=[topic]
        ))
        return await self._expect(P.UNSUBACK)

    async def publish(self, topic: str, payload: bytes = b"",
                      qos: int = 0, retain: bool = False,
                      properties: Optional[dict] = None) -> Optional[int]:
        pid = self._pid() if qos else None
        await self._send(P.Publish(
            topic=topic, payload=payload, qos=qos, retain=retain,
            packet_id=pid, properties=properties or {},
        ))
        if qos == 1:
            await self._expect(P.PUBACK)
        elif qos == 2:
            await self._expect(P.PUBREC)
            await self._send(P.PubRel(packet_id=pid))
            await self._expect(P.PUBCOMP)
        return pid

    async def recv(self, timeout: float = 5.0) -> P.Publish:
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def puback(self, packet_id: int) -> None:
        """Manual QoS1 ack (use with auto_ack=False)."""
        await self._send(P.PubAck(packet_id=packet_id))

    async def ping(self) -> None:
        await self._send(P.PingReq())

    async def disconnect(self, reason_code: int = P.RC_SUCCESS) -> None:
        try:
            await self._send(P.Disconnect(reason_code=reason_code))
        except ConnectionError:
            pass
        await self.close()

    async def close(self) -> None:
        if self._recv_task:
            self._recv_task.cancel()
        if self._writer:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, Exception):
                pass
