"""Builtin SQL functions — parity with
``apps/emqx_rule_engine/src/emqx_rule_funcs.erl`` — 131 funcs covering
the math/bit/string/map/array/date/compression/hash/topic families, same
names/semantics.
"""

from __future__ import annotations

import base64
import gzip as _gzip
import hashlib
import json
import math
import re
import time
import uuid
import zlib
from typing import Any, Callable

FUNCS: dict[str, Callable] = {}


def f(name: str):
    def deco(fn):
        FUNCS[name] = fn
        return fn
    return deco


def _num(x) -> float:
    if isinstance(x, bool):
        return 1.0 if x else 0.0
    if isinstance(x, (int, float)):
        return x
    return float(x)


def _str(x) -> str:
    if isinstance(x, bytes):
        return x.decode(errors="replace")
    if isinstance(x, bool):
        return "true" if x else "false"
    if x is None:
        return ""
    if isinstance(x, float) and x.is_integer():
        return str(int(x))
    return str(x)


# -- math (emqx_rule_funcs math section) ----------------------------------

for _name in ("sin cos tan asin acos atan sinh cosh tanh log log10 log2 "
              "exp sqrt").split():
    FUNCS[_name] = (lambda fn: lambda x: fn(_num(x)))(
        getattr(math, _name if _name != "log2" else "log2"))
FUNCS["abs"] = lambda x: abs(_num(x))
FUNCS["ceil"] = lambda x: math.ceil(_num(x))
FUNCS["floor"] = lambda x: math.floor(_num(x))
FUNCS["round"] = lambda x: round(_num(x))
FUNCS["power"] = lambda x, y: math.pow(_num(x), _num(y))
FUNCS["fmod"] = lambda x, y: math.fmod(_num(x), _num(y))
FUNCS["random"] = lambda: __import__("random").random()
FUNCS["pi"] = lambda: math.pi

# -- type checks / conversion ---------------------------------------------

FUNCS["is_null"] = lambda x: x is None
FUNCS["is_not_null"] = lambda x: x is not None
FUNCS["is_num"] = lambda x: isinstance(x, (int, float)) \
    and not isinstance(x, bool)
FUNCS["is_int"] = lambda x: isinstance(x, int) and not isinstance(x, bool)
FUNCS["is_float"] = lambda x: isinstance(x, float)
FUNCS["is_str"] = lambda x: isinstance(x, str)
FUNCS["is_bool"] = lambda x: isinstance(x, bool)
FUNCS["is_map"] = lambda x: isinstance(x, dict)
FUNCS["is_array"] = lambda x: isinstance(x, list)
FUNCS["str"] = _str
FUNCS["str_utf8"] = _str
FUNCS["int"] = lambda x: int(_num(x))
FUNCS["float"] = lambda x: float(_num(x))
FUNCS["bool"] = lambda x: (x in (True, "true", 1))
FUNCS["num"] = _num


# -- strings ---------------------------------------------------------------

FUNCS["lower"] = lambda s: _str(s).lower()
FUNCS["upper"] = lambda s: _str(s).upper()
FUNCS["trim"] = lambda s: _str(s).strip()
FUNCS["ltrim"] = lambda s: _str(s).lstrip()
FUNCS["rtrim"] = lambda s: _str(s).rstrip()
FUNCS["reverse"] = lambda s: _str(s)[::-1]
FUNCS["strlen"] = lambda s: len(_str(s))
FUNCS["substr"] = lambda s, start, ln=None: (
    _str(s)[int(start):] if ln is None
    else _str(s)[int(start):int(start) + int(ln)])
FUNCS["split"] = lambda s, sep=",": [p for p in _str(s).split(_str(sep))
                                     if p != ""]
FUNCS["concat"] = lambda *xs: "".join(_str(x) for x in xs)
FUNCS["sprintf"] = lambda fmt, *xs: _str(fmt) % xs
FUNCS["pad"] = lambda s, ln, side="trailing", ch=" ": (
    _str(s).ljust(int(ln), ch) if side == "trailing"
    else _str(s).rjust(int(ln), ch))
FUNCS["replace"] = lambda s, old, new: _str(s).replace(_str(old), _str(new))
FUNCS["regex_match"] = lambda s, p: re.search(p, _str(s)) is not None
FUNCS["regex_replace"] = lambda s, p, r: re.sub(p, r, _str(s))
FUNCS["regex_extract"] = lambda s, p: (
    (m := re.search(p, _str(s))) and (m.group(1) if m.groups()
                                      else m.group(0)) or "")
FUNCS["ascii"] = lambda s: ord(_str(s)[0]) if _str(s) else None
FUNCS["find"] = lambda s, sub: (
    _str(s)[i:] if (i := _str(s).find(_str(sub))) >= 0 else "")
FUNCS["tokens"] = FUNCS["split"]
FUNCS["startswith"] = lambda s, p: _str(s).startswith(_str(p))
FUNCS["endswith"] = lambda s, p: _str(s).endswith(_str(p))


@f("like")
def _like(s, pattern):
    """SQL LIKE: % = any run, _ = one char."""
    rx = re.escape(_str(pattern)).replace("%", ".*").replace("_", ".")
    return re.fullmatch(rx, _str(s)) is not None


# -- maps / arrays ---------------------------------------------------------

FUNCS["map_get"] = lambda k, m, default=None: (
    m.get(_str(k), default) if isinstance(m, dict) else default)
FUNCS["map_put"] = lambda k, v, m: {**(m or {}), _str(k): v}
FUNCS["map_keys"] = lambda m: list((m or {}).keys())
FUNCS["map_values"] = lambda m: list((m or {}).values())
FUNCS["mget"] = FUNCS["map_get"]
FUNCS["mput"] = FUNCS["map_put"]
FUNCS["nth"] = lambda n, xs: (
    xs[int(n) - 1] if isinstance(xs, list) and 1 <= int(n) <= len(xs)
    else None)
FUNCS["length"] = lambda xs: len(xs)
FUNCS["sublist"] = lambda ln, xs: xs[:int(ln)]
FUNCS["first"] = lambda xs: xs[0] if xs else None
FUNCS["last"] = lambda xs: xs[-1] if xs else None
FUNCS["contains"] = lambda x, xs: x in xs
FUNCS["range"] = lambda a, b: list(range(int(a), int(b) + 1))


# -- json / binary ---------------------------------------------------------

FUNCS["json_encode"] = lambda x: json.dumps(x, separators=(",", ":"))
FUNCS["json_decode"] = lambda s: json.loads(
    s if isinstance(s, (str, bytes)) else _str(s))
FUNCS["base64_encode"] = lambda b: base64.b64encode(
    b if isinstance(b, bytes) else _str(b).encode()).decode()
FUNCS["base64_decode"] = lambda s: base64.b64decode(_str(s))
FUNCS["bin2hexstr"] = lambda b: (
    b if isinstance(b, bytes) else _str(b).encode()).hex()
FUNCS["hexstr2bin"] = lambda s: bytes.fromhex(_str(s))
FUNCS["byteize"] = lambda x: x if isinstance(x, bytes) else _str(x).encode()
FUNCS["subbits"] = lambda b, ln: int.from_bytes(
    (b if isinstance(b, bytes) else _str(b).encode()), "big") \
    >> max(0, len(b) * 8 - int(ln))


# -- hashing / ids ---------------------------------------------------------

FUNCS["md5"] = lambda s: hashlib.md5(
    s if isinstance(s, bytes) else _str(s).encode()).hexdigest()
FUNCS["sha"] = lambda s: hashlib.sha1(
    s if isinstance(s, bytes) else _str(s).encode()).hexdigest()
FUNCS["sha256"] = lambda s: hashlib.sha256(
    s if isinstance(s, bytes) else _str(s).encode()).hexdigest()
FUNCS["crc32"] = lambda s: zlib.crc32(
    s if isinstance(s, bytes) else _str(s).encode())
FUNCS["uuid_v4"] = lambda: str(uuid.uuid4())


# -- time ------------------------------------------------------------------

FUNCS["now_timestamp"] = lambda unit="second": (
    int(time.time()) if unit == "second"
    else time.time_ns() // 1_000_000 if unit == "millisecond"
    else time.time_ns() // 1000 if unit == "microsecond"
    else time.time_ns())
FUNCS["now_rfc3339"] = lambda: time.strftime(
    "%Y-%m-%dT%H:%M:%S%z", time.localtime())
FUNCS["unix_ts_to_rfc3339"] = lambda ts, unit="second": time.strftime(
    "%Y-%m-%dT%H:%M:%S%z", time.localtime(
        _num(ts) / {"second": 1, "millisecond": 1000,
                    "microsecond": 1e6}.get(unit, 1)))
FUNCS["timezone_to_second"] = lambda tz: int(_num(tz))


# -- mqtt ------------------------------------------------------------------

@f("topic")
def _topic_join(*words):
    return "/".join(_str(w) for w in words)


@f("nth_topic_level")
def _nth_topic_level(n, topic):
    parts = _str(topic).split("/")
    n = int(n)
    return parts[n - 1] if 1 <= n <= len(parts) else None


FUNCS["term_to_binary"] = lambda x: json.dumps(x).encode()
FUNCS["binary_to_term"] = lambda b: json.loads(b)


# -- bit / binary ops (emqx_rule_funcs.erl bit* family) --------------------

FUNCS["bitand"] = lambda x, y: int(_num(x)) & int(_num(y))
FUNCS["bitor"] = lambda x, y: int(_num(x)) | int(_num(y))
FUNCS["bitxor"] = lambda x, y: int(_num(x)) ^ int(_num(y))
FUNCS["bitnot"] = lambda x: ~int(_num(x))
FUNCS["bitsl"] = lambda x, n: int(_num(x)) << int(_num(n))
FUNCS["bitsr"] = lambda x, n: int(_num(x)) >> int(_num(n))
FUNCS["bitsize"] = lambda b: len(b) * 8 if isinstance(b, (bytes, bytearray)) \
    else len(_str(b).encode()) * 8
FUNCS["mod"] = lambda x, y: int(_num(x)) % int(_num(y))
FUNCS["eq"] = lambda x, y: x == y


@f("subbits")
def _subbits(b, start_or_len, ln=None):
    """subbits(Bytes, Len) / subbits(Bytes, Start, Len) — big-endian
    unsigned int of the selected bit range (1-based start)."""
    data = b if isinstance(b, (bytes, bytearray)) else _str(b).encode()
    val = int.from_bytes(data, "big")
    total = len(data) * 8
    if ln is None:
        start, ln = 1, int(_num(start_or_len))
    else:
        start, ln = int(_num(start_or_len)), int(_num(ln))
    if start < 1 or start + ln - 1 > total:
        return None
    return (val >> (total - (start - 1) - ln)) & ((1 << ln) - 1)


# -- inverse hyperbolics ----------------------------------------------------

FUNCS["acosh"] = lambda x: math.acosh(_num(x))
FUNCS["asinh"] = lambda x: math.asinh(_num(x))
FUNCS["atanh"] = lambda x: math.atanh(_num(x))
def _float2str(x, prec=10):
    s = f"{_num(x):.{int(prec)}f}"
    # only trim the fractional part — prec=0 must not eat integer zeros
    return s.rstrip("0").rstrip(".") if "." in s else s


FUNCS["float2str"] = _float2str


# -- compression / hashing / encoding ---------------------------------------

def _as_bytes(x):
    return x if isinstance(x, (bytes, bytearray)) else _str(x).encode()


FUNCS["gzip"] = lambda b: _gzip.compress(_as_bytes(b))
FUNCS["gunzip"] = lambda b: _gzip.decompress(_as_bytes(b))
FUNCS["zip"] = lambda b: zlib.compress(_as_bytes(b), 9)[2:-4]
FUNCS["unzip"] = lambda b: zlib.decompress(_as_bytes(b), wbits=-15)
FUNCS["zip_compress"] = lambda b: zlib.compress(_as_bytes(b))
FUNCS["zip_uncompress"] = lambda b: zlib.decompress(_as_bytes(b))


@f("hash")
def _hash(alg, data):
    return hashlib.new(_str(alg), _as_bytes(data)).hexdigest()


FUNCS["term_encode"] = FUNCS["term_to_binary"]
FUNCS["term_decode"] = FUNCS["binary_to_term"]


# -- topic predicates --------------------------------------------------------

@f("contains_topic")
def _contains_topic(topics, topic, *rest):
    items = topics if isinstance(topics, list) else [topics]
    return any(_str(t) == _str(topic) for t in items)


@f("contains_topic_match")
def _contains_topic_match(filters, topic, *rest):
    from emqx_tpu.core import topic as T

    items = filters if isinstance(filters, list) else [filters]
    return any(T.match(_str(topic), _str(fl)) for fl in items)


@f("find_topic_filter")
def _find_topic_filter(filters, topic):
    from emqx_tpu.core import topic as T

    items = filters if isinstance(filters, list) else [filters]
    for fl in items:
        if T.match(_str(topic), _str(fl)):
            return _str(fl)
    return None


# -- maps --------------------------------------------------------------------

FUNCS["map_new"] = lambda: {}
FUNCS["map"] = lambda x=None: dict(x) if isinstance(x, dict) else {}


@f("map_path")
def _map_path(path, obj):
    cur = obj
    for seg in _str(path).lstrip("$.").split("."):
        if isinstance(cur, dict) and seg in cur:
            cur = cur[seg]
        else:
            return None
    return cur


# -- dates -------------------------------------------------------------------

@f("format_date")
def _format_date(unit, offset, fmt, ts=None):
    from datetime import datetime, timedelta, timezone

    ts_s = (_num(ts) if ts is not None else time.time()) / {
        "second": 1, "millisecond": 1000, "microsecond": 1e6,
        "nanosecond": 1e9}.get(_str(unit), 1)
    off = _str(offset)
    if off in ("", "local"):
        return time.strftime(_str(fmt), time.localtime(ts_s))
    if off in ("Z", "z", "+00:00", "0"):
        tz = timezone.utc
    else:
        sign = -1 if off.startswith("-") else 1
        hh, _, mm = off.lstrip("+-").partition(":")
        tz = timezone(sign * timedelta(hours=int(hh or 0),
                                       minutes=int(mm or 0)))
    return datetime.fromtimestamp(ts_s, tz).strftime(_str(fmt))


@f("date_to_unix_ts")
def _date_to_unix_ts(unit, fmt, date):
    mult = {"second": 1, "millisecond": 1000, "microsecond": 1_000_000,
            "nanosecond": 1_000_000_000}.get(_str(unit), 1)
    return int(time.mktime(time.strptime(_str(date), _str(fmt)))) * mult


@f("rfc3339_to_unix_ts")
def _rfc3339_to_unix_ts(date, unit="second"):
    from datetime import datetime

    dt = datetime.fromisoformat(_str(date).replace("Z", "+00:00"))
    mult = {"second": 1, "millisecond": 1000, "microsecond": 1_000_000,
            "nanosecond": 1_000_000_000}.get(_str(unit), 1)
    return int(dt.timestamp() * mult)


FUNCS["time_unit"] = lambda u: {"second": 1, "millisecond": 1000,
                                "microsecond": 1_000_000,
                                "nanosecond": 1_000_000_000}.get(_str(u), 1)


# -- per-rule kv store (emqx_rule_funcs kv_store_* / proc_dict_*) -----------
#
# The reference scopes the store per rule (the rule's worker process
# dictionary); a process-global dict would let rules collide on keys and
# grow without bound.  The engine sets the active rule id around each
# apply (engine.py) via a contextvar; keys are bounded per rule with
# oldest-first eviction.

import contextvars

_RULE_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "emqx_rule_id", default="")
_KV_STORE: dict = {}          # rule_id → {key: value}
_KV_MAX_KEYS = 10_000


def set_rule_context(rule_id):
    """Returns a token for reset_rule_context (used by the engine)."""
    return _RULE_CTX.set(rule_id)


def reset_rule_context(token) -> None:
    _RULE_CTX.reset(token)


def drop_rule_store(rule_id) -> None:
    _KV_STORE.pop(rule_id, None)


def _kv() -> dict:
    return _KV_STORE.setdefault(_RULE_CTX.get(), {})


@f("kv_store_put")
def _kv_store_put(k, v):
    d = _kv()
    k = _str(k)
    if len(d) >= _KV_MAX_KEYS and k not in d:
        d.pop(next(iter(d)))          # evict oldest insertion
    d[k] = v
    return v


FUNCS["kv_store_get"] = lambda k, default=None: _kv().get(_str(k), default)


@f("kv_store_del")
def _kv_store_del(k):
    _kv().pop(_str(k), None)
    return None
FUNCS["proc_dict_put"] = FUNCS["kv_store_put"]
FUNCS["proc_dict_get"] = FUNCS["kv_store_get"]
FUNCS["proc_dict_del"] = FUNCS["kv_store_del"]


# -- remaining emqx_rule_funcs.erl surface (round 3) -----------------------

FUNCS["null"] = lambda: None        # null/0: the SQL undefined literal


@f("find_s")
def _find_s(s, sub, direction="leading"):
    """find with an explicit direction (find_s/3): 'leading' scans from
    the left (= find/2), 'trailing' from the right."""
    s, sub = _str(s), _str(sub)
    i = s.find(sub) if _str(direction) == "leading" else s.rfind(sub)
    return s[i:] if i >= 0 else ""


FUNCS["sprintf_s"] = FUNCS["sprintf"]       # erlang-side alias


@f("jq")
def _jq(program, value, _timeout_ms=None):
    # the reference runs this through the optional libjq NIF
    # (emqx_rule_funcs.erl:806-828, jq:process_json/3 → list of
    # outputs); this build ships its own jq-subset interpreter instead
    # (utils/jq.py). jq/3's timeout is a NIF-dirty-scheduler concern
    # the in-process evaluator doesn't have; accepted and ignored.
    from emqx_tpu.utils.jq import JqError, jq as run_jq
    if isinstance(program, (bytes, bytearray)):
        program = program.decode("utf-8")
    if isinstance(value, str):
        # reference semantics: the SQL value is a binary holding JSON
        # text (jq:process_json/3 parses it); our runtime hands SQL
        # binaries over as str, so decode here — invalid JSON fails the
        # rule, same as the NIF. utils/jq.py itself never sniffs str.
        try:
            value = json.loads(value)
        except ValueError as e:
            raise JqError(f"jq: invalid JSON input: {e}") from None
    return run_jq(program, value)


# -- message-context accessors (clientid/0, payload/0, ... in the
# reference read the event's message record; here they read the rule's
# event columns via the CONTEXT_FUNCS registry the runtime passes
# columns into)

CONTEXT_FUNCS: dict[str, Callable] = {}


for _col in ("clientid", "username", "payload", "qos", "topic",
             "peerhost", "flags", "timestamp"):
    CONTEXT_FUNCS[_col] = (lambda c: lambda cols: cols.get(c))(_col)
CONTEXT_FUNCS["clientip"] = lambda cols: cols.get("peerhost")
CONTEXT_FUNCS["msgid"] = lambda cols: cols.get("id")
CONTEXT_FUNCS["flag"] = lambda cols, name: (
    (cols.get("flags") or {}).get(_str(name)))
CONTEXT_FUNCS["rule_id"] = lambda cols: _RULE_CTX.get()
