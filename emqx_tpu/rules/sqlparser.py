"""Rule SQL parser — parity with the ``rulesql`` dep
(``apps/emqx_rule_engine/src/emqx_rule_sqlparser.erl`` wraps it).

Grammar (the surface EMQX rules use):

    SELECT <fields> FROM <topics> [WHERE <cond>]
    FOREACH <expr> [AS ident] [DO <fields>] [INCASE <cond>]
        FROM <topics> [WHERE <cond>]

    fields := * | expr [AS dotted_ident] (, ...)
    topics := 'string' (, ...)          -- topic filters / $events/...
    expr   := OR / AND / NOT chains over comparisons
              (=, !=, <>, >, <, >=, <=, IN (..), LIKE? → not in ref),
              arithmetic (+ - * / div mod), string concat via +,
              function calls f(a, b), dotted refs payload.x.y[1],
              literals (numbers, 'strings', true/false/null), CASE WHEN

Produces a small AST of tuples:
    ("const", v) ("var", ["payload","x"]) ("call", name, [args])
    ("op", sym, l, r) ("neg", e) ("not", e) ("and"/"or", l, r)
    ("in", e, [items]) ("case", [(when, then)...], else_or_None)
    ("index", e, idx_expr)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<dq>"(?:[^"]|"")*")
  | (?P<cmp><>|!=|>=|<=|=|>|<)
  | (?P<op>[+\-*/(),.\[\]])
  | (?P<word>[A-Za-z_$][A-Za-z0-9_$#/+-]*)
""", re.VERBOSE)

KEYWORDS = {"select", "from", "where", "as", "and", "or", "not", "in",
            "foreach", "do", "incase", "case", "when", "then", "else",
            "end", "div", "mod", "true", "false", "null", "like"}


class SqlError(ValueError):
    pass


@dataclass
class Token:
    kind: str       # num | str | word | cmp | op
    val: str
    pos: int


def tokenize(sql: str) -> list[Token]:
    out, i = [], 0
    while i < len(sql):
        m = _TOKEN.match(sql, i)
        if m is None:
            raise SqlError(f"bad token at {sql[i:i+12]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "dq":                 # "quoted identifier"
            out.append(Token("word", val[1:-1].replace('""', '"'), m.start()))
        elif kind == "str":
            out.append(Token("str", val[1:-1].replace("''", "'"), m.start()))
        else:
            out.append(Token(kind, val, m.start()))
    return out


@dataclass
class Select:
    fields: list            # [("*",)| (expr, alias|None)]
    topics: list[str]
    where: Optional[tuple]  # expr AST
    # FOREACH extras
    foreach: Optional[tuple] = None       # expr producing an array
    foreach_alias: Optional[str] = None
    do_fields: Optional[list] = None
    incase: Optional[tuple] = None

    @property
    def is_foreach(self) -> bool:
        return self.foreach is not None


class _P:
    def __init__(self, toks: list[Token]) -> None:
        self.toks = toks
        self.i = 0

    def peek(self) -> Optional[Token]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Token:
        t = self.peek()
        if t is None:
            raise SqlError("unexpected end of SQL")
        self.i += 1
        return t

    def kw(self, word: str) -> bool:
        t = self.peek()
        if t and t.kind == "word" and t.val.lower() == word:
            self.i += 1
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.kw(word):
            t = self.peek()
            raise SqlError(f"expected {word.upper()}, got "
                           f"{t.val if t else 'EOF'!r}")

    def expect_op(self, sym: str) -> None:
        t = self.next()
        if t.kind != "op" or t.val != sym:
            raise SqlError(f"expected {sym!r}, got {t.val!r}")

    def at_op(self, sym: str) -> bool:
        t = self.peek()
        if t and t.kind == "op" and t.val == sym:
            self.i += 1
            return True
        return False

    # -- statements ---------------------------------------------------------

    def parse(self) -> Select:
        if self.kw("foreach"):
            return self._foreach()
        self.expect_kw("select")
        fields = self._fields()
        self.expect_kw("from")
        topics = self._topics()
        where = self._expr() if self.kw("where") else None
        self._eof()
        return Select(fields, topics, where)

    def _foreach(self) -> Select:
        fe = self._expr()
        alias = None
        if self.kw("as"):
            alias = self._dotted()[-1]
        do_fields = self._fields() if self.kw("do") else None
        incase = self._expr() if self.kw("incase") else None
        self.expect_kw("from")
        topics = self._topics()
        where = self._expr() if self.kw("where") else None
        self._eof()
        return Select([("*",)], topics, where, foreach=fe,
                      foreach_alias=alias, do_fields=do_fields,
                      incase=incase)

    def _eof(self) -> None:
        if self.peek() is not None:
            raise SqlError(f"trailing input at {self.peek().val!r}")

    def _fields(self) -> list:
        fields = []
        while True:
            if self.at_op("*"):
                fields.append(("*",))
            else:
                e = self._expr()
                alias = None
                if self.kw("as"):
                    alias = ".".join(self._dotted())
                fields.append((e, alias))
            if not self.at_op(","):
                return fields

    def _topics(self) -> list[str]:
        topics = []
        while True:
            t = self.next()
            if t.kind not in ("str", "word"):
                raise SqlError(f"expected topic, got {t.val!r}")
            topics.append(t.val)
            if not self.at_op(","):
                return topics

    # -- expressions (precedence climbing) ----------------------------------

    def _expr(self):
        return self._or()

    def _or(self):
        left = self._and()
        while self.kw("or"):
            left = ("or", left, self._and())
        return left

    def _and(self):
        left = self._not()
        while self.kw("and"):
            left = ("and", left, self._not())
        return left

    def _not(self):
        if self.kw("not"):
            return ("not", self._not())
        return self._cmp()

    def _cmp(self):
        left = self._sum()
        t = self.peek()
        if t and t.kind == "cmp":
            self.i += 1
            return ("op", t.val, left, self._sum())
        if t and t.kind == "word" and t.val.lower() == "in":
            self.i += 1
            self.expect_op("(")
            items = []
            while True:
                items.append(self._expr())
                if not self.at_op(","):
                    break
            self.expect_op(")")
            return ("in", left, items)
        if t and t.kind == "word" and t.val.lower() == "like":
            self.i += 1
            pat = self.next()
            if pat.kind != "str":
                raise SqlError("LIKE needs a string pattern")
            return ("call", "like", [left, ("const", pat.val)])
        return left

    def _sum(self):
        left = self._term()
        while True:
            if self.at_op("+"):
                left = ("op", "+", left, self._term())
            elif self.at_op("-"):
                left = ("op", "-", left, self._term())
            else:
                return left

    def _term(self):
        left = self._unary()
        while True:
            if self.at_op("*"):
                left = ("op", "*", left, self._unary())
            elif self.at_op("/"):
                left = ("op", "/", left, self._unary())
            elif self.kw("div"):
                left = ("op", "div", left, self._unary())
            elif self.kw("mod"):
                left = ("op", "mod", left, self._unary())
            else:
                return left

    def _unary(self):
        if self.at_op("-"):
            return ("neg", self._unary())
        return self._postfix()

    def _postfix(self):
        e = self._atom()
        while True:
            if self.at_op("["):
                idx = self._expr()
                self.expect_op("]")
                e = ("index", e, idx)
            else:
                return e

    def _atom(self):
        t = self.next()
        if t.kind == "num":
            return ("const", float(t.val) if "." in t.val else int(t.val))
        if t.kind == "str":
            return ("const", t.val)
        if t.kind == "op" and t.val == "(":
            e = self._expr()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.val == "[":
            # array literal: ["a", b.c, 1] (rulesql array syntax)
            items = []
            if not self.at_op("]"):
                while True:
                    items.append(self._expr())
                    if not self.at_op(","):
                        break
                self.expect_op("]")
            return ("list", items)
        if t.kind == "word":
            low = t.val.lower()
            if low == "true":
                return ("const", True)
            if low == "false":
                return ("const", False)
            if low == "null":
                return ("const", None)
            if low == "case":
                return self._case()
            # function call?
            if self.at_op("("):
                args = []
                if not self.at_op(")"):
                    while True:
                        args.append(self._expr())
                        if not self.at_op(","):
                            break
                    self.expect_op(")")
                return ("call", t.val.lower(), args)
            # dotted variable reference
            path = [t.val]
            while self.at_op("."):
                nxt = self.next()
                if nxt.kind not in ("word", "num"):
                    raise SqlError(f"bad path segment {nxt.val!r}")
                path.append(nxt.val)
            return ("var", path)
        raise SqlError(f"unexpected token {t.val!r}")

    def _case(self):
        whens = []
        while self.kw("when"):
            cond = self._expr()
            self.expect_kw("then")
            whens.append((cond, self._expr()))
        els = self._expr() if self.kw("else") else None
        self.expect_kw("end")
        if not whens:
            raise SqlError("CASE needs at least one WHEN")
        return ("case", whens, els)

    def _dotted(self) -> list[str]:
        t = self.next()
        if t.kind != "word":
            raise SqlError(f"expected identifier, got {t.val!r}")
        path = [t.val]
        while self.at_op("."):
            path.append(self.next().val)
        return path


def parse(sql: str) -> Select:
    return _P(tokenize(sql)).parse()
