"""Rule runtime — parity with
``apps/emqx_rule_engine/src/emqx_rule_runtime.erl:58-205``.

Evaluates a parsed ``Select`` against an event's column map:
WHERE filters, SELECT projects (with aliases and nested paths), FOREACH
fans an array column out to one result per element (with DO projection
and INCASE filter). ``payload`` auto-decodes from JSON on first nested
access, as the reference's column resolution does.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from emqx_tpu.rules.funcs import CONTEXT_FUNCS, FUNCS
from emqx_tpu.rules.sqlparser import Select, SqlError


class RuleEvalError(ValueError):
    pass


def _decode_payload(val):
    if isinstance(val, (bytes, str)):
        try:
            return json.loads(val)
        except Exception:
            return None
    return val


def _lookup(columns: dict, path: list[str]) -> Any:
    cur: Any = columns
    for i, key in enumerate(path):
        if isinstance(cur, dict):
            if key in cur:
                cur = cur[key]
            elif (key == "payload" or i > 0) and isinstance(
                    cur.get(key, None), (bytes,)):
                cur = cur[key]
            else:
                return None
        elif isinstance(cur, list):
            try:
                cur = cur[int(key) - 1]           # SQL arrays are 1-based
            except (ValueError, IndexError):
                return None
        else:
            return None
        # nested access into a raw payload: decode JSON lazily
        if (isinstance(cur, (bytes,)) or isinstance(cur, str)) \
                and i < len(path) - 1:
            cur = _decode_payload(cur)
    return cur


def eval_expr(ast, columns: dict) -> Any:
    tag = ast[0]
    if tag == "const":
        return ast[1]
    if tag == "var":
        return _lookup(columns, ast[1])
    if tag == "neg":
        return -eval_expr(ast[1], columns)
    if tag == "not":
        return not _truthy(eval_expr(ast[1], columns))
    if tag == "and":
        return _truthy(eval_expr(ast[1], columns)) \
            and _truthy(eval_expr(ast[2], columns))
    if tag == "or":
        return _truthy(eval_expr(ast[1], columns)) \
            or _truthy(eval_expr(ast[2], columns))
    if tag == "in":
        v = eval_expr(ast[1], columns)
        return any(v == eval_expr(item, columns) for item in ast[2])
    if tag == "case":
        for cond, then in ast[1]:
            if _truthy(eval_expr(cond, columns)):
                return eval_expr(then, columns)
        return eval_expr(ast[2], columns) if ast[2] is not None else None
    if tag == "list":
        return [eval_expr(item, columns) for item in ast[1]]
    if tag == "index":
        seq = eval_expr(ast[1], columns)
        idx = eval_expr(ast[2], columns)
        if isinstance(seq, list):
            try:
                return seq[int(idx) - 1]
            except (IndexError, ValueError):
                return None
        if isinstance(seq, dict):
            return seq.get(idx)
        return None
    if tag == "op":
        return _binop(ast[1],
                      eval_expr(ast[2], columns),
                      eval_expr(ast[3], columns))
    if tag == "call":
        cfn = CONTEXT_FUNCS.get(ast[1])
        if cfn is not None and not (ast[2] and ast[1] in FUNCS):
            # message-context accessors (clientid(), payload(), flag(x))
            # read the event columns, not just their arguments. Names
            # shared with value builtins keep the builtin when called
            # WITH arguments: topic() reads the column, topic('a', id)
            # stays the join function.
            return cfn(columns, *[eval_expr(a, columns) for a in ast[2]])
        fn = FUNCS.get(ast[1])
        if fn is None:
            raise RuleEvalError(f"unknown SQL function {ast[1]!r}")
        return fn(*[eval_expr(a, columns) for a in ast[2]])
    raise RuleEvalError(f"bad AST node {tag!r}")


def _truthy(v: Any) -> bool:
    return bool(v) and v is not None


def _binop(sym: str, l: Any, r: Any) -> Any:
    if sym == "=":
        return _eq(l, r)
    if sym in ("!=", "<>"):
        return not _eq(l, r)
    if sym in (">", "<", ">=", "<="):
        try:
            ln, rn = _coerce_num(l), _coerce_num(r)
        except (TypeError, ValueError):
            ln, rn = str(l), str(r)
        return {">": ln > rn, "<": ln < rn,
                ">=": ln >= rn, "<=": ln <= rn}[sym]
    if sym == "+":
        if isinstance(l, str) or isinstance(r, str):
            # string + string concatenates (rulesql does this)
            from emqx_tpu.rules.funcs import _str
            return _str(l) + _str(r)
        return _coerce_num(l) + _coerce_num(r)
    if sym == "-":
        return _coerce_num(l) - _coerce_num(r)
    if sym == "*":
        return _coerce_num(l) * _coerce_num(r)
    if sym == "/":
        return _coerce_num(l) / _coerce_num(r)
    if sym == "div":
        return int(_coerce_num(l)) // int(_coerce_num(r))
    if sym == "mod":
        return int(_coerce_num(l)) % int(_coerce_num(r))
    raise RuleEvalError(f"bad operator {sym!r}")


def _coerce_num(v: Any):
    if isinstance(v, bool):
        raise TypeError("bool in arithmetic")
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        return float(v) if "." in v else int(v)
    if isinstance(v, bytes):
        return float(v) if b"." in v else int(v)
    raise TypeError(f"not a number: {v!r}")


def _eq(l: Any, r: Any) -> bool:
    if isinstance(l, bytes):
        l = l.decode(errors="replace")
    if isinstance(r, bytes):
        r = r.decode(errors="replace")
    if isinstance(l, (int, float)) and isinstance(r, (int, float)) \
            and not isinstance(l, bool) and not isinstance(r, bool):
        return float(l) == float(r)
    return l == r


def _project(fields, columns: dict) -> dict:
    out: dict[str, Any] = {}
    for fld in fields:
        if fld == ("*",):
            for k, v in columns.items():
                out.setdefault(k, v)
            continue
        expr, alias = fld
        val = eval_expr(expr, columns)
        if alias is None:
            alias = ".".join(expr[1]) if expr[0] == "var" else "value"
        # dotted alias builds nested maps (SELECT x AS a.b)
        parts = alias.split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return out


def apply_select(sel: Select, columns: dict) -> Optional[list[dict]]:
    """Run WHERE + SELECT (+FOREACH). Returns None if filtered out,
    else a list of result column maps (len>1 only for FOREACH)."""
    if sel.where is not None and not _truthy(eval_expr(sel.where, columns)):
        return None
    if not sel.is_foreach:
        return [_project(sel.fields, columns)]
    arr = eval_expr(sel.foreach, columns)
    if not isinstance(arr, list):
        return None
    results = []
    alias = sel.foreach_alias or "item"
    for item in arr:
        cols = {**columns, alias: item, "item": item}
        if sel.incase is not None and not _truthy(
                eval_expr(sel.incase, cols)):
            continue
        results.append(_project(sel.do_fields or [("*",)], cols)
                       if sel.do_fields else
                       {**cols})
    return results
