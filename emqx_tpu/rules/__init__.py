"""SQL rule engine (SURVEY.md §1 L8) — parity with
``apps/emqx_rule_engine``: SQL over hook events, builtin function
library, republish/console/custom actions."""
