"""Hook-event → SQL-columns mapping — parity with
``apps/emqx_rule_engine/src/emqx_rule_events.erl:75-123``.

Each hookpoint surfaces as an event topic selectable in FROM:

    message.publish      → plain topic filters ("t/#")
    message.delivered    → "$events/message_delivered"
    message.acked        → "$events/message_acked"
    message.dropped      → "$events/message_dropped"
    client.connected     → "$events/client_connected"
    client.disconnected  → "$events/client_disconnected"
    session.subscribed   → "$events/session_subscribed"
    session.unsubscribed → "$events/session_unsubscribed"
"""

from __future__ import annotations

import time
from typing import Any

from emqx_tpu.core.message import Message

EVENT_TOPICS = {
    "$events/message_delivered": "message.delivered",
    "$events/message_acked": "message.acked",
    "$events/message_dropped": "message.dropped",
    "$events/client_connected": "client.connected",
    "$events/client_disconnected": "client.disconnected",
    "$events/session_subscribed": "session.subscribed",
    "$events/session_unsubscribed": "session.unsubscribed",
}


def message_columns(msg: Message, node: str = "") -> dict[str, Any]:
    """Columns for message.publish (emqx_rule_events:eventmsg_publish)."""
    props = msg.headers.get("properties") or {}
    return {
        "id": msg.id,
        "event": "message.publish",
        "clientid": msg.from_,
        "username": msg.headers.get("username"),
        "payload": msg.payload,
        "peerhost": (msg.headers.get("peername") or "").rsplit(":", 1)[0],
        "topic": msg.topic,
        "qos": msg.qos,
        "flags": dict(msg.flags),
        "retain": 1 if msg.retain else 0,
        "pub_props": props,
        "timestamp": msg.timestamp,
        "publish_received_at": msg.timestamp,
        "node": node,
    }


def event_columns(event: str, args: tuple, node: str = "") -> dict[str, Any]:
    """Columns for the $events/* hookpoints; ``args`` are the hook args
    as fired by the broker."""
    ts = time.time_ns() // 1_000_000
    base = {"event": event, "timestamp": ts, "node": node}
    if event == "client.connected":
        ci = args[0]
        return {**base,
                "clientid": getattr(ci, "clientid", None),
                "username": getattr(ci, "username", None),
                "keepalive": getattr(ci, "keepalive", 0),
                "proto_ver": getattr(ci, "proto_ver", 0),
                "peername": getattr(ci, "peername", ""),
                "clean_start": getattr(ci, "clean_start", True),
                "connected_at": getattr(ci, "connected_at", ts)}
    if event == "client.disconnected":
        ci, reason = args[0], args[1] if len(args) > 1 else "normal"
        return {**base,
                "clientid": getattr(ci, "clientid", None),
                "username": getattr(ci, "username", None),
                "reason": reason,
                "disconnected_at": ts}
    if event in ("session.subscribed", "session.unsubscribed"):
        sid, topic = args[0], args[1]
        opts = args[2] if len(args) > 2 else None
        return {**base, "clientid": sid, "topic": topic,
                "qos": getattr(opts, "qos", 0)}
    if event == "message.delivered":
        cid, topic = args[0], args[1]
        return {**base, "clientid": cid, "topic": topic}
    if event == "message.acked":
        cid, packet_id = args[0], args[1]
        return {**base, "clientid": cid, "packet_id": packet_id}
    if event == "message.dropped":
        msg, reason = args[0], args[1] if len(args) > 1 else "unknown"
        cols = message_columns(msg, node)
        return {**cols, "event": "message.dropped", "reason": reason}
    return {**base, "args": list(map(str, args))}
