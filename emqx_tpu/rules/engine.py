"""Rule engine — parity with
``apps/emqx_rule_engine/src/emqx_rule_engine.erl`` +
``emqx_rule_actions.erl``.

Rules = SQL + ordered actions, keyed by id. FROM topics split into the
message.publish path (topic-filter matched per publish,
emqx_rule_engine.erl:198-205's topic index) and $events/* hookpoints.
Actions: ``republish`` (topic/payload/qos templates with ``${var}``
placeholders — emqx_plugin_libs_rule's preproc_tmpl), ``console``, and
registered custom functions (the bridge seam). Per-rule counters ride a
MetricsWorker ('matched'/'passed'/'failed'/'actions.success'/...).
"""

from __future__ import annotations

import logging
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Message
from emqx_tpu.observe.metrics import MetricsWorker
from emqx_tpu.router.trie import Trie
from emqx_tpu.rules import events as EV
from emqx_tpu.rules import funcs as rule_funcs
from emqx_tpu.rules.runtime import apply_select, eval_expr
from emqx_tpu.rules.sqlparser import Select, parse

log = logging.getLogger("emqx_tpu.rules")

_TMPL = re.compile(r"\$\{([^}]+)\}")

RULE_COUNTERS = ["matched", "passed", "failed", "failed.exception",
                 "failed.no_result", "actions.total", "actions.success",
                 "actions.failed"]

# message-plane event topics: their hookpoints (message.delivered/acked/
# dropped) fire per-delivery on the Python path only — a subscribed rule
# is incompatible with native fast-path delivery for ANY topic
MESSAGE_EVENT_TOPICS = frozenset((
    "$events/message_delivered", "$events/message_acked",
    "$events/message_dropped"))


def render_template(tmpl: str, columns: dict) -> str:
    """${a.b} placeholder substitution (preproc_tmpl/proc_tmpl)."""
    from emqx_tpu.rules.funcs import _str

    def sub(m):
        val = eval_expr(("var", m.group(1).split(".")), columns)
        if isinstance(val, (dict, list)):
            import json
            return json.dumps(val, separators=(",", ":"))
        return _str(val)

    return _TMPL.sub(sub, tmpl)


@dataclass
class Rule:
    id: str
    sql: str
    select: Select
    actions: list = field(default_factory=list)
    enabled: bool = True
    description: str = ""
    # split FROM list
    publish_topics: list[str] = field(default_factory=list)
    event_topics: list[str] = field(default_factory=list)


class RuleEngine:
    def __init__(self, node: str = "node1",
                 publish_fn: Optional[Callable[[Message], None]] = None
                 ) -> None:
        self.node = node
        self.publish_fn = publish_fn
        self.rules: dict[str, Rule] = {}
        self.metrics = MetricsWorker()
        self._action_types: dict[str, Callable] = {
            "republish": self._act_republish,
            "console": self._act_console,
        }
        self._console_out: list[dict] = []       # console sink (tests/CLI)
        self._hooked: Optional[Hooks] = None
        # fired after every create/delete — the native host flushes its
        # publish permits here so a new rule is seen by topics that were
        # already fast-pathing (broker/native_server.py)
        self.on_topology_change: list = []
        # topic index over rule FROM filters: per-publish rule lookup is
        # O(matched filters), not O(rules) — the emqx_rule_engine.erl
        # :198-205 topic-index semantics (host side); with a RouterModel
        # attached the same filters also co-batch into the device trie
        # (BASELINE config 5) and arrive pre-matched via on_matched
        self._pub_trie = Trie()
        self._filter_rules: dict[str, set[str]] = {}   # filter → rule ids
        # guards the trie + filter index: create/delete arrive on REST
        # threads while rules_for_topic runs on every publish (broker
        # poll thread / pipeline flusher) — an unguarded trie walk over
        # a mutating dict tree can raise mid-match
        self._index_lock = threading.RLock()
        self._model = None                             # RouterModel | None
        # device co-batch gate: while the broker folds a device batch's
        # message.publish hooks ON THIS THREAD, _on_publish defers to
        # on_matched. Thread-local state (not a message header): a header
        # would leak into copies other hooks store (e.g. the delayed
        # queue) and silently suppress rules on their later republish
        self._gate = threading.local()

    # -- rule CRUD (emqx_rule_engine API) -----------------------------------

    def create_rule(self, id: str, sql: str, actions: list,
                    enabled: bool = True, description: str = "") -> Rule:
        select = parse(sql)
        publish_topics, event_topics = [], []
        for t in select.topics:
            if t in EV.EVENT_TOPICS:
                event_topics.append(t)
            elif t.startswith("$events/"):
                raise ValueError(f"unknown event topic {t!r}")
            else:
                if not T.validate_filter(t):
                    # reject BEFORE any state mutates — a late failure in
                    # _index (device aux_register) would leave a
                    # half-registered rule
                    raise ValueError(f"invalid topic filter {t!r}")
                publish_topics.append(t)
        rule = Rule(id=id, sql=sql, select=select, actions=list(actions),
                    enabled=enabled, description=description,
                    publish_topics=publish_topics,
                    event_topics=event_topics)
        with self._index_lock:
            # replacement is atomic under the lock: a publish matching
            # between unindex(old) and index(new) would otherwise see
            # NO rule for a filter both versions share
            if id in self.rules:
                self._unindex(self.rules[id])
            self.rules[id] = rule
            self._index(rule)
        self.metrics.create_metrics(id, RULE_COUNTERS)
        for cb in self.on_topology_change:
            cb()
        return rule

    def delete_rule(self, id: str) -> bool:
        self.metrics.clear_metrics(id)
        rule_funcs.drop_rule_store(id)
        with self._index_lock:
            rule = self.rules.pop(id, None)
            if rule is not None:
                self._unindex(rule)
        if rule is not None:
            for cb in self.on_topology_change:
                cb()
        return rule is not None

    def _index(self, rule: Rule) -> None:
        with self._index_lock:
            for f in rule.publish_topics:
                rids = self._filter_rules.setdefault(f, set())
                if not rids:
                    self._pub_trie.insert(f)
                    if self._model is not None:
                        self._model.aux_register(f)
                rids.add(rule.id)

    def _unindex(self, rule: Rule) -> None:
        with self._index_lock:
            for f in rule.publish_topics:
                rids = self._filter_rules.get(f)
                if rids is None:
                    continue
                rids.discard(rule.id)
                if not rids:
                    del self._filter_rules[f]
                    self._pub_trie.delete(f)
                    if self._model is not None:
                        self._model.aux_release(f)

    def attach_model(self, model) -> None:
        """Co-batch rule FROM filters into the device router's trie
        (publish_batch then reports rule matches alongside fan-out —
        BASELINE config 5)."""
        with self._index_lock:        # uniform guard for _filter_rules
            self._model = model
            for f in self._filter_rules:
                model.aux_register(f)

    def get_rule(self, id: str) -> Optional[Rule]:
        return self.rules.get(id)

    def list_rules(self) -> list[Rule]:
        return list(self.rules.values())

    def register_action(self, name: str, fn: Callable) -> None:
        """Custom action type (the bridge seam): fn(columns, args)."""
        self._action_types[name] = fn

    def unregister_action(self, name: str) -> None:
        self._action_types.pop(name, None)

    # -- hook wiring --------------------------------------------------------

    def attach(self, hooks: Hooks) -> None:
        self._hooked = hooks
        hooks.add("message.publish", self._on_publish, priority=-50)
        for event_topic, hookpoint in EV.EVENT_TOPICS.items():
            if hookpoint == "message.publish":
                continue
            hooks.add(hookpoint, self._make_event_cb(event_topic),
                      priority=-50)

    def _make_event_cb(self, event_topic: str):
        hookpoint = EV.EVENT_TOPICS[event_topic]

        def cb(*args):
            with self._index_lock:    # snapshot: REST threads mutate
                rules = list(self.rules.values())
            for rule in rules:
                if rule.enabled and event_topic in rule.event_topics:
                    cols = EV.event_columns(hookpoint, args, self.node)
                    self._apply_rule(rule, cols)
            return None
        return cb

    # -- the publish path ----------------------------------------------------

    def publish_filters(self) -> list[str]:
        """Every live FROM topic filter (enabled and disabled rules
        alike — enablement is re-checked at fire time). The native
        server mirrors these into the C++ table as rule-tap entries
        (broker/native_server._sync_rule_taps)."""
        with self._index_lock:
            return list(self._filter_rules.keys())

    def watches_message_events(self) -> bool:
        """True while any enabled rule consumes message-plane events
        ($events/message_delivered / _acked / _dropped). Those
        hookpoints fire only on the Python delivery path, so the native
        fast path must not carry ANY topic while such a rule exists —
        its deliveries/acks/drops would silently never reach the rule
        (broker/native_server._slow_consumers_watch). A live scan, not
        a cached count: callers (and tests) flip rule.enabled in place,
        which _make_event_cb honours dynamically — the permit gate must
        see the same state. The grant loop hoists this call out of its
        per-topic work, so O(rules) runs once per grant cycle."""
        with self._index_lock:
            return any(r.enabled
                       and any(t in MESSAGE_EVENT_TOPICS
                               for t in r.event_topics)
                       for r in self.rules.values())

    def rules_for_topic(self, topic: str) -> list[Rule]:
        """Trie-indexed lookup: O(matched filters), not O(rules)
        (emqx_rule_engine.erl:198-205 get_rules_for_topic)."""
        with self._index_lock:
            return self._rules_of(self._pub_trie.match(topic))

    def _rules_of(self, filters) -> list[Rule]:
        out: list[Rule] = []
        seen: set[str] = set()
        for f in filters:
            for rid in self._filter_rules.get(f, ()):
                if rid not in seen:
                    seen.add(rid)
                    rule = self.rules.get(rid)
                    if rule is not None and rule.enabled:
                        out.append(rule)
        return out

    def ingest(self, msg: Message) -> None:
        """Feed a non-broker message into rule matching — the bridge
        ingress hook-topic path ('$bridges/...', emqx_rule_events.erl:145)
        where rules fire without a broker publish."""
        self._on_publish(msg)

    def publish_gate(self, on: bool) -> None:
        """broker.publish_batch brackets its hook fold with this so the
        kernel (not the hook) does the matching for batched messages."""
        self._gate.on = on

    def _on_publish(self, msg: Message, *rest):
        if msg.topic.startswith("$SYS/"):
            return None
        if self._model is not None and getattr(self._gate, "on", False):
            # device batch in flight: the kernel matches this topic
            # against the co-batched rule filters; the broker hands the
            # result to on_matched — no second trie walk here
            return None
        self._fire(msg, self.rules_for_topic(msg.topic))
        return None

    def on_matched(self, msg: Message, matched_filters) -> None:
        """Device co-batch sink (broker.rules_matched_fn): the kernel
        already matched ``msg.topic`` against the shared trie;
        ``matched_filters`` maps to rules with dict lookups only.
        ``None`` means the topic took the host-oracle fallback — match
        on the host trie instead."""
        if msg.topic.startswith("$SYS/"):
            return
        if matched_filters is None:
            rules = self.rules_for_topic(msg.topic)
        else:
            rules = self._rules_of(matched_filters)
        self._fire(msg, rules)

    def _fire(self, msg: Message, rules: list[Rule]) -> None:
        if not rules:
            return
        cols = EV.message_columns(msg, self.node)
        loop_guard = msg.headers.get("republish_by")
        for rule in rules:
            if rule.id == loop_guard:
                continue          # a rule never re-fires on its own
            self._apply_rule(rule, cols)

    # -- evaluation (emqx_rule_runtime:apply_rules) --------------------------

    def _apply_rule(self, rule: Rule, columns: dict) -> None:
        self.metrics.inc(rule.id, "matched")
        # kv_store_*/proc_dict_* funcs are scoped per rule (reference:
        # the rule worker's process dictionary); the contextvar tells
        # them whose store is active
        ctx_token = rule_funcs.set_rule_context(rule.id)
        try:
            try:
                results = apply_select(rule.select, columns)
            except Exception:
                log.exception("rule %s SQL failed", rule.id)
                self.metrics.inc(rule.id, "failed")
                self.metrics.inc(rule.id, "failed.exception")
                return
            if results is None:
                self.metrics.inc(rule.id, "failed")
                self.metrics.inc(rule.id, "failed.no_result")
                return
            self.metrics.inc(rule.id, "passed")
            for res in results:
                for action in rule.actions:
                    self._run_action(rule, action, res)
        finally:
            rule_funcs.reset_rule_context(ctx_token)

    def _run_action(self, rule: Rule, action: dict, columns: dict) -> None:
        self.metrics.inc(rule.id, "actions.total")
        fn = self._action_types.get(action.get("function", "console"))
        try:
            if fn is None:
                raise ValueError(
                    f"unknown action {action.get('function')!r}")
            fn({**columns, "__rule_id": rule.id},
               action.get("args") or {})
            self.metrics.inc(rule.id, "actions.success")
        except Exception:
            log.exception("rule %s action failed", rule.id)
            self.metrics.inc(rule.id, "actions.failed")

    # -- builtin actions ----------------------------------------------------

    def _act_republish(self, columns: dict, args: dict) -> None:
        if self.publish_fn is None:
            raise RuntimeError("republish: no publish_fn wired")
        topic = render_template(args.get("topic", "${topic}"), columns)
        payload = render_template(
            args.get("payload", "${payload}"), columns)
        qos_t = args.get("qos", 0)
        qos = (int(render_template(str(qos_t), columns))
               if isinstance(qos_t, str) else int(qos_t))
        retain = bool(args.get("retain", False))
        self.publish_fn(Message(
            topic=topic, payload=payload.encode(), qos=qos,
            from_=str(columns.get("clientid") or "rule_engine"),
            flags={"retain": retain},
            headers={"republish_by": columns.get("__rule_id"),
                     "properties": {}},
        ))

    def _act_console(self, columns: dict, args: dict) -> None:
        out = {k: v for k, v in columns.items() if not k.startswith("__")}
        self._console_out.append(out)
        del self._console_out[:-200]
        log.info("rule %s console: %s", columns.get("__rule_id"), out)

    # -- SQL test API (emqx_rule_sqltester) ---------------------------------

    def test_sql(self, sql: str, context: dict) -> Optional[list[dict]]:
        """Dry-run a SQL statement against a sample context (the
        dashboard's rule tester)."""
        sel = parse(sql)
        return apply_select(sel, context)
