"""TrieIndex — the level-packed, device-resident form of the wildcard trie.

This is the TPU-era answer to ``emqx_trie.erl``'s ETS ordered_set walk
(emqx_trie.erl:282-344): instead of one ETS lookup per topic level per
message, the whole trie lives in HBM as flat int32 arrays and a *batch* of
topics is matched per kernel launch (emqx_tpu.ops.trie_match).

Layout
------
Nodes are integer ids (root = 0). Per node:

- ``plus_child[n]``  child via a ``+`` edge, -1 if none
- ``hash_fid[n]``    filter id of the ``prefix/#`` filter hanging under n
                     (``#`` is always terminal, so the '#' child is folded
                     into its parent as a filter id), -1 if none
- ``node_fid[n]``    filter id of a filter ending exactly at n, -1 if none

Exact (non-wildcard) edges live in one open-addressed hash table keyed by
``(parent_node, word_id)``:

- ``ht_parent[s] / ht_word[s] / ht_child[s]`` with -1 marking empty slots;
  linear probing, builder-verified max probe length ≤ ``max_probes`` (the
  table is grown until that bound holds, so the device probe loop is a
  *static* unrolled bound).

Words are interned host-side: PAD=0 (beyond end of topic), PLUS=1, HASH=2,
UNK=3 (topic word never seen in any filter — can only match wildcards),
real words ≥ 4. Wildcard ids never appear as hash-table keys, which is what
makes the device walk agree with the host oracle on degenerate topics
containing literal '+'/'#'.

Match-uniqueness invariant (why the kernel needs no dedup): a filter is
emitted either as ``hash_fid`` at exactly one (node, depth) or as
``node_fid`` at exactly one node at end-of-topic; trie nodes are a tree, so
a frontier never contains the same node twice ⇒ every matching filter id is
emitted exactly once per topic.

Incremental maintenance (emqx_trie.erl:113-144 — O(topic-depth) insert
and delete, the BASELINE.json north-star sentence)
---------------------------------------------------------------------
The numpy arrays ARE the trie: ``insert``/``delete`` walk them directly
and patch in place —

- insert appends nodes into pre-allocated capacity (arrays are built
  with ~1.5× headroom and every slot pre-initialised to -1, so a fresh
  node needs **no** device write), claims free edge-table slots within
  the probe bound, and sets the terminal fid;
- delete clears the terminal fid only.  Edges/nodes of dead paths stay
  as garbage until the next compaction — they match nothing (fid = -1)
  and removing them eagerly would need probe-chain repair.  ``garbage``
  counts them so the owner can ``rebuild()`` opportunistically.

Every patched index is recorded in ``pending`` (array-name → dirty
indices); the device owner (models.RouterModel) drains it and scatters
just those elements into HBM with a donated jit — subscribe→routable is
O(topic-depth), not O(table).  Structural growth (node capacity, edge
load > 50%, probe-bound overflow) flips ``needs_rebuild`` and the next
``ensure()`` does a double-buffered full rebuild with fresh headroom.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from emqx_tpu.core import topic as T

PAD = 0
PLUS_ID = 1
HASH_ID = 2
UNK = 3
FIRST_WORD_ID = 4

_MIX_A = np.uint32(0x9E3779B1)
_MIX_B = np.uint32(0x85EBCA77)


def edge_hash(parent: np.ndarray, word: np.ndarray, mask: int) -> np.ndarray:
    """Slot hash for the (parent, word) edge key — same formula on host
    (builder) and device (prober); uint32 wraparound arithmetic."""
    with np.errstate(over="ignore"):  # uint32 wraparound is the point
        p = parent.astype(np.uint32) * _MIX_A
        w = word.astype(np.uint32) * _MIX_B
        h = p ^ w
        h ^= h >> np.uint32(15)
        h *= np.uint32(0x2C1B3C6D)
        h ^= h >> np.uint32(12)
        return (h & np.uint32(mask)).astype(np.int32)


def edge_step(parent: np.ndarray, word: np.ndarray, mask: int) -> np.ndarray:
    """Double-hashing probe stride for the edge key (odd → coprime with
    the pow2 table, so the sequence visits distinct slots). Linear
    probing's primary clustering made >8-probe chains common enough at
    tens of millions of edges to force table doublings (r2's 10M build
    grew the table 4×); per-key strides keep the probe bound honest at
    4× load. Must match the device prober (ops/trie_match.py)."""
    with np.errstate(over="ignore"):
        p = parent.astype(np.uint32) * np.uint32(0xC2B2AE3D)
        w = word.astype(np.uint32) * np.uint32(0x27D4EB2F)
        h = p ^ w
        h ^= h >> np.uint32(13)
        h *= np.uint32(0x165667B1)
        h ^= h >> np.uint32(16)
        return ((h | np.uint32(1)) & np.uint32(mask)).astype(np.int32)


@dataclass
class TrieIndexArrays:
    """The device-side arrays (numpy here; moved to HBM by the matcher).

    Arrays are allocated at CAPACITY (≥ live size) so in-place appends
    need no realloc; ``n_nodes`` is the live node count."""

    ht_parent: np.ndarray
    ht_word: np.ndarray
    ht_child: np.ndarray
    plus_child: np.ndarray
    hash_fid: np.ndarray
    node_fid: np.ndarray
    n_nodes: int
    n_filters: int
    max_probes: int


class TrieIndex:
    """Host-side builder + incremental maintainer: filters → interned
    vocab + flat trie arrays, patched in place per mutation (see module
    docstring)."""

    def __init__(self, max_levels: int = 16, max_probes: int = 8) -> None:
        self.max_levels = max_levels
        self.max_probes = max_probes
        # minimum edge-table size for the next rebuild.  The sharded
        # wrapper (ShardedTrieIndex) raises this so every shard's table
        # is the SAME pow2 — the device stacks shards into one [S, H]
        # buffer and the probe mask (H-1) must hold per shard.
        self.ht_size_floor = 64
        self.vocab: dict[str, int] = {}
        self.filters: list[Optional[str]] = []   # fid -> filter string
        self._filter_ids: dict[str, int] = {}
        self._free_fids: list[int] = []
        # fid-reuse quarantine: while any publish batch is in flight
        # (submitted, not yet decoded), freed fids must NOT be reused —
        # the in-flight results reference them, and a reuse would decode
        # a stale match as the NEW filter (wrong-subscriber delivery).
        # RouterModel brackets submit/collect with begin/end_inflight.
        self._inflight = 0
        self._quarantined_fids: list[int] = []
        self.arrays: Optional[TrieIndexArrays] = None
        self.n_nodes = 0
        self.n_edges = 0
        self.garbage = 0          # deletes since last rebuild (dead paths)
        self.needs_rebuild = True
        self.rebuild_count = 0    # observability + test hook
        # array-name → set of dirty indices awaiting device scatter
        self.pending: dict[str, set[int]] = {
            "ht_parent": set(), "ht_word": set(), "ht_child": set(),
            "plus_child": set(), "hash_fid": set(), "node_fid": set(),
        }

    # -- vocab -------------------------------------------------------------

    def intern(self, word: str) -> int:
        wid = self.vocab.get(word)
        if wid is None:
            wid = FIRST_WORD_ID + len(self.vocab)
            self.vocab[word] = wid
        return wid

    def word_id(self, word: str) -> int:
        if word == T.PLUS:
            return PLUS_ID
        if word == T.HASH:
            return HASH_ID
        return self.vocab.get(word, UNK)

    # -- filter set mutation ----------------------------------------------

    def fid_of(self, filt: str) -> Optional[int]:
        return self._filter_ids.get(filt)

    def insert(self, filt: str) -> int:
        """Register a filter, return its stable fid.  O(topic-depth)
        in-place patch unless a rebuild is already pending."""
        if not T.validate_filter(filt):
            # same guard as Router.add_route: an invalid filter (e.g.
            # 'a/#/b') would be silently truncated at '#' by rebuild() and
            # diverge from the host oracle
            raise ValueError(f"invalid topic filter: {filt!r}")
        fid = self._filter_ids.get(filt)
        if fid is not None:
            return fid
        if self._free_fids:
            fid = self._free_fids.pop()
            self.filters[fid] = filt
        else:
            fid = len(self.filters)
            self.filters.append(filt)
        self._filter_ids[filt] = fid
        if not self.needs_rebuild and self.arrays is not None:
            self._insert_arrays(filt, fid)
        else:
            self.needs_rebuild = True
            for w in T.words(filt):
                if w not in (T.PLUS, T.HASH):
                    self.intern(w)
        return fid

    def begin_inflight(self) -> None:
        self._inflight += 1

    def end_inflight(self) -> None:
        self._inflight -= 1
        if self._inflight <= 0:
            self._inflight = 0
            if self._quarantined_fids:
                self._free_fids.extend(self._quarantined_fids)
                self._quarantined_fids.clear()

    def delete(self, filt: str) -> Optional[int]:
        fid = self._filter_ids.pop(filt, None)
        if fid is None:
            return None
        self.filters[fid] = None
        (self._quarantined_fids if self._inflight
         else self._free_fids).append(fid)
        if not self.needs_rebuild and self.arrays is not None:
            self._delete_arrays(filt, fid)
            self.garbage += 1
        return fid

    def load(self, filters: Sequence[str]) -> None:
        for f in filters:
            self.insert(f)

    # -- incremental array patching ---------------------------------------

    def _mark(self, name: str, idx: int) -> None:
        self.pending[name].add(idx)

    def _new_node(self) -> Optional[int]:
        a = self.arrays
        if self.n_nodes >= a.plus_child.shape[0]:
            self.needs_rebuild = True
            return None
        idx = self.n_nodes
        self.n_nodes = idx + 1
        a.n_nodes = self.n_nodes
        # plus/hash/node entries are pre-initialised -1 on host AND
        # device, so a fresh node costs zero writes
        return idx

    def _ht_find(self, parent: int, wid: int
                 ) -> tuple[Optional[int], Optional[int]]:
        """(child, free_slot): child if the edge exists, else the first
        free slot within the probe bound (None, None = no room)."""
        a = self.arrays
        mask = a.ht_parent.shape[0] - 1
        slot = int(edge_hash(np.int32(parent), np.int32(wid), mask))
        step = int(edge_step(np.int32(parent), np.int32(wid), mask))
        for p in range(self.max_probes):
            s = (slot + p * step) & mask
            sp = int(a.ht_parent[s])
            if sp == -1:
                return None, s
            if sp == parent and int(a.ht_word[s]) == wid:
                return int(a.ht_child[s]), None
        return None, None

    def _insert_arrays(self, filt: str, fid: int) -> None:
        a = self.arrays
        node = 0
        for w in T.words(filt):
            if w == T.HASH:           # '#' is terminal: fold to parent
                a.hash_fid[node] = fid
                self._mark("hash_fid", node)
                a.n_filters = len(self.filters)
                return
            if w == T.PLUS:
                c = int(a.plus_child[node])
                if c == -1:
                    c = self._new_node()
                    if c is None:
                        return              # rebuild pending
                    a.plus_child[node] = c
                    self._mark("plus_child", node)
                node = c
            else:
                wid = self.intern(w)
                child, free = self._ht_find(node, wid)
                if child is None:
                    c = self._new_node()
                    if c is None:
                        return
                    if free is None:        # probe bound full here
                        self.needs_rebuild = True
                        return
                    a.ht_parent[free] = node
                    a.ht_word[free] = wid
                    a.ht_child[free] = c
                    for nm in ("ht_parent", "ht_word", "ht_child"):
                        self._mark(nm, free)
                    self.n_edges += 1
                    if 2 * self.n_edges > a.ht_parent.shape[0]:
                        # >50% load: grow at the NEXT ensure(); this
                        # insert itself is already placed and valid
                        self.needs_rebuild = True
                    node = c
                else:
                    node = child
        a.node_fid[node] = fid
        self._mark("node_fid", node)
        a.n_filters = len(self.filters)

    def _delete_arrays(self, filt: str, fid: int) -> None:
        a = self.arrays
        node = 0
        for w in T.words(filt):
            if w == T.HASH:
                if int(a.hash_fid[node]) == fid:
                    a.hash_fid[node] = -1
                    self._mark("hash_fid", node)
                return
            if w == T.PLUS:
                node = int(a.plus_child[node])
            else:
                wid = self.vocab.get(w)
                if wid is None:
                    return                  # never inserted ⇒ no-op
                node, _ = self._ht_find(node, wid)  # type: ignore
            if node is None or node < 0:
                return                      # path absent (defensive)
        if int(a.node_fid[node]) == fid:
            a.node_fid[node] = -1
            self._mark("node_fid", node)

    def drain_updates(self) -> dict[str, list[int]]:
        """Dirty indices per array since the last drain (values live in
        ``self.arrays``); clears the pending sets."""
        out = {k: sorted(v) for k, v in self.pending.items() if v}
        for v in self.pending.values():
            v.clear()
        return out

    # -- build -------------------------------------------------------------

    # above this many live filters the vectorized builder wins (the
    # python pointer-trie walk costs ~100s/1M filters; the numpy
    # level-synchronous build is ~20× faster and is what makes the
    # BASELINE config-3 cold start (10M filters) feasible)
    VECTOR_BUILD_MIN = 50_000

    def rebuild(self) -> TrieIndexArrays:
        """Double-buffered full rebuild: one pass over filters → fresh
        flat arrays with ~1.5× node headroom and ≤25% edge-table load
        (so the next growth rebuild is a long way off)."""
        n_live = sum(1 for f in self.filters if f is not None)
        if n_live >= self.VECTOR_BUILD_MIN:
            return self._rebuild_vectorized()
        return self._rebuild_scalar()

    def _rebuild_scalar(self) -> TrieIndexArrays:
        # 1. build a pointer trie over word ids
        children: list[dict[int, int]] = [{}]   # node -> {word_id: child}
        plus: list[int] = [-1]
        hashf: list[int] = [-1]
        nodef: list[int] = [-1]

        def new_node() -> int:
            children.append({})
            plus.append(-1)
            hashf.append(-1)
            nodef.append(-1)
            return len(children) - 1

        n_edges = 0
        for fid, filt in enumerate(self.filters):
            if filt is None:
                continue
            node = 0
            ws = T.words(filt)
            for i, w in enumerate(ws):
                if w == T.HASH:
                    hashf[node] = fid        # '#' is terminal: fold to parent
                    break
                if w == T.PLUS:
                    if plus[node] == -1:
                        plus[node] = new_node()
                    node = plus[node]
                else:
                    wid = self.intern(w)
                    nxt = children[node].get(wid)
                    if nxt is None:
                        nxt = new_node()
                        children[node][wid] = nxt
                        n_edges += 1
                    node = nxt
            else:
                nodef[node] = fid
        n_nodes = len(children)
        cap = 64
        while cap < n_nodes + n_nodes // 2:
            cap *= 2

        # 2. open-addressed edge table, grown until probe bound holds
        size = max(64, self.ht_size_floor)
        while size < 4 * max(1, n_edges):
            size *= 2
        while True:
            ht_parent = np.full(size, -1, np.int32)
            ht_word = np.full(size, -1, np.int32)
            ht_child = np.full(size, -1, np.int32)
            mask = size - 1
            ok = True
            for parent, edges in enumerate(children):
                for wid, child in edges.items():
                    slot = int(edge_hash(np.int32(parent), np.int32(wid), mask))
                    step = int(edge_step(np.int32(parent), np.int32(wid),
                                         mask))
                    for probe in range(self.max_probes):
                        s = (slot + probe * step) & mask
                        if ht_parent[s] == -1:
                            ht_parent[s] = parent
                            ht_word[s] = wid
                            ht_child[s] = child
                            break
                    else:
                        ok = False
                        break
                if not ok:
                    break
            if ok:
                break
            size *= 2

        def padded(src: list[int]) -> np.ndarray:
            out = np.full(cap, -1, np.int32)
            out[:n_nodes] = src
            return out

        self.arrays = TrieIndexArrays(
            ht_parent=ht_parent,
            ht_word=ht_word,
            ht_child=ht_child,
            plus_child=padded(plus),
            hash_fid=padded(hashf),
            node_fid=padded(nodef),
            n_nodes=n_nodes,
            n_filters=len(self.filters),
            max_probes=self.max_probes,
        )
        self.n_nodes = n_nodes
        self.n_edges = n_edges
        self.garbage = 0
        self.needs_rebuild = False
        self.rebuild_count += 1
        for v in self.pending.values():      # superseded by the rebuild
            v.clear()
        return self.arrays

    def _rebuild_vectorized(self) -> TrieIndexArrays:
        """Numpy level-synchronous trie build (same result as the scalar
        builder, ~20× faster at millions of filters).

        All filters advance one topic level per iteration, so every
        (parent, word) pair seen at iteration *i* keys a depth-*i* node;
        ``np.unique`` over the pair set mints the level's node ids in one
        shot.  The edge table fills with vectorized probe rounds: each
        round places every still-unplaced edge whose probe slot is free,
        first-come-per-slot arbitration via ``np.unique(return_index)``.
        """
        live_fids = np.asarray(
            [fid for fid, f in enumerate(self.filters) if f is not None],
            np.int64)
        word_lists = [T.words(self.filters[f]) for f in live_fids]
        L = self.max_levels
        # intern new words through the existing vocab (ids must stay
        # stable — tokenize depends on them); dict-dedupe + sorted for a
        # deterministic id order (an object-dtype np.unique here cost a
        # 30s python-string sort at 2M filters)
        fresh = {w for ws in word_lists for w in ws
                 if w not in (T.PLUS, T.HASH) and w not in self.vocab}
        for w in sorted(fresh):
            self.intern(w)
        F = len(live_fids)
        toks = np.full((F, max(1, L)), -1, np.int64)
        lengths = np.fromiter(map(len, word_lists), np.int64, F)
        # validate_filter guarantees '#' is only ever the LAST word, so
        # hash detection is a tail check, not a scan
        has_hash_l = np.fromiter(
            (1 if ws and ws[-1] == T.HASH else 0 for ws in word_lists),
            np.int64, F)
        hash_pos = np.where(has_hash_l == 1, lengths - 1, -np.int64(1))
        eff_len = np.where(hash_pos >= 0, hash_pos, lengths)
        # scatter the (depth-clipped) token ids in one shot
        clip = np.minimum(eff_len, L)
        vocab = self.vocab
        flat_ids = np.fromiter(
            (PLUS_ID if w == T.PLUS else vocab[w]
             for ws, n in zip(word_lists, clip.tolist())
             for w in ws[:n]),
            np.int64)
        rows = np.repeat(np.arange(F), clip)
        ends = np.cumsum(clip)
        cols = np.arange(len(flat_ids)) - np.repeat(ends - clip, clip)
        toks[rows, cols] = flat_ids

        cur = np.zeros(F, np.int64)           # current node per filter
        n_nodes = 1
        plus_edges: list[tuple[np.ndarray, np.ndarray]] = []
        exact_edges: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        for i in range(L):
            act = eff_len > i
            if not act.any():
                break
            pa, wi = cur[act], toks[act, i]
            keys = pa * (len(vocab) + FIRST_WORD_ID + 2) + wi
            uniq, inv = np.unique(keys, return_inverse=True)
            child = n_nodes + np.arange(len(uniq))
            n_nodes += len(uniq)
            # representative (parent, word) per unique key
            first = np.full(len(uniq), -1, np.int64)
            first[inv[::-1]] = np.arange(len(pa))[::-1]   # first index
            rp, rw, rc = pa[first], wi[first], child
            isplus = rw == PLUS_ID
            plus_edges.append((rp[isplus], rc[isplus]))
            exact_edges.append((rp[~isplus], rw[~isplus], rc[~isplus]))
            cur[act] = child[inv]

        cap = 64
        while cap < n_nodes + n_nodes // 2:
            cap *= 2
        plus_child = np.full(cap, -1, np.int32)
        hash_fid = np.full(cap, -1, np.int32)
        node_fid = np.full(cap, -1, np.int32)
        for rp, rc in plus_edges:
            plus_child[rp] = rc
        # terminals beyond depth L are unreachable from the device matcher
        # (topics deeper than max_levels take the host-oracle fallback in
        # tokenize()), so — like the scalar builder's deeper-than-L nodes —
        # they are simply not marked; marking them at the truncated depth-L
        # node would create FALSE matches for depth-L topics
        has_hash = (hash_pos >= 0) & (hash_pos <= L)
        hash_fid[cur[has_hash]] = live_fids[has_hash]
        ends = (hash_pos < 0) & (lengths <= L)
        node_fid[cur[ends]] = live_fids[ends]

        ep = np.concatenate([e[0] for e in exact_edges]) \
            if exact_edges else np.zeros(0, np.int64)
        ew = np.concatenate([e[1] for e in exact_edges]) \
            if exact_edges else np.zeros(0, np.int64)
        ec = np.concatenate([e[2] for e in exact_edges]) \
            if exact_edges else np.zeros(0, np.int64)
        n_edges = len(ep)

        size = max(64, self.ht_size_floor)
        while size < 4 * max(1, n_edges):
            size *= 2
        while True:
            ht_parent = np.full(size, -1, np.int32)
            ht_word = np.full(size, -1, np.int32)
            ht_child = np.full(size, -1, np.int32)
            mask = size - 1
            home = edge_hash(ep.astype(np.int32), ew.astype(np.int32),
                             mask).astype(np.int64)
            stride = edge_step(ep.astype(np.int32), ew.astype(np.int32),
                               mask).astype(np.int64)
            unplaced = np.arange(n_edges)
            for probe in range(self.max_probes):
                if len(unplaced) == 0:
                    break
                s = (home[unplaced] + probe * stride[unplaced]) & mask
                free = ht_parent[s] == -1
                cand = unplaced[free]
                cs = s[free]
                # first-come-per-slot: np.unique picks one winner per slot
                uslot, first_idx = np.unique(cs, return_index=True)
                winners = cand[first_idx]
                ht_parent[uslot] = ep[winners]
                ht_word[uslot] = ew[winners]
                ht_child[uslot] = ec[winners]
                placed = np.zeros(len(unplaced), bool)
                # a candidate is placed iff its slot now holds its own
                # child id (child ids are unique per edge, so equality
                # identifies the winner; losers retry at the next probe)
                placed[free] = ht_child[cs] == ec[cand]
                unplaced = unplaced[~placed]
            if len(unplaced) and self._kick_place(
                    unplaced, ep, ew, ec, home, stride,
                    ht_parent, ht_word, ht_child, mask):
                unplaced = unplaced[:0]
            if len(unplaced) == 0:
                break
            size *= 2                     # pathological fallback only

        self.arrays = TrieIndexArrays(
            ht_parent=ht_parent, ht_word=ht_word, ht_child=ht_child,
            plus_child=plus_child, hash_fid=hash_fid, node_fid=node_fid,
            n_nodes=n_nodes, n_filters=len(self.filters),
            max_probes=self.max_probes,
        )
        self.n_nodes = n_nodes
        self.n_edges = n_edges
        self.garbage = 0
        self.needs_rebuild = False
        self.rebuild_count += 1
        for v in self.pending.values():
            v.clear()
        return self.arrays

    def _kick_place(self, unplaced, ep, ew, ec, home, stride,
                    ht_parent, ht_word, ht_child, mask) -> bool:
        """Depth-1 displacement for the rare edges whose whole probe
        window is full (expected O(n·α^max_probes) ≈ a handful at 4×
        headroom): evict one window occupant to the first EMPTY slot of
        ITS OWN probe sequence and take its place.

        Correctness of the device prober's stop-at-empty rule is
        preserved: a kick only CONSUMES empties (the vacated slot is
        immediately refilled by the stuck edge), so every key's probe
        prefix stays fully occupied. Returns False if any edge stays
        unplaceable (caller doubles the table — pathological hash
        behaviour only)."""
        for e in unplaced:
            placed = False
            for p in range(self.max_probes):
                s = int((home[e] + p * stride[e]) & mask)
                # the occupant's key is right there in the table — derive
                # its probe sequence and find an empty alternative
                op, ow = np.int32(ht_parent[s]), np.int32(ht_word[s])
                oh = int(edge_hash(op, ow, mask))
                ostep = int(edge_step(op, ow, mask))
                for p2 in range(self.max_probes):
                    s2 = (oh + p2 * ostep) & mask
                    if ht_parent[s2] == -1:
                        ht_parent[s2] = op
                        ht_word[s2] = ow
                        ht_child[s2] = ht_child[s]
                        ht_parent[s] = ep[e]
                        ht_word[s] = ew[e]
                        ht_child[s] = ec[e]
                        placed = True
                        break
                if placed:
                    break
            if not placed:
                return False
        return True

    def ensure(self) -> TrieIndexArrays:
        if self.needs_rebuild or self.arrays is None:
            return self.rebuild()
        return self.arrays

    # -- topic tokenizer ---------------------------------------------------

    def tokenize(
        self, topics: Sequence[str]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, list[int]]:
        """topics → (tokens [B,L], lengths [B], sys_flags [B], too_long).

        ``too_long`` lists batch positions whose topic exceeds max_levels —
        they must take the host-oracle fallback (mirrors the reference's
        escape hatch for pathological topics).
        """
        B, L = len(topics), self.max_levels
        tokens = np.zeros((B, L), np.int32)
        lengths = np.zeros(B, np.int32)
        sys_flags = np.zeros(B, bool)
        too_long: list[int] = []
        for b, topic in enumerate(topics):
            ws = T.words(topic)
            if len(ws) > L:
                too_long.append(b)
                # length 0 + sys flag ⇒ the kernel emits nothing for this
                # row (even root '#'/'+' which match an empty prefix);
                # caller routes it through the host oracle instead
                lengths[b] = 0
                sys_flags[b] = True
                continue
            lengths[b] = len(ws)
            sys_flags[b] = ws[0].startswith("$") if ws else False
            for i, w in enumerate(ws):
                tokens[b, i] = self.word_id(w)
        return tokens, lengths, sys_flags, too_long


# ---------------------------------------------------------------------------
# subscription-space sharding: the trie partitioned along the tp mesh axis
# ---------------------------------------------------------------------------


def shard_of_filter(filt: str, n_shards: int) -> int:
    """Stable filter → shard assignment. crc32, NOT Python hash():
    str hashing is salted per process, and the shard a filter lives in
    must survive restarts (the bench disk cache and any future
    cross-process handoff key on it)."""
    return zlib.crc32(filt.encode()) % n_shards


class _ShardedFilters:
    """Read-only fid → filter view over a ShardedTrieIndex.

    Global fids interleave the per-shard namespaces:
    ``global = local * S + shard``, so each shard's fid space grows
    independently while every global fid stays stable and decodes with
    one divmod.  Gaps (a shard shorter than the longest) read as None —
    the same convention as a freed fid in the flat TrieIndex.
    """

    def __init__(self, owner: "ShardedTrieIndex") -> None:
        self._owner = owner

    def __len__(self) -> int:
        s = self._owner.shards
        return self._owner.n_shards * max(
            (len(t.filters) for t in s), default=0)

    def __getitem__(self, g) -> Optional[str]:
        g = int(g)
        shard = g % self._owner.n_shards
        local = g // self._owner.n_shards
        fl = self._owner.shards[shard].filters
        return fl[local] if 0 <= local < len(fl) else None

    def __iter__(self):
        for g in range(len(self)):
            yield self[g]


class ShardedTrieIndex:
    """S per-shard TrieIndexes presenting one fid namespace — the
    subscription-space partition of the level-packed trie.

    Each filter lives in exactly one shard (``shard_of_filter``), each
    shard owns its own node/edge arrays, and the device stacks them
    into ``[S, ...]`` buffers sharded along the ``tp`` mesh axis
    (ops.trie_match.stacked_trie_arrays) so 10M+ filters stop being a
    single chip's HBM problem.  Invariants:

    - the word vocab is SHARED (one dict aliased into every shard):
      tokenized topics are matched against every shard, so word ids
      must agree across shards;
    - global fids are ``local * S + shard`` (see _ShardedFilters);
      the per-shard trie arrays store LOCAL fids and the device match
      translates local → global with one fused elementwise op;
    - every shard's edge table is the SAME pow2 size (``ensure``
      equalizes via ``ht_size_floor`` + rebuild) because the stacked
      [S, H] probe mask is shared;
    - an incremental insert/delete touches only the owning shard's
      arrays, and ``drain_updates`` reports (shard, index) pairs so the
      device scatter patches just that shard's slice of [S, ...].

    S = 1 degenerates to the flat layout bit-for-bit (global == local).
    """

    def __init__(self, n_shards: int, max_levels: int = 16,
                 max_probes: int = 8) -> None:
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = n_shards
        self.max_levels = max_levels
        self.max_probes = max_probes
        self.shards = [TrieIndex(max_levels, max_probes)
                       for _ in range(n_shards)]
        shared_vocab = self.shards[0].vocab
        for s in self.shards[1:]:
            s.vocab = shared_vocab
        self.vocab = shared_vocab
        self.filters = _ShardedFilters(self)

    # -- fid namespace -----------------------------------------------------

    def _shard(self, filt: str) -> int:
        return shard_of_filter(filt, self.n_shards)

    def _global(self, shard: int, local: int) -> int:
        return local * self.n_shards + shard

    def insert(self, filt: str) -> int:
        shard = self._shard(filt)
        return self._global(shard, self.shards[shard].insert(filt))

    def delete(self, filt: str) -> Optional[int]:
        shard = self._shard(filt)
        local = self.shards[shard].delete(filt)
        return None if local is None else self._global(shard, local)

    def fid_of(self, filt: str) -> Optional[int]:
        shard = self._shard(filt)
        local = self.shards[shard].fid_of(filt)
        return None if local is None else self._global(shard, local)

    def load(self, filters: Sequence[str]) -> None:
        for f in filters:
            self.insert(f)

    def begin_inflight(self) -> None:
        for s in self.shards:
            s.begin_inflight()

    def end_inflight(self) -> None:
        for s in self.shards:
            s.end_inflight()

    @property
    def _inflight(self) -> int:
        return self.shards[0]._inflight

    # -- build / maintenance ----------------------------------------------

    @property
    def needs_rebuild(self) -> bool:
        return any(s.needs_rebuild or s.arrays is None for s in self.shards)

    @property
    def rebuild_count(self) -> int:
        return sum(s.rebuild_count for s in self.shards)

    @property
    def garbage(self) -> int:
        return sum(s.garbage for s in self.shards)

    def ensure(self) -> list[TrieIndexArrays]:
        """Rebuild dirty shards, then equalize edge-table sizes: the
        stacked [S, H] device buffer shares one probe mask, so every
        shard must sit at the common (max) pow2 H."""
        for s in self.shards:
            s.ensure()
        H = max(s.arrays.ht_parent.shape[0] for s in self.shards)
        for s in self.shards:
            if s.arrays.ht_parent.shape[0] != H:
                s.ht_size_floor = H
                s.rebuild()
        return [s.arrays for s in self.shards]

    def drain_updates(self) -> dict[str, list[tuple[int, int]]]:
        """Dirty (shard, index) pairs per array since the last drain —
        the per-shard patch stream: a steady-state subscribe touches
        O(topic-depth) elements of ONE shard's arrays, never the mesh."""
        out: dict[str, list[tuple[int, int]]] = {}
        for si, s in enumerate(self.shards):
            for name, idxs in s.drain_updates().items():
                out.setdefault(name, []).extend((si, i) for i in idxs)
        return out

    # -- topic tokenizer ---------------------------------------------------

    def intern(self, word: str) -> int:
        return self.shards[0].intern(word)

    def word_id(self, word: str) -> int:
        return self.shards[0].word_id(word)

    def tokenize(self, topics: Sequence[str]):
        # the vocab is shared, so shard 0's tokenizer speaks for all
        return self.shards[0].tokenize(topics)
