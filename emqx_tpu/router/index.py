"""TrieIndex — the level-packed, device-resident form of the wildcard trie.

This is the TPU-era answer to ``emqx_trie.erl``'s ETS ordered_set walk
(emqx_trie.erl:282-344): instead of one ETS lookup per topic level per
message, the whole trie lives in HBM as flat int32 arrays and a *batch* of
topics is matched per kernel launch (emqx_tpu.ops.trie_match).

Layout
------
Nodes are integer ids (root = 0). Per node:

- ``plus_child[n]``  child via a ``+`` edge, -1 if none
- ``hash_fid[n]``    filter id of the ``prefix/#`` filter hanging under n
                     (``#`` is always terminal, so the '#' child is folded
                     into its parent as a filter id), -1 if none
- ``node_fid[n]``    filter id of a filter ending exactly at n, -1 if none

Exact (non-wildcard) edges live in one open-addressed hash table keyed by
``(parent_node, word_id)``:

- ``ht_parent[s] / ht_word[s] / ht_child[s]`` with -1 marking empty slots;
  linear probing, builder-verified max probe length ≤ ``max_probes`` (the
  table is grown until that bound holds, so the device probe loop is a
  *static* unrolled bound).

Words are interned host-side: PAD=0 (beyond end of topic), PLUS=1, HASH=2,
UNK=3 (topic word never seen in any filter — can only match wildcards),
real words ≥ 4. Wildcard ids never appear as hash-table keys, which is what
makes the device walk agree with the host oracle on degenerate topics
containing literal '+'/'#'.

Match-uniqueness invariant (why the kernel needs no dedup): a filter is
emitted either as ``hash_fid`` at exactly one (node, depth) or as
``node_fid`` at exactly one node at end-of-topic; trie nodes are a tree, so
a frontier never contains the same node twice ⇒ every matching filter id is
emitted exactly once per topic.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from emqx_tpu.core import topic as T

PAD = 0
PLUS_ID = 1
HASH_ID = 2
UNK = 3
FIRST_WORD_ID = 4

_MIX_A = np.uint32(0x9E3779B1)
_MIX_B = np.uint32(0x85EBCA77)


def edge_hash(parent: np.ndarray, word: np.ndarray, mask: int) -> np.ndarray:
    """Slot hash for the (parent, word) edge key — same formula on host
    (builder) and device (prober); uint32 wraparound arithmetic."""
    with np.errstate(over="ignore"):  # uint32 wraparound is the point
        p = parent.astype(np.uint32) * _MIX_A
        w = word.astype(np.uint32) * _MIX_B
        h = p ^ w
        h ^= h >> np.uint32(15)
        h *= np.uint32(0x2C1B3C6D)
        h ^= h >> np.uint32(12)
        return (h & np.uint32(mask)).astype(np.int32)


@dataclass
class TrieIndexArrays:
    """The device-side arrays (numpy here; moved to HBM by the matcher)."""

    ht_parent: np.ndarray
    ht_word: np.ndarray
    ht_child: np.ndarray
    plus_child: np.ndarray
    hash_fid: np.ndarray
    node_fid: np.ndarray
    n_nodes: int
    n_filters: int
    max_probes: int


class TrieIndex:
    """Host-side builder: filters → interned vocab + flat trie arrays.

    Built from ``Router.snapshot_filters()`` (full rebuild) or patched via
    ``insert``/``delete`` then ``rebuild()`` — round-1 policy is
    double-buffered full rebuilds (cheap: one linear pass over filters);
    true in-place device deltas are a later optimisation, the refcount
    bookkeeping for them already lives in the host ``Trie``.
    """

    def __init__(self, max_levels: int = 16, max_probes: int = 8) -> None:
        self.max_levels = max_levels
        self.max_probes = max_probes
        self.vocab: dict[str, int] = {}
        self.filters: list[str] = []       # fid -> filter string
        self._filter_ids: dict[str, int] = {}
        self._free_fids: list[int] = []
        self.arrays: Optional[TrieIndexArrays] = None
        self._dirty = True

    # -- vocab -------------------------------------------------------------

    def intern(self, word: str) -> int:
        wid = self.vocab.get(word)
        if wid is None:
            wid = FIRST_WORD_ID + len(self.vocab)
            self.vocab[word] = wid
        return wid

    def word_id(self, word: str) -> int:
        if word == T.PLUS:
            return PLUS_ID
        if word == T.HASH:
            return HASH_ID
        return self.vocab.get(word, UNK)

    # -- filter set mutation ----------------------------------------------

    def fid_of(self, filt: str) -> Optional[int]:
        return self._filter_ids.get(filt)

    def insert(self, filt: str) -> int:
        """Register a filter, return its stable fid."""
        if not T.validate_filter(filt):
            # same guard as Router.add_route: an invalid filter (e.g.
            # 'a/#/b') would be silently truncated at '#' by rebuild() and
            # diverge from the host oracle
            raise ValueError(f"invalid topic filter: {filt!r}")
        fid = self._filter_ids.get(filt)
        if fid is not None:
            return fid
        if self._free_fids:
            fid = self._free_fids.pop()
            self.filters[fid] = filt
        else:
            fid = len(self.filters)
            self.filters.append(filt)
        self._filter_ids[filt] = fid
        for w in T.words(filt):
            if w not in (T.PLUS, T.HASH):
                self.intern(w)
        self._dirty = True
        return fid

    def delete(self, filt: str) -> Optional[int]:
        fid = self._filter_ids.pop(filt, None)
        if fid is None:
            return None
        self.filters[fid] = None
        self._free_fids.append(fid)
        self._dirty = True
        return fid

    def load(self, filters: Sequence[str]) -> None:
        for f in filters:
            self.insert(f)

    # -- build -------------------------------------------------------------

    def rebuild(self) -> TrieIndexArrays:
        """One linear pass over filters → flat arrays."""
        # 1. build a pointer trie over word ids
        children: list[dict[int, int]] = [{}]   # node -> {word_id: child}
        plus: list[int] = [-1]
        hashf: list[int] = [-1]
        nodef: list[int] = [-1]

        def new_node() -> int:
            children.append({})
            plus.append(-1)
            hashf.append(-1)
            nodef.append(-1)
            return len(children) - 1

        n_edges = 0
        for fid, filt in enumerate(self.filters):
            if filt is None:
                continue
            node = 0
            ws = T.words(filt)
            for i, w in enumerate(ws):
                if w == T.HASH:
                    hashf[node] = fid        # '#' is terminal: fold to parent
                    break
                if w == T.PLUS:
                    if plus[node] == -1:
                        plus[node] = new_node()
                    node = plus[node]
                else:
                    wid = self.intern(w)
                    nxt = children[node].get(wid)
                    if nxt is None:
                        nxt = new_node()
                        children[node][wid] = nxt
                        n_edges += 1
                    node = nxt
            else:
                nodef[node] = fid
        n_nodes = len(children)

        # 2. open-addressed edge table, grown until probe bound holds
        size = 64
        while size < 4 * max(1, n_edges):
            size *= 2
        while True:
            ht_parent = np.full(size, -1, np.int32)
            ht_word = np.full(size, -1, np.int32)
            ht_child = np.full(size, -1, np.int32)
            mask = size - 1
            ok = True
            for parent, edges in enumerate(children):
                for wid, child in edges.items():
                    slot = int(edge_hash(np.int32(parent), np.int32(wid), mask))
                    for probe in range(self.max_probes):
                        s = (slot + probe) & mask
                        if ht_parent[s] == -1:
                            ht_parent[s] = parent
                            ht_word[s] = wid
                            ht_child[s] = child
                            break
                    else:
                        ok = False
                        break
                if not ok:
                    break
            if ok:
                break
            size *= 2

        self.arrays = TrieIndexArrays(
            ht_parent=ht_parent,
            ht_word=ht_word,
            ht_child=ht_child,
            plus_child=np.asarray(plus, np.int32),
            hash_fid=np.asarray(hashf, np.int32),
            node_fid=np.asarray(nodef, np.int32),
            n_nodes=n_nodes,
            n_filters=len(self.filters),
            max_probes=self.max_probes,
        )
        self._dirty = False
        return self.arrays

    def ensure(self) -> TrieIndexArrays:
        if self._dirty or self.arrays is None:
            return self.rebuild()
        return self.arrays

    # -- topic tokenizer ---------------------------------------------------

    def tokenize(
        self, topics: Sequence[str]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, list[int]]:
        """topics → (tokens [B,L], lengths [B], sys_flags [B], too_long).

        ``too_long`` lists batch positions whose topic exceeds max_levels —
        they must take the host-oracle fallback (mirrors the reference's
        escape hatch for pathological topics).
        """
        B, L = len(topics), self.max_levels
        tokens = np.zeros((B, L), np.int32)
        lengths = np.zeros(B, np.int32)
        sys_flags = np.zeros(B, bool)
        too_long: list[int] = []
        for b, topic in enumerate(topics):
            ws = T.words(topic)
            if len(ws) > L:
                too_long.append(b)
                # length 0 + sys flag ⇒ the kernel emits nothing for this
                # row (even root '#'/'+' which match an empty prefix);
                # caller routes it through the host oracle instead
                lengths[b] = 0
                sys_flags[b] = True
                continue
            lengths[b] = len(ws)
            sys_flags[b] = ws[0].startswith("$") if ws else False
            for i, w in enumerate(ws):
                tokens[b, i] = self.word_id(w)
        return tokens, lengths, sys_flags, too_long
