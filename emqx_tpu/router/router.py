"""Route table + match_routes — parity with ``apps/emqx/src/emqx_router.erl``.

- routes: topic-filter → set of destinations (the ``emqx_route`` bag table,
  emqx_router.erl:78-92). A destination is a node name, ``(group, node)``
  for shared subs, or a session id.
- only wildcard filters enter the trie (emqx_trie.erl:262-264); exact-topic
  routes are matched by direct dict lookup (emqx_router.erl:141-153).
- add/delete are serialized per topic in the reference via a pool worker
  picked by topic hash (emqx_router.erl:200-204); here a single lock guards
  the table + trie + delta log so the same ordering discipline holds.
- every mutation appends to a **delta log** consumed by (a) the device-index
  incremental refresher and (b) cluster replication (the mria-rlog analogue,
  SURVEY.md §2.4).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Optional

from emqx_tpu.core import topic as T
from emqx_tpu.core.message import Route
from emqx_tpu.router.trie import Trie


@dataclass(frozen=True)
class RouteDelta:
    seq: int
    op: str            # "add" | "del"
    topic: str
    dest: Any
    filter_new: bool   # first route for this filter / last route removed


class Router:
    """Node-local replica of the cluster route table."""

    def __init__(self) -> None:
        self._routes: dict[str, set[Any]] = {}
        self._trie = Trie()
        self._lock = threading.RLock()
        self._seq = 0
        self._base_seq = 0
        self._log: list[RouteDelta] = []
        # route observers: fn(op, topic, dest) with op in {"add","del"},
        # fired UNDER the router lock so callbacks see table order —
        # an add/del pair for the same route delivered out of order
        # would permanently desync a mirror. Observers must be quick
        # and must not call back into the Router. The native host
        # mirrors REMOTE routes as punt markers through this seam so
        # its fast path stays complete on a clustered node
        # (broker/native_server.py)
        self.route_observers: list = []

    # -- mutation (emqx_router:do_add_route/2 :123-138) ---------------------

    def add_route(self, topic: str, dest: Any = "local") -> bool:
        if not T.validate_filter(topic):
            # channel/session reject invalid filters before routing; this
            # guard keeps the trie consistent with the match oracle even
            # for direct API users
            raise ValueError(f"invalid topic filter: {topic!r}")
        with self._lock:
            dests = self._routes.setdefault(topic, set())
            if dest in dests:
                return False
            dests.add(dest)
            filter_new = False
            if T.wildcard(topic):
                filter_new = self._trie.insert(topic)
            self._append("add", topic, dest, filter_new)
            for obs in self.route_observers:
                obs("add", topic, dest)
            return True

    def delete_route(self, topic: str, dest: Any = "local") -> bool:
        with self._lock:
            dests = self._routes.get(topic)
            if dests is None or dest not in dests:
                return False
            dests.discard(dest)
            if not dests:
                del self._routes[topic]
            filter_gone = False
            if T.wildcard(topic):
                filter_gone = self._trie.delete(topic)
            self._append("del", topic, dest, filter_gone)
            for obs in self.route_observers:
                obs("del", topic, dest)
            return True

    def _append(self, op: str, topic: str, dest: Any, fnew: bool) -> None:
        self._seq += 1
        self._log.append(RouteDelta(self._seq, op, topic, dest, fnew))

    # -- read path (emqx_router:match_routes/1 :141-153) --------------------

    def match_routes(self, topic: str) -> list[Route]:
        with self._lock:
            out: list[Route] = []
            for dest in self._routes.get(topic, ()):
                out.append(Route(topic, dest))
            for filt in self._trie.match(topic):
                for dest in self._routes.get(filt, ()):
                    out.append(Route(filt, dest))
            return out

    def lookup_routes(self, topic: str) -> list[Route]:
        with self._lock:
            return [Route(topic, d) for d in self._routes.get(topic, ())]

    def has_route(self, topic: str, dest: Any) -> bool:
        with self._lock:
            return dest in self._routes.get(topic, ())

    def dump(self) -> list[tuple[str, Any]]:
        """All (topic, dest) pairs — route-observer bootstrap snapshot."""
        with self._lock:
            return [(t, d) for t, ds in self._routes.items() for d in ds]

    def topics(self) -> list[str]:
        with self._lock:
            return list(self._routes)

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "routes.count": sum(len(d) for d in self._routes.values()),
                "topics.count": len(self._routes),
                "filters.count": len(self._trie),
            }

    # -- node-down purge (emqx_router_helper semantics) ----------------------

    def cleanup_dest(self, dest: Any) -> int:
        """Purge every route pointing at ``dest`` (dead node/session)."""
        with self._lock:
            victims = [t for t, ds in self._routes.items() if dest in ds]
            for t in victims:
                self.delete_route(t, dest)
            return len(victims)

    # -- delta log (device refresh + replication) ----------------------------

    def deltas_since(self, seq: int) -> Optional[list[RouteDelta]]:
        """Deltas after ``seq``; None if that prefix was trimmed away
        (consumer must full-resync — mria replicant bootstrap analogue)."""
        with self._lock:
            if seq < self._base_seq or seq > self._seq:
                # prefix trimmed away, or consumer is ahead of us (we
                # restarted fresh): either way its state is unreachable
                # from this log — full resync required
                return None
            if not self._log or seq >= self._log[-1].seq:
                return []
            # log is append-only with dense seqs; _base_seq = seq of the
            # entry preceding _log[0]
            return self._log[seq - self._base_seq:]

    def trim_log(self, upto_seq: int) -> None:
        """Drop deltas ≤ upto_seq once every consumer has applied them."""
        with self._lock:
            upto = min(upto_seq, self._seq)
            if upto <= self._base_seq:
                return
            del self._log[: upto - self._base_seq]
            self._base_seq = upto

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def snapshot_filters(self) -> list[tuple[str, int]]:
        """(filter, refcount) snapshot taken under the router lock — the
        device-index builder's input (never hand out the live trie)."""
        with self._lock:
            return list(self._trie.filters())

    def match_filters(self, topic: str) -> list[str]:
        """Wildcard filters matching ``topic`` (host-oracle path)."""
        with self._lock:
            return self._trie.match(topic)
