from emqx_tpu.router.trie import Trie
from emqx_tpu.router.router import Router

__all__ = ["Trie", "Router"]
