"""Host-side wildcard-filter trie: the correctness oracle.

Semantic parity with ``apps/emqx/src/emqx_trie.erl`` (insert/1 :113-127,
match/1 :146-169/:282-344, delete/1 :129-144): only *wildcard* filters are
stored (``emqx_trie.erl:262-264``); edges/terminals are refcounted so
concurrent subscribe/unsubscribe of the same filter compose; match walks
topic words branching on ``+`` and probing a ``#`` terminal at every level;
topics whose first level starts with ``$`` skip root wildcards.

The reference compacts multi-word prefixes into single ETS keys to shrink
ETS lookups (``emqx_trie.erl:199-233``); that is a BEAM-storage optimisation
— our equivalent packing lives in the *device* index builder
(``emqx_tpu.router.index``), so the host oracle stays a plain pointer trie.

This structure is also the mutation source of truth: the device index is
(re)built/delta-patched from it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, Optional

from emqx_tpu.core import topic as T


@dataclass
class _Node:
    children: dict[str, "_Node"] = field(default_factory=dict)
    # refcount of filters terminating exactly at this node
    term_count: int = 0
    # full filter string for terminals (host-side convenience)
    filter: Optional[str] = None


class Trie:
    """Refcounted wildcard-filter trie with MQTT match semantics."""

    def __init__(self) -> None:
        self._root = _Node()
        self._count = 0  # distinct filters stored

    def __len__(self) -> int:
        return self._count

    def is_empty(self) -> bool:
        return self._count == 0

    # -- mutation ----------------------------------------------------------

    def insert(self, filt: str) -> bool:
        """Insert one refcount of ``filt``. True if the filter is new."""
        node = self._root
        for w in T.words(filt):
            node = node.children.setdefault(w, _Node())
        node.term_count += 1
        if node.term_count == 1:
            node.filter = filt
            self._count += 1
            return True
        return False

    def delete(self, filt: str) -> bool:
        """Drop one refcount of ``filt``. True if the filter is now gone."""
        path: list[tuple[_Node, str]] = []
        node = self._root
        for w in T.words(filt):
            child = node.children.get(w)
            if child is None:
                return False
            path.append((node, w))
            node = child
        if node.term_count == 0:
            return False
        node.term_count -= 1
        if node.term_count > 0:
            return False
        node.filter = None
        self._count -= 1
        # prune now-empty nodes bottom-up
        for parent, w in reversed(path):
            child = parent.children[w]
            if child.term_count == 0 and not child.children:
                del parent.children[w]
            else:
                break
        return True

    # -- match -------------------------------------------------------------

    def match(self, topic: str) -> list[str]:
        """All stored filters matching publish-topic ``topic``.

        Iterative frontier walk (no recursion: filters may legally have
        thousands of levels). The frontier at level *i* is the set of trie
        nodes whose path matches ``ws[:i]`` — the same shape the device
        kernel uses, so this doubles as its semantic oracle.
        """
        ws = T.words(topic)
        out: list[str] = []
        sys_root = T.is_sys(ws)
        frontier = [self._root]
        for i, w in enumerate(ws):
            nxt: list[_Node] = []
            for node in frontier:
                hash_child = node.children.get(T.HASH)
                if hash_child is not None and not (sys_root and i == 0):
                    # '#' child matches the remainder (incl. zero levels)
                    if hash_child.term_count > 0:
                        out.append(hash_child.filter)
                # a literal '#' topic word (illegal in validated names) must
                # not descend into the '#' terminal — it already matched via
                # hash_child above; descending would emit the filter twice
                exact = node.children.get(w) if w != T.HASH else None
                if exact is not None:
                    nxt.append(exact)
                # w == '+' (legal only in not-yet-validated names) would make
                # exact and plus the same node — don't double-count it
                if w != T.PLUS and not (sys_root and i == 0):
                    plus = node.children.get(T.PLUS)
                    if plus is not None:
                        nxt.append(plus)
            frontier = nxt
            if not frontier:
                break
        for node in frontier:
            if node.term_count > 0:
                out.append(node.filter)
            hash_child = node.children.get(T.HASH)
            if hash_child is not None and hash_child.term_count > 0:
                out.append(hash_child.filter)
        return out

    # -- introspection (device-index builder input) ------------------------

    def filters(self) -> Iterator[tuple[str, int]]:
        """Yield (filter, refcount) for all stored filters."""
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node.term_count > 0:
                yield node.filter, node.term_count
            stack.extend(node.children.values())
