"""Data bridges — the ``emqx_bridge`` app."""

from emqx_tpu.bridge.bridge import Bridge, BridgeManager   # noqa: F401
