"""Bridge config layer — parity with ``apps/emqx_bridge/src/``
(``emqx_bridge_resource.erl`` naming, the ``$bridges/...`` ingress hook
topics of emqx_rule_events.erl:145, and the bridge↔rule-action seam).

A bridge = connector + ResourceManager + BufferWorker under a
``type:name`` id:

- egress: registered as rule action ``type:name`` — the rule's output
  columns render through the bridge's templates into a request and
  flow through the buffer worker (batching, disk queue, retry).
- ``direct_publish``: hook a local topic filter straight to the bridge
  (the config-only egress path that needs no SQL rule).
- mqtt ingress: remote messages re-publish locally under
  ``local_topic`` and/or fire rules FROM ``$bridges/mqtt:name``.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional

from emqx_tpu.core.message import Message
from emqx_tpu.resource.resource import ResourceManager
from emqx_tpu.resource.worker import BufferWorker
from emqx_tpu.rules.engine import render_template


def _json_safe(columns: dict) -> dict:
    """Bytes → str so a rendered request survives the buffer worker's
    JSON disk-queue codec (one rule for every renderer branch)."""
    return {k: (v.decode("utf-8", "replace") if isinstance(v, bytes)
                else v) for k, v in columns.items()}
from emqx_tpu.rules.events import message_columns

BRIDGE_HOOK_PREFIX = "$bridges"


class Bridge:
    def __init__(self, type: str, name: str, conf: dict,
                 manager: ResourceManager, worker: BufferWorker) -> None:
        self.type, self.name = type, name
        self.id = f"{type}:{name}"
        self.conf = conf
        self.manager = manager
        self.worker = worker
        self.enabled = True

    # -- template rendering (request per bridge type) ------------------------

    def render_request(self, columns: dict) -> Any:
        c = self.conf
        if self.type == "http":
            body_tmpl = c.get("body", "")
            body = (render_template(body_tmpl, columns) if body_tmpl
                    else json.dumps({k: v for k, v in columns.items()
                                     if not isinstance(v, bytes)}))
            return {
                "method": c.get("method", "post"),
                "path": render_template(c.get("path", "/"), columns),
                "headers": c.get("headers") or {},
                "body": body,
            }
        if self.type == "mqtt":
            remote = (c.get("egress") or {}).get("remote") or {}
            topic_tmpl = remote.get("topic") or "${topic}"
            payload_tmpl = remote.get("payload")
            payload = (render_template(payload_tmpl, columns)
                       if payload_tmpl else columns.get("payload", ""))
            if isinstance(payload, bytes):
                # the request must survive the worker's JSON disk codec;
                # the connector re-encodes to bytes on publish
                payload = payload.decode("utf-8", "replace")
            return {
                "topic": render_template(topic_tmpl, columns),
                "payload": payload,
                "qos": remote.get("qos", columns.get("qos", 0)),
                "retain": bool(remote.get("retain", False)),
            }
        if self.type == "redis":
            # emqx_ee_bridge_redis: command template per message
            tmpl = c.get("command_template") or [
                "LPUSH", "mqtt:${topic}", "${payload}"]
            return {"cmd": [render_template(x, columns) for x in tmpl]}
        if self.type in ("mysql", "pgsql", "postgresql"):
            # emqx_ee_bridge_mysql/pgsql: one INSERT per message from a
            # sql template (client-side bound, connector/pgsql.render_sql)
            tmpl = c.get("sql") or (
                "INSERT INTO mqtt_msg (topic, qos, payload) VALUES "
                "(${topic}, ${qos}, ${payload})")
            return {"sql": tmpl, "binds": _json_safe(columns)}
        if self.type == "mongodb":
            # emqx_ee_bridge_mongodb: payload template → one document
            coll = c.get("collection", "mqtt_msg")
            tmpl = c.get("payload_template")
            if tmpl:
                doc = {"payload": render_template(tmpl, columns)}
            else:
                doc = {k: v for k, v in _json_safe(columns).items()
                       if isinstance(v, (str, int, float, bool))
                       or v is None}
            return {"insert": coll, "documents": [doc]}
        if self.type == "kafka":
            # emqx_ee_bridge_kafka (wolff): key/value templates per
            # message; key defaults to clientid (the reference's
            # key_template default), value to the payload
            key_t = c.get("key_template") or "${clientid}"
            val_t = c.get("value_template")
            value = (render_template(val_t, columns) if val_t
                     else _json_safe(columns).get("payload", ""))
            return {
                "topic": c.get("kafka_topic", "mqtt"),
                "key": render_template(key_t, columns),
                "value": value,
            }
        if self.type == "gcp_pubsub":
            # emqx_ee_connector_gcp_pubsub encode_payload/2: data =
            # base64 of the rendered payload template (whole-columns
            # JSON when no template); orderingKey/attributes templates
            # are renderer extras on the same message shape
            import base64 as _b64
            tmpl = c.get("payload_template")
            data = (render_template(tmpl, columns) if tmpl
                    else json.dumps(_json_safe(columns)))
            msg: dict = {"data": _b64.b64encode(data.encode()).decode()}
            if c.get("attributes_template"):
                msg["attributes"] = {
                    k: render_template(v, columns)
                    for k, v in c["attributes_template"].items()}
            if c.get("ordering_key_template"):
                msg["orderingKey"] = render_template(
                    c["ordering_key_template"], columns)
            return {"messages": [msg]}
        if self.type == "influxdb":
            # emqx_ee_bridge_influxdb: write_syntax template → one line
            # of line protocol, shipped over the HTTP connector's /write
            tmpl = c.get("write_syntax") or \
                "mqtt,topic=${topic} payload=\"${payload}\""
            return {
                "method": "post",
                "path": c.get("path", "/write"),
                "headers": {"Content-Type": "text/plain"},
                "body": render_template(tmpl, columns),
            }
        # generic connectors take the columns (bytes decoded — requests
        # must survive the buffer worker's JSON disk codec)
        return _json_safe(columns)

    def send(self, columns: dict) -> bool:
        if not self.enabled:
            return False
        return self.worker.enqueue(self.render_request(columns))

    def status(self) -> dict:
        return {
            "id": self.id, "type": self.type, "name": self.name,
            "enabled": self.enabled,
            "resource": self.manager.status(),
            "queuing": self.worker.queuing(),
            "metrics": dict(self.worker.metrics),
        }


class BridgeManager:
    """Create/delete/enable bridges; ticks their resource FSMs + buffer
    workers from the app housekeeping timer."""

    def __init__(self, rules=None, publish_fn=None, hooks=None,
                 queue_base_dir: Optional[str] = None) -> None:
        self.rules = rules
        self.publish_fn = publish_fn
        self.hooks = hooks
        self.queue_base_dir = queue_base_dir
        self.bridges: dict[str, Bridge] = {}
        self._lock = threading.RLock()
        # fired after create/delete — the native host flushes its
        # publish permits here so a new egress bridge sees topics that
        # were already fast-pathing (broker/native_server.py)
        self.on_topology_change: list = []

    # -- lifecycle -----------------------------------------------------------

    def create(self, type: str, name: str, connector, conf: Optional[dict]
               = None, *, start: bool = True, **worker_opts) -> Bridge:
        conf = conf or {}
        bid = f"{type}:{name}"
        with self._lock:
            if bid in self.bridges:
                raise ValueError(f"bridge {bid} already exists")
            manager = ResourceManager(
                bid, connector, conf,
                auto_restart_s=conf.get("auto_restart_s", 2.0),
                health_check_s=conf.get("health_check_s", 15.0),
            )
            qdir = None
            if self.queue_base_dir and conf.get("disk_queue", False):
                qdir = f"{self.queue_base_dir}/{type}_{name}"
            # auto_flush: production bridges honour batch_time_s with a
            # dedicated flusher; the 5s app tick is only the safety net
            worker_opts.setdefault("auto_flush", True)
            worker = BufferWorker(manager, queue_dir=qdir, **worker_opts)
            bridge = Bridge(type, name, conf, manager, worker)
            self.bridges[bid] = bridge
        if start:
            manager.start()
        # record each detach as soon as its attach lands, so a failure
        # mid-way (e.g. ingress subscribe on a dead remote) can always
        # unwind completely — otherwise delete() would leave a rule
        # action / publish hook pointing at a dead bridge forever
        bridge._cleanups = cleanups = []
        try:
            # rule-action seam: actions reference bridges as "type:name"
            if self.rules is not None:
                self.rules.register_action(
                    bid, lambda columns, args, b=bridge: b.send(columns))
                cleanups.append(
                    lambda: self.rules.unregister_action(bid))
            # direct egress from a local topic filter (config-only path)
            local = ((conf.get("egress") or {}).get("local") or {})
            if local.get("topic") and self.hooks is not None:
                filt = local["topic"]
                hook_fn = (lambda msg, b=bridge, f=filt:
                           self._direct_egress(msg, b, f))
                self.hooks.add("message.publish", hook_fn, priority=-150)
                cleanups.append(
                    lambda: self.hooks.delete("message.publish", hook_fn))
            # mqtt ingress leg
            ingress = ((conf.get("ingress") or {}).get("remote") or {})
            if ingress.get("topic") and hasattr(connector,
                                                "subscribe_remote"):
                rfilt = ingress["topic"]
                connector.subscribe_remote(
                    rfilt,
                    lambda t, p, q, b=bridge: self._on_ingress(b, t, p, q),
                )
                cleanups.append(
                    lambda: connector.unsubscribe_remote(rfilt))
        except Exception:
            self.delete(bid)
            raise
        for cb in self.on_topology_change:
            cb()
        return bridge

    def _direct_egress(self, msg: Message, bridge: Bridge, filt: str):
        from emqx_tpu.core import topic as T
        if not msg.sys and T.match(msg.topic, filt):
            bridge.send(message_columns(msg))
        return None

    def _on_ingress(self, bridge: Bridge, topic: str, payload: bytes,
                    qos: int) -> None:
        """Remote → local: republish under local_topic and/or feed rules
        bound to the ``$bridges/mqtt:name`` hook topic."""
        local = ((bridge.conf.get("ingress") or {}).get("local") or {})
        hook_topic = f"{BRIDGE_HOOK_PREFIX}/{bridge.id}"
        if self.rules is not None:
            self.rules.ingest(Message(
                topic=hook_topic, payload=payload, qos=qos,
                headers={"bridge_origin_topic": topic},
            ))
        if local.get("topic") and self.publish_fn is not None:
            cols = {"topic": topic,
                    "payload": payload.decode("utf-8", "replace"),
                    "qos": qos}
            self.publish_fn(Message(
                topic=render_template(local["topic"], cols),
                payload=payload,
                qos=int(local.get("qos", qos)),
            ))

    def delete(self, bid: str) -> bool:
        with self._lock:
            bridge = self.bridges.pop(bid, None)
        if bridge is None:
            return False
        # detach every traffic source first, or the dead bridge keeps
        # accumulating requests in a queue nothing will ever flush
        for fn in getattr(bridge, "_cleanups", ()):
            try:
                fn()
            except Exception:
                pass
        bridge.enabled = False
        bridge.worker.close()
        bridge.manager.stop()
        for cb in self.on_topology_change:
            cb()
        return True

    def get(self, bid: str) -> Optional[Bridge]:
        return self.bridges.get(bid)

    def list(self) -> list[dict]:
        return [b.status() for b in self.bridges.values()]

    def enable(self, bid: str, on: bool = True) -> bool:
        b = self.bridges.get(bid)
        if b is None:
            return False
        b.enabled = on
        b.worker.paused = not on     # keep buffered data while disabled
        if on and b.manager.state == "stopped":
            b.manager.start()
        elif not on:
            b.manager.stop()
        return True

    # -- periodic ------------------------------------------------------------

    def tick(self) -> None:
        for b in list(self.bridges.values()):
            if b.enabled:
                b.manager.tick()
                b.worker.tick()

    def stop_all(self) -> None:
        for b in list(self.bridges.values()):
            b.manager.stop()
