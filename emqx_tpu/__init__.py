"""emqx_tpu — a TPU-native distributed MQTT broker framework.

A ground-up re-architecture of the capability set of EMQX 5.0.14
(reference: /root/reference, Erlang/OTP) where the per-message routing hot
path — wildcard subscription matching and subscriber fan-out — executes as
batched JAX/XLA (and Pallas) kernels over a level-packed topic trie resident
in TPU HBM, while the broker runtime (protocol engine, sessions, cluster
plane, control plane) is host-side Python/C++.

Package map (SURVEY.md §2 component inventory → our layout):

- ``core``      topic algebra, message model  (emqx_topic.erl, emqx.hrl)
- ``router``    host trie oracle, route table, device trie index
                (emqx_trie.erl, emqx_router.erl)
- ``ops``       batched device kernels: trie match, bitmap fan-out
                (replaces emqx_trie:match/1 per-message ETS walk)
- ``parallel``  mesh/sharding: dp (topic batch) × tp (subscriber-bitmap
                shard) over jax.sharding.Mesh (replaces mria/gen_rpc scale-out)
- ``models``    the flagship jittable "router model": match + fan-out step
- ``mqtt``      MQTT 3.1/3.1.1/5.0 frame codec (emqx_frame.erl)
- ``session``   inflight / mqueue / session FSM (emqx_session.erl et al.)
- ``broker``    pub/sub fabric, hooks, dispatch (emqx_broker.erl, emqx_hooks.erl)
- ``access``    authn chains, authz sources, banned, limiter
- ``rules``     SQL rule engine (emqx_rule_engine)
- ``cluster``   route-delta replication, forwarding, versioned protos
- ``observe``   metrics, stats, $SYS, tracing, prometheus
- ``utils``     config, pool, guid, misc
"""

__version__ = "0.1.0"
