"""Gateway behaviours + context + manager — parity with
``apps/emqx_gateway/src/bhvrs/`` (emqx_gateway_frame / _channel / _impl,
apps/emqx_gateway/src/bhvrs/emqx_gateway_channel.erl:29-105) and
``emqx_gateway_ctx.erl`` (the broker-facing API handed to channels).

A gateway = Impl (lifecycle + listeners) + Frame (codec) + Channel
(per-client FSM). Channels never touch the broker directly: everything
goes through the GwContext, which applies the gateway's mountpoint and
registers the channel with the core CM so broker dispatch reaches it
(``ch.send(ch.handle_deliver(items))`` duck-type, broker/cm.py).
"""

from __future__ import annotations

from typing import Any, Optional

from emqx_tpu.core.message import Message, SubOpts


class GwFrame:
    """Frame codec behaviour (emqx_gateway_frame.erl)."""

    def initial_parse_state(self, opts: Optional[dict] = None) -> Any:
        return b""

    def parse(self, data: bytes, state: Any) -> tuple[list, Any]:
        raise NotImplementedError

    def serialize(self, pkt: Any) -> bytes:
        raise NotImplementedError


class GwChannel:
    """Per-client protocol FSM behaviour (emqx_gateway_channel.erl).

    Ducks the core Channel surface the CM dispatch path expects.
    """

    conn_state = "idle"
    clientid: Optional[str] = None

    def handle_in(self, frame: Any) -> list:
        raise NotImplementedError

    def handle_deliver(self, deliveries: list) -> list:
        raise NotImplementedError

    def handle_timeout(self, kind: str) -> list:
        return []

    def terminate(self, reason: str) -> None:
        pass

    def send(self, frames: list) -> None:
        """Bound to the transport by the conn adapter."""

    def request_close(self) -> None:
        """Ask the transport to drop this connection; bound by the conn
        adapter, thread-safe. Needed by channels whose disconnect
        decision lands on a worker thread (exproto) — the run loop only
        polls conn_state after inbound frames."""

    # CM duck-type (takeover/discard on clientid clash)
    def takeover(self):
        return None, []

    def discard(self) -> None:
        self.terminate("discarded")


class GatewayImpl:
    """Gateway lifecycle behaviour (emqx_gateway_impl.erl)."""

    name = "?"

    def on_gateway_load(self, ctx: "GwContext", conf: dict) -> None:
        raise NotImplementedError

    async def start_listeners(self) -> None:
        raise NotImplementedError

    async def stop_listeners(self) -> None:
        pass

    def on_gateway_unload(self) -> None:
        pass


class GwContext:
    """emqx_gateway_ctx: the only broker surface a channel sees."""

    def __init__(self, app, gwname: str, mountpoint: str = "") -> None:
        self.app = app
        self.gwname = gwname
        self.mountpoint = mountpoint
        # live sessions of THIS gateway (clientid → connected_at ms) —
        # backs the per-gateway clients REST surface (emqx_gateway_api)
        self.sessions: dict[str, int] = {}

    # -- topic namespace -----------------------------------------------------

    def mount(self, topic: str) -> str:
        return self.mountpoint + topic if self.mountpoint else topic

    def unmount(self, topic: str) -> str:
        if self.mountpoint and topic.startswith(self.mountpoint):
            return topic[len(self.mountpoint):]
        return topic

    # -- client lifecycle ----------------------------------------------------

    def open_session(self, clientid: str, channel) -> None:
        """Register with the core CM (clientid clash kicks the old one,
        the gateway default — emqx_gateway_cm discard semantics)."""
        old = self.app.cm.lookup_channel(clientid)
        if old is not None and old is not channel:
            old.discard()
        self.app.cm.register_channel(clientid, channel)
        import time as _t
        self.sessions[clientid] = int(_t.time() * 1000)
        self.app.hooks.run("client.connected",
                           ({"clientid": clientid, "gateway": self.gwname},))

    def close_session(self, clientid: str, channel=None,
                      reason: str = "closed") -> None:
        self.app.broker.subscriber_down(clientid)
        self.app.cm.unregister_channel(clientid, channel)
        self.sessions.pop(clientid, None)
        self.app.hooks.run(
            "client.disconnected",
            ({"clientid": clientid, "gateway": self.gwname}, reason))

    def authenticate(self, clientid: str, username=None,
                     password=None) -> bool:
        try:
            # banned first — the reference gateway channels carry a
            # literal "TODO: How to implement the banned in the gateway
            # instance?" (emqx_stomp_channel.erl:427); enforcing the
            # shared table here closes that gap for every gateway
            banned = getattr(self.app.access, "banned", None)
            if banned is not None and banned.check(
                    {"clientid": clientid, "username": username}):
                return False
            res = self.app.hooks.run_fold(
                "client.authenticate",
                ({"clientid": clientid, "username": username,
                  "password": password, "peername": "gw"},),
                {"result": "ok"},
            )
        except Exception:
            return False      # fail closed, like the core channel
        # authenticators answer 'ok' / 'error' (access/control.py) —
        # anything but 'ok' is a denial (broker/channel.py does the same)
        return (res or {}).get("result", "ok") == "ok"

    # -- pub/sub -------------------------------------------------------------

    def authorize(self, clientid: str, action: str, topic: str) -> bool:
        """client.authorize fold, same contract as the MQTT channel
        (broker/channel.py:281) — gateway clients go through the very
        same ACL chain."""
        verdict = self.app.hooks.run_fold(
            "client.authorize",
            ({"clientid": clientid, "username": None,
              "peername": f"gw:{self.gwname}"}, action, topic),
            "allow",
        )
        return verdict == "allow"

    def publish(self, clientid: str, topic: str, payload: bytes,
                qos: int = 0, retain: bool = False,
                props: Optional[dict] = None) -> bool:
        mounted = self.mount(topic)
        if not self.authorize(clientid, "publish", mounted):
            self.metrics_inc("messages.dropped.authz")
            return False
        msg = Message(
            topic=mounted, payload=payload, qos=qos,
            from_=clientid, flags={"retain": retain} if retain else {},
            headers={"properties": props or {}, "gateway": self.gwname},
        )
        self.app.cm.dispatch(self.app.broker.publish(msg))
        return True

    def subscribe(self, clientid: str, topic: str, qos: int = 0) -> bool:
        mounted = self.mount(topic)
        if not self.authorize(clientid, "subscribe", mounted):
            return False
        self.app.broker.subscribe(clientid, mounted, SubOpts(qos=qos))
        return True

    def unsubscribe(self, clientid: str, topic: str) -> bool:
        return self.app.broker.unsubscribe(clientid, self.mount(topic))

    def metrics_inc(self, key: str) -> None:
        self.app.metrics.inc(f"gateway.{self.gwname}.{key}")


class GatewayManager:
    """Load/unload gateway instances (emqx_gateway.erl registry)."""

    def __init__(self, app) -> None:
        self.app = app
        self.gateways: dict[str, GatewayImpl] = {}
        self.contexts: dict[str, GwContext] = {}
        self._unload_tasks: set = set()   # keep refs: loop holds weak refs

    def load(self, impl: GatewayImpl, conf: Optional[dict] = None
             ) -> GatewayImpl:
        conf = conf or {}
        if impl.name in self.gateways:
            raise ValueError(f"gateway {impl.name} already loaded")
        ctx = GwContext(self.app, impl.name,
                        mountpoint=conf.get("mountpoint", ""))
        impl.on_gateway_load(ctx, conf)
        self.gateways[impl.name] = impl
        self.contexts[impl.name] = ctx
        return impl

    def unload(self, name: str) -> bool:
        impl = self.gateways.get(name)
        if impl is None:
            return False
        # an unloaded gateway must stop accepting traffic: tear down its
        # listeners first, then run the impl's unload hook (scheduled if
        # we're on a running loop, inline otherwise)
        import asyncio

        async def teardown() -> None:
            await impl.stop_listeners()
            impl.on_gateway_unload()

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            ctx = self.contexts.get(name)
            self.gateways.pop(name, None)
            self.contexts.pop(name, None)

            async def guarded() -> None:
                try:
                    await teardown()
                except Exception:
                    # a failed teardown must not leave a LIVE listener
                    # invisible to (and un-unloadable by) the API —
                    # re-register so the operator can retry
                    import logging
                    reg_as = name
                    if name in self.gateways:
                        # a NEW gateway was loaded under this name while
                        # teardown ran — never clobber it; park the
                        # half-torn-down instance under an alias so its
                        # possibly-still-bound listeners stay VISIBLE
                        # and unloadable (retry via the alias)
                        reg_as = f"{name}~failed-{id(impl) & 0xFFFF:x}"
                    logging.getLogger("emqx_tpu.gateway").exception(
                        "gateway %s teardown failed; re-registered "
                        "as %s", name, reg_as)
                    self.gateways[reg_as] = impl
                    if ctx is not None:
                        self.contexts[reg_as] = ctx

            task = loop.create_task(guarded())
            self._unload_tasks.add(task)
            task.add_done_callback(self._unload_tasks.discard)
            return True
        # off-loop caller (REST handler thread): the listener's sockets
        # belong to ITS loop — teardown must run there, not in a fresh
        # asyncio.run() loop (cross-loop await fails). Deregister only
        # AFTER teardown succeeds: a timeout must not leave a live
        # listener invisible to (and un-unloadable by) the API.
        target = getattr(getattr(impl, "listener", None), "_loop", None)
        if target is not None and target.is_running():
            asyncio.run_coroutine_threadsafe(
                teardown(), target).result(timeout=10)
        else:
            asyncio.run(teardown())
        self.gateways.pop(name, None)
        self.contexts.pop(name, None)
        return True

    def get(self, name: str) -> Optional[GatewayImpl]:
        return self.gateways.get(name)

    def list(self) -> list[dict]:
        out = []
        # snapshot: called from the REST handler THREAD while the event
        # loop mutates the registries
        for n, impl in list(self.gateways.items()):
            ctx = self.contexts.get(n)
            out.append({
                "name": n, "status": "running",
                "port": getattr(impl, "port", None),
                "mountpoint": getattr(ctx, "mountpoint", ""),
                "current_connections": len(ctx.sessions) if ctx else 0,
            })
        return out

    def clients(self, name: str) -> Optional[list[dict]]:
        """Per-gateway connected clients (emqx_gateway_api_clients)."""
        ctx = self.contexts.get(name)
        if ctx is None:
            return None
        snapshot = dict(ctx.sessions)       # REST thread vs event loop
        return [{"clientid": cid, "connected_at": at,
                 "gateway": name}
                for cid, at in sorted(snapshot.items())]
