"""CoAP gateway — parity with ``apps/emqx_gateway/src/coap/``
(message codec: emqx_coap_frame.erl / RFC 7252; pub-sub resource:
emqx_coap_pubsub_handler.erl).

Codec is full RFC 7252 (options with 13/14 delta/length extensions,
tokens, all four message types). The pub/sub surface:

    PUT/POST coap://host/ps/{topic}          → publish (2.04)
    GET      .../ps/{topic} Observe:0        → subscribe (2.05 + seq)
    GET      .../ps/{topic} Observe:1        → unsubscribe (2.07-ish 2.05)
    GET      .../ps/{topic}                  → read latest retained (2.05/4.04)

Observed deliveries arrive as NON 2.05 notifications carrying the
subscribe token and a rolling Observe sequence.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.gateway.ctx import GatewayImpl, GwChannel, GwContext, GwFrame

CON, NON, ACK, RST = 0, 1, 2, 3

# method / response codes (class.detail → byte)
EMPTY = 0x00
GET, POST, PUT, DELETE = 0x01, 0x02, 0x03, 0x04
CREATED, DELETED, VALID, CHANGED, CONTENT = 0x41, 0x42, 0x43, 0x44, 0x45
BAD_REQUEST, UNAUTHORIZED, NOT_FOUND, NOT_ALLOWED = 0x80, 0x81, 0x84, 0x85

# option numbers
OPT_OBSERVE, OPT_URI_PATH, OPT_CONTENT_FORMAT, OPT_URI_QUERY = 6, 11, 12, 15
OPT_LOCATION_PATH = 8
OPT_ETAG = 4
OPT_BLOCK2, OPT_BLOCK1 = 23, 27          # RFC 7959 (emqx_coap_frame.erl
OPT_SIZE2, OPT_SIZE1 = 28, 60            # encode_option block1/block2)

CONTINUE_231 = 0x5F                      # 2.31 Continue
REQUEST_ENTITY_INCOMPLETE = 0x88         # 4.08
REQUEST_ENTITY_TOO_LARGE = 0x8D          # 4.13


def parse_block(v: bytes) -> tuple[int, int, int]:
    """Block option value → (num, more, size). RFC 7959 §2.2: 0-3 byte
    uint of NUM<<4 | M<<3 | SZX, size = 2^(SZX+4). SZX=7 is reserved
    (RFC 8323 repurposes it as BERT) — rejected, not misread as 2048."""
    u = int.from_bytes(v, "big")
    if (u & 0x07) == 7:
        raise ValueError("reserved SZX 7")
    return u >> 4, (u >> 3) & 1, 1 << ((u & 0x07) + 4)


def encode_block(num: int, more: int, size: int) -> bytes:
    szx = max(0, size.bit_length() - 5)
    u = (num << 4) | (more << 3) | szx
    n = max(1, (u.bit_length() + 7) // 8)
    return u.to_bytes(n, "big") if u else b"\x00"


@dataclass
class CoapMessage:
    type: int = CON
    code: int = EMPTY
    mid: int = 0
    token: bytes = b""
    options: list = field(default_factory=list)   # [(number, bytes)]
    payload: bytes = b""

    def opt(self, number: int) -> Optional[bytes]:
        for n, v in self.options:
            if n == number:
                return v
        return None

    def opts(self, number: int) -> list[bytes]:
        return [v for n, v in self.options if n == number]

    def uri_path(self) -> list[str]:
        return [v.decode("utf-8", "replace")
                for v in self.opts(OPT_URI_PATH)]

    def queries(self) -> dict[str, str]:
        out = {}
        for v in self.opts(OPT_URI_QUERY):
            k, _, val = v.decode("utf-8", "replace").partition("=")
            out[k] = val
        return out

    def observe(self) -> Optional[int]:
        v = self.opt(OPT_OBSERVE)
        if v is None:
            return None
        return int.from_bytes(v, "big") if v else 0


def _ext_decode(nibble: int, data: bytes, off: int) -> tuple[int, int]:
    if nibble == 13:
        return data[off] + 13, off + 1
    if nibble == 14:
        return struct.unpack_from(">H", data, off)[0] + 269, off + 2
    return nibble, off


def _ext_encode(value: int) -> tuple[int, bytes]:
    if value < 13:
        return value, b""
    if value < 269:
        return 13, bytes([value - 13])
    return 14, struct.pack(">H", value - 269)


class Frame(GwFrame):
    """One datagram = one message."""

    def parse(self, data: bytes, state) -> tuple[list, None]:
        if len(data) < 4:
            return [], None
        b0, code, mid = data[0], data[1], struct.unpack_from(">H", data, 2)[0]
        ver, typ, tkl = b0 >> 6, (b0 >> 4) & 0x3, b0 & 0xF
        if ver != 1 or tkl > 8:
            return [], None
        off = 4
        token, off = data[off:off + tkl], off + tkl
        options: list = []
        number = 0
        while off < len(data) and data[off] != 0xFF:
            d, ln = data[off] >> 4, data[off] & 0xF
            off += 1
            d, off = _ext_decode(d, data, off)
            ln, off = _ext_decode(ln, data, off)
            number += d
            options.append((number, data[off:off + ln]))
            off += ln
        payload = data[off + 1:] if off < len(data) else b""
        return [CoapMessage(typ, code, mid, token, options, payload)], None

    def serialize(self, m: CoapMessage) -> bytes:
        out = bytearray()
        out.append((1 << 6) | (m.type << 4) | len(m.token))
        out.append(m.code)
        out += struct.pack(">H", m.mid)
        out += m.token
        prev = 0
        for number, value in sorted(m.options, key=lambda o: o[0]):
            d, dext = _ext_encode(number - prev)
            ln, lext = _ext_encode(len(value))
            out.append((d << 4) | ln)
            out += dext + lext + value
            prev = number
        if m.payload:
            out.append(0xFF)
            out += m.payload
        return bytes(out)


def uri_path_opts(path: str) -> list:
    return [(OPT_URI_PATH, seg.encode())
            for seg in path.split("/") if seg]


# -- transport machine (emqx_coap_tm.erl) -----------------------------------

ACK_TIMEOUT = 2.0            # RFC 7252 §4.8
ACK_RANDOM_FACTOR = 1.5
MAX_RETRANSMIT = 4
EXCHANGE_LIFETIME = 247.0    # §4.8.2 — dedup window for CON exchanges
NON_LIFETIME = 145.0


class TransportManager:
    """Per-endpoint CoAP message-layer state (emqx_coap_tm.erl):

    - **inbound dedup**: a retransmitted CON (same mid inside
      EXCHANGE_LIFETIME) gets the CACHED response replayed instead of
      re-executing the request (publish/subscribe are not idempotent);
      duplicate NONs are dropped silently.
    - **outbound reliability**: CON messages we originate are tracked
      until ACK/RST, retransmitted with exponential backoff
      (ACK_TIMEOUT×ACK_RANDOM_FACTOR, doubling, MAX_RETRANSMIT tries);
      a give-up surfaces the mids so the channel can cancel state
      (e.g. drop a dead observer, §4.2).
    """

    def __init__(self, now_fn=None) -> None:
        import time as _time
        self.now = now_fn or _time.monotonic
        # inbound: mid → (request token, cached response frames,
        # expire_at). The token rides along because a retransmission is
        # BYTE-IDENTICAL (RFC 7252 §4.2): a fast client whose 16-bit mid
        # counter wraps inside EXCHANGE_LIFETIME reuses a mid for a NEW
        # exchange, and keying on the mid alone replayed the OLD cached
        # response at it (the parity-audit "MID-dedup window wrap" bug —
        # the request was silently swallowed). A differing token proves
        # the mid was recycled: evict, treat as fresh.
        self._seen: dict[int, tuple[bytes, list, float]] = {}
        # outbound: mid → [msg, tries, next_at, timeout]
        self._pending: dict[int, list] = {}

    # -- inbound dedup -------------------------------------------------------

    def dedup(self, m: CoapMessage):
        """None = fresh message; list = replay this cached response."""
        hit = self._seen.get(m.mid)
        if hit is None:
            return None
        token, frames, expire_at = hit
        if self.now() >= expire_at:
            del self._seen[m.mid]
            return None
        if token != m.token:
            # recycled mid (client counter wrapped): a new exchange,
            # not a retransmission — never replay the old response
            del self._seen[m.mid]
            return None
        return frames        # may be [] (duplicate NON → drop silently)

    def remember(self, m: CoapMessage, response: list) -> None:
        life = EXCHANGE_LIFETIME if m.type == CON else NON_LIFETIME
        self._seen[m.mid] = (m.token, list(response), self.now() + life)

    # -- outbound CON reliability -------------------------------------------

    def track(self, m: CoapMessage) -> CoapMessage:
        if m.type == CON:
            timeout = ACK_TIMEOUT * ACK_RANDOM_FACTOR
            self._pending[m.mid] = [m, 0, self.now() + timeout, timeout]
        return m

    def on_ack(self, mid: int) -> bool:
        return self._pending.pop(mid, None) is not None

    on_rst = on_ack

    def tick(self) -> tuple[list[CoapMessage], list[int]]:
        """(messages to retransmit now, mids given up on)."""
        now = self.now()
        retx: list[CoapMessage] = []
        gave_up: list[int] = []
        for mid, st in list(self._pending.items()):
            msg, tries, next_at, timeout = st
            if now < next_at:
                continue
            if tries >= MAX_RETRANSMIT:
                del self._pending[mid]
                gave_up.append(mid)
                continue
            st[1] = tries + 1
            st[3] = timeout * 2
            st[2] = now + st[3]
            retx.append(msg)
        # dedup-cache GC rides the same tick
        for mid, (_t, _f, exp) in list(self._seen.items()):
            if now >= exp:
                del self._seen[mid]
        return retx, gave_up

    def pending_count(self) -> int:
        return len(self._pending)


class Channel(GwChannel):
    """One CoAP endpoint (per UDP peer)."""

    PS_PREFIX = "ps"

    def __init__(self, ctx: GwContext) -> None:
        self.ctx = ctx
        self.conn_state = "connected"       # connectionless transport
        self.clientid: Optional[str] = None
        # topic → [token, qos, seq]: PER-OBSERVER 24-bit sequence
        # numbers (RFC 7641 §4.4 orders notifications per observation;
        # the old channel-wide counter also CRASHED in to_bytes(3) at
        # 2^24 — the parity-audit rollover bug). seq wraps mod 2^24.
        self.observers: dict[str, list] = {}
        self._mid = 0
        self._registered = False
        self.tm = TransportManager()
        self._con_topic: dict[int, str] = {}   # pending notify mid → topic
        # RFC 7959 block1 reassembly: uri-path → (next_num, buffer,
        # last_activity); one in-progress upload per path per endpoint
        self._block1: dict[str, tuple[int, bytearray, float]] = {}
        self.max_body = 64 * 1024              # 4.13 past this
        self.block2_size = 1024                # auto-slice threshold

    def _next_mid(self) -> int:
        self._mid = self._mid % 0xFFFF + 1
        return self._mid

    def _ensure_client(self, m: CoapMessage) -> bool:
        q = m.queries()
        want = q.get("clientid")
        if self._registered:
            if not want or want == self.clientid:
                return True
            # the peer RE-REGISTERS under a new identity (a rebooted or
            # re-provisioned device on the same 5-tuple): the old
            # session's observers must not leak into the new one — a
            # stale token the new client reuses for its own exchange
            # would mis-correlate notifications — and the new clientid
            # must be re-authenticated, not waved through (the SN
            # re-CONNECT ghost/ban-bypass analogue from the PR 6 audit)
            for topic in list(self.observers):
                self._cancel_observe(topic)
            self._con_topic.clear()
            self._block1.clear()
            self.ctx.close_session(self.clientid, self, "re-register")
            self._registered = False
        self.clientid = want or f"coap-{id(self):x}"
        if not self.ctx.authenticate(self.clientid,
                                     username=q.get("username"),
                                     password=q.get("password")):
            return False
        self.ctx.open_session(self.clientid, self)
        self._registered = True
        return True

    # -- inbound -------------------------------------------------------------

    def handle_in(self, m: CoapMessage) -> list[CoapMessage]:
        if m.code == EMPTY and m.type == CON:
            # CoAP ping (RFC 7252 §4.3): pong with RST. The client's mid
            # space is independent of ours — it must NOT settle a
            # pending notify that happens to share the number.
            return [CoapMessage(RST, EMPTY, m.mid, b"")]
        if m.type in (ACK, RST):
            # message-layer signal for a CON we originated (notify):
            # ACK settles it; RST additionally cancels the observation
            # (RFC 7641 §3.6 / emqx_coap_tm ack handling)
            if m.type == RST:
                self.tm.on_rst(m.mid)
                self._cancel_observe(self._con_topic.pop(m.mid, None))
            else:
                self.tm.on_ack(m.mid)
                self._con_topic.pop(m.mid, None)
            return []
        if m.code == EMPTY:
            return []               # NON empty: nothing to do
        cached = self.tm.dedup(m)
        if cached is not None:
            return list(cached)     # retransmitted request: replay reply
        out = self._handle_request(m)
        self.tm.remember(m, out)
        return out

    def _cancel_observe(self, topic: Optional[str]) -> None:
        if topic is not None and topic in self.observers:
            del self.observers[topic]
            self.ctx.unsubscribe(self.clientid, topic)

    def _handle_request(self, m: CoapMessage) -> list[CoapMessage]:
        reply_type = ACK if m.type == CON else NON
        path = m.uri_path()

        def reply(code: int, payload: bytes = b"", options=()) -> CoapMessage:
            return CoapMessage(reply_type, code, m.mid, m.token,
                               list(options), payload)

        if not path or path[0] != self.PS_PREFIX:
            return [reply(NOT_FOUND)]
        topic = "/".join(path[1:])
        if not topic:
            return [reply(BAD_REQUEST)]
        if not self._ensure_client(m):
            return [reply(UNAUTHORIZED)]

        if m.code in (PUT, POST):
            payload = m.payload
            b1 = m.opt(OPT_BLOCK1)
            if b1 is not None:                 # RFC 7959 block1 upload
                try:
                    num, more, size = parse_block(b1)
                except ValueError:
                    return [reply(BAD_REQUEST)]
                import time as _t
                cur = self._block1.get(topic)
                if num == 0:
                    cur = (0, bytearray(), 0.0)
                elif cur is None or cur[0] != num:
                    # out-of-order / unknown transfer: 4.08 (§2.5)
                    self._block1.pop(topic, None)
                    return [reply(REQUEST_ENTITY_INCOMPLETE,
                                  options=[(OPT_BLOCK1, b1)])]
                buf = cur[1]
                buf += m.payload
                if len(buf) > self.max_body:
                    self._block1.pop(topic, None)
                    return [reply(REQUEST_ENTITY_TOO_LARGE)]
                if more:
                    self._block1[topic] = (num + 1, buf, _t.monotonic())
                    return [reply(CONTINUE_231, options=[
                        (OPT_BLOCK1, encode_block(num, 1, size))])]
                self._block1.pop(topic, None)
                payload = bytes(buf)
            qos = int(m.queries().get("qos", 0))
            retain = m.queries().get("retain") in ("true", "1")
            self.ctx.publish(self.clientid, topic, payload, qos,
                             retain=retain)
            opts = ([(OPT_BLOCK1, b1)] if b1 is not None else [])
            return [reply(CHANGED, options=opts)]
        if m.code == GET:
            obs = m.observe()
            if obs == 0:
                qos = int(m.queries().get("qos", 0))
                self.observers[topic] = [m.token, qos, 1]
                self.ctx.subscribe(self.clientid, topic, qos=qos)
                return [reply(CONTENT, options=[
                    (OPT_OBSERVE, (1).to_bytes(3, "big"))])]
            if obs == 1:
                self._cancel_observe(topic if topic in self.observers
                                     else None)
                return [reply(CONTENT)]
            # plain read: latest retained message on the topic
            msgs = getattr(self.ctx.app, "retainer", None)
            if msgs is not None:
                found = msgs.match(self.ctx.mount(topic))
                if found:
                    body = found[-1].payload
                    b2 = m.opt(OPT_BLOCK2)
                    if b2 is None and len(body) <= self.block2_size:
                        return [reply(CONTENT, payload=body)]
                    # RFC 7959 block2 download: client-requested block
                    # (or server-initiated slicing past the threshold).
                    # Stateless: each block re-reads the retained store;
                    # the ETag (§2.4) lets the client detect a retained
                    # update between blocks instead of accepting a TORN
                    # concatenation of old and new bodies.
                    try:
                        num, _more, size = (parse_block(b2)
                                            if b2 is not None
                                            else (0, 0, self.block2_size))
                    except ValueError:
                        return [reply(BAD_REQUEST)]
                    lo = num * size
                    if lo >= len(body) and num:
                        return [reply(BAD_REQUEST)]
                    chunk = body[lo:lo + size]
                    more = 1 if lo + size < len(body) else 0
                    import zlib as _z
                    etag = _z.crc32(body).to_bytes(4, "big")
                    return [reply(CONTENT, payload=chunk, options=[
                        (OPT_ETAG, etag),
                        (OPT_BLOCK2, encode_block(num, more, size)),
                        (OPT_SIZE2, len(body).to_bytes(
                            max(1, (len(body).bit_length() + 7) // 8),
                            "big"))])]
            return [reply(NOT_FOUND)]
        if m.code == DELETE:
            return [reply(DELETED)]
        return [reply(NOT_ALLOWED)]

    # -- outbound ------------------------------------------------------------

    def handle_deliver(self, deliveries: list) -> list[CoapMessage]:
        out = []
        for sub_topic, msg in deliveries:
            plain = self.ctx.unmount(msg.topic)
            token = qos = None
            obs = None
            for obs_topic, rec in self.observers.items():
                from emqx_tpu.core import topic as T
                if T.match(plain, obs_topic):
                    token, qos, obs_topic_hit = rec[0], rec[1], obs_topic
                    obs = rec
                    break
            if token is None:
                continue
            # the observation's OWN rolling sequence, wrapping mod 2^24
            # (the Observe option is a 3-byte uint — RFC 7641 §4.4; the
            # old shared counter crashed in to_bytes at the boundary)
            obs[2] = (obs[2] + 1) & 0xFFFFFF
            # QoS≥1 subscriptions notify as CON: tracked, retransmitted,
            # observation cancelled on RST or give-up (emqx_coap
            # notify_type per-subscription qos)
            mtype = CON if qos else NON
            mid = self._next_mid()
            note = CoapMessage(
                mtype, CONTENT, mid, token,
                [(OPT_OBSERVE, obs[2].to_bytes(3, "big"))],
                msg.payload)
            if mtype == CON:
                self.tm.track(note)
            # NON notifies are remembered too: a client that lost its
            # observe state answers RST, which must cancel the
            # observation for ANY notification type (RFC 7641 §3.6)
            self._con_topic[mid] = obs_topic_hit
            if len(self._con_topic) > 512:        # bound NON history —
                # but never evict a mid whose CON is still awaiting ACK
                # (losing it would orphan the give-up/RST cancel path)
                for old in list(self._con_topic):
                    if old not in self.tm._pending:
                        del self._con_topic[old]
                        break
            out.append(note)
        return out

    def housekeep(self) -> list[CoapMessage]:
        """Listener tick: retransmit due CONs; a give-up drops the dead
        observer (RFC 7641 §4.5 — stop notifying unresponsive clients)."""
        retx, gave_up = self.tm.tick()
        for mid in gave_up:
            self._cancel_observe(self._con_topic.pop(mid, None))
        # abandoned block1 uploads must not pin buffers forever: a
        # 60s idle TTL frees them; past the cap, evict the STALEST
        # (never an actively-progressing upload in insertion order)
        import time as _t
        now = _t.monotonic()
        stale = [k for k, (_n, _b, at) in self._block1.items()
                 if now - at > 60.0]
        for k in stale:
            del self._block1[k]
        while len(self._block1) > 8:
            oldest = min(self._block1, key=lambda k: self._block1[k][2])
            del self._block1[oldest]
        return retx

    def terminate(self, reason: str) -> None:
        if self._registered:
            self._registered = False
            self.ctx.close_session(self.clientid, self, reason)


class CoapGateway(GatewayImpl):
    name = "coap"

    def __init__(self, host: str = "127.0.0.1", port: int = 5683) -> None:
        self.host, self.port = host, port
        self.listener = None
        self.ctx: Optional[GwContext] = None

    def on_gateway_load(self, ctx: GwContext, conf: dict) -> None:
        from emqx_tpu.gateway.conn import UdpGwListener

        self.ctx = ctx
        self.host = conf.get("host", self.host)
        self.port = conf.get("port", self.port)
        self.listener = UdpGwListener(
            lambda: Channel(self.ctx), Frame(),
            host=self.host, port=self.port)

    async def start_listeners(self) -> None:
        await self.listener.start()
        self.port = self.listener.port

    async def stop_listeners(self) -> None:
        await self.listener.stop()
