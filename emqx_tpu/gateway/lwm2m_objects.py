"""LwM2M object registry — the ``apps/emqx_gateway/src/lwm2m`` XML
object-definition store (emqx_lwm2m_xml_object.erl + the OMA registry
DDF files it loads), as data.

The reference ships OMA DDF XML for the core objects and uses them to
translate numeric paths (``/3/0/0``) to names (``Device/Manufacturer``),
validate operations (Read/Write/Execute per resource), and type wire
values. This registry covers OMA core objects 0-7 with the same
surface: lookup by object id or name, resource metadata, path
translation both ways, and operation checks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class LwResource:
    rid: int
    name: str
    operations: str          # subset of "RWE"
    type: str = "String"     # String|Integer|Float|Boolean|Opaque|Time|Objlnk
    mandatory: bool = False
    multiple: bool = False


@dataclass(frozen=True)
class LwObject:
    oid: int
    name: str
    urn: str
    multiple: bool
    resources: tuple
    _by_id: dict = field(default_factory=dict, compare=False)

    def resource(self, rid: int) -> Optional[LwResource]:
        if not self._by_id:
            self._by_id.update({r.rid: r for r in self.resources})
        return self._by_id.get(rid)


def _obj(oid, name, urn, multiple, rows) -> LwObject:
    return LwObject(oid, name, urn, multiple, tuple(
        LwResource(*row) for row in rows))


# OMA LwM2M core objects (oma.org registry; column order:
# rid, name, operations, type, mandatory, multiple)
OBJECTS: dict[int, LwObject] = {o.oid: o for o in [
    _obj(0, "LWM2M Security", "urn:oma:lwm2m:oma:0", True, [
        (0, "LWM2M Server URI", "", "String", True),
        (1, "Bootstrap-Server", "", "Boolean", True),
        (2, "Security Mode", "", "Integer", True),
        (3, "Public Key or Identity", "", "Opaque", True),
        (4, "Server Public Key", "", "Opaque", True),
        (5, "Secret Key", "", "Opaque", True),
        (10, "Short Server ID", "", "Integer"),
    ]),
    _obj(1, "LWM2M Server", "urn:oma:lwm2m:oma:1", True, [
        (0, "Short Server ID", "R", "Integer", True),
        (1, "Lifetime", "RW", "Integer", True),
        (2, "Default Minimum Period", "RW", "Integer"),
        (3, "Default Maximum Period", "RW", "Integer"),
        (4, "Disable", "E"),
        (5, "Disable Timeout", "RW", "Integer"),
        (6, "Notification Storing When Disabled or Offline", "RW",
         "Boolean", True),
        (7, "Binding", "RW", "String", True),
        (8, "Registration Update Trigger", "E", "", True),
    ]),
    _obj(2, "LWM2M Access Control", "urn:oma:lwm2m:oma:2", True, [
        (0, "Object ID", "R", "Integer", True),
        (1, "Object Instance ID", "R", "Integer", True),
        (2, "ACL", "RW", "Integer", False, True),
        (3, "Access Control Owner", "RW", "Integer", True),
    ]),
    _obj(3, "Device", "urn:oma:lwm2m:oma:3", False, [
        (0, "Manufacturer", "R"),
        (1, "Model Number", "R"),
        (2, "Serial Number", "R"),
        (3, "Firmware Version", "R"),
        (4, "Reboot", "E", "", True),
        (5, "Factory Reset", "E"),
        (6, "Available Power Sources", "R", "Integer", False, True),
        (7, "Power Source Voltage", "R", "Integer", False, True),
        (8, "Power Source Current", "R", "Integer", False, True),
        (9, "Battery Level", "R", "Integer"),
        (10, "Memory Free", "R", "Integer"),
        (11, "Error Code", "R", "Integer", True, True),
        (12, "Reset Error Code", "E"),
        (13, "Current Time", "RW", "Time"),
        (14, "UTC Offset", "RW"),
        (15, "Timezone", "RW"),
        (16, "Supported Binding and Modes", "R", "String", True),
    ]),
    _obj(4, "Connectivity Monitoring", "urn:oma:lwm2m:oma:4", False, [
        (0, "Network Bearer", "R", "Integer", True),
        (1, "Available Network Bearer", "R", "Integer", True, True),
        (2, "Radio Signal Strength", "R", "Integer", True),
        (3, "Link Quality", "R", "Integer"),
        (4, "IP Addresses", "R", "String", True, True),
        (5, "Router IP Addresses", "R", "String", False, True),
        (6, "Link Utilization", "R", "Integer"),
        (7, "APN", "R", "String", False, True),
        (8, "Cell ID", "R", "Integer"),
        (9, "SMNC", "R", "Integer"),
        (10, "SMCC", "R", "Integer"),
    ]),
    _obj(5, "Firmware Update", "urn:oma:lwm2m:oma:5", False, [
        (0, "Package", "W", "Opaque", True),
        (1, "Package URI", "W", "String", True),
        (2, "Update", "E", "", True),
        (3, "State", "R", "Integer", True),
        (5, "Update Result", "R", "Integer", True),
        (6, "PkgName", "R"),
        (7, "PkgVersion", "R"),
    ]),
    _obj(6, "Location", "urn:oma:lwm2m:oma:6", False, [
        (0, "Latitude", "R", "Float", True),
        (1, "Longitude", "R", "Float", True),
        (2, "Altitude", "R", "Float"),
        (3, "Radius", "R", "Float"),
        (4, "Velocity", "R", "Opaque"),
        (5, "Timestamp", "R", "Time", True),
        (6, "Speed", "R", "Float"),
    ]),
    _obj(7, "Connectivity Statistics", "urn:oma:lwm2m:oma:7", False, [
        (0, "SMS Tx Counter", "R", "Integer"),
        (1, "SMS Rx Counter", "R", "Integer"),
        (2, "Tx Data", "R", "Integer"),
        (3, "Rx Data", "R", "Integer"),
        (4, "Max Message Size", "R", "Integer"),
        (5, "Average Message Size", "R", "Integer"),
        (6, "Start", "E", "", True),
        (7, "Stop", "E", "", True),
    ]),
]}

_BY_NAME = {o.name: o for o in OBJECTS.values()}


def object_by_id(oid: int) -> Optional[LwObject]:
    return OBJECTS.get(oid)


def object_by_name(name: str) -> Optional[LwObject]:
    return _BY_NAME.get(name)


def parse_path(path: str) -> tuple:
    """'/3/0/9' → (3, 0, 9); missing levels are None; non-numeric
    segments make the whole path unknown (None, None, None)."""
    parts = [p for p in path.split("/") if p != ""]
    out = []
    for p in parts[:3]:
        try:
            out.append(int(p))
        except ValueError:
            return (None, None, None)
    while len(out) < 3:
        out.append(None)
    return tuple(out)


def translate_path(path: str) -> Optional[str]:
    """'/3/0/0' → 'Device/0/Manufacturer' (None for unknown objects —
    the reference answers {error, no_xml_definition})."""
    oid, inst, rid = parse_path(path)
    obj = OBJECTS.get(oid)
    if obj is None:
        return None
    parts = [obj.name]
    if inst is not None:
        parts.append(str(inst))
    if rid is not None:
        res = obj.resource(rid)
        parts.append(res.name if res is not None else str(rid))
    return "/".join(parts)


def check_operation(path: str, op: str) -> bool:
    """Is ``op`` ('R'|'W'|'E') allowed at the resource?  Object/instance
    level allows R/W (covers discover/observe). VENDOR objects (outside
    the core registry) are permitted — the gateway has no definition to
    validate against, so the device decides (the reference only rejects
    when it HAS an XML def that forbids the op). A malformed path is
    rejected."""
    oid, _inst, rid = parse_path(path)
    if oid is None:
        return False                    # malformed path
    obj = OBJECTS.get(oid)
    if obj is None:
        return True                     # vendor object: forward as-is
    if rid is None:
        return op in ("R", "W")
    res = obj.resource(rid)
    if res is None:
        return False
    return op in res.operations


def parse_core_links(payload: str) -> list[dict]:
    """CoRE link-format registration payload ('</3/0>,</5>;ver=1.0') →
    [{path, oid, instance, name}] with registry names resolved."""
    out = []
    for link in payload.split(","):
        link = link.strip()
        if not link.startswith("<"):
            continue
        target = link[1:link.index(">")] if ">" in link else ""
        if not target or target == "/":
            continue
        oid, inst, _ = parse_path(target)
        if oid is None:
            continue
        obj = OBJECTS.get(oid)
        out.append({
            "path": target,
            "oid": oid,
            "instance": inst,
            "name": obj.name if obj is not None else None,
        })
    return out
