"""Polyglot edge-protocol gateways — the ``emqx_gateway`` app
(STOMP, MQTT-SN, CoAP, LwM2M, ExProto behind shared behaviours)."""

from emqx_tpu.gateway.ctx import (          # noqa: F401
    GatewayManager, GwContext, GwFrame, GwChannel, GatewayImpl,
)
