"""ExProto over real gRPC — the ``emqx.exproto.v1`` services of
``apps/emqx_gateway/src/exproto/protos/exproto.proto``:

- the broker STREAMS socket/message events to the external service's
  ``ConnectionHandler`` (client-streaming RPCs, emqx_exproto_gcli.erl);
- the external service drives the connection back through the
  broker-hosted ``ConnectionAdapter`` (7 unary RPCs,
  emqx_exproto_gsvr.erl): Send/Close/Authenticate/StartTimer/Publish/
  Subscribe/Unsubscribe, addressed by the ``conn`` ref.

Schemas ride the generic proto3 codec from exhook/pbwire.py. The
framed-transport gateway (gateway/exproto.py) remains the
dependency-free alternative; this module is selected with
``ExprotoGateway(conf={"transport": "grpc", ...})`` equivalents in
tests and direct construction.

``GrpcProtocolHandlerHost`` hosts a user protocol implementation as the
external service side — in production that's the user's own gRPC
server in any language; here it doubles as the test harness
(the exproto_echo_svr analogue).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Optional

from emqx_tpu.exhook.pbwire import decode, encode
from emqx_tpu.gateway.ctx import GatewayImpl, GwChannel, GwContext

# ---------------------------------------------------------------------------
# emqx.exproto.v1 schemas (exproto.proto field numbers)

ADDRESS = {1: ("host", "str"), 2: ("port", "u32")}
CERT_INFO = {1: ("cn", "str"), 2: ("dn", "str")}
CONN_INFO = {1: ("socktype", "enum"), 2: ("peername", "msg", ADDRESS),
             3: ("sockname", "msg", ADDRESS),
             4: ("peercert", "msg", CERT_INFO)}
CLIENT_INFO = {1: ("proto_name", "str"), 2: ("proto_ver", "str"),
               3: ("clientid", "str"), 4: ("username", "str"),
               5: ("mountpoint", "str")}
MESSAGE = {1: ("node", "str"), 2: ("id", "str"), 3: ("qos", "u32"),
           4: ("from", "str"), 5: ("topic", "str"),
           6: ("payload", "bytes"), 7: ("timestamp", "u64")}

CODE_RESPONSE = {1: ("code", "enum"), 2: ("message", "str")}
EMPTY_SUCCESS: dict = {}

# ConnectionAdapter (broker-hosted) request schemas
ADAPTER_RPCS = {
    "Send": {1: ("conn", "str"), 2: ("bytes", "bytes")},
    "Close": {1: ("conn", "str")},
    "Authenticate": {1: ("conn", "str"),
                     2: ("clientinfo", "msg", CLIENT_INFO),
                     3: ("password", "str")},
    "StartTimer": {1: ("conn", "str"), 2: ("type", "enum"),
                   3: ("interval", "u32")},
    "Publish": {1: ("conn", "str"), 2: ("topic", "str"), 3: ("qos", "u32"),
                4: ("payload", "bytes")},
    "Subscribe": {1: ("conn", "str"), 2: ("topic", "str"),
                  3: ("qos", "u32")},
    "Unsubscribe": {1: ("conn", "str"), 2: ("topic", "str")},
}

# ConnectionHandler (external service) event schemas — client-streaming
HANDLER_RPCS = {
    "OnSocketCreated": {1: ("conn", "str"),
                        2: ("conninfo", "msg", CONN_INFO)},
    "OnSocketClosed": {1: ("conn", "str"), 2: ("reason", "str")},
    "OnReceivedBytes": {1: ("conn", "str"), 2: ("bytes", "bytes")},
    "OnTimerTimeout": {1: ("conn", "str"), 2: ("type", "enum")},
    "OnReceivedMessages": {1: ("conn", "str"),
                           2: ("messages", ("rep", "msg"), MESSAGE)},
}

ADAPTER_SERVICE = "emqx.exproto.v1.ConnectionAdapter"
HANDLER_SERVICE = "emqx.exproto.v1.ConnectionHandler"

RC_SUCCESS, RC_UNKNOWN, RC_NOT_ALIVE, RC_PARAMS, RC_TYPE, RC_DENY = range(6)

_IDENT = lambda b: b      # noqa: E731


# ---------------------------------------------------------------------------
# broker → handler event streams


class HandlerClient:
    """Client-streaming event lanes to the external ConnectionHandler:
    one long-lived stream per RPC, queue-fed, transparently reopened on
    failure (emqx_exproto_gcli keeps per-RPC gRPC streams the same
    way)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0) -> None:
        import grpc

        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self.timeout_s = timeout_s
        self._lanes: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()
        self._closed = False

    def _lane(self, rpc: str) -> queue.Queue:
        with self._lock:
            q = self._lanes.get(rpc)
            if q is None:
                q = queue.Queue()
                self._lanes[rpc] = q
                self._start_stream(rpc)
            return q

    def _start_stream(self, rpc: str) -> None:
        stub = self._channel.stream_unary(
            f"/{HANDLER_SERVICE}/{rpc}",
            request_serializer=_IDENT, response_deserializer=_IDENT)

        def feed(q: queue.Queue):
            while True:
                item = q.get()
                if item is None:
                    return
                yield item

        def run():
            while True:
                with self._lock:
                    if self._closed:
                        return
                    cur = self._lanes[rpc]
                try:
                    stub(feed(cur))        # completes when feed() ends
                    return                 # clean close()
                except Exception:          # noqa: BLE001 — stream died.
                    # grpcio's request-consumer thread may still be
                    # blocked inside feed(cur).q.get(): swap a FRESH
                    # queue in for new events, then poison the old one
                    # so the abandoned consumer exits instead of eating
                    # a future event. Events in the old queue are lost
                    # (fire-and-forget, like the reference's async gcli
                    # casts).
                    import time
                    with self._lock:
                        if self._closed:
                            return
                        self._lanes[rpc] = queue.Queue()
                    cur.put(None)
                    time.sleep(0.2)

        threading.Thread(target=run, daemon=True,
                         name=f"exproto-grpc-{rpc}").start()

    def emit(self, rpc: str, values: dict) -> None:
        self._lane(rpc).put(encode(HANDLER_RPCS[rpc], values))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for q in self._lanes.values():
                q.put(None)
        self._channel.close()


# ---------------------------------------------------------------------------
# handler → broker adapter service


class AdapterServer:
    """Broker-hosted ConnectionAdapter: routes unary calls by conn ref
    to live channels (emqx_exproto_gsvr.erl)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 workers: int = 4) -> None:
        from emqx_tpu.exhook.grpc_transport import make_grpc_server

        self.channels: dict[str, "GrpcChannel"] = {}
        self._server, self.port = make_grpc_server(
            ADAPTER_SERVICE, ADAPTER_RPCS, self._dispatch,
            host=host, port=port, workers=workers)

    def _code(self, code: int, message: str = "") -> bytes:
        return encode(CODE_RESPONSE, {"code": code, "message": message})

    def _dispatch(self, rpc: str, req: bytes) -> bytes:
        try:
            request = decode(ADAPTER_RPCS[rpc], req)
        except ValueError as e:
            return self._code(RC_TYPE, str(e))
        ch = self.channels.get(request.get("conn", ""))
        if ch is None:
            return self._code(RC_NOT_ALIVE, "conn process not alive")
        try:
            return self._code(*ch.handle_adapter(rpc, request))
        except Exception as e:      # noqa: BLE001 — protocol reply
            return self._code(RC_UNKNOWN, str(e))

    def start(self) -> "AdapterServer":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=0.2)


# ---------------------------------------------------------------------------
# the channel


class GrpcChannel(GwChannel):
    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, ctx: GwContext, handler: HandlerClient,
                 adapter: AdapterServer) -> None:
        self.ctx = ctx
        self.handler = handler
        self.adapter = adapter
        with GrpcChannel._seq_lock:
            GrpcChannel._seq += 1
            self.conn_ref = f"grpc-conn-{GrpcChannel._seq}"
        self.conn_state = "connected"
        self.clientid: Optional[str] = None
        self.peername: Optional[tuple] = None   # bound by the transport
        adapter.channels[self.conn_ref] = self

    def on_socket_ready(self) -> None:
        """Transport bound (peername now real) — announce the socket."""
        peer = self.peername or ("0.0.0.0", 0)
        self.handler.emit("OnSocketCreated", {
            "conn": self.conn_ref,
            "conninfo": {"socktype": 0,
                         "peername": {"host": str(peer[0]),
                                      "port": int(peer[1])}}})

    # -- adapter ops (called from gRPC worker threads) -----------------------

    def handle_adapter(self, rpc: str, req: dict) -> tuple[int, str]:
        if rpc == "Send":
            self.send([req.get("bytes", b"")])
            return RC_SUCCESS, ""
        if rpc == "Close":
            self.conn_state = "disconnected"
            self.request_close()
            return RC_SUCCESS, ""
        if rpc == "Authenticate":
            ci = req.get("clientinfo") or {}
            cid = ci.get("clientid") or ""
            if not cid:
                return RC_PARAMS, "clientid required"
            if not self.ctx.authenticate(cid, ci.get("username") or None,
                                         req.get("password") or None):
                return RC_DENY, "authentication failed"
            self.clientid = cid
            self.ctx.open_session(cid, self)
            return RC_SUCCESS, ""
        if self.clientid is None:
            return RC_DENY, "not authenticated"
        if rpc == "Publish":
            self.ctx.publish(self.clientid, req.get("topic", ""),
                             req.get("payload", b""),
                             int(req.get("qos", 0)))
            return RC_SUCCESS, ""
        if rpc == "Subscribe":
            self.ctx.subscribe(self.clientid, req.get("topic", ""),
                               int(req.get("qos", 0)))
            return RC_SUCCESS, ""
        if rpc == "Unsubscribe":
            self.ctx.unsubscribe(self.clientid, req.get("topic", ""))
            return RC_SUCCESS, ""
        if rpc == "StartTimer":
            # KEEPALIVE timer: the conn loop owns idle timeouts; accept
            return RC_SUCCESS, ""
        return RC_TYPE, f"unsupported rpc {rpc}"

    # -- GwChannel -----------------------------------------------------------

    def handle_in(self, data: bytes) -> list:
        self.handler.emit("OnReceivedBytes",
                          {"conn": self.conn_ref, "bytes": data})
        return []          # replies arrive via adapter Send

    def handle_deliver(self, deliveries: list) -> list:
        self.handler.emit("OnReceivedMessages", {
            "conn": self.conn_ref,
            "messages": [{
                "id": str(msg.id), "qos": msg.qos, "from": str(msg.from_),
                "topic": self.ctx.unmount(msg.topic),
                "payload": msg.payload, "timestamp": msg.timestamp,
            } for _st, msg in deliveries]})
        return []

    def terminate(self, reason: str) -> None:
        if self.conn_state != "terminated":
            self.conn_state = "terminated"
            self.adapter.channels.pop(self.conn_ref, None)
            self.handler.emit("OnSocketClosed",
                              {"conn": self.conn_ref, "reason": reason})
            if self.clientid is not None:
                self.ctx.close_session(self.clientid, self, reason)
            self.request_close()      # admin kick drops the transport


class GrpcExprotoGateway(GatewayImpl):
    """The gRPC-transport exproto gateway: TCP listener + adapter
    server + handler event streams."""

    name = "exproto"

    def __init__(self, handler_host: str = "127.0.0.1",
                 handler_port: int = 9100, host: str = "127.0.0.1",
                 port: int = 7993, adapter_port: int = 0) -> None:
        self.handler_addr = (handler_host, handler_port)
        self.host, self.port = host, port
        self.adapter_port = adapter_port
        self.listener = None
        self.adapter: Optional[AdapterServer] = None
        self.handler: Optional[HandlerClient] = None
        self.ctx: Optional[GwContext] = None

    def on_gateway_load(self, ctx: GwContext, conf: dict) -> None:
        from emqx_tpu.gateway.conn import TcpGwListener
        from emqx_tpu.gateway.exproto import RawFrame

        self.ctx = ctx
        self.host = conf.get("host", self.host)
        self.port = conf.get("port", self.port)
        if "handler_host" in conf or "handler_port" in conf:
            self.handler_addr = (conf.get("handler_host", "127.0.0.1"),
                                 conf.get("handler_port", 9100))
        self.adapter = AdapterServer(
            port=int(conf.get("adapter_port", self.adapter_port))).start()
        self.handler = HandlerClient(*self.handler_addr)
        self.listener = TcpGwListener(
            lambda: GrpcChannel(self.ctx, self.handler, self.adapter),
            RawFrame(), host=self.host, port=self.port)

    async def start_listeners(self) -> None:
        await self.listener.start()
        self.port = self.listener.port

    async def stop_listeners(self) -> None:
        await self.listener.stop()
        if self.handler is not None:
            self.handler.close()
        if self.adapter is not None:
            self.adapter.stop()


# ---------------------------------------------------------------------------
# external-service side host (test harness / SDK)


class GrpcProtocolHandlerHost:
    """Host a protocol implementation as the emqx.exproto.v1
    ConnectionHandler service, with an adapter-client bound back to the
    broker (the role a user's gRPC service plays; in-repo analogue of
    the reference's exproto_echo_svr).

    impl contract (all optional):
      on_socket_created(conn, conninfo, adapter),
      on_received_bytes(conn, data, adapter),
      on_received_messages(conn, messages, adapter),
      on_socket_closed(conn, reason), on_timer_timeout(conn, type).
    ``adapter`` exposes send/close/authenticate/publish/subscribe/
    unsubscribe/start_timer — each returns (code, message).
    """

    def __init__(self, impl: Any, host: str = "127.0.0.1",
                 port: int = 0, workers: int = 4) -> None:
        from emqx_tpu.exhook.grpc_transport import make_grpc_server

        self.impl = impl
        self.adapter_client: Optional["AdapterClient"] = None
        self._server, self.port = make_grpc_server(
            HANDLER_SERVICE, HANDLER_RPCS, self._consume,
            streaming=True, host=host, port=port, workers=workers)

    def connect_adapter(self, host: str, port: int) -> None:
        self.adapter_client = AdapterClient(host, port)

    def _consume(self, rpc: str, it) -> bytes:
        for raw in it:
            event = decode(HANDLER_RPCS[rpc], raw)
            conn = event.get("conn", "")
            if rpc == "OnSocketCreated":
                fn = getattr(self.impl, "on_socket_created", None)
                if fn:
                    fn(conn, event.get("conninfo") or {},
                       self.adapter_client)
            elif rpc == "OnReceivedBytes":
                fn = getattr(self.impl, "on_received_bytes", None)
                if fn:
                    fn(conn, event.get("bytes", b""), self.adapter_client)
            elif rpc == "OnReceivedMessages":
                fn = getattr(self.impl, "on_received_messages", None)
                if fn:
                    fn(conn, event.get("messages", []),
                       self.adapter_client)
            elif rpc == "OnSocketClosed":
                fn = getattr(self.impl, "on_socket_closed", None)
                if fn:
                    fn(conn, event.get("reason", ""))
            elif rpc == "OnTimerTimeout":
                fn = getattr(self.impl, "on_timer_timeout", None)
                if fn:
                    fn(conn, event.get("type", 0))
        return b""                                 # EmptySuccess

    def start(self) -> "GrpcProtocolHandlerHost":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=0.2)


class AdapterClient:
    """The external service's view of the broker-hosted
    ConnectionAdapter (7 unary RPCs)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0) -> None:
        import grpc

        self._channel = grpc.insecure_channel(f"{host}:{port}")
        self.timeout_s = timeout_s
        self._stubs: dict[str, Any] = {}

    def _call(self, rpc: str, values: dict) -> tuple[int, str]:
        import grpc

        stub = self._stubs.get(rpc)
        if stub is None:
            stub = self._channel.unary_unary(
                f"/{ADAPTER_SERVICE}/{rpc}",
                request_serializer=_IDENT, response_deserializer=_IDENT)
            self._stubs[rpc] = stub
        try:
            resp = stub(encode(ADAPTER_RPCS[rpc], values),
                        timeout=self.timeout_s)
        except grpc.RpcError as e:
            raise ConnectionError(f"adapter {rpc}: {e.code().name}") \
                from None
        out = decode(CODE_RESPONSE, resp)
        return out.get("code", RC_UNKNOWN), out.get("message", "")

    def send(self, conn: str, data: bytes):
        return self._call("Send", {"conn": conn, "bytes": data})

    def close(self, conn: str):
        return self._call("Close", {"conn": conn})

    def authenticate(self, conn: str, clientid: str,
                     username: str = "", password: str = ""):
        return self._call("Authenticate", {
            "conn": conn, "password": password,
            "clientinfo": {"proto_name": "exproto", "proto_ver": "1",
                           "clientid": clientid, "username": username}})

    def start_timer(self, conn: str, interval: int):
        return self._call("StartTimer",
                          {"conn": conn, "type": 0, "interval": interval})

    def publish(self, conn: str, topic: str, payload: bytes, qos: int = 0):
        return self._call("Publish", {"conn": conn, "topic": topic,
                                      "qos": qos, "payload": payload})

    def subscribe(self, conn: str, topic: str, qos: int = 0):
        return self._call("Subscribe",
                          {"conn": conn, "topic": topic, "qos": qos})

    def unsubscribe(self, conn: str, topic: str):
        return self._call("Unsubscribe", {"conn": conn, "topic": topic})

    def close_channel(self) -> None:
        self._channel.close()
