"""ExProto gateway — parity with ``apps/emqx_gateway/src/exproto/``
(emqx_exproto_gsvr.erl / _gcli.erl): the *protocol itself* lives in an
external service. The gateway owns the socket and the broker seam; every
socket event is RPC'd to the external ConnectionHandler, which answers
with a command list.

Wire: the same length-prefixed codec frames as exhook (the reference
reuses its gRPC stack for both; we reuse ours — emqx_tpu/exhook/proto.py).

Handler RPCs (mirror exproto.proto ConnectionHandler):
    OnSocketCreated{conn, peername}       → commands
    OnReceivedBytes{conn, bytes_hex}      → commands
    OnReceivedMessages{conn, messages}    → commands
    OnSocketClosed{conn}

Commands (the ConnectionAdapter surface the external service drives):
    {"type": "send",        "bytes_hex": ...}
    {"type": "authenticate","clientid": ..., "username":?, "password":?}
    {"type": "publish",     "topic": ..., "payload_hex": ..., "qos":?}
    {"type": "subscribe",   "topic": ..., "qos":?}
    {"type": "unsubscribe", "topic": ...}
    {"type": "close"}
"""

from __future__ import annotations

import queue
import socket
import socketserver
import threading
from typing import Any, Optional

from emqx_tpu.exhook import proto as rpc
from emqx_tpu.gateway.ctx import GatewayImpl, GwChannel, GwContext, GwFrame


class RawFrame(GwFrame):
    """Pass-through: the external handler does the parsing."""

    def parse(self, data: bytes, state) -> tuple[list, Any]:
        return [data], state

    def serialize(self, pkt: bytes) -> bytes:
        return pkt


class Channel(GwChannel):
    _seq = 0

    def __init__(self, ctx: GwContext, handler_addr: tuple[str, int],
                 timeout_s: float = 5.0) -> None:
        self.ctx = ctx
        self.handler_addr = handler_addr
        self.timeout_s = timeout_s
        Channel._seq += 1
        self.conn_ref = f"conn-{Channel._seq}"
        self.conn_state = "connected"
        self.clientid: Optional[str] = None
        self._sock: Optional[socket.socket] = None
        # handler RPCs are blocking network calls and must never run on
        # the broker's event loop — a per-channel worker serializes them
        # (per-connection ordering) and pushes replies via the
        # thread-safe ``send`` the conn adapter binds
        self._queue: "queue.Queue" = queue.Queue()
        self._worker = threading.Thread(
            target=self._drain, daemon=True,
            name=f"exproto-{self.conn_ref}")
        self._worker.start()
        self._enqueue("OnSocketCreated",
                      {"conn": self.conn_ref, "peername": "tcp"})

    # -- RPC to the external handler (worker thread only) --------------------

    def _enqueue(self, rpc_name: str, args: dict) -> None:
        self._queue.put((rpc_name, args))

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                break
            frames = self._call(*item)
            if frames:
                self.send(frames)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, rpc_name: str, args: dict) -> list:
        try:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self.handler_addr, timeout=self.timeout_s)
            rpc.send_frame(self._sock, {"rpc": rpc_name, "args": args})
            resp = rpc.recv_frame(self._sock)
        except OSError:
            self._sock = None
            return self._exec([{"type": "close"}])
        if resp is None or resp.get("error"):
            return []
        return self._exec(resp.get("result") or [])

    def _exec(self, commands: list) -> list:
        """Run adapter commands; returns frames to send to the device."""
        out = []
        for cmd in commands:
            kind = cmd.get("type")
            if kind == "send":
                out.append(bytes.fromhex(cmd.get("bytes_hex", "")))
            elif kind == "authenticate":
                cid = cmd.get("clientid") or f"exproto-{self.conn_ref}"
                if self.ctx.authenticate(cid, cmd.get("username"),
                                         cmd.get("password")):
                    self.clientid = cid
                    self.ctx.open_session(cid, self)
            elif kind == "publish" and self.clientid:
                self.ctx.publish(
                    self.clientid, cmd["topic"],
                    bytes.fromhex(cmd.get("payload_hex", "")),
                    int(cmd.get("qos", 0)))
            elif kind == "subscribe" and self.clientid:
                self.ctx.subscribe(self.clientid, cmd["topic"],
                                   int(cmd.get("qos", 0)))
            elif kind == "unsubscribe" and self.clientid:
                self.ctx.unsubscribe(self.clientid, cmd["topic"])
            elif kind == "close":
                self.conn_state = "disconnected"
                # the conn loop only polls conn_state after inbound data;
                # we're on the worker thread, so drop the transport actively
                self.request_close()
        return out

    # -- GwChannel -----------------------------------------------------------

    def handle_in(self, data: bytes) -> list[bytes]:
        self._enqueue("OnReceivedBytes",
                      {"conn": self.conn_ref, "bytes_hex": data.hex()})
        return []      # replies arrive via send() once the worker answers

    def handle_deliver(self, deliveries: list) -> list[bytes]:
        msgs = [{
            "topic": self.ctx.unmount(msg.topic),
            "payload_hex": msg.payload.hex(),
            "qos": msg.qos,
        } for _st, msg in deliveries]
        self._enqueue("OnReceivedMessages",
                      {"conn": self.conn_ref, "messages": msgs})
        return []

    def terminate(self, reason: str) -> None:
        if self.conn_state != "terminated":
            self.conn_state = "terminated"
            self._enqueue("OnSocketClosed",
                          {"conn": self.conn_ref, "reason": reason})
            self._queue.put(None)     # worker closes the RPC socket
            if self.clientid is not None:
                self.ctx.close_session(self.clientid, self, reason)
            self.request_close()      # admin kick drops the transport


class ExprotoGateway(GatewayImpl):
    name = "exproto"

    def __init__(self, handler_host: str = "127.0.0.1",
                 handler_port: int = 9100,
                 host: str = "127.0.0.1", port: int = 7993) -> None:
        self.handler_addr = (handler_host, handler_port)
        self.host, self.port = host, port
        self.listener = None
        self.ctx: Optional[GwContext] = None

    def on_gateway_load(self, ctx: GwContext, conf: dict) -> None:
        from emqx_tpu.gateway.conn import TcpGwListener

        self.ctx = ctx
        self.host = conf.get("host", self.host)
        self.port = conf.get("port", self.port)
        if "handler_host" in conf or "handler_port" in conf:
            self.handler_addr = (conf.get("handler_host", "127.0.0.1"),
                                 conf.get("handler_port", 9100))
        self.listener = TcpGwListener(
            lambda: Channel(self.ctx, self.handler_addr), RawFrame(),
            host=self.host, port=self.port)

    async def start_listeners(self) -> None:
        await self.listener.start()
        self.port = self.listener.port

    async def stop_listeners(self) -> None:
        await self.listener.stop()


class ConnectionHandler:
    """Base class for external protocol implementations (the role the
    user's gRPC service plays against the reference). Override the
    ``on_*`` methods; each returns a command list."""

    def dispatch(self, rpc_name: str, args: dict) -> list:
        fn = getattr(self, _snake(rpc_name), None)
        return fn(args) if fn is not None else []

    def on_socket_created(self, args: dict) -> list:
        return []

    def on_received_bytes(self, args: dict) -> list:
        return []

    def on_received_messages(self, args: dict) -> list:
        return []

    def on_socket_closed(self, args: dict) -> list:
        return []


def _snake(rpc_name: str) -> str:
    out = []
    for ch in rpc_name:
        if ch.isupper() and out:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class HandlerServer:
    """Threaded TCP host for a ConnectionHandler (the demo external
    service; production handlers are separate processes)."""

    def __init__(self, handler: ConnectionHandler,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        h = handler

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = rpc.recv_frame(self.request)
                    except OSError:
                        return
                    if req is None:
                        return
                    try:
                        result = h.dispatch(req.get("rpc", ""),
                                            req.get("args") or {})
                        resp = {"result": result}
                    except Exception as e:      # noqa: BLE001 — relay
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    try:
                        rpc.send_frame(self.request, resp)
                    except OSError:
                        return

        class _S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _S((host, port), _H)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="exproto-handler")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
