"""STOMP 1.2 gateway — parity with
``apps/emqx_gateway/src/stomp/`` (frame: emqx_stomp_frame.erl,
channel: emqx_stomp_channel.erl).

STOMP destinations map 1:1 onto topics. SEND publishes; SUBSCRIBE
(id + destination) bridges into the broker; deliveries come back as
MESSAGE frames carrying ``subscription``/``message-id``. RECEIPT is
honored on any client frame carrying ``receipt``; ERROR closes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.gateway.ctx import GatewayImpl, GwChannel, GwContext, GwFrame

SUPPORTED_VERSIONS = ("1.0", "1.1", "1.2")


@dataclass
class StompFrame:
    command: str
    headers: dict = field(default_factory=dict)
    body: bytes = b""


# -- codec (emqx_stomp_frame.erl) -------------------------------------------

def _unescape(s: str) -> str:
    return (s.replace("\\r", "\r").replace("\\n", "\n")
             .replace("\\c", ":").replace("\\\\", "\\"))


def _escape(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\r", "\\r")
             .replace("\n", "\\n").replace(":", "\\c"))


class Frame(GwFrame):
    def initial_parse_state(self, opts: Optional[dict] = None) -> bytes:
        return b""

    def parse(self, data: bytes, state: bytes) -> tuple[list, bytes]:
        buf = (state or b"") + data
        out: list[StompFrame] = []
        while True:
            # heart-beats: bare EOLs between frames
            buf = buf.lstrip(b"\r\n")
            # header block ends at the first blank line — LF or CRLF
            # line endings are both spec-legal (STOMP 1.2 §augmented BNF)
            p_lf, p_crlf = buf.find(b"\n\n"), buf.find(b"\r\n\r\n")
            if p_crlf >= 0 and (p_lf < 0 or p_crlf < p_lf):
                head, body_start = buf[:p_crlf], p_crlf + 4
            elif p_lf >= 0:
                head, body_start = buf[:p_lf], p_lf + 2
            else:
                break                                 # incomplete head
            lines = head.decode("utf-8", "replace").split("\n")
            command = lines[0].strip("\r")
            headers: dict = {}
            for line in lines[1:]:
                line = line.rstrip("\r")
                if not line:
                    continue
                k, _, v = line.partition(":")
                # repeated header: first occurrence wins (spec)
                headers.setdefault(_unescape(k), _unescape(v))
            # content-length framing lets bodies carry NUL bytes;
            # without it the body ends at the first NUL
            clen = headers.get("content-length")
            if clen is not None and clen.isdigit():
                n = int(clen)
                if len(buf) < body_start + n + 1:
                    break                             # incomplete body
                body = buf[body_start:body_start + n]
                buf = buf[body_start + n + 1:]        # skip the NUL
            else:
                end = buf.find(b"\x00", body_start)
                if end < 0:
                    break
                body = buf[body_start:end]
                buf = buf[end + 1:]
            out.append(StompFrame(command, headers, body))
        return out, buf

    def serialize(self, pkt: StompFrame) -> bytes:
        if pkt.command == "":            # server heart-beat
            return b"\n"
        lines = [pkt.command]
        hdrs = dict(pkt.headers)
        if pkt.body and "content-length" not in hdrs:
            hdrs["content-length"] = str(len(pkt.body))
        for k, v in hdrs.items():
            lines.append(f"{_escape(str(k))}:{_escape(str(v))}")
        return ("\n".join(lines) + "\n\n").encode() + pkt.body + b"\x00"


# -- channel (emqx_stomp_channel.erl) ---------------------------------------

class Channel(GwChannel):
    def __init__(self, ctx: GwContext) -> None:
        self.ctx = ctx
        self.conn_state = "idle"
        self.clientid: Optional[str] = None
        self.subs: dict[str, str] = {}       # sub id -> destination
        self._msg_seq = 0
        # STOMP transactions (emqx_stomp_channel.erl:453,547): BEGIN
        # opens a buffer; SEND/ACK/NACK carrying `transaction` defer
        # into it; COMMIT replays in order; ABORT (or the timeout in
        # housekeep) discards. txid → (started_at_monotonic, [thunks])
        self._tx: dict[str, tuple[float, list]] = {}
        self.tx_timeout_s = 60.0
        self.max_tx = 16                 # concurrent txs per channel
        self.max_tx_ops = 1000           # buffered frames per tx
        self._session_open = False

    # -- inbound -------------------------------------------------------------

    def handle_in(self, frame: StompFrame) -> list[StompFrame]:
        cmd = frame.command.upper()
        if self.conn_state in ("disconnected", "terminated"):
            return []        # kicked/closed: drop, never publish
        if self.conn_state == "idle" and cmd not in ("CONNECT", "STOMP"):
            return [self._error("Not connected")]
        try:
            handler = getattr(self, f"_in_{cmd.lower()}", None)
            if handler is None:
                return [self._error(f"Unknown command {cmd}")]
            out = handler(frame)
        except Exception as e:
            return [self._error(str(e))]
        receipt = frame.headers.get("receipt")
        if receipt and cmd not in ("CONNECT", "STOMP") and not any(
                f.command == "ERROR" for f in out):
            # STOMP: a failed frame answers ERROR, never RECEIPT — a
            # RECEIPT after ERROR would tell the client its COMMIT of
            # an expired transaction succeeded
            out.append(StompFrame("RECEIPT", {"receipt-id": receipt}))
        return out

    def _in_connect(self, frame: StompFrame) -> list[StompFrame]:
        if self.conn_state == "connected":
            return [self._error("Already connected")]
        accepts = (frame.headers.get("accept-version") or "1.0").split(",")
        version = max((v for v in accepts if v in SUPPORTED_VERSIONS),
                      default=None)
        if version is None:
            return [self._error("Supported protocol versions < 1.2")]
        login = frame.headers.get("login")
        self.clientid = (frame.headers.get("client-id")
                         or login or f"stomp-{id(self):x}")
        if not self.ctx.authenticate(
                self.clientid, username=login,
                password=frame.headers.get("passcode")):
            return [self._error("Login failed")]
        self.ctx.open_session(self.clientid, self)
        self._session_open = True
        self.conn_state = "connected"
        return [StompFrame("CONNECTED", {
            "version": version, "server": "emqx-tpu",
            "heart-beat": frame.headers.get("heart-beat", "0,0"),
        })]

    _in_stomp = _in_connect

    def _in_send(self, frame: StompFrame) -> list[StompFrame]:
        dest = frame.headers.get("destination")
        if not dest:
            return [self._error("Missing destination")]

        def do(dest=dest, body=frame.body, headers=dict(frame.headers)):
            self.ctx.publish(self.clientid, dest, body,
                             qos=0, props={
                                 k: v for k, v in headers.items()
                                 if k not in ("destination", "receipt",
                                              "content-length",
                                              "transaction")
                             })
        return self._maybe_defer(frame, do)

    # -- transactions --------------------------------------------------------

    def _maybe_defer(self, frame: StompFrame, thunk) -> list[StompFrame]:
        txid = frame.headers.get("transaction")
        if txid is None:
            thunk()
            return []
        tx = self._tx.get(txid)
        if tx is None:
            return [self._error(f"Transaction {txid} not found")]
        if len(tx[1]) >= self.max_tx_ops:   # bound buffered bodies
            self._tx.pop(txid, None)
            return [self._error(f"Transaction {txid} too large")]
        tx[1].append(thunk)
        return []

    def _in_begin(self, frame: StompFrame) -> list[StompFrame]:
        import time
        txid = frame.headers.get("transaction")
        if not txid:
            return [self._error("Missing transaction")]
        if txid in self._tx:
            return [self._error(f"Transaction {txid} already started")]
        if len(self._tx) >= self.max_tx:     # bound tx count
            return [self._error("Too many open transactions")]
        self._tx[txid] = (time.monotonic(), [])
        return []

    def _in_commit(self, frame: StompFrame) -> list[StompFrame]:
        txid = frame.headers.get("transaction")
        tx = self._tx.pop(txid, None)
        if tx is None:
            return [self._error(f"Transaction {txid} not found")]
        for thunk in tx[1]:
            thunk()
        return []

    def _in_abort(self, frame: StompFrame) -> list[StompFrame]:
        txid = frame.headers.get("transaction")
        if self._tx.pop(txid, None) is None:
            return [self._error(f"Transaction {txid} not found")]
        return []

    def housekeep(self) -> list[StompFrame]:
        import time
        now = time.monotonic()
        dead = [txid for txid, (at, _ops) in self._tx.items()
                if now - at > self.tx_timeout_s]
        for txid in dead:
            del self._tx[txid]
        return []

    def _in_subscribe(self, frame: StompFrame) -> list[StompFrame]:
        sid = frame.headers.get("id")
        dest = frame.headers.get("destination")
        if not sid or not dest:
            return [self._error("Missing id or destination")]
        if sid in self.subs:
            return [self._error(f"Subscription id {sid} already exists")]
        self.subs[sid] = dest
        self.ctx.subscribe(self.clientid, dest, qos=0)
        return []

    def _in_unsubscribe(self, frame: StompFrame) -> list[StompFrame]:
        sid = frame.headers.get("id")
        dest = self.subs.pop(sid, None)
        if dest is not None and dest not in self.subs.values():
            self.ctx.unsubscribe(self.clientid, dest)
        return []

    def _in_ack(self, frame: StompFrame) -> list[StompFrame]:
        # QoS0 bridge: ack itself is a no-op (reference parity) — but a
        # transactional ack must still validate its transaction
        return self._maybe_defer(frame, lambda: None)

    def _in_nack(self, frame: StompFrame) -> list[StompFrame]:
        return self._maybe_defer(frame, lambda: None)

    def _in_disconnect(self, frame: StompFrame) -> list[StompFrame]:
        self.conn_state = "disconnected"
        return []

    # -- outbound ------------------------------------------------------------

    def handle_deliver(self, deliveries: list) -> list[StompFrame]:
        out = []
        for sub_topic, msg in deliveries:
            plain = self.ctx.unmount(sub_topic)
            for sid, dest in self.subs.items():
                if _dest_match(plain, dest):
                    self._msg_seq += 1
                    out.append(StompFrame("MESSAGE", {
                        "subscription": sid,
                        "message-id": str(self._msg_seq),
                        "destination": self.ctx.unmount(msg.topic),
                    }, msg.payload))
                    break
        return out

    def terminate(self, reason: str) -> None:
        # session cleanup keys on _session_open, NOT conn_state: a
        # graceful DISCONNECT (or an ERROR) flips conn_state before the
        # transport teardown reaches here, and gating on it would leak
        # ghost entries into ctx.sessions / the gateway REST surface
        if self._session_open:
            self._session_open = False
            self.ctx.close_session(self.clientid, self, reason)
        if self.conn_state != "terminated":
            self.conn_state = "terminated"
            self._tx.clear()
            # an admin kick must actually drop the socket, not leave it
            # open until the client's next frame
            self.request_close()

    def _error(self, text: str) -> StompFrame:
        # STOMP 1.2 §ERROR: the server MUST close the connection just
        # after sending an ERROR frame. The TCP adapter closes on
        # conn_state == "disconnected" after flushing our reply; the
        # explicit request_close() makes the close adapter-independent
        # (it is deferred via call_soon_threadsafe, so the ERROR frame
        # is written before the socket drops — never a half-open
        # session whose subsequent frames we silently swallow).
        self.conn_state = "disconnected"
        self.request_close()
        return StompFrame("ERROR", {"message": text}, text.encode())


def _dest_match(topic: str, dest: str) -> bool:
    from emqx_tpu.core import topic as T
    return T.match(topic, dest)


class StompGateway(GatewayImpl):
    name = "stomp"

    def __init__(self, host: str = "127.0.0.1", port: int = 61613) -> None:
        self.host, self.port = host, port
        self.listener = None
        self.ctx: Optional[GwContext] = None

    def on_gateway_load(self, ctx: GwContext, conf: dict) -> None:
        from emqx_tpu.gateway.conn import TcpGwListener

        self.ctx = ctx
        self.host = conf.get("host", self.host)
        self.port = conf.get("port", self.port)
        self.listener = TcpGwListener(
            lambda: Channel(self.ctx), Frame(),
            host=self.host, port=self.port)

    async def start_listeners(self) -> None:
        await self.listener.start()
        self.port = self.listener.port

    async def stop_listeners(self) -> None:
        await self.listener.stop()
