"""MQTT-SN 1.2 gateway over UDP — parity with
``apps/emqx_gateway/src/mqttsn/`` (frame: emqx_sn_frame.erl, channel:
emqx_sn_channel.erl, topic-id registry: emqx_sn_registry.erl).

Topic-id spaces: normal (per-client REGISTER/auto-register on deliver),
predefined (gateway-wide table from config), short (2-char names).
QoS0/1 bridge plus the spec's QoS -1 publish-without-connect for
predefined topics.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.gateway.ctx import GatewayImpl, GwChannel, GwContext, GwFrame

# message types (MQTT-SN 1.2 §5.2.1)
ADVERTISE, SEARCHGW, GWINFO = 0x00, 0x01, 0x02
CONNECT, CONNACK = 0x04, 0x05
WILLTOPICREQ, WILLTOPIC, WILLMSGREQ, WILLMSG = 0x06, 0x07, 0x08, 0x09
REGISTER, REGACK = 0x0A, 0x0B
PUBLISH, PUBACK, PUBCOMP, PUBREC, PUBREL = 0x0C, 0x0D, 0x0E, 0x0F, 0x10
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 0x12, 0x13, 0x14, 0x15
PINGREQ, PINGRESP, DISCONNECT = 0x16, 0x17, 0x18

RC_ACCEPTED, RC_CONGESTION, RC_INVALID_TOPIC_ID, RC_NOT_SUPPORTED = 0, 1, 2, 3

# the long-form length prefix is a u16: no SN message may exceed 65535
# wire bytes (§5.2.1). Oversized deliveries DROP at the translation seam
# on both planes (sn.h kMaxPayload/kMaxTopic mirror these).
MAX_PAYLOAD = 0xFFFF - 9          # PUBLISH overhead: long-form 2 + 7
MAX_TOPIC = 0xFFFF - 8            # REGISTER overhead: long-form 2 + 6

# flag bits
F_DUP, F_RETAIN, F_WILL, F_CLEAN = 0x80, 0x10, 0x08, 0x04
TID_NORMAL, TID_PREDEF, TID_SHORT = 0, 1, 2


def qos_of(flags: int) -> int:
    q = (flags >> 5) & 0x3
    return -1 if q == 3 else q


def qos_flags(qos: int) -> int:
    return 0x60 if qos < 0 else (qos & 0x3) << 5


@dataclass
class SnMessage:
    type: int
    flags: int = 0
    topic_id: int = 0
    msg_id: int = 0
    topic_name: str = ""
    data: bytes = b""
    duration: int = 0
    clientid: str = ""
    rc: int = 0


class Frame(GwFrame):
    """One datagram = one message (length-prefixed, emqx_sn_frame.erl)."""

    def parse(self, data: bytes, state) -> tuple[list, None]:
        out = []
        while data:
            if data[0] == 0x01:
                if len(data) < 3:
                    break
                (ln,) = struct.unpack_from(">H", data, 1)
                if ln < 4:          # length covers the 3-byte prefix + type
                    break           # malformed: refuse, don't spin
                if ln > len(data):  # truncated: refuse (a shorter slice
                    break           # would crash the body parse below —
                                    # oracle-parity audit; the native
                                    # codec stops at the same boundary)
                body, data = data[3:ln], data[ln:]
            else:
                ln = data[0]
                if ln < 2:          # ln==0/1 would not consume any bytes
                    break
                if ln > len(data):  # truncated datagram: refuse
                    break
                body, data = data[1:ln], data[ln:]
            if body:
                out.append(self._parse_body(body))
        return out, None

    def _parse_body(self, b: bytes) -> SnMessage:
        t = b[0]
        m = SnMessage(type=t)
        if t == CONNECT:
            m.flags, _proto = b[1], b[2]
            (m.duration,) = struct.unpack_from(">H", b, 3)
            m.clientid = b[5:].decode("utf-8", "replace")
        elif t in (CONNACK, WILLTOPICREQ, WILLMSGREQ, PINGRESP):
            if len(b) > 1:
                m.rc = b[1]
        elif t == REGISTER:
            m.topic_id, m.msg_id = struct.unpack_from(">HH", b, 1)
            m.topic_name = b[5:].decode("utf-8", "replace")
        elif t == REGACK:
            m.topic_id, m.msg_id = struct.unpack_from(">HH", b, 1)
            m.rc = b[5]
        elif t == PUBLISH:
            m.flags = b[1]
            m.topic_id, m.msg_id = struct.unpack_from(">HH", b, 2)
            m.data = b[6:]
        elif t == PUBACK:
            m.topic_id, m.msg_id = struct.unpack_from(">HH", b, 1)
            m.rc = b[5]
        elif t in (PUBREC, PUBREL, PUBCOMP, UNSUBACK):
            (m.msg_id,) = struct.unpack_from(">H", b, 1)
        elif t in (SUBSCRIBE, UNSUBSCRIBE):
            m.flags = b[1]
            (m.msg_id,) = struct.unpack_from(">H", b, 2)
            rest = b[4:]
            if m.flags & 0x3 in (TID_PREDEF,):
                (m.topic_id,) = struct.unpack_from(">H", rest, 0)
            else:
                m.topic_name = rest.decode("utf-8", "replace")
        elif t == SUBACK:
            m.flags = b[1]
            m.topic_id, m.msg_id = struct.unpack_from(">HH", b, 2)
            m.rc = b[6]
        elif t == PINGREQ:
            m.clientid = b[1:].decode("utf-8", "replace")
        elif t == DISCONNECT:
            if len(b) >= 3:
                (m.duration,) = struct.unpack_from(">H", b, 1)
        elif t == SEARCHGW:
            m.rc = b[1] if len(b) > 1 else 0       # radius
        return m

    def serialize(self, m: SnMessage) -> bytes:
        t = m.type
        if t == CONNACK:
            body = bytes([t, m.rc])
        elif t == CONNECT:
            body = bytes([t, m.flags, 0x01]) + struct.pack(
                ">H", m.duration) + m.clientid.encode()
        elif t == REGISTER:
            body = bytes([t]) + struct.pack(
                ">HH", m.topic_id, m.msg_id) + m.topic_name.encode()
        elif t == REGACK:
            body = bytes([t]) + struct.pack(
                ">HH", m.topic_id, m.msg_id) + bytes([m.rc])
        elif t == PUBLISH:
            body = bytes([t, m.flags]) + struct.pack(
                ">HH", m.topic_id, m.msg_id) + m.data
        elif t == PUBACK:
            body = bytes([t]) + struct.pack(
                ">HH", m.topic_id, m.msg_id) + bytes([m.rc])
        elif t in (PUBREC, PUBREL, PUBCOMP, UNSUBACK):
            body = bytes([t]) + struct.pack(">H", m.msg_id)
        elif t in (SUBSCRIBE, UNSUBSCRIBE):
            body = bytes([t, m.flags]) + struct.pack(">H", m.msg_id)
            if m.flags & 0x3 == TID_PREDEF:
                body += struct.pack(">H", m.topic_id)
            else:
                body += m.topic_name.encode()
        elif t == SUBACK:
            body = bytes([t, m.flags]) + struct.pack(
                ">HH", m.topic_id, m.msg_id) + bytes([m.rc])
        elif t == PINGREQ:
            # a sleeping client's wake ping carries its clientid
            # (MQTT-SN §5.4.21) — the old bare serialization couldn't
            # round-trip what parse() reads (oracle-parity audit)
            body = bytes([t]) + m.clientid.encode()
        elif t == PINGRESP:
            body = bytes([t])
        elif t == DISCONNECT:
            # duration > 0 = the client announces sleep (§5.4.22); the
            # old serializer dropped it, so a real client built on this
            # codec could never ENTER sleep mode (oracle-parity audit)
            body = bytes([t])
            if m.duration:
                body += struct.pack(">H", m.duration)
        elif t == GWINFO:
            body = bytes([t, m.rc])
        elif t == ADVERTISE:
            body = bytes([t, m.rc]) + struct.pack(">H", m.duration)
        else:
            body = bytes([t])
        ln = len(body) + 1
        if ln < 256:
            return bytes([ln]) + body
        return b"\x01" + struct.pack(">H", ln + 2) + body


class Registry:
    """Gateway-wide predefined ids + per-client registered ids
    (emqx_sn_registry.erl)."""

    def __init__(self, predefined: Optional[dict[int, str]] = None) -> None:
        self.predefined = dict(predefined or {})

    def predefined_topic(self, tid: int) -> Optional[str]:
        return self.predefined.get(tid)


class Channel(GwChannel):
    def __init__(self, ctx: GwContext, registry: Registry) -> None:
        self.ctx = ctx
        self.registry = registry
        self.conn_state = "idle"
        self.clientid: Optional[str] = None
        self.topic_of_id: dict[int, str] = {}      # normal ids, per client
        self.id_of_topic: dict[str, int] = {}
        self._next_tid = 0
        self._next_mid = 0
        # publisher-side qos2 exactly-once: msg ids published but not
        # yet released (the broker-side "method B" hold, like the core
        # session's awaiting-rel set)
        self._awaiting_rel: set[int] = set()
        self.awake = True
        self._sleep_buffer: list = []   # deliveries parked during sleep
        self.max_sleep_buffer = 1000    # drop-oldest past this (mqueue-ish)
        self.sleep_until: Optional[float] = None   # wall-clock deadline

    def _alloc_tid(self, topic: str) -> int:
        tid = self.id_of_topic.get(topic)
        if tid is None:
            # wrap in 1..0xFFFE skipping ids still in use — 0x0000 AND
            # 0xFFFF are both reserved (§5.3.11); the old unbounded
            # counter overflowed struct.pack(">H") after 65535
            # registrations (oracle-parity audit: the native registry
            # wraps, this one crashed)
            for _ in range(0xFFFE):
                self._next_tid = self._next_tid % 0xFFFE + 1
                if self._next_tid not in self.topic_of_id:
                    break
            else:
                return 0            # registry full: no id assignable
            tid = self._next_tid
            self.id_of_topic[topic] = tid
            self.topic_of_id[tid] = topic
        return tid

    def _mid(self) -> int:
        self._next_mid = self._next_mid % 0xFFFF + 1
        return self._next_mid

    def _resolve(self, m: SnMessage) -> Optional[str]:
        kind = m.flags & 0x3
        if kind == TID_PREDEF:
            return self.registry.predefined_topic(m.topic_id)
        if kind == TID_SHORT:
            return struct.pack(">H", m.topic_id).decode("latin1")
        return self.topic_of_id.get(m.topic_id)

    # -- inbound -------------------------------------------------------------

    def handle_in(self, m: SnMessage) -> list[SnMessage]:
        t = m.type
        if t == SEARCHGW:
            return [SnMessage(GWINFO, rc=1)]       # gw id 1
        if t == CONNECT:
            new_cid = m.clientid or f"sn-{id(self):x}"
            # a re-CONNECT under a different clientid must release the
            # old registration first, or it leaks as a ghost session
            if getattr(self, "_session_open", False) \
                    and self.clientid != new_cid:
                self._session_open = False
                self.ctx.close_session(self.clientid, self,
                                       "reconnected")
            self.clientid = new_cid
            if not self.ctx.authenticate(self.clientid):
                # a rejected (re-)CONNECT must fully de-authenticate the
                # channel: staying "connected" would let the next
                # PUBLISH run as the DENIED identity (ban bypass). A
                # same-clientid re-CONNECT that got denied (freshly
                # banned) also releases its still-open session — it must
                # not linger as a ghost registration
                if getattr(self, "_session_open", False):
                    self._session_open = False
                    self.ctx.close_session(new_cid, self, "auth_denied")
                self.conn_state = "idle"
                self.clientid = None
                return [SnMessage(CONNACK, rc=RC_NOT_SUPPORTED)]
            self.ctx.open_session(self.clientid, self)
            self._session_open = True
            self.conn_state = "connected"
            # every (re-)CONNECT starts fresh per-session gateway state
            # — native-plane parity (a re-CONNECT there is a brand-new
            # conn): the topic-id registry, the qos2 dedup set, and
            # sleep state do not survive the session boundary. A stale
            # _awaiting_rel entry would otherwise swallow a rebooted
            # client's qos2 publish reusing the same msg id (PUBREC
            # answered, ctx.publish skipped — silent loss).
            self.id_of_topic = {}
            self.topic_of_id = {}
            self._awaiting_rel = set()
            self.awake = True
            self._sleep_buffer = []
            return [SnMessage(CONNACK, rc=RC_ACCEPTED)]
        if t == PUBLISH and qos_of(m.flags) == -1:
            # QoS -1: fire-and-forget on a predefined/short topic,
            # no connection required (MQTT-SN §6.8)
            topic = (self.registry.predefined_topic(m.topic_id)
                     if m.flags & 0x3 == TID_PREDEF else self._resolve(m))
            if topic:
                self.ctx.publish(m.clientid or "sn-anon", topic, m.data, 0,
                                 retain=bool(m.flags & F_RETAIN))
            return []
        if self.conn_state != "connected":
            return ([SnMessage(DISCONNECT)]
                    if t not in (PINGREQ, DISCONNECT) else [])
        if t == REGISTER:
            tid = self._alloc_tid(m.topic_name)
            # tid 0 is the reserved invalid id: a full registry answers
            # "rejected: congestion" (native-plane parity), never a
            # success carrying an id the client cannot publish on
            return [SnMessage(REGACK, topic_id=tid, msg_id=m.msg_id,
                              rc=RC_ACCEPTED if tid else RC_CONGESTION)]
        if t == PUBLISH:
            topic = self._resolve(m)
            qos = max(0, qos_of(m.flags))
            if topic is None:
                return ([SnMessage(PUBACK, topic_id=m.topic_id,
                                   msg_id=m.msg_id,
                                   rc=RC_INVALID_TOPIC_ID)]
                        if qos > 0 else [])
            if qos == 2:
                # exactly-once, broker "method B" (publish on PUBLISH,
                # hold the id until PUBREL): the old code answered
                # PUBACK to a qos2 publish — a spec violation (§6.13
                # mandates PUBREC) AND a double-publish on every DUP
                # retransmit (oracle-parity audit)
                if m.msg_id not in self._awaiting_rel:
                    self._awaiting_rel.add(m.msg_id)
                    self.ctx.publish(self.clientid, topic, m.data, qos,
                                     retain=bool(m.flags & F_RETAIN))
                return [SnMessage(PUBREC, msg_id=m.msg_id)]
            self.ctx.publish(self.clientid, topic, m.data, qos,
                             retain=bool(m.flags & F_RETAIN))
            if qos > 0:
                return [SnMessage(PUBACK, topic_id=m.topic_id,
                                  msg_id=m.msg_id, rc=RC_ACCEPTED)]
            return []
        if t == PUBREL:
            # release half of the qos2 exchange; a PUBREL for an id we
            # no longer hold still completes with PUBCOMP [MQTT-4.3.3]
            self._awaiting_rel.discard(m.msg_id)
            return [SnMessage(PUBCOMP, msg_id=m.msg_id)]
        if t == SUBSCRIBE:
            kind = m.flags & 0x3
            if kind == TID_PREDEF:
                topic = self.registry.predefined_topic(m.topic_id)
                tid = m.topic_id
            else:
                topic = m.topic_name
                # wildcard filters get no id (delivery registers one)
                tid = (0 if ("#" in topic or "+" in topic)
                       else self._alloc_tid(topic))
            if not topic:
                return [SnMessage(SUBACK, flags=m.flags, topic_id=0,
                                  msg_id=m.msg_id,
                                  rc=RC_INVALID_TOPIC_ID)]
            # grant what delivery can honour: handle_deliver caps every
            # outbound PUBLISH at qos1, so granting a requested qos2 was
            # a lie on the wire (oracle-parity audit — the native plane
            # grants the same cap)
            qos = min(1, max(0, qos_of(m.flags)))
            if not self.ctx.subscribe(self.clientid, topic, qos):
                return [SnMessage(SUBACK, flags=m.flags, topic_id=0,
                                  msg_id=m.msg_id, rc=RC_NOT_SUPPORTED)]
            return [SnMessage(SUBACK, flags=qos_flags(qos), topic_id=tid,
                              msg_id=m.msg_id, rc=RC_ACCEPTED)]
        if t == UNSUBSCRIBE:
            topic = (self.registry.predefined_topic(m.topic_id)
                     if m.flags & 0x3 == TID_PREDEF else m.topic_name)
            if topic:
                self.ctx.unsubscribe(self.clientid, topic)
            return [SnMessage(UNSUBACK, msg_id=m.msg_id)]
        if t == PUBACK:
            return []
        if t == PINGREQ:
            # waking from sleep flushes parked messages, then PINGRESP
            # (MQTT-SN §6.14: buffered delivery on the keepalive ping)
            self.awake = True
            self.sleep_until = None
            parked, self._sleep_buffer = self._sleep_buffer, []
            return self.handle_deliver(parked) + [SnMessage(PINGRESP)]
        if t == DISCONNECT:
            if m.duration:           # sleep mode: keep session, stop io
                self.awake = False
                import time as _time
                self.sleep_until = _time.time() + m.duration
                return [SnMessage(DISCONNECT)]
            self.conn_state = "disconnected"
            return [SnMessage(DISCONNECT)]
        return []

    # -- outbound ------------------------------------------------------------

    def handle_deliver(self, deliveries: list) -> list[SnMessage]:
        if not self.awake:
            # asleep (radio off): park until the next PINGREQ, bounded
            # drop-oldest like the session mqueue
            self._sleep_buffer.extend(deliveries)
            overflow = len(self._sleep_buffer) - self.max_sleep_buffer
            if overflow > 0:
                del self._sleep_buffer[:overflow]
            return []
        out: list[SnMessage] = []
        for _sub_topic, msg in deliveries:
            topic = self.ctx.unmount(msg.topic)
            if (len(msg.payload) > MAX_PAYLOAD
                    or len(topic.encode()) > MAX_TOPIC):
                # can't fit the u16 wire length: drop, exactly like the
                # native plane — serializing would raise mid-delivery
                continue
            tid = self.id_of_topic.get(topic)
            if tid is None:
                # auto-register so the client can decode the id
                tid = self._alloc_tid(topic)
                if not tid:
                    # registry full: nothing deliverable on this topic —
                    # drop, exactly like the native plane (SnDeliverTid
                    # returns 0 and bails); emitting the reserved id 0
                    # on the wire would be a protocol violation
                    continue
                out.append(SnMessage(REGISTER, topic_id=tid,
                                     msg_id=self._mid(),
                                     topic_name=topic))
            out.append(SnMessage(
                PUBLISH, flags=qos_flags(min(msg.qos, 1)),
                topic_id=tid,
                msg_id=self._mid() if msg.qos else 0,
                data=msg.payload))
        return out

    def terminate(self, reason: str) -> None:
        # key on an open session, not conn_state: a device-initiated
        # DISCONNECT flips conn_state before the UDP listener calls
        # terminate, which would leak the session registration
        if getattr(self, "_session_open", False):
            self._session_open = False
            self.ctx.close_session(self.clientid, self, reason)
        self.conn_state = "disconnected"


class MqttsnGateway(GatewayImpl):
    name = "mqttsn"

    def __init__(self, host: str = "127.0.0.1", port: int = 1884,
                 predefined: Optional[dict[int, str]] = None) -> None:
        self.host, self.port = host, port
        self.registry = Registry(predefined)
        self.listener = None
        self.ctx: Optional[GwContext] = None

    def on_gateway_load(self, ctx: GwContext, conf: dict) -> None:
        from emqx_tpu.gateway.conn import UdpGwListener

        self.ctx = ctx
        self.host = conf.get("host", self.host)
        self.port = conf.get("port", self.port)
        for tid, topic in (conf.get("predefined") or {}).items():
            self.registry.predefined[int(tid)] = topic
        self.listener = UdpGwListener(
            lambda: Channel(self.ctx, self.registry), Frame(),
            host=self.host, port=self.port)

    async def start_listeners(self) -> None:
        await self.listener.start()
        self.port = self.listener.port

    async def stop_listeners(self) -> None:
        await self.listener.stop()
