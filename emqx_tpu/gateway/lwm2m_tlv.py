"""OMA LwM2M TLV content codec — the ``emqx_lwm2m_tlv.erl`` +
value-translation half of ``emqx_lwm2m_message.erl``.

Wire format (OMA-TS-LightweightM2M §6.4.3): each entry is

    type byte: bits 7-6 identifier kind (00 object instance,
               01 resource instance, 10 multiple resource,
               11 resource with value)
               bit 5    identifier width (0: 1 byte, 1: 2 bytes)
               bits 4-3 length width (00: bits 2-0 hold the length,
               01/10/11: 1/2/3 extra length bytes)
    identifier, [length], value  — nested for instance containers.

Values type against the object registry (lwm2m_objects.py, the XML DDF
store): Integer/Time are signed big-endian 1/2/4/8 bytes, Float is
IEEE754 4/8, Boolean one byte, String UTF-8, Opaque raw, Objlnk two
uint16s. ``tlv_to_path_values`` / ``path_values_to_tlv`` are the
JSON↔TLV halves the reference's command translator uses for Read
responses, Notify bodies and Write payloads.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

from emqx_tpu.gateway import lwm2m_objects as objects

OBJ_INSTANCE, RES_INSTANCE, MULTI_RES, RESOURCE = (
    "obj_inst", "res_inst", "multi_res", "resource")
_KIND_BITS = {0: OBJ_INSTANCE, 1: RES_INSTANCE, 2: MULTI_RES, 3: RESOURCE}
_BITS_KIND = {v: k for k, v in _KIND_BITS.items()}

CONTENT_TLV = 11542          # application/vnd.oma.lwm2m+tlv
CONTENT_JSON = 11543         # application/vnd.oma.lwm2m+json
CONTENT_TEXT = 0             # text/plain (single-resource reads)
CONTENT_OPAQUE = 42


class TlvError(Exception):
    pass


# ---------------------------------------------------------------------------
# structural codec


def tlv_decode(data: bytes) -> list[dict]:
    """-> [{kind, id, value | children}] (children for containers)."""
    out, pos = [], 0
    n = len(data)
    while pos < n:
        t = data[pos]
        pos += 1
        kind = _KIND_BITS[(t >> 6) & 0x03]
        id_w = 2 if t & 0x20 else 1
        if pos + id_w > n:
            raise TlvError("truncated identifier")
        ident = int.from_bytes(data[pos:pos + id_w], "big")
        pos += id_w
        len_w = (t >> 3) & 0x03
        if len_w == 0:
            length = t & 0x07
        else:
            if pos + len_w > n:
                raise TlvError("truncated length")
            length = int.from_bytes(data[pos:pos + len_w], "big")
            pos += len_w
        if pos + length > n:
            raise TlvError("truncated value")
        body = data[pos:pos + length]
        pos += length
        if kind in (OBJ_INSTANCE, MULTI_RES):
            out.append({"kind": kind, "id": ident,
                        "children": tlv_decode(body)})
        else:
            out.append({"kind": kind, "id": ident, "value": body})
    return out


def tlv_encode(entries: list[dict]) -> bytes:
    out = bytearray()
    for e in entries:
        kind = e["kind"]
        if kind in (OBJ_INSTANCE, MULTI_RES):
            body = tlv_encode(e["children"])
        else:
            body = bytes(e["value"])
        ident = int(e["id"])
        t = _BITS_KIND[kind] << 6
        id_bytes = 2 if ident > 0xFF else 1
        if id_bytes == 2:
            t |= 0x20
        n = len(body)
        if n < 8:
            t |= n
            len_bytes = b""
        else:
            ln_w = 1 if n < (1 << 8) else 2 if n < (1 << 16) else 3
            t |= ln_w << 3
            len_bytes = n.to_bytes(ln_w, "big")
        out.append(t)
        out += ident.to_bytes(id_bytes, "big")
        out += len_bytes
        out += body
    return bytes(out)


# ---------------------------------------------------------------------------
# value codec (resource data types from the registry)


def decode_value(raw: bytes, rtype: str) -> Any:
    if rtype in ("Integer", "Time"):
        if len(raw) not in (1, 2, 4, 8):
            raise TlvError(f"bad integer width {len(raw)}")
        return int.from_bytes(raw, "big", signed=True)
    if rtype == "Float":
        if len(raw) == 4:
            return struct.unpack(">f", raw)[0]
        if len(raw) == 8:
            return struct.unpack(">d", raw)[0]
        raise TlvError(f"bad float width {len(raw)}")
    if rtype == "Boolean":
        if len(raw) != 1 or raw[0] > 1:
            raise TlvError("bad boolean")
        return raw[0] == 1
    if rtype == "Objlnk":
        if len(raw) != 4:
            raise TlvError("bad objlnk")
        oid, iid = struct.unpack(">HH", raw)
        return f"{oid}:{iid}"
    if rtype == "Opaque":
        return raw.hex()
    return raw.decode("utf-8", "replace")            # String


def encode_value(value: Any, rtype: str) -> bytes:
    if rtype in ("Integer", "Time"):
        v = int(value)
        for w in (1, 2, 4, 8):
            if -(1 << (8 * w - 1)) <= v < (1 << (8 * w - 1)):
                return v.to_bytes(w, "big", signed=True)
        raise TlvError(f"integer out of range: {value}")
    if rtype == "Float":
        return struct.pack(">d", float(value))
    if rtype == "Boolean":
        truthy = value in (True, 1, "1", "true", "True")
        return b"\x01" if truthy else b"\x00"
    if rtype == "Objlnk":
        oid, _, iid = str(value).partition(":")
        return struct.pack(">HH", int(oid), int(iid or 0))
    if rtype == "Opaque":
        return bytes.fromhex(value) if isinstance(value, str) else \
            bytes(value)
    return str(value).encode()                       # String


# ---------------------------------------------------------------------------
# path-addressed translation (emqx_lwm2m_message tlv_to_json/json_to_tlv)


def _rtype(oid: int, rid: int) -> str:
    obj = objects.OBJECTS.get(oid)
    res = obj.resource(rid) if obj else None
    return res.type if res else "Opaque"


def tlv_to_path_values(base_path: str, data: bytes) -> list[dict]:
    """TLV body of a Read/Observe response on ``base_path``
    (``/oid[/iid[/rid]]``) → [{path, name, value}] rows, values typed
    by the registry."""
    segs = [s for s in base_path.split("/") if s]
    if not segs:
        raise TlvError("TLV needs an object path")
    oid = int(segs[0])
    rows: list[dict] = []

    def emit(iid: Optional[int], rid: int, raw: bytes,
             sub: Optional[int] = None) -> None:
        rtype = _rtype(oid, rid)
        path = f"/{oid}" + (f"/{iid}" if iid is not None else "") + \
            f"/{rid}" + (f"/{sub}" if sub is not None else "")
        rows.append({"path": path,
                     "name": objects.translate_path(f"/{oid}/0/{rid}"),
                     "value": decode_value(raw, rtype)})

    entries = tlv_decode(data)
    iid_ctx = int(segs[1]) if len(segs) > 1 else None
    for e in entries:
        if e["kind"] == OBJ_INSTANCE:
            for r in e["children"]:
                if r["kind"] == MULTI_RES:
                    for ri in r["children"]:
                        emit(e["id"], r["id"], ri["value"], ri["id"])
                else:
                    emit(e["id"], r["id"], r["value"])
        elif e["kind"] == MULTI_RES:
            for ri in e["children"]:
                emit(iid_ctx, e["id"], ri["value"], ri["id"])
        elif e["kind"] == RESOURCE:
            emit(iid_ctx, e["id"], e["value"])
        else:                                        # bare res_inst
            rid = int(segs[2]) if len(segs) > 2 else e["id"]
            emit(iid_ctx, rid, e["value"], e["id"])
    return rows


def path_values_to_tlv(base_path: str, values: list[dict]) -> bytes:
    """[{path, value}] rows under ``base_path`` → a TLV Write body.

    Row paths are absolute (``/oid/iid/rid[/sub]``) or relative to the
    base. Nesting follows the base depth: an object base groups rows
    into OBJ_INSTANCE containers; multi-resource sub-ids nest as
    MULTI_RES → RES_INSTANCE. Malformed rows raise TlvError (never
    KeyError/IndexError — callers fall back on TlvError)."""
    base = [s for s in base_path.split("/") if s]
    if not base:
        raise TlvError("TLV needs an object path")
    try:
        oid = int(base[0])
    except ValueError as e:
        raise TlvError(f"bad object id in {base_path!r}") from e

    # normalize every row to (iid|None, rid, sub|None, value)
    norm: list[tuple[Optional[int], int, Optional[int], Any]] = []
    for row in values:
        if not isinstance(row, dict) or "path" not in row \
                or "value" not in row:
            raise TlvError(f"write row needs path+value: {row!r}")
        raw_p = str(row["path"])
        p = [s for s in raw_p.split("/") if s]
        if not p:
            raise TlvError(f"empty write path in row {row!r}")
        segs = p if raw_p.startswith("/") else base + p
        try:
            nums = [int(s) for s in segs]
        except ValueError as e:
            raise TlvError(f"non-numeric path {raw_p!r}") from e
        if nums[0] != oid or len(nums) < 2 or len(nums) > 4:
            raise TlvError(f"path {raw_p!r} outside base {base_path!r}")
        iid = nums[1] if len(nums) >= 3 else None
        rid = nums[2] if len(nums) >= 3 else nums[1]
        sub = nums[3] if len(nums) == 4 else None
        norm.append((iid, rid, sub, row["value"]))

    def resource_entries(rows) -> list[dict]:
        by_rid: dict[int, list] = {}
        for _iid, rid, sub, value in rows:
            by_rid.setdefault(rid, []).append((sub, value))
        out = []
        for rid, items in by_rid.items():
            rtype = _rtype(oid, rid)
            subs = [sub for sub, _v in items]
            if len(set(subs)) != len(subs):
                raise TlvError(
                    f"duplicate write target resource {rid}")
            if any(s is not None for s in subs):
                if any(s is None for s in subs):
                    # a whole-resource row mixed with res-instance rows
                    # has no defined TLV encoding
                    raise TlvError(
                        f"resource {rid}: mixed instance and "
                        "whole-resource rows")
                out.append({"kind": MULTI_RES, "id": rid, "children": [
                    {"kind": RES_INSTANCE, "id": sub,
                     "value": encode_value(v, rtype)}
                    for sub, v in items]})
            else:
                ((_s, v),) = items
                out.append({"kind": RESOURCE, "id": rid,
                            "value": encode_value(v, rtype)})
        return out

    if len(base) >= 2:                    # instance (or deeper) base:
        return tlv_encode(resource_entries(norm))    # flat resources
    by_iid: dict[int, list] = {}
    for row in norm:
        by_iid.setdefault(row[0] or 0, []).append(row)
    return tlv_encode([
        {"kind": OBJ_INSTANCE, "id": iid,
         "children": resource_entries(rows)}
        for iid, rows in by_iid.items()])
