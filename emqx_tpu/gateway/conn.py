"""Generic gateway connection adapters — the
``emqx_gateway_conn.erl`` (1236 LoC) analogue: one TCP adapter and one
UDP adapter that own the socket, run the Frame codec, and drive any
GwChannel. Protocol modules supply only Frame + Channel.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

from emqx_tpu.gateway.ctx import GwChannel, GwFrame

log = logging.getLogger(__name__)


class TcpGwConnection:
    def __init__(self, frame: GwFrame, channel: GwChannel,
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
        self.frame = frame
        self.channel = channel
        self.reader = reader
        self.writer = writer
        self.parse_state = frame.initial_parse_state()
        self.closed = False
        self._loop = asyncio.get_event_loop()
        channel.send = self.send_frames
        channel.request_close = self.request_close
        channel.peername = writer.get_extra_info("peername")
        ready = getattr(channel, "on_socket_ready", None)
        if ready is not None:        # channels that announce the socket
            ready()                  # to an external service (exproto)

    def send_frames(self, pkts: list) -> None:
        if self.closed or not pkts:
            return
        data = b"".join(self.frame.serialize(p) for p in pkts)
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            self.writer.write(data)
        else:
            self._loop.call_soon_threadsafe(self.writer.write, data)

    def request_close(self) -> None:
        """Thread-safe transport teardown: closing the writer unblocks the
        reader so the run loop exits and terminates the channel."""
        def do() -> None:
            self.closed = True
            try:
                self.writer.close()
            except Exception:
                pass
        self._loop.call_soon_threadsafe(do)

    async def run(self) -> None:
        try:
            while not self.closed:
                data = await self.reader.read(65536)
                if not data:
                    break
                pkts, self.parse_state = self.frame.parse(
                    data, self.parse_state)
                for pkt in pkts:
                    out = self.channel.handle_in(pkt)
                    self.send_frames(out)
                    if self.channel.conn_state == "disconnected":
                        self.closed = True
                        break
                await self.writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            log.exception("gateway connection crashed")
        finally:
            await self.close("sock_closed")

    async def close(self, reason: str) -> None:
        if not self.closed:
            self.closed = True
        self.channel.terminate(reason)
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


class TcpGwListener:
    """esockd-analogue acceptor for a TCP gateway."""

    def __init__(self, make_channel: Callable[[], GwChannel],
                 frame: GwFrame, host: str = "127.0.0.1",
                 port: int = 0, tick_interval_s: float = 1.0) -> None:
        self.make_channel = make_channel
        self.frame = frame
        self.host, self.port = host, port
        self.tick_interval_s = tick_interval_s
        self._server: Optional[asyncio.AbstractServer] = None
        self._tick_task: Optional[asyncio.Task] = None
        self.connections: set[TcpGwConnection] = set()

    async def _on_connect(self, reader, writer) -> None:
        conn = TcpGwConnection(self.frame, self.make_channel(),
                               reader, writer)
        self.connections.add(conn)
        try:
            await conn.run()
        finally:
            self.connections.discard(conn)

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        # channel housekeeping (tx timeouts, retransmits) — the TCP
        # transport needs the same periodic drive UdpGwListener has
        self._tick_task = self._loop.create_task(self._tick_loop())

    async def _tick_loop(self) -> None:
        while True:
            await asyncio.sleep(self.tick_interval_s)
            for conn in list(self.connections):
                hk = getattr(conn.channel, "housekeep", None)
                if hk is None:
                    continue
                try:
                    frames = hk()
                    if frames:
                        conn.send_frames(frames)
                except Exception:
                    log.exception("gateway channel housekeep crashed")

    async def stop(self) -> None:
        if self._tick_task is not None:
            self._tick_task.cancel()
        for conn in list(self.connections):
            await conn.close("server_shutdown")
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class UdpGwListener(asyncio.DatagramProtocol):
    """UDP gateway transport (esockd udp): one channel per peer addr,
    expired by the protocol's own keepalive."""

    def __init__(self, make_channel: Callable[[], GwChannel],
                 frame: GwFrame, host: str = "127.0.0.1",
                 port: int = 0, idle_timeout_s: float = 300.0,
                 gc_interval_s: float = 30.0,
                 tick_interval_s: float = 0.5) -> None:
        self.make_channel = make_channel
        self.frame = frame
        self.host, self.port = host, port
        self.idle_timeout_s = idle_timeout_s
        self.gc_interval_s = gc_interval_s
        self.tick_interval_s = tick_interval_s
        self.channels: dict[tuple, GwChannel] = {}
        self._last_seen: dict[tuple, float] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._gc_task: Optional[asyncio.Task] = None
        self._tick_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self.transport, _ = await self._loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port))
        if self.port == 0:
            self.port = self.transport.get_extra_info("sockname")[1]
        self._gc_task = self._loop.create_task(self._gc_loop())
        self._tick_task = self._loop.create_task(self._tick_loop())

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gc_interval_s)
            self.expire_idle()

    async def _tick_loop(self) -> None:
        """Sub-second channel housekeeping: protocols with their own
        transport reliability (CoAP CON retransmission) return frames
        due for (re)send from ``housekeep()``."""
        while True:
            await asyncio.sleep(self.tick_interval_s)
            self.tick_channels()

    def tick_channels(self) -> None:
        for ch in list(self.channels.values()):
            hk = getattr(ch, "housekeep", None)
            if hk is None:
                continue
            try:
                frames = hk()
                if frames:
                    ch.send(frames)
            except Exception:
                log.exception("gateway channel housekeep crashed")

    def expire_idle(self, now: Optional[float] = None) -> int:
        """Drop peers silent past idle_timeout_s — without this the
        per-addr channel map grows forever (spoofed source ports, dead
        clients that never DISCONNECT)."""
        import time as _time

        now = self._loop.time() if now is None else now
        wall = _time.time()
        dead = [
            addr for addr, t in self._last_seen.items()
            if now - t >= self.idle_timeout_s
            # a sleeping client (MQTT-SN) is expected-silent until its
            # announced wake deadline — don't GC its session away
            and not (
                (su := getattr(self.channels.get(addr), "sleep_until",
                               None)) is not None and wall < su)
        ]
        for addr in dead:
            ch = self.channels.pop(addr, None)
            self._last_seen.pop(addr, None)
            if ch is not None:
                ch.terminate("idle_timeout")
        return len(dead)

    async def stop(self) -> None:
        if self._gc_task is not None:
            self._gc_task.cancel()
        if self._tick_task is not None:
            self._tick_task.cancel()
        for ch in list(self.channels.values()):
            ch.terminate("server_shutdown")
        self.channels.clear()
        self._last_seen.clear()
        if self.transport is not None:
            self.transport.close()

    # -- DatagramProtocol ----------------------------------------------------

    def datagram_received(self, data: bytes, addr: tuple) -> None:
        ch = self.channels.get(addr)
        if ch is None:
            ch = self.make_channel()
            ch.send = self._sender(addr)
            ch.request_close = self._closer(addr)
            ch.peername = addr
            ready = getattr(ch, "on_socket_ready", None)
            if ready is not None:
                ready()
            self.channels[addr] = ch
        self._last_seen[addr] = self._loop.time()
        try:
            pkts, _ = self.frame.parse(data, None)   # UDP: whole datagrams
            for pkt in pkts:
                ch.send(ch.handle_in(pkt))
            if ch.conn_state == "disconnected":
                ch.terminate("closed")
                self.channels.pop(addr, None)
                self._last_seen.pop(addr, None)
        except Exception:
            log.exception("udp gateway datagram crashed")

    def _closer(self, addr: tuple) -> Callable[[], None]:
        def close() -> None:
            def do() -> None:
                ch = self.channels.pop(addr, None)
                self._last_seen.pop(addr, None)
                if ch is not None:
                    ch.terminate("closed")
            if self._loop is not None:
                self._loop.call_soon_threadsafe(do)
        return close

    def _sender(self, addr: tuple) -> Callable[[list], None]:
        def send(pkts: list) -> None:
            if not pkts or self.transport is None:
                return
            try:
                on_loop = asyncio.get_running_loop() is self._loop
            except RuntimeError:
                on_loop = False
            for p in pkts:                 # one datagram per message
                data = self.frame.serialize(p)
                if not data:
                    continue
                if on_loop:
                    self.transport.sendto(data, addr)
                else:
                    self._loop.call_soon_threadsafe(
                        self.transport.sendto, data, addr)
        return send
