"""LwM2M gateway — parity with ``apps/emqx_gateway/src/lwm2m/``
(emqx_lwm2m_channel.erl registration interface + the mqtt-topic
up/down link convention), riding the CoAP codec from coap.py.

Registration interface (OMA LwM2M 1.0 §8.2, CoAP bootstrap):

    POST /rd?ep={name}&lt={s}&lwm2m={ver}   → 2.01 + Location /rd/{id}
    POST /rd/{id}  (update)                 → 2.04
    DELETE /rd/{id}                         → 2.02

Uplink events publish to ``lwm2m/{ep}/up/{event}`` (register, update,
notify); downlink commands are MQTT messages on ``lwm2m/{ep}/dn/#``
delivered to the device as CoAP POSTs carrying the payload.
"""

from __future__ import annotations

import json
from typing import Optional

from emqx_tpu.gateway import lwm2m_objects as objects
from emqx_tpu.gateway import lwm2m_tlv as tlv
from emqx_tpu.gateway.coap import (
    ACK, BAD_REQUEST, CHANGED, CREATED, DELETE, DELETED, Frame, GET,
    NON, NOT_FOUND, OPT_CONTENT_FORMAT, OPT_LOCATION_PATH, POST, PUT,
    CoapMessage,
)
from emqx_tpu.gateway.ctx import GatewayImpl, GwChannel, GwContext

UPLINK = "lwm2m/{ep}/up/{event}"
DOWNLINK = "lwm2m/{ep}/dn/#"


class Channel(GwChannel):
    def __init__(self, ctx: GwContext) -> None:
        from emqx_tpu.gateway.coap import TransportManager

        self.ctx = ctx
        self.conn_state = "connected"
        self.clientid: Optional[str] = None
        self.endpoint: Optional[str] = None
        self.reg_id: Optional[str] = None
        self.lifetime = 86400
        self.objects: list[dict] = []      # registry-resolved reg links
        self._mid = 0
        # same message-layer machine as the coap gateway: registration
        # CON retransmits must not re-execute (duplicate register
        # uplinks), and downlink CON POSTs retransmit until ACKed
        self.tm = TransportManager()
        # mid → {reqID, msgType, path} so device responses / timeouts
        # correlate back to the command they answer
        self._cmd_ctx: dict[int, dict] = {}
        # paths this server observed (downlink observe commands) — used
        # to type TLV notify bodies when the device omits ?path=
        self._observed: set[str] = set()

    def _next_mid(self) -> int:
        self._mid = self._mid % 0xFFFF + 1
        return self._mid

    def _uplink(self, event: str, data: dict) -> None:
        self.ctx.publish(
            self.clientid,
            UPLINK.format(ep=self.endpoint, event=event),
            json.dumps(data).encode(), qos=0)

    # -- inbound -------------------------------------------------------------

    def handle_in(self, m: CoapMessage) -> list[CoapMessage]:
        from emqx_tpu.gateway.coap import CON, EMPTY, RST

        if m.code == EMPTY and m.type == CON:        # CoAP ping → RST pong
            return [CoapMessage(RST, EMPTY, m.mid, b"")]
        if m.type in (ACK, RST):
            settled = self.tm.on_ack(m.mid)          # settles downlink CONs
            ctx = self._cmd_ctx.pop(m.mid, {})
            # a REFUSED observe (error ACK, or RST — which carries code
            # EMPTY) must not poison the single-observation typing
            # heuristic for TLV notifies
            if (ctx.get("msgType") == "observe" and ctx.get("path")
                    and (m.code >= 0x80 or m.type == RST)):
                self._observed.discard(str(ctx["path"]))
            if settled and m.type == ACK and m.code != EMPTY:
                # piggybacked device response to a downlink command
                # (read value / write result) — surface it as the uplink
                # the reference's emqx_lwm2m_cmd produces, echoing the
                # command's reqID/msgType/path for correlation. TLV
                # bodies decode into typed {path, name, value} rows via
                # the object registry (emqx_lwm2m_message tlv_to_json)
                self._uplink("response", {
                    "ep": self.endpoint,
                    "reqID": ctx.get("reqID"),
                    "msgType": ctx.get("msgType"),
                    "data": {
                        "path": ctx.get("path"),
                        "code": f"{m.code >> 5}.{m.code & 0x1F:02d}",
                        "content": self._decode_content(
                            m, ctx.get("path")),
                    }})
            return []
        if m.code == EMPTY:
            return []
        cached = self.tm.dedup(m)
        if cached is not None:
            return list(cached)      # duplicate CON: replay, don't re-run
        out = self._handle_request(m)
        self.tm.remember(m, out)
        return out

    def housekeep(self) -> list[CoapMessage]:
        retx, gave_up = self.tm.tick()
        for mid in gave_up:
            # an unresponsive device surfaces as a timeout uplink rather
            # than silence (the reference's command timeout response)
            ctx = self._cmd_ctx.pop(mid, {})
            if ctx.get("msgType") == "observe" and ctx.get("path"):
                self._observed.discard(str(ctx["path"]))   # never ACKed
            self._uplink("response", {
                "ep": self.endpoint,
                "reqID": ctx.get("reqID"),
                "msgType": ctx.get("msgType"),
                "data": {"path": ctx.get("path"),
                         "code": "5.04", "codeMsg": "timeout"}})
        return retx

    def _handle_request(self, m: CoapMessage) -> list[CoapMessage]:
        reply_type = ACK if m.type == 0 else NON
        path = m.uri_path()

        def reply(code: int, options=(), payload: bytes = b"") -> CoapMessage:
            return CoapMessage(reply_type, code, m.mid, m.token,
                               list(options), payload)

        if not path or path[0] != "rd":
            return [reply(NOT_FOUND)]
        if m.code == POST and len(path) == 1:
            q = m.queries()
            ep = q.get("ep")
            if not ep:
                return [reply(BAD_REQUEST)]
            if not self.ctx.authenticate(f"lwm2m-{ep}"):
                return [reply(BAD_REQUEST)]
            self.endpoint = ep
            self.clientid = f"lwm2m-{ep}"
            self.lifetime = int(q.get("lt", 86400))
            self.reg_id = f"{abs(hash(ep)) % 100000}"
            self.ctx.open_session(self.clientid, self)
            # downlink command subscription for this endpoint
            self.ctx.subscribe(self.clientid, DOWNLINK.format(ep=ep), 0)
            # registry-resolved object list (emqx_lwm2m_xml_object):
            # CoRE links → [{path, oid, instance, name}] so consumers see
            # 'Device'/'Firmware Update', not bare numeric ids
            links = objects.parse_core_links(
                m.payload.decode("utf-8", "replace"))
            self.objects = links
            self._uplink("register", {
                "ep": ep, "lt": self.lifetime,
                "lwm2m": q.get("lwm2m", "1.0"),
                "objects": links,
                "alternatePath": q.get("apn", "/"),
            })
            return [reply(CREATED, options=[
                (OPT_LOCATION_PATH, b"rd"),
                (OPT_LOCATION_PATH, self.reg_id.encode()),
            ])]
        if m.code == POST and len(path) == 2:
            if path[1] != self.reg_id:
                return [reply(NOT_FOUND)]
            q = m.queries()
            if "lt" in q:
                self.lifetime = int(q["lt"])
            if m.payload:
                # registration update may carry a fresh object list
                self.objects = objects.parse_core_links(
                    m.payload.decode("utf-8", "replace"))
            self._uplink("update", {"ep": self.endpoint,
                                    "lt": self.lifetime,
                                    "objects": self.objects})
            return [reply(CHANGED)]
        if m.code == DELETE and len(path) == 2:
            if path[1] != self.reg_id:
                return [reply(NOT_FOUND)]
            self._uplink("deregister", {"ep": self.endpoint})
            self.conn_state = "disconnected"
            return [reply(DELETED)]
        # device-originated notify (e.g. POST /rd/{id}/notify) — only from
        # a registered endpoint, addressed by its own registration id
        if m.code == POST and len(path) == 3 and path[2] == "notify":
            if self.reg_id is None or path[1] != self.reg_id:
                return [reply(NOT_FOUND)]
            # TLV typing needs the observed path: take the device's
            # ?path= echo when present, else correlate with the ONE
            # outstanding observe (the common single-observation case)
            base = m.queries().get("path", "")
            if not base and len(self._observed) == 1:
                (base,) = self._observed
            self._uplink("notify", {
                "ep": self.endpoint,
                "payload": self._decode_content(m, base)})
            return [reply(CHANGED)]
        return [reply(NOT_FOUND)]

    # -- outbound (downlink commands as CoAP POSTs) --------------------------

    # write-attr targets notification ATTRIBUTES (pmin/pmax/gt/lt) of
    # readable/observable resources, not the resource value — gate it on
    # R, not W (OMA TS §5.1.2)
    _OPS = {"read": "R", "observe": "R", "discover": "R",
            "write": "W", "write-attr": "R", "execute": "E"}

    def _decode_content(self, m: CoapMessage, base_path) -> object:
        """Device payload → structured rows when the content-format says
        TLV (emqx_lwm2m_message); plain text passes through."""
        cf = m.opt(OPT_CONTENT_FORMAT)
        fmt = int.from_bytes(cf, "big") if cf else None
        if fmt == tlv.CONTENT_TLV:
            if base_path:
                try:
                    return tlv.tlv_to_path_values(str(base_path),
                                                  m.payload)
                except (tlv.TlvError, ValueError):
                    pass             # malformed TLV: hex below
            # binary without a typing context must surface as hex, not
            # utf-8 mojibake
            return m.payload.hex()
        if fmt == tlv.CONTENT_OPAQUE:
            return m.payload.hex()
        return m.payload.decode("utf-8", "replace")

    def handle_deliver(self, deliveries: list) -> list[CoapMessage]:
        out = []
        for _sub_topic, msg in deliveries:
            plain = self.ctx.unmount(msg.topic)
            parts = plain.split("/")
            # lwm2m/{ep}/dn/... → POST /dn/{...} to the device
            cmd_path = parts[3:] if len(parts) > 3 else []
            # JSON commands ({msgType, data.path}) validate against the
            # object registry before reaching the device: an operation a
            # resource doesn't support answers an uplink error instead
            # (emqx_lwm2m_cmd + xml_object op checks)
            try:
                cmd = json.loads(msg.payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                cmd = None
            if isinstance(cmd, dict) and cmd.get("msgType") in self._OPS:
                path = str((cmd.get("data") or {}).get("path", ""))
                if path and not objects.check_operation(
                        path, self._OPS[cmd["msgType"]]):
                    self._uplink("response", {
                        "ep": self.endpoint,
                        "reqID": cmd.get("reqID"),
                        "msgType": cmd["msgType"],
                        "data": {"path": path,
                                 "code": "4.05",
                                 "codeMsg": "method not allowed",
                                 "name": objects.translate_path(path)},
                    })
                    continue
            opts = [(11, seg.encode()) for seg in (["dn"] + cmd_path)]
            payload = msg.payload
            # a write command whose data.content is [{path, value}]
            # rows ships as a typed TLV body (emqx_lwm2m_cmd +
            # emqx_lwm2m_message json_to_tlv), not raw JSON
            if (isinstance(cmd, dict) and cmd.get("msgType") == "write"
                    and isinstance((cmd.get("data") or {}).get("content"),
                                   list)):
                data = cmd["data"]
                try:
                    payload = tlv.path_values_to_tlv(
                        str(data.get("basePath") or data.get("path")),
                        data["content"])
                    opts.append((OPT_CONTENT_FORMAT,
                                 tlv.CONTENT_TLV.to_bytes(2, "big")))
                except (tlv.TlvError, ValueError, TypeError,
                        KeyError, IndexError):
                    # unencodable rows: raw JSON falls through — and a
                    # malformed command must never escape into
                    # CM.dispatch (it has no per-channel containment)
                    pass
            cmd_msg = CoapMessage(
                0, POST, self._next_mid(),
                b"", opts, payload)             # CON request to device
            self.tm.track(cmd_msg)              # retransmit until ACKed
            if isinstance(cmd, dict):
                self._cmd_ctx[cmd_msg.mid] = {
                    "reqID": cmd.get("reqID"),
                    "msgType": cmd.get("msgType"),
                    "path": (cmd.get("data") or {}).get("path"),
                }
                obs_path = (cmd.get("data") or {}).get("path")
                if cmd.get("msgType") == "observe" and obs_path:
                    self._observed.add(str(obs_path))
                elif cmd.get("msgType") == "cancel-observe" and obs_path:
                    self._observed.discard(str(obs_path))
            out.append(cmd_msg)
        return out

    def terminate(self, reason: str) -> None:
        if self.clientid is not None:
            self.ctx.close_session(self.clientid, self, reason)
            self.clientid = None


class Lwm2mGateway(GatewayImpl):
    name = "lwm2m"

    def __init__(self, host: str = "127.0.0.1", port: int = 5783) -> None:
        self.host, self.port = host, port
        self.listener = None
        self.ctx: Optional[GwContext] = None

    def on_gateway_load(self, ctx: GwContext, conf: dict) -> None:
        from emqx_tpu.gateway.conn import UdpGwListener

        self.ctx = ctx
        self.host = conf.get("host", self.host)
        self.port = conf.get("port", self.port)
        self.listener = UdpGwListener(
            lambda: Channel(self.ctx), Frame(),
            host=self.host, port=self.port)

    async def start_listeners(self) -> None:
        await self.listener.start()
        self.port = self.listener.port

    async def stop_listeners(self) -> None:
        await self.listener.stop()
