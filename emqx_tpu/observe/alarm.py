"""Alarms — parity with ``apps/emqx/src/emqx_alarm.erl``.

Activate/deactivate named alarms with details; deactivated alarms move
to a bounded history (the reference's mnesia ``emqx_deactivated_alarm``
with validity sweep). An optional publish hook mirrors the reference's
``alarm.activated``/``alarm.deactivated`` $SYS messages.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class Alarm:
    name: str
    details: dict = field(default_factory=dict)
    message: str = ""
    activate_at: float = field(default_factory=time.time)
    deactivate_at: Optional[float] = None


class AlarmManager:
    def __init__(self, history_size: int = 1000,
                 on_change: Optional[Callable[[str, Alarm], None]] = None
                 ) -> None:
        self._active: dict[str, Alarm] = {}
        self._history: list[Alarm] = []
        self.history_size = history_size
        self.on_change = on_change

    def activate(self, name: str, details: Optional[dict] = None,
                 message: str = "") -> bool:
        """→ False if already active (reference returns
        {error, already_existed})."""
        if name in self._active:
            return False
        alarm = Alarm(name, details or {}, message or name)
        self._active[name] = alarm
        if self.on_change:
            self.on_change("activated", alarm)
        return True

    def deactivate(self, name: str) -> bool:
        alarm = self._active.pop(name, None)
        if alarm is None:
            return False
        alarm.deactivate_at = time.time()
        self._history.append(alarm)
        del self._history[:-self.history_size]
        if self.on_change:
            self.on_change("deactivated", alarm)
        return True

    def ensure(self, name: str, active: bool,
               details: Optional[dict] = None, message: str = "") -> None:
        """Idempotent edge-trigger helper used by monitors."""
        if active:
            self.activate(name, details, message)
        else:
            self.deactivate(name)

    def is_active(self, name: str) -> bool:
        return name in self._active

    def get_alarms(self, which: str = "all") -> list[Alarm]:
        if which == "activated":
            return list(self._active.values())
        if which == "deactivated":
            return list(self._history)
        return list(self._active.values()) + list(self._history)

    def delete_all_deactivated(self) -> None:
        self._history.clear()
