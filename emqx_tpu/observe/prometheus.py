"""Prometheus text exposition — parity with
``apps/emqx_prometheus/src/emqx_prometheus.erl``.

Renders the metric counters, stat gauges, VM/process figures, the
native host's fast-path stat slots (``emqx_native_*`` gauges), and the
native telemetry plane's latency histograms
(``emqx_latency_*_seconds`` with ``_bucket``/``_sum``/``_count``
series) into the text 0.0.4 format the scrape endpoint serves. Metric
names map ``a.b.c`` → ``emqx_a_b_c`` as the reference's collector does.
"""

from __future__ import annotations

import os
import time
from typing import Optional


def _san(name: str) -> str:
    return "emqx_" + name.replace(".", "_")


def _render_hists(lines: list[str], hists: dict, node: str) -> None:
    """``_bucket``/``_sum``/``_count`` series per latency histogram.

    Bucket edges convert ns → seconds (prometheus convention); only
    buckets with occupants are listed (le labels are explicit, so a
    sparse cumulative series stays well-formed) plus the mandatory
    ``le="+Inf"`` line.
    """
    from emqx_tpu.observe.metrics import HIST_EDGES_NS

    for name, h in sorted(hists.items()):
        mn = _san(name) + "_seconds"
        lines.append(f"# TYPE {mn} histogram")
        cum = 0
        for i in range(63):  # bucket 63 is the +Inf line below
            c = int(h.counts[i])
            if c == 0:
                continue
            cum += c
            lines.append(f'{mn}_bucket{{node="{node}",'
                         f'le="{HIST_EDGES_NS[i] / 1e9:.9g}"}} {cum}')
        lines.append(f'{mn}_bucket{{node="{node}",le="+Inf"}} {h.count}')
        lines.append(f'{mn}_sum{{node="{node}"}} {h.sum_ns / 1e9:.9g}')
        lines.append(f'{mn}_count{{node="{node}"}} {h.count}')


def render(metrics=None, stats=None, extra: Optional[dict] = None,
           node: str = "emqx_tpu", native: Optional[dict] = None) -> str:
    lines: list[str] = []
    label = f'{{node="{node}"}}'
    if metrics is not None:
        for name, val in sorted(metrics.all().items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} counter")
            lines.append(f"{mn}{label} {val}")
        hists = getattr(metrics, "hists", None)
        if callable(hists):
            h = hists()
            if h:
                _render_hists(lines, h, node)
    if stats is not None:
        for name, val in sorted(stats.all().items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    if native:
        # the C++ host's monotonic stat slots (NativeHost.stats());
        # tests/test_stats_lint.py asserts every exported slot lands here
        for name, val in sorted(native.items()):
            mn = "emqx_native_" + name.replace(".", "_")
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    # VM slice (the reference exports erlang_vm_*; we export process RSS)
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        lines.append("# TYPE emqx_vm_memory_bytes gauge")
        lines.append(
            f"emqx_vm_memory_bytes{label} "
            f"{rss_pages * os.sysconf('SC_PAGE_SIZE')}")
    except OSError:
        pass
    if extra:
        for name, val in sorted(extra.items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    lines.append(f"# EOF scraped_at={int(time.time())}")
    return "\n".join(lines) + "\n"
