"""Prometheus text exposition — parity with
``apps/emqx_prometheus/src/emqx_prometheus.erl``.

Renders the metric counters, stat gauges, and VM/process figures into
the text 0.0.4 format the scrape endpoint serves. Metric names map
``a.b.c`` → ``emqx_a_b_c`` as the reference's collector does.
"""

from __future__ import annotations

import os
import time
from typing import Optional


def _san(name: str) -> str:
    return "emqx_" + name.replace(".", "_")


def render(metrics=None, stats=None, extra: Optional[dict] = None,
           node: str = "emqx_tpu") -> str:
    lines: list[str] = []
    label = f'{{node="{node}"}}'
    if metrics is not None:
        for name, val in sorted(metrics.all().items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} counter")
            lines.append(f"{mn}{label} {val}")
    if stats is not None:
        for name, val in sorted(stats.all().items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    # VM slice (the reference exports erlang_vm_*; we export process RSS)
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        lines.append("# TYPE emqx_vm_memory_bytes gauge")
        lines.append(
            f"emqx_vm_memory_bytes{label} "
            f"{rss_pages * os.sysconf('SC_PAGE_SIZE')}")
    except OSError:
        pass
    if extra:
        for name, val in sorted(extra.items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    lines.append(f"# EOF scraped_at={int(time.time())}")
    return "\n".join(lines) + "\n"
