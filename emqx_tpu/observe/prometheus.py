"""Prometheus text exposition — parity with
``apps/emqx_prometheus/src/emqx_prometheus.erl``.

Renders the metric counters, stat gauges, VM/process figures, the
native host's fast-path stat slots (``emqx_native_*`` gauges — with a
``shard`` label per shard host when the server is sharded), and the
native telemetry plane's latency histograms
(``emqx_latency_*_seconds`` with ``_bucket``/``_sum``/``_count``
series; per-shard stage histograms render under the SAME metric name
with a ``shard`` label) into the text 0.0.4 format the scrape endpoint
serves. Metric names map ``a.b.c`` → ``emqx_a_b_c`` as the reference's
collector does.

Round 13: with ``openmetrics=True`` the histogram ``_bucket`` lines
carry OpenMetrics-style exemplars (``# {trace_id="..."} value ts``)
hung off the distributed-tracing plane's sampled trace ids, so a
latency spike links straight to a stitched per-message timeline.
Exemplar syntax is ILLEGAL in the classic text 0.0.4 format (the
default scrape — a 0.0.4 parser errors on the ``#`` after the sample
value, failing the whole scrape), so the default render omits them;
scrapers opt in via ``GET /api/v5/prometheus?format=openmetrics``.
"""

from __future__ import annotations

import os
import re
import time
from typing import Optional

_SHARD_HIST_RE = re.compile(r"^latency\.(native|kernel)\.shard(\d+)\.(.+)$")


def _san(name: str) -> str:
    return "emqx_" + name.replace(".", "_")


def _render_hists(lines: list[str], hists: dict, node: str,
                  openmetrics: bool = False) -> None:
    """``_bucket``/``_sum``/``_count`` series per latency histogram.

    Bucket edges convert ns → seconds (prometheus convention); only
    buckets with occupants are listed (le labels are explicit, so a
    sparse cumulative series stays well-formed) plus the mandatory
    ``le="+Inf"`` line. Names of the ``latency.native.shard<i>.<stage>``
    shape render under the aggregate stage's metric name with a
    ``shard="<i>"`` label (one TYPE line per metric name).
    """
    from emqx_tpu.observe.metrics import HIST_EDGES_NS

    rows = []
    for name, h in hists.items():
        m = _SHARD_HIST_RE.match(name)
        if m:
            base = _san(
                f"latency.{m.group(1)}.{m.group(3)}") + "_seconds"
            label = f'{{node="{node}",shard="{m.group(2)}"}}'
            bucket_label = f'node="{node}",shard="{m.group(2)}"'
        else:
            base = _san(name) + "_seconds"
            label = f'{{node="{node}"}}'
            bucket_label = f'node="{node}"'
        rows.append((base, label, bucket_label, h))
    rows.sort(key=lambda r: (r[0], r[1]))
    typed = None
    for base, label, bucket_label, h in rows:
        if base != typed:
            lines.append(f"# TYPE {base} histogram")
            typed = base
        cum = 0
        ex = ((getattr(h, "exemplars", None) or {})
              if openmetrics else {})
        unrendered = dict(ex)
        for i in range(63):  # bucket 63 is the +Inf line below
            c = int(h.counts[i])
            if c == 0:
                continue
            cum += c
            line = (f'{base}_bucket{{{bucket_label},'
                    f'le="{HIST_EDGES_NS[i] / 1e9:.9g}"}} {cum}')
            if i in ex:
                tid, val_ns, ts = ex[i]
                unrendered.pop(i, None)
                line += (f' # {{trace_id="{tid:016x}"}} '
                         f"{val_ns / 1e9:.9g} {ts:.3f}")
            lines.append(line)
        inf_line = (f'{base}_bucket{{{bucket_label},le="+Inf"}} '
                    f"{h.count}")
        if unrendered:
            # an exemplar whose own bucket printed no line (the
            # exemplar came from the span plane, the histogram counts
            # from the 1-in-8 sampler — they need not coincide) still
            # surfaces, on the mandatory +Inf line
            tid, val_ns, ts = max(unrendered.values(),
                                  key=lambda e: e[2])
            inf_line += (f' # {{trace_id="{tid:016x}"}} '
                         f"{val_ns / 1e9:.9g} {ts:.3f}")
        lines.append(inf_line)
        lines.append(f"{base}_sum{label} {h.sum_ns / 1e9:.9g}")
        lines.append(f"{base}_count{label} {h.count}")


def render(metrics=None, stats=None, extra: Optional[dict] = None,
           node: str = "emqx_tpu", native: Optional[dict] = None,
           native_shards: Optional[list] = None,
           native_store: Optional[dict] = None,
           kernel: Optional[dict] = None,
           openmetrics: bool = False) -> str:
    lines: list[str] = []
    label = f'{{node="{node}"}}'
    if metrics is not None:
        for name, val in sorted(metrics.all().items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} counter")
            lines.append(f"{mn}{label} {val}")
        hists = getattr(metrics, "hists", None)
        if callable(hists):
            h = hists()
            if h:
                _render_hists(lines, h, node, openmetrics)
    if stats is not None:
        for name, val in sorted(stats.all().items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    typed_native: set = set()
    if native:
        # the C++ host's monotonic stat slots (NativeHost.stats());
        # tests/test_stats_lint.py asserts every exported slot lands here
        for name, val in sorted(native.items()):
            mn = "emqx_native_" + name.replace(".", "_")
            lines.append(f"# TYPE {mn} gauge")
            typed_native.add(mn)
            lines.append(f"{mn}{label} {val}")
    if native_store:
        # the durable store's slots (STORE_STAT_NAMES — round 18, the
        # one-recovery-path surface: segment/session/trunk-ring gauges
        # next to the append/replay counters)
        for name, val in sorted(native_store.items()):
            mn = "emqx_native_store_" + name.replace(".", "_")
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    if native_shards:
        # per-shard series under the same names, shard-labelled (round
        # 13 satellite): operators see which epoll plane is hot, not
        # just the aggregate; the label set is pinned by the stats lint
        for i, st in enumerate(native_shards):
            for name, val in sorted(st.items()):
                mn = "emqx_native_" + name.replace(".", "_")
                if mn not in typed_native:
                    lines.append(f"# TYPE {mn} gauge")
                    typed_native.add(mn)
                lines.append(f'{mn}{{node="{node}",shard="{i}"}} {val}')
    if kernel:
        # the TPU router's trie-health gauges (DeviceMetricsFold
        # .gauges()): list-valued entries are per-shard and render one
        # shard-labelled series each, scalars render plain
        for name, val in sorted(kernel.items()):
            mn = "emqx_kernel_" + name.replace(".", "_")
            lines.append(f"# TYPE {mn} gauge")
            if isinstance(val, (list, tuple)):
                for i, v in enumerate(val):
                    lines.append(
                        f'{mn}{{node="{node}",shard="{i}"}} {v}')
            else:
                lines.append(f"{mn}{label} {val}")
    # VM slice (the reference exports erlang_vm_*; we export process RSS)
    try:
        with open(f"/proc/{os.getpid()}/statm") as f:
            rss_pages = int(f.read().split()[1])
        lines.append("# TYPE emqx_vm_memory_bytes gauge")
        lines.append(
            f"emqx_vm_memory_bytes{label} "
            f"{rss_pages * os.sysconf('SC_PAGE_SIZE')}")
    except OSError:
        pass
    if extra:
        for name, val in sorted(extra.items()):
            mn = _san(name)
            lines.append(f"# TYPE {mn} gauge")
            lines.append(f"{mn}{label} {val}")
    lines.append(f"# EOF scraped_at={int(time.time())}")
    return "\n".join(lines) + "\n"
