"""OS / runtime monitors — ``emqx_os_mon.erl`` / ``emqx_vm_mon.erl`` /
``emqx_sys_mon.erl`` analogues.

Watermark checks over /proc (CPU busy fraction, memory use, open fds vs
limit) plus runtime signals (event-loop lag from Olp, GC pressure) raised
as edge-triggered alarms through the AlarmManager — the same
alarm-name surface the reference exposes (``high_cpu_usage``,
``high_system_memory_usage``, ``too_many_processes`` → here fd exhaustion).
"""

from __future__ import annotations

import os
import time
from typing import Optional


def _read_proc_stat() -> Optional[tuple[int, int]]:
    """(busy_jiffies, total_jiffies) from /proc/stat, None off-Linux."""
    try:
        with open("/proc/stat", "r", encoding="ascii") as fh:
            parts = fh.readline().split()
    except OSError:
        return None
    if parts[0] != "cpu" or len(parts) < 5:
        return None
    vals = [int(x) for x in parts[1:11]]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)   # idle + iowait
    return sum(vals) - idle, sum(vals)


def _read_mem_fraction() -> Optional[float]:
    try:
        with open("/proc/meminfo", "r", encoding="ascii") as fh:
            info = {}
            for line in fh:
                k, _, v = line.partition(":")
                info[k] = int(v.split()[0])
    except (OSError, ValueError, IndexError):
        return None
    total = info.get("MemTotal")
    avail = info.get("MemAvailable")
    if not total or avail is None:
        return None
    return 1.0 - avail / total


def _read_fd_fraction() -> Optional[float]:
    try:
        n_open = len(os.listdir("/proc/self/fd"))
        import resource

        soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    except (OSError, ImportError, ValueError):
        return None
    if soft <= 0:
        return None
    return n_open / soft


class SysMon:
    """Periodic watermark checks → alarms (edge-triggered via ensure)."""

    def __init__(self, alarms, *, olp=None,
                 cpu_high: float = 0.80, cpu_low: float = 0.60,
                 mem_high: float = 0.70,
                 fd_high: float = 0.85,
                 interval_s: float = 60.0) -> None:
        self.alarms = alarms
        self.olp = olp
        self.cpu_high, self.cpu_low = cpu_high, cpu_low
        self.mem_high = mem_high
        self.fd_high = fd_high
        self.interval_s = interval_s
        self._last_check = 0.0
        self._last_stat = _read_proc_stat()
        self._cpu_alarm = False

    def check(self) -> dict:
        """One pass; returns the readings (for /api and tests)."""
        readings: dict = {}
        stat = _read_proc_stat()
        if stat is not None and self._last_stat is not None:
            dbusy = stat[0] - self._last_stat[0]
            dtotal = stat[1] - self._last_stat[1]
            if dtotal > 0:
                cpu = dbusy / dtotal
                readings["cpu"] = cpu
                # hysteresis like the reference's cpu_high/low watermarks
                if cpu >= self.cpu_high:
                    self._cpu_alarm = True
                elif cpu <= self.cpu_low:
                    self._cpu_alarm = False
                self.alarms.ensure(
                    "high_cpu_usage", self._cpu_alarm,
                    message=f"cpu {cpu:.0%} (high={self.cpu_high:.0%})")
        self._last_stat = stat

        mem = _read_mem_fraction()
        if mem is not None:
            readings["mem"] = mem
            self.alarms.ensure(
                "high_system_memory_usage", mem >= self.mem_high,
                message=f"mem {mem:.0%} (high={self.mem_high:.0%})")

        fds = _read_fd_fraction()
        if fds is not None:
            readings["fds"] = fds
            self.alarms.ensure(
                "too_many_open_files", fds >= self.fd_high,
                message=f"fds {fds:.0%} of rlimit")

        if self.olp is not None:
            readings["loop_lag_ms"] = self.olp.lag_ms
            # the long_schedule analogue: sustained event-loop lag
            self.alarms.ensure(
                "runtime_overloaded", self.olp.is_overloaded(),
                message=f"event-loop lag {self.olp.lag_ms:.0f}ms")
        return readings

    def tick(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        if now - self._last_check < self.interval_s:
            return False
        self._last_check = now
        self.check()
        return True
