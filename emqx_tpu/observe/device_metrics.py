"""Kernel-plane observability fold (ISSUE 18).

The device router computes a compact per-batch counters vector IN the
routing program (ops/trie_match.py KERNEL_COUNTER_FIELDS — frontier
peak, probe iterations, candidate counts pre/post-compact, compact-slot
utilization, overflow/truncation rows) and ships it in the same
``publish_batch_collect`` device_get as the results — no extra sync.
This module is the host side: it folds those vectors plus the model's
submit/step/decode wall timings into the SAME observability surfaces
the native plane already uses —

- ``LatencyHistogram`` stages ``latency.kernel.submit|step|decode``
  (prometheus ``emqx_latency_kernel_*_seconds``, render-at-zero; the
  $SYS latency heartbeat once observed; ``$SYS/.../kernel/<stage>/...``
  always);
- trie-health gauges from the (Sharded)TrieIndex — per-shard filter
  counts, live-node occupancy, edge-table load factor, shard-skew
  ratio, patch-upload bytes — the ``emqx_kernel_*`` prometheus gauges
  and the ``GET /api/v5/kernel/stats`` mgmt snapshot;
- fixed metric slots ``messages.kernel.hostmatch`` /
  ``kernel.uploads`` / ``kernel.upload_patches`` (promoted from the
  model's ad-hoc counters);
- span stages ``kernel_submit`` / ``kernel_collect`` for 1-in-N
  sampled batches into a ``SpanCollector``, so a traced message's
  timeline no longer has a hole where the TPU was.

The degradation-ledger reasons (``kernel_overflow`` /
``kernel_hostmatch``) fold at the BROKER's publish_batch_collect
fallback seam (broker/broker.py), next to ``device_failover`` — the
fold here never double-counts them.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

# field order of the in-kernel counters vector — a LITERAL copy of
# ops/trie_match.py KERNEL_COUNTER_FIELDS. tests/test_kernel_counters_
# lint.py holds the two in parity so the packer and this decoder cannot
# drift; keep both edits in one commit.
KERNEL_COUNTER_FIELDS = (
    "frontier_peak",
    "probe_iters",
    "cand_pre",
    "cand_post",
    "compact_peak",
    "overflow_rows",
    "trunc_rows",
)

# per-field fold rule: peaks take max across shards/batches, the rest sum
_PEAK_FIELDS = ("frontier_peak", "compact_peak")

# stage names — latency.kernel.<stage> histograms + the $SYS
# kernel/<stage>/p50|p99 heartbeat subtree
KERNEL_STAGES = ("submit", "step", "decode")


class KernelCounters:
    """Decoded view of one batch's raw counters block.

    Accepts the flat ``[C]`` vector or the sharded ``[S, C]`` block;
    ``per_shard`` is always 2-D ``[S, C]`` (S=1 for the flat layout).
    """

    __slots__ = ("per_shard",)

    def __init__(self, raw) -> None:
        a = np.asarray(raw, dtype=np.int64)
        C = len(KERNEL_COUNTER_FIELDS)
        if a.size % C:
            raise ValueError(
                f"counters block of {a.size} elements is not a multiple "
                f"of the {C}-field layout")
        self.per_shard = a.reshape(-1, C)

    @property
    def n_shards(self) -> int:
        return self.per_shard.shape[0]

    def field(self, name: str) -> np.ndarray:
        """Per-shard [S] vector of one named field."""
        return self.per_shard[:, KERNEL_COUNTER_FIELDS.index(name)]

    def value(self, name: str) -> int:
        """Shard-aggregated scalar (max for peaks, sum otherwise)."""
        col = self.field(name)
        return int(col.max() if name in _PEAK_FIELDS else col.sum())

    def as_dict(self) -> dict[str, int]:
        return {n: self.value(n) for n in KERNEL_COUNTER_FIELDS}


class DeviceMetricsFold:
    """Per-batch fold point the RouterModel notifies at collect time.

    Single-writer like LatencyHistogram: batches collect on one thread
    (the pipeline's flush worker); readers (scrape/mgmt/$SYS) tolerate
    torn-but-monotone snapshots, the repo-wide observe discipline.
    """

    def __init__(self, metrics, ledger=None, spans=None, model=None,
                 node: str = "", sample_every: int = 8) -> None:
        self.metrics = metrics
        self.ledger = ledger          # kept for symmetry/mgmt; reasons
        #                               fold at the broker seam
        self.spans = spans            # SpanCollector | None
        self.model = model            # RouterModel | None
        self.node = node
        self.sample_every = max(1, int(sample_every))
        self.batches = 0
        self.host_batches = 0
        self.host_topics = 0
        self.last: Optional[KernelCounters] = None
        self.totals: dict[str, int] = {n: 0 for n in KERNEL_COUNTER_FIELDS}
        self.last_trace_id = 0
        self._synced: dict[str, int] = {}
        # register the stage histograms NOW: fixed stages render at
        # zero in prometheus before the first batch (the render-at-zero
        # discipline every other plane follows)
        self._hists = {
            s: metrics.register_hist(f"latency.kernel.{s}")
            for s in KERNEL_STAGES
        }

    # -- model notification seams ------------------------------------------

    def on_batch(self, counters, *, n_topics: int, submit_ns: int,
                 step_ns: int, decode_ns: int, t_submit_ns: int,
                 t_collect_ns: int) -> None:
        """One device batch collected. ``counters`` is the raw [C] or
        [S, C] block from the shared device_get (None when the model
        was built with kernel_telemetry off)."""
        self.batches += 1
        self._hists["submit"].observe(submit_ns)
        self._hists["step"].observe(step_ns)
        self._hists["decode"].observe(decode_ns)
        if counters is not None:
            kc = KernelCounters(counters)
            self.last = kc
            for name in KERNEL_COUNTER_FIELDS:
                v = kc.value(name)
                if name in _PEAK_FIELDS:
                    self.totals[name] = max(self.totals[name], v)
                else:
                    self.totals[name] += v
        # trace stitching: every sample_every-th batch (the FIRST one
        # included, so a single-batch test sees a timeline) mints a
        # trace id and lands kernel_submit/kernel_collect span points
        # on the monotonic clock the rest of the span plane uses
        if self.spans is not None and (self.batches - 1) \
                % self.sample_every == 0:
            tid = (int(t_submit_ns) ^ (self.batches << 48)) \
                & 0xFFFFFFFFFFFFFFFF
            self.last_trace_id = tid
            self.spans.record(tid, "kernel_submit", t_submit_ns,
                              aux=n_topics, node=self.node)
            self.spans.record(tid, "kernel_collect", t_collect_ns,
                              aux=n_topics, node=self.node)
            # hang the submit→collect wall off the step histogram as an
            # OpenMetrics exemplar — a latency spike links to the trace
            self._hists["step"].put_exemplar(tid, step_ns)
        self._sync_slots()

    def on_host_batch(self, n_topics: int) -> None:
        """One batch served by the cpu host-matcher instead of the
        kernel (local tally only; the messages.kernel.hostmatch slot
        and the kernel_hostmatch ledger leg fold at the broker seam)."""
        self.host_batches += 1
        self.host_topics += int(n_topics)
        self._sync_slots()

    def _sync_slots(self) -> None:
        """Diff the model's ad-hoc upload counters into their fixed
        metric slots (promotion without changing the model's test
        surface). messages.kernel.hostmatch is NOT synced here — the
        broker increments it at its collect seam (next to the
        kernel_hostmatch ledger record), and syncing it too would
        double-count."""
        m = self.model
        if m is None:
            return
        for attr, slot in (
                ("upload_count", "kernel.uploads"),
                ("patch_count", "kernel.upload_patches")):
            cur = int(getattr(m, attr, 0))
            delta = cur - self._synced.get(slot, 0)
            if delta > 0:
                self.metrics.inc(slot, delta)
                self._synced[slot] = cur

    # -- read surfaces ------------------------------------------------------

    def stage_hists(self) -> dict:
        return dict(self._hists)

    def gauges(self) -> dict:
        """Trie-health + upload gauges for the prometheus ``kernel=``
        section (``emqx_kernel_<name>``; list values render one series
        per shard with a ``shard`` label)."""
        self._sync_slots()
        out: dict = {"batches": self.batches,
                     "host_batches": self.host_batches}
        m = self.model
        if m is not None:
            out["shards"] = getattr(m, "n_shards", 1)
            out["launches"] = getattr(m, "launch_count", 0)
            out["uploads"] = getattr(m, "upload_count", 0)
            out["upload_patches"] = getattr(m, "patch_count", 0)
            out["patch_upload_bytes"] = getattr(m, "patch_upload_bytes", 0)
            idx = m.index
            shards = getattr(idx, "shards", None) or [idx]
            filters = [sum(1 for f in s.filters if f is not None)
                       for s in shards]
            occ, load = [], []
            for s in shards:
                arrays = getattr(s, "arrays", None)
                cap = (arrays.plus_child.shape[0]
                       if arrays is not None else 0)
                ht = (arrays.ht_parent.shape[0]
                      if arrays is not None else 0)
                occ.append(round(s.n_nodes / cap, 4) if cap else 0.0)
                load.append(round(s.n_edges / ht, 4) if ht else 0.0)
            total = sum(filters)
            mean = total / max(1, len(filters))
            out["filters"] = filters if len(filters) > 1 else filters[0]
            out["filters_total"] = total
            out["node_occupancy"] = occ if len(occ) > 1 else occ[0]
            out["edge_load"] = load if len(load) > 1 else load[0]
            out["shard_skew"] = (round(max(filters) / mean, 4)
                                 if mean > 0 else 1.0)
        if self.last is not None:
            for name in KERNEL_COUNTER_FIELDS:
                col = self.last.field(name)
                out[f"last.{name}"] = (col.tolist() if len(col) > 1
                                       else int(col[0]))
        return out

    def kernel_summary(self) -> dict:
        """Stage percentiles + counter totals — the bench/server
        surface (``server.kernel_summary()``)."""
        self._sync_slots()
        return {
            "batches": self.batches,
            "host_batches": self.host_batches,
            "stages": {s: h.summary() for s, h in self._hists.items()},
            "counters": dict(self.totals),
            "last_counters": (self.last.as_dict()
                              if self.last is not None else None),
        }

    def snapshot(self) -> dict:
        """The mgmt ``GET /api/v5/kernel/stats`` body: trie health +
        last-batch counters, shard-resolved."""
        out = {
            "ts_ms": int(time.time() * 1000),
            "gauges": self.gauges(),
            "summary": self.kernel_summary(),
        }
        if self.last is not None:
            out["last_per_shard"] = {
                n: self.last.field(n).tolist()
                for n in KERNEL_COUNTER_FIELDS}
        return out

    def spans_recent(self, limit: int = 32) -> list[dict]:
        """Assembled recent kernel traces, JSON-shaped like the native
        server's spans_recent (the app's default native_spans_fn when
        no native server is attached)."""
        if self.spans is None:
            return []
        out = []
        for tid, spans in self.spans.recent(limit):
            out.append({
                "trace_id": f"{tid:016x}",
                "spans": [{"t_ns": t, "stage": s, "shard": sh,
                           "node": n, "aux": a}
                          for t, s, sh, n, a in spans],
            })
        return out
