"""Structured logging — the ``emqx_logger_jsonfmt.erl`` /
``emqx_logger_textfmt.erl`` + ``?SLOG`` surface (SURVEY §5: structured
log events carry clientid/topic metadata the trace handlers filter on).

``slog(level, msg, **fields)`` is the ?SLOG analogue: fields travel as
record attributes (not rendered into the message), so the JSON
formatter emits them as first-class keys and the text formatter
appends them as ``k: v`` pairs — the reference's two console formats.

``setup_logging`` wires a console handler onto the ``emqx_tpu`` logger
tree; config drives it via ``log.console`` (emqx_conf_schema's log
handlers, minimal subset).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Optional

# standard LogRecord attributes — anything else on the record is a
# structured field (came in via `extra=`)
_RESERVED = frozenset(logging.LogRecord(
    "", 0, "", 0, "", (), None).__dict__) | {
    "message", "asctime", "taskName"}


def _fields(record: logging.LogRecord) -> dict:
    return {k: v for k, v in record.__dict__.items()
            if k not in _RESERVED and not k.startswith("_")}


def _ts(record: logging.LogRecord) -> str:
    t = time.localtime(record.created)
    return (time.strftime("%Y-%m-%dT%H:%M:%S", t) +
            f".{int(record.msecs):03d}")


class JsonFormatter(logging.Formatter):
    """One JSON object per line (emqx_logger_jsonfmt)."""

    def format(self, record: logging.LogRecord) -> str:
        out: dict[str, Any] = {
            "time": _ts(record),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
            "logger": record.name,
        }
        out.update(_fields(record))
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class TextFormatter(logging.Formatter):
    """``2026-07-30T12:00:00.123 [warning] msg, clientid: c1`` —
    the reference's console text format."""

    def format(self, record: logging.LogRecord) -> str:
        parts = [f"{_ts(record)} [{record.levelname.lower()}] "
                 f"{record.getMessage()}"]
        for k, v in _fields(record).items():
            parts.append(f"{k}: {v}")
        line = ", ".join(parts)
        if record.exc_info:
            line += "\n" + self.formatException(record.exc_info)
        return line


_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR,
           "critical": logging.CRITICAL}


def setup_logging(level: str = "warning", formatter: str = "text",
                  stream=None, to: str = "console",
                  file_path: str = "log/emqx.log",
                  logger_name: str = "emqx_tpu") -> logging.Handler:
    """Configure the framework logger tree's handlers
    (emqx_conf_schema log.console / log.file: ``to`` selects console,
    file, or both; the file handler creates its directory). Replaces
    handlers a previous call installed; returns the console (or sole)
    handler. The tree owns its output (propagate=False) — like the
    reference's dedicated logger handlers, records do not ALSO flow to
    root handlers."""
    import os

    logger = logging.getLogger(logger_name)
    logger.setLevel(_LEVELS.get(level, logging.WARNING))
    for h in list(logger.handlers):
        if getattr(h, "_emqx_console", False):
            logger.removeHandler(h)
            if isinstance(h, logging.FileHandler):
                h.close()
    fmt = JsonFormatter() if formatter == "json" else TextFormatter()
    handler: Optional[logging.Handler] = None
    if to in ("console", "both"):
        handler = logging.StreamHandler(stream or sys.stderr)
        handler._emqx_console = True
        handler.setFormatter(fmt)
        logger.addHandler(handler)
    if to in ("file", "both"):
        d = os.path.dirname(file_path)
        if d:
            os.makedirs(d, exist_ok=True)
        fh = logging.FileHandler(file_path)
        fh._emqx_console = True           # same replace-on-reconfigure
        fh.setFormatter(fmt)
        logger.addHandler(fh)
        handler = handler or fh
    logger.propagate = False
    return handler


# extra= keys that would collide with LogRecord's own attributes make
# stdlib makeRecord RAISE ("Attempt to overwrite ..."), crashing the
# caller that merely tried to log — natural ?SLOG field names like
# `name` or `module` land in this set, so they are suffixed instead
_EXTRA_RESERVED = frozenset(logging.LogRecord(
    "", 0, "", 0, "", (), None).__dict__) | {"message", "asctime"}


def slog(level: str, msg: str, *, logger: Optional[str] = None,
         **fields: Any) -> None:
    """?SLOG: structured fields ride the record, not the message."""
    safe = {(k if k not in _EXTRA_RESERVED else k + "_"): v
            for k, v in fields.items()}
    logging.getLogger(logger or "emqx_tpu").log(
        _LEVELS.get(level, logging.INFO), msg, extra=safe)
